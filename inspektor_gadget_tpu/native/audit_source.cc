// AuditSource — host-wide capability/seccomp observation via NETLINK_AUDIT.
//
// Reference contract: capable.bpf.c:1-250 (kprobe cap_capable, every
// capability check on the host) and audit-seccomp.bpf.c:1-65 (kprobe
// audit_seccomp, every seccomp verdict on the host). Without kprobes the
// kernel still exports both facts through the audit subsystem:
//  - seccomp kills emit AUDIT_SECCOMP (1326) records whenever auditing is
//    enabled — no rules needed;
//  - capability denials are observed from syscall outcomes: two audit exit
//    rules (exit==-EPERM, exit==-EACCES, keyed "igtpu" so only our rules
//    are removed at teardown) make every failed privileged syscall emit an
//    AUDIT_SYSCALL (1300) record, which maps to the implied capability via
//    the same syscall→capability table the per-target ptrace window uses —
//    identical verdict-from-outcome semantics, but host-wide.
//  - LSM denials (AUDIT_AVC 1400) carrying "capability=N" map directly.
//
// Records are read from the AUDIT_NLGRP_READLOG multicast group (kernel
// >= 3.16, CAP_AUDIT_READ) so a live auditd keeps working untouched. When
// auditing is disabled and no daemon owns it, the source enables it for
// the capture's lifetime and restores the prior state on teardown.

#ifdef __linux__
#include <fcntl.h>
#include <poll.h>
#include <unistd.h>

#include <linux/audit.h>
#include <linux/netlink.h>
#include <sys/socket.h>

#include <cstring>
#include <string>
#include <unordered_map>

#include "ringbuf.h"

namespace ig {

namespace {

constexpr char kRuleKey[] = "igtpu";

#if defined(__x86_64__)
constexpr const char* kNativeArch = "c000003e";  // AUDIT_ARCH_X86_64
#elif defined(__aarch64__)
constexpr const char* kNativeArch = "c00000b7";  // AUDIT_ARCH_AARCH64
#else
constexpr const char* kNativeArch = "";
#endif

// "key=value" field extraction from an audit record body. Values are either
// bare tokens or double-quoted strings (comm="x").
bool audit_field(const std::string& body, const char* key, std::string& out) {
  std::string needle = std::string(key) + "=";
  size_t pos = 0;
  while ((pos = body.find(needle, pos)) != std::string::npos) {
    // must start a field (preceded by space or start)
    if (pos != 0 && body[pos - 1] != ' ') {
      pos += needle.size();
      continue;
    }
    size_t v = pos + needle.size();
    if (v < body.size() && body[v] == '"') {
      size_t end = body.find('"', v + 1);
      if (end == std::string::npos) return false;
      out = body.substr(v + 1, end - v - 1);
    } else {
      size_t end = body.find(' ', v);
      out = body.substr(v, end == std::string::npos ? end : end - v);
    }
    return true;
  }
  return false;
}

long audit_field_long(const std::string& body, const char* key, long dflt) {
  std::string v;
  if (!audit_field(body, key, v)) return dflt;
  return strtol(v.c_str(), nullptr, 10);
}

}  // namespace

class AuditSource : public Source {
 public:
  AuditSource(size_t ring_pow2, const std::string& cfg) : Source(ring_pow2) {
    eperm_rules_ = cfg_get(cfg, "eperm_rules", "0") == "1";
  }
  ~AuditSource() override { stop(); }

  // Window exists when the audit netlink family answers a status query and
  // the READLOG multicast group is bindable (CAP_AUDIT_READ).
  static bool supported() {
    int rx = socket(AF_NETLINK, SOCK_RAW | SOCK_CLOEXEC, NETLINK_AUDIT);
    if (rx < 0) return false;
    struct sockaddr_nl sa{};
    sa.nl_family = AF_NETLINK;
    sa.nl_groups = AUDIT_NLGRP_READLOG;
    bool ok = bind(rx, (struct sockaddr*)&sa, sizeof(sa)) == 0;
    close(rx);
    if (!ok) return false;
    uint32_t enabled, pid;
    return query_status(enabled, pid);
  }

 protected:
  void run() override {
    // control plane state: remember what we changed, restore on exit
    uint32_t enabled = 0, daemon_pid = 0;
    if (!query_status(enabled, daemon_pid)) return;
    bool we_enabled = false;
    if (!enabled && daemon_pid == 0) {
      we_enabled = set_enabled(1);
    }
    int rx = socket(AF_NETLINK, SOCK_RAW | SOCK_CLOEXEC, NETLINK_AUDIT);
    if (rx < 0) {
      if (we_enabled) set_enabled(0);
      return;
    }
    struct sockaddr_nl sa{};
    sa.nl_family = AF_NETLINK;
    sa.nl_groups = AUDIT_NLGRP_READLOG;
    if (bind(rx, (struct sockaddr*)&sa, sizeof(sa)) != 0) {
      close(rx);
      if (we_enabled) set_enabled(0);
      return;
    }
    // grow the rx buffer: a match-all-EPERM rule can burst
    int rcvbuf = 4 << 20;
    setsockopt(rx, SOL_SOCKET, SO_RCVBUF, &rcvbuf, sizeof(rcvbuf));
    bool rules_added = false;
    if (eperm_rules_) {
      rules_added = rule_op(AUDIT_ADD_RULE, -1 /*EPERM*/);
      rules_added = rule_op(AUDIT_ADD_RULE, -13 /*EACCES*/) || rules_added;
    }
    struct pollfd pfd{rx, POLLIN, 0};
    char buf[65536];
    while (running_.load(std::memory_order_relaxed)) {
      if (poll(&pfd, 1, 100) <= 0) continue;
      ssize_t len = recv(rx, buf, sizeof(buf), 0);
      if (len <= 0) continue;
      // kauditd sends ONE record per datagram with nlmsg_len == datagram
      // size, NOT 4-byte aligned — NLMSG_NEXT's aligned subtraction goes
      // negative, so the remainder must stay signed (a size_t cast would
      // wrap and "validate" garbage past the record)
      int rem = (int)len;
      for (struct nlmsghdr* h = (struct nlmsghdr*)buf; NLMSG_OK(h, rem);
           h = NLMSG_NEXT(h, rem)) {
        size_t blen = h->nlmsg_len - NLMSG_HDRLEN;
        std::string body((char*)NLMSG_DATA(h), blen);
        parse_record(h->nlmsg_type, body);
      }
    }
    if (rules_added) {
      rule_op(AUDIT_DEL_RULE, -1);
      rule_op(AUDIT_DEL_RULE, -13);
    }
    if (we_enabled) set_enabled(0);
    close(rx);
  }

 private:
  // ---- record parsing -----------------------------------------------------

  void parse_record(uint16_t type, const std::string& body) {
    if (type == AUDIT_SECCOMP) {
      parse_seccomp(body);
    } else if (type == AUDIT_SYSCALL) {
      parse_syscall(body);
    } else if (type == AUDIT_AVC) {
      parse_avc(body);
    }
  }

  void parse_seccomp(const std::string& body) {
    if (kNativeArch[0]) {
      std::string arch;
      if (audit_field(body, "arch", arch) && arch != kNativeArch) return;
    }
    Event ev{};
    ev.ts_ns = now_ns();
    ev.kind = EV_AUDIT;
    ev.pid = (uint32_t)audit_field_long(body, "pid", 0);
    ev.uid = (uint32_t)audit_field_long(body, "uid", 0);
    ev.aux1 = (uint64_t)audit_field_long(body, "syscall", -1);
    uint64_t sig = (uint64_t)audit_field_long(body, "sig", 0);
    std::string code;
    uint64_t code_v = 0;
    if (audit_field(body, "code", code))
      code_v = strtoull(code.c_str(), nullptr, 16);
    ev.aux2 = (sig << 32) | (code_v & 0xFFFFFFFF);
    fill_from_record(ev, body);
    emit(ev);
  }

  void parse_syscall(const std::string& body) {
    // only the records our rules generated: a host auditd's own rules may
    // stream successes and unrelated syscalls here too
    std::string key, success;
    if (!audit_field(body, "key", key) || key != kRuleKey) return;
    if (audit_field(body, "success", success) && success == "yes") return;
    if (kNativeArch[0]) {
      std::string arch;
      if (audit_field(body, "arch", arch) && arch != kNativeArch) return;
    }
    long nr = audit_field_long(body, "syscall", -1);
    int cap = cap_for_syscall_nr(nr);
    if (cap < 0) return;  // not a capability-implying syscall
    Event ev{};
    ev.ts_ns = now_ns();
    ev.kind = EV_CAPABILITY;
    ev.pid = (uint32_t)audit_field_long(body, "pid", 0);
    ev.uid = (uint32_t)audit_field_long(body, "uid", 0);
    ev.aux1 = 0;  // denial observed from the failed outcome
    ev.aux2 = (uint64_t)cap;
    fill_from_record(ev, body);
    emit(ev);
  }

  void parse_avc(const std::string& body) {
    // LSM denial with an explicit capability number (SELinux/AppArmor)
    std::string capv;
    if (!audit_field(body, "capability", capv)) return;
    Event ev{};
    ev.ts_ns = now_ns();
    ev.kind = EV_CAPABILITY;
    ev.pid = (uint32_t)audit_field_long(body, "pid", 0);
    ev.aux1 = 0;
    ev.aux2 = strtoull(capv.c_str(), nullptr, 10);
    fill_from_record(ev, body);
    emit(ev);
  }

  void fill_from_record(Event& ev, const std::string& body) {
    // the record's own comm beats a /proc lookup: the task is often
    // already dead (seccomp kill) by the time we parse
    std::string comm;
    if (audit_field(body, "comm", comm) && !comm.empty()) {
      size_t c = comm.size() < sizeof(ev.comm) - 1 ? comm.size()
                                                   : sizeof(ev.comm) - 1;
      memcpy(ev.comm, comm.data(), c);
      ev.key_hash = fnv1a64(comm.data(), comm.size());
      vocab_.put(ev.key_hash, comm.data(), comm.size());
    }
    // mntns for the container filter; the victim may already be gone
    char path[64], link[64];
    snprintf(path, sizeof(path), "/proc/%u/ns/mnt", ev.pid);
    ssize_t ln = readlink(path, link, sizeof(link) - 1);
    if (ln > 0) {
      link[ln] = 0;
      const char* lb = strchr(link, '[');
      if (lb) ev.mntns = strtoull(lb + 1, nullptr, 10);
    }
  }

  // syscall nr → implied capability, from the ptrace window's tables
  // (kSyscallNames for nr→name, kSpecs for name→cap) so both flavours
  // report identical capability semantics.
  static int cap_for_syscall_nr(long nr) {
    static const std::unordered_map<long, int>* idx = [] {
      auto* m = new std::unordered_map<long, int>();
      for (const SyscallName* s = kSyscallNames; s->name; s++) {
        for (const SysSpec* sp = kSpecs; sp->name; sp++) {
          if (strcmp(sp->name, s->name) == 0) {
            if (sp->cap >= 0) (*m)[s->nr] = sp->cap;
            break;
          }
        }
      }
      return m;
    }();
    auto it = idx->find(nr);
    return it == idx->end() ? -1 : it->second;
  }

  // ---- audit control plane (unicast request/ack) --------------------------

  static int ctl_socket() {
    int sd = socket(AF_NETLINK, SOCK_RAW | SOCK_CLOEXEC, NETLINK_AUDIT);
    if (sd < 0) return -1;
    struct sockaddr_nl sa{};
    sa.nl_family = AF_NETLINK;
    if (bind(sd, (struct sockaddr*)&sa, sizeof(sa)) != 0) {
      close(sd);
      return -1;
    }
    return sd;
  }

  static bool ctl_request(uint16_t type, const void* payload, size_t plen,
                          char* reply, size_t rcap, uint16_t* rtype) {
    int sd = ctl_socket();
    if (sd < 0) return false;
    // audit_rule_data alone is 1040 bytes (4 × 64-slot u32 arrays) before
    // the filter-key string, so the frame must hold well over 1 KiB
    char msg[NLMSG_HDRLEN + 2048];
    if (plen > 2048) {
      close(sd);
      return false;
    }
    auto* nlh = (struct nlmsghdr*)msg;
    memset(msg, 0, sizeof(msg));
    nlh->nlmsg_len = NLMSG_LENGTH(plen);
    nlh->nlmsg_type = type;
    nlh->nlmsg_flags = NLM_F_REQUEST | (reply ? 0 : NLM_F_ACK);
    nlh->nlmsg_seq = 1;
    if (plen) memcpy(NLMSG_DATA(nlh), payload, plen);
    bool ok = send(sd, msg, nlh->nlmsg_len, 0) == (ssize_t)nlh->nlmsg_len;
    if (ok) {
      struct pollfd pfd{sd, POLLIN, 0};
      if (poll(&pfd, 1, 500) > 0) {
        char rbuf[8192];
        ssize_t len = recv(sd, rbuf, sizeof(rbuf), 0);
        if (len > 0) {
          auto* rh = (struct nlmsghdr*)rbuf;
          if (rtype) *rtype = rh->nlmsg_type;
          if (rh->nlmsg_type == NLMSG_ERROR) {
            int err = *(int*)NLMSG_DATA(rh);
            ok = err == 0;
          }
          if (reply && NLMSG_OK(rh, (size_t)len)) {
            size_t blen = rh->nlmsg_len - NLMSG_HDRLEN;
            if (blen > rcap) blen = rcap;
            memcpy(reply, NLMSG_DATA(rh), blen);
          }
        } else {
          ok = false;
        }
      } else {
        ok = false;
      }
    }
    close(sd);
    return ok;
  }

  static bool query_status(uint32_t& enabled, uint32_t& pid) {
    char reply[sizeof(struct audit_status)] = {};
    uint16_t rtype = 0;
    if (!ctl_request(AUDIT_GET, nullptr, 0, reply, sizeof(reply), &rtype))
      return false;
    if (rtype != AUDIT_GET) return false;
    auto* st = (struct audit_status*)reply;
    enabled = st->enabled;
    pid = st->pid;
    return true;
  }

  static bool set_enabled(uint32_t v) {
    struct audit_status st{};
    st.mask = AUDIT_STATUS_ENABLED;
    st.enabled = v;
    return ctl_request(AUDIT_SET, &st, sizeof(st), nullptr, 0, nullptr);
  }

  // Add/remove one "exit filter, always, all syscalls, exit==<errno>" rule
  // tagged with our filter key so teardown removes exactly what we added.
  static bool rule_op(uint16_t op, int exit_value) {
    size_t keylen = strlen(kRuleKey);
    size_t plen = sizeof(struct audit_rule_data) + keylen;
    std::string storage(plen, '\0');
    auto* r = (struct audit_rule_data*)storage.data();
    r->flags = AUDIT_FILTER_EXIT;
    r->action = AUDIT_ALWAYS;
    for (int i = 0; i < AUDIT_BITMASK_SIZE; i++) r->mask[i] = 0xFFFFFFFF;
    r->field_count = 2;
    r->fields[0] = AUDIT_EXIT;
    r->values[0] = (uint32_t)exit_value;
    r->fieldflags[0] = AUDIT_EQUAL;
    r->fields[1] = AUDIT_FILTERKEY;
    r->values[1] = (uint32_t)keylen;
    r->fieldflags[1] = AUDIT_EQUAL;
    r->buflen = (uint32_t)keylen;
    memcpy(r->buf, kRuleKey, keylen);
    return ctl_request(op, r, plen, nullptr, 0, nullptr);
  }

  bool eperm_rules_ = false;
};

}  // namespace ig
#endif  // __linux__
