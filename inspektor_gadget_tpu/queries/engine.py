"""Seal-tick incremental evaluation of standing queries.

The core move: the merge algebra (history/window.py) makes a range
answer a FOLD, and folds over a sliding window need not be recomputed —
each seal tick merges ONE new window into a running materialized
answer. Because HLL registers merge by max, the monoid is not
invertible (you cannot subtract the window that just slid out), so
eviction uses the two-stack sliding-window aggregation trick: a back
list accumulating new windows left-to-right and a front stack of
suffix aggregates built when the front drains. Every push/evict is
amortized O(1) merges — refresh cost is independent of range length,
which is the whole economic argument for standing queries.

Exactness: every plane is exact integer arithmetic (CMS/entropy/
invertible/quantile adds, HLL max, candidate sums), so pairwise
association changes nothing, and merged_to_sealed orders candidates by
(-count, key) — a pure function of content. The standing answer is
therefore BYTE-IDENTICAL (same window digest) to an ad-hoc
answer_query fold over the same sealed windows, and the tests assert
exactly that. Plane refusal (a window missing the invertible/quantile
plane poisons the range) is an AND over windows — associative — so
refusal outcomes match too; only the human-readable skipped NOTES are
fold-shape-dependent, and notes are not state.

Eviction mirrors `header_overlaps`: a window leaves the fold when
`end_ts < cutoff`, exactly the predicate fetch_windows uses to exclude
it from an ad-hoc range query — standing coverage and recompute
coverage can never disagree at a boundary. Coverage only moves at seal
ticks, so a read between ticks is stale by at most one seal interval.
"""

from __future__ import annotations

import hashlib
import threading

from ..history.query import pack_frames
from ..history.window import (SealedWindow, encode_window, merge_windows,
                              merged_to_sealed)
from ..telemetry import registry as tm
from .cache import ResultCache
from .spec import QUERY_SCHEMA, StandingQuery

_tm_folds = tm.counter(
    "ig_query_folds_total",
    "window merges performed by the standing-query plane (cache hits "
    "perform zero)")
_tm_refreshes = tm.counter(
    "ig_query_refresh_total",
    "standing-query materializations (one per query per seal tick)",
    labels=("query",))
_tm_published = tm.counter(
    "ig_query_published_total",
    "materialized answers published over the summary tier",
    labels=("query",))
_tm_windows = tm.gauge(
    "ig_query_windows",
    "sealed windows currently inside a standing query's sliding range",
    labels=("query",))


class SlidingFold:
    """Two-stack sliding-window aggregation over the window monoid.

    Entries are normalized at push (merge of one window → int64 lanes,
    window ordinal 0) so every aggregate — and the final answer — has
    the exact dtype/shape an ad-hoc fold produces. Not thread-safe;
    the owning engine serializes access.
    """

    def __init__(self, *, gadget: str, node: str):
        self.gadget = gadget
        self.node = node
        # back: arrival order, back_agg = fold(back) oldest-first
        self._back: list[tuple[dict, SealedWindow]] = []
        self._back_agg: SealedWindow | None = None
        # front: stack of (meta, win, agg-of-this-and-all-younger-front)
        # with the OLDEST entry on top (popped first)
        self._front: list[tuple[dict, SealedWindow, SealedWindow]] = []
        self.folds = 0   # merge_windows calls — the cost being amortized

    def _seal(self, wins: list[SealedWindow]) -> SealedWindow:
        self.folds += 1
        _tm_folds.inc()
        return merged_to_sealed(merge_windows(wins), gadget=self.gadget,
                                node=self.node, window=0, run_id="")

    def push(self, win: SealedWindow) -> None:
        meta = {"digest": win.digest, "window": int(win.window),
                "level": int(win.level), "start_ts": float(win.start_ts),
                "end_ts": float(win.end_ts), "events": int(win.events)}
        norm = self._seal([win])
        self._back.append((meta, norm))
        self._back_agg = (norm if self._back_agg is None
                         else self._seal([self._back_agg, norm]))

    def _flip(self) -> None:
        agg: SealedWindow | None = None
        for meta, w in reversed(self._back):
            agg = w if agg is None else self._seal([w, agg])
            self._front.append((meta, w, agg))
        self._back = []
        self._back_agg = None

    def evict_older_than(self, cutoff: float) -> int:
        """Drop windows with end_ts < cutoff — the exact complement of
        header_overlaps(start_ts=cutoff). Returns evicted count."""
        n = 0
        while True:
            if not self._front:
                if not self._back:
                    break
                self._flip()
            meta = self._front[-1][0]
            if meta["end_ts"] >= cutoff:
                break
            self._front.pop()
            n += 1
        return n

    def __len__(self) -> int:
        return len(self._front) + len(self._back)

    def metas(self) -> list[dict]:
        """Covered windows, oldest first."""
        return ([e[0] for e in reversed(self._front)]
                + [e[0] for e in self._back])

    def coverage(self) -> frozenset:
        return frozenset(m["digest"] for m in self.metas())

    def value(self) -> SealedWindow | None:
        """Materialized fold of every covered window — ≤ 1 merge on top
        of the maintained aggregates."""
        front_agg = self._front[-1][2] if self._front else None
        if front_agg is None:
            return self._back_agg
        if self._back_agg is None:
            return front_agg
        return self._seal([front_agg, self._back_agg])


class StandingQueryEngine:
    """Per-run registry of standing queries: one SlidingFold per query,
    refreshed on every seal tick, fronted by the digest-keyed cache."""

    def __init__(self, specs: list[StandingQuery], *, gadget: str,
                 node: str = "", cache_bytes: int = 8 << 20):
        self.gadget = gadget
        self.node = node
        self.specs = {q.id: q for q in specs}
        self.cache = ResultCache(max_bytes=cache_bytes)
        self._folds = {q.id: SlidingFold(gadget=gadget, node=node)
                       for q in specs}
        self._mu = threading.Lock()
        self._ticks = 0
        self._published = {q.id: 0 for q in specs}
        self._refreshed = {q.id: 0 for q in specs}

    # -- internals (call with _mu held) -------------------------------------

    def _materialize(self, q: StandingQuery,
                     fold: SlidingFold) -> tuple[dict, bytes] | None:
        norm = fold.value()
        if norm is None:
            return None
        metas = fold.metas()
        cov = hashlib.sha256(
            "\n".join(sorted(m["digest"] for m in metas)).encode()
        ).hexdigest()
        header = {
            "schema": QUERY_SCHEMA,
            "id": q.id,
            "gadget": self.gadget,
            "node": self.node,
            "stats": list(q.stats),
            "key": q.key,
            "top": int(q.top),
            "range_s": float(q.range_s),
            "windows": len(metas),
            "levels": sorted({m["level"] for m in metas}),
            "coverage_digest": cov,
            "tick": self._ticks,
            "start_ts": float(norm.start_ts),
            "end_ts": float(norm.end_ts),
            "events": int(norm.events),
            "drops": int(norm.drops),
        }
        return header, pack_frames([encode_window(norm)])

    # -- seal-tick feed ------------------------------------------------------

    def on_seal(self, win: SealedWindow,
                now: float) -> list[tuple[dict, bytes]]:
        """Fold one just-sealed window into every standing query; cache
        the refreshed answers; return the (header, payload) pairs due
        for publication this tick (per-query `every` cadence)."""
        out: list[tuple[dict, bytes]] = []
        with self._mu:
            self._ticks += 1
            for qid, q in self.specs.items():
                fold = self._folds[qid]
                fold.push(win)
                fold.evict_older_than(now - q.range_s)
                _tm_windows.labels(query=qid).set(len(fold))
                mat = self._materialize(q, fold)
                if mat is None:
                    continue
                self._refreshed[qid] += 1
                _tm_refreshes.labels(query=qid).inc()
                self.cache.put(qid, fold.coverage(), mat[0], mat[1])
                if self._ticks % q.every == 0:
                    self._published[qid] += 1
                    _tm_published.labels(query=qid).inc()
                    out.append(mat)
        return out

    # -- read path -----------------------------------------------------------

    def read(self, qid: str) -> tuple[dict, bytes, bool] | None:
        """(header, payload, from_cache) for one query, or None when the
        range is empty. The repeat-read contract: within one coverage
        (i.e. between seal ticks) the second read is a cache hit and
        performs ZERO window folds."""
        with self._mu:
            q = self.specs.get(qid)
            if q is None:
                raise KeyError(f"no standing query {qid!r} "
                               f"(registered: {sorted(self.specs)})")
            fold = self._folds[qid]
            cov = fold.coverage()
            if not cov:
                return None
            hit = self.cache.get(qid, cov)
            if hit is not None:
                return hit[0], hit[1], True
            mat = self._materialize(q, fold)
            if mat is None:
                return None
            self.cache.put(qid, cov, mat[0], mat[1])
            return mat[0], mat[1], False

    def stats(self) -> list[dict]:
        """One accounting row per query (dump_state / doctor / watch)."""
        with self._mu:
            cache = self.cache.stats()
            rows = []
            for qid, q in sorted(self.specs.items()):
                fold = self._folds[qid]
                metas = fold.metas()
                rows.append({
                    "id": qid,
                    "gadget": self.gadget,
                    "stats": list(q.stats),
                    "key": q.key,
                    "range_s": float(q.range_s),
                    "every": int(q.every),
                    "windows": len(metas),
                    "events": sum(m["events"] for m in metas),
                    "ticks": self._ticks,
                    "refreshed": self._refreshed[qid],
                    "published": self._published[qid],
                    "folds": fold.folds,
                    "cache": cache,
                })
            return rows


# -- process-wide registry ---------------------------------------------------
# run_id → engine, mirroring operators/tpusketch.py's `_live` so the
# agent's DumpState, doctor, and `ig-tpu watch --local` can read
# standing-query state without importing the operator (or jax).

_LIVE: dict[str, StandingQueryEngine] = {}
_LIVE_MU = threading.Lock()


def register(run_id: str, engine: StandingQueryEngine) -> None:
    with _LIVE_MU:
        _LIVE[run_id] = engine


def unregister(run_id: str) -> None:
    with _LIVE_MU:
        _LIVE.pop(run_id, None)


def live_engines() -> list[tuple[str, StandingQueryEngine]]:
    with _LIVE_MU:
        return sorted(_LIVE.items())


def live_stats() -> list[dict]:
    """Flat accounting rows across every live engine, run_id attached."""
    rows = []
    for run_id, eng in live_engines():
        for row in eng.stats():
            rows.append({"run_id": run_id, **row})
    return rows


__all__ = ["SlidingFold", "StandingQueryEngine", "register",
           "unregister", "live_engines", "live_stats"]
