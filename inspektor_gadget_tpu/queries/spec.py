"""Standing-query registration grammar.

A standing query is a CONTINUOUS question — "top-k over the last 15
minutes", "cardinality of tenant X over the last hour" — registered
once and answered incrementally at every seal tick instead of re-folded
per request. The grammar deliberately reuses the vocabulary the rest of
the plane already speaks: statistics are `answer_query`'s blocks
(top-k / cardinality / entropy / heavy-flow decode / quantiles), slice
keys are the history plane's (``mntns:<ns>``, ``kind:<k>``, crossed),
and validation is the alert-rule discipline (alerts/rules.py): every
misconfig raises a typed QueryError at LOAD time, before the first seal
tick, never mid-stream.

A query document is JSON (or YAML when pyyaml is present): a list of
query objects, or ``{"queries": [...]}``::

    [{"id": "hot-tenants", "stats": ["topk", "cardinality"],
      "range": "15m", "top": 10},
     {"id": "tail-latency", "stats": ["quantiles"], "range": "1h",
      "every": 6}]
"""

from __future__ import annotations

import dataclasses
import json
import re

from ..params.validators import parse_duration

QUERY_SCHEMA = "ig-tpu/standing-query/v1"

# the statistic vocabulary IS answer_query's block list: each name maps
# to one block of the materialized answer (history/query.py renders all
# of them from the same merged window, so `stats` selects what the
# consumer asked to watch, not what gets folded)
STATISTICS = ("topk", "cardinality", "entropy", "heavy_flows",
              "quantiles")

_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.-]{0,63}$")

TOP_MAX = 1024


class QueryError(ValueError):
    """A standing-query document failed validation (load-time, loud)."""


@dataclasses.dataclass(frozen=True)
class StandingQuery:
    """One validated continuous query."""

    id: str
    stats: tuple[str, ...]         # subset of STATISTICS, order kept
    range_s: float                 # sliding window length (seconds)
    key: str = ""                  # optional subpopulation slice
    top: int = 10                  # heavy hitters / flows to materialize
    every: int = 1                 # publish every N seal ticks

    def identity(self) -> str:
        """Canonical spec identity — half of the result-cache key (the
        other half is the covered digest set)."""
        return json.dumps({
            "schema": QUERY_SCHEMA, "id": self.id,
            "stats": list(self.stats), "range_s": self.range_s,
            "key": self.key, "top": self.top, "every": self.every,
        }, sort_keys=True, separators=(",", ":"))

    def describe(self) -> str:
        rng = (f"{self.range_s:g}s" if self.range_s < 120
               else f"{self.range_s / 60:g}m")
        parts = [f"{'/'.join(self.stats)} over last {rng}"]
        if self.key:
            parts.append(f"slice {self.key}")
        if self.every > 1:
            parts.append(f"every {self.every} seals")
        return f"{self.id}: " + ", ".join(parts)


_KNOWN_KEYS = frozenset({"id", "stats", "range", "key", "top", "every"})


def _parse_query(raw: object, idx: int, *, default_every: int = 1,
                 max_range_s: float | None = None) -> StandingQuery:
    if not isinstance(raw, dict):
        raise QueryError(f"query #{idx}: expected an object, got "
                         f"{type(raw).__name__}")
    qid = raw.get("id")
    if not isinstance(qid, str) or not _ID_RE.match(qid):
        raise QueryError(f"query #{idx}: id must match "
                         f"{_ID_RE.pattern!r}, got {qid!r}")
    unknown = sorted(set(raw) - _KNOWN_KEYS)
    if unknown:
        raise QueryError(f"query {qid!r}: unknown key(s) {unknown} "
                         f"(expected {sorted(_KNOWN_KEYS)})")
    stats = raw.get("stats")
    if not isinstance(stats, list) or not stats:
        raise QueryError(f"query {qid!r}: stats must be a non-empty "
                         f"list from {STATISTICS}")
    seen: list[str] = []
    for s in stats:
        if s not in STATISTICS:
            raise QueryError(f"query {qid!r}: unknown statistic {s!r} "
                             f"(one of {STATISTICS})")
        if s in seen:
            raise QueryError(f"query {qid!r}: duplicate statistic {s!r}")
        seen.append(s)
    rng = raw.get("range")
    if rng is None:
        raise QueryError(f"query {qid!r}: missing 'range' (the sliding "
                         "window length, e.g. \"15m\")")
    if isinstance(rng, bool) or not isinstance(rng, (int, float, str)):
        raise QueryError(f"query {qid!r}: range must be seconds or a "
                         f"duration string, got {rng!r}")
    try:
        range_s = (float(rng) if isinstance(rng, (int, float))
                   else parse_duration(rng))
    except ValueError as e:
        raise QueryError(f"query {qid!r}: bad range {rng!r}: {e}") from None
    if range_s <= 0:
        raise QueryError(f"query {qid!r}: range must be > 0 seconds, "
                         f"got {range_s!r}")
    if max_range_s is not None and range_s > max_range_s:
        raise QueryError(f"query {qid!r}: range {range_s:g}s exceeds the "
                         f"configured cap of {max_range_s:g}s "
                         "(query-max-range)")
    key = raw.get("key", "")
    if not isinstance(key, str):
        raise QueryError(f"query {qid!r}: key must be a string slice "
                         f"like 'mntns:4026531840', got {key!r}")
    top = raw.get("top", 10)
    if isinstance(top, bool) or not isinstance(top, int) \
            or not 1 <= top <= TOP_MAX:
        raise QueryError(f"query {qid!r}: top must be an int in "
                         f"[1, {TOP_MAX}], got {top!r}")
    every = raw.get("every", default_every)
    if isinstance(every, bool) or not isinstance(every, int) or every < 1:
        raise QueryError(f"query {qid!r}: every must be an int >= 1 "
                         f"(publish cadence in seal ticks), got {every!r}")
    return StandingQuery(id=qid, stats=tuple(seen), range_s=range_s,
                         key=key, top=top, every=every)


def _parse_doc(text: str, source: str) -> object:
    text = text.strip()
    if not text:
        raise QueryError(f"{source}: empty query document")
    try:
        import yaml
        try:
            return yaml.safe_load(text)
        except yaml.YAMLError as e:
            raise QueryError(f"{source}: unparseable YAML/JSON: "
                             f"{e}") from None
    except ImportError:
        try:
            return json.loads(text)
        except json.JSONDecodeError as e:
            raise QueryError(f"{source}: unparseable JSON (pyyaml not "
                             f"installed): {e}") from None


def load_queries(text: str, source: str = "<queries>", *,
                 default_every: int = 1,
                 max_range_s: float | None = None) -> list[StandingQuery]:
    """Parse + validate a query document; raises QueryError on anything
    off (the rules.py load-time discipline)."""
    doc = _parse_doc(text, source)
    if isinstance(doc, dict):
        extra = sorted(set(doc) - {"queries"})
        if extra:
            raise QueryError(f"{source}: unknown top-level key(s) {extra} "
                             "(expected 'queries')")
        doc = doc.get("queries")
    if doc is None or doc == []:
        raise QueryError(f"{source}: no queries defined")
    if not isinstance(doc, list):
        raise QueryError(f"{source}: expected a list of queries, got "
                         f"{type(doc).__name__}")
    queries = [_parse_query(q, i, default_every=default_every,
                            max_range_s=max_range_s)
               for i, q in enumerate(doc)]
    seen: dict[str, int] = {}
    for i, q in enumerate(queries):
        if q.id in seen:
            raise QueryError(f"{source}: duplicate query id {q.id!r} "
                             f"(queries #{seen[q.id]} and #{i})")
        seen[q.id] = i
    return queries


def load_queries_file(path: str, **kw) -> list[StandingQuery]:
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        raise QueryError(f"cannot read query file {path!r}: {e}") from None
    return load_queries(text, source=path, **kw)


__all__ = ["QUERY_SCHEMA", "STATISTICS", "TOP_MAX", "QueryError",
           "StandingQuery", "load_queries", "load_queries_file"]
