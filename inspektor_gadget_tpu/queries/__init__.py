"""Standing queries: registered once, answered incrementally.

spec.py — the registration grammar (alert-rule validation discipline);
cache.py — the digest-keyed result cache (exact invalidation);
engine.py — seal-tick incremental folds (two-stack sliding aggregation)
plus the process-wide live-engine registry the agent/doctor/CLI read.
"""

from .cache import ResultCache
from .engine import (SlidingFold, StandingQueryEngine, live_engines,
                     live_stats, register, unregister)
from .spec import (QUERY_SCHEMA, STATISTICS, QueryError, StandingQuery,
                   load_queries, load_queries_file)

__all__ = ["QUERY_SCHEMA", "STATISTICS", "QueryError", "ResultCache",
           "SlidingFold", "StandingQuery", "StandingQueryEngine",
           "live_engines", "live_stats", "load_queries",
           "load_queries_file", "register", "unregister"]
