"""Digest-keyed standing-query result cache.

Window digests are content-addressed (history/window.py:window_digest
hashes the sketch planes themselves), so cache invalidation here is
EXACT, not heuristic: an entry is keyed on the frozenset of sealed-window
digests the materialized answer covers. If a reader's coverage matches,
the bytes are exactly right — bit-identical to refolding those windows.
If coverage moved (a seal tick landed, eviction dropped the tail,
compaction rewrote the range), the key no longer matches and the entry
is provably stale; there is no TTL, no "probably fine" window.

Accounting is loud: hit / miss / invalidation counters (per query id)
plus a resident-bytes gauge, all in the process registry so `top
metrics`, doctor, and the Prometheus endpoint see the same numbers.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from ..telemetry import registry as tm

_tm_hits = tm.counter(
    "ig_query_cache_hits_total",
    "standing-query result-cache hits (coverage matched exactly)",
    labels=("query",))
_tm_misses = tm.counter(
    "ig_query_cache_misses_total",
    "standing-query result-cache misses (no entry for this coverage)",
    labels=("query",))
_tm_invalidations = tm.counter(
    "ig_query_cache_invalidations_total",
    "standing-query cache entries dropped because coverage moved",
    labels=("query",))
_tm_bytes = tm.gauge(
    "ig_query_cache_bytes",
    "resident bytes across all standing-query cache entries")


class ResultCache:
    """LRU-by-bytes cache of encoded materialized answers.

    Key: (query id, frozenset of covered window digests). A put for a
    query id whose coverage differs from the cached one *replaces* the
    old entry and counts an invalidation — per query there is exactly
    one live coverage, the current one.
    """

    def __init__(self, max_bytes: int = 8 << 20):
        if max_bytes <= 0:
            raise ValueError(f"cache max_bytes must be > 0, got {max_bytes}")
        self.max_bytes = int(max_bytes)
        self._mu = threading.Lock()
        # query id -> (coverage, header, payload, nbytes); OrderedDict
        # gives LRU order (move_to_end on hit).
        self._entries: "OrderedDict[str, tuple[frozenset, dict, bytes, int]]" = OrderedDict()
        self._bytes = 0
        self._hits = 0
        self._misses = 0
        self._invalidations = 0

    # -- internals (call with _mu held) -------------------------------------

    def _drop(self, qid: str, *, invalidation: bool) -> None:
        _cov, _hdr, _payload, n = self._entries.pop(qid)
        self._bytes -= n
        if invalidation:
            self._invalidations += 1
            _tm_invalidations.labels(query=qid).inc()

    # -- public --------------------------------------------------------------

    def get(self, qid: str, coverage: frozenset) -> tuple[dict, bytes] | None:
        """Return (header, payload) iff the cached entry covers exactly
        `coverage`; a coverage mismatch drops the stale entry (counted
        as an invalidation) and reads as a miss."""
        with self._mu:
            ent = self._entries.get(qid)
            if ent is not None and ent[0] == coverage:
                self._entries.move_to_end(qid)
                self._hits += 1
                _tm_hits.labels(query=qid).inc()
                return ent[1], ent[2]
            if ent is not None:  # present but provably stale
                self._drop(qid, invalidation=True)
                _tm_bytes.set(self._bytes)
            self._misses += 1
            _tm_misses.labels(query=qid).inc()
            return None

    def put(self, qid: str, coverage: frozenset, header: dict,
            payload: bytes) -> None:
        nbytes = len(payload) + 512  # header + key bookkeeping estimate
        with self._mu:
            if qid in self._entries:
                stale = self._entries[qid][0] != coverage
                self._drop(qid, invalidation=stale)
            self._entries[qid] = (coverage, dict(header), payload, nbytes)
            self._bytes += nbytes
            # LRU eviction by bytes; never evict the entry just written
            self._entries.move_to_end(qid)
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                victim = next(iter(self._entries))
                if victim == qid:
                    break
                self._drop(victim, invalidation=False)
            _tm_bytes.set(self._bytes)

    def stats(self) -> dict:
        with self._mu:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "hits": self._hits,
                "misses": self._misses,
                "invalidations": self._invalidations,
            }


__all__ = ["ResultCache"]
