"""inspektor_gadget_tpu — a TPU-native streaming-analytics framework.

Re-designed from scratch with the capability surface of Inspektor Gadget
(reference at /root/reference: a Kubernetes-native eBPF observability
framework). Where the reference runs eBPF programs per node and merges JSON
streams client-side, this framework batches events into struct-of-arrays
tensors and maintains mergeable sketches (count-min / HyperLogLog / entropy /
autoencoder anomaly scores) in JAX, merged cluster-wide with jax.lax.psum over
a device mesh.

Layer map (mirrors reference SURVEY §1, re-architected TPU-first):

  sources/    event capture: C++ capture shims + ring buffer bridge, synthetic
              replay generators          (ref: pkg/gadgets/*/tracer/bpf/*.bpf.c)
  columns/    typed column system, filters, sort, formatter, tensorization
                                          (ref: pkg/columns, pkg/parser)
  params/     self-describing param/flag system (ref: pkg/params)
  gadgets/    gadget descriptors + capability protocols + registry
                                          (ref: pkg/gadgets, pkg/gadget-registry)
  operators/  pluggable enrichment pipeline with dependency sort
                                          (ref: pkg/operators)
  containers/ container collection, selectors, pubsub, tracer collection
                                          (ref: pkg/container-collection)
  runtime/    local + distributed (gRPC fan-out) runtimes (ref: pkg/runtime)
  agent/      per-node agent service      (ref: pkg/gadgettracermanager,
                                           pkg/gadget-service)
  ops/        JAX/Pallas sketch kernels: count-min, HLL, entropy, top-k
  models/     autoencoder anomaly scorer (advise-style analytics)
  parallel/   meshes, shardings, psum sketch merges, distributed init
  cli/        auto-generated CLI from the gadget registry (ref: cmd/)
  native/     C++ sources for capture shims and the ring-buffer bridge
"""

__version__ = "0.1.0"
