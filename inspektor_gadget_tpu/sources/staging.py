"""Pinned host staging for the sketch-ingest hot path.

Two pieces, both counted in the telemetry registry:

- PinnedBufferPool: reusable page-aligned (mmap-backed, best-effort
  mlocked) uint32 blocks the native exporter (`ig_source_pop_folded`)
  fills directly — the role pinned perf-ring pages play for the
  reference's BPF side. Page alignment + stable addresses are what lets
  the PJRT host→device DMA run zero-copy from the block; reuse (a pool
  *hit*) is what keeps the allocator out of the 100M-ev/s loop.
- H2DStager: a depth-N double buffer overlapping the host→device
  transfer of batch k+1 with device compute of batch k. A staged block
  is only returned to the pool once its *consumer fence* (the device
  computation that read the staged arrays) completes — correct on every
  backend, including CPU PJRT where `jnp.asarray` may alias the host
  buffer instead of copying it.

The hot path touches exactly one lock per batch (the pool's); everything
else is slot arithmetic.
"""

from __future__ import annotations

import mmap
import threading
import time
from typing import Any, Sequence

import numpy as np

from ..telemetry import counter, gauge

# pinned-buffer-pool telemetry (ISSUE 10 satellite): a healthy steady
# state is ~100% hits after warmup — misses in steady state mean the
# pool is undersized and the allocator is back on the hot path. The
# `lane` label (ISSUE 14: one device lane per chip under sharded
# ingest) keys each pool/stager to its chip; the single-chip path is
# lane "0", so pre-sharding dashboards keep reading the same series.
_tm_pool_hits = counter("ig_ingest_pool_hits_total",
                        "staging blocks served from the pinned pool",
                        ("lane",))
_tm_pool_misses = counter("ig_ingest_pool_misses_total",
                          "staging blocks freshly allocated (pool empty "
                          "or shape mismatch)", ("lane",))
_tm_inflight = gauge("ig_ingest_h2d_inflight",
                     "staged H2D transfers not yet fenced (double-buffer "
                     "occupancy)", ("lane",))


def _alloc_pinned(lanes: int, capacity: int) -> np.ndarray:
    """One page-aligned uint32 block. mmap gives page alignment (and keeps
    the pages stable for DMA); mlock is attempted best-effort — an
    RLIMIT_MEMLOCK refusal degrades to plain page-aligned memory, it never
    fails the pipeline."""
    nbytes = lanes * capacity * 4
    mm = mmap.mmap(-1, max(nbytes, mmap.PAGESIZE))
    arr = np.frombuffer(mm, dtype=np.uint32, count=lanes * capacity)
    arr = arr.reshape(lanes, capacity)  # .base chain keeps mm alive
    try:
        import ctypes
        libc = ctypes.CDLL(None, use_errno=True)
        libc.mlock(ctypes.c_void_p(arr.ctypes.data),
                   ctypes.c_size_t(nbytes))
    except Exception:  # lint: allow-silent-except — mlock is a best-effort optimization (RLIMIT_MEMLOCK refusal is the normal unprivileged case); page-aligned memory without the lock is still correct
        pass
    return arr


class PinnedBufferPool:
    """Free list of identically-shaped (lanes, capacity) uint32 blocks.

    get() pops a reusable block (hit) or allocates a fresh pinned one
    (miss); put() returns a block for reuse. The pool never shrinks below
    what was returned and never grows past `max_free` retained blocks —
    a burst allocates, steady state recycles.
    """

    def __init__(self, capacity: int, lanes: int = 3, max_free: int = 8,
                 lane: int | str = 0):
        self.capacity = int(capacity)
        self.lanes = int(lanes)
        self.max_free = int(max_free)
        self.lane = str(lane)
        self._hits = _tm_pool_hits.labels(lane=self.lane)
        self._misses = _tm_pool_misses.labels(lane=self.lane)
        self._free: list[np.ndarray] = []
        self._mu = threading.Lock()

    def get(self) -> np.ndarray:
        with self._mu:
            if self._free:
                blk = self._free.pop()
                self._hits.inc()
                return blk
        self._misses.inc()
        return _alloc_pinned(self.lanes, self.capacity)

    def put(self, block: np.ndarray) -> None:
        if block.shape != (self.lanes, self.capacity):
            return  # shape changed mid-run (pad growth): drop, don't poison
        with self._mu:
            if len(self._free) < self.max_free:
                self._free.append(block)

    def free_blocks(self) -> int:
        with self._mu:
            return len(self._free)


class H2DStager:
    """Depth-N staged host→device ring.

    stage(block, arrays) dispatches the (async) device put of the host
    lane views and parks (block, fence) in a ring slot; the transfer of
    batch k+1 therefore overlaps device compute of batch k (and deeper,
    at depth > 2). fence(token) pins the newest slot's release to a
    *consumer* output (e.g. the updated bundle's `events` leaf): the
    block returns to the pool only after the computation that read the
    staged arrays completed — the one point the hot path may wait, and
    only when it is >= depth batches ahead of the device.
    """

    def __init__(self, pool: PinnedBufferPool, depth: int = 2,
                 device: Any | None = None, stats: Any | None = None):
        self.pool = pool
        self.depth = max(int(depth), 1)
        # multi-lane mode (ISSUE 14): pin transfers to one chip so lane
        # k+1's H2D overlaps lane k's compute; None keeps the default-
        # device placement (the single-chip path, unchanged)
        self.device = device
        # pipeline health plane (telemetry/pipeline.py PipelineStats):
        # the ring slot the next stage() lands on is a FREE diagnostic —
        # empty means the device already drained everything in flight
        # (host-bound: a starved tick), occupied means the host is a
        # full ring depth ahead and must block (device-bound: a
        # saturated tick, with the block_until_ready stall timed)
        self.stats = stats
        self._lane_i = int(pool.lane) if str(pool.lane).isdigit() else 0
        self._inflight = _tm_inflight.labels(lane=pool.lane)
        self._slots: list[tuple[np.ndarray, Any] | None] = [None] * self.depth
        self._i = 0

    def _occupied(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    def stage(self, block: np.ndarray,
              arrays: Sequence[np.ndarray]) -> tuple:
        import jax
        import jax.numpy as jnp

        old = self._slots[self._i]
        if old is not None:
            if self.stats is not None:
                t0 = time.perf_counter()
                self._retire(old)
                self.stats.note_saturated(time.perf_counter() - t0,
                                          lane=self._lane_i)
            else:
                self._retire(old)
        elif self.stats is not None:
            self.stats.note_starved(lane=self._lane_i)
        if self.device is not None:
            devs = tuple(jax.device_put(a, self.device) for a in arrays)
        else:
            devs = tuple(jnp.asarray(a) for a in arrays)
        self._inflight.inc()
        self._slots[self._i] = (block, devs)
        self.last_slot = self._i
        self._i = (self._i + 1) % self.depth
        if self.stats is not None:
            self.stats.note_occupancy("h2d", self._occupied(),
                                      lane=self._lane_i)
        return devs

    def fence(self, token: Any) -> None:
        """Attach the consumer's output to the most recently staged slot;
        its block is released only once `token` is ready."""
        self.fence_slot((self._i - 1) % self.depth, token)

    def fence_slot(self, j: int, token: Any) -> None:
        """Fence a SPECIFIC slot (the `last_slot` captured at stage time).
        The sharded ingest plane stages a lane, parks it in the open
        round, and only learns its consumer token when the round
        dispatches — by which point another thread's flush may have
        staged a filler into the same stager, so "most recent" is not
        necessarily the right slot."""
        slot = self._slots[j]
        if slot is not None:
            self._slots[j] = (slot[0], token)

    def _retire(self, slot: tuple[np.ndarray, Any]) -> None:
        import jax
        block, fence = slot
        jax.block_until_ready(fence)
        self._inflight.dec()
        self.pool.put(block)

    def drain(self) -> None:
        """Block on every outstanding fence and return all blocks — run
        teardown / before a harvest that must see all updates applied."""
        for j, slot in enumerate(self._slots):
            if slot is not None:
                self._retire(slot)
                self._slots[j] = None
        if self.stats is not None:
            # teardown accounting: the occupancy gauge must read 0 once
            # every in-flight block is back in the pool
            self.stats.note_occupancy("h2d", 0, lane=self._lane_i)
