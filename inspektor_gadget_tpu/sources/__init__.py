"""Event sources: the capture layer feeding gadgets.

Native C++ sources (native/) capture or synthesize events into lock-free
rings; the ctypes bridge pops them as struct-of-arrays batches (bridge.py).
A pure-Python synthetic source provides a no-toolchain fallback with the
same interface. Replay sources make every test deterministic — the analogue
of the reference's fake-container runners (internal/test/runner.go).
"""

from .batch import EventBatch, BATCH_COLUMNS, FoldedBatch, FOLDED_LANES
from .staging import H2DStager, PinnedBufferPool
from .bridge import (
    NativeCapture,
    native_available,
    make_cfg,
    sources_stats,
    SRC_SYNTH_EXEC,
    SRC_SYNTH_TCP,
    SRC_SYNTH_DNS,
    SRC_PROC_EXEC,
    SRC_PROC_TCP,
    SRC_FANOTIFY_EXEC,
    SRC_FANOTIFY_OPEN,
    SRC_MOUNTINFO,
    SRC_SOCK_DIAG,
    SRC_KMSG_OOM,
    SRC_PTRACE,
    SRC_FANOTIFY_RUNC,
    SRC_PERF_CPU,
)
from .synthetic import PySyntheticSource

__all__ = [
    "EventBatch", "BATCH_COLUMNS", "FoldedBatch", "FOLDED_LANES",
    "H2DStager", "PinnedBufferPool",
    "NativeCapture", "native_available", "make_cfg", "sources_stats",
    "SRC_SYNTH_EXEC", "SRC_SYNTH_TCP", "SRC_SYNTH_DNS",
    "SRC_PROC_EXEC", "SRC_PROC_TCP",
    "SRC_FANOTIFY_EXEC", "SRC_FANOTIFY_OPEN", "SRC_MOUNTINFO",
    "SRC_SOCK_DIAG", "SRC_KMSG_OOM", "SRC_PTRACE", "SRC_FANOTIFY_RUNC",
    "SRC_PERF_CPU",
    "PySyntheticSource",
]
