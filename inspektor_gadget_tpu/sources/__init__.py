"""Event sources: the capture layer feeding gadgets.

Native C++ sources (native/) capture or synthesize events into lock-free
rings; the ctypes bridge pops them as struct-of-arrays batches (bridge.py).
A pure-Python synthetic source provides a no-toolchain fallback with the
same interface. Replay sources make every test deterministic — the analogue
of the reference's fake-container runners (internal/test/runner.go).
"""

from .batch import EventBatch, BATCH_COLUMNS
from .bridge import (
    NativeCapture,
    native_available,
    SRC_SYNTH_EXEC,
    SRC_SYNTH_TCP,
    SRC_SYNTH_DNS,
    SRC_PROC_EXEC,
    SRC_PROC_TCP,
)
from .synthetic import PySyntheticSource

__all__ = [
    "EventBatch", "BATCH_COLUMNS",
    "NativeCapture", "native_available",
    "SRC_SYNTH_EXEC", "SRC_SYNTH_TCP", "SRC_SYNTH_DNS",
    "SRC_PROC_EXEC", "SRC_PROC_TCP",
    "PySyntheticSource",
]
