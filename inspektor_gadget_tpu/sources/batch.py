"""EventBatch: the struct-of-arrays unit of transport.

Fixed-capacity columnar batches with an explicit valid-count and cumulative
loss counters — the contract every hop preserves (capture ring → bridge →
sketch plane → agent stream), reproducing the reference's end-to-end loss
accounting (perf LostSamples → tracer warn events → stream EventLost →
seq-gap checks; SURVEY §5 failure detection).
"""

from __future__ import annotations

import dataclasses

import numpy as np

# Canonical wire columns (matches native/events.h Event layout).
BATCH_COLUMNS: dict[str, np.dtype] = {
    "ts": np.dtype(np.uint64),
    "key_hash": np.dtype(np.uint64),
    "aux1": np.dtype(np.uint64),
    "aux2": np.dtype(np.uint64),
    "mntns": np.dtype(np.uint64),
    "pid": np.dtype(np.uint32),
    "ppid": np.dtype(np.uint32),
    "uid": np.dtype(np.uint32),
    "kind": np.dtype(np.uint32),
}


@dataclasses.dataclass
class EventBatch:
    cols: dict[str, np.ndarray]
    count: int                 # valid rows (rest is padding)
    seq: int = 0               # first event's sequence number
    drops: int = 0             # cumulative upstream drops at pop time
    comm: np.ndarray | None = None  # (capacity, 8) uint8 display prefixes

    @property
    def capacity(self) -> int:
        return len(next(iter(self.cols.values())))

    def mask(self) -> np.ndarray:
        m = np.zeros(self.capacity, dtype=bool)
        m[: self.count] = True
        return m

    @classmethod
    def alloc(cls, capacity: int, with_comm: bool = True) -> "EventBatch":
        cols = {n: np.zeros(capacity, dtype=dt) for n, dt in BATCH_COLUMNS.items()}
        comm = np.zeros((capacity, 8), dtype=np.uint8) if with_comm else None
        return cls(cols=cols, count=0, comm=comm)

    def comm_str(self, i: int) -> str:
        if self.comm is None:
            return ""
        raw = bytes(self.comm[i])
        return raw.split(b"\0", 1)[0].decode("utf-8", "replace")


# Lane order of the folded SoA block — rows 0..2 of one (lanes >= 3,
# capacity) uint32 array per batch: a single pinned allocation carries
# all lanes, so one pool slot == one batch and the native exporter fills
# all three with one call. Blocks may carry extra rows (tpusketch's
# staging pool allocates 4 lanes so the same pool serves the EventBatch
# path); a block's shape must match the pool it came from or put()
# drops it.
FOLDED_LANES = ("keys", "weights", "mntns")


@dataclasses.dataclass
class FoldedBatch:
    """Pre-folded struct-of-arrays batch — the sketch plane's native unit.

    Produced by `ig_source_pop_folded` (native/api.cc) draining a capture
    ring directly into caller-owned uint32 lanes: `keys` is the xor-folded
    key_hash (the sketch key width, no Python decode/fold pass), `weights`
    the per-event weight (1 today; reserved for capture-side aggregation),
    `mntns` the xor-folded mount-ns id (exact for real ns inodes < 2^32 —
    the late-enrichment display key). The lanes are rows 0..2 of ONE
    pinned (lanes >= 3, capacity) block owned by a PinnedBufferPool slot;
    consumers must release the block back to the SAME pool once the H2D
    transfer completes.
    """

    lanes: "np.ndarray"        # (>=3, capacity) uint32 — pool-owned block
    count: int                 # valid rows (rest is padding)
    seq: int = 0               # first event's sequence number
    drops: int = 0             # cumulative upstream drops at pop time

    @property
    def capacity(self) -> int:
        return self.lanes.shape[1]

    @property
    def keys(self) -> "np.ndarray":
        return self.lanes[0]

    @property
    def weights(self) -> "np.ndarray":
        return self.lanes[1]

    @property
    def mntns(self) -> "np.ndarray":
        return self.lanes[2]
