"""EventBatch: the struct-of-arrays unit of transport.

Fixed-capacity columnar batches with an explicit valid-count and cumulative
loss counters — the contract every hop preserves (capture ring → bridge →
sketch plane → agent stream), reproducing the reference's end-to-end loss
accounting (perf LostSamples → tracer warn events → stream EventLost →
seq-gap checks; SURVEY §5 failure detection).
"""

from __future__ import annotations

import dataclasses

import numpy as np

# Canonical wire columns (matches native/events.h Event layout).
BATCH_COLUMNS: dict[str, np.dtype] = {
    "ts": np.dtype(np.uint64),
    "key_hash": np.dtype(np.uint64),
    "aux1": np.dtype(np.uint64),
    "aux2": np.dtype(np.uint64),
    "mntns": np.dtype(np.uint64),
    "pid": np.dtype(np.uint32),
    "ppid": np.dtype(np.uint32),
    "uid": np.dtype(np.uint32),
    "kind": np.dtype(np.uint32),
}


@dataclasses.dataclass
class EventBatch:
    cols: dict[str, np.ndarray]
    count: int                 # valid rows (rest is padding)
    seq: int = 0               # first event's sequence number
    drops: int = 0             # cumulative upstream drops at pop time
    comm: np.ndarray | None = None  # (capacity, 8) uint8 display prefixes
    # pipeline-health watermarks (epoch seconds; 0.0 = unstamped): one
    # stamp per BATCH, never per event — host lag = pop_ts − oldest_ts,
    # device lag = dispatch − pop_ts (telemetry/pipeline.py)
    pop_ts: float = 0.0        # wall clock when the host popped the batch
    oldest_ts: float = 0.0     # oldest event timestamp in the batch

    @property
    def capacity(self) -> int:
        return len(next(iter(self.cols.values())))

    def mask(self) -> np.ndarray:
        m = np.zeros(self.capacity, dtype=bool)
        m[: self.count] = True
        return m

    @classmethod
    def alloc(cls, capacity: int, with_comm: bool = True) -> "EventBatch":
        cols = {n: np.zeros(capacity, dtype=dt) for n, dt in BATCH_COLUMNS.items()}
        comm = np.zeros((capacity, 8), dtype=np.uint8) if with_comm else None
        return cls(cols=cols, count=0, comm=comm)

    def comm_str(self, i: int) -> str:
        if self.comm is None:
            return ""
        raw = bytes(self.comm[i])
        return raw.split(b"\0", 1)[0].decode("utf-8", "replace")


# Lane order of the folded SoA block — rows 0..3 of one (lanes >= 3,
# capacity) uint32 array per batch: a single pinned allocation carries
# all lanes, so one pool slot == one batch and the native exporter fills
# them with one call. The values lane (row 3, per-event magnitude for
# the DDSketch quantile plane) is optional: 3-lane blocks simply don't
# carry it and `FoldedBatch.values` reports None. Blocks may carry extra
# rows (tpusketch's staging pool allocates 4+ lanes so the same pool
# serves the EventBatch path); a block's shape must match the pool it
# came from or put() drops it.
FOLDED_LANES = ("keys", "weights", "mntns", "values")


@dataclasses.dataclass
class FoldedBatch:
    """Pre-folded struct-of-arrays batch — the sketch plane's native unit.

    Produced by `ig_source_pop_folded` (native/api.cc) draining a capture
    ring directly into caller-owned uint32 lanes: `keys` is the xor-folded
    key_hash (the sketch key width, no Python decode/fold pass), `weights`
    the per-event weight (1 today; reserved for capture-side aggregation),
    `mntns` the xor-folded mount-ns id (exact for real ns inodes < 2^32 —
    the late-enrichment display key). Blocks popped through
    `ig_source_pop_folded2` additionally fill `values` (row 3): the
    per-event magnitude — latency ns or byte count, saturate-cast from
    the kind's aux1, 0 for kinds without one — feeding the DDSketch
    quantile plane. The lanes are the leading rows of ONE pinned
    (lanes >= 3, capacity) block owned by a PinnedBufferPool slot;
    consumers must release the block back to the SAME pool once the H2D
    transfer completes.
    """

    lanes: "np.ndarray"        # (>=3, capacity) uint32 — pool-owned block
    count: int                 # valid rows (rest is padding)
    seq: int = 0               # first event's sequence number
    drops: int = 0             # cumulative upstream drops at pop time
    # True only when the producer actually FILLED row 3 (pop_folded2):
    # legacy 4-lane pool blocks keep row 3 as scratch, so shape alone
    # cannot prove the lane holds real magnitudes
    has_values: bool = False
    # pipeline-health watermarks (epoch seconds; 0.0 = unstamped). The
    # folded lanes carry no per-event timestamp column, so oldest_ts is
    # the previous pop's wall clock — a documented UPPER-bound watermark
    # (no event in this batch can predate the last drain that emptied
    # the ring region it came from)
    pop_ts: float = 0.0
    oldest_ts: float = 0.0

    @property
    def capacity(self) -> int:
        return self.lanes.shape[1]

    @property
    def keys(self) -> "np.ndarray":
        return self.lanes[0]

    @property
    def weights(self) -> "np.ndarray":
        return self.lanes[1]

    @property
    def mntns(self) -> "np.ndarray":
        return self.lanes[2]

    @property
    def values(self) -> "np.ndarray | None":
        """Per-event magnitude lane (uint32 latency ns / bytes), or None
        for batches popped without the value lane."""
        if self.has_values and self.lanes.shape[0] >= 4:
            return self.lanes[3]
        return None
