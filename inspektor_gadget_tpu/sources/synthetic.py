"""Pure-Python synthetic source — same interface as NativeCapture.

The no-toolchain fallback (the role pkg/standardgadgets plays for the
reference when CO-RE/BTF is unavailable: same events, slower path,
standardtracerbase.go:40-81). Deterministic per seed; numpy-vectorized.
"""

from __future__ import annotations

import time

import numpy as np

from ..columns.columns import fnv1a64
from .batch import EventBatch


class PySyntheticSource:
    def __init__(self, kind: int = 1, *, seed: int = 0, vocab: int = 1000,
                 zipf_s: float = 1.2, batch_size: int = 8192):
        self.kind = kind
        self.batch_size = batch_size
        self._rng = np.random.default_rng(seed or 42)
        self._names = [f"proc-{i}" for i in range(vocab)]
        self._hashes = np.array([fnv1a64(n) for n in self._names], dtype=np.uint64)
        # zipf pmf over a finite vocab
        ranks = np.arange(1, vocab + 1, dtype=np.float64)
        pmf = ranks ** (-zipf_s)
        self._pmf = pmf / pmf.sum()
        self._vocab = {int(h): n for h, n in zip(self._hashes, self._names)}
        self._seq = 0

    def start(self) -> None:  # interface parity
        pass

    def stop(self) -> None:
        pass

    def close(self) -> None:
        pass

    def generate(self, n: int | None = None) -> EventBatch:
        n = n or self.batch_size
        idx = self._rng.choice(len(self._pmf), size=n, p=self._pmf)
        b = EventBatch.alloc(n, with_comm=False)
        b.cols["ts"][:] = time.time_ns()
        b.cols["key_hash"][:] = self._hashes[idx]
        b.cols["mntns"][:] = np.uint64(4026531840) + (idx % 64).astype(np.uint64)
        b.cols["pid"][:] = self._rng.integers(1000, 51000, n, dtype=np.uint32)
        b.cols["uid"][:] = self._rng.integers(0, 4, n, dtype=np.uint32)
        b.cols["kind"][:] = self.kind
        b.cols["aux1"][:] = self._rng.integers(0, 2**63, n, dtype=np.uint64)
        b.cols["aux2"][:] = self._rng.integers(0, 2**16, n, dtype=np.uint64)
        b.count = n
        b.seq = self._seq
        self._seq += n
        # pipeline-health watermarks: synthesis IS the pop, so both
        # stamps land on the same clock read (host lag 0 by definition —
        # the device-lag watermark downstream stays meaningful)
        b.pop_ts = b.oldest_ts = time.time()
        return b

    pop = generate

    def drops(self) -> int:
        return 0

    def vocab_lookup(self, key_hash: int) -> str:
        return self._vocab.get(int(key_hash), "")
