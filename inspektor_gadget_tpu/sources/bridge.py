"""ctypes bridge to libigcapture.so — the cgo analogue.

Loads (building on demand) the native capture library and exposes sources
that pop struct-of-arrays EventBatches with zero per-event Python work:
numpy buffers are handed to C++ which fills them directly.

Reference contract being replaced: cilium/ebpf perf.Reader → Go structs
(pkg/gadgets/*/tracer/tracer.go run loops). Loss/seq accounting carried
through (tracer.go:148-151's LostSamples handling).
"""

from __future__ import annotations

import ctypes
import subprocess
import time
from pathlib import Path

import numpy as np

from .batch import EventBatch, FoldedBatch

SRC_SYNTH_EXEC = 1
SRC_SYNTH_TCP = 2
SRC_SYNTH_DNS = 3
SRC_PROC_EXEC = 100
SRC_PROC_TCP = 101
SRC_FANOTIFY_EXEC = 102
SRC_FANOTIFY_OPEN = 103
SRC_MOUNTINFO = 104
SRC_SOCK_DIAG = 105
SRC_KMSG_OOM = 106
SRC_PTRACE = 108
SRC_FANOTIFY_RUNC = 109
SRC_PERF_CPU = 110
SRC_BLK_TRACE = 111
SRC_TCP_BYTES = 112
SRC_AUDIT = 113
SRC_CAP_TRACE = 114
SRC_FS_TRACE = 115
SRC_SOCK_STATE = 116
SRC_SIG_TRACE = 117
SRC_PKT_DNS = 200
SRC_PKT_SNI = 201
SRC_PKT_FLOW = 202

# kinds that take a "key=value\x1f..." config string (create_cfg path)
_CFG_KINDS = {SRC_FANOTIFY_OPEN, SRC_MOUNTINFO, SRC_SOCK_DIAG, SRC_KMSG_OOM,
              SRC_PTRACE, SRC_FANOTIFY_RUNC, SRC_PERF_CPU, SRC_BLK_TRACE,
              SRC_TCP_BYTES, SRC_AUDIT, SRC_CAP_TRACE, SRC_FS_TRACE,
              SRC_SOCK_STATE, SRC_SIG_TRACE}


def make_cfg(**kw) -> str:
    """Build the config string for cfg-kind sources. A cmd list is joined
    with \\x1e (unit separators keep arbitrary argv content safe)."""
    parts = []
    for k, v in kw.items():
        if v is None:
            continue
        if isinstance(v, (list, tuple)):
            v = "\x1e".join(str(x) for x in v)
        parts.append(f"{k}={v}")
    return "\x1f".join(parts)

_NATIVE_DIR = Path(__file__).resolve().parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "libigcapture.so"

_lib = None
_lib_err: str | None = None


def _load():
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    try:
        lib = _load_and_bind(rebuild=not _LIB_PATH.exists())
    except AttributeError:
        # a stale libigcapture.so from before a symbol was added: force a
        # rebuild once, then rebind — else every native call would crash
        # instead of degrading
        try:
            lib = _load_and_bind(rebuild=True)
        except (OSError, subprocess.CalledProcessError, AttributeError) as e:
            _lib_err = str(e)
            return None
    except (OSError, subprocess.CalledProcessError) as e:
        _lib_err = str(e)
        return None
    _lib = lib
    return lib


def _load_and_bind(rebuild: bool):
    if rebuild:
        subprocess.run(
            ["make", "-C", str(_NATIVE_DIR), "-B"],
            check=True, capture_output=True, text=True,
        )
    lib = ctypes.CDLL(str(_LIB_PATH))

    u64, u32, i64, f64 = (ctypes.c_uint64, ctypes.c_uint32, ctypes.c_int64,
                          ctypes.c_double)
    p64 = ctypes.POINTER(ctypes.c_uint64)
    p32 = ctypes.POINTER(ctypes.c_uint32)
    lib.ig_source_create.argtypes = [u32, u64, f64, u32, f64, u32]
    lib.ig_source_create.restype = u64
    lib.ig_source_create_cfg.argtypes = [u32, ctypes.c_char_p, u32]
    lib.ig_source_create_cfg.restype = u64
    lib.ig_source_set_filter.argtypes = [u64, p64, i64]
    lib.ig_source_set_filter.restype = ctypes.c_int
    lib.ig_source_filtered.argtypes = [u64]
    lib.ig_source_filtered.restype = u64
    lib.ig_ptrace_exit_status.argtypes = [u64]
    lib.ig_ptrace_exit_status.restype = ctypes.c_int
    lib.ig_perf_supported.argtypes = []
    lib.ig_perf_supported.restype = ctypes.c_int
    lib.ig_blktrace_supported.argtypes = []
    lib.ig_blktrace_supported.restype = ctypes.c_int
    lib.ig_tcpinfo_supported.argtypes = []
    lib.ig_tcpinfo_supported.restype = ctypes.c_int
    lib.ig_audit_supported.argtypes = []
    lib.ig_audit_supported.restype = ctypes.c_int
    lib.ig_captrace_supported.argtypes = []
    lib.ig_captrace_supported.restype = ctypes.c_int
    lib.ig_fstrace_supported.argtypes = []
    lib.ig_fstrace_supported.restype = ctypes.c_int
    lib.ig_sockstate_supported.argtypes = []
    lib.ig_sockstate_supported.restype = ctypes.c_int
    lib.ig_sigtrace_supported.argtypes = []
    lib.ig_sigtrace_supported.restype = ctypes.c_int
    for fn in ("ig_source_start", "ig_source_stop", "ig_source_destroy"):
        getattr(lib, fn).argtypes = [u64]
        getattr(lib, fn).restype = ctypes.c_int
    lib.ig_source_pop_batch.argtypes = [u64, i64] + [p64] * 5 + [p32] * 4 + [
        ctypes.c_char_p]
    lib.ig_source_pop_batch.restype = i64
    lib.ig_source_pop_folded.argtypes = [u64, i64, p32, p32, p32]
    lib.ig_source_pop_folded.restype = i64
    lib.ig_source_pop_folded2.argtypes = [u64, i64, p32, p32, p32, p32]
    lib.ig_source_pop_folded2.restype = i64
    lib.ig_source_drops.argtypes = [u64]
    lib.ig_source_drops.restype = u64
    lib.ig_source_produced.argtypes = [u64]
    lib.ig_source_produced.restype = u64
    lib.ig_synth_generate.argtypes = [u64, i64, p64, p64, p32, p32]
    lib.ig_synth_generate.restype = i64
    lib.ig_synth_generate_folded.argtypes = [u64, i64, p32]
    lib.ig_synth_generate_folded.restype = i64
    lib.ig_vocab_lookup.argtypes = [u64, u64, ctypes.c_char_p, i64]
    lib.ig_vocab_lookup.restype = i64
    lib.ig_vocab_lookup_batch.argtypes = [
        u64, p64, i64, ctypes.c_char_p, i64,
        ctypes.POINTER(ctypes.c_int32)]
    lib.ig_vocab_lookup_batch.restype = i64
    lib.ig_sources_stats.argtypes = [p64, p32] + [p64] * 7 + [i64]
    lib.ig_sources_stats.restype = i64
    lib.ig_fanotify_supported.argtypes = []
    lib.ig_fanotify_supported.restype = ctypes.c_int
    lib.ig_containers_set.argtypes = [u64, ctypes.c_char_p, i64]
    lib.ig_containers_remove.argtypes = [u64]
    lib.ig_containers_lookup.argtypes = [u64, ctypes.c_char_p, i64]
    lib.ig_containers_lookup.restype = i64
    lib.ig_containers_count.restype = i64
    return lib


# -- containers map (ref: pkg/gadgettracermanager/containers-map) -----------

def containers_map_set(mntns: int, name: str) -> None:
    lib = _load()
    if lib is not None:
        raw = name.encode("utf-8", "replace")
        lib.ig_containers_set(mntns, raw, len(raw))


def containers_map_remove(mntns: int) -> None:
    lib = _load()
    if lib is not None:
        lib.ig_containers_remove(mntns)


def containers_map_lookup(mntns: int) -> str:
    lib = _load()
    if lib is None:
        return ""
    buf = ctypes.create_string_buffer(256)
    n = lib.ig_containers_lookup(mntns, buf, 256)
    return buf.raw[:n].decode("utf-8", "replace") if n > 0 else ""


def native_available() -> bool:
    return _load() is not None


def blktrace_supported() -> bool:
    """Per-IO block window (tracefs block events) available on this host."""
    lib = _load()
    return lib is not None and bool(lib.ig_blktrace_supported())


def tcpinfo_supported() -> bool:
    """Per-connection TCP byte counters (sock_diag INET_DIAG_INFO)."""
    lib = _load()
    return lib is not None and bool(lib.ig_tcpinfo_supported())


def fanotify_supported() -> bool:
    """fanotify mount marks available (needs CAP_SYS_ADMIN)."""
    lib = _load()
    return lib is not None and bool(lib.ig_fanotify_supported())


def audit_supported() -> bool:
    """Host-wide kernel audit window (NETLINK_AUDIT readlog multicast)."""
    lib = _load()
    return lib is not None and bool(lib.ig_audit_supported())


def captrace_supported() -> bool:
    """cap_capable tracepoint window (tracefs, kernel >= 6.7)."""
    lib = _load()
    return lib is not None and bool(lib.ig_captrace_supported())


def fstrace_supported() -> bool:
    """raw_syscalls tracepoint window (host-wide fsslower)."""
    lib = _load()
    return lib is not None and bool(lib.ig_fstrace_supported())


def sockstate_supported() -> bool:
    """inet_sock_set_state tracepoint (event-driven trace/tcp)."""
    lib = _load()
    return lib is not None and bool(lib.ig_sockstate_supported())


def sigtrace_supported() -> bool:
    """signal_generate tracepoint (full sigsnoop parity)."""
    lib = _load()
    return lib is not None and bool(lib.ig_sigtrace_supported())


_SRC_KIND_NAMES = {
    SRC_SYNTH_EXEC: "synth/exec", SRC_SYNTH_TCP: "synth/tcp",
    SRC_SYNTH_DNS: "synth/dns", SRC_PROC_EXEC: "netlink/proc",
    SRC_PROC_TCP: "proc/tcp", SRC_FANOTIFY_EXEC: "fanotify/exec",
    SRC_FANOTIFY_OPEN: "fanotify/open", SRC_MOUNTINFO: "mountinfo",
    SRC_SOCK_DIAG: "sock_diag", SRC_KMSG_OOM: "kmsg/oom",
    SRC_PTRACE: "ptrace", SRC_FANOTIFY_RUNC: "fanotify/runc",
    SRC_PERF_CPU: "perf/cpu", SRC_BLK_TRACE: "blk/trace",
    SRC_TCP_BYTES: "sock_diag/tcpinfo", SRC_AUDIT: "netlink/audit",
    SRC_CAP_TRACE: "tracefs/cap", SRC_FS_TRACE: "tracefs/fs",
    SRC_SOCK_STATE: "tracefs/sock", SRC_SIG_TRACE: "tracefs/signal",
    SRC_PKT_DNS: "pkt/dns",
    SRC_PKT_SNI: "pkt/sni", SRC_PKT_FLOW: "pkt/flow",
}


def sources_stats(cap: int = 256) -> list[dict]:
    """Enumerate every live native capture source with self-stats (the
    top/ebpf contract: reference pkg/gadgets/top/ebpf/tracer.go:55-418 —
    per-program runtime + counters; here per-source capture-thread CPU
    time, ring occupancy/capacity, produced/consumed/drops/filtered)."""
    lib = _load()
    if lib is None:
        return []
    ids = np.zeros(cap, np.uint64)
    kinds = np.zeros(cap, np.uint32)
    cols = [np.zeros(cap, np.uint64) for _ in range(7)]
    n = lib.ig_sources_stats(
        _p64(ids), _p32(kinds), *[_p64(c) for c in cols], cap)
    if n <= 0:
        return []
    produced, consumed, drops, filtered, ring_len, ring_cap, cpu_ns = cols
    out = []
    for i in range(int(n)):
        k = int(kinds[i])
        out.append({
            "id": int(ids[i]),
            "kind": k,
            "kind_name": _SRC_KIND_NAMES.get(k, str(k)),
            "produced": int(produced[i]),
            "consumed": int(consumed[i]),
            "drops": int(drops[i]),
            "filtered": int(filtered[i]),
            "ring_len": int(ring_len[i]),
            "ring_cap": int(ring_cap[i]),
            "cpu_ns": int(cpu_ns[i]),
        })
    return out


def _p64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


def _p32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))


class NativeCapture:
    """A native capture source popping columnar EventBatches."""

    def __init__(self, kind: int, *, seed: int = 0, rate: float = 0.0,
                 vocab: int = 1000, zipf_s: float = 1.2, ring_pow2: int = 20,
                 batch_size: int = 8192, cfg: str = ""):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native capture unavailable: {_lib_err}")
        self._lib = lib
        if kind in _CFG_KINDS:
            self._h = lib.ig_source_create_cfg(
                kind, cfg.encode("utf-8", "replace"), ring_pow2)
        else:
            self._h = lib.ig_source_create(kind, seed, rate, vocab, zipf_s,
                                           ring_pow2)
        if self._h == 0:
            raise ValueError(f"unknown source kind {kind}")
        self.batch_size = batch_size
        self._batch = EventBatch.alloc(batch_size)
        self._seq = 0
        self.kind = kind
        # pipeline-health watermark: wall clock of the last pop that
        # drained this source's ring — the folded path's oldest_ts
        # upper bound (folded lanes carry no per-event timestamp)
        self._last_pop_ts = 0.0

    def start(self) -> None:
        self._lib.ig_source_start(self._h)

    def stop(self) -> None:
        self._lib.ig_source_stop(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.ig_source_destroy(self._h)
            self._h = 0

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        # Stop capture (joins the thread, releases fds) but keep the native
        # handle alive: the vocab side-table must stay resolvable after the
        # window closes so labels (paths, syscall lines, comms) can still be
        # looked up from drained rows. The handle is freed on explicit
        # close() or GC.
        self.stop()

    def __del__(self):
        try:
            self.close()
        except Exception:  # lint: allow-silent-except — logging is unsafe during interpreter shutdown
            pass

    def pop(self) -> EventBatch:
        """Pop up to batch_size events; reuses one internal buffer set."""
        b = self._batch
        c = b.cols
        got = self._lib.ig_source_pop_batch(
            self._h, self.batch_size,
            _p64(c["ts"]), _p64(c["key_hash"]), _p64(c["aux1"]),
            _p64(c["aux2"]), _p64(c["mntns"]),
            _p32(c["pid"]), _p32(c["ppid"]), _p32(c["uid"]), _p32(c["kind"]),
            b.comm.ctypes.data_as(ctypes.c_char_p),
        )
        if got < 0:
            raise RuntimeError("pop on destroyed source")
        b.count = int(got)
        b.seq = self._seq
        self._seq += int(got)
        b.drops = int(self._lib.ig_source_drops(self._h))
        # batch-grain watermarks: one clock read + one vectorized min —
        # the native ts column is CLOCK_REALTIME ns, comparable with
        # time.time() epoch seconds
        b.pop_ts = time.time()
        b.oldest_ts = (float(c["ts"][: b.count].min()) / 1e9
                       if b.count else b.pop_ts)
        self._last_pop_ts = b.pop_ts
        return b

    def pop_folded(self, block: np.ndarray,
                   with_values: bool = False) -> FoldedBatch:
        """Drain the ring straight into a (3+, capacity) pre-folded SoA
        block — keys/weights/mntns uint32 lanes, filled by ONE native
        crossing (`ig_source_pop_folded`) with zero per-event Python
        work. `block` is typically a PinnedBufferPool slot wrapped
        zero-copy (np.frombuffer over the pinned mmap), so the lanes the
        C++ exporter writes ARE the H2D staging buffer: no Event structs,
        no decode, no separate fold pass. With `with_values=True` the
        block needs a 4th lane and `ig_source_pop_folded2` additionally
        fills it with the per-event magnitude (latency ns / bytes,
        saturate-cast aux1; 0 for kinds without one) — the DDSketch
        quantile plane's value lane, same single crossing."""
        need = 4 if with_values else 3
        if block.shape[0] < need or block.dtype != np.uint32:
            raise ValueError(
                f"pop_folded needs a ({need}, capacity) uint32 block")
        if with_values:
            got = self._lib.ig_source_pop_folded2(
                self._h, block.shape[1],
                _p32(block[0]), _p32(block[1]), _p32(block[2]),
                _p32(block[3]))
        else:
            got = self._lib.ig_source_pop_folded(
                self._h, block.shape[1],
                _p32(block[0]), _p32(block[1]), _p32(block[2]))
        if got < 0:
            raise RuntimeError("pop_folded on destroyed source")
        now = time.time()
        fb = FoldedBatch(lanes=block, count=int(got), seq=self._seq,
                         drops=int(self._lib.ig_source_drops(self._h)),
                         has_values=with_values,
                         pop_ts=now,
                         oldest_ts=self._last_pop_ts or now)
        self._seq += int(got)
        self._last_pop_ts = now
        return fb

    def generate(self, n: int) -> EventBatch:
        """Synchronous synthetic generation (bench path; no capture thread)."""
        b = EventBatch.alloc(n, with_comm=False)
        c = b.cols
        got = self._lib.ig_synth_generate(
            self._h, n, _p64(c["key_hash"]), _p64(c["mntns"]),
            _p32(c["pid"]), _p32(c["uid"]),
        )
        if got < 0:
            raise RuntimeError("generate on non-synthetic source")
        b.count = int(got)
        # the fast generate path fills the sketch-relevant columns only;
        # stamp kind/ts host-side
        ev_kind = {SRC_SYNTH_EXEC: 1, SRC_SYNTH_TCP: 4, SRC_SYNTH_DNS: 7}.get(
            self.kind, self.kind)
        b.cols["kind"][: b.count] = ev_kind
        b.cols["ts"][: b.count] = np.uint64(time.time_ns())
        b.pop_ts = b.oldest_ts = time.time()
        self._last_pop_ts = b.pop_ts
        return b

    def generate_folded(self, n: int, out: np.ndarray | None = None) -> np.ndarray:
        """Synchronous synthetic generation of xor-folded uint32 keys (the
        sketch plane's native width) straight into a staging buffer — no
        Event structs, no separate fold pass (bench hot path). A caller
        buffer that cannot hold n uint32 keys is an ERROR, not a silent
        fresh allocation: hot-path callers ignore the return value and
        would otherwise sketch the buffer's stale previous contents."""
        if out is None:
            out = np.empty(n, dtype=np.uint32)
        elif out.size < n or out.dtype != np.uint32:
            raise ValueError(
                f"generate_folded needs a uint32 buffer of >= {n} "
                f"entries, got {out.dtype}[{out.size}]")
        got = self._lib.ig_synth_generate_folded(self._h, n, _p32(out))
        if got < 0:
            raise RuntimeError("generate_folded on non-synthetic source")
        return out[:got]

    def drops(self) -> int:
        return int(self._lib.ig_source_drops(self._h))

    def produced(self) -> int:
        return int(self._lib.ig_source_produced(self._h))

    def set_filter(self, mntns_ids) -> None:
        """Install the capture-side mntns filter (None clears). The filter
        runs in the C++ capture thread before events reach the ring —
        the tracer-collection mntnsset-map contract."""
        if mntns_ids is None:
            self._lib.ig_source_set_filter(
                self._h, ctypes.cast(None, ctypes.POINTER(ctypes.c_uint64)), 0)
            return
        arr = np.fromiter(mntns_ids, dtype=np.uint64)
        # an empty-but-present filter blocks everything, matching an empty
        # mntns map in the reference
        if arr.size == 0:
            arr = np.zeros(1, dtype=np.uint64)
            self._lib.ig_source_set_filter(self._h, _p64(arr), 0)
            return
        self._lib.ig_source_set_filter(self._h, _p64(arr), arr.size)

    def filtered(self) -> int:
        return int(self._lib.ig_source_filtered(self._h))

    def ptrace_exit_status(self) -> int:
        return int(self._lib.ig_ptrace_exit_status(self._h))

    def vocab_lookup(self, key_hash: int) -> str:
        buf = ctypes.create_string_buffer(256)
        n = self._lib.ig_vocab_lookup(self._h, key_hash, buf, 256)
        return buf.raw[:n].decode("utf-8", "replace") if n > 0 else ""

    def vocab_lookup_batch(self, keys, stride: int = 256) -> list[str]:
        """Un-hash many keys with ONE native crossing (the display decode
        hot loop; per-row ctypes calls cost ~15us each)."""
        keys64 = np.ascontiguousarray(keys, dtype=np.uint64)
        n = keys64.size
        if n == 0:
            return []
        out = ctypes.create_string_buffer(n * stride)
        lens = np.zeros(n, dtype=np.int32)
        r = self._lib.ig_vocab_lookup_batch(
            self._h, _p64(keys64), n, out,
            stride, lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if r < 0:
            return [""] * n
        raw = out.raw
        ls = lens.tolist()
        return [raw[i * stride:i * stride + ls[i]].decode("utf-8", "replace")
                if ls[i] > 0 else "" for i in range(n)]
