"""Test configuration: force an 8-device virtual CPU platform before JAX init.

Mirrors the reference's test strategy of kernel-real-but-container-free unit
tests (reference internal/test/runner.go:103-218 unshares namespaces to fake
containers); here the analogue is a virtual 8-device CPU mesh standing in for
a TPU pod slice so sharding/psum paths are exercised without TPU hardware.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
