"""Test configuration: force an 8-device virtual CPU platform before JAX use.

Mirrors the reference's test strategy of kernel-real-but-container-free unit
tests (reference internal/test/runner.go:103-218 unshares namespaces to fake
containers); here the analogue is a virtual 8-device CPU mesh standing in for
a TPU pod slice so sharding/psum paths are exercised without TPU hardware.

Note: the environment's sitecustomize pre-imports jax with the axon TPU
platform, so env vars alone are ignored — jax.config.update must run before
first backend use.
"""

import os

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
