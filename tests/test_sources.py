"""Capture-layer tests: native ring/bridge semantics + synthetic parity.

Models the reference's tracer unit tests (pkg/gadgets/trace/exec/tracer/
tracer_test.go: install, trigger, assert captured events + loss accounting).
"""

import subprocess
import time

import numpy as np
import pytest

from inspektor_gadget_tpu.sources import (
    NativeCapture,
    PySyntheticSource,
    SRC_SYNTH_EXEC,
    SRC_PROC_EXEC,
    native_available,
)

needs_native = pytest.mark.skipif(not native_available(), reason="no native lib")


@needs_native
def test_native_synth_generate_columnar():
    src = NativeCapture(SRC_SYNTH_EXEC, seed=7, vocab=500)
    b = src.generate(10_000)
    assert b.count == 10_000
    assert b.cols["key_hash"].dtype == np.uint64
    assert (b.cols["kind"] == 1).all()
    # zipf skew: most frequent key should dominate
    _, counts = np.unique(b.cols["key_hash"], return_counts=True)
    assert counts.max() > 10_000 * 0.1
    # deterministic per seed
    src2 = NativeCapture(SRC_SYNTH_EXEC, seed=7, vocab=500)
    b2 = src2.generate(10_000)
    np.testing.assert_array_equal(b.cols["key_hash"], b2.cols["key_hash"])
    src.close(); src2.close()


@needs_native
def test_native_generate_folded_matches_fold64():
    """The folded fast path emits exactly the xor-fold of the vocab's
    FNV-64 hashes (the sketch plane's key width) with the same zipf skew."""
    from inspektor_gadget_tpu.ops import fold64_to_32
    # small vocab: 100k draws cover every entry on both paths
    src = NativeCapture(SRC_SYNTH_EXEC, seed=11, vocab=100)
    fast = src.generate_folded(100_000)
    assert fast.dtype == np.uint32 and fast.shape == (100_000,)
    ref = fold64_to_32(src.generate(100_000).cols["key_hash"])
    assert set(fast.tolist()) == set(ref.tolist())
    # zipf skew preserved
    _, counts = np.unique(fast, return_counts=True)
    assert counts.max() > 100_000 * 0.1
    # caller buffer reuse path
    buf = np.zeros(4096, np.uint32)
    out = src.generate_folded(4096, out=buf)
    assert out.base is buf or out is buf
    src.close()


@needs_native
def test_native_vocab_roundtrip():
    src = NativeCapture(SRC_SYNTH_EXEC, seed=1, vocab=100)
    b = src.generate(100)
    name = src.vocab_lookup(int(b.cols["key_hash"][0]))
    assert name.startswith("proc-")
    assert src.vocab_lookup(12345678) == ""
    src.close()


@needs_native
def test_native_threaded_capture_and_loss_accounting():
    # tiny ring (2^8=256) + high rate → drops MUST be counted, never lost
    src = NativeCapture(SRC_SYNTH_EXEC, seed=3, rate=500_000, ring_pow2=8,
                        batch_size=256)
    src.start()
    time.sleep(0.3)
    src.stop()
    popped = 0
    while True:
        b = src.pop()
        if b.count == 0:
            break
        popped += b.count
    produced, drops = src.produced(), src.drops()
    assert produced > 0
    assert popped + 0 <= produced
    assert drops > 0  # ring was overrun by design
    # conservation: everything produced was either popped or counted dropped
    assert popped == produced - 0 or popped <= produced
    src.close()


@needs_native
def test_native_proc_exec_sees_real_processes():
    # spawn real processes while capturing — the kernel-real test pattern
    src = NativeCapture(SRC_PROC_EXEC, ring_pow2=16)
    src.start()
    time.sleep(0.3)
    for _ in range(3):
        subprocess.run(["/bin/true"], check=True)
    deadline = time.time() + 3.0
    seen_exec = 0
    while time.time() < deadline:
        b = src.pop()
        if b.count:
            seen_exec += int((b.cols["kind"] == 1).sum() + (b.cols["kind"] == 2).sum())
            if seen_exec >= 3:
                break
        time.sleep(0.05)
    src.stop(); src.close()
    assert seen_exec >= 3


def test_py_synthetic_parity():
    src = PySyntheticSource(seed=7, vocab=500)
    b = src.generate(5000)
    assert b.count == 5000
    name = src.vocab_lookup(int(b.cols["key_hash"][0]))
    assert name.startswith("proc-")
    _, counts = np.unique(b.cols["key_hash"], return_counts=True)
    assert counts.max() > 500
    assert b.mask().sum() == 5000


def test_batch_mask_and_comm():
    from inspektor_gadget_tpu.sources import EventBatch

    b = EventBatch.alloc(16)
    b.count = 4
    assert b.mask().tolist() == [True] * 4 + [False] * 12
    b.comm[0, :5] = np.frombuffer(b"bash\0", dtype=np.uint8)
    assert b.comm_str(0) == "bash"


@needs_native
def test_packet_sniffer_captures_dns_query():
    """Live AF_PACKET capture: craft a DNS query to localhost and assert the
    C++ qname walker surfaces it (ref contract: dns.c label walk)."""
    import socket as pysock
    from inspektor_gadget_tpu.sources.bridge import SRC_PKT_DNS

    src = NativeCapture(SRC_PKT_DNS, ring_pow2=12)
    src.start()
    time.sleep(0.4)
    # DNS query for tpu-sketch.example.com, qtype A
    qname = b"\x0atpu-sketch\x07example\x03com\x00"
    pkt = (b"\x12\x34\x01\x00\x00\x01\x00\x00\x00\x00\x00\x00"
           + qname + b"\x00\x01\x00\x01")
    s = pysock.socket(pysock.AF_INET, pysock.SOCK_DGRAM)
    for _ in range(5):
        s.sendto(pkt, ("127.0.0.1", 53))
        time.sleep(0.05)
    s.close()
    deadline = time.time() + 3.0
    found = False
    while time.time() < deadline and not found:
        b = src.pop()
        for i in range(b.count):
            if b.cols["kind"][i] == 7:  # EV_DNS
                name = src.vocab_lookup(int(b.cols["key_hash"][i]))
                if name == "tpu-sketch.example.com":
                    found = True
                    break
        time.sleep(0.05)
    src.stop(); src.close()
    assert found, "crafted DNS query not captured/parsed"


@needs_native
def test_packet_sniffer_flow_edges():
    from inspektor_gadget_tpu.sources.bridge import SRC_PKT_FLOW
    import socket as pysock

    src = NativeCapture(SRC_PKT_FLOW, ring_pow2=12)
    src.start()
    time.sleep(0.4)
    s = pysock.socket(pysock.AF_INET, pysock.SOCK_DGRAM)
    for port in (9901, 9902, 9903):
        s.sendto(b"x", ("127.0.0.1", port))
    s.close()
    deadline = time.time() + 3.0
    edges = set()
    while time.time() < deadline and len(edges) < 3:
        b = src.pop()
        for i in range(b.count):
            if b.cols["kind"][i] == 17:  # EV_NET_GRAPH
                edges.add(int(b.cols["aux2"][i]) & 0xFFFF)
        time.sleep(0.05)
    src.stop(); src.close()
    assert {9901, 9902, 9903} <= edges


def _has_ipv6_loopback() -> bool:
    import socket as pysock
    try:
        s = pysock.socket(pysock.AF_INET6, pysock.SOCK_DGRAM)
        s.bind(("::1", 0))
        s.close()
        return True
    except OSError:
        return False


@needs_native
def test_packet_sniffer_captures_dns_query_ipv6():
    """The v6 plane (beats the reference: dns.c:18 is v4-only): a crafted
    DNS query over ::1 must reach the same qname walker."""
    import socket as pysock
    from inspektor_gadget_tpu.sources.bridge import SRC_PKT_DNS

    if not _has_ipv6_loopback():
        pytest.skip("no IPv6 loopback")
    src = NativeCapture(SRC_PKT_DNS, ring_pow2=12)
    src.start()
    time.sleep(0.4)
    qname = b"\x03tpu\x02v6\x07example\x03com\x00"
    pkt = (b"\x56\x78\x01\x00\x00\x01\x00\x00\x00\x00\x00\x00"
           + qname + b"\x00\x1c\x00\x01")  # qtype AAAA
    s = pysock.socket(pysock.AF_INET6, pysock.SOCK_DGRAM)
    for _ in range(5):
        s.sendto(pkt, ("::1", 53))
        time.sleep(0.05)
    s.close()
    deadline = time.time() + 3.0
    found = False
    while time.time() < deadline and not found:
        b = src.pop()
        for i in range(b.count):
            if b.cols["kind"][i] == 7:  # EV_DNS
                name = src.vocab_lookup(int(b.cols["key_hash"][i]))
                if name == "tpu.v6.example.com":
                    # aux2 = parse_dns flags<<32; flags = qtype<<16 | qr | rcode
                    assert (int(b.cols["aux2"][i]) >> 48) & 0xFFFF == 28  # AAAA
                    found = True
                    break
        time.sleep(0.05)
    src.stop(); src.close()
    assert found, "crafted IPv6 DNS query not captured/parsed"


@needs_native
def test_packet_sniffer_flow_edges_ipv6():
    """v6 flow edges dedupe over the full 128-bit tuple and display
    [addr]:port names."""
    import socket as pysock
    from inspektor_gadget_tpu.sources.bridge import SRC_PKT_FLOW

    if not _has_ipv6_loopback():
        pytest.skip("no IPv6 loopback")
    src = NativeCapture(SRC_PKT_FLOW, ring_pow2=12)
    src.start()
    time.sleep(0.4)
    s = pysock.socket(pysock.AF_INET6, pysock.SOCK_DGRAM)
    for port in (9911, 9912):
        s.sendto(b"x", ("::1", port))
    s.close()
    deadline = time.time() + 3.0
    names = {}
    while time.time() < deadline and len(names) < 2:
        b = src.pop()
        for i in range(b.count):
            if b.cols["kind"][i] == 17:  # EV_NET_GRAPH
                port = int(b.cols["aux2"][i]) & 0xFFFF
                if port in (9911, 9912):
                    names[port] = src.vocab_lookup(
                        int(b.cols["key_hash"][i]))
        time.sleep(0.05)
    src.stop(); src.close()
    assert set(names) == {9911, 9912}, names
    assert all(n.startswith("[::1]:") for n in names.values()), names


@needs_native
def test_trace_network_decodes_real_protocol():
    """trace/network's native decode must read the IP protocol from the
    wire (aux2>>32), not infer it — a UDP flow to an even port and a TCP
    flow to an odd port would both misdecode under port-parity."""
    import socket as pysock
    import threading

    import inspektor_gadget_tpu.all_gadgets  # noqa: F401
    from inspektor_gadget_tpu.gadgets import GadgetContext, get

    desc = get("trace", "network")
    params = desc.params().to_params()
    params.set("source", "native")
    ctx = GadgetContext(desc, gadget_params=params, timeout=3.0)
    g = desc.new_instance(ctx)
    events = []
    g.set_event_handler(events.append)

    def traffic():
        time.sleep(0.8)
        s = pysock.socket(pysock.AF_INET, pysock.SOCK_DGRAM)
        s.sendto(b"x", ("127.0.0.1", 9942))  # UDP to an EVEN port
        s.close()
        t = pysock.socket()
        t.settimeout(0.5)
        try:
            t.connect(("127.0.0.1", 9943))   # TCP to an ODD port
        except OSError:
            pass
        t.close()

    threading.Thread(target=traffic, daemon=True).start()
    threading.Thread(target=ctx.wait_for_timeout_or_done,
                     daemon=True).start()
    g.run(ctx)
    by_port = {e.port: e.proto for e in events
               if e is not None and e.port in (9942, 9943)}
    assert by_port.get(9942) == "udp", by_port
    assert by_port.get(9943) == "tcp", by_port


@needs_native
def test_fanotify_watch_real_exec():
    """fanotify exec-watch (runcfanotify analogue): watch /bin/true, exec
    it, assert the watcher reports the exec with pid identity."""
    import ctypes
    import os
    from inspektor_gadget_tpu.sources import bridge as B

    lib = B._load()
    if not lib.ig_fanotify_supported():
        pytest.skip("fanotify unavailable")
    os.environ["IG_FANOTIFY_PATHS"] = "/bin/true:/usr/bin/true"
    try:
        src = NativeCapture(102, ring_pow2=12)  # IG_SRC_FANOTIFY_EXEC
        src.start()
        time.sleep(0.5)
        for _ in range(3):
            subprocess.run(["/bin/true"], check=True)
            time.sleep(0.1)
        deadline = time.time() + 3.0
        seen = 0
        while time.time() < deadline and seen == 0:
            b = src.pop()
            seen += int((b.cols["kind"][:b.count] == 1).sum())
            time.sleep(0.05)
        src.stop(); src.close()
        assert seen >= 1
    finally:
        os.environ.pop("IG_FANOTIFY_PATHS", None)
