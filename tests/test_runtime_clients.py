"""Runtime clients + enrichment options.

Reference contracts: pkg/container-utils/containerd/containerd.go (task
state: id/pid/bundle), cri/cri.go:1-295 (ListContainers + verbose
ContainerStatus, pid parsed from the info JSON), and
pkg/container-collection/options.go:132-197 (runtime enrichment
auto-chain), :303 (WithHost), :628 (WithOCIConfigEnrichment). Every
backend degrades gracefully when its socket/dir is absent.
"""

import json
import os
import tempfile
from concurrent import futures

import grpc
import pytest

from inspektor_gadget_tpu.containers import (
    Container, ContainerCollection, ContainerdClient, CriGrpcClient,
    with_host, with_oci_config_enrichment, with_runtime_enrichment,
)
from inspektor_gadget_tpu.containers import cri_pb2


# ---------------------------------------------------------------------------
# containerd: on-disk runtime-v2 task state
# ---------------------------------------------------------------------------

def _fake_task(root, ns, cid, pid, annotations):
    d = os.path.join(root, ns, cid)
    os.makedirs(d)
    with open(os.path.join(d, "init.pid"), "w") as f:
        f.write(str(pid))
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump({"annotations": annotations,
                   "process": {"env": ["A=1"]},
                   "mounts": [{"destination": "/etc/hosts"}]}, f)


def test_containerd_client_reads_task_state(tmp_path):
    root = str(tmp_path)
    _fake_task(root, "k8s.io", "abcdef123456789", 4242, {
        "io.kubernetes.cri.container-name": "web",
        "io.kubernetes.cri.sandbox-name": "pod-1",
        "io.kubernetes.cri.sandbox-namespace": "prod",
    })
    _fake_task(root, "moby", "fedcba987654321", 4343, {})
    client = ContainerdClient(task_root=root)
    assert client.available()
    got = {c.id: c for c in client.get_containers()}
    assert len(got) == 2
    web = got["abcdef123456"]
    assert (web.name, web.pid, web.pod, web.namespace, web.runtime) == \
        ("web", 4242, "pod-1", "prod", "containerd")
    assert web.bundle.endswith("abcdef123456789")
    # lookup by full id prefix
    assert client.get_container("abcdef123456789").name == "web"


def test_containerd_client_degrades_without_root(tmp_path):
    client = ContainerdClient(task_root=str(tmp_path / "nope"))
    assert not client.available()
    assert client.get_containers() == []


# ---------------------------------------------------------------------------
# CRI over gRPC against a fake CRI server (the real wire path)
# ---------------------------------------------------------------------------

class _FakeCri:
    def __init__(self):
        self.calls: dict[str, int] = {}
        self.containers = [
            ("c1" * 16, "web", {"io.kubernetes.pod.name": "pod-a",
                                "io.kubernetes.pod.namespace": "ns-a"}, 111),
            ("d2" * 16, "db", {}, 222),
        ]

    def version(self, request: bytes, ctx) -> bytes:
        self.calls["Version"] = self.calls.get("Version", 0) + 1
        return cri_pb2.VersionResponse(
            version="0.1.0", runtime_name="fake-cri",
            runtime_version="1.0", runtime_api_version="v1",
        ).SerializeToString()

    def list_containers(self, request: bytes, ctx) -> bytes:
        self.calls["ListContainers"] = self.calls.get("ListContainers", 0) + 1
        req = cri_pb2.ListContainersRequest.FromString(request)
        assert req.filter.state.state == cri_pb2.CONTAINER_RUNNING
        resp = cri_pb2.ListContainersResponse()
        for cid, name, labels, _pid in self.containers:
            c = resp.containers.add()
            c.id = cid
            c.metadata.name = name
            c.state = cri_pb2.CONTAINER_RUNNING
            for k, v in labels.items():
                c.labels[k] = v
        return resp.SerializeToString()

    def container_status(self, request: bytes, ctx) -> bytes:
        self.calls["ContainerStatus"] = self.calls.get("ContainerStatus",
                                                       0) + 1
        req = cri_pb2.ContainerStatusRequest.FromString(request)
        assert req.verbose
        match = next(((n, l, p) for cid, n, l, p in self.containers
                      if cid == req.container_id), None)
        resp = cri_pb2.ContainerStatusResponse()
        if match is None:
            # real runtimes answer NOT_FOUND for a vanished container
            ctx.abort(grpc.StatusCode.NOT_FOUND, "no such container")
        name, labels, pid = match
        resp.status.id = req.container_id
        resp.status.metadata.name = name
        for k, v in labels.items():
            resp.status.labels[k] = v
        resp.info["info"] = json.dumps({"pid": pid, "sandboxID": "s1"})
        return resp.SerializeToString()


@pytest.fixture()
def fake_cri():
    tmp = tempfile.mkdtemp()
    sock = f"{tmp}/cri.sock"
    fake = _FakeCri()
    ident = lambda b: b  # noqa: E731
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    handlers = {
        "Version": grpc.unary_unary_rpc_method_handler(
            fake.version, request_deserializer=ident,
            response_serializer=ident),
        "ListContainers": grpc.unary_unary_rpc_method_handler(
            fake.list_containers, request_deserializer=ident,
            response_serializer=ident),
        "ContainerStatus": grpc.unary_unary_rpc_method_handler(
            fake.container_status, request_deserializer=ident,
            response_serializer=ident),
    }
    server.add_generic_rpc_handlers((
        grpc.method_handlers_generic_handler("runtime.v1.RuntimeService",
                                             handlers),
    ))
    server.add_insecure_port(f"unix://{sock}")
    server.start()
    yield sock, fake
    server.stop(grace=0.2)


def test_cri_grpc_client_lists_with_pids(fake_cri):
    sock, _fake = fake_cri
    client = CriGrpcClient(socket_path=sock)
    assert client.available()
    assert client.version() == "fake-cri"
    got = {c.name: c for c in client.get_containers()}
    assert set(got) == {"web", "db"}
    assert got["web"].pid == 111 and got["db"].pid == 222
    assert got["web"].pod == "pod-a" and got["web"].namespace == "ns-a"
    assert got["web"].runtime == "cri"
    assert client.get_container("c1" * 16).name == "web"
    client.close()


def test_cri_grpc_client_single_channel_rpc_budget(fake_cri, monkeypatch):
    """A 10-container listing must cost ONE dial and 1+N RPCs (list +
    verbose status per container for the pid) — the reference's cri.go
    holds a single long-lived conn; N+1 channels per list is the bug."""
    sock, fake = fake_cri
    fake.containers = [
        (f"{i:02d}" * 16, f"c{i}", {}, 1000 + i) for i in range(10)
    ]
    dials = 0
    real_dial = grpc.insecure_channel

    def counting_dial(*a, **kw):
        nonlocal dials
        dials += 1
        return real_dial(*a, **kw)

    monkeypatch.setattr(grpc, "insecure_channel", counting_dial)
    with CriGrpcClient(socket_path=sock) as client:
        got = client.get_containers()
    assert len(got) == 10
    assert {c.name: c.pid for c in got} == {
        f"c{i}": 1000 + i for i in range(10)}
    assert dials == 1
    assert fake.calls["ListContainers"] == 1
    assert fake.calls["ContainerStatus"] == 10


def test_cri_grpc_client_redials_after_transport_error(fake_cri, tmp_path):
    """A transport-level RpcError (UNAVAILABLE on a dead socket) drops the
    cached channel; the next call transparently redials."""
    sock, _fake = fake_cri
    client = CriGrpcClient(socket_path=str(tmp_path / "dead.sock"))
    with pytest.raises(grpc.RpcError):
        client.version()
    assert client._channel is None  # transport failure dropped the channel
    client.socket_path = sock
    assert client.version() == "fake-cri"  # redialed against the live one
    client.close()


def test_cri_grpc_client_keeps_channel_on_not_found(fake_cri):
    """An application-level status (vanished container mid-listing) must
    NOT tear down the shared channel."""
    sock, fake = fake_cri
    client = CriGrpcClient(socket_path=sock)
    assert client.version() == "fake-cri"
    chan = client._channel
    # unknown id → fake aborts with NOT_FOUND; get_container absorbs it
    assert client.get_container("ff" * 16) is None
    assert client._channel is chan  # same channel, no redial
    client.close()


def test_cri_grpc_client_degrades_without_socket(tmp_path):
    client = CriGrpcClient(socket_path=str(tmp_path / "absent.sock"))
    assert not client.available()


# ---------------------------------------------------------------------------
# enrichment options
# ---------------------------------------------------------------------------

class _FakeRuntime:
    """Duck-typed RuntimeClient backed by a dict."""

    def __init__(self, containers):
        self.by_id = {c.id: c for c in containers}

    def available(self):
        return True

    def get_containers(self):
        return list(self.by_id.values())

    def get_container(self, cid):
        return self.by_id.get(cid[:12])


def test_runtime_enrichment_auto_chain():
    """A container added with only an id (the OCI-hook shape) is completed
    from the runtime client (options.go:132-197 semantics)."""
    full = Container(id="aaa111bbb222", name="web", pid=os.getpid(),
                     namespace="ns", pod="pod-x", runtime="fake",
                     labels={"team": "infra"})
    cc = ContainerCollection()
    cc.initialize(with_runtime_enrichment(client=_FakeRuntime([full])))
    # seeded from the runtime
    assert cc.get("aaa111bbb222").name == "web"
    cc.remove_container("aaa111bbb222")
    # hook-shaped add: id only → enricher completes it
    cc.add_container(Container(id="aaa111bbb222"))
    got = cc.get("aaa111bbb222")
    assert (got.name, got.pid, got.pod, got.labels["team"]) == \
        ("web", os.getpid(), "pod-x", "infra")
    # namespace enrichment chained: pid → mntns resolved
    assert got.mntns > 0


def test_runtime_enrichment_keeps_unknown_containers():
    cc = ContainerCollection()
    cc.initialize(with_runtime_enrichment(client=_FakeRuntime([])))
    cc.add_container(Container(id="unknown-to-runtime", name="manual",
                               pid=os.getpid()))
    assert cc.get("unknown-to-runtime").name == "manual"


def test_oci_config_enrichment(tmp_path):
    bundle = tmp_path / "c9"
    bundle.mkdir()
    (bundle / "config.json").write_text(json.dumps({
        "process": {"env": ["PATH=/usr/bin", "MODE=prod"]},
        "mounts": [{"destination": "/data"}, {"destination": "/etc/ssl"}],
        "annotations": {"org.opencontainers.image.ref.name": "img:1"},
        "linux": {"seccomp": {"defaultAction": "SCMP_ACT_ERRNO"}},
    }))
    cc = ContainerCollection()
    cc.initialize(with_oci_config_enrichment(bundle_root=str(tmp_path)))
    cc.add_container(Container(id="c9", name="app", pid=os.getpid()))
    got = cc.get("c9")
    assert got.mounts == ["/data", "/etc/ssl"]
    assert "MODE=prod" in got.env
    assert got.labels["org.opencontainers.image.ref.name"] == "img:1"
    assert got.seccomp_profile == "SCMP_ACT_ERRNO"


def test_oci_annotation_dialects_resolve_identity(tmp_path):
    """Both runtime annotation dialects map to pod/namespace/container
    identity with no k8s API (ref: oci-annotations resolver_containerd.go,
    resolver_crio.go)."""
    cases = {
        "cd1": {  # containerd dialect
            "io.kubernetes.cri.sandbox-name": "pod-cd",
            "io.kubernetes.cri.sandbox-namespace": "ns-cd",
            "io.kubernetes.cri.sandbox-uid": "uid-cd",
            "io.kubernetes.cri.container-name": "app-cd",
            "io.kubernetes.cri.container-type": "container",
        },
        "cr1": {  # cri-o dialect
            "io.container.manager": "cri-o",
            "io.kubernetes.pod.name": "pod-cr",
            "io.kubernetes.pod.namespace": "ns-cr",
            "io.kubernetes.pod.uid": "uid-cr",
            "io.kubernetes.container.name": "app-cr",
            "io.kubernetes.cri-o.ContainerType": "container",
        },
    }
    for cid, annotations in cases.items():
        bundle = tmp_path / cid
        bundle.mkdir()
        (bundle / "config.json").write_text(
            json.dumps({"annotations": annotations}))
    cc = ContainerCollection()
    cc.initialize(with_oci_config_enrichment(bundle_root=str(tmp_path)))
    cc.add_container(Container(id="cd1", pid=os.getpid()))
    cc.add_container(Container(id="cr1", pid=os.getpid()))
    cd = cc.get("cd1")
    assert (cd.pod, cd.namespace, cd.name) == ("pod-cd", "ns-cd", "app-cd")
    cr = cc.get("cr1")
    assert (cr.pod, cr.namespace, cr.name) == ("pod-cr", "ns-cr", "app-cr")


def test_oci_annotation_mixed_dialect_falls_back_per_field():
    """Real bundles mix dialects (containerd sandbox keys + kubelet
    container-name label); each field falls back to the other dialect
    instead of returning empty."""
    from inspektor_gadget_tpu.containers.oci_annotations import (
        resolve_identity,
    )
    ident = resolve_identity({
        "io.kubernetes.cri.sandbox-namespace": "ns-mixed",
        "io.kubernetes.container.name": "app-mixed",  # kubelet key only
    })
    assert ident is not None and ident.runtime == "containerd"
    assert ident.namespace == "ns-mixed"
    assert ident.name == "app-mixed"
    # mirror case: cri-o detected, pod name only under the containerd key
    ident2 = resolve_identity({
        "io.container.manager": "cri-o",
        "io.kubernetes.pod.namespace": "ns2",
        "io.kubernetes.cri.sandbox-name": "pod2",
    })
    assert ident2 is not None and ident2.runtime == "cri-o"
    assert (ident2.namespace, ident2.pod) == ("ns2", "pod2")


def test_oci_annotation_resolver_unknown_dialect():
    from inspektor_gadget_tpu.containers.oci_annotations import (
        resolve_identity, resolver_for,
    )
    assert resolve_identity({"unrelated": "x"}) is None
    assert resolver_for("docker") is None
    ident = resolver_for("containerd").resolve(
        {"io.kubernetes.cri.sandbox-name": "p"})
    assert ident.pod == "p" and ident.runtime == "containerd"


def test_with_host_adds_host_pseudo_container():
    cc = ContainerCollection()
    cc.initialize(with_host())
    host = cc.get("host")
    assert host is not None and host.pid == 1 and host.runtime == "host"
    # pid 1's namespaces aren't always readable (sandboxed /proc); only
    # assert the mntns index when the probe could resolve it
    if host.mntns:
        assert cc.lookup_by_mntns(host.mntns).id == "host"
