"""Alerting-plane unit tier: rule loading (every edge case fails LOUDLY
at load — the satellite contract), the hysteresis + debounce state
machine under an injected clock, sinks, the cluster dedup aggregator,
and the CLI verbs."""

from __future__ import annotations

import json
import logging

import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.alerts import (
    AlertEngine,
    ClusterAlertAggregator,
    LogSink,
    RuleError,
    WebhookFileSink,
    load_rules,
    load_rules_file,
)
from inspektor_gadget_tpu.alerts.store import ActiveAlerts
from inspektor_gadget_tpu.operators.tpusketch import SketchSummary


def summary(entropy=0.0, events=1000, drops=0, distinct=10.0,
            hh=((1, 500), (2, 100)), anomaly=None, epoch=1):
    return SketchSummary(events=events, drops=drops, distinct=distinct,
                         entropy_bits=entropy,
                         heavy_hitters=[tuple(x) for x in hh],
                         anomaly=anomaly, epoch=epoch)


# -- rule loading: every edge case is a LOAD-time failure -------------------

def test_load_rules_yaml_and_json():
    yaml_doc = """
rules:
  - id: e1
    kind: entropy_jump
    threshold: 1.5
    for: 250ms
    cooldown: 2s
"""
    (r,) = load_rules(yaml_doc)
    assert (r.id, r.kind, r.threshold) == ("e1", "entropy_jump", 1.5)
    assert r.for_s == 0.25 and r.cooldown_s == 2.0
    assert r.field == "entropy_bits"  # implied by the kind
    json_doc = json.dumps([{"id": "t1", "kind": "threshold",
                            "field": "drops", "threshold": 5}])
    (r,) = load_rules(json_doc)
    assert r.field == "drops" and r.threshold == 5.0


def test_load_rules_empty_document_fails():
    with pytest.raises(RuleError, match="empty rule document"):
        load_rules("")
    with pytest.raises(RuleError, match="no rules"):
        load_rules("rules: []")
    with pytest.raises(RuleError, match="no rules"):
        load_rules("{}")


def test_load_rules_unknown_field_fails():
    doc = json.dumps([{"id": "x", "kind": "threshold",
                       "field": "entropy_bitz", "threshold": 1}])
    with pytest.raises(RuleError, match="unknown summary field"):
        load_rules(doc)
    doc = json.dumps([{"id": "x", "kind": "ratio", "field": "drops",
                       "denom": "nope", "threshold": 1}])
    with pytest.raises(RuleError, match="unknown denom field"):
        load_rules(doc)


def test_load_rules_bad_threshold_type_fails():
    doc = json.dumps([{"id": "x", "kind": "threshold", "field": "events",
                       "threshold": "very high"}])
    with pytest.raises(RuleError, match="threshold must be a number"):
        load_rules(doc)
    # bool is not a number here (YAML 'threshold: true' trap)
    doc = json.dumps([{"id": "x", "kind": "threshold", "field": "events",
                       "threshold": True}])
    with pytest.raises(RuleError, match="threshold must be a number"):
        load_rules(doc)


def test_load_rules_duplicate_ids_fail():
    doc = json.dumps([
        {"id": "dup", "kind": "threshold", "field": "events",
         "threshold": 1},
        {"id": "dup", "kind": "threshold", "field": "drops",
         "threshold": 2},
    ])
    with pytest.raises(RuleError, match="duplicate rule id 'dup'"):
        load_rules(doc)


def test_load_rules_unknown_keys_and_kinds_fail():
    with pytest.raises(RuleError, match="unknown key"):
        load_rules(json.dumps([{"id": "x", "kind": "threshold",
                                "field": "events", "threshold": 1,
                                "treshold": 2}]))
    with pytest.raises(RuleError, match="unknown kind"):
        load_rules(json.dumps([{"id": "x", "kind": "entropy_bump",
                                "threshold": 1}]))
    with pytest.raises(RuleError, match="unknown op"):
        load_rules(json.dumps([{"id": "x", "kind": "threshold",
                                "field": "events", "op": "=>",
                                "threshold": 1}]))
    with pytest.raises(RuleError, match="unknown severity"):
        load_rules(json.dumps([{"id": "x", "kind": "threshold",
                                "field": "events", "threshold": 1,
                                "severity": "apocalyptic"}]))
    with pytest.raises(RuleError, match="missing 'threshold'"):
        load_rules(json.dumps([{"id": "x", "kind": "threshold",
                                "field": "events"}]))
    with pytest.raises(RuleError, match="missing or non-string 'id'"):
        load_rules(json.dumps([{"kind": "threshold", "field": "events",
                                "threshold": 1}]))


def test_load_rules_file_missing_and_empty(tmp_path):
    with pytest.raises(RuleError, match="cannot read rule file"):
        load_rules_file(str(tmp_path / "absent.yaml"))
    empty = tmp_path / "empty.yaml"
    empty.write_text("")
    with pytest.raises(RuleError, match="empty rule document"):
        load_rules_file(str(empty))


def test_operator_fails_loudly_at_run_start(tmp_path):
    """A bad rule file fails the RUN (via install_operators), not the
    first harvest — driven through the real LocalRuntime path."""
    from inspektor_gadget_tpu.gadgets import GadgetContext, get
    from inspektor_gadget_tpu.operators import operators as op_registry
    from inspektor_gadget_tpu.params import Collection
    from inspektor_gadget_tpu.runtime.local import LocalRuntime

    bad = tmp_path / "bad.yaml"
    bad.write_text("rules:\n  - id: x\n    kind: nope\n    threshold: 1\n")
    desc = get("trace", "exec")
    gp = desc.params().to_params()
    gp.set("source", "pysynthetic")
    op = op_registry.get("alerts")
    ap = op.instance_params().to_params()
    ap.set("rules-file", str(bad))
    ctx = GadgetContext(desc, gadget_params=gp,
                        operator_params=Collection({"operator.alerts.": ap}),
                        timeout=0.3)
    result = LocalRuntime().run_gadget(ctx)
    err = result.errors().get("local", "")
    assert "unknown kind" in err, err


# -- the state machine under an injected clock ------------------------------

def _engine(doc, **kw):
    return AlertEngine(load_rules(json.dumps(doc)), node="n0",
                       dry_run=True, **kw)


def test_threshold_debounce_pending_firing_resolved():
    e = _engine([{"id": "d", "kind": "threshold", "field": "drops",
                  "op": ">", "threshold": 10, "for": 1.0}])
    assert e.observe(summary(drops=3), now=0.0) == []
    (ev,) = e.observe(summary(drops=50), now=1.0)
    assert ev.transition == "pending" and ev.value == 50
    assert e.observe(summary(drops=60), now=1.5) == []  # for not elapsed
    (ev,) = e.observe(summary(drops=60), now=2.1)
    assert ev.transition == "firing"
    assert e.firing() == [("d", "")]
    (ev,) = e.observe(summary(drops=0), now=3.0)
    assert ev.transition == "resolved"
    assert e.firing() == []


def test_debounce_retracts_pending_without_firing():
    e = _engine([{"id": "d", "kind": "threshold", "field": "drops",
                  "op": ">", "threshold": 10, "for": 1.0}])
    (ev,) = e.observe(summary(drops=50), now=0.0)
    assert ev.transition == "pending"
    # condition gone before `for` elapsed: never fires (the debounce),
    # but the surfaced pending is retracted so consumers drop it
    (ev,) = e.observe(summary(drops=0), now=0.5)
    assert ev.transition == "resolved"
    assert e.firing() == []
    # and the next trip starts a FRESH pending window
    (ev,) = e.observe(summary(drops=50), now=1.0)
    assert ev.transition == "pending"
    assert e.observe(summary(drops=50), now=1.5) == []


def test_hysteresis_clear_level_holds_alert():
    e = _engine([{"id": "h", "kind": "threshold", "field": "drops",
                  "op": ">", "threshold": 10, "clear": 5}])
    evs = e.observe(summary(drops=20), now=0.0)
    assert [v.transition for v in evs] == ["pending", "firing"]  # for: 0
    # between clear and threshold: still firing (no flap)
    assert e.observe(summary(drops=7), now=1.0) == []
    assert e.firing() == [("h", "")]
    (ev,) = e.observe(summary(drops=2), now=2.0)  # below clear: released
    assert ev.transition == "resolved"


def test_cooldown_suppresses_retrigger():
    e = _engine([{"id": "c", "kind": "threshold", "field": "drops",
                  "op": ">", "threshold": 10, "cooldown": 10.0}])
    e.observe(summary(drops=20), now=0.0)
    e.observe(summary(drops=0), now=1.0)   # resolved at t=1
    assert e.observe(summary(drops=20), now=5.0) == []  # cooling down
    evs = e.observe(summary(drops=20), now=12.0)        # cooldown over
    assert [v.transition for v in evs] == ["pending", "firing"]


def test_ratio_no_data_does_not_trigger_lt_rules():
    """events=0 means 'no data', not 'ratio 0' — an op:'<' rule must not
    trip on the empty first harvest."""
    e = _engine([{"id": "r", "kind": "ratio", "field": "hh_top_count",
                  "denom": "events", "op": "<", "threshold": 0.1}])
    assert e.observe(summary(events=0, hh=()), now=0.0) == []


def test_vanished_pending_key_resets_debounce():
    """A pending whose key vanishes is retracted; a later reuse of the
    slot starts a FRESH `for` window instead of firing instantly off the
    frozen `since`."""
    e = _engine([{"id": "a", "kind": "anomaly_score", "threshold": 0.5,
                  "for": 30.0}])
    (ev,) = e.observe(summary(anomaly={1: 0.9}), now=0.0)
    assert ev.transition == "pending"
    (ev,) = e.observe(summary(anomaly={}), now=5.0)  # container gone
    assert (ev.key, ev.transition) == ("mntns:1", "resolved")
    (ev,) = e.observe(summary(anomaly={1: 0.9}), now=3600.0)  # slot reused
    assert ev.transition == "pending"
    assert e.observe(summary(anomaly={1: 0.9}), now=3605.0) == []  # held


def test_ratio_rule():
    e = _engine([{"id": "r", "kind": "ratio", "field": "drops",
                  "denom": "events", "op": ">", "threshold": 0.01}])
    assert e.observe(summary(events=1000, drops=5), now=0.0) == []
    evs = e.observe(summary(events=1000, drops=50), now=1.0)
    assert evs[-1].transition == "firing" and evs[-1].value == 0.05


def test_entropy_jump_uses_baseline_window():
    e = _engine([{"id": "e", "kind": "entropy_jump", "threshold": 1.0,
                  "window": 3}])
    for t, h in enumerate([4.0, 4.1, 3.9]):
        assert e.observe(summary(entropy=h), now=float(t)) == []
    evs = e.observe(summary(entropy=7.5), now=3.0)  # jump vs mean(4.0)
    assert [v.transition for v in evs] == ["pending", "firing"]
    # entropy stays at the new level: the baseline catches up → resolved
    out = []
    for t in range(4, 9):
        out += e.observe(summary(entropy=7.5), now=float(t))
    assert [v.transition for v in out] == ["resolved"]


def test_cardinality_spike_factor():
    e = _engine([{"id": "c", "kind": "cardinality_spike", "factor": 3.0,
                  "window": 2}])
    assert e.observe(summary(distinct=100), now=0.0) == []
    assert e.observe(summary(distinct=110), now=1.0) == []
    evs = e.observe(summary(distinct=900), now=2.0)
    assert evs[-1].transition == "firing"


def test_heavy_hitter_churn_jaccard():
    e = _engine([{"id": "hh", "kind": "heavy_hitter_churn",
                  "threshold": 0.5}])
    base = summary(hh=((1, 9), (2, 8), (3, 7), (4, 6)))
    assert e.observe(base, now=0.0) == []            # no previous set
    assert e.observe(base, now=1.0) == []            # identical: dist 0
    churned = summary(hh=((9, 9), (8, 8), (7, 7), (4, 6)))  # 1 of 7 shared
    evs = e.observe(churned, now=2.0)
    assert evs[-1].transition == "firing"
    assert evs[-1].value > 0.5


def test_heavy_hitter_churn_empty_baseline_is_not_churn():
    """Traffic first appearing (empty → nonempty top-k) is not turnover;
    churn needs a nonempty baseline."""
    e = _engine([{"id": "hh", "kind": "heavy_hitter_churn",
                  "threshold": 0.5}])
    assert e.observe(summary(hh=()), now=0.0) == []          # empty
    assert e.observe(summary(hh=((1, 9), (2, 8))), now=1.0) == []
    # but a REAL full turnover after that baseline still fires
    evs = e.observe(summary(hh=((8, 9), (9, 8))), now=2.0)
    assert evs[-1].transition == "firing"


def test_anomaly_score_per_container_keys():
    e = _engine([{"id": "a", "kind": "anomaly_score", "threshold": 0.5}])
    evs = e.observe(summary(anomaly={111: 0.9, 222: 0.1}), now=0.0)
    assert {v.key for v in evs} == {"mntns:111"}
    assert evs[-1].transition == "firing"
    # second container trips independently; the first stays firing
    evs = e.observe(summary(anomaly={111: 0.9, 222: 0.8}), now=1.0)
    assert {v.key for v in evs} == {"mntns:222"}
    assert set(e.firing()) == {("a", "mntns:111"), ("a", "mntns:222")}
    # a container that VANISHES resolves its alert (slot gone)
    evs = e.observe(summary(anomaly={222: 0.8}), now=2.0)
    assert [(v.key, v.transition) for v in evs] == [
        ("mntns:111", "resolved")]


def test_debounced_pending_does_not_linger_in_active_table():
    """A pending that never fires emits nothing, but the process-wide
    table must not keep showing it as pending forever."""
    from inspektor_gadget_tpu.alerts import ACTIVE, load_rules as _lr
    rules = _lr(json.dumps([{"id": "linger-test", "kind": "threshold",
                             "field": "drops", "threshold": 10,
                             "for": 5.0}]))
    e = AlertEngine(rules, node="n0")  # real delivery: writes the table
    e.observe(summary(drops=50), now=0.0)
    (entry,) = [a for a in ACTIVE.all() if a["rule"] == "linger-test"]
    assert entry["state"] == "pending"
    e.observe(summary(drops=0), now=1.0)  # debounced away, silently
    (entry,) = [a for a in ACTIVE.all() if a["rule"] == "linger-test"]
    assert entry["state"] == "resolved"


def test_engine_close_resolves_active_alerts():
    """End-of-run teardown: a stopped run must not read as a live
    incident forever (gauge, table, stream all see the resolve)."""
    e = _engine([{"id": "c1", "kind": "threshold", "field": "drops",
                  "threshold": 1},
                 {"id": "c2", "kind": "threshold", "field": "events",
                  "threshold": 10, "for": 60.0}])
    e.observe(summary(drops=5, events=100), now=0.0)
    assert e.firing() == [("c1", "")]  # c2 still pending (for=60)
    evs = e.close(now=1.0)
    assert sorted((v.rule, v.transition) for v in evs) == [
        ("c1", "resolved"), ("c2", "resolved")]
    assert e.firing() == []
    assert e.close(now=2.0) == []  # idempotent


def test_aggregator_node_done_reconciles_lost_resolves():
    """Stream end resolves whatever a node still held active — a dropped
    EV_ALERT 'resolved' (or a crashed node) must not wedge the cluster
    alert."""
    surfaced = []
    agg = ClusterAlertAggregator(surfaced.append, store=ActiveAlerts())
    agg.observe("n0", _alert("n0", "firing"))
    agg.observe("n1", _alert("n1", "firing"))
    # n0's resolved never arrives; its stream ends
    assert agg.node_done("n0") == []      # n1 still holds it
    assert agg.active()                   # cluster alert still active
    (ev,) = agg.node_done("n1")           # last node out resolves it
    assert ev["transition"] == "resolved"
    assert set(ev["nodes"]) == {"n0", "n1"}
    assert agg.active() == []
    assert surfaced[-1]["transition"] == "resolved"


def test_store_new_episode_resets_node_attribution():
    """A re-fired alert must not inherit node lists (or age) from prior,
    resolved episodes."""
    store = ActiveAlerts()
    store.update({**_alert("nA", "firing"), "nodes": ["nA"]},
                 scope="cluster")
    store.update({**_alert("nA", "resolved"), "nodes": ["nA"]},
                 scope="cluster")
    store.update({**_alert("nB", "firing"), "nodes": ["nB"]},
                 scope="cluster")
    (entry,) = [a for a in store.all() if a["scope"] == "cluster"]
    assert entry["nodes"] == ["nB"], entry


# -- sinks ------------------------------------------------------------------

def test_webhook_file_sink_json_lines(tmp_path):
    path = tmp_path / "hooks.jsonl"
    rules = load_rules(json.dumps(
        [{"id": "w", "kind": "threshold", "field": "drops",
          "threshold": 1}]))
    e = AlertEngine(rules, node="n0", sinks=[WebhookFileSink(str(path))])
    e.observe(summary(drops=5), now=0.0)
    e.observe(summary(drops=0), now=1.0)
    events = WebhookFileSink.read(str(path))
    assert [ev["transition"] for ev in events] == [
        "pending", "firing", "resolved"]
    assert events[0]["rule"] == "w" and events[0]["node"] == "n0"
    # torn tail is tolerated, prefix survives
    with open(path, "a") as f:
        f.write('{"transition": "fir')
    assert len(WebhookFileSink.read(str(path))) == 3


def test_log_sink_levels(caplog):
    sink = LogSink(logging.getLogger("ig-tpu.alerts.test"))
    rules = load_rules(json.dumps(
        [{"id": "l", "kind": "threshold", "field": "drops", "threshold": 1,
          "severity": "critical"}]))
    e = AlertEngine(rules, sinks=[sink])
    with caplog.at_level(logging.INFO, logger="ig-tpu.alerts.test"):
        e.observe(summary(drops=5), now=0.0)
    firing = [r for r in caplog.records if "firing" in r.getMessage()]
    assert firing and firing[0].levelno == logging.ERROR  # critical


# -- cluster dedup ----------------------------------------------------------

def _alert(node, transition, rule="r1", key=""):
    return {"rule": rule, "key": key, "transition": transition,
            "node": node, "severity": "warning", "kind": "threshold",
            "value": 1.0, "threshold": 0.5, "ts": 123.0}


def test_cluster_dedup_fires_once_for_n_nodes():
    surfaced = []
    store = ActiveAlerts()
    agg = ClusterAlertAggregator(surfaced.append, store=store)
    assert agg.observe("n0", _alert("n0", "pending")) is not None
    assert agg.observe("n1", _alert("n1", "pending")) is None  # folded
    assert agg.observe("n0", _alert("n0", "firing")) is not None
    assert agg.observe("n1", _alert("n1", "firing")) is None   # folded
    assert [s["transition"] for s in surfaced] == ["pending", "firing"]
    # the store's cluster entry carries BOTH nodes
    (entry,) = [a for a in store.all() if a["scope"] == "cluster"]
    assert set(entry["nodes"]) == {"n0", "n1"}
    # resolved only when the LAST node resolves
    assert agg.observe("n0", _alert("n0", "resolved")) is None
    assert agg.observe("n1", _alert("n1", "resolved")) is not None
    assert surfaced[-1]["transition"] == "resolved"
    assert set(surfaced[-1]["nodes"]) == {"n0", "n1"}


def test_cluster_dedup_distinct_keys_fire_separately():
    surfaced = []
    agg = ClusterAlertAggregator(surfaced.append, store=ActiveAlerts())
    agg.observe("n0", _alert("n0", "firing", key="mntns:1"))
    agg.observe("n1", _alert("n1", "firing", key="mntns:2"))
    assert len([s for s in surfaced if s["transition"] == "firing"]) == 2


# -- CLI verbs --------------------------------------------------------------

RULES_YAML = """
rules:
  - id: ej
    kind: entropy_jump
    threshold: 1.0
    window: 3
  - id: drops
    kind: ratio
    field: drops
    denom: events
    threshold: 0.01
"""


def test_cli_alerts_rules_ok_and_bad(tmp_path, capsys):
    from inspektor_gadget_tpu.cli.main import main as cli_main
    good = tmp_path / "rules.yaml"
    good.write_text(RULES_YAML)
    assert cli_main(["alerts", "rules", "--file", str(good)]) == 0
    out = capsys.readouterr().out
    assert "2 rule(s) ok" in out and "ej:" in out
    bad = tmp_path / "bad.yaml"
    bad.write_text("rules:\n  - id: x\n    kind: threshold\n"
                   "    field: nope\n    threshold: 1\n")
    assert cli_main(["alerts", "rules", "--file", str(bad)]) == 2
    assert "unknown summary field" in capsys.readouterr().err


def test_cli_alerts_test_replay(tmp_path, capsys):
    from inspektor_gadget_tpu.cli.main import main as cli_main
    rules = tmp_path / "rules.yaml"
    rules.write_text(RULES_YAML)
    lines = []
    for h in [4.0, 4.0, 4.0, 8.0, 8.0, 8.0, 8.0, 8.0]:
        lines.append(json.dumps({"events": 1000, "drops": 0,
                                 "distinct": 10.0, "entropy": h,
                                 "heavy_hitters": [[1, 100]], "epoch": 1}))
    recorded = tmp_path / "summaries.jsonl"
    recorded.write_text("\n".join(lines))
    assert cli_main(["alerts", "test", "--file", str(rules),
                     "--summaries", str(recorded)]) == 0
    out = capsys.readouterr().out
    assert "ej -> pending" in out and "ej -> firing" in out
    assert "ej -> resolved" in out
    assert "8 summaries" in out
