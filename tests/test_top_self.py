"""top/self — capture-plane self-stats (top/ebpf parity).

Reference contract being mirrored: pkg/gadgets/top/ebpf/tracer.go:55-418
enumerates every loaded BPF program with runtime/run-count; here every
live native source reports thread CPU time, ring occupancy and loss
counters through ig_sources_stats while other gadgets run.
"""

import threading
import time

import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.gadgets import GadgetContext, get
from inspektor_gadget_tpu.runtime import LocalRuntime
from inspektor_gadget_tpu.sources import (
    NativeCapture, SRC_SYNTH_EXEC, native_available, sources_stats,
)

needs_native = pytest.mark.skipif(not native_available(), reason="no native lib")


@needs_native
def test_sources_stats_enumerates_live_source():
    src = NativeCapture(SRC_SYNTH_EXEC, seed=3, rate=100_000, vocab=50)
    src.start()
    try:
        time.sleep(0.6)
        stats = sources_stats()
        mine = [s for s in stats if s["id"] == src._h]
        assert mine, f"source {src._h} not enumerated in {stats}"
        s = mine[0]
        assert s["kind_name"] == "synth/exec"
        assert s["produced"] > 0
        assert s["ring_cap"] == 1 << 20
        assert 0 <= s["ring_len"] <= s["ring_cap"]
        # a thread generating 100k ev/s has measurable CPU time
        assert s["cpu_ns"] > 0
        # counter invariant is only exact when the producer is quiescent
        # (the three loads are not one atomic snapshot)
        src.stop()
        s = [x for x in sources_stats() if x["id"] == src._h][0]
        assert s["consumed"] + s["ring_len"] == s["produced"]
        assert s["consumed"] == 0  # nothing popped
    finally:
        src.stop()
        src.close()
    assert all(s["id"] != src._h for s in sources_stats()), \
        "destroyed source still enumerated"


@needs_native
def test_stats_survive_concurrent_stop():
    """ig_sources_stats races start/stop without crashing or UB (the
    cpu_mu_ ordering contract around pthread_getcpuclockid + join)."""
    src = NativeCapture(SRC_SYNTH_EXEC, seed=4, rate=50_000, vocab=10)
    errors = []

    def churn():
        try:
            for _ in range(20):
                src.start()
                time.sleep(0.01)
                src.stop()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    t = threading.Thread(target=churn)
    t.start()
    for _ in range(200):
        sources_stats()
    t.join()
    src.close()
    assert not errors


@needs_native
def test_top_self_observes_running_trace_gadget():
    """Run trace/exec (synthetic native source) and top/self concurrently:
    the exec gadget's source must appear with real CPU/ring numbers."""
    desc = get("trace", "exec")
    params = desc.params().to_params()
    params.set("source", "synthetic")
    params.set("rate", "100000")
    tctx = GadgetContext(desc, gadget_params=params, timeout=3.0)
    t = threading.Thread(
        target=lambda: LocalRuntime().run_gadget(tctx), daemon=True)
    t.start()
    time.sleep(0.8)  # let the trace source spin up

    sdesc = get("top", "self")
    sparams = sdesc.params().to_params()
    sparams.set("interval", "500ms")
    sctx = GadgetContext(sdesc, gadget_params=sparams, timeout=1.8)
    arrays = []
    result = LocalRuntime().run_gadget(sctx, on_event_array=arrays.append)
    tctx.cancel()
    t.join(4.0)
    assert not result.errors(), result.errors()
    assert arrays, "top/self produced no interval arrays"
    rows = [r for tick in arrays for r in tick]
    exec_rows = [r for r in rows if r.source == "synth/exec"]
    assert exec_rows, f"exec source missing from {[r.source for r in rows]}"
    # the later ticks have a produced-delta → positive rate; at least one
    # tick must show the source actually producing and burning CPU
    assert any(r.rate > 0 for r in exec_rows)
    assert any(r.cpu_pct > 0 for r in exec_rows)
    assert all("/" in r.ring for r in exec_rows)
