"""Chaos tier (ISSUE 11): supervised streams must survive injected faults.

Fast deterministic subset (tier-1):
- retry policy shape (capped exponential, full jitter) + validation,
- transport-vs-fatal error classification,
- fleet health state machine incl. straggler detection against the
  fleet's rolling p95 and clock-skew tolerance (injected SkewClock),
- resume protocol at the client level: a proxy-cut stream re-attaches
  with `resume {run_id, last_seq}`, ring replay produces NO duplicate
  seqs, an unknown run answers `unknown_run`,
- a lingering detached run is visible in DumpState + `fleet health`
  and cancels itself after the linger window,
- a supervised 2-node fan-out survives a connection cut mid-run
  (reconnect counted, result NOT partial, accounting exact:
  records + gaps == last_seq per node),
- a node that never heals ends `dead` with the result explicitly
  partial — bounded time, no hang,
- the chaos ACCEPTANCE e2e: a 3-agent run under chaos proxies survives
  (a) one agent SIGKILLed and respawned mid-run (resume finds
  unknown_run, capture restarts, the killed life's sealed windows
  backfill-merge into the result) and (b) a blackhole partition ~2×
  the backoff horizon that heals (the node passes through `dead` and
  resurrects, resuming from last_seq with no duplicate seqs). The
  unfaulted node doubles as the in-run control: the partitioned node's
  delivered stream must stay within tolerance of it, because its agent
  kept capturing into the replay ring the whole time.

Shared-run cases (ISSUE 12): cutting a subscriber mid-stream leaves the
shared run and its peers whole (the dead subscriber lingers resumable);
SIGKILLing the agent under a shared run answers unknown_run to EVERY
subscriber, and each one's supervisor backfills its gap from the dead
life's sealed windows independently.

Slow soak (`-m slow`, excluded from tier-1): N nodes, repeated mixed
faults, PLUS subscriber churn against a shared run (some rounds leaving
by proxy cut), invariants (no wedged run, exact per-node seq
accounting, stream states drained, bounded thread growth) + the N-node
merge/ingest scaling points published as schema-valid PerfRecords.
"""

from __future__ import annotations

import os
import random
import tempfile
import threading
import time

import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.agent.client import AgentClient
from inspektor_gadget_tpu.agent.service import serve
from inspektor_gadget_tpu.gadgets import GadgetContext, get
from inspektor_gadget_tpu.params import ParamError, Params
from inspektor_gadget_tpu.runtime.grpc_runtime import GrpcRuntime
from inspektor_gadget_tpu.runtime.supervisor import (
    DEAD, FATAL, FleetHealth, HEALTHY, RECONNECTING, RetryPolicy,
    STRAGGLING, TRANSPORT, classify_error,
)
from inspektor_gadget_tpu.telemetry import REGISTRY
from inspektor_gadget_tpu.testing.chaos import (
    AgentProcess, ChaosProxy, SkewClock, SubscriberChurn,
)

pytestmark = pytest.mark.filterwarnings("ignore::pytest.PytestUnhandledThreadExceptionWarning")


def _counter_value(name: str, **labels) -> float:
    """Sum of the family's samples matching every given label pair
    (label order in the exposition follows declaration, not the call)."""
    total = 0.0
    for key, v in REGISTRY.snapshot().items():
        if key != name and not key.startswith(name + "{"):
            continue
        if all(f'{k}="{lv}"' in key for k, lv in labels.items()):
            total += v
    return total


# ---------------------------------------------------------------------------
# retry policy + classification units
# ---------------------------------------------------------------------------

def test_retry_policy_backoff_shape():
    pol = RetryPolicy(base=0.1, cap=1.0, horizon=5.0, attempt_deadline=1.0,
                      rng=random.Random(7))
    # ceilings double then cap
    assert pol.ceiling(0) == pytest.approx(0.1)
    assert pol.ceiling(1) == pytest.approx(0.2)
    assert pol.ceiling(3) == pytest.approx(0.8)
    assert pol.ceiling(4) == pytest.approx(1.0)
    assert pol.ceiling(50) == pytest.approx(1.0)  # huge attempt, no overflow
    # full jitter: every delay lands in [0, ceiling] and they are not
    # all equal (the whole point is decorrelating reconnect stampedes)
    delays = [pol.delay(3) for _ in range(200)]
    assert all(0.0 <= d <= 0.8 for d in delays)
    assert len({round(d, 6) for d in delays}) > 50


def test_retry_policy_validation():
    with pytest.raises(ValueError):
        RetryPolicy(base=0.0)
    with pytest.raises(ValueError):
        RetryPolicy(base=1.0, cap=0.5)
    with pytest.raises(ValueError):
        RetryPolicy(horizon=0.0)
    with pytest.raises(ValueError):
        RetryPolicy(attempt_deadline=-1.0)


def test_error_classification():
    # transport trouble → retry with resume
    for err in ("UNAVAILABLE: connection reset", "DEADLINE_EXCEEDED: x",
                "ABORTED: peer", "INTERNAL: RST_STREAM",
                "channel not ready after 5.0s",
                "socket: connection refused"):
        assert classify_error(err) == TRANSPORT, err
    # deterministic failures → fatal, never retried
    for err in ("unknown gadget trace/nope", "INVALID_ARGUMENT: bad param",
                "gadget run failed: boom"):
        assert classify_error(err) == FATAL, err
    # a gadget-reported error is fatal even when the text looks netty
    assert classify_error("UNAVAILABLE: x", gadget_error=True) == FATAL
    assert classify_error(None) == FATAL


def test_stop_result_timeout_param_validated():
    rt = GrpcRuntime({})
    params = Params(rt.params())
    params.set("stop-result-timeout", "45s")
    assert params.get("stop-result-timeout").as_duration() == 45.0
    with pytest.raises(ParamError):
        params.set("stop-result-timeout", "0s")
    with pytest.raises(ParamError):
        params.set("stop-result-timeout", "banana")
    with pytest.raises(ParamError):
        params.set("retry-horizon", "-5s")


# ---------------------------------------------------------------------------
# fleet health state machine (injected clock, incl. skew)
# ---------------------------------------------------------------------------

def test_fleet_health_state_machine_and_straggler_p95():
    clk = SkewClock(base=lambda: 0.0)  # fully deterministic time
    h = FleetHealth(["a", "b", "c"], clock=clk, straggler_factor=4.0,
                    straggler_floor=0.5)
    assert h.states() == {"a": HEALTHY, "b": HEALTHY, "c": HEALTHY}
    # no cadence yet → no straggler threshold → nobody flagged
    clk.skew(100.0)
    assert h.check_stragglers() == []

    # establish a ~0.1s fleet cadence on a and b
    for _ in range(50):
        clk.skew(0.1)
        h.observe("a")
        h.observe("b")
    # c silent for 10× the cadence-derived threshold → straggling;
    # a and b stay healthy
    assert h.straggler_threshold() == pytest.approx(0.5)  # floor wins
    clk.skew(0.3)
    h.observe("a")
    h.observe("b")
    flagged = h.check_stragglers()
    assert flagged == ["c"]
    assert h.get("c") == STRAGGLING
    # a record from the straggler heals it
    h.observe("c")
    assert h.get("c") == HEALTHY

    # supervisor-owned transitions + resurrection on data
    h.mark("b", RECONNECTING)
    assert h.get("b") == RECONNECTING
    h.mark("b", DEAD)
    assert h.get("b") == DEAD
    h.observe("b")  # data after death = resurrection
    assert h.get("b") == HEALTHY

    # forward clock skew: one check may flag conservatively, the next
    # record heals — skew must never wedge a node unhealthy
    clk.skew(50.0)
    h.check_stragglers()
    h.observe("a")
    assert h.get("a") == HEALTHY
    # backward-looking: a backwards step must not poison the p95 with
    # negative intervals
    before = h.fleet_p95()
    clk.skew(-25.0)
    h.observe("a")
    assert h.fleet_p95() >= 0.0 if before is None else h.fleet_p95() >= 0.0

    # transitions counter saw the dead label
    assert _counter_value("ig_fleet_transitions_total", node="b",
                          to="dead") >= 1.0


# ---------------------------------------------------------------------------
# resume protocol (client ↔ agent through a chaos proxy)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def chaos_agents():
    """Two in-process agents on unix sockets, each behind a TCP chaos
    proxy; yields {node: (proxy, unix_target)}."""
    tmp = tempfile.mkdtemp()
    servers, proxies, targets = [], {}, {}
    for i in range(2):
        addr = f"unix://{tmp}/chaos{i}.sock"
        server, _agent = serve(addr, node_name=f"cnode-{i}")
        servers.append(server)
        proxy = ChaosProxy(addr)
        proxies[f"cnode-{i}"] = proxy
        targets[f"cnode-{i}"] = proxy.target
    yield {"proxies": proxies, "targets": targets}
    for p in proxies.values():
        p.close()
    for s in servers:
        s.stop(grace=0.5)


RUN_PARAMS = {"gadget.source": "pysynthetic", "gadget.rate": "2000",
              "gadget.batch-size": "128"}


def test_resume_replays_ring_without_duplicate_seqs(chaos_agents):
    proxy = chaos_agents["proxies"]["cnode-0"]
    target = chaos_agents["targets"]["cnode-0"]
    client = AgentClient(target, "cnode-0")
    seqs1: list[int] = []
    got_enough = threading.Event()

    def on_msg1(_n, seq, _t):
        seqs1.append(seq)
        if len(seqs1) >= 50:
            got_enough.set()

    holder: dict = {}

    def run1():
        holder["out"] = client.run_gadget(
            "trace", "exec", RUN_PARAMS, timeout=0.0,
            run_id="resume-unit", resumable=True, linger=8.0, ring=8192,
            on_message=on_msg1)

    t = threading.Thread(target=run1, daemon=True)
    t.start()
    assert got_enough.wait(20.0), "no stream traffic before the cut"
    proxy.cut()
    t.join(timeout=20.0)
    assert not t.is_alive(), "cut stream did not return"
    out1 = holder["out"]
    assert out1["error"], "a severed stream must surface a transport error"
    assert classify_error(out1["error"]) == TRANSPORT, out1["error"]
    last1 = out1["last_seq"]
    assert last1 >= 50
    # exact accounting on the first leg
    assert out1["records"] + out1["gaps"] == last1

    # re-attach after the cut: replay starts at last_seq+1, no overlap
    client.reconnect()
    stop = threading.Event()
    seqs2: list[int] = []

    def on_msg2(_n, seq, _t):
        seqs2.append(seq)
        if len(seqs2) >= 50:
            stop.set()

    out2 = client.run_gadget(
        "trace", "exec", RUN_PARAMS, timeout=0.0,
        run_id="resume-unit", resume_from=last1,
        on_message=on_msg2, stop_event=stop)
    client.close()
    assert out2["error"] is None, out2["error"]
    ack = out2["resume"]
    assert ack and ack["run_id"] == "resume-unit"
    assert ack["missed"] == 0, "8192-deep ring must cover a fast cut"
    assert seqs2, "no messages after resume"
    assert min(seqs2) == last1 + 1, "replay must start right after last_seq"
    assert not (set(seqs1) & set(seqs2)), "duplicate seqs across resume"
    assert seqs2 == sorted(seqs2)
    assert out2["records"] + out2["gaps"] == out2["last_seq"] - last1


def test_resume_unknown_run_is_reported(chaos_agents):
    target = chaos_agents["targets"]["cnode-1"]
    client = AgentClient(target, "cnode-1")
    out = client.run_gadget("trace", "exec", {}, timeout=0.0,
                            run_id="never-started", resume_from=123)
    client.close()
    assert out["unknown_run"] is True
    assert "unknown run" in (out["error"] or "")
    # the supervisor branches on unknown_run BEFORE classification —
    # restart fresh + backfill, not resume-retry
    assert not out["resume"]


def test_lingering_run_visible_then_self_cancels(chaos_agents):
    proxy = chaos_agents["proxies"]["cnode-1"]
    target = chaos_agents["targets"]["cnode-1"]
    client = AgentClient(target, "cnode-1")
    started = threading.Event()

    def run1():
        client.run_gadget("trace", "exec", RUN_PARAMS, timeout=0.0,
                          run_id="linger-unit", resumable=True, linger=1.0,
                          on_message=lambda *_: started.set())

    t = threading.Thread(target=run1, daemon=True)
    t.start()
    assert started.wait(20.0)
    proxy.cut()
    t.join(timeout=20.0)

    # a second client sees the detached run awaiting resume…
    probe = AgentClient(target, "cnode-1", rpc_deadline=5.0)
    deadline = time.monotonic() + 5.0
    row = None
    while time.monotonic() < deadline:
        rows = [r for r in probe.dump_state().get("runs", [])
                if r["run_id"] == "linger-unit"]
        if rows and not rows[0]["attached"] and not rows[0]["done"]:
            row = rows[0]
            break
        time.sleep(0.1)
    assert row, "detached run not visible in DumpState"
    assert row["resumable"] and row["detached_for"] >= 0.0

    # …and the fleet health CLI renders it
    from inspektor_gadget_tpu.cli.main import main as cli_main
    import io
    import contextlib
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli_main(["fleet", "health",
                       "--remote", f"cnode-1={target}"])
    assert rc == 0
    assert "awaiting resume: linger-unit" in buf.getvalue()

    # past the linger window the run cancels itself and the stream
    # state retires — no zombie gadget, no registry growth
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        rows = [r for r in probe.dump_state().get("runs", [])
                if r["run_id"] == "linger-unit" and not r["done"]]
        if not rows:
            break
        time.sleep(0.2)
    assert not rows, "detached run did not cancel after its linger window"
    probe.close()
    client.close()


# ---------------------------------------------------------------------------
# shared-run subscribers under fault (ISSUE 12 fast tier)
# ---------------------------------------------------------------------------

def test_cut_subscriber_leaves_shared_run_whole(chaos_agents):
    """A subscriber severed mid-stream is THAT subscriber's problem:
    the shared gadget keeps capturing, the owner's stream never blips,
    and the dead subscriber lingers detached awaiting a resume instead
    of taking the run down with it."""
    target = chaos_agents["targets"]["cnode-0"]
    sub_proxy = ChaosProxy(target)  # the subscriber's own breakable path
    owner_stop = threading.Event()
    owner_holder: dict = {}
    owner_seqs: list[int] = []
    started = threading.Event()

    def owner():
        client = AgentClient(target, "cnode-0")
        owner_holder["out"] = client.run_gadget(
            "trace", "exec",
            dict(RUN_PARAMS, **{"gadget.rate": "1600"}),
            timeout=0.0, run_id="sub-cut", share=True, keepalive=1.0,
            on_message=lambda _n, s, _t: (owner_seqs.append(s),
                                          started.set()),
            stop_event=owner_stop)
        client.close()

    t_owner = threading.Thread(target=owner, daemon=True)
    t_owner.start()
    assert started.wait(30.0), "shared run never produced"

    sub_holder: dict = {}
    sub_seqs: list[int] = []

    def subscriber():
        client = AgentClient(sub_proxy.target, "cut-sub")
        sub_holder["out"] = client.run_gadget(
            "", "", attach_to="sub-cut",
            subscriber={"queue": 1024},
            on_message=lambda _n, s, _t: sub_seqs.append(s))
        client.close()

    t_sub = threading.Thread(target=subscriber, daemon=True)
    t_sub.start()
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline and len(sub_seqs) < 20:
        time.sleep(0.05)
    assert len(sub_seqs) >= 20, "subscriber saw no traffic before the cut"
    owner_before_cut = len(owner_seqs)
    sub_proxy.cut()
    t_sub.join(timeout=20.0)
    assert not t_sub.is_alive()
    out = sub_holder["out"]
    assert out["error"], "a severed subscriber must surface its error"
    assert classify_error(out["error"]) == TRANSPORT

    # the run and the owner are untouched; the cut subscriber's state
    # lingers detached (resumable by the PR-8 protocol, per subscriber)
    time.sleep(0.7)
    probe = AgentClient(target, "probe", rpc_deadline=5.0)
    rows = [r for r in probe.dump_state().get("runs", [])
            if r["run_id"] == "sub-cut"]
    probe.close()
    assert rows and not rows[0]["done"], "subscriber cut killed the run"
    assert len(owner_seqs) > owner_before_cut, "owner stream blipped"
    sub_rows = {s["sub_id"]: s for s in rows[0]["subscribers"]}
    cut_rows = [s for s in sub_rows.values()
                if not s["attached"] and not s["left"]]
    assert cut_rows, f"cut subscriber not lingering: {sub_rows}"
    owner_stop.set()
    t_owner.join(timeout=20.0)
    assert owner_holder["out"]["error"] is None
    # exact accounting end to end for the owner despite the peer's death
    o = owner_holder["out"]
    assert o["records"] + o["gaps"] == o["last_seq"]
    assert o["sub_drops"] == 0
    sub_proxy.close()


def test_agent_sigkill_subscribers_unknown_run_then_independent_backfill(
        tmp_path_factory):
    """SIGKILL the agent under a shared run with two subscribers: BOTH
    resumes answer unknown_run (the new life has nothing to resume),
    and each subscriber's supervisor heals its own gap from the dead
    life's sealed windows INDEPENDENTLY — two clients, two fetches,
    the same sealed truth."""
    from inspektor_gadget_tpu.history import HISTORY
    from inspektor_gadget_tpu.runtime.supervisor import NodeSupervisor

    hist = str(tmp_path_factory.mktemp("subkill-history"))
    tmp = tempfile.mkdtemp()
    addr = f"unix://{tmp}/subkill.sock"
    proc = AgentProcess("subkill-node", addr, history_dir=hist)
    proc.start(wait=True, timeout=90.0)
    clients: list[AgentClient] = []
    try:
        params = {"gadget.source": "pysynthetic", "gadget.rate": "2000",
                  "operator.tpusketch.enable": "true",
                  "operator.tpusketch.log2-width": "10",
                  "operator.tpusketch.hll-p": "10",
                  "operator.tpusketch.harvest-interval": "300ms",
                  "operator.tpusketch.history": "true",
                  "operator.tpusketch.history-interval": "0",
                  "operator.tpusketch.history-log2-width": "10",
                  "operator.tpusketch.history-slots": "4"}
        # warm the subprocess's sketch path so the measured life seals
        warm = AgentClient(addr, "subkill-node")
        warm.run_gadget("trace", "exec", params, timeout=1.5,
                        outputs=("summary",))
        warm.close()

        owner_stop = threading.Event()
        holder: dict = {}
        got = threading.Event()

        def owner():
            c = AgentClient(addr, "subkill-node")
            clients.append(c)
            holder["owner"] = c.run_gadget(
                "trace", "exec", params, timeout=0.0, run_id="subkill",
                share=True, resumable=True, keepalive=8.0,
                outputs=("summary",),
                on_message=lambda *_: got.set(), stop_event=owner_stop)

        def second():
            c = AgentClient(addr, "subkill-2")
            clients.append(c)
            holder["second"] = c.run_gadget(
                "", "", attach_to="subkill",
                on_message=lambda *_: None)

        t1 = threading.Thread(target=owner, daemon=True)
        t1.start()
        assert got.wait(60.0), "shared run never produced"
        t2 = threading.Thread(target=second, daemon=True)
        t2.start()
        time.sleep(2.5)  # let the run seal a few 300ms windows

        kill_wall = time.time()
        proc.kill()
        t1.join(timeout=30.0)
        t2.join(timeout=30.0)
        assert holder["owner"]["error"] and holder["second"]["error"]
        proc.respawn(wait=True, timeout=90.0)

        # every subscriber's resume answers unknown_run on the new life
        for name, last in (("subkill-r1", holder["owner"]["last_seq"]),
                           ("subkill-r2", 0)):
            c = AgentClient(addr, name)
            out = c.run_gadget("trace", "exec", {}, timeout=0.0,
                               run_id="subkill", resume_from=int(last))
            c.close()
            assert out["unknown_run"] is True, (name, out)

        # each subscriber's supervisor backfills INDEPENDENTLY from the
        # dead life's sealed windows
        health = FleetHealth(["subkill-node"])
        outs = []
        for name in ("bf-1", "bf-2"):
            c = AgentClient(addr, name)
            sup = NodeSupervisor(
                "subkill-node", c,
                policy=RetryPolicy(base=0.05, cap=0.2, horizon=2.0,
                                   attempt_deadline=1.0),
                health=health, run_id="subkill", gadget="trace/exec",
                done=lambda: True)
            out = {"backfill": [], "backfilled": 0}
            sup._backfill(kill_wall - 30.0, time.time() + 1.0, out)
            c.close()
            outs.append(out)
        for out in outs:
            assert out["backfilled"] > 0, \
                "subscriber recovered nothing from the dead life"
        assert outs[0]["backfilled"] == outs[1]["backfilled"], \
            "independent backfills must recover the same sealed truth"
    finally:
        for c in clients:
            try:
                c.close()
            except Exception:  # noqa: BLE001 — dead channels
                pass
        proc.stop()
        HISTORY.close_all()


# ---------------------------------------------------------------------------
# supervised fan-out (fast e2e)
# ---------------------------------------------------------------------------

def _fast_runtime_params(runtime, **overrides):
    p = Params(runtime.params())
    defaults = {"retry-base": "50ms", "retry-cap": "400ms",
                "attempt-deadline": "1s", "retry-horizon": "2s",
                "resume-ring": "16384", "resume-linger": "8s",
                "straggler-floor": "1s"}
    defaults.update(overrides)
    for k, v in defaults.items():
        p.set(k, v)
    return p


def test_supervised_fanout_survives_cut(chaos_agents):
    targets = dict(chaos_agents["targets"])
    runtime = GrpcRuntime(targets)
    desc = get("trace", "exec")
    params = desc.params().to_params()
    params.set("source", "pysynthetic")
    params.set("rate", "1500")
    params.set("batch-size", "128")
    ctx = GadgetContext(desc, gadget_params=params,
                        runtime_params=_fast_runtime_params(runtime),
                        timeout=5.0)
    events = []

    def cutter():
        time.sleep(1.2)
        chaos_agents["proxies"]["cnode-0"].cut()

    threading.Thread(target=cutter, daemon=True).start()
    result = runtime.run_gadget(ctx, on_event=events.append)
    runtime.close()

    assert set(result.keys()) == set(targets)
    assert not result.errors(), result.errors()
    assert result["cnode-0"].reconnects >= 1
    assert result["cnode-0"].health == "healthy"
    assert result.partial is False
    assert result.health == {"cnode-0": "healthy", "cnode-1": "healthy"}
    assert sorted(result.contributing()) == sorted(targets)
    # events flowed from both nodes, including post-cut
    assert {e.node for e in events} == set(targets)
    # EXACT accounting: every seq is either received or a counted gap
    for node, r in result.items():
        assert r.records + r.gaps == r.last_seq, (node, r)
    assert _counter_value("ig_fleet_reconnects_total",
                          node="cnode-0") >= 1.0


def test_never_healing_node_is_dead_and_result_partial(chaos_agents):
    # one real node + one target nobody serves (connection refused):
    # the run must complete in bounded time with the dead node LABELED
    # dead and the combined result explicitly partial
    targets = {"cnode-0": chaos_agents["targets"]["cnode-0"],
               "ghost": "127.0.0.1:1"}
    runtime = GrpcRuntime(targets)
    desc = get("trace", "exec")
    params = desc.params().to_params()
    params.set("source", "pysynthetic")
    params.set("rate", "1000")
    ctx = GadgetContext(desc, gadget_params=params,
                        runtime_params=_fast_runtime_params(
                            runtime, **{"retry-horizon": "600ms",
                                        "attempt-deadline": "400ms"}),
                        timeout=2.5)
    t0 = time.monotonic()
    result = runtime.run_gadget(ctx, on_event=lambda e: None)
    elapsed = time.monotonic() - t0
    runtime.close()

    assert elapsed < 30.0, "never-healing node must not wedge the run"
    assert result["cnode-0"].error is None
    assert result["ghost"].error, "dead node must carry its last error"
    assert result["ghost"].health == "dead"
    assert result.health["ghost"] == "dead"
    assert result.partial is True
    assert result.contributing() == ["cnode-0"]
    assert _counter_value("ig_runtime_node_errors_total", node="ghost",
                          **{"class": "transport"}) >= 1.0


def test_unknown_gadget_is_a_gadget_error(chaos_agents):
    """A run-setup refusal (unknown gadget) reaches the client flagged
    gadget_error so the supervisor classifies it fatal, not transport."""
    client = AgentClient(chaos_agents["targets"]["cnode-0"], "cnode-0")
    out = client.run_gadget("trace", "no-such-gadget", {}, timeout=1.0)
    client.close()
    assert out["error"]
    assert out["gadget_error"] is True
    assert classify_error(out["error"],
                          gadget_error=out["gadget_error"]) == FATAL


def _stub_supervisor(attempts, *, done=lambda: False):
    """A NodeSupervisor with the network seams stubbed out: attempt
    results come from a scripted list, channel readiness is instant."""
    from inspektor_gadget_tpu.runtime.supervisor import NodeSupervisor

    class _Client:
        def reconnect(self):
            pass

    health = FleetHealth(["n"], straggler_floor=0.1)
    sup = NodeSupervisor(
        "n", _Client(),
        policy=RetryPolicy(base=0.001, cap=0.002, horizon=0.5,
                           attempt_deadline=0.1),
        health=health, run_id="r", gadget="trace/exec", done=done,
        backfill=False)
    sup._wait_channel_ready = lambda: True
    calls = []

    def attempt(resume_from, rid):
        calls.append(resume_from)
        base = {"result": None, "error": None, "gaps": 0, "dropped": 0,
                "records": 0, "last_seq": 0, "resume": None,
                "unknown_run": False, "gadget_error": False}
        base.update(attempts[min(len(calls) - 1, len(attempts) - 1)])
        return base

    return sup, health, attempt, calls


def test_supervisor_fatal_gadget_error_not_retried():
    sup, health, attempt, calls = _stub_supervisor([
        {"error": "gadget run failed: boom", "gadget_error": True},
    ])
    out = sup.run(attempt)
    assert out["error"] == "gadget run failed: boom"
    assert len(calls) == 1, "fatal errors must not trigger the retry loop"
    assert out["reconnects"] == 0
    assert health.get("n") == DEAD


def test_supervisor_backfills_on_resume_missed_and_resets_outage():
    """A resume ack with missed>0 must trigger the sealed-window
    backfill for the outage interval, and a successful re-attach must
    CLEAR the outage clock — a later unrelated blip starts a fresh
    horizon instead of inheriting the first outage's start time."""
    sup, health, attempt, calls = _stub_supervisor([
        {"error": "UNAVAILABLE: cut", "last_seq": 40, "records": 40},
        {"error": "UNAVAILABLE: cut again", "last_seq": 70, "records": 25,
         "resume": {"run_id": "r", "missed": 5, "replayed": 25}},
        {"error": None, "last_seq": 90, "records": 20,
         "resume": {"run_id": "r", "missed": 0, "replayed": 0}},
    ])
    backfills = []
    sup._backfill_enabled = True
    sup._backfill = lambda since, until, out: backfills.append((since, until))
    out = sup.run(attempt)
    assert out["error"] is None
    # exactly one backfill: the missed-5 re-attach; the missed-0 one not
    assert len(backfills) == 1
    since, until = backfills[0]
    assert since < until
    assert health.get("n") == HEALTHY
    assert calls == [None, 40, 70]


def test_supervisor_unknown_run_restarts_seq_space():
    """After an agent respawn (unknown_run) the new life numbers its
    stream from 1: the supervisor must reset its resume baseline, not
    resume the new ring from the dead life's high seq."""
    sup, health, attempt, calls = _stub_supervisor([
        {"error": "UNAVAILABLE: killed", "last_seq": 40, "records": 40},
        {"error": "unknown run 'r'", "unknown_run": True},
        {"error": "UNAVAILABLE: flap", "last_seq": 0, "records": 0},
        {"error": None, "last_seq": 30, "records": 30,
         "resume": {"run_id": "r", "missed": 0, "replayed": 30}},
    ])
    out = sup.run(attempt)
    assert out["error"] is None
    # after unknown_run: fresh start (None), then resume from the NEW
    # life's baseline 0 — never from the dead life's 40
    assert calls == [None, 40, None, 0]
    assert out["last_seq"] == 30
    assert out["records"] == 70


def test_supervisor_resumes_transport_errors_until_clean():
    sup, health, attempt, calls = _stub_supervisor([
        {"error": "UNAVAILABLE: cut", "last_seq": 40, "records": 40},
        {"error": "UNAVAILABLE: still down"},
        {"error": None, "last_seq": 90, "records": 50,
         "resume": {"run_id": "r", "missed": 0, "replayed": 10}},
    ])
    out = sup.run(attempt)
    assert out["error"] is None
    # first attempt fresh, then resume-from-40 on every retry
    assert calls == [None, 40, 40]
    assert out["reconnects"] == 2
    assert out["records"] == 90 and out["last_seq"] == 90
    assert health.get("n") == HEALTHY


# ---------------------------------------------------------------------------
# the chaos ACCEPTANCE e2e: SIGKILL+respawn and a healed 2×-horizon partition
# ---------------------------------------------------------------------------

def test_chaos_acceptance_sigkill_respawn_and_partition_heal(
        tmp_path_factory):
    """3-agent run under chaos proxies (ISSUE 11 acceptance):

    - `aknode` (real subprocess) is SIGKILLed mid-run and respawned on
      the same address + history dir: the resume finds `unknown_run`,
      capture restarts fresh, and the killed life's SEALED windows
      backfill-merge into the node's result (accounted in
      ig_fleet_backfilled_records_total),
    - `anode-1` is blackhole-partitioned for ~2.7× the backoff horizon,
      passes through `dead`, heals, and resumes from last_seq with ring
      replay (exact seq accounting, no duplicates by construction),
    - `anode-0` is never faulted — the in-run control: the partitioned
      node's server-side sketch totals must match it within a documented
      tolerance, because its agent kept capturing the whole time.

    The run completes without manual intervention and the result is NOT
    partial (every node healed)."""
    from inspektor_gadget_tpu.history import HISTORY, merge_windows
    from inspektor_gadget_tpu.operators import operators as op_registry
    from inspektor_gadget_tpu.params import Collection

    hist_base = str(tmp_path_factory.mktemp("chaos-history"))
    tmp = tempfile.mkdtemp()
    servers, proxies, targets = [], {}, {}
    HISTORY.set_base_dir(hist_base)
    agent_proc = None
    runtime = None
    try:
        for i in range(2):
            addr = f"unix://{tmp}/acc{i}.sock"
            server, _ = serve(addr, node_name=f"anode-{i}")
            servers.append(server)
            proxies[f"anode-{i}"] = ChaosProxy(addr)
            targets[f"anode-{i}"] = proxies[f"anode-{i}"].target
        ak_addr = f"unix://{tmp}/acc-k.sock"
        agent_proc = AgentProcess("aknode", ak_addr, history_dir=hist_base)
        agent_proc.start(wait=True, timeout=90.0)
        proxies["aknode"] = ChaosProxy(ak_addr)
        targets["aknode"] = proxies["aknode"].target

        # warm the fresh subprocess's sketch path (jit compiles on first
        # harvest): the measured first life must spend its time SEALING
        # windows, not compiling — otherwise the pre-kill life can end
        # with nothing sealed and there is nothing to backfill
        warm = AgentClient(ak_addr, "aknode")
        warm.run_gadget("trace", "exec",
                        {"gadget.source": "pysynthetic",
                         "gadget.rate": "2000",
                         "operator.tpusketch.enable": "true",
                         "operator.tpusketch.log2-width": "10",
                         "operator.tpusketch.hll-p": "10",
                         "operator.tpusketch.harvest-interval": "300ms"},
                        timeout=1.5, outputs=("summary",))
        warm.close()

        desc = get("trace", "exec")
        params = desc.params().to_params()
        params.set("source", "pysynthetic")
        params.set("rate", "600")
        params.set("batch-size", "64")
        op_params = Collection()
        sp = op_registry.get("tpusketch").instance_params().to_params()
        for k, v in (("enable", "true"), ("log2-width", "10"),
                     ("hll-p", "10"), ("harvest-interval", "500ms"),
                     ("history", "true"), ("history-interval", "0"),
                     ("history-log2-width", "10"), ("history-slots", "4")):
            sp.set(k, v)
        op_params["operator.tpusketch."] = sp

        runtime = GrpcRuntime(targets)
        ctx = GadgetContext(
            desc, gadget_params=params, operator_params=op_params,
            runtime_params=_fast_runtime_params(
                runtime, **{"retry-horizon": "1500ms",
                            "attempt-deadline": "1s"}),
            timeout=14.0)

        events = []
        summaries: dict = {}

        def on_summary(node, s):
            summaries.setdefault(node, []).append(s)

        def chaos_script():
            time.sleep(3.0)
            # (b) partition anode-1 ~2.7× the 1.5s horizon, then heal
            proxies["anode-1"].partition(mode="blackhole")
            # (a) SIGKILL the real agent mid-run; respawn on the same
            # address + dirs (no waiting — the supervisor's retry loop
            # must discover the new life on its own). By now the first
            # life has sealed several 500ms windows — the state the
            # backfill recovers.
            time.sleep(1.5)
            agent_proc.kill()
            agent_proc.respawn(wait=False)
            time.sleep(2.5)
            proxies["anode-1"].heal()

        threading.Thread(target=chaos_script, daemon=True).start()
        result = runtime.run_gadget(ctx, on_event=events.append,
                                    on_summary=on_summary)

        assert set(result.keys()) == {"anode-0", "anode-1", "aknode"}
        # the run completed without manual intervention, nobody wedged,
        # and every node healed → the answer is NOT partial
        assert not result.errors(), result.errors()
        assert result.partial is False, result.health

        # (b) the partitioned node: went through dead (2× horizon),
        # resurrected, resumed from last_seq with exact accounting
        r1 = result["anode-1"]
        assert r1.reconnects >= 1
        assert r1.health == "healthy"
        assert r1.records + r1.gaps == r1.last_seq
        assert _counter_value("ig_fleet_transitions_total",
                              node="anode-1", to="dead") >= 1.0
        assert _counter_value("ig_fleet_reconnects_total",
                              node="anode-1") >= 1.0

        # (a) the killed node: reconnected to its NEW life and healed
        # the gap from the old life's sealed windows
        rk = result["aknode"]
        assert rk.reconnects >= 1
        assert rk.health == "healthy"
        assert rk.backfilled > 0, \
            "killed node must recover sealed windows from its past life"
        assert rk.backfill, "backfilled SealedWindows must ride the result"
        merged = merge_windows(rk.backfill)
        assert merged.events == rk.backfilled
        assert _counter_value("ig_fleet_backfilled_records_total",
                              node="aknode") >= float(rk.backfilled)

        # delivered stream: the resumed node stays within tolerance of
        # the in-run control (its agent captured through the partition
        # into the replay ring — resume is NOT restart)
        per_node = {n: 0 for n in targets}
        for e in events:
            per_node[e.node] += 1
        assert per_node["anode-0"] > 200, per_node
        assert per_node["anode-1"] >= 0.55 * per_node["anode-0"], per_node
        assert per_node["aknode"] > 0, per_node

        # server-side sketch totals: partitioned node ≈ control within
        # the documented tolerance (docs/robustness.md: rate-jitter
        # bound, not sketch error — CMS totals are exact adds)
        ev0 = max(s["events"] for s in summaries["anode-0"])
        ev1 = max(s["events"] for s in summaries["anode-1"])
        assert ev1 >= 0.55 * ev0, (ev0, ev1)
    finally:
        if runtime is not None:
            runtime.close()
        for p in proxies.values():
            p.close()
        if agent_proc is not None:
            agent_proc.stop()
        for s in servers:
            s.stop(grace=0.5)
        HISTORY.close_all()
        HISTORY.set_base_dir(None)


# ---------------------------------------------------------------------------
# the full soak: N nodes, repeated mixed faults, invariants + scaling points
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_soak_fleet_chaos_invariants_and_scaling(tmp_path_factory):
    """ROADMAP soak invariants at 4 nodes over ~20s of injected chaos:
    no wedged run, exact per-node seq accounting (received + gaps ==
    last_seq), every node healthy at the end, stream states drained
    (no leaked lingering runs), bounded thread growth, and the N-node
    merge/ingest scaling points published as schema-valid PerfRecords
    so fleet-scale regressions can gate like speed regressions."""
    from inspektor_gadget_tpu.history import HISTORY, decode_frames, merge_windows
    from inspektor_gadget_tpu.operators import operators as op_registry
    from inspektor_gadget_tpu.params import Collection
    from inspektor_gadget_tpu.perf.ledger import append_record, read_ledger
    from inspektor_gadget_tpu.perf.provenance import build_provenance
    from inspektor_gadget_tpu.perf.schema import make_record

    n_nodes = 4
    hist_base = str(tmp_path_factory.mktemp("soak-history"))
    tmp = tempfile.mkdtemp()
    HISTORY.set_base_dir(hist_base)
    servers, agents, proxies, targets = [], [], {}, {}
    runtime = None
    baseline_threads = threading.active_count()
    try:
        for i in range(n_nodes):
            addr = f"unix://{tmp}/soak{i}.sock"
            server, agent = serve(addr, node_name=f"snode-{i}")
            servers.append(server)
            agents.append(agent)
            proxies[f"snode-{i}"] = ChaosProxy(addr)
            targets[f"snode-{i}"] = proxies[f"snode-{i}"].target

        desc = get("trace", "exec")
        params = desc.params().to_params()
        params.set("source", "pysynthetic")
        params.set("rate", "1200")
        params.set("batch-size", "128")
        op_params = Collection()
        sp = op_registry.get("tpusketch").instance_params().to_params()
        for k, v in (("enable", "true"), ("log2-width", "10"),
                     ("hll-p", "10"), ("harvest-interval", "1s"),
                     ("history", "true"), ("history-interval", "0"),
                     ("history-log2-width", "10"), ("history-slots", "4")):
            sp.set(k, v)
        op_params["operator.tpusketch."] = sp

        runtime = GrpcRuntime(targets)
        ctx = GadgetContext(
            desc, gadget_params=params, operator_params=op_params,
            runtime_params=_fast_runtime_params(
                runtime, **{"share": "true", "run-keepalive": "1s"}),
            timeout=20.0)

        events = []
        faults = {"count": 0}

        def chaos_loop():
            rng = random.Random(11)
            nodes = sorted(proxies)
            time.sleep(2.0)
            while faults["count"] < 6:
                node = nodes[faults["count"] % len(nodes)]
                kind = faults["count"] % 3
                if kind == 0:
                    proxies[node].cut()
                elif kind == 1:
                    proxies[node].set_latency(0.05 + rng.random() * 0.1)
                    time.sleep(1.0)
                    proxies[node].heal()
                else:
                    proxies[node].partition(mode="blackhole")
                    time.sleep(1.2)
                    proxies[node].heal()
                faults["count"] += 1
                time.sleep(1.3)

        # subscriber churn rides the soak: dashboard clients attach and
        # leave (some by proxy cut) against snode-0's SHARED run while
        # the connection chaos plays out — the leak/thread invariants
        # below now cover the multiplexing plane too
        churn = SubscriberChurn(
            targets["snode-0"], f"{ctx.run_id}-snode-0",
            node="soak-churner", proxy=proxies["snode-0"],
            subscriber={"queue": 256, "priority": "low"})

        def churn_loop():
            time.sleep(3.0)  # let the shared run start producing
            stop_at = time.monotonic() + 12.0
            while time.monotonic() < stop_at:
                churn.round(hold=0.6, cut=(churn.rounds % 4 == 3))

        t0 = time.monotonic()
        threading.Thread(target=chaos_loop, daemon=True).start()
        threading.Thread(target=churn_loop, daemon=True).start()
        result = runtime.run_gadget(ctx, on_event=events.append)
        duration = time.monotonic() - t0

        # invariant: no wedged run, every node answered and healed
        assert set(result.keys()) == set(targets)
        assert not result.errors(), result.errors()
        assert result.partial is False, result.health
        assert faults["count"] >= 5, "chaos loop did not run"
        # invariant: exact seq accounting per node despite N faults
        for node, r in result.items():
            assert r.records + r.gaps == r.last_seq, (node, r)
        total_reconnects = sum(r.reconnects for r in result.values())
        assert total_reconnects >= 2, "faults produced no reconnects?"
        # the churn really happened, and some rounds attached cleanly
        # (rounds overlapping a proxy fault may error — that IS the
        # chaos; the invariants below are what must hold regardless)
        assert churn.rounds >= 6, f"subscriber churn barely ran: {churn.rounds}"
        assert churn.acks >= 2, "no churn subscriber ever attached"
        assert churn.cuts >= 1, "no churn subscriber left by cut"

        # invariant: stream states drain (no leaked lingering runs)
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            leftovers = [rid for a in agents for rid in a._streams]
            if not leftovers:
                break
            time.sleep(0.3)
        assert not leftovers, f"leaked stream states: {leftovers}"
        # invariant: bounded growth — the run's threads wind down
        deadline = time.monotonic() + 10.0
        while (threading.active_count() > baseline_threads + 24
               and time.monotonic() < deadline):
            time.sleep(0.3)
        assert threading.active_count() <= baseline_threads + 24

        # scaling points → schema-valid PerfRecords in a ledger
        frames_per_node, _errs = runtime.fetch_windows(gadget="trace/exec")
        windows = []
        for res in frames_per_node.values():
            windows.extend(decode_frames(res["frames"]))
        assert windows, "soak sealed no windows"
        m0 = time.perf_counter()
        merged = merge_windows(windows)
        merge_s = max(time.perf_counter() - m0, 1e-9)
        assert merged.events > 0
        ledger = str(tmp_path_factory.mktemp("soak-ledger") / "PERF.jsonl")
        prov = build_provenance("cpu", False)
        ingest_rec = make_record(
            config=f"soak-fleet-{n_nodes}node", metric="fleet_ingest",
            unit="ev/s", value=len(events) / duration,
            stages={"merge": {"seconds": merge_s,
                              "events": float(merged.events)},
                    "harvest": {"events": float(len(events)),
                                "seconds": duration}},
            provenance=prov,
            extra={"nodes": n_nodes, "faults": faults["count"],
                   "reconnects": total_reconnects,
                   "windows": len(windows)})
        merge_rec = make_record(
            config=f"soak-fleet-{n_nodes}node", metric="fleet_merge",
            unit="windows/s", value=len(windows) / merge_s,
            stages={"merge": {"seconds": merge_s,
                              "calls": float(len(windows))}},
            provenance=prov,
            extra={"nodes": n_nodes})
        append_record(ingest_rec, path=ledger)
        append_record(merge_rec, path=ledger)
        read = read_ledger(path=ledger)
        assert len(read.records) == 2 and not read.skipped
    finally:
        if runtime is not None:
            runtime.close()
        for p in proxies.values():
            p.close()
        for s in servers:
            s.stop(grace=0.5)
        HISTORY.close_all()
        HISTORY.set_base_dir(None)
