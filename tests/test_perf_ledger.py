"""Perf-observability plane: PerfRecord schema, append-only ledger,
noise-aware regression comparison, and a tiny-n stage-segmented harness
smoke run (the acceptance gates of the perf plane).

Key provenance contracts pinned here:

- a record without provenance (or with a non-bool degraded flag) cannot
  enter the ledger;
- `degraded: true` records are never baseline material, and a TPU
  candidate whose only history is degraded/CPU records is REFUSED
  (exit 3), not silently compared;
- a synthetic 20% throughput regression exits nonzero; an in-band run
  exits zero;
- concurrent appends never interleave bytes (one os.write on O_APPEND).
"""

from __future__ import annotations

import json
import threading

import pytest

from inspektor_gadget_tpu.perf import (
    append_record,
    bench_json_to_record,
    compare_record,
    make_record,
    read_ledger,
    run_harness,
    validate_record,
)
from inspektor_gadget_tpu.perf.compare import (
    RC_REGRESSION,
    RC_REFUSED,
    compare_ledger,
    render_compare,
    render_report,
)
from inspektor_gadget_tpu.perf.schema import SCHEMA_ID


def prov(platform="tpu", degraded=False, sha="deadbeef"):
    return {
        "git_sha": sha, "git_dirty": False,
        "host": {"hostname": "h", "machine": "x86_64", "python": "3.12"},
        "platform": platform, "degraded": degraded,
        "probe": {"outcome": "ok", "attempts": []},
    }


def rec(value, platform="tpu", degraded=False, config="bench.e2e", ts=None):
    return make_record(
        config=config, metric="sketch_ingest_throughput_e2e",
        unit="events/sec/chip", value=value,
        stages={"pop": {"ev_per_s": value * 1.5, "seconds": 1.0}},
        provenance=prov(platform, degraded),
        ts=ts or f"2026-08-0{1 + (int(value) % 8)}T00:00:00+00:00",
    )


# ---------------------------------------------------------------------------
# schema validation
# ---------------------------------------------------------------------------

def test_valid_record_passes():
    r = rec(100e6)
    assert r["schema"] == SCHEMA_ID
    assert r["direction"] == "higher_better"  # derived from the /s unit
    assert validate_record(r) == []


def test_missing_provenance_rejected():
    r = rec(100e6)
    del r["provenance"]
    errs = validate_record(r)
    assert any("provenance" in e for e in errs)


def test_bad_fields_rejected():
    r = rec(100e6)
    r["provenance"]["degraded"] = "no"       # not a bool
    r["provenance"]["platform"] = "quantum"  # not a platform
    r["stages"]["pop"]["ev_per_s"] = "fast"  # not a number
    r["value"] = "big"
    errs = "\n".join(validate_record(r))
    for frag in ("degraded", "platform", "ev_per_s", "$.value"):
        assert frag in errs, errs


def test_make_record_refuses_invalid():
    with pytest.raises(ValueError, match="provenance"):
        make_record(config="c", metric="m", unit="ev/s", value=1.0,
                    stages={}, provenance={"git_sha": "x"})


def test_latency_unit_defaults_lower_better():
    r = make_record(config="c", metric="merge_latency", unit="ms",
                    value=1.0, stages={}, provenance=prov())
    assert r["direction"] == "lower_better"


# ---------------------------------------------------------------------------
# ledger append/read
# ---------------------------------------------------------------------------

def test_append_and_read_roundtrip(tmp_path):
    p = str(tmp_path / "PERF.jsonl")
    append_record(rec(1e6), p)
    append_record(rec(2e6), p)
    lr = read_ledger(p)
    assert [r["value"] for r in lr.records] == [1e6, 2e6]
    assert lr.skipped == []


def test_append_refuses_invalid(tmp_path):
    p = str(tmp_path / "PERF.jsonl")
    bad = rec(1e6)
    bad["provenance"]["degraded"] = "maybe"
    with pytest.raises(ValueError, match="refusing to append"):
        append_record(bad, p)
    assert read_ledger(p).records == []


def test_read_tolerates_corrupt_and_truncated_lines(tmp_path):
    p = tmp_path / "PERF.jsonl"
    append_record(rec(1e6), str(p))
    with open(p, "a") as f:
        f.write('{"not": "a record"}\n')
        f.write('{"schema": "ig-tpu/perf-record/v1", "trunc')  # crash tail
    lr = read_ledger(str(p))
    assert len(lr.records) == 1
    assert len(lr.skipped) == 2


def test_append_atomicity_under_concurrency(tmp_path):
    p = str(tmp_path / "PERF.jsonl")
    n_threads, per_thread = 8, 25

    def writer(i):
        for j in range(per_thread):
            append_record(rec(1e6 + i * 1000 + j), p)

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    lr = read_ledger(p)
    assert lr.skipped == []                      # no interleaved bytes
    assert len(lr.records) == n_threads * per_thread


# ---------------------------------------------------------------------------
# comparator: regression gates + provenance rules
# ---------------------------------------------------------------------------

def _history(values, **kw):
    return [rec(v, ts=f"2026-07-{i + 1:02d}T00:00:00+00:00", **kw)
            for i, v in enumerate(values)]


def test_comparator_flags_20pct_regression():
    hist = _history([100e6, 101e6, 99e6, 100.5e6, 99.5e6])
    res = compare_record(rec(80e6), hist)
    assert res.status == "regression"
    assert res.rc == RC_REGRESSION != 0
    assert res.pool_n == 5


def test_comparator_passes_in_band_run():
    hist = _history([100e6, 101e6, 99e6, 100.5e6, 99.5e6])
    res = compare_record(rec(97e6), hist)   # within the 15% floor band
    assert res.status == "ok"
    assert res.rc == 0


def test_degraded_records_never_baseline():
    # 5 honest TPU records at ~100M plus degraded junk at 50M: the
    # baseline must come from the honest pool only
    hist = (_history([100e6, 101e6, 99e6, 100.5e6, 99.5e6])
            + _history([50e6, 51e6], platform="cpu", degraded=True))
    res = compare_record(rec(80e6), hist)
    assert res.status == "regression"       # 80M vs 100M, not vs 50M
    assert res.pool_n == 5


def test_tpu_claim_refused_on_degraded_only_history():
    hist = _history([50e6, 51e6, 52e6], platform="cpu", degraded=True)
    res = compare_record(rec(77.9e6, platform="tpu"), hist)
    assert res.status == "refused"
    assert res.rc == RC_REFUSED != 0
    assert "refusing to baseline" in res.detail


def test_cpu_candidate_baselines_against_cpu_records():
    hist = _history([2.0e6, 2.1e6, 1.9e6], platform="cpu", degraded=False)
    res = compare_record(rec(2.05e6, platform="cpu"), hist)
    assert res.status == "ok"


def test_lower_better_direction_flips_the_gate():
    base = dict(config="m", metric="merge", unit="ms", stages={})
    hist = [make_record(value=v, provenance=prov(),
                        ts=f"2026-07-{i + 1:02d}T00:00:00+00:00", **base)
            for i, v in enumerate([1.0, 1.05, 0.95])]
    cand = make_record(value=2.0, provenance=prov(), **base)
    assert compare_record(cand, hist).status == "regression"
    cand = make_record(value=0.5, provenance=prov(), **base)
    assert compare_record(cand, hist).status == "improved"


def test_compare_ledger_end_to_end(tmp_path):
    p = str(tmp_path / "PERF.jsonl")
    for r in _history([100e6, 101e6, 99e6, 100.5e6]):
        append_record(r, p)
    append_record(rec(79e6, ts="2026-08-01T00:00:00+00:00"), p)
    results = compare_ledger(read_ledger(p).records)
    assert len(results) == 1
    assert results[0].status == "regression"
    assert "REGR" in render_compare(results)


def test_bench_cli_compare_exit_codes(tmp_path):
    from inspektor_gadget_tpu.cli.bench import main as bench_main
    p = str(tmp_path / "PERF.jsonl")
    for r in _history([100e6, 101e6, 99e6, 100.5e6]):
        append_record(r, p)
    append_record(rec(99.5e6, ts="2026-08-01T00:00:00+00:00"), p)
    assert bench_main(["compare", "--ledger", p]) == 0
    append_record(rec(75e6, ts="2026-08-02T00:00:00+00:00"), p)
    assert bench_main(["compare", "--ledger", p]) == RC_REGRESSION


def test_bench_import_and_report(tmp_path, capsys):
    from inspektor_gadget_tpu.cli.bench import main as bench_main
    bench_doc = {
        "n": 4,
        "parsed": {"metric": "sketch_ingest_throughput_e2e",
                   "value": 76359636.5, "unit": "events/sec/chip",
                   "extra": {"platform": "tpu", "degraded": False,
                             "host_plane_ev_per_s": 130455732.5,
                             "device_plane_ev_per_s": 2646607627.7,
                             "merge_ms_p50": 0.08}},
    }
    src = tmp_path / "BENCH_r04.json"
    src.write_text(json.dumps(bench_doc))
    p = str(tmp_path / "PERF.jsonl")
    assert bench_main(["import", str(src), "--ledger", p]) == 0
    # idempotent: the same artifact is not imported twice
    assert bench_main(["import", str(src), "--ledger", p]) == 0
    records = read_ledger(p).records
    assert len(records) == 1
    r = records[0]
    assert r["provenance"]["platform"] == "tpu"
    assert r["stages"]["merge"]["ms_p50"] == 0.08
    assert bench_main(["report", "--ledger", p]) == 0
    out = capsys.readouterr().out
    assert "bench.e2e" in out and "tpu" in out


def test_bench_json_to_record_marks_degraded():
    doc = {"parsed": {"metric": "m", "value": 2062450.8,
                      "unit": "events/sec/chip",
                      "extra": {"platform": "cpu", "degraded": True,
                                "error": {"tpu_probe": "timeout"}}}}
    r = bench_json_to_record(doc, "BENCH_r05.json")
    assert r["provenance"]["degraded"] is True
    assert r["provenance"]["platform"] == "cpu"
    assert "timeout" in r["provenance"]["probe"]["detail"]


def test_render_report_empty_ledger():
    assert "empty" in render_report([])


# ---------------------------------------------------------------------------
# tiny-n harness smoke (tier-1: JAX pinned to CPU by conftest)
# ---------------------------------------------------------------------------

def test_harness_tiny_smoke_classic(tmp_path):
    trace_out = str(tmp_path / "trace.json")
    r = run_harness("tiny", platform="cpu", trace_out=trace_out,
                    pipeline="classic")
    assert validate_record(r) == []
    assert r["value"] > 0
    assert r["provenance"]["platform"] == "cpu"
    assert r["provenance"]["degraded"] is False   # cpu requested ≠ degraded
    assert r["provenance"]["probe"]["outcome"] == "ok"
    # per-stage attribution: every throughput stage present and busy
    for stage in ("pop", "decode", "enrich", "fold32", "h2d",
                  "bundle_update", "merge"):
        assert stage in r["stages"], r["stages"].keys()
        assert r["stages"][stage]["seconds"] >= 0
    assert r["stages"]["bundle_update"]["ev_per_s"] > 0
    assert r["stages"]["merge"]["ms_p50"] >= 0
    assert r["extra"]["pipeline"].startswith("pop(")
    assert "->decode->enrich->fold32" in r["extra"]["pipeline"]
    # harvest runs every harvest_every batches; tiny windows on a slow
    # host may finish under one interval, so presence is conditional but
    # the ledger roundtrip is not
    p = str(tmp_path / "PERF.jsonl")
    append_record(r, p)
    assert read_ledger(p).records[0]["config"] == "harness.tiny"
    # the Chrome-trace attachment is real and span-bearing
    with open(trace_out) as f:
        doc = json.load(f)
    names = {e.get("name") for e in doc["traceEvents"]}
    assert any(str(n).startswith("perf/run/tiny") for n in names)
    assert "perf/pop" in names and "perf/bundle_update" in names


def test_harness_tiny_smoke_fused(tmp_path):
    """The fused (default) pipeline attributes to the NEW stage names —
    pop_folded → h2d_overlap → fused_update — and records which host
    implementation ran in extra.pipeline (ISSUE 10 satellite: the stage
    list must name the fused stages; the series key stays harness.tiny)."""
    r = run_harness("tiny", platform="cpu")
    assert validate_record(r) == []
    assert r["value"] > 0
    for stage in ("pop_folded", "h2d_overlap", "fused_update", "merge"):
        assert stage in r["stages"], r["stages"].keys()
    for gone in ("pop", "decode", "enrich", "fold32", "h2d",
                 "bundle_update"):
        assert gone not in r["stages"]
    assert r["stages"]["fused_update"]["ev_per_s"] > 0
    assert r["extra"]["pipeline"].startswith("pop_folded(")
    assert "->h2d_overlap(" in r["extra"]["pipeline"]
    assert r["extra"]["host_plane_ev_per_s"] > 0
    assert r["config"] == "harness.tiny"  # same ledger series as classic


def test_fused_host_plane_beats_classic(tmp_path):
    """The acceptance comparison (ISSUE 10): the fused host plane
    (pop_folded→h2d_overlap) must beat the classic host stage total
    (pop→decode→enrich→fold32→h2d) on the same config. BOTH arms drive
    the native synthetic source, so the ratio measures the restructure
    (SoA exporter + pinned staging vs struct pop + decode + fold), not
    the generator. The e2e config's production batch shape is the claim's
    regime — tiny batches are fixed-cost-dominated; the threshold is a
    generous floor under the ledgered ~3.5×, so CI noise can't flake it."""
    from inspektor_gadget_tpu.sources.bridge import native_available
    if not native_available():
        pytest.skip("native folded exporter unavailable "
                    "(doctor: native_lib/native_toolchain rows)")
    fused = run_harness("e2e", platform="cpu", seconds=0.4)
    classic = run_harness("e2e", platform="cpu", seconds=0.4,
                          pipeline="classic")
    ratio = (fused["extra"]["host_plane_ev_per_s"]
             / max(classic["extra"]["host_plane_ev_per_s"], 1.0))
    assert ratio > 1.5, (
        f"fused host plane only {ratio:.2f}x classic "
        f"({fused['extra']['host_plane_ev_per_s']:,.0f} vs "
        f"{classic['extra']['host_plane_ev_per_s']:,.0f} ev/s)")


def test_harness_unknown_config():
    with pytest.raises(ValueError, match="unknown harness config"):
        run_harness("nope", platform="cpu")


def test_probe_retry_clamps_zero_attempts(monkeypatch):
    """IG_PLATFORM_PROBE_ATTEMPTS=0 (or attempts=0) must still probe
    once and degrade normally — never skip the loop and crash."""
    from inspektor_gadget_tpu.utils import platform_probe as pp

    calls = []

    def fake_probe():
        calls.append(1)
        return pp.ProbeResult(True, "cpu", "fake", 0.01)

    out = pp.acquire_platform_with_retry(
        "auto", attempts=0, horizon=0.0, probe_fn=fake_probe)
    assert out["platform"] == "cpu"
    assert len(out["attempts"]) == 1
    monkeypatch.setattr(pp, "DEFAULT_PROBE_ATTEMPTS", 0)
    out = pp.acquire_platform_with_retry(
        "auto", horizon=0.0, probe_fn=fake_probe)
    assert len(out["attempts"]) == 1


def test_same_second_records_still_baseline(tmp_path):
    """Two runs appended within the same UTC second (identical ts) are
    distinct records; the earlier one must stay baseline-eligible for
    the later one."""
    ts = "2026-08-03T00:00:00+00:00"
    older = rec(100e6, ts=ts)
    cand = rec(78e6, ts=ts)  # 22% down, same second
    res = compare_record(cand, [older, cand])
    assert res.pool_n == 1
    assert res.status == "regression"
