"""k8sutil KubeClient against a fake apiserver (ref: pkg/k8sutil — the
clientset constructor; here credential resolution + typed REST helpers)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from inspektor_gadget_tpu.utils.k8s import KubeClient, pod_source_from_client

_PODS = {"items": [{
    "metadata": {"name": "ig-agent-a", "namespace": "ig-tpu",
                 "uid": "u1", "labels": {"k8s-app": "ig-tpu-agent"}},
    "spec": {"nodeName": "node-a", "hostNetwork": True,
             "containers": [{"name": "agent", "image": "ig:latest"}]},
    "status": {"containerStatuses": [
        {"name": "agent", "containerID": "containerd://deadbeef1234"}]},
}]}

_NODES = {"items": [{"metadata": {"name": "node-a"}},
                    {"metadata": {"name": "node-b"}}]}

_DS = {"status": {"desiredNumberScheduled": 2, "numberReady": 2}}


class _FakeApi(BaseHTTPRequestHandler):
    requests: list = []

    def do_GET(self):
        _FakeApi.requests.append((self.path, self.headers.get("Authorization")))
        if self.path.startswith("/api/v1/pods"):
            body = _PODS
        elif self.path.startswith("/api/v1/nodes"):
            body = _NODES
        elif "daemonsets" in self.path:
            body = _DS
        else:
            self.send_error(404)
            return
        data = json.dumps(body).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *a):
        pass


@pytest.fixture()
def fake_api():
    server = HTTPServer(("127.0.0.1", 0), _FakeApi)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    _FakeApi.requests.clear()
    yield f"http://127.0.0.1:{server.server_port}"
    server.shutdown()


def test_list_pods_nodes_and_rollout(fake_api):
    client = KubeClient(server=fake_api, token="tok-123")
    assert client.available()
    pods = client.list_pods(node_name="node-a")
    assert pods[0]["metadata"]["name"] == "ig-agent-a"
    assert client.node_names() == ["node-a", "node-b"]
    assert client.daemonset_status("ig-tpu", "ig-tpu-agent") == (2, 2)
    # bearer token attached; node field selector encoded
    path, auth = _FakeApi.requests[0]
    assert auth == "Bearer tok-123"
    assert "fieldSelector=spec.nodeName%3Dnode-a" in path


def test_pod_source_adapter_feeds_informer(fake_api):
    from inspektor_gadget_tpu.containers import (
        ContainerCollection, with_pod_informer,
    )
    client = KubeClient(server=fake_api)
    cc = ContainerCollection()
    cc.initialize(with_pod_informer(pod_source_from_client(client),
                                    interval=30.0))
    try:
        got = cc.get_all()
        assert len(got) == 1
        c = got[0]
        assert (c.pod, c.namespace, c.id) == \
            ("ig-agent-a", "ig-tpu", "deadbeef1234")
    finally:
        cc._pod_informer.stop()


def test_out_of_cluster_unavailable(monkeypatch):
    monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
    client = KubeClient()
    assert not client.available()
