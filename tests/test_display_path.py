"""Vectorized display path: filter pushdown into the batch loop.

Reference contract: the tracer hot loop filters BEFORE building events
(pkg/gadgets/trace/exec/tracer/tracer.go:134-188); here the CLI pushes its
column filters into the gadget (ctx.extra) so non-matching rows are dropped
columnar and never materialize as Python objects. Correctness bar: the
pushed-down path must show exactly the rows the row-wise match_event
baseline shows.
"""

import numpy as np
import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.columns import match_event, parse_filters
from inspektor_gadget_tpu.gadgets import GadgetContext, get
from inspektor_gadget_tpu.runtime.local import LocalRuntime


def _gadget_with_batch(filter_spec: str):
    """One deterministic batch + a gadget with the filters pushed down —
    the same data drives both the columnar and the row-wise path."""
    desc = get("trace", "exec")
    params = desc.params().to_params()
    params.set("source", "pysynthetic")
    params.set("seed", "7")
    cols = desc.columns()
    filters = parse_filters(filter_spec, cols) if filter_spec else []
    extra = {"display_filters": filters, "display_columns": cols}
    ctx = GadgetContext(desc, gadget_params=params, extra=extra)
    g = desc.new_instance(ctx)
    g.source = g._make_source()
    batch = g.source.generate(4096)
    g._current_source = g.source
    return g, batch, filters, cols


def _key(ev):
    return (ev.timestamp, ev.pid, ev.ppid, ev.uid, ev.comm, ev.mountnsid)


@pytest.mark.parametrize("spec", [
    "pid:>2000",          # numeric comparison → columnar
    "uid:!3",             # negated numeric → columnar
    "comm:proc-1",        # short comm → exact u64 word compare
    "comm:proc-11",       # prefix of other comms (proc-110..) — must not over-match
    "pid:>1000,uid:2",    # conjunction
    "comm:~proc-[12]$",   # regex → residual row path
    "",                   # unfiltered
    "pid:>5000000000",    # out of uint32 range → row-path fallback, no crash
    "uid:!-1",            # negative on unsigned → row-path fallback
    # VERDICT Weak #5 / next-round #6: comm-regex and multi-filter
    # pushdown combinations through the 1d display hot path
    "comm:~^proc-4",          # anchored regex, higher match rate
    "comm:~proc-(1|2)0$",     # alternation regex
    "comm:!proc-1",           # negated comm equality
    "comm:~proc-[0-9]$,pid:>1000",   # regex residual + numeric columnar
    "comm:proc-7,uid:!3,pid:>500",   # triple conjunction, mixed kinds
    "pid:>1000,pid:!2048",           # two numeric filters, same column
    "uid:>1,uid:2",                  # range + equality on one column
])
def test_pushdown_matches_rowwise_baseline(spec):
    g, batch, filters, cols = _gadget_with_batch(spec)
    baseline = [e for e in g.decode_rows(batch, range(batch.count))
                if not filters or match_event(e, filters, cols)]
    shown = []
    g.set_event_handler(shown.append)
    g._emit_display_rows(batch)
    assert [_key(e) for e in shown] == [_key(e) for e in baseline]
    if spec != "pid:>5000000000":  # that one legitimately matches nothing
        assert baseline, f"baseline for {spec!r} matched nothing — weak test"


def test_comm_regex_conjunction_keeps_columnar_prefilter():
    """A comm-regex rides the residual row path, but the equality filter
    in the same conjunction must STILL prefilter columnar — the mask may
    keep extra rows for the residual check, never drop a matching one."""
    g, batch, filters, cols = _gadget_with_batch(
        "comm:~proc-[0-9]$,uid:2")
    mask, residual = g._display_batch_mask(batch)
    assert residual, "regex filters must leave a residual row check"
    baseline_keep = [i for i, e in enumerate(
        g.decode_rows(batch, range(batch.count)))
        if match_event(e, filters, cols)]
    kept = set(np.flatnonzero(mask[: batch.count]).tolist())
    assert set(baseline_keep) <= kept
    # and the uid leg did prune something columnar
    assert len(kept) < batch.count


def test_multi_filter_pushdown_sets_applied_flag():
    """A fully-columnar conjunction must mark display_filters_applied so
    the CLI's on_event skips the per-row re-check (the 1d fast path)."""
    g, batch, filters, cols = _gadget_with_batch("pid:>1000,uid:2")
    shown = []
    g.set_event_handler(shown.append)
    g._emit_display_rows(batch)
    assert g.ctx.extra.get("display_filters_applied"), (
        "columnar-only conjunction should not need the row re-check")
    assert shown and all(
        match_event(e, filters, cols) for e in shown)


def test_noncanonical_eq_keeps_row_semantics():
    """'pid:07' string-compares in the row path (no match); the columnar
    path must not silently turn it into a numeric match."""
    g, batch, filters, cols = _gadget_with_batch("pid:07")
    shown = []
    g.set_event_handler(shown.append)
    g._emit_display_rows(batch)
    assert shown == []


def test_long_comm_prefix_needs_residual():
    """An 8+-char comm value can only prefix-test columnar; the residual
    exact check must reject same-prefix longer names."""
    desc = get("trace", "exec")
    ctx = GadgetContext(desc, gadget_params=desc.params().to_params(),
                        extra={"display_filters": parse_filters(
                            "comm:processor-x", desc.columns()),
                            "display_columns": desc.columns()})
    g = desc.new_instance(ctx)
    from inspektor_gadget_tpu.sources.batch import EventBatch
    batch = EventBatch.alloc(4)
    batch.count = 3
    for i, name in enumerate([b"processo", b"processo", b"other\0\0\0"]):
        batch.comm[i, :len(name)] = np.frombuffer(name, dtype=np.uint8)
    mask, residual = g._display_batch_mask(batch)
    # prefix keeps both "processo*" rows; residual must disambiguate
    assert mask.tolist() == [True, True, False]
    assert residual, "8-byte prefix match must keep the exact row check"


def test_none_decodes_skip_residual_filter():
    """Gadgets whose decode_row declines rows (returns None — e.g.
    audit/seccomp's non-denial syscalls) must not feed None into the
    residual match_event when a filter is pushed down."""
    desc = get("audit", "seccomp")
    params = desc.params().to_params()
    params.set("source", "pysynthetic")
    cols = desc.columns()
    extra = {"display_filters": parse_filters("syscall:openat", cols),
             "display_columns": cols}
    ctx = GadgetContext(desc, gadget_params=params, extra=extra)
    g = desc.new_instance(ctx)
    g.source = g._make_source()
    batch = g.source.generate(512)
    g._current_source = g.source
    shown = []
    g.set_event_handler(shown.append)
    g._emit_display_rows(batch)  # must not raise on None rows
    assert all(e is not None for e in shown)


def test_bulk_key_resolution_matches_scalar():
    desc = get("trace", "exec")
    params = desc.params().to_params()
    params.set("source", "pysynthetic")
    ctx = GadgetContext(desc, gadget_params=params, timeout=0.3)
    g = desc.new_instance(ctx)
    g.source = g._make_source()
    batch = g.source.pop() if hasattr(g.source, "pop") else None
    if batch is None or batch.count == 0:
        batch = g.source.generate(100)
    g._current_source = g.source
    keys = batch.cols["key_hash"][:50]
    bulk = g.resolve_keys_bulk(keys)
    scalar = [g.resolve_key(int(k)) for k in keys]
    assert bulk == scalar
