"""Real-capture tests: each gadget triggers a real system action and
asserts the captured event — the reference's kernel-real tracer-test
pattern (pkg/gadgets/trace/exec/tracer/tracer_test.go:35-301: install,
trigger, assert) applied to every formerly-synthetic gadget.
"""

from __future__ import annotations

import os
import socket
import subprocess
import time

import numpy as np
import pytest

from inspektor_gadget_tpu.sources import (
    NativeCapture, native_available, make_cfg,
    SRC_FANOTIFY_OPEN, SRC_MOUNTINFO, SRC_SOCK_DIAG, SRC_KMSG_OOM,
    SRC_PTRACE, SRC_FANOTIFY_RUNC, SRC_PERF_CPU, SRC_SYNTH_EXEC,
)

needs_native = pytest.mark.skipif(not native_available(), reason="no native lib")
needs_root = pytest.mark.skipif(os.geteuid() != 0, reason="needs root")

EV_OPEN, EV_BIND, EV_SIGNAL, EV_MOUNT, EV_OOMKILL = 3, 8, 9, 10, 11
EV_CAPABILITY, EV_FSSLOWER, EV_SYSCALL, EV_PERF, EV_CONTAINER = 12, 13, 18, 19, 20


def drain(src, want, timeout=4.0, kinds=None):
    """Pop until `want(rows) -> bool` is satisfied; returns collected rows
    as (kind, key_hash, aux1, aux2, pid, ppid, mntns, comm) tuples."""
    rows = []
    deadline = time.time() + timeout
    while time.time() < deadline:
        b = src.pop()
        c = b.cols
        for i in range(b.count):
            if kinds is not None and int(c["kind"][i]) not in kinds:
                continue
            rows.append((int(c["kind"][i]), int(c["key_hash"][i]),
                         int(c["aux1"][i]), int(c["aux2"][i]),
                         int(c["pid"][i]), int(c["ppid"][i]),
                         int(c["mntns"][i]), b.comm_str(i)))
        if want(rows):
            return rows
        time.sleep(0.05)
    return rows


# ---------------------------------------------------------------------------
# trace/open — fanotify mount mark sees a real file open with its path
# ---------------------------------------------------------------------------

@needs_native
@needs_root
def test_open_sees_real_file_access():
    src = NativeCapture(SRC_FANOTIFY_OPEN, cfg=make_cfg(paths="/tmp"),
                        ring_pow2=14)
    with src:
        time.sleep(0.3)
        subprocess.run(
            ["sh", "-c", "echo payload > /tmp/ig_open_probe && cat /tmp/ig_open_probe >/dev/null"],
            check=True)
        rows = drain(src, lambda r: any(
            src.vocab_lookup(a1) == "/tmp/ig_open_probe" for _, _, a1, *_ in r),
            kinds={EV_OPEN})
    hits = [r for r in rows if src.vocab_lookup(r[2]) == "/tmp/ig_open_probe"]
    assert hits, "fanotify did not surface the probe file open"
    # the writer (sh) produced a modify bit; the reader (cat) a plain open
    assert any(r[3] & 2 for r in hits) or any(r[3] & 1 for r in hits)
    assert all(r[4] != 0 for r in hits)  # pid attributed


# ---------------------------------------------------------------------------
# trace/mount — mountinfo diff sees a real tmpfs mount + umount
# ---------------------------------------------------------------------------

@needs_native
@needs_root
def test_mount_sees_real_tmpfs_mount():
    os.makedirs("/tmp/ig_mnt_probe", exist_ok=True)
    src = NativeCapture(SRC_MOUNTINFO, ring_pow2=12)
    with src:
        time.sleep(0.3)
        subprocess.run(["mount", "-t", "tmpfs", "ig_probe_fs", "/tmp/ig_mnt_probe"],
                       check=True)
        time.sleep(0.4)
        subprocess.run(["umount", "/tmp/ig_mnt_probe"], check=True)
        rows = drain(src, lambda r: len(r) >= 2, kinds={EV_MOUNT})
    payloads = [(src.vocab_lookup(kh).split("\x1f"), aux2)
                for _, kh, _, aux2, *_ in rows]
    mounts = [(p, a) for p, a in payloads if p[0] == "ig_probe_fs"]
    assert any(a & 1 == 0 for _, a in mounts), "mount event missing"
    assert any(a & 1 == 1 for _, a in mounts), "umount event missing"
    src_name, target, fstype = mounts[0][0]
    assert target == "/tmp/ig_mnt_probe" and fstype == "tmpfs"


# ---------------------------------------------------------------------------
# trace/bind — sock_diag diff sees real TCP listen + UDP bind with pid
# ---------------------------------------------------------------------------

@needs_native
def test_bind_sees_real_listeners():
    src = NativeCapture(SRC_SOCK_DIAG, cfg=make_cfg(interval_ms=30),
                        ring_pow2=12)
    with src:
        time.sleep(0.4)
        tcp = socket.socket()
        tcp.bind(("127.0.0.1", 48712))
        tcp.listen(1)
        udp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        udp.bind(("0.0.0.0", 48713))
        rows = drain(src, lambda r: len({x[3] & 0xFFFF for x in r}
                                        & {48712, 48713}) == 2,
                     kinds={EV_BIND})
        tcp.close()
        udp.close()
    by_port = {r[3] & 0xFFFF: r for r in rows}
    assert 48712 in by_port and 48713 in by_port
    assert (by_port[48712][3] >> 16) & 0xFF == 6    # IPPROTO_TCP
    assert (by_port[48713][3] >> 16) & 0xFF == 17   # IPPROTO_UDP
    assert by_port[48712][4] == os.getpid()         # resolved to this process
    assert by_port[48712][7] == "python"[:7] or by_port[48712][7].startswith("py")


# ---------------------------------------------------------------------------
# trace/oomkill — kmsg parser decodes a real kernel-log OOM record
# (injected through /dev/kmsg so the test does not have to OOM the host;
#  the read path — kmsg stream, record framing, field parse — is the real one)
# ---------------------------------------------------------------------------

@needs_native
@needs_root
def test_oomkill_parses_kmsg_record():
    src = NativeCapture(SRC_KMSG_OOM, ring_pow2=12)
    with src:
        time.sleep(0.3)
        with open("/dev/kmsg", "w") as f:
            f.write("Out of memory: Killed process 31337 (ig_victim) "
                    "total-vm:204800kB, anon-rss:1024kB\n")
        rows = drain(src, lambda r: len(r) >= 1, kinds={EV_OOMKILL})
    assert rows, "kmsg OOM record not captured"
    kind, kh, pages, _aux2, pid, *_ = rows[0]
    assert pid == 31337
    assert src.vocab_lookup(kh) == "ig_victim"
    assert pages == 204800 // 4


# ---------------------------------------------------------------------------
# trace/signal — netlink exit records decode a real fatal signal
# ---------------------------------------------------------------------------

@needs_native
@needs_root
def test_signal_sees_real_fatal_signal():
    from inspektor_gadget_tpu.sources import SRC_PROC_EXEC
    src = NativeCapture(SRC_PROC_EXEC, ring_pow2=16)
    with src:
        time.sleep(0.3)
        # a child that kills itself with SIGUSR1 (fatal by default)
        subprocess.run(["sh", "-c", "kill -USR1 $$"], check=False)
        rows = drain(src, lambda r: any(x[3] == 10 for x in r),
                     kinds={EV_SIGNAL})
    fatal = [r for r in rows if r[3] == 10]
    assert fatal, "fatal SIGUSR1 not decoded from exit record"
    assert fatal[0][2] == 1  # origin: fatal


# ---------------------------------------------------------------------------
# ptrace stream — syscalls, signals (both sides), capabilities, fsslower
# ---------------------------------------------------------------------------

@needs_native
@needs_root
def test_ptrace_decodes_real_syscalls():
    src = NativeCapture(SRC_PTRACE, ring_pow2=16, cfg=make_cfg(
        cmd=["sh", "-c", "cat /etc/hostname >/dev/null"]))
    with src:
        rows = drain(src, lambda r: src.ptrace_exit_status() >= 0
                     and len(r) > 20, kinds={EV_SYSCALL}, timeout=6.0)
    lines = [src.vocab_lookup(kh) for _, kh, *_ in rows]
    execves = [l for l in lines if l.startswith("execve(")]
    opens = [l for l in lines if "/etc/hostname" in l]
    # sh's resolved path varies by host ($PATH walk: /bin/sh, /usr/bin/sh…)
    # — assert a successful execve of *some* sh, not a fixed location
    assert any(('/sh"' in l or '"sh"' in l) and l.endswith("= 0")
               for l in execves), execves
    assert any(l.startswith("openat(") and l.endswith("= 3") for l in opens), opens
    # nr/ret packed in aux2: every execve that succeeded has ret 0
    exec_rows = [r for r in rows if src.vocab_lookup(r[1]).startswith("execve(")
                 and src.vocab_lookup(r[1]).endswith("= 0")]
    assert all((r[3] & 0xFFFFFFFF) == 0 for r in exec_rows)


@needs_native
@needs_root
def test_ptrace_derives_capability_and_signal_events():
    open("/tmp/ig_cap_probe", "w").write("x")
    src = NativeCapture(SRC_PTRACE, ring_pow2=16, cfg=make_cfg(
        cmd=["sh", "-c", "chown 0:0 /tmp/ig_cap_probe; kill -TERM $$"]))
    with src:
        rows = drain(src, lambda r: src.ptrace_exit_status() >= 0,
                     kinds={EV_CAPABILITY, EV_SIGNAL}, timeout=6.0)
    caps = [r for r in rows if r[0] == EV_CAPABILITY]
    sigs = [r for r in rows if r[0] == EV_SIGNAL]
    assert any(r[3] == 0 and r[2] == 1 for r in caps), "CAP_CHOWN allow missing"
    assert any(r[3] == 5 for r in caps), "CAP_KILL missing"
    # sender (aux1=2) and delivery (aux1=0) sides of SIGTERM(15)
    assert any(r[3] == 15 and r[2] == 2 for r in sigs), "sender side missing"
    assert any(r[3] == 15 and r[2] == 0 for r in sigs), "delivery stop missing"


@needs_native
@needs_root
def test_ptrace_fsslower_measures_real_latency():
    src = NativeCapture(SRC_PTRACE, ring_pow2=16, cfg=make_cfg(
        cmd=["sh", "-c", "cat /etc/hostname >/dev/null"], min_lat_us=0))
    with src:
        rows = drain(src, lambda r: src.ptrace_exit_status() >= 0,
                     kinds={EV_FSSLOWER}, timeout=6.0)
    opens = [r for r in rows if (r[3] >> 32) == 3
             and src.vocab_lookup(r[1]) == "/etc/hostname"]
    assert opens, "open of /etc/hostname not measured"
    assert all(r[2] > 0 for r in opens)  # nonzero latency_us


# ---------------------------------------------------------------------------
# gadget-level: end-to-end through the framework with real capture
# ---------------------------------------------------------------------------

def _run_gadget(category, name, flags, trigger=None, timeout=4.0):
    """Run a gadget through the full framework (LocalRuntime + operators)
    while a trigger performs the real system action."""
    import threading
    import inspektor_gadget_tpu.all_gadgets  # noqa: F401
    from inspektor_gadget_tpu.gadgets import GadgetContext, get
    from inspektor_gadget_tpu.runtime import LocalRuntime

    desc = get(category, name)
    params = desc.params().to_params()
    for k, v in flags.items():
        params.set(k, str(v))
    ctx = GadgetContext(desc, gadget_params=params, timeout=timeout)
    events = []
    box = {}

    def _run():
        box["result"] = LocalRuntime().run_gadget(ctx, on_event=events.append)

    th = threading.Thread(target=_run)
    th.start()
    try:
        time.sleep(0.6)
        if trigger:
            trigger()
    finally:
        th.join(timeout + 6)
        ctx.cancel()
        th.join(4)
    result = box.get("result")
    if result is not None:
        assert not result.errors(), result.errors()
    return result, events


@needs_native
@needs_root
def test_trace_open_gadget_real_end_to_end():
    def trigger():
        # repeat the open until the run window closes: under load the
        # capture source may start after the first write, and fanotify
        # only reports opens that happen while the mark is live
        for _ in range(8):
            subprocess.run(["sh", "-c", "date > /tmp/ig_g_open"], check=True)
            time.sleep(0.3)
    _, events = _run_gadget("trace", "open", {"source": "native",
                                              "paths": "/tmp"},
                            trigger, timeout=3.0)
    assert any(e.path == "/tmp/ig_g_open" for e in events)
    hit = next(e for e in events if e.path == "/tmp/ig_g_open")
    assert hit.pid > 0 and hit.comm != ""


@needs_native
@needs_root
def test_trace_bind_gadget_real_end_to_end():
    sock = {}
    def trigger():
        s = socket.socket()
        s.bind(("127.0.0.1", 48714))
        s.listen(1)
        sock["s"] = s
    _, events = _run_gadget("trace", "bind", {"source": "native"},
                            trigger, timeout=3.0)
    if "s" in sock:
        sock["s"].close()
    hits = [e for e in events if e.port == 48714]
    assert hits and hits[0].protocol == "tcp"
    assert hits[0].pid == os.getpid()


@needs_native
@needs_root
def test_trace_capabilities_gadget_real_end_to_end():
    open("/tmp/ig_g_cap", "w").write("x")
    _, events = _run_gadget(
        "trace", "capabilities",
        {"source": "native", "command": "chown 0:0 /tmp/ig_g_cap"},
        timeout=5.0)
    assert any(e.cap == "CHOWN" and e.verdict == "allow" for e in events)


@needs_native
@needs_root
def test_trace_fsslower_gadget_real_end_to_end():
    _, events = _run_gadget(
        "trace", "fsslower",
        {"source": "native", "command": "cat /etc/hostname",
         "min-latency": "0"},
        timeout=5.0)
    assert any(e.file == "/etc/hostname" and e.op == "open" for e in events)


@needs_native
@needs_root
def test_traceloop_real_syscall_history():
    import inspektor_gadget_tpu.all_gadgets  # noqa: F401
    from inspektor_gadget_tpu.gadgets import GadgetContext, get
    desc = get("traceloop", "traceloop")
    params = desc.params().to_params()
    params.set("source", "native")
    params.set("command", "cat /etc/hostname")
    ctx = GadgetContext(desc, gadget_params=params, timeout=6.0)
    g = desc.new_instance(ctx)
    g.run(ctx)
    records = g.read()
    names = {r.syscall for r in records}
    assert "execve" in names and "openat" in names
    opens = [r for r in records if r.syscall == "openat"
             and "/etc/hostname" in r.args]
    assert opens and opens[0].ret == 3
    assert all(r.pid > 0 for r in records)


@needs_native
@needs_root
def test_advise_seccomp_profile_exact_syscall_set():
    import inspektor_gadget_tpu.all_gadgets  # noqa: F401
    from inspektor_gadget_tpu.gadgets import GadgetContext, get
    import json
    desc = get("advise", "seccomp-profile")
    params = desc.params().to_params()
    params.set("source", "native")
    params.set("command", "cat /etc/hostname")
    ctx = GadgetContext(desc, gadget_params=params, timeout=6.0)
    g = desc.new_instance(ctx)
    out = g.run_with_result(ctx)
    profiles = json.loads(out.decode())
    assert profiles, "no profile generated"
    prof = next(iter(profiles.values()))
    names = set(prof["syscalls"][0]["names"])
    # the syscalls cat actually made (beyond the baseline set)
    for expected in ("execve", "openat", "read", "close"):
        assert expected in names
    # and nothing fabricated: a syscall cat never makes must be absent
    assert "reboot" not in names and "swapon" not in names


@needs_native
@needs_root
def test_audit_seccomp_sees_real_denial():
    # A child that drops to uid 1 then chowns a root-owned file: the kernel
    # denies with EPERM — exactly the ERRNO outcome audit/seccomp reports.
    open("/tmp/ig_audit_probe", "w").write("x")
    os.chown("/tmp/ig_audit_probe", 0, 0)
    # -S skips site processing: this image's sitecustomize boots a TPU
    # backend at interpreter start, which is slow under load (and hangs
    # outright when the device tunnel is down) — the probe only needs os
    cmd = ("python -S -c \"import os; os.setuid(1); "
           "os.chown('/tmp/ig_audit_probe', 1, 1)\"")
    _, events = _run_gadget("audit", "seccomp",
                            {"source": "native", "command": cmd},
                            timeout=8.0)
    denied = [e for e in events if e is not None and e.code == "ERRNO"]
    assert any(e.syscall in ("chown", "fchownat") for e in denied), \
        [f"{e.syscall}:{e.code}" for e in events if e is not None]


@needs_native
@needs_root
def test_captrace_source_sees_allows_and_denies():
    """The cap_capable tracepoint window directly: it must observe BOTH
    allowed and denied checks (the property the audit EPERM flavour
    lacks). A root chown exercises CAP_CHOWN allowed; an unprivileged one
    is denied."""
    from inspektor_gadget_tpu.sources.bridge import (
        NativeCapture, SRC_CAP_TRACE, captrace_supported,
    )
    if not captrace_supported():
        pytest.skip("cap_capable tracepoint unavailable")
    target = "/tmp/ig_captrace_probe"
    open(target, "w").close()
    src = NativeCapture(SRC_CAP_TRACE, ring_pow2=18, batch_size=8192)
    src.start()
    try:
        time.sleep(0.5)  # instance + enable
        allows, denies = [], []
        deadline = time.monotonic() + 6.0
        flip = [0]
        while time.monotonic() < deadline and not (allows and denies):
            # root: a REAL ownership change each time (chown to the current
            # owner short-circuits before the capability check)
            flip[0] ^= 1
            os.chown(target, 65534 * flip[0], 65534 * flip[0])
            subprocess.run(
                ["setpriv", "--reuid", "65534", "--clear-groups",
                 "chown", "0:0", target],
                check=False, stderr=subprocess.DEVNULL)  # denied
            time.sleep(0.3)
            b = src.pop()
            c = b.cols
            for i in range(b.count):
                if int(c["kind"][i]) != 12 or int(c["aux2"][i]) != 0:
                    continue  # EV_CAPABILITY, CAP_CHOWN only
                (allows if int(c["aux1"][i]) else denies).append(
                    (int(c["pid"][i]), b.comm_str(i)))
        assert allows, "no allowed CAP_CHOWN check observed"
        assert denies, "no denied CAP_CHOWN check observed"
        assert all(pid > 0 and comm for pid, comm in allows + denies)
    finally:
        src.stop()
        src.close()
        os.unlink(target)


@needs_native
@needs_root
def test_audit_source_eperm_rules_capability_denial():
    """The NETLINK_AUDIT flavour directly (the gadget prefers the
    cap_capable tracepoint when available, so this window needs its own
    coverage): EPERM exit rules surface an unprivileged chown as a
    capability denial, and rules + audit state are restored at close."""
    from inspektor_gadget_tpu.sources.bridge import (
        NativeCapture, SRC_AUDIT, audit_supported, make_cfg,
    )
    if not audit_supported():
        pytest.skip("audit window unavailable")
    target = "/tmp/ig_auditsrc_probe"
    open(target, "w").close()
    src = NativeCapture(SRC_AUDIT, ring_pow2=16, batch_size=4096,
                        cfg=make_cfg(eperm_rules=1))
    src.start()
    try:
        time.sleep(0.8)  # rule install
        deadline = time.monotonic() + 6.0
        denials = []
        while time.monotonic() < deadline and not denials:
            subprocess.run(
                ["setpriv", "--reuid", "65534", "--clear-groups",
                 "chown", "0:0", target],
                check=False, stderr=subprocess.DEVNULL)
            time.sleep(0.3)
            b = src.pop()
            c = b.cols
            for i in range(b.count):
                if (int(c["kind"][i]) == 12       # EV_CAPABILITY
                        and int(c["aux1"][i]) == 0  # deny
                        and int(c["aux2"][i]) == 0):  # CAP_CHOWN
                    denials.append((int(c["pid"][i]), int(c["uid"][i])))
        assert denials, "no CAP_CHOWN denial from the audit window"
        assert all(uid == 65534 for _pid, uid in denials)
    finally:
        src.stop()
        src.close()
        os.unlink(target)


@needs_native
@needs_root
def test_profile_cpu_perf_sampler_real_samples():
    import inspektor_gadget_tpu.all_gadgets  # noqa: F401
    from inspektor_gadget_tpu.gadgets import GadgetContext, get
    import threading
    spin = subprocess.Popen(
        ["python", "-S", "-c",
         "import time,sys\nt=time.time()\nwhile time.time()-t<6: pass"])
    try:
        desc = get("profile", "cpu")
        params = desc.params().to_params()
        params.set("sampler", "perf")
        params.set("profile-output", "folded")
        params.set("pid", str(spin.pid))
        ctx = GadgetContext(desc, gadget_params=params, timeout=2.5)
        g = desc.new_instance(ctx)
        timer = threading.Timer(2.5, ctx.cancel)
        timer.start()
        out = g.run_with_result(ctx).decode()
        timer.cancel()
    finally:
        spin.kill()
        spin.wait()
    lines = [l for l in out.splitlines() if l.strip()]
    assert lines, "no perf samples for a spinning child"
    total = sum(int(l.rsplit(" ", 1)[1]) for l in lines)
    # 49 Hz over ~2.5s on the spinning pid → expect a healthy fraction
    assert total >= 20, f"only {total} samples"
    assert any(l.startswith("python;") for l in lines)


@needs_native
@needs_root
def test_capture_side_filter_counts_and_blocks():
    """The C++ mntns filter drops events before the ring and accounts them
    (tracer-collection mntnsset contract)."""
    src = NativeCapture(SRC_SYNTH_EXEC, seed=5, rate=200_000, vocab=100,
                        ring_pow2=16)
    # synthetic events use mntns 4026531840+idx%64; allow exactly one
    allowed = {4026531840 + 7}
    src.set_filter(allowed)
    src.start()
    time.sleep(0.4)
    src.stop()
    popped = 0
    bad = 0
    while True:
        b = src.pop()
        if b.count == 0:
            break
        popped += b.count
        bad += int((~np.isin(b.cols["mntns"][:b.count],
                             np.fromiter(allowed, np.uint64))).sum())
    filtered = src.filtered()
    src.close()
    assert bad == 0, "filtered event leaked into the ring"
    assert popped > 0, "allowed mntns never captured"
    assert filtered > popped, "filtered accounting missing"
