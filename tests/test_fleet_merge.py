"""ISSUE 20 tentpole acceptance: the tree-merged fleet summary is
byte-identical to the flat client-side fold — same frame bytes, not
just same digest — for every plane combination, at any fan-in, through
the client-driven tier AND the server-side aggregator tier, under
partition, refusal, approx taint, and crash-mid-fold refolds (which
must never double-count a leaf)."""

from __future__ import annotations

import random

import pytest

from inspektor_gadget_tpu.fleet import (
    canonical_order,
    flat_summary,
    fold_tree,
)
from inspektor_gadget_tpu.fleet.sim import GADGET, SimAgent, SimFleet
from inspektor_gadget_tpu.history import encode_window, pack_frames


def frame(win) -> bytes:
    return pack_frames([encode_window(win)])


PLANES = [
    pytest.param({}, id="base"),
    pytest.param({"inv": True}, id="inv"),
    pytest.param({"qt": True}, id="qt"),
    pytest.param({"rs": True}, id="rs"),
    pytest.param({"inv": True, "qt": True, "rs": True}, id="all"),
    pytest.param({"inv": True, "qt": True, "rs": True, "approx": True},
                 id="all+approx"),
]


# ---------------------------------------------------------------------------
# the identity matrix: fan-in × planes × tier
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fan_in", [2, 4, 8])
@pytest.mark.parametrize("planes", PLANES)
def test_tree_fold_byte_identical_to_flat(fan_in, planes):
    # 9 agents: every fan-in here produces a remainder chunk somewhere,
    # so promotion (the shape that once permuted the label-map order)
    # is always part of the matrix
    fleet = SimFleet(9, n_windows=2, **planes)
    topo = fleet.topology(f"auto:{fan_in}")
    flat = fleet.flat_reference()

    tf = fold_tree(topo, fleet.fetch_leaf, gadget=GADGET)
    assert frame(tf.window) == frame(flat)
    assert tf.window.digest == flat.digest
    assert tf.levels == {0: 18}
    assert tf.errors == {} and tf.fallback == []
    assert all(p == "tree" for p in tf.paths.values())

    # the server-side aggregator tier (one fetch_subtree hop per zone)
    # seals the same bytes
    tf2 = fold_tree(topo, fleet.fetch_leaf,
                    fetch_subtree=fleet.make_fetch_subtree(),
                    gadget=GADGET)
    assert frame(tf2.window) == frame(flat)
    assert tf2.subtree_folds >= 1


def test_declared_zone_shuffled_child_order_same_digest():
    """Zone members listed in any order still seal the same digest —
    the merge algebra is commutative on every digest-covered plane."""
    fleet = SimFleet(8, n_windows=1, inv=True, qt=True, rs=True)
    flat = fleet.flat_reference()
    rng = random.Random(3)
    for _ in range(4):
        members = [fleet.nodes()[:4], fleet.nodes()[4:]]
        for m in members:
            rng.shuffle(m)
        spec = (f"za={','.join(members[0])};"
                f"zb={','.join(members[1])}")
        tf = fold_tree(fleet.topology(spec), fleet.fetch_leaf,
                       gadget=GADGET)
        assert tf.window.digest == flat.digest


def test_declared_contiguous_zones_full_byte_identity():
    # contiguous zones in roster order preserve canonical leaf order,
    # so even the digest-exempt label map matches byte-for-byte
    fleet = SimFleet(8, n_windows=1, inv=True)
    spec = ("za=n000,n001,n002;zb=n003,n004,n005;zc=n006,n007")
    tf = fold_tree(fleet.topology(spec), fleet.fetch_leaf, gadget=GADGET)
    assert frame(tf.window) == frame(fleet.flat_reference())


# ---------------------------------------------------------------------------
# determinism pin (satellite b): the flat fold itself
# ---------------------------------------------------------------------------

def test_flat_fold_identical_bytes_regardless_of_reply_order():
    fleet = SimFleet(12, n_windows=2, inv=True, qt=True)
    summaries = [fleet.agents[n].summary()["window"]
                 for n in fleet.nodes()]
    anchor = frame(flat_summary(summaries, gadget=GADGET))
    rng = random.Random(11)
    for _ in range(5):
        shuffled = summaries[:]
        rng.shuffle(shuffled)
        assert frame(flat_summary(shuffled, gadget=GADGET)) == anchor


def test_canonical_order_is_pure_function_of_window_set():
    fleet = SimFleet(6, n_windows=2)
    ws = fleet.reachable_windows()
    shuffled = ws[:]
    random.Random(5).shuffle(shuffled)
    assert [w.digest for w in canonical_order(shuffled)] == \
        [w.digest for w in ws]


# ---------------------------------------------------------------------------
# partition / churn accounting
# ---------------------------------------------------------------------------

def test_partitioned_leaves_become_error_rows_not_poison():
    fleet = SimFleet(16, n_windows=1, inv=True)
    fleet.partition("n003", "n007", "n012")
    topo = fleet.topology("auto:4")
    tf = fold_tree(topo, fleet.fetch_leaf, gadget=GADGET)
    # identical to the flat fold over the REACHABLE set
    assert frame(tf.window) == frame(fleet.flat_reference())
    assert sorted(tf.errors) == ["n003", "n007", "n012"]
    assert all("unreachable" in e or "partition" in e
               for e in tf.errors.values())
    assert all(tf.paths[n] == "unreachable"
               for n in ("n003", "n007", "n012"))
    assert tf.levels == {0: 13}
    # heal and refold: the healed fleet answers whole again
    fleet.heal()
    tf2 = fold_tree(topo, fleet.fetch_leaf, gadget=GADGET)
    assert tf2.errors == {}
    assert frame(tf2.window) == frame(fleet.flat_reference())


def test_whole_fleet_partitioned_yields_no_window():
    fleet = SimFleet(4, n_windows=1)
    fleet.partition(*fleet.nodes())
    tf = fold_tree(fleet.topology("auto"), fleet.fetch_leaf,
                   gadget=GADGET)
    assert tf.window is None
    assert len(tf.errors) == 4
    assert tf.aggregate["digest"] == ""
    assert tf.aggregate["missing"] == sorted(fleet.nodes())


# ---------------------------------------------------------------------------
# refusal propagation through the tiers
# ---------------------------------------------------------------------------

def test_geometry_mismatch_skipped_with_note_both_paths():
    fleet = SimFleet(8, n_windows=1, inv=True)
    odd = fleet.nodes()[5]
    a = fleet.agents[odd]
    fleet.agents[odd] = SimAgent(odd, a.seed, n_windows=1, inv=True,
                                 width=32)  # disagreeing CMS geometry
    topo = fleet.topology("auto:4")
    tf = fold_tree(topo, fleet.fetch_leaf, gadget=GADGET)
    flat = fleet.flat_reference()
    assert frame(tf.window) == frame(flat)
    # the refusal surfaced, naming the odd window, in the tree's
    # accounting — answer_query renders tf.dropped as dropped_windows
    assert any(odd in note for note in tf.dropped)
    # and through the server-side tier
    tf2 = fold_tree(topo, fleet.fetch_leaf,
                    fetch_subtree=fleet.make_fetch_subtree(),
                    gadget=GADGET)
    assert tf2.window.digest == flat.digest
    assert any(odd in note for note in tf2.dropped)


def test_partial_plane_coverage_drops_plane_with_note():
    # half the fleet seals the invertible plane, half doesn't: total-
    # coverage refusal drops it everywhere, with the note propagated
    fleet = SimFleet(8, n_windows=1, inv=True, qt=True)
    for n in fleet.nodes()[4:]:
        a = fleet.agents[n]
        fleet.agents[n] = SimAgent(n, a.seed, n_windows=1, qt=True)
    topo = fleet.topology("auto:4")
    tf = fold_tree(topo, fleet.fetch_leaf, gadget=GADGET)
    flat = fleet.flat_reference()
    assert tf.window.digest == flat.digest
    assert tf.window.inv_count is None and flat.inv_count is None
    assert tf.window.qt_counts is not None  # covered plane survives
    assert any("invertible" in note for note in tf.dropped)
    assert any("invertible" in note for note in tf.aggregate["skipped"])


def test_approx_taint_from_one_agent_ors_through_the_tree():
    fleet = SimFleet(8, n_windows=1)
    tainted = fleet.nodes()[6]
    a = fleet.agents[tainted]
    fleet.agents[tainted] = SimAgent(tainted, a.seed, n_windows=1,
                                     approx=True)
    tf = fold_tree(fleet.topology("auto:4"), fleet.fetch_leaf,
                   gadget=GADGET)
    flat = fleet.flat_reference()
    assert tf.window.approx and flat.approx
    assert frame(tf.window) == frame(flat)
    assert tf.aggregate["approx"] is True


# ---------------------------------------------------------------------------
# crash mid-fold: refold answers the same bytes, no double-count
# ---------------------------------------------------------------------------

def test_client_fold_crash_refolds_flat_without_double_count(monkeypatch):
    from inspektor_gadget_tpu.fleet import aggregator as agg_mod
    fleet = SimFleet(16, n_windows=1, inv=True)
    topo = fleet.topology("auto:4")
    flat = fleet.flat_reference()

    real = agg_mod.merged_to_sealed
    crashed = []

    def crash_once(merged, *, gadget, node):
        if node == "agg1-001" and not crashed:
            crashed.append(node)
            raise RuntimeError("injected seal crash")
        return real(merged, gadget=gadget, node=node)

    monkeypatch.setattr(agg_mod, "merged_to_sealed", crash_once)
    tf = fold_tree(topo, fleet.fetch_leaf, gadget=GADGET)
    assert crashed == ["agg1-001"]
    assert tf.fallback == ["agg1-001"]
    assert any("crashed" in note for note in tf.dropped)
    # the answer is unchanged — the subtree re-folded flat from the
    # leaves' CACHED summaries
    assert frame(tf.window) == frame(flat)
    # exactly-once: one fetch per leaf for the whole query, crash
    # refold included
    assert sorted(fleet.fetches) == fleet.nodes()
    assert all(v == 1 for v in fleet.fetches.values())
    assert tf.levels == {0: 16}
    # the crashed zone's leaves answered via the fallback path
    assert [n for n, p in sorted(tf.paths.items())
            if p == "flat-fallback"] == ["n004", "n005", "n006", "n007"]


def test_remote_aggregator_unreachable_falls_back_exactly_once():
    fleet = SimFleet(16, n_windows=2, inv=True, qt=True)
    topo = fleet.topology("auto:4")
    flat = fleet.flat_reference()
    # the sim's server-side tier is recursive, so a failed mid-tree
    # aggregator surfaces at the root hop: the whole tree re-folds flat
    tf = fold_tree(
        topo, fleet.fetch_leaf,
        fetch_subtree=fleet.make_fetch_subtree(fail={"agg1-001"}),
        gadget=GADGET)
    assert tf.fallback  # some subtree answered flat
    assert frame(tf.window) == frame(flat)
    # exactly-once accounting across remote replies + client refolds:
    # every (leaf, window) counted once — 16 agents × 2 windows
    assert tf.levels == {0: 32}


def test_root_aggregator_down_means_whole_tree_flat_fallback():
    fleet = SimFleet(8, n_windows=1)
    topo = fleet.topology("auto:4")
    tf = fold_tree(topo, fleet.fetch_leaf,
                   fetch_subtree=fleet.make_fetch_subtree(fail={"fleet"}),
                   gadget=GADGET)
    assert tf.fallback == ["fleet"]
    assert tf.subtree_folds == 0
    assert all(p == "flat-fallback" for p in tf.paths.values())
    assert frame(tf.window) == frame(fleet.flat_reference())


# ---------------------------------------------------------------------------
# the root aggregate header matches the wire contract
# ---------------------------------------------------------------------------

def test_root_aggregate_carries_wire_schema_fields():
    from inspektor_gadget_tpu.agent import wire
    fleet = SimFleet(6, n_windows=1)
    fleet.partition("n002")
    tf = fold_tree(fleet.topology("auto:4"), fleet.fetch_leaf,
                   gadget=GADGET)
    assert set(tf.aggregate) == set(wire.FLEET_AGGREGATE_FIELDS)
    assert tf.aggregate["schema"] == wire.FLEET_AGGREGATE_SCHEMA
    assert tf.aggregate["aggregator"] == "fleet"
    assert tf.aggregate["missing"] == ["n002"]
    assert tf.aggregate["digest"] == tf.window.digest
    assert tf.aggregate["folded"] == 5
