"""Multi-chip sharded ingest (ISSUE 14): parity, validation, harness.

The tentpole contract under test: one fused SketchBundle replica per
device lane, batches round-robined onto per-chip pinned rings, psum/pmax
collective merge at harvest ONLY — and the harvested bundle is
BIT-IDENTICAL to the single-chip fold of the same event stream, so
`window_digest`, history sealing, alerts, and replay `--verify` ride
unchanged. The 8-device topology comes from tests/conftest.py
(`--xla_force_host_platform_device_count=8` on CPU).

Candidate-exactness note: the top-k parity cases keep the key vocabulary
under the candidate-table size k, where the streaming candidate set is
exactly the distinct-key set on every path. Above k the table is a
documented approximation on ALL paths (single-chip included) and the
union-at-harvest can only widen the candidate pool.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.gadgets import GadgetContext, get
from inspektor_gadget_tpu.operators.operators import get as get_op
from inspektor_gadget_tpu.ops.sketches import (
    SketchBundle,
    bundle_ingest_jit,
    bundle_init,
    bundle_stack_sharded,
    make_bundle_harvest_sharded,
    make_bundle_ingest_sharded,
)
from inspektor_gadget_tpu.params import ParamError
from inspektor_gadget_tpu.parallel.mesh import NODE_AXIS, ingest_mesh
from inspektor_gadget_tpu.sources.synthetic import PySyntheticSource

KW = dict(depth=3, log2_width=9, hll_p=7, entropy_log2_width=6, k=64)
BATCH = 512


@pytest.fixture(autouse=True)
def _release_instances():
    """Instances built outside a real gadget run never see
    post_gadget_run — drop them from the live table (checkpoint_all
    iterates it) and drain their stagers (the h2d inflight gauge) so no
    state leaks into other test files."""
    from inspektor_gadget_tpu.operators import tpusketch
    before = set(tpusketch._live)
    yield
    with tpusketch._live_mu:
        fresh = [rid for rid in list(tpusketch._live) if rid not in before]
        insts = [tpusketch._live.pop(rid) for rid in fresh]
    for inst in insts:
        if getattr(inst, "_stager", None) is not None:
            inst._stager.drain()
        for st in getattr(inst, "_lane_stagers", []):
            st.drain()
        inst._stats.unregister()
        inst._pstats.unregister()


def _assert_bundles_bit_identical(a: SketchBundle, b: SketchBundle,
                                  ctx: str = "") -> None:
    for name, xa, xb in (
        ("cms.table", a.cms.table, b.cms.table),
        ("cms.total", a.cms.total, b.cms.total),
        ("hll.registers", a.hll.registers, b.hll.registers),
        ("entropy.counts", a.entropy.counts, b.entropy.counts),
        ("topk.keys", a.topk.keys, b.topk.keys),
        ("topk.counts", a.topk.counts, b.topk.counts),
        ("events", a.events, b.events),
        ("drops", a.drops, b.drops),
    ):
        assert np.array_equal(np.asarray(xa), np.asarray(xb)), \
            f"{ctx}: leaf {name} diverged"


def _mixed_batches(rng, count: int = 13) -> list[tuple]:
    """(keys, weights, drops) triples with ragged tails (mask shapes the
    single-chip path compiles anyway) and a vocab < k for candidate
    exactness."""
    out = []
    for i in range(count):
        n = BATCH if i % 3 else 300 + i
        keys = np.zeros(BATCH, np.uint32)
        keys[:n] = rng.integers(1, 50, n)
        w = np.zeros(BATCH, np.uint32)
        w[:n] = 1
        out.append((keys, w, float(i % 2)))
    return out


def _fold_reference(batches) -> SketchBundle:
    ref = bundle_init(**KW)
    tok = None
    for k_np, w_np, dr in batches:
        ref, tok = bundle_ingest_jit(ref, jnp.asarray(k_np),
                                     jnp.asarray(k_np), jnp.asarray(k_np),
                                     jnp.asarray(w_np), jnp.float32(dr))
    if tok is not None:
        jax.block_until_ready(tok)
    return ref


def _sharded_fold(batches, chips: int, harvest_mid: int | None = None):
    """Round-robin `batches` over a `chips`-lane mesh; returns the final
    harvested bundle (plus the mid-run harvest when asked). Tail rounds
    pad empty lanes with zero-weight fillers, exactly like the operator's
    flush."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = ingest_mesh(chips)
    like = bundle_init(**KW)
    stacked = bundle_stack_sharded(bundle_init(**KW), mesh)
    step = make_bundle_ingest_sharded(mesh, like)
    harvest = make_bundle_harvest_sharded(mesh, like)
    sh = NamedSharding(mesh, P(NODE_AXIS))
    mid = None
    i = 0
    while i < len(batches):
        round_b = list(batches[i:i + chips])
        while len(round_b) < chips:
            round_b.append((np.zeros(BATCH, np.uint32),
                            np.zeros(BATCH, np.uint32), 0.0))
        keys = jax.device_put(np.stack([b[0] for b in round_b]), sh)
        wts = jax.device_put(np.stack([b[1] for b in round_b]), sh)
        drs = jax.device_put(np.asarray([b[2] for b in round_b],
                                        np.float32), sh)
        stacked, tok = step(stacked, keys, keys, keys, wts, drs)
        i += chips
        if harvest_mid is not None and mid is None and i >= harvest_mid:
            # mid-run collective harvest: reads the live lane bundles
            # (never donates) while ingest continues after it
            mid = harvest(stacked)
    jax.block_until_ready(tok)
    return harvest(stacked), mid


def test_sharded_harvest_bit_identical_across_1_2_4_8():
    """THE acceptance anchor: every SketchBundle leaf of the collective
    harvest equals the single-chip fold across 1/2/4/8 lanes, over a
    stream with ragged tails, per-batch drops, and uneven final rounds —
    and a mid-run harvest matches the single-chip fold of the same
    prefix."""
    rng = np.random.default_rng(7)
    batches = _mixed_batches(rng)
    ref_all = _fold_reference(batches)
    for chips in (1, 2, 4, 8):
        prefix = ((len(batches) // chips) // 2) * chips or chips
        got, mid = _sharded_fold(batches, chips, harvest_mid=prefix)
        _assert_bundles_bit_identical(ref_all, got, ctx=f"chips={chips}")
        ref_prefix = _fold_reference(batches[:prefix])
        _assert_bundles_bit_identical(ref_prefix, mid,
                                      ctx=f"chips={chips} mid-run")


def test_sharded_window_digest_identical_across_device_counts():
    """History-plane determinism (ISSUE 14 satellite): a window sealed
    from the harvested state carries the SAME state-only content digest
    at every device count — replay `--verify` and byte-identical reseal
    cannot hold otherwise."""
    from inspektor_gadget_tpu.history import window_digest
    from inspektor_gadget_tpu.history.window import SealedWindow

    rng = np.random.default_rng(24)
    batches = _mixed_batches(rng, count=9)

    def seal(b: SketchBundle) -> str:
        return window_digest(SealedWindow(
            gadget="trace/parity", node="n0", run_id="r", window=1,
            start_ts=1.0, end_ts=2.0, events=int(b.events), drops=0,
            cms=np.asarray(b.cms.table, dtype=np.int32),
            hll=np.asarray(b.hll.registers, dtype=np.int32),
            ent=np.asarray(b.entropy.counts, dtype=np.float32),
            topk_keys=np.asarray(b.topk.keys),
            topk_counts=np.asarray(b.topk.counts, dtype=np.int64),
            slices={}))

    want = seal(_fold_reference(batches))
    for chips in (2, 4, 8):
        got, _ = _sharded_fold(batches, chips)
        assert seal(got) == want, f"chips={chips} window digest diverged"


# ---------------------------------------------------------------------------
# operator tier
# ---------------------------------------------------------------------------

def _make_instance(extra_params: dict, gadget_params: dict | None = None):
    desc = get("trace", "exec")
    ctx = GadgetContext(desc)
    for k, v in (gadget_params or {}).items():
        ctx.gadget_params.set(k, v)
    op = get_op("tpusketch")
    p = op.instance_params().to_params()
    p.set("enable", "true")
    p.set("log2-width", "8")
    p.set("hll-p", "6")
    p.set("entropy-log2-width", "6")
    p.set("topk", "64")
    for k, v in extra_params.items():
        p.set(k, v)
    return op.instantiate(ctx, None, p)


@pytest.fixture()
def batches():
    src = PySyntheticSource(seed=5, vocab=40, batch_size=BATCH)
    return [src.generate(BATCH) for _ in range(10)]


def test_operator_sharded_summary_matches_single_chip(batches):
    """Uneven round-robin fills through the REAL operator: 10 batches
    over 4 lanes (two full rounds + a flushed partial), harvested twice
    (mid-run + teardown) — summaries identical to the unsharded
    instance's, heavy hitters included."""
    ref = _make_instance({})
    for b in batches[:6]:
        ref.enrich_batch(b)
    s_ref_mid = ref.harvest()
    for b in batches[6:]:
        ref.enrich_batch(b)
    s_ref = ref.harvest()
    ref.post_gadget_run()

    for chips in ("2", "4", "auto"):
        inst = _make_instance({"shard-ingest": "true", "chips": chips})
        assert inst._shard_on
        for b in batches[:6]:
            inst.enrich_batch(b)
        s_mid = inst.harvest()
        for b in batches[6:]:
            inst.enrich_batch(b)
        s = inst.harvest()
        for got, want in ((s_mid, s_ref_mid), (s, s_ref)):
            assert got.events == want.events
            assert got.drops == want.drops
            assert got.distinct == want.distinct
            assert got.entropy_bits == want.entropy_bits
            assert got.heavy_hitters == want.heavy_hitters
        inst.post_gadget_run()


def test_operator_sharded_deterministic_across_runs(batches):
    """Two fresh sharded instances over the same batch stream produce the
    same summary sequence — the determinism replay `--verify` leans on
    (round-robin assignment and flush boundaries are functions of the
    stream alone)."""
    def run():
        inst = _make_instance({"shard-ingest": "true", "chips": "4"})
        out = []
        for i, b in enumerate(batches):
            inst.enrich_batch(b)
            if (i + 1) % 3 == 0:
                s = inst.harvest()
                out.append((s.events, s.distinct, s.entropy_bits,
                            tuple(s.heavy_hitters)))
        inst.post_gadget_run()
        return out

    assert run() == run()


def test_chips_one_is_the_exact_unsharded_path(batches):
    """chips=1 dispatch pin (zero regression risk): no mesh, no sharded
    state, the PR-7 single-pool path — and the same summary."""
    ref = _make_instance({})
    one = _make_instance({"shard-ingest": "true", "chips": "1"})
    assert not one._shard_on
    assert one._sharded is None and one._mesh is None
    for b in batches:
        ref.enrich_batch(b)
        one.enrich_batch(b)
    assert one._pool is not None and not one._lane_pools
    s_ref, s_one = ref.harvest(), one.harvest()
    assert (s_one.events, s_one.heavy_hitters) == \
        (s_ref.events, s_ref.heavy_hitters)
    ref.post_gadget_run()
    one.post_gadget_run()


def test_ingest_folded_rides_the_sharded_lanes():
    """The zero-copy SoA path under sharding: folded_block() hands out
    the next lane's pinned block and the absorbed totals match the
    unsharded fold."""
    from inspektor_gadget_tpu.sources.batch import FoldedBatch

    inst = _make_instance({"shard-ingest": "true", "chips": "2"})
    total = 0
    for i in range(5):  # odd count: last round flushes a filler lane
        block = inst.folded_block()
        n = 200 + i
        block[0][:n] = np.arange(1, n + 1, dtype=np.uint32)
        block[1][:n] = 1
        inst.ingest_folded(FoldedBatch(lanes=block, count=n))
        total += n
    s = inst.harvest()
    assert s.events == total
    inst.post_gadget_run()


def test_sharded_harvest_under_ingest_pressure():
    """Cross-thread flush safety (the review-hardened path): harvests —
    which flush the open round with cached zero-lane fillers and run the
    collective — fire from this thread while a pump thread keeps
    staging batches onto the lane stagers lock-free. The flush must
    never touch stager state the capture thread mutates, so no torn
    slots, no lost fences, no errors, and events keep growing."""
    import threading
    import time as _time

    inst = _make_instance({"shard-ingest": "true", "chips": "4"})
    src = PySyntheticSource(seed=11, vocab=40, batch_size=BATCH)
    stop = threading.Event()
    errors: list = []

    def pump():
        try:
            while not stop.is_set():
                inst.enrich_batch(src.generate(BATCH))
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    t = threading.Thread(target=pump)
    t.start()
    try:
        deadline = _time.monotonic() + 1.5
        last = -1
        harvests = 0
        while _time.monotonic() < deadline:
            s = inst.harvest()
            assert s.events >= last
            last = s.events
            harvests += 1
    finally:
        stop.set()
        t.join(timeout=10.0)
    assert not errors, errors
    assert harvests > 0 and last > 0
    inst.post_gadget_run()


# ---------------------------------------------------------------------------
# loud validation (FetchWindows discipline: typed errors before batch 1)
# ---------------------------------------------------------------------------

def test_chips_beyond_local_devices_is_a_param_error():
    with pytest.raises(ParamError, match="exceeds"):
        _make_instance({"shard-ingest": "true", "chips": "99"})
    # chips is validated against the host even without shard-ingest
    with pytest.raises(ParamError, match="exceeds"):
        _make_instance({"chips": "99"})


def test_shard_ingest_on_one_device_host_is_a_param_error(monkeypatch):
    import inspektor_gadget_tpu.operators.tpusketch as T
    monkeypatch.setattr(T, "_local_device_count", lambda: 1)
    with pytest.raises(ParamError, match=">= 2 local devices"):
        _make_instance({"shard-ingest": "true"})


def test_non_divisible_batch_size_is_a_param_error():
    with pytest.raises(ParamError, match="not divisible"):
        _make_instance({"shard-ingest": "true", "chips": "3"},
                       gadget_params={"batch-size": "1000"})


def test_chips_param_rejects_garbage_loudly():
    with pytest.raises(ParamError, match="integer or 'auto'"):
        _make_instance({"chips": "banana"})
    with pytest.raises(ParamError, match=">= 1"):
        _make_instance({"chips": "0"})


def test_ig_shard_disable_escape_hatch(monkeypatch, batches):
    monkeypatch.setenv("IG_SHARD_DISABLE", "1")
    inst = _make_instance({"shard-ingest": "true", "chips": "4"})
    assert not inst._shard_on
    inst.enrich_batch(batches[0])
    assert inst._sharded is None and inst._pool is not None
    inst.post_gadget_run()
    # the hatch outranks the topology checks: a fleet-wide chips=N
    # config must still start on a host that degraded below N devices
    # when the operator forces the single-chip path
    inst2 = _make_instance({"shard-ingest": "true", "chips": "99"})
    assert not inst2._shard_on
    inst2.post_gadget_run()


# ---------------------------------------------------------------------------
# harness arm (bench/CI plumbing)
# ---------------------------------------------------------------------------

def test_harness_sharded_smoke_tiny():
    """Tier-1 smoke for the chips-scaling arm: a tiny sharded run emits a
    schema-valid record under the device-plane series with the scale
    point in extra.chips and the honest wall rates beside the
    aggregate."""
    from inspektor_gadget_tpu.perf.harness import run_harness
    from inspektor_gadget_tpu.perf.schema import validate_record

    rec = run_harness("tiny", platform="cpu", pipeline="sharded", chips=2)
    assert validate_record(rec) == []
    assert rec["metric"] == "sketch_ingest_device_plane_aggregate"
    assert rec["config"] == "harness.tiny"
    ex = rec["extra"]
    assert ex["chips"] == 2
    assert ex["lane_batch"] * 2 == ex["batch"]
    assert ex["per_chip_ev_per_s"] > 0
    assert ex["device_plane_wall_ev_per_s"] > 0
    assert ex["e2e_wall_ev_per_s"] > 0
    assert "per_chip_ev_per_s x chips" in ex["aggregation"]
    assert rec["value"] == pytest.approx(ex["per_chip_ev_per_s"] * 2)
    assert "sharded_update" in rec["stages"]
    assert "h2d_lanes" in rec["stages"]


def test_harness_sharded_validation_is_loud():
    from inspektor_gadget_tpu.perf.harness import run_harness

    with pytest.raises(ValueError, match="out of range"):
        run_harness("tiny", platform="cpu", pipeline="sharded", chips=99)
    with pytest.raises(ValueError, match="needs pipeline=sharded"):
        run_harness("tiny", platform="cpu", pipeline="fused", chips=2)
    with pytest.raises(ValueError, match="unknown pipeline"):
        run_harness("tiny", platform="cpu", pipeline="warp")
