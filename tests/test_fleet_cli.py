"""Fleet CLI plane (ISSUE 20 satellites): every fleet verb reports an
unreachable agent with the SAME row shape and the SAME exit code (the
runs verb used to render its own dashed variant — the drift this file
pins shut), and the new `fleet topology` verb renders the merge tree
and its wire economics."""

from __future__ import annotations

import json

import pytest

from inspektor_gadget_tpu.cli.fleet import (
    _fleet_rc,
    _sweep_agents,
    _unreachable_line,
    cmd_fleet_accuracy,
    cmd_fleet_lag,
    cmd_fleet_queries,
    cmd_fleet_runs,
    cmd_fleet_topology,
)

GADGET = "trace/exec"


class _Args:
    remote = ""
    gadget = ""
    deadline = 0.5
    output = "table"
    topology = "auto"
    fan_in = 0
    all = False
    watch = 0.0
    iterations = 0


class _DeadClient:
    """Every dial raises — the uniformly-unreachable fleet."""

    def __init__(self, target, node, rpc_deadline=3.0):
        raise ConnectionError(f"dial {target}: connection refused")


class _HalfDeadClient:
    """n0 answers with empty state; every other node raises."""

    def __init__(self, target, node, rpc_deadline=3.0):
        if node != "n0":
            raise ConnectionError(f"dial {target}: connection refused")
        self.node = node

    def dump_state(self):
        return {"runs": [], "standing_queries": [], "pipeline": [],
                "accuracy": []}

    def close(self):
        pass


FLEET_VERBS = [
    pytest.param(cmd_fleet_runs, id="runs"),
    pytest.param(cmd_fleet_queries, id="queries"),
    pytest.param(cmd_fleet_accuracy, id="accuracy"),
    pytest.param(cmd_fleet_lag, id="lag"),
]


@pytest.mark.parametrize("verb", FLEET_VERBS)
def test_unreachable_row_shape_and_rc_uniform(verb, monkeypatch, capsys):
    """The satellite bugfix pin: same `node unreachable: err` row, rc 1,
    across every fleet sweep verb — parametrized so a verb regrowing
    its own error rendering fails here by name."""
    from inspektor_gadget_tpu.agent import client as agent_client
    monkeypatch.setattr(agent_client, "AgentClient", _HalfDeadClient)
    args = _Args()
    args.remote = "n0=unix:///tmp/a.sock,n1=unix:///tmp/b.sock"
    assert verb(args) == 1
    out = capsys.readouterr().out
    expected = _unreachable_line(
        {"node": "n1",
         "error": "dial unix:///tmp/b.sock: connection refused"})
    assert expected == ("n1" + " " * 11
                        + "unreachable: dial unix:///tmp/b.sock: "
                          "connection refused")
    assert expected in out
    # no dashed or per-verb variant row shapes
    assert "n1" + " " * 11 + "-" not in out


@pytest.mark.parametrize("verb", FLEET_VERBS)
def test_all_reachable_rc_zero(verb, monkeypatch):
    from inspektor_gadget_tpu.agent import client as agent_client

    class _Fine(_HalfDeadClient):
        def __init__(self, target, node, rpc_deadline=3.0):
            self.node = node

    monkeypatch.setattr(agent_client, "AgentClient", _Fine)
    args = _Args()
    args.remote = "n0=unix:///tmp/a.sock,n1=unix:///tmp/b.sock"
    assert verb(args) == 0


@pytest.mark.parametrize("verb", FLEET_VERBS)
def test_json_error_rows_keep_payload_keys(verb, monkeypatch, capsys):
    """The -o json shape is stable under failure: an unreachable node's
    row still carries the verb's payload key (empty), so dashboards
    never KeyError on a partition."""
    from inspektor_gadget_tpu.agent import client as agent_client
    monkeypatch.setattr(agent_client, "AgentClient", _DeadClient)
    args = _Args()
    args.remote = "n0=unix:///tmp/a.sock"
    args.output = "json"
    assert verb(args) == 1
    doc = json.loads(capsys.readouterr().out)
    row = doc["agents"][0]
    assert row["node"] == "n0"
    assert "connection refused" in row["error"]
    payload_keys = {"runs", "queries"} & set(row)
    assert payload_keys, row  # the verb's list key survives the error
    assert all(row[k] == [] for k in payload_keys)


def test_sweep_agents_copies_mutable_defaults(monkeypatch):
    from inspektor_gadget_tpu.agent import client as agent_client
    monkeypatch.setattr(agent_client, "AgentClient", _DeadClient)
    rows = _sweep_agents(
        {"a": "t1", "b": "t2"}, 0.1,
        lambda c: (_ for _ in ()).throw(RuntimeError("x")), runs=[])
    rows[0]["runs"].append("poison")
    assert rows[1]["runs"] == []  # no shared list between rows


def test_fleet_rc_and_line_helpers():
    ok = {"node": "n0", "error": ""}
    bad = {"node": "n1", "error": "boom"}
    assert _fleet_rc([ok]) == 0
    assert _fleet_rc([ok, bad]) == 1
    assert _unreachable_line(bad) == "n1" + " " * 11 + "unreachable: boom"
    assert _unreachable_line(bad, width=14) == (
        "n1" + " " * 13 + "unreachable: boom")


# ---------------------------------------------------------------------------
# fleet topology verb
# ---------------------------------------------------------------------------

def _topo_args(n: int = 6, **kw) -> _Args:
    args = _Args()
    args.remote = ",".join(f"n{i}=unix:///tmp/{i}.sock"
                           for i in range(n))
    for k, v in kw.items():
        setattr(args, k, v)
    return args


def test_topology_table_renders_tree_and_wire_cost(capsys):
    assert cmd_fleet_topology(_topo_args(6)) == 0
    out = capsys.readouterr().out
    assert "merge tree over 6 agent(s): depth 2, fan-in 4" in out
    # 6 leaves + 2 zones under the root = 8 edges; + 1 root frame
    assert "9 window frame(s) through the tree vs 6 flat" in out
    assert "client link folds 2 instead of 6" in out
    assert "fleet/" in out


def test_topology_json_carries_wire_accounting(capsys):
    assert cmd_fleet_topology(_topo_args(6, output="json")) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["topology"]["leaves"] == 6
    assert doc["wire_windows_tree"] == doc["topology"]["edges"] + 1
    assert doc["wire_windows_flat"] == 6


def test_topology_fan_in_shorthand(capsys):
    assert cmd_fleet_topology(_topo_args(8, fan_in=2,
                                         output="json")) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["spec"] == "auto:2"
    assert doc["topology"]["fan_in"] == 2
    assert doc["topology"]["depth"] == 3


def test_topology_declared_spec_and_bad_spec(capsys):
    args = _topo_args(4, topology="za=n0,n1;zb=n2,n3", output="json")
    assert cmd_fleet_topology(args) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["topology"]["aggregators"] == 3
    bad = _topo_args(4, topology="za=n0,nope")
    assert cmd_fleet_topology(bad) == 2
    assert "unknown agent" in capsys.readouterr().err


def test_topology_no_agents_rc2(capsys, monkeypatch, tmp_path):
    from inspektor_gadget_tpu.cli import deploy
    monkeypatch.setattr(deploy, "STATE_FILE",
                        str(tmp_path / "none.json"))
    args = _Args()
    assert cmd_fleet_topology(args) == 2
    assert "no agents" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# query --topology plumbing
# ---------------------------------------------------------------------------

def test_query_topology_requires_remote(capsys):
    from inspektor_gadget_tpu.cli.main import build_parser
    parser = build_parser()
    args = parser.parse_args(["query", "--topology", "auto"])
    assert args.func(args) == 2
    assert "--topology needs --remote" in capsys.readouterr().err


def test_query_topology_bad_spec_rc2(capsys):
    from inspektor_gadget_tpu.cli.main import build_parser
    parser = build_parser()
    args = parser.parse_args([
        "query", "--remote", "n0=unix:///tmp/x.sock",
        "--topology", "auto:x"])
    assert args.func(args) == 2
    assert "auto:<int>" in capsys.readouterr().err
