"""Capture/replay acceptance tier (ISSUE 5):

- a recording armed over the agent RPCs journals a 2-agent GrpcRuntime
  run (batches + summaries + alert transitions per node),
- the per-node journals are pulled into one client-side bundle,
- a SIGKILLed writer tears a journal mid-segment; reopening drops the
  torn tail with the loss accounted,
- replaying the journal through the REAL operator chain (enrich →
  tpusketch → alerts) on the injected clock reproduces the recorded
  alert lifecycle exactly — same rule, key, state sequence, and
  debounce epoch — and the same summary digest sequence,
- `ig-tpu replay --verify` asserts the same from the CLI, `ig-tpu
  record list`/`alerts test --journal` read the artifacts, and the
  capture counters surface in the Prometheus exposition.
"""

from __future__ import annotations

import binascii
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import zlib

import numpy as np
import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.agent import wire
from inspektor_gadget_tpu.agent.service import serve
from inspektor_gadget_tpu.capture import (
    RECORDINGS,
    JournalReader,
    is_journal,
    replay_journal,
)
from inspektor_gadget_tpu.gadgets import GadgetContext
from inspektor_gadget_tpu.gadgets import registry as gadget_registry
from inspektor_gadget_tpu.gadgets.interface import GadgetDesc, GadgetType
from inspektor_gadget_tpu.operators import operators as op_registry
from inspektor_gadget_tpu.params import Collection, ParamDescs

RULE_ID = "entropy-jump"
FOR_S = 0.05
EPOCH_GAP_S = 0.08
REC_ID = "e2e-incident"

RULES_DOC = json.dumps({"rules": [{
    "id": RULE_ID, "kind": "entropy_jump", "threshold": 1.0, "window": 3,
    "for": FOR_S, "cooldown": "5s", "severity": "warning",
}]})


class _CaptureSynthGadget:
    """Scripted key distribution (constant → uniform → constant) with one
    EXPLICIT harvest per batch: the recorded journal then carries
    deterministic harvest boundaries for the replay to reproduce."""

    def __init__(self, ctx):
        self.ctx = ctx
        self._batch_handler = None

    def set_batch_handler(self, handler):
        self._batch_handler = handler

    def run(self, ctx):
        from inspektor_gadget_tpu.operators import tpusketch
        from inspektor_gadget_tpu.sources.batch import EventBatch
        rng = np.random.default_rng(7)
        phases = (
            [np.full(2048, 0xDEADBEEF, dtype=np.uint64)] * 3
            + [rng.integers(1, 2**32, 8192, dtype=np.uint64)
               for _ in range(3)]
            + [np.full(64, 0xDEADBEEF, dtype=np.uint64)] * 3
        )
        inst = next((i for i in tpusketch.live_instances()
                     if i.ctx.run_id == ctx.run_id), None)
        for keys in phases:
            if ctx.done:
                return
            b = EventBatch.alloc(len(keys), with_comm=False)
            b.cols["key_hash"][:] = keys
            b.cols["mntns"][:] = 1
            b.cols["ts"][:] = time.time_ns()
            b.count = len(keys)
            if self._batch_handler is not None:
                self._batch_handler(b)
            if inst is not None:
                inst.harvest()
            ctx.sleep_or_done(EPOCH_GAP_S)


class _CaptureSynthDesc(GadgetDesc):
    name = "capturesynth"
    category = "trace"
    gadget_type = GadgetType.TRACE
    description = "scripted-entropy batch gadget (capture/replay e2e)"
    event_cls = None

    def params(self) -> ParamDescs:
        return ParamDescs()

    def new_instance(self, ctx) -> _CaptureSynthGadget:
        return _CaptureSynthGadget(ctx)


@pytest.fixture(scope="module", autouse=True)
def synth_gadget():
    desc = _CaptureSynthDesc()
    gadget_registry.register(desc)
    yield desc
    gadget_registry._REGISTRY.pop((desc.category, desc.name), None)


@pytest.fixture(scope="module")
def agents():
    servers, targets = [], {}
    tmp = tempfile.mkdtemp()
    for i in range(2):
        addr = f"unix://{tmp}/cap-agent{i}.sock"
        server, _ = serve(addr, node_name=f"cnode-{i}")
        servers.append(server)
        targets[f"cnode-{i}"] = addr
    yield targets
    for s in servers:
        s.stop(grace=0.5)


@pytest.fixture(scope="module")
def capture_area(tmp_path_factory):
    base = str(tmp_path_factory.mktemp("capture-area"))
    RECORDINGS.set_base_dir(base)
    yield base
    RECORDINGS.set_base_dir(None)


def _op_params() -> Collection:
    col = Collection()
    ap = op_registry.get("alerts").instance_params().to_params()
    ap.set("rules", RULES_DOC)
    col["operator.alerts."] = ap
    sp = op_registry.get("tpusketch").instance_params().to_params()
    for k, v in (("enable", "true"), ("depth", "4"), ("log2-width", "10"),
                 ("hll-p", "8"), ("entropy-log2-width", "8"),
                 ("topk", "16"), ("harvest-interval", "1h")):
        sp.set(k, v)
    col["operator.tpusketch."] = sp
    return col


def _transition_key(a: dict) -> tuple:
    return (a.get("rule"), a.get("key", ""), a.get("transition"),
            a.get("epoch"))


def _frame(header: dict, payload: bytes = b"") -> bytes:
    zp = zlib.compress(wire.encode_msg(header, payload), 1)
    return (len(zp).to_bytes(4, "little")
            + (zlib.crc32(zp) & 0xFFFFFFFF).to_bytes(4, "little") + zp)


@pytest.fixture(scope="module")
def recorded_bundle(agents, capture_area, tmp_path_factory):
    """Arm → run on both agents → stop → fetch: the shared journey every
    test below inspects from a different side."""
    from inspektor_gadget_tpu.runtime.grpc_runtime import GrpcRuntime
    runtime = GrpcRuntime(dict(agents))
    cluster_events: list[dict] = []
    try:
        results, errors = runtime.start_recording(REC_ID)
        assert not errors, errors
        assert set(results) == set(agents)

        desc = gadget_registry.get("trace", "capturesynth")
        ctx = GadgetContext(desc, operator_params=_op_params(), timeout=120.0)
        run = runtime.run_gadget(ctx, on_alert=cluster_events.append)
        assert not run.errors(), run.errors()

        stop_results, stop_errors = runtime.stop_recording(REC_ID)
        assert not stop_errors, stop_errors

        bundle_dir = str(tmp_path_factory.mktemp("bundle"))
        bundle = runtime.fetch_recording(REC_ID, bundle_dir)
        assert not bundle["errors"], bundle["errors"]
    finally:
        runtime.close()
    return {"bundle_dir": bundle_dir, "bundle": bundle,
            "cluster_events": cluster_events}


def _node_journal(bundle_dir: str, node: str) -> str:
    """The fetched journal recorded BY `node` (manifest-addressed)."""
    root = os.path.join(bundle_dir, node)
    for name in sorted(os.listdir(root)):
        jpath = os.path.join(root, name)
        if is_journal(jpath) and \
                JournalReader(jpath).manifest.get("node") == node:
            return jpath
    raise AssertionError(f"no journal recorded by {node} under {root}")


def test_record_kill_replay_end_to_end(recorded_bundle, agents):
    bundle_dir = recorded_bundle["bundle_dir"]

    # -- the 2-agent run produced one journal per node, with provenance --
    journals = {n: _node_journal(bundle_dir, n) for n in agents}
    for node, jpath in journals.items():
        m = JournalReader(jpath).manifest
        assert m["node"] == node
        assert m["gadget"] == "trace/capturesynth"
        assert m["recording_id"] == REC_ID
        assert "operator.alerts.rules" in m["params"]
        assert m["git_sha"]  # provenance stamped, not guessed

    # the cluster fold-in fired exactly once during the recorded run
    cluster = [e for e in recorded_bundle["cluster_events"]
               if e["rule"] == RULE_ID]
    assert [e["transition"] for e in cluster] == \
        ["pending", "firing", "resolved"]

    # -- SIGKILL a writer mid-segment: the journal survives ---------------
    victim = journals["cnode-0"]
    segs = sorted(f for f in os.listdir(victim) if f.endswith(".igj"))
    seg = os.path.join(victim, segs[-1])
    reader0 = JournalReader(victim)
    pre_records = sum(1 for _ in reader0.records())
    assert not reader0.losses
    good = _frame({"type": wire.EV_JOURNAL_MARK, "seq": 10_000,
                   "ts": time.time(), "mark": "pre-kill"})
    torn = _frame({"type": wire.EV_JOURNAL_MARK, "seq": 10_001,
                   "ts": time.time(), "mark": "never-lands"})
    child = subprocess.Popen([
        sys.executable, "-c",
        "import binascii, os, signal, sys\n"
        "f = open(sys.argv[1], 'ab')\n"
        "f.write(binascii.unhexlify(sys.argv[2]))\n"
        "f.write(binascii.unhexlify(sys.argv[3]))\n"
        "f.flush(); os.fsync(f.fileno())\n"
        "os.kill(os.getpid(), signal.SIGKILL)\n",
        seg, binascii.hexlify(good).decode(),
        binascii.hexlify(torn[: len(torn) // 2]).decode(),
    ])
    child.wait(timeout=30)
    assert child.returncode == -signal.SIGKILL

    # reopen: the torn tail is dropped and the loss is ACCOUNTED; every
    # record up to and including the killed writer's last whole frame
    # survives
    reader = JournalReader(victim)
    recs = list(reader.records())
    assert len(recs) == pre_records + 1
    assert recs[-1][0]["mark"] == "pre-kill"
    assert len(reader.losses) == 1
    assert reader.losses[0].dropped_bytes == len(torn) // 2

    # -- replay both journals: identical lifecycle, deterministically -----
    for node, jpath in journals.items():
        res = replay_journal(jpath, speed=0.0)
        recorded = [a for a in res.recorded_alerts if a["rule"] == RULE_ID]
        replayed = [a for a in res.alerts if a["rule"] == RULE_ID]
        # same rule, key, state sequence, and debounce epoch — exactly
        assert [_transition_key(a) for a in replayed] == \
            [_transition_key(a) for a in recorded], (node, replayed, recorded)
        assert [a["transition"] for a in replayed] == \
            ["pending", "firing", "resolved"]
        # debounce timing on the injected clock: firing held ≥ `for`
        pend = next(a for a in replayed if a["transition"] == "pending")
        fire = next(a for a in replayed if a["transition"] == "firing")
        assert fire["epoch"] > pend["epoch"]
        # the replayed sketch summaries digest-match the recording
        assert res.digests_match, (node, res.recorded_digests, res.digests)
        # one harvest per scripted batch + the run's teardown harvest
        assert len(res.digests) == 10
        assert res.events == 3 * 2048 + 3 * 8192 + 3 * 64


def test_replay_is_deterministic_run_to_run(recorded_bundle, agents):
    jpath = _node_journal(recorded_bundle["bundle_dir"], "cnode-1")
    a = replay_journal(jpath, speed=0.0)
    b = replay_journal(jpath, speed=0.0)
    # byte-identical summary sequence: same digests in the same order
    assert a.digests == b.digests
    assert [_transition_key(x) for x in a.alerts] == \
        [_transition_key(x) for x in b.alerts]


def test_replay_cli_verify_and_record_verbs(recorded_bundle, agents,
                                            capsys, capture_area):
    from inspektor_gadget_tpu.cli.main import main as cli_main
    jpath = _node_journal(recorded_bundle["bundle_dir"], "cnode-1")

    assert cli_main(["replay", jpath, "--verify"]) == 0
    out = capsys.readouterr().out
    assert "verify=ok" in out and RULE_ID in out

    spec = ",".join(f"{k}={v}" for k, v in agents.items())
    assert cli_main(["record", "list", "--remote", spec]) == 0
    out = capsys.readouterr().out
    assert REC_ID in out and "stopped" in out

    assert cli_main(["record", "inspect",
                     recorded_bundle["bundle_dir"]]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert any(str(wire.EV_SUMMARY) in j["by_type"]
               for j in doc["journals"].values())


def test_alerts_test_consumes_journals(recorded_bundle, tmp_path, capsys):
    from inspektor_gadget_tpu.cli.main import main as cli_main
    rules = tmp_path / "rules.json"
    rules.write_text(RULES_DOC)
    jpath = _node_journal(recorded_bundle["bundle_dir"], "cnode-0")
    assert cli_main(["alerts", "test", "--file", str(rules),
                     "--journal", jpath]) == 0
    cap = capsys.readouterr()
    assert f"{RULE_ID} -> firing" in cap.out
    assert "0 still firing" in cap.out

    # the old recorded-summary format still reads, loudly deprecated
    summaries = tmp_path / "summaries.jsonl"
    lines = [json.dumps({"events": 10, "drops": 0, "distinct": 1.0,
                         "entropy": e, "epoch": i, "heavy_hitters": []})
             for i, e in enumerate([0.0, 0.0, 0.0, 7.5, 7.5, 0.0])]
    summaries.write_text("\n".join(lines) + "\n")
    assert cli_main(["alerts", "test", "--file", str(rules),
                     "--summaries", str(summaries)]) == 0
    cap = capsys.readouterr()
    assert "deprecated" in cap.err
    # exactly one of --journal/--summaries
    assert cli_main(["alerts", "test", "--file", str(rules)]) == 2


def test_bench_replay_reproducible_input(recorded_bundle):
    from inspektor_gadget_tpu.perf.harness import run_harness
    jpath = _node_journal(recorded_bundle["bundle_dir"], "cnode-1")
    rec = run_harness("tiny", platform="cpu", seconds=0.05, replay=jpath)
    replay_prov = rec["provenance"]["replay"]
    assert replay_prov["journal"] == jpath
    assert replay_prov["digest"] == JournalReader(jpath).digest()
    assert replay_prov["batches"] == 9  # 9 scripted batches recorded
    assert rec["extra"]["replay_digest"] == replay_prov["digest"]


def test_alert_firing_at_run_end_is_journaled_and_replays(tmp_path):
    """An alert still firing when the run ends resolves via the engine's
    close(); the capture operator must still have its writers open at
    that point (teardown runs in reverse instantiation order, and alerts
    depends on capture exactly for this) or the recorded journal and its
    replay disagree on the final transitions."""
    from inspektor_gadget_tpu.runtime.local import LocalRuntime
    rules = json.dumps({"rules": [{
        "id": "hot", "kind": "threshold", "field": "events", "op": ">",
        "threshold": 10, "severity": "info",
    }]})
    col = _op_params()
    col["operator.alerts."].set("rules", rules)
    cp = op_registry.get("capture").instance_params().to_params()
    capdir = str(tmp_path / "runcap")
    cp.set("dir", capdir)
    col["operator.capture."] = cp
    desc = gadget_registry.get("trace", "capturesynth")
    ctx = GadgetContext(desc, operator_params=col, timeout=60.0)
    result = LocalRuntime().run_gadget(ctx)
    assert not result.errors(), result.errors()

    from inspektor_gadget_tpu.capture import iter_journals
    (jpath,) = list(iter_journals(capdir))
    res = replay_journal(jpath, speed=0.0)
    recorded = [a["transition"] for a in res.recorded_alerts
                if a["rule"] == "hot"]
    # the end-of-run resolve IS in the journal...
    assert recorded and recorded[-1] == "resolved"
    assert recorded == ["pending", "firing", "resolved"]
    # ...and the replay reproduces the full lifecycle exactly
    assert res.alerts_match, (res.recorded_alerts, res.alerts)
    assert res.digests_match


def test_capture_telemetry_and_doctor_surfaces(recorded_bundle):
    from inspektor_gadget_tpu.doctor import probe_windows
    from inspektor_gadget_tpu.telemetry import render_prometheus
    text = render_prometheus()
    assert "ig_capture_records_total" in text
    assert "ig_capture_bytes_total" in text
    assert "ig_capture_drops_total" in text  # the SIGKILL tear was counted
    w = probe_windows()["capture_dir"]
    assert w.ok and "writable" in w.detail
