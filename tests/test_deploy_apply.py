"""Deploy apply + rollout wait + undeploy + exec-tunnel dialer.

Reference contracts: cmd/kubectl-gadget/deploy.go:100-546 (apply manifests,
wait for DaemonSet rollout), undeploy.go (delete them), and
pkg/runtime/grpc/k8s-exec-dialer.go:1-132 (gRPC dialed over an exec
stream's stdio). The cluster is the FakeClusterApplier double whose state
lands in a pod-manifest file the pod informer watches — the full
deploy → discovery → undeploy round-trip without a kube API.
"""

import sys
import tempfile
import textwrap
import time
from pathlib import Path

import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.cli.apply import (
    FakeClusterApplier, deploy, manifest_kind_name, split_manifests, undeploy,
)
from inspektor_gadget_tpu.cli.deploy import render_manifests


def test_split_manifests_and_kind_name():
    docs = split_manifests(render_manifests())
    kinds = [manifest_kind_name(d) for d in docs]
    assert ("Namespace", "ig-tpu") in kinds
    assert ("DaemonSet", "ig-tpu-agent") in kinds
    assert ("ClusterRole", "ig-tpu-agent") in kinds
    assert len(docs) == 5


def test_deploy_applies_and_waits_for_rollout(tmp_path):
    pod_file = str(tmp_path / "pods.json")
    applier = FakeClusterApplier(pod_file, nodes=("node-a", "node-b"),
                                 ready_after=2)  # ready on the 3rd poll
    desired, ready = deploy(applier, render_manifests(),
                            rollout_timeout=10.0, poll=0.05)
    assert (desired, ready) == (2, 2)
    assert ("DaemonSet", "ig-tpu-agent") in applier.applied
    assert applier._status_polls >= 3  # rollout actually waited


def test_deploy_rollout_timeout(tmp_path):
    applier = FakeClusterApplier(str(tmp_path / "pods.json"),
                                 ready_after=10**9)
    with pytest.raises(TimeoutError):
        deploy(applier, render_manifests(), rollout_timeout=0.3, poll=0.05)


def test_deploy_discovery_undeploy_roundtrip(tmp_path):
    """Applied DaemonSet → agent pods appear in the file-manifest pod
    source → informer feeds a collection; undeploy removes them."""
    from inspektor_gadget_tpu.containers import (
        ContainerCollection, file_pod_source, with_pod_informer,
    )

    pod_file = str(tmp_path / "pods.json")
    applier = FakeClusterApplier(pod_file, nodes=("node-a", "node-b"))
    manifests = render_manifests()
    deploy(applier, manifests, rollout_timeout=5.0, poll=0.05)

    cc = ContainerCollection()
    cc.initialize(with_pod_informer(file_pod_source(pod_file),
                                    interval=0.1))
    try:
        deadline = time.time() + 3.0
        while time.time() < deadline and len(cc) < 2:
            time.sleep(0.05)
        pods = {c.pod for c in cc.get_all()}
        assert pods == {"ig-tpu-agent-node-a", "ig-tpu-agent-node-b"}

        removed = undeploy(applier, manifests)
        assert ("DaemonSet", "ig-tpu-agent") in removed
        deadline = time.time() + 3.0
        while time.time() < deadline and len(cc) > 0:
            time.sleep(0.05)
        assert len(cc) == 0, "undeployed pods still in the collection"
    finally:
        cc._pod_informer.stop()


# ---------------------------------------------------------------------------
# exec-tunnel dialer: real agent, gRPC over a subprocess's stdio
# ---------------------------------------------------------------------------

_BRIDGE = textwrap.dedent("""
    import socket, sys, threading
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.connect(sys.argv[1])
    def out():
        while True:
            d = s.recv(65536)
            if not d: break
            sys.stdout.buffer.write(d); sys.stdout.buffer.flush()
    t = threading.Thread(target=out, daemon=True); t.start()
    while True:
        d = sys.stdin.buffer.read1(65536)
        if not d: break
        s.sendall(d)
    s.shutdown(socket.SHUT_WR); t.join(2)
""")


def test_exec_tunnel_dialer_runs_gadget():
    """AgentClient over an ExecTunnelDialer whose subprocess bridges stdio
    to the agent's unix socket — the kubectl-exec dial path with a python
    stdio proxy standing in for kubectl."""
    from inspektor_gadget_tpu.agent.client import AgentClient
    from inspektor_gadget_tpu.agent.dialer import ExecTunnelDialer
    from inspektor_gadget_tpu.agent.service import serve

    tmp = tempfile.mkdtemp()
    sock = f"{tmp}/agent.sock"
    server, _ = serve(f"unix://{sock}", node_name="tunneled")
    dialer = ExecTunnelDialer([sys.executable, "-S", "-c", _BRIDGE, sock])
    client = AgentClient("tunneled-agent", "tunneled", dialer=dialer)
    try:
        cat = client.get_catalog()
        assert any(g["name"] == "exec" for g in cat["gadgets"])
        rows = []
        res = client.run_gadget(
            "trace", "exec",
            {"gadget.source": "pysynthetic", "gadget.rate": "20000",
             "gadget.batch-size": "256"},
            timeout=1.0, on_json=lambda node, row: rows.append(row))
        assert res["error"] is None
        assert len(rows) > 10
        assert rows[0]["node"] == "tunneled"
    finally:
        client.close()
        server.stop(grace=0.5)


def test_grpc_runtime_dialer_factory():
    """GrpcRuntime fans out through per-node dialers when a factory is
    given (the runtime-level seam)."""
    from inspektor_gadget_tpu.agent.dialer import ExecTunnelDialer
    from inspektor_gadget_tpu.agent.service import serve
    from inspektor_gadget_tpu.gadgets import GadgetContext, get
    from inspektor_gadget_tpu.runtime import GrpcRuntime

    tmp = tempfile.mkdtemp()
    sock = f"{tmp}/agent.sock"
    server, _ = serve(f"unix://{sock}", node_name="node-t")

    made = []

    def factory(node, target):
        d = ExecTunnelDialer([sys.executable, "-S", "-c", _BRIDGE, sock])
        made.append(node)
        return d

    runtime = GrpcRuntime({"node-t": "tunnel:opaque"}, dialer_factory=factory)
    desc = get("trace", "exec")
    params = desc.params().to_params()
    params.set("source", "pysynthetic")
    params.set("rate", "10000")
    ctx = GadgetContext(desc, gadget_params=params, timeout=1.0)
    events = []
    result = runtime.run_gadget(ctx, on_event=events.append)
    runtime.close()
    server.stop(grace=0.5)
    assert made == ["node-t"]
    assert not result.errors()
    assert events and events[0].node == "node-t"
