"""Distributed runtime tests: real gRPC agents + client fan-out.

Models the reference's integration tier (SURVEY §4: deploy agents, run
kubectl-gadget, match JSON events) scaled to in-process agents on unix
sockets — 3 'nodes' on one host.
"""

import tempfile
import threading
import time
from pathlib import Path

import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.agent.service import serve
from inspektor_gadget_tpu.agent.client import AgentClient
from inspektor_gadget_tpu.agent.stream import GadgetStream, LOST_MARKER
from inspektor_gadget_tpu.gadgets import GadgetContext, get
from inspektor_gadget_tpu.params import Params
from inspektor_gadget_tpu.snapshotcombiner import SnapshotCombiner


@pytest.fixture(scope="module")
def agents():
    servers = []
    targets = {}
    tmp = tempfile.mkdtemp()
    for i in range(3):
        addr = f"unix://{tmp}/agent{i}.sock"
        server, agent = serve(addr, node_name=f"node-{i}")
        servers.append(server)
        targets[f"node-{i}"] = addr
    yield targets
    for s in servers:
        s.stop(grace=0.5)


def test_catalog_roundtrip(agents):
    client = AgentClient(next(iter(agents.values())), "node-0")
    cat = client.get_catalog()
    names = {(g["category"], g["name"]) for g in cat["gadgets"]}
    assert ("trace", "exec") in names
    assert any(op["name"] == "tpusketch" for op in cat["operators"])
    client.close()


def test_single_node_stream_with_seq(agents):
    client = AgentClient(agents["node-1"], "node-1")
    rows = []
    res = client.run_gadget(
        "trace", "exec",
        {"gadget.source": "pysynthetic", "gadget.rate": "20000",
         "gadget.batch-size": "256"},
        timeout=1.0, on_json=lambda node, row: rows.append((node, row)),
    )
    assert res["error"] is None
    assert len(rows) > 50
    assert rows[0][0] == "node-1"
    assert rows[0][1]["comm"].startswith("proc-")
    # loss accounting contract (not zero-loss: under CPU contention the
    # server's bounded buffer may drop, as the reference's does —
    # service.go:160-167): every client-observed seq gap must be covered by
    # the server's drop count. Drops past the last delivered message (tail
    # eviction while the run winds down) legitimately show no gap, so
    # dropped > 0 with gaps == 0 is valid — the reverse is not.
    assert res["gaps"] <= res["dropped"], "seq gaps exceed drop accounting"
    client.close()


def test_fanout_runtime_merges_nodes(agents):
    from inspektor_gadget_tpu.runtime import GrpcRuntime

    desc = get("trace", "exec")
    params = desc.params().to_params()
    params.set("source", "pysynthetic")
    params.set("rate", "5000")
    params.set("batch-size", "256")
    ctx = GadgetContext(desc, gadget_params=params, timeout=1.5)
    runtime = GrpcRuntime(dict(agents))
    events = []
    result = runtime.run_gadget(ctx, on_event=events.append)
    assert set(result.keys()) == {"node-0", "node-1", "node-2"}
    assert not result.errors()
    nodes_seen = {e.node for e in events}
    assert nodes_seen == {"node-0", "node-1", "node-2"}
    runtime.close()


def test_fanout_real_host_wide_window(agents):
    """The distributed plane carries REAL capture windows, not just the
    synthetic streams: trace/capabilities through the gRPC fan-out with a
    live unprivileged-chown workload must deliver denial rows from every
    node (each agent runs its own host-wide window)."""
    import os
    import shutil
    import subprocess

    from inspektor_gadget_tpu.runtime import GrpcRuntime
    from inspektor_gadget_tpu.sources.bridge import (audit_supported,
                                                     captrace_supported)
    if os.geteuid() != 0 or not shutil.which("setpriv"):
        pytest.skip("needs root + setpriv")
    if not (captrace_supported() or audit_supported()):
        pytest.skip("no host-wide capability window")

    target = f"/tmp/ig_fanout_cap_{os.getpid()}"
    open(target, "w").close()
    stop = threading.Event()

    def trigger():
        time.sleep(0.8)
        while not stop.is_set():
            subprocess.run(["setpriv", "--reuid", "65534", "--clear-groups",
                            "chown", "0:0", target],
                           check=False, stderr=subprocess.DEVNULL)
            stop.wait(0.25)

    t = threading.Thread(target=trigger)
    t.start()
    runtime = None
    try:
        desc = get("trace", "capabilities")
        params = desc.params().to_params()
        ctx = GadgetContext(desc, gadget_params=params, timeout=4.0)
        runtime = GrpcRuntime(dict(agents))
        events = []
        result = runtime.run_gadget(ctx, on_event=events.append)
    finally:
        if runtime is not None:
            runtime.close()
        stop.set()
        t.join()
        os.unlink(target)
    assert not result.errors(), result.errors()
    denials = [e for e in events
               if getattr(e, "cap", "") == "CHOWN"
               and getattr(e, "verdict", "") == "deny"]
    assert denials, f"{len(events)} events, no CHOWN denials"
    # every node observed the host-wide workload (shared kernel)
    assert {e.node for e in denials} == {"node-0", "node-1", "node-2"}


def test_fanout_node_filter(agents):
    from inspektor_gadget_tpu.runtime import GrpcRuntime

    desc = get("trace", "exec")
    params = desc.params().to_params()
    params.set("source", "pysynthetic")
    params.set("rate", "5000")
    rt_params = Params(GrpcRuntime(dict(agents)).params())
    rt_params.set("node", "node-2")
    ctx = GadgetContext(desc, gadget_params=params,
                        runtime_params=rt_params, timeout=1.0)
    runtime = GrpcRuntime(dict(agents))
    events = []
    result = runtime.run_gadget(ctx, on_event=events.append)
    assert set(result.keys()) == {"node-2"}
    assert {e.node for e in events} == {"node-2"}
    runtime.close()


def test_summary_stream_sketch_merge(agents):
    """Nodes stream sketch digests; client merges (the low-bandwidth path)."""
    client = AgentClient(agents["node-0"], "node-0")
    summaries = []
    res = client.run_gadget(
        "trace", "exec",
        {"gadget.source": "pysynthetic", "gadget.rate": "50000",
         "operator.tpusketch.enable": "true",
         "operator.tpusketch.log2-width": "12",
         "operator.tpusketch.hll-p": "10",
         "operator.tpusketch.harvest-interval": "300ms"},
        timeout=1.5, outputs=("summary",),
        on_summary=lambda node, s: summaries.append(s),
    )
    assert res["error"] is None
    assert summaries
    last = summaries[-1]
    assert last["events"] > 500
    assert last["heavy_hitters"]
    client.close()


def test_container_hook_rpc(agents):
    client = AgentClient(agents["node-0"], "node-0")
    r = client.add_container({"id": "h1", "name": "hooked", "pid": 1,
                              "mntns": 777777})
    assert r["ok"]
    r2 = client.remove_container("h1")
    assert r2["ok"]
    client.close()


def test_dump_state_debug_rpc(agents):
    client = AgentClient(agents["node-0"], "node-0")
    client.apply_trace({"metadata": {"name": "dump-t",
                                     "annotations": {}},
                        "spec": {"gadget": "trace/exec"}})
    state = client.dump_state()
    assert "threads" in state and state["threads"]
    # CRD-path state rides the same dump
    assert any(t["name"] == "dump-t" for t in state["traces"])
    client.delete_trace("dump-t")
    client.close()


# -- stream semantics (ref: stream.go tests) --------------------------------

def test_stream_replay_history():
    s = GadgetStream()
    for i in range(150):
        s.publish(i)
    sub = s.subscribe("late", replay=True)
    # only the last 100 retained
    items = list(sub.queue)
    assert len(items) == 100
    assert items[0] == 50 and items[-1] == 149


def test_stream_overrun_marks_loss():
    s = GadgetStream()
    sub = s.subscribe("slow", replay=False)
    for i in range(500):
        s.publish(i)
    items = list(sub.queue)
    assert LOST_MARKER in items
    assert len(items) <= 251


def test_snapshot_combiner_ttl():
    c = SnapshotCombiner(ttl_ticks=2)
    c.add_snapshot("node-0", ["a", "b"])
    c.add_snapshot("node-1", ["c"])
    assert sorted(c.get_snapshots()) == ["a", "b", "c"]
    # node-1 refreshes, node-0 ages out after ttl
    c.add_snapshot("node-1", ["c2"])
    out = c.get_snapshots()
    assert "c2" in out and "a" in out
    out = c.get_snapshots()
    assert out == ["c2"] or out == []  # node-0 aged out


def test_node_failure_isolated(agents):
    """Kill one agent mid-run: its node reports an error, others stream on
    (ref: CombinedGadgetResult partial results, runtime.go:42-79)."""
    import tempfile
    from inspektor_gadget_tpu.agent.service import serve as serve_agent
    from inspektor_gadget_tpu.runtime import GrpcRuntime

    tmp = tempfile.mkdtemp()
    addr = f"unix://{tmp}/doomed.sock"
    doomed_server, _ = serve_agent(addr, node_name="doomed")
    targets = dict(agents)
    targets["doomed"] = addr

    desc = get("trace", "exec")
    params = desc.params().to_params()
    params.set("source", "pysynthetic")
    params.set("rate", "3000")
    ctx = GadgetContext(desc, gadget_params=params, timeout=2.0)
    runtime = GrpcRuntime(targets)
    events = []

    def killer():
        time.sleep(0.6)
        doomed_server.stop(grace=0)

    threading.Thread(target=killer, daemon=True).start()
    result = runtime.run_gadget(ctx, on_event=events.append)
    runtime.close()
    healthy = {"node-0", "node-1", "node-2"}
    assert healthy <= set(result.keys())
    for n in healthy:
        assert result[n].error is None, result[n].error
    assert {e.node for e in events} >= healthy
