"""Distributed tracing plane: span semantics, cross-process propagation
through a real client→agent gRPC run, Chrome-trace export, ring
retention, the flight recorder (including crash dumps), the bounded
platform probe (VERDICT hole #1 regression), and the logger satellites
(StreamLogger run/trace IDs, get_logger level stability)."""

from __future__ import annotations

import json
import logging
import tempfile
import threading
import time

import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.agent.client import AgentClient
from inspektor_gadget_tpu.agent.service import serve
from inspektor_gadget_tpu.gadgets import GadgetContext, get
from inspektor_gadget_tpu.params import Collection
from inspektor_gadget_tpu.runtime.grpc_runtime import GrpcRuntime
from inspektor_gadget_tpu.telemetry.tracing import (
    RECORDER,
    TRACER,
    FlightRecorder,
    SpanContext,
    Tracer,
    export_chrome,
    install_crash_handlers,
    parse_traceparent,
)


# ---------------------------------------------------------------------------
# span + context semantics (private Tracer instances)
# ---------------------------------------------------------------------------

def test_traceparent_roundtrip_and_malformed():
    ctx = SpanContext(trace_id="ab" * 16, span_id="cd" * 8, sampled=True)
    back = parse_traceparent(ctx.to_traceparent())
    assert back == ctx
    off = SpanContext(trace_id="ab" * 16, span_id="cd" * 8, sampled=False)
    assert parse_traceparent(off.to_traceparent()).sampled is False
    for bad in ("", "00-zz-xx-01", "nope", "00-abc-def-01", 42, None):
        assert parse_traceparent(bad) is None


def test_span_parent_linkage_and_contextvar_nesting():
    t = Tracer(capacity=64)
    with t.span("outer") as outer:
        with t.span("inner"):  # implicit parent via contextvar
            pass
        assert t.current_context() == outer.context
    assert t.current_context() is None
    recs = {r.name: r for r in t.records()}
    assert recs["inner"].trace_id == recs["outer"].trace_id
    assert recs["inner"].parent_id == recs["outer"].span_id
    assert recs["outer"].parent_id == ""
    assert recs["inner"].duration >= 0


def test_span_records_error_and_explicit_parent():
    t = Tracer(capacity=64)
    remote = SpanContext(trace_id="11" * 16, span_id="22" * 8)
    with pytest.raises(RuntimeError):
        with t.span("child", parent=remote):
            raise RuntimeError("boom")
    (rec,) = t.records()
    assert rec.trace_id == remote.trace_id
    assert rec.parent_id == remote.span_id
    assert "RuntimeError: boom" in rec.error


def test_ring_eviction_is_bounded():
    from inspektor_gadget_tpu.telemetry.tracing import _tm_evicted
    before = _tm_evicted.value
    t = Tracer(capacity=10)
    for i in range(35):
        with t.span(f"s{i}"):
            pass
    recs = t.records()
    assert len(recs) == 10
    assert [r.name for r in recs] == [f"s{i}" for i in range(25, 35)]
    assert _tm_evicted.value - before == 25


def test_head_sampling_propagates_but_records_nothing():
    t = Tracer(capacity=64, sample_rate=0.0)
    with t.span("root") as root:
        assert root.context.sampled is False
        with t.span("child") as child:
            # the trace identity still propagates for downstream peers
            assert child.context.trace_id == root.context.trace_id
    assert t.records() == []


# ---------------------------------------------------------------------------
# end-to-end: one trace across client → agent RPC → operators → device plane
# ---------------------------------------------------------------------------

def _sketch_run_ctx(timeout: float) -> GadgetContext:
    desc = get("trace", "exec")
    params = desc.params().to_params()
    params.set("source", "pysynthetic")
    params.set("rate", "20000")
    params.set("batch-size", "256")
    from inspektor_gadget_tpu.operators.operators import get as get_op
    sp = get_op("tpusketch").instance_params().to_params()
    for k, v in (("enable", "true"), ("log2-width", "8"), ("hll-p", "6"),
                 ("entropy-log2-width", "6"), ("topk", "8"),
                 ("harvest-interval", "300ms")):
        sp.set(k, v)
    op_params = Collection()
    op_params["operator.tpusketch."] = sp
    return GadgetContext(desc, gadget_params=params,
                         operator_params=op_params, timeout=timeout)


@pytest.fixture(scope="module")
def agent_node():
    tmp = tempfile.mkdtemp()
    addr = f"unix://{tmp}/agent.sock"
    server, agent = serve(addr, node_name="trace-node")
    # warm the sketch-plane jit for these shapes: under full-suite load a
    # first-touch compile can eat a short run's whole window
    from inspektor_gadget_tpu.runtime.local import LocalRuntime
    LocalRuntime().run_gadget(_sketch_run_ctx(1.0))
    yield {"trace-node": addr}
    server.stop(grace=0.5)


def _run_traced(agents) -> str:
    """Run trace/exec with the sketch plane through the gRPC fan-out;
    returns the minted trace ID. Retries once: under heavy suite load a
    short run can deliver zero events without that being a bug."""
    for attempt in (1, 2):
        ctx = _sketch_run_ctx(timeout=1.2 * attempt)
        runtime = GrpcRuntime(dict(agents))
        events = []
        result = runtime.run_gadget(ctx, on_event=events.append)
        runtime.close()
        assert not result.errors()
        if events:
            return ctx.extra["trace_ctx"].trace_id
    raise AssertionError("no events delivered in two attempts")


def test_one_trace_id_with_correct_parentage_across_grpc_run(agent_node):
    tid = _run_traced(agent_node)
    # the agent's run span closes as its stream generator unwinds, which
    # can lag the client return by a beat
    deadline = time.monotonic() + 5.0
    needed = {"client/run/trace/exec", "client/node/trace-node",
              "agent/RunGadget", "agent/run/trace/exec", "run/trace/exec",
              "op/tpusketch", "tpusketch/h2d", "tpusketch/update",
              "tpusketch/harvest"}
    while time.monotonic() < deadline:
        names = {r.name for r in TRACER.records(trace_id=tid)}
        if needed <= names:
            break
        time.sleep(0.05)
    recs = TRACER.records(trace_id=tid)
    names = {r.name for r in recs}
    assert needed <= names, f"missing {needed - names}"

    # correct parentage: a device-plane span must chain up to the client
    # root through operator chain, agent run, agent RPC, and node spans
    by_id = {r.span_id: r for r in recs}
    update = next(r for r in recs if r.name == "tpusketch/update")
    chain = [update.name]
    r = update
    while r.parent_id:
        r = by_id[r.parent_id]
        chain.append(r.name)
    assert chain == ["tpusketch/update", "op/tpusketch", "run/trace/exec",
                     "agent/run/trace/exec", "agent/RunGadget",
                     "client/node/trace-node", "client/run/trace/exec"]


def test_chrome_trace_export_schema(agent_node):
    tid = _run_traced(agent_node)
    time.sleep(0.3)
    doc = export_chrome(TRACER.records(), trace_id=tid)
    # JSON-serializable and Perfetto-shaped
    parsed = json.loads(json.dumps(doc))
    assert parsed["displayTimeUnit"] == "ms"
    events = parsed["traceEvents"]
    spans = [e for e in events if e["ph"] == "X"]
    meta = [e for e in events if e["ph"] == "M"]
    assert spans and meta
    for e in spans:
        assert {"name", "ph", "cat", "ts", "dur", "pid", "tid",
                "args"} <= set(e)
        assert isinstance(e["ts"], (int, float)) and e["dur"] >= 0
        assert e["args"]["trace_id"] == tid
    # every span's parent_id is resolvable inside the same export
    ids = {e["args"]["span_id"] for e in spans}
    for e in spans:
        assert e["args"]["parent_id"] == "" or e["args"]["parent_id"] in ids
    # metadata names processes for the merged view
    assert any(m["name"] == "process_name" for m in meta)


def test_chrome_trace_contains_pipeline_stage_spans(agent_node):
    """Pipeline health plane (ISSUE 18): each harvest tick renders one
    span per instrumented stage, with the watermark/quantile accounting
    in the span args and the run's trace ID threaded through — so
    `debug trace export` shows a real pipeline timeline."""
    tid = _run_traced(agent_node)
    time.sleep(0.3)
    recs = TRACER.records(trace_id=tid)
    stage = [r for r in recs if r.name.startswith("tpusketch/stage/")]
    names = {r.name for r in stage}
    assert {"tpusketch/stage/pop", "tpusketch/stage/h2d"} <= names, names
    pop = next(r for r in stage if r.name == "tpusketch/stage/pop")
    assert {"watermark_s", "p50_s", "p99_s", "count"} <= set(pop.attrs)
    assert pop.attrs["count"] > 0
    # ring warmup guarantees starved ticks, so the stager span rendered
    stager = next(r for r in stage if r.name == "tpusketch/stage/stager")
    assert stager.attrs["starved"] > 0
    assert 0.0 < stager.attrs["starved_ratio"] <= 1.0
    # stage spans parent under the harvest span of the same trace
    by_id = {r.span_id: r for r in recs}
    assert by_id[pop.parent_id].name == "tpusketch/harvest"
    # and they survive the Chrome export with identity + accounting args
    doc = export_chrome(recs, trace_id=tid)
    spans = [e for e in doc["traceEvents"]
             if e["ph"] == "X" and e["name"].startswith("tpusketch/stage/")]
    assert spans
    for e in spans:
        assert e["args"]["trace_id"] == tid
    pe = next(e for e in spans if e["name"] == "tpusketch/stage/pop")
    assert pe["args"]["count"] > 0 and "watermark_s" in pe["args"]


def test_flight_record_over_dump_state_rpc(agent_node):
    _run_traced(agent_node)
    client = AgentClient(next(iter(agent_node.values())), "trace-node")
    fr = client.flight_record()
    client.close()
    assert fr["pid"] > 0
    assert fr["facts"].get("node")
    assert any(s["name"].startswith("agent/") for s in fr["spans"])
    # the snapshot round-trips through the wire as JSON already
    assert isinstance(fr["logs"], list) and isinstance(fr["errors"], list)


def test_remote_log_lines_carry_run_and_trace_ids(agent_node):
    """A server-side ctx.logger warning must reach the client stream with
    the run/trace IDs threaded through the StreamLogger header."""
    got = []
    client = AgentClient(next(iter(agent_node.values())), "trace-node")
    parent = SpanContext(trace_id="ef" * 16, span_id="12" * 8)
    # a no-target traceloop run fails loudly inside the gadget run; the
    # server's ctx.logger.exception record multiplexes onto the stream
    res = client.run_gadget(
        "traceloop", "traceloop", {}, timeout=2.0,
        on_log=lambda node, sev, msg, hdr: got.append((sev, msg, hdr)),
        trace_ctx=parent,
    )
    client.close()
    assert res["error"] and "target" in res["error"]
    assert got, "no log records multiplexed onto the stream"
    sev, msg, hdr = got[0]
    assert "gadget run failed" in msg
    assert hdr.get("run_id")
    assert hdr.get("trace_id") == parent.trace_id


# ---------------------------------------------------------------------------
# flight recorder: crash dumps
# ---------------------------------------------------------------------------

def test_flight_record_dump_on_simulated_thread_crash(tmp_path):
    rec = FlightRecorder(Tracer(capacity=16))
    rec.set_fact("platform", "cpu")
    with rec.tracer.span("doomed-work"):
        pass
    rec.record_log({"ts": time.time(), "level": "INFO", "logger": "t",
                    "msg": "about to die", "run_id": "", "trace_id": ""})
    path = tmp_path / "flight.json"
    prev = threading.excepthook
    threading.excepthook = lambda args: None  # silence the default printer
    try:
        uninstall = install_crash_handlers(str(path), recorder=rec,
                                           signals=())
        t = threading.Thread(target=lambda: 1 / 0)
        t.start()
        t.join()
        uninstall()
    finally:
        threading.excepthook = prev
    dumped = json.loads(path.read_text())
    assert dumped["facts"]["platform"] == "cpu"
    assert any(s["name"] == "doomed-work" for s in dumped["spans"])
    assert any(l["msg"] == "about to die" for l in dumped["logs"])
    assert any(e["kind"] == "ZeroDivisionError" for e in dumped["errors"])
    assert "1 / 0" in dumped["errors"][-1]["traceback"] or \
        dumped["errors"][-1]["traceback"]


def test_flight_record_dump_on_sigterm(tmp_path):
    """A killed process leaves evidence: SIGTERM → dump, then exit via
    the chained handler. Exercised in a subprocess so the signal's
    process-exit semantics stay real."""
    import subprocess
    import sys
    path = tmp_path / "flight-term.json"
    code = f"""
import os, signal
from inspektor_gadget_tpu.telemetry.tracing import (
    RECORDER, TRACER, install_crash_handlers)
with TRACER.span("pre-kill"):
    pass
RECORDER.set_fact("platform", "cpu")
install_crash_handlers({str(path)!r})
os.kill(os.getpid(), signal.SIGTERM)
"""
    p = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=120)
    assert p.returncode != 0  # terminated, not a clean exit
    dumped = json.loads(path.read_text())
    assert any(s["name"] == "pre-kill" for s in dumped["spans"])
    assert any(e["kind"] == "signal" for e in dumped["errors"])


def test_ig_logger_records_land_in_flight_recorder():
    """telemetry/tracing attaches a handler to the 'ig-tpu' root logger:
    any component's warning is retained for post-mortem reads."""
    marker = f"flight-marker-{time.time_ns()}"
    logging.getLogger("ig-tpu.test-component").warning(marker)
    snap = RECORDER.snapshot()
    assert any(l["msg"] == marker for l in snap["logs"])


# ---------------------------------------------------------------------------
# platform probe (VERDICT hole #1): degrade within the timeout, never hang
# ---------------------------------------------------------------------------

def test_unreachable_tpu_degrades_within_probe_timeout():
    from inspektor_gadget_tpu.utils import platform_probe as pp
    fallbacks_before = pp._tm_fallbacks.value

    def hanging_probe():
        time.sleep(30)  # models PJRT backend init wedging forever
        return pp.ProbeResult(True, "tpu", "", 30.0)

    t0 = time.monotonic()
    out = pp.acquire_platform("auto", timeout=0.3, probe_fn=hanging_probe)
    elapsed = time.monotonic() - t0
    assert elapsed < 5.0, f"probe hung {elapsed:.1f}s past its bound"
    assert out["platform"] == "cpu"
    assert out["degraded"] is True
    assert "timed out" in out["detail"]
    assert pp._tm_fallbacks.value == fallbacks_before + 1
    # the outcome is recorded for doctor + flight recorder
    assert pp.last_acquire()["platform"] == "cpu"
    assert RECORDER.snapshot()["facts"]["platform"] == "cpu"


def test_probe_outcomes():
    from inspektor_gadget_tpu.utils import platform_probe as pp
    # accelerator found: no degrade, platform honored
    out = pp.acquire_platform(
        "auto", timeout=5.0,
        probe_fn=lambda: pp.ProbeResult(True, "tpu", "8 devices", 0.1))
    assert out == {"requested": "auto", "platform": "tpu", "degraded": False,
                   "detail": "8 devices", "elapsed": 0.1}
    # cpu-only host under auto: cpu without counting a fallback
    out = pp.acquire_platform(
        "auto", timeout=5.0,
        probe_fn=lambda: pp.ProbeResult(True, "cpu", "cpu only", 0.1))
    assert out["platform"] == "cpu" and out["degraded"] is False
    # tpu explicitly requested on a cpu-only host IS a degrade
    out = pp.acquire_platform(
        "tpu", timeout=5.0,
        probe_fn=lambda: pp.ProbeResult(True, "cpu", "cpu only", 0.1))
    assert out["platform"] == "cpu" and out["degraded"] is True
    # cpu requested: probe never runs
    calls = []
    out = pp.acquire_platform(
        "cpu", probe_fn=lambda: calls.append(1))
    assert out["platform"] == "cpu" and not calls
    with pytest.raises(ValueError):
        pp.acquire_platform("gpu")


def test_agent_serve_exposes_platform_flag():
    """The agent's arg surface carries --platform auto|tpu|cpu."""
    from inspektor_gadget_tpu.agent.main import main as agent_main
    with pytest.raises(SystemExit) as e:
        agent_main(["serve", "--platform", "gpu"])
    assert e.value.code == 2  # argparse rejects unknown platforms


# ---------------------------------------------------------------------------
# logger satellites
# ---------------------------------------------------------------------------

def test_get_logger_does_not_clobber_configured_level():
    from inspektor_gadget_tpu.utils.logger import DEBUG, get_logger
    name = f"ig-tpu.level-test-{time.time_ns()}"
    first = get_logger(name, DEBUG)
    assert first.level == logging.DEBUG
    # a later caller with the default level must NOT win
    again = get_logger(name)
    assert again is first
    assert again.level == logging.DEBUG


def test_stream_logger_threads_run_and_trace_ids():
    from inspektor_gadget_tpu.utils.logger import WARN, StreamLogger
    pushed = []
    sl = StreamLogger(lambda kind, hdr, payload: pushed.append(
        (kind, hdr, payload)), run_id="r-1", trace_id="t-1")
    sl.warn("careful")
    (kind, hdr, payload) = pushed[0]
    assert kind == WARN << 16
    assert hdr == {"run_id": "r-1", "trace_id": "t-1"}
    assert payload == b"careful"


def test_stream_log_handler_maps_levels():
    from inspektor_gadget_tpu.utils.logger import (
        ERROR, INFO, StreamLogger, StreamLogHandler)
    pushed = []
    handler = StreamLogHandler(StreamLogger(
        lambda kind, hdr, payload: pushed.append((kind >> 16, payload))))
    log = logging.getLogger(f"ig-tpu.slh-{time.time_ns()}")
    log.addHandler(handler)
    log.setLevel(logging.INFO)
    log.info("hello %d", 7)
    log.error("bad")
    log.removeHandler(handler)
    assert (INFO, b"hello 7") in pushed
    assert (ERROR, b"bad") in pushed
