"""Tier-1 lint: every doc throughput claim must be backed by a
ledger/BENCH artifact with matching platform/degraded provenance
(tools/check_perf_claims.py — the drift that produced the round-5
"77.9M ev/s, real TPU" claim from a degraded CPU record becomes a test
failure), plus self-tests that the checker catches each failure mode.
"""

from __future__ import annotations

import json
from pathlib import Path

from tools.check_perf_claims import (
    check_claim,
    check_repo,
    collect_backings,
    extract_claims,
)

ROOT = Path(__file__).resolve().parent.parent


def test_repo_docs_have_backed_claims():
    violations, checked, _waived = check_repo(ROOT)
    assert not violations, "\n".join(violations)
    assert checked > 0, "checker found no claims at all — regex broke?"


def _repo_with(tmp_path, doc: str, bench: dict | None = None):
    (tmp_path / "docs").mkdir(exist_ok=True)
    (tmp_path / "docs" / "performance.md").write_text(doc)
    if bench is not None:
        (tmp_path / "BENCH_r01.json").write_text(json.dumps(bench))
    return tmp_path


TPU_BENCH = {"parsed": {"value": 76.4e6, "unit": "events/sec/chip",
                        "extra": {"platform": "tpu", "degraded": False}}}
CPU_BENCH = {"parsed": {"value": 77.9e6, "unit": "events/sec/chip",
                        "extra": {"platform": "cpu", "degraded": True}}}


def test_backed_claim_passes(tmp_path):
    root = _repo_with(tmp_path, "measured **76.4M ev/s** on TPU\n",
                      TPU_BENCH)
    violations, checked, _ = check_repo(root)
    assert violations == [] and checked == 1


def test_unbacked_claim_fails(tmp_path):
    # 77.9M with only a 76.4M record on disk: the round-5 figure, a
    # near-miss that must NOT count as backed (1% tolerance)
    root = _repo_with(tmp_path, "headline: 77.9M ev/s, real TPU\n",
                      TPU_BENCH)
    violations, _, _ = check_repo(root)
    assert len(violations) == 1
    assert "NO ledger/BENCH artifact" in violations[0]


def test_degraded_backing_must_be_labeled(tmp_path):
    # the exact round-5 failure: a number whose only artifact is a
    # degraded CPU record, presented without saying so
    root = _repo_with(tmp_path, "headline: 77.9M ev/s, real TPU\n",
                      CPU_BENCH)
    violations, _, _ = check_repo(root)
    assert len(violations) == 1
    assert "degraded/CPU" in violations[0]
    # the same number WITH the label passes
    root = _repo_with(tmp_path,
                      "round 5: 77.9M ev/s (degraded CPU fallback)\n",
                      CPU_BENCH)
    violations, _, _ = check_repo(root)
    assert violations == []


def test_targets_and_waivers_skipped(tmp_path):
    doc = ("target: ≥5M ev/s per node\n"
           "observed ~123M ev/s once (unrecorded in-round run)\n")
    root = _repo_with(tmp_path, doc, TPU_BENCH)
    violations, checked, waived = check_repo(root)
    assert violations == [] and checked == 0 and waived == 1


def test_range_claims_match_any_value_inside(tmp_path):
    root = _repo_with(tmp_path, "sustained 51–76M events/sec/chip (TPU)\n",
                      TPU_BENCH)  # 76.4M sits at the top of the range
    violations, checked, _ = check_repo(root)
    assert violations == [] and checked == 1


def test_approx_claims_get_wider_tolerance(tmp_path):
    # ~80M vs a 76.4M artifact: 4.5% — inside the 15% approx band,
    # outside nothing; a plain 80M claim (4.7% off) still passes 5%?
    # no: 80 vs 76.4 is 4.5% of 80 → borderline; use 85M to be clear
    root = _repo_with(tmp_path, "roughly ~85M ev/s\n", TPU_BENCH)
    violations, _, _ = check_repo(root)
    assert violations == []
    root = _repo_with(tmp_path, "exactly 85M ev/s\n", TPU_BENCH)
    violations, _, _ = check_repo(root)
    assert len(violations) == 1


def test_ledger_records_back_claims(tmp_path):
    from inspektor_gadget_tpu.perf import append_record, make_record
    ledger_dir = tmp_path / "benchmarks" / "ledger"
    ledger_dir.mkdir(parents=True)
    rec = make_record(
        config="harness.e2e", metric="m", unit="events/sec/chip",
        value=42e6,
        stages={"fold32": {"ev_per_s": 200e6, "seconds": 0.5}},
        provenance={"git_sha": "abc", "git_dirty": False,
                    "host": {"hostname": "h", "machine": "m",
                             "python": "3"},
                    "platform": "tpu", "degraded": False,
                    "probe": {"outcome": "ok", "attempts": []}})
    append_record(rec, str(ledger_dir / "PERF.jsonl"))
    root = _repo_with(tmp_path,
                      "42M ev/s e2e; fold stage 200M ev/s\n")
    violations, checked, _ = check_repo(root)
    assert violations == [] and checked == 2


def test_extract_claims_shapes():
    claims = extract_claims(
        "a 5.1-6.0M ev/s b ~2.8B events/sec/chip c ≥5M ev/s d "
        "130.5M ev/s", "f.md")
    by_text = {c.text.strip(): c for c in claims}
    rng = by_text["5.1-6.0M ev/s"]
    assert (rng.lo, rng.hi) == (5.1e6, 6.0e6)
    assert by_text["~2.8B events/sec"].approx  # match stops at /sec
    assert [c for c in claims if c.skipped and "target" in c.skipped]
    assert by_text["130.5M ev/s"].lo == 130.5e6


def _fleet_ledger(tmp_path):
    from inspektor_gadget_tpu.perf import append_record, make_record
    ledger_dir = tmp_path / "benchmarks" / "ledger"
    ledger_dir.mkdir(parents=True)
    rec = make_record(
        config="fleet-merge-tree", metric="query_agents100",
        unit="queries/s", value=30.0,
        stages={"tree_fold": {"seconds": 0.03, "events": 100.0}},
        provenance={"git_sha": "abc", "git_dirty": False,
                    "host": {"hostname": "h", "machine": "m",
                             "python": "3"},
                    "platform": "cpu", "degraded": False,
                    "probe": {"outcome": "ok", "attempts": []}},
        extra={"wire_windows": 134, "client_link_windows": 2})
    append_record(rec, str(ledger_dir / "PERF.jsonl"))


def test_wire_window_claims_backed_by_fleet_ledger(tmp_path):
    # ISSUE 20: "N window-frame(s)" counts are structural facts matched
    # exactly against extra.wire_windows / client_link_windows — a CPU
    # record backs them without the degraded label (topology, not speed)
    _fleet_ledger(tmp_path)
    root = _repo_with(tmp_path,
                      "the client link folds 2 window-frames; the tree "
                      "moves 134 window-frames total\n")
    violations, checked, _ = check_repo(root)
    assert violations == [] and checked == 2
    root = _repo_with(tmp_path, "the tree moves 133 window-frames\n")
    violations, _, _ = check_repo(root)
    assert len(violations) == 1 and "NO ledger" in violations[0]


def test_observability_doc_scanned_for_wire_claims_only(tmp_path):
    # docs/observability.md quotes the fictional round-5 "77.9M ev/s"
    # in prose, so it joins the scan for wire counts ONLY
    _fleet_ledger(tmp_path)
    _repo_with(tmp_path, "no claims here\n")
    (tmp_path / "docs" / "observability.md").write_text(
        'the incident: "77.9M ev/s, real TPU"\n'
        "the fleet root folds 7 window-frames\n")
    violations, _, _ = check_repo(tmp_path)
    assert len(violations) == 1
    assert "window-frame" in violations[0]  # ev/s prose NOT flagged
    (tmp_path / "docs" / "observability.md").write_text(
        "the fleet root folds 2 window-frames\n")
    violations, _, _ = check_repo(tmp_path)
    assert violations == []


def test_check_claim_nearest_hint(tmp_path):
    root = _repo_with(tmp_path, "x\n", TPU_BENCH)
    backings = collect_backings(root)
    claims = extract_claims("we do 999M ev/s\n", "f.md")
    msg = check_claim(claims[0], backings)
    assert "nearest artifact value" in msg
