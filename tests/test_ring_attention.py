"""Ring/Ulysses/blockwise attention + sequence anomaly scorer tests.

Runs on the 8-device virtual CPU mesh (conftest.py). Equivalence tests
pin fp32 so streaming-softmax accumulation differences stay ~1e-5.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from inspektor_gadget_tpu.models.seqmodel import (
    SeqConfig, make_sp_train_step, seq_init, seq_loss, seq_score,
    seq_train_step, tokens_from_keys,
)
from inspektor_gadget_tpu.parallel.ring_attention import (
    blockwise_attention, full_attention, make_ring_attention,
)

B, T, H, D = 2, 256, 4, 16


def _qkv(seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, T, H, D)), dtype)
    return mk(), mk(), mk()


def _seq_mesh(n=4):
    return Mesh(np.array(jax.devices()[:n]), ("seq",))


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_matches_full(causal):
    q, k, v = _qkv()
    ref = full_attention(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, causal=causal, chunk=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_sharded_attention_matches_full(impl, causal):
    q, k, v = _qkv()
    ref = full_attention(q, k, v, causal=causal)
    fn = make_ring_attention(_seq_mesh(), causal=causal, impl=impl)
    out = fn(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_eight_way():
    q, k, v = _qkv(seed=3)
    ref = full_attention(q, k, v, causal=True)
    fn = make_ring_attention(Mesh(np.array(jax.devices()), ("seq",)),
                             causal=True, impl="ring")
    np.testing.assert_allclose(np.asarray(fn(q, k, v)), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def _cfg():
    return SeqConfig(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                     dtype=jnp.float32)


def test_seq_model_trains():
    cfg = _cfg()
    scorer = seq_init(cfg, seed=0)
    rng = np.random.default_rng(0)
    # learnable structure: ascending mod-vocab runs
    starts = rng.integers(0, 64, size=(8, 1))
    toks = jnp.asarray((starts + np.arange(65)) % 64, np.int32)
    first = float(seq_loss(scorer.params, toks, cfg))
    for _ in range(200):
        scorer, loss = seq_train_step(scorer, toks)
    assert float(loss) < first * 0.4, (first, float(loss))


def test_seq_score_flags_shuffled_sequences():
    cfg = _cfg()
    scorer = seq_init(cfg, seed=0)
    rng = np.random.default_rng(1)
    starts = rng.integers(0, 64, size=(16, 1))
    normal = (starts + np.arange(65)) % 64
    for _ in range(80):
        scorer, _ = seq_train_step(scorer, jnp.asarray(normal, np.int32))
    weird = normal.copy()
    for row in weird:
        rng.shuffle(row)
    s_norm = np.asarray(seq_score(scorer, jnp.asarray(normal, np.int32)))
    s_weird = np.asarray(seq_score(scorer, jnp.asarray(weird, np.int32)))
    assert s_weird.mean() > s_norm.mean() * 1.5


@pytest.mark.parametrize("attn", ["ring", "ulysses"])
def test_sp_train_step_matches_single_device(attn):
    cfg = _cfg()
    mesh = _seq_mesh(4)
    scorer = seq_init(cfg, seed=0)
    rng = np.random.default_rng(2)
    toks = jnp.asarray(rng.integers(0, 64, size=(2, 128)), np.int32)

    # single-device reference first: sp_step donates params/opt_state
    ref_loss = seq_loss(scorer.params, toks, cfg)
    ref_scorer, _ = seq_train_step(seq_init(cfg, seed=0), toks)

    sp_step = make_sp_train_step(mesh, cfg, attn=attn)
    p_sp, o_sp, loss_sp = sp_step(scorer.params, scorer.opt_state, toks)
    # SP loss masks only the final global position, like seq_loss's shift
    np.testing.assert_allclose(float(loss_sp), float(ref_loss),
                               rtol=1e-4, atol=1e-5)
    flat_sp = jax.tree.leaves(p_sp)
    flat_ref = jax.tree.leaves(ref_scorer.params)
    for a, b in zip(flat_sp, flat_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-4)


def test_tokens_from_keys():
    keys = np.array([1, 513, 2**40 + 7], dtype=np.uint64)
    t = tokens_from_keys(keys, 512)
    assert t.dtype == np.int32
    assert list(t) == [1, 1, int((2**40 + 7) % 512)]


def test_blockwise_backend_handles_non_divisible_length():
    """seq_score trims to T-1 (e.g. 255): chunk choice must still divide."""
    cfg = _cfg()
    scorer = seq_init(cfg, seed=0)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 256)),
                       np.int32)
    out = np.asarray(seq_score(scorer, toks, attn="blockwise"))
    ref = np.asarray(seq_score(scorer, toks, attn="full"))
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)
