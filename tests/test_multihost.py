"""Multi-host distributed tests: two real OS processes join a
jax.distributed world over a TCP coordinator and psum-merge sketch state
across process boundaries — the framework's analogue of the reference's
cluster-integration tier (SURVEY §4: envtest / kind clusters), standing in
for multi-host TPU pods on CPU devices.
"""

import json
import socket
import subprocess
import sys
import textwrap

import pytest

WORKER = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, os.getcwd())  # repo root (cwd set by the test)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")

    coord, pid = sys.argv[1], int(sys.argv[2])
    # version drift (shard_map home + check flag) is resolved in ONE
    # place now: the parallel.compat shim (ISSUE 14 satellite)
    from inspektor_gadget_tpu.parallel.compat import shard_map
    _smkw = {"check_vma": False}
    from inspektor_gadget_tpu.parallel.distributed import (
        init_distributed, make_multihost_mesh, world_size,
    )
    init_distributed(coord, num_processes=2, process_id=pid)
    assert world_size() == 2, world_size()

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from inspektor_gadget_tpu.ops import (
        bundle_init, bundle_update, hll_estimate,
    )
    from inspektor_gadget_tpu.parallel.cluster import cluster_merge
    from inspektor_gadget_tpu.parallel.mesh import NODE_AXIS

    mesh = make_multihost_mesh()
    assert mesh.shape[NODE_AXIS] == 4  # 2 procs x 2 virtual devices

    # each process contributes a disjoint key range; after the psum merge
    # every process must see the union's statistics
    def node_update(keys):
        keys = keys.reshape(-1)  # local shard arrives as [1, per_node]
        b = bundle_init(depth=4, log2_width=10, hll_p=8,
                        entropy_log2_width=7, k=16)
        b = bundle_update(b, keys, keys, keys, jnp.ones(keys.shape, bool))
        # cluster_merge takes the sharded-state convention: leading node axis
        return cluster_merge(jax.tree.map(lambda x: x[None], b))

    per_node = 512
    rng = np.random.default_rng(0)
    all_keys = rng.integers(1, 2**31, (4, per_node), dtype=np.int64)
    global_keys = jnp.asarray(all_keys.astype(np.uint32))

    step = jax.jit(shard_map(
        node_update, mesh=mesh, in_specs=P(NODE_AXIS), out_specs=P(),
        **_smkw))
    sharding = NamedSharding(mesh, P(NODE_AXIS))
    garr = jax.make_array_from_process_local_data(sharding, np.asarray(
        all_keys.astype(np.uint32))[pid * 2:(pid + 1) * 2])
    try:
        merged = step(garr)
    except Exception as e:
        if "Multiprocess computations aren't implemented" in str(e):
            # this jaxlib's CPU backend cannot run cross-process
            # collectives at all — an environment limitation, not a bug
            print(json.dumps({"skip": str(e)}), flush=True)
            sys.exit(0)
        raise
    # out_specs=P() -> replicated result; read this process's local shards
    local = jax.tree.map(lambda a: a.addressable_shards[0].data, merged)
    est = float(hll_estimate(local.hll))
    events = float(local.events)
    true_card = len(set(all_keys.reshape(-1).tolist()))
    print(json.dumps({"pid": pid, "events": events, "est": est,
                      "true": true_card}))
""")


# Documented budget for a cluster merge racing fixed-rate local ingest in
# the 4-process world (docs/performance.md "cross-process merge" rows):
# everything shares ONE contended CPU core in CI, so the budget carries
# that contention factor rather than pretending each proc owns a core.
MERGE_UNDER_INGEST_P95_BUDGET_MS = 2500.0


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


ELASTIC_WORKER = textwrap.dedent("""
    import json, os, sys, threading, time
    sys.path.insert(0, os.getcwd())
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")

    coord_a, coord_b, pid, tmpdir = (
        sys.argv[1], sys.argv[2], int(sys.argv[3]), sys.argv[4])
    # version drift (shard_map home + check flag) is resolved in ONE
    # place now: the parallel.compat shim (ISSUE 14 satellite)
    from inspektor_gadget_tpu.parallel.compat import shard_map
    _smkw = {"check_vma": False}
    from inspektor_gadget_tpu.parallel.distributed import (
        init_distributed, make_multihost_mesh, world_size,
    )
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from inspektor_gadget_tpu.ops import bundle_init, bundle_update
    from inspektor_gadget_tpu.parallel.cluster import cluster_merge
    from inspektor_gadget_tpu.parallel.mesh import NODE_AXIS

    SHAPE = dict(depth=4, log2_width=10, hll_p=8, entropy_log2_width=7,
                 k=16)
    PER_PROC = 512

    def local_keys_np(seed, n=PER_PROC):
        rng = np.random.default_rng(seed)
        return rng.integers(1, 2**31, n, dtype=np.int64).astype(np.uint32)

    def merge_world(n_procs, bundle, ingest_hz=0):
        '''Stack [bundle, empty] per process (empty is merge-neutral) and
        psum over the node axis; returns (merged_events, p50_ms, stats).
        ingest_hz > 0 additionally times the merge ticks WHILE a local
        ingest thread runs bundle_update at that fixed batch rate — the
        contention the production agent lives under (VERDICT #5).'''
        mesh = make_multihost_mesh()
        assert mesh.shape[NODE_AXIS] == 2 * n_procs, mesh.shape
        empty = bundle_init(**SHAPE)
        stacked = jax.tree.map(lambda a, b: np.stack([np.asarray(a),
                                                      np.asarray(b)]),
                               bundle, empty)
        sharding = NamedSharding(mesh, P(NODE_AXIS))
        garr = jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(sharding, x),
            stacked)
        step = jax.jit(shard_map(
            cluster_merge, mesh=mesh, in_specs=P(NODE_AXIS), out_specs=P(),
            **_smkw))
        merged = step(garr)
        jax.block_until_ready(merged.events)

        def timed_ticks(n):
            ticks = []
            for _ in range(n):
                t0 = time.perf_counter()
                jax.block_until_ready(step(garr).events)
                ticks.append((time.perf_counter() - t0) * 1000.0)
            return ticks

        idle = timed_ticks(10)
        stats = {}
        if ingest_hz:
            # fixed-rate local ingest (batches of PER_PROC keys) racing
            # the cluster merges — bundle_update at this shape is already
            # compiled, so the thread contends on compute, not compile
            stop = threading.Event()
            counted = [0]

            def ingest_loop():
                contend = bundle_init(**SHAPE)
                period = 1.0 / ingest_hz
                while not stop.is_set():
                    t0 = time.perf_counter()
                    k = jnp.asarray(local_keys_np(5000 + counted[0]))
                    contend = bundle_update(
                        contend, k, k, k, jnp.ones(k.shape, bool))
                    jax.block_until_ready(contend.events)
                    counted[0] += 1
                    left = period - (time.perf_counter() - t0)
                    if left > 0:
                        stop.wait(left)

            t = threading.Thread(target=ingest_loop, daemon=True)
            t.start()
            time.sleep(0.05)  # let the ingest loop reach steady state
            under = timed_ticks(10)
            stop.set()
            t.join(timeout=10)
            stats = {
                "merge_under_ingest_p50_ms":
                    float(np.percentile(under, 50)),
                "merge_under_ingest_p95_ms":
                    float(np.percentile(under, 95)),
                "merge_idle_p95_ms": float(np.percentile(idle, 95)),
                "ingest_batches": counted[0],
                "ingest_hz": ingest_hz,
            }
        local_m = jax.tree.map(lambda a: a.addressable_shards[0].data, merged)
        return (float(local_m.events), float(np.percentile(idle, 50)),
                stats)

    # the world must exist BEFORE any jax computation (backends snapshot
    # the distributed config at creation)
    init_distributed(coord_a, num_processes=4, process_id=pid)
    assert world_size() == 4

    # per-PROCESS local state, retained across world re-formation — the
    # role of pinned maps surviving restarts, at the collective tier
    local = bundle_init(**SHAPE)
    k = jnp.asarray(local_keys_np(100 + pid))
    local = bundle_update(local, k, k, k, jnp.ones(k.shape, bool))

    try:
        events1, p50_1, contention = merge_world(4, local, ingest_hz=50)
    except Exception as e:
        if "Multiprocess computations aren't implemented" in str(e):
            print(json.dumps({"phase": 1, "pid": pid, "skip": str(e)}),
                  flush=True)
            sys.exit(0)
        raise
    print(json.dumps({"phase": 1, "pid": pid, "merged_events": events1,
                      "merge_p50_ms": p50_1, **contention}), flush=True)

    # host-offload, tear the world down, forget its backend (survivor
    # restart semantics: state lives on the host between worlds)
    local_np = jax.tree.map(np.asarray, local)
    jax.distributed.shutdown()
    import jax.extend.backend as jeb
    jeb.clear_backends()

    # keep ingesting (host-side) while waiting; the kill lands here
    go2 = os.path.join(tmpdir, "phase2_go")
    extra_batches = []
    while not os.path.exists(go2):
        if len(extra_batches) < 20:
            extra_batches.append(
                local_keys_np(1000 + pid * 31 + len(extra_batches), 64))
        time.sleep(0.05)

    # survivors re-form a 3-process world and merge their retained state
    init_distributed(coord_b, num_processes=3, process_id=pid)
    local = jax.tree.map(jnp.asarray, local_np)
    for kb in extra_batches:
        k = jnp.asarray(kb)
        local = bundle_update(local, k, k, k, jnp.ones(k.shape, bool))
    assert world_size() == 3
    events2, p50_2, _ = merge_world(3, local)
    print(json.dumps({"phase": 2, "pid": pid,
                      "local_events": float(local.events),
                      "merged_events": events2,
                      "merge_p50_ms": p50_2}), flush=True)
""")


def test_two_process_sketch_merge(tmp_path):
    coord = f"127.0.0.1:{_free_port()}"
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coord, str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd="/root/repo")
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=220)
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        line = [ln for ln in out.splitlines() if ln.startswith("{")][-1]
        outs.append(json.loads(line))
    skips = [o for o in outs if "skip" in o]
    if skips:
        pytest.skip(f"backend cannot run multiprocess collectives: "
                    f"{skips[0]['skip']}")
    # both processes observed the full 4-node union
    for o in outs:
        assert o["events"] == 4 * 512, o
        assert abs(o["est"] - o["true"]) / o["true"] < 0.1, o


def test_four_process_kill_one_and_remerge(tmp_path):
    """The deepened tier (VERDICT r4 item 8): a 4-process world merges and
    reports cross-process merge timing; one worker is SIGKILLed mid-ingest;
    the surviving three re-form a smaller world and their merge preserves
    every survivor's retained counts (node-failure semantics at the
    collective tier — per-node error isolation, runtime.go:42-79, where
    the 'partial result' is the survivors' union)."""
    import json as _json
    import os
    import signal
    import time

    coord_a = f"127.0.0.1:{_free_port()}"
    coord_b = f"127.0.0.1:{_free_port()}"
    script = tmp_path / "elastic_worker.py"
    script.write_text(ELASTIC_WORKER)
    # stderr goes to files: an undrained stderr PIPE deadlocks a chatty
    # worker at the ~64KB pipe buffer
    err_files = [open(tmp_path / f"worker{i}.err", "w+") for i in range(4)]
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coord_a, coord_b, str(i),
             str(tmp_path)],
            stdout=subprocess.PIPE, stderr=err_files[i], text=True,
            cwd="/root/repo")
        for i in range(4)
    ]

    def worker_stderr(i: int) -> str:
        err_files[i].flush()
        err_files[i].seek(0)
        return err_files[i].read()[-3000:]

    def check_alive(expected: set):
        for i in expected:
            if procs[i].poll() not in (None, 0):
                raise AssertionError(
                    f"worker {i} died early: {worker_stderr(i)}")

    try:
        # wait for phase 1 from every worker (read incrementally so the
        # pipes don't fill)
        phase1 = {}
        deadline = time.time() + 360
        import selectors
        sel = selectors.DefaultSelector()
        for i, p in enumerate(procs):
            os.set_blocking(p.stdout.fileno(), False)
            sel.register(p.stdout, selectors.EVENT_READ, i)
        while len(phase1) < 4 and time.time() < deadline:
            for key, _ in sel.select(timeout=1.0):
                chunk = key.fileobj.readline()
                while chunk:
                    if chunk.startswith("{"):
                        rec = _json.loads(chunk)
                        if rec.get("phase") == 1:
                            phase1[key.data] = rec
                    chunk = key.fileobj.readline()
            check_alive({0, 1, 2, 3})
        skips = [r for r in phase1.values() if "skip" in r]
        if skips:
            pytest.skip(f"backend cannot run multiprocess collectives: "
                        f"{skips[0]['skip']}")
        assert len(phase1) == 4, f"phase1 incomplete: {phase1}"
        # 4 procs x 512 keys each, merged across the world
        for rec in phase1.values():
            assert rec["merged_events"] == 4 * 512, rec
        p50_4proc = phase1[0]["merge_p50_ms"]

        # merge-under-ingest contention (VERDICT #5): the merges were
        # timed WHILE every worker ingested at a fixed 50 Hz batch rate;
        # the ingest threads must have made real progress, and the
        # contended p95 stays inside the documented budget (the 1-core
        # contention factor is part of that budget — see
        # MERGE_UNDER_INGEST_P95_BUDGET_MS and docs/performance.md)
        for rec in phase1.values():
            assert rec["ingest_batches"] > 0, (
                "ingest thread starved out entirely during merges", rec)
            assert (rec["merge_under_ingest_p95_ms"]
                    <= MERGE_UNDER_INGEST_P95_BUDGET_MS), rec
        print("merge under 50Hz ingest: p50 "
              f"{phase1[0]['merge_under_ingest_p50_ms']:.1f} ms, p95 "
              f"{phase1[0]['merge_under_ingest_p95_ms']:.1f} ms "
              f"(idle p50 {p50_4proc:.1f} ms, idle p95 "
              f"{phase1[0]['merge_idle_p95_ms']:.1f} ms; "
              f"{phase1[0]['ingest_batches']} batches ingested)")

        # SIGKILL worker 3 mid-ingest, then release the survivors; its
        # EOF'd pipe must leave the selector or select() busy-spins
        procs[3].send_signal(signal.SIGKILL)
        procs[3].wait(timeout=10)
        sel.unregister(procs[3].stdout)
        (tmp_path / "phase2_go").write_text("go")

        phase2 = {}
        deadline = time.time() + 360
        while len(phase2) < 3 and time.time() < deadline:
            for key, _ in sel.select(timeout=1.0):
                chunk = key.fileobj.readline()
                while chunk:
                    if chunk.startswith("{"):
                        rec = _json.loads(chunk)
                        if rec.get("phase") == 2:
                            phase2[key.data] = rec
                    chunk = key.fileobj.readline()
            check_alive({0, 1, 2})
        assert len(phase2) == 3, f"phase2 incomplete: {phase2}"
        survivors_local = sum(r["local_events"] for r in phase2.values())
        for rec in phase2.values():
            # the re-formed merge carries EVERY survivor's retained counts
            assert rec["merged_events"] == survivors_local, (
                rec, survivors_local)
            assert rec["local_events"] >= 512  # pre-kill state not lost
        print(f"cross-process merge p50: 4-proc {p50_4proc:.2f} ms, "
              f"3-proc {phase2[0]['merge_p50_ms']:.2f} ms")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                pass
        for f in err_files:
            f.close()
