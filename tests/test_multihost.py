"""Multi-host distributed tests: two real OS processes join a
jax.distributed world over a TCP coordinator and psum-merge sketch state
across process boundaries — the framework's analogue of the reference's
cluster-integration tier (SURVEY §4: envtest / kind clusters), standing in
for multi-host TPU pods on CPU devices.
"""

import json
import socket
import subprocess
import sys
import textwrap

import pytest

WORKER = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, os.getcwd())  # repo root (cwd set by the test)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")

    coord, pid = sys.argv[1], int(sys.argv[2])
    from inspektor_gadget_tpu.parallel.distributed import (
        init_distributed, make_multihost_mesh, world_size,
    )
    init_distributed(coord, num_processes=2, process_id=pid)
    assert world_size() == 2, world_size()

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from inspektor_gadget_tpu.ops import (
        bundle_init, bundle_update, hll_estimate,
    )
    from inspektor_gadget_tpu.parallel.cluster import cluster_merge
    from inspektor_gadget_tpu.parallel.mesh import NODE_AXIS

    mesh = make_multihost_mesh()
    assert mesh.shape[NODE_AXIS] == 4  # 2 procs x 2 virtual devices

    # each process contributes a disjoint key range; after the psum merge
    # every process must see the union's statistics
    def node_update(keys):
        keys = keys.reshape(-1)  # local shard arrives as [1, per_node]
        b = bundle_init(depth=4, log2_width=10, hll_p=8,
                        entropy_log2_width=7, k=16)
        b = bundle_update(b, keys, keys, keys, jnp.ones(keys.shape, bool))
        # cluster_merge takes the sharded-state convention: leading node axis
        return cluster_merge(jax.tree.map(lambda x: x[None], b))

    per_node = 512
    rng = np.random.default_rng(0)
    all_keys = rng.integers(1, 2**31, (4, per_node), dtype=np.int64)
    global_keys = jnp.asarray(all_keys.astype(np.uint32))

    step = jax.jit(jax.shard_map(
        node_update, mesh=mesh, in_specs=P(NODE_AXIS), out_specs=P(),
        check_vma=False))
    sharding = NamedSharding(mesh, P(NODE_AXIS))
    garr = jax.make_array_from_process_local_data(sharding, np.asarray(
        all_keys.astype(np.uint32))[pid * 2:(pid + 1) * 2])
    merged = step(garr)
    # out_specs=P() -> replicated result; read this process's local shards
    local = jax.tree.map(lambda a: a.addressable_shards[0].data, merged)
    est = float(hll_estimate(local.hll))
    events = float(local.events)
    true_card = len(set(all_keys.reshape(-1).tolist()))
    print(json.dumps({"pid": pid, "events": events, "est": est,
                      "true": true_card}))
""")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_sketch_merge(tmp_path):
    coord = f"127.0.0.1:{_free_port()}"
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coord, str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd="/root/repo")
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=220)
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        line = [ln for ln in out.splitlines() if ln.startswith("{")][-1]
        outs.append(json.loads(line))
    # both processes observed the full 4-node union
    for o in outs:
        assert o["events"] == 4 * 512, o
        assert abs(o["est"] - o["true"]) / o["true"] < 0.1, o
