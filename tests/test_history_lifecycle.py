"""Tiered history lifecycle (ISSUE 13): resolution schedules,
time-decayed compaction, archive offload/rehydration, query pushdown,
and the pagination/accounting satellites.

The fast tier of the subsystem: everything here runs on synthetic
sealed windows (no gadget runs, no jax device work) so the crash,
interleaving, and exactness disciplines are pinned cheaply;
tests/test_history_tiers_e2e.py drives the same machinery through real
agents and the tpusketch sealer.
"""

from __future__ import annotations

import math
import os
import threading

import numpy as np
import pytest

from inspektor_gadget_tpu.history import (
    ARCHIVE_MANIFEST,
    ArchiveTier,
    CompactionEngine,
    FilesystemArchive,
    HistoryStore,
    SealedWindow,
    answer_query,
    decode_frames,
    dedupe_compacted,
    level_counts,
    merge_windows,
    parse_schedule,
    window_digest,
)
from inspektor_gadget_tpu.history.lifecycle import (
    DEFAULT_SCHEDULE,
    _tm_compactions,
    _tm_reclaimed,
)
from inspektor_gadget_tpu.history.store import HISTORY_METRICS

T0 = 1_000_000.0
FUTURE = T0 + 10_000_000.0


def _window(i: int, *, node="lcnode", gadget="trace/lc", span=10.0,
            events=100, depth=4, width=64, seed=None) -> SealedWindow:
    rng = np.random.default_rng(i if seed is None else seed)
    win = SealedWindow(
        gadget=gadget, node=node, run_id="r1", window=i + 1,
        start_ts=T0 + i * span, end_ts=T0 + (i + 1) * span,
        events=events, drops=i % 3,
        cms=rng.integers(0, 50, (depth, width)).astype(np.int32),
        hll=rng.integers(0, 6, 256).astype(np.int32),
        # integer-valued float32 buckets: sums stay exact under any
        # association, so compaction equality asserts can be exact
        ent=rng.integers(0, 20, 64).astype(np.float32),
        topk_keys=rng.integers(1, 1 << 31, 8).astype(np.uint32),
        topk_counts=rng.integers(1, 100, 8).astype(np.int64),
        slices={f"mntns:{100 + i % 2}": {
            "events": 10, "hll": rng.integers(0, 3, 256).astype(np.uint8),
            "ent": rng.integers(0, 5, 64).astype(np.int64),
            "hh": [(int(i) + 1, 3)]}},
    )
    win.digest = window_digest(win)
    return win


def _seed_store(tmp_path, n=12, *, node="lcnode", rotate_every=None,
                **writer_kw):
    store = HistoryStore()
    base = str(tmp_path / "hist")
    store.set_base_dir(base)
    writer = store.writer_for("trace-lc", node=node, base_dir=base,
                              **writer_kw)
    for i in range(n):
        store.append_window(_window(i, node=node), writer=writer)
        if rotate_every and (i + 1) % rotate_every == 0:
            writer.rotate()
    writer.rotate()
    return store, base, os.path.join(base, f"{node}--trace-lc")


def _ground_truth(store, base):
    frames = list(store.fetch_windows(base_dir=base, gadget="trace/lc"))
    return merge_windows(decode_frames(frames))


def _fold(store, base):
    frames = list(store.fetch_windows(base_dir=base, gadget="trace/lc"))
    kept, notes = dedupe_compacted(decode_frames(frames))
    return merge_windows(kept), kept, notes


def _assert_fold_equals(merged, truth):
    assert merged.events == truth.events
    assert merged.drops == truth.drops
    assert np.array_equal(merged.cms, truth.cms)
    assert np.array_equal(merged.hll, truth.hll)
    assert np.array_equal(merged.ent, truth.ent)
    assert merged.candidates == truth.candidates
    for skey, s in truth.slices.items():
        assert merged.slices[skey]["events"] == s["events"]
        assert np.array_equal(merged.slices[skey]["hll"], s["hll"])


# ---------------------------------------------------------------------------
# Resolution schedule grammar
# ---------------------------------------------------------------------------

def test_schedule_grammar_accepts_documented_forms():
    levels = parse_schedule("1m@24h,10m@7d,1h@inf")
    assert [lvl.resolution for lvl in levels] == [60.0, 600.0, 3600.0]
    assert levels[1].horizon == 7 * 86400.0
    assert math.isinf(levels[-1].horizon)
    # the unicode infinity and day+duration composites parse too
    levels = parse_schedule("30s@5m, 5m@1d12h, 1h@∞")
    assert levels[1].horizon == 86400.0 + 12 * 3600.0
    # the default the params layer ships must itself be valid AND match
    # the operator's copy (kept literal there to avoid an import cycle)
    from inspektor_gadget_tpu.operators.tpusketch import _DEFAULT_SCHEDULE
    assert _DEFAULT_SCHEDULE == DEFAULT_SCHEDULE
    parse_schedule(DEFAULT_SCHEDULE)


@pytest.mark.parametrize("spec,frag", [
    ("", "empty"),
    ("1m", "not <resolution>@<horizon>"),
    ("1m@", "not <resolution>@<horizon>"),
    ("@1h", "not <resolution>@<horizon>"),
    ("banana@1h", "invalid duration"),
    ("0s@1h,1m@inf", "resolution must be a finite positive"),
    ("inf@1h,1m@inf", "resolution must be a finite positive"),
    ("10m@1h,1m@2h,1h@inf", "strictly coarsen"),
    ("1m@2h,10m@1h,1h@inf", "strictly grow"),
    ("1m@24h,10m@7d", "last horizon must be inf"),
    ("1m@inf,10m@inf", "strictly grow"),
])
def test_schedule_grammar_is_loud(spec, frag):
    with pytest.raises(ValueError):
        parse_schedule(spec)
    try:
        parse_schedule(spec)
    except ValueError as e:
        assert frag in str(e), (spec, str(e))


def test_history_params_validated_loudly():
    """The params layer refuses a bad schedule / cache budget BEFORE a
    run starts (the stop-result-timeout pattern)."""
    from inspektor_gadget_tpu.operators import operators as op_registry
    from inspektor_gadget_tpu.params import ParamError
    sp = op_registry.get("tpusketch").instance_params().to_params()
    with pytest.raises(ParamError, match="history-schedule"):
        sp.set("history-schedule", "10m@1h,1m@2h")
    with pytest.raises(ParamError, match="history-archive-cache-bytes"):
        sp.set("history-archive-cache-bytes", "12")
    sp.set("history-schedule", "30s@10m,10m@inf")  # good one sticks
    sp.set("history-compact", "true")
    with pytest.raises(ParamError):
        sp.set("history-compact", "maybe")


# ---------------------------------------------------------------------------
# Compaction: exactness, provenance, crash discipline
# ---------------------------------------------------------------------------

def test_compaction_folds_exactly_and_audits_provenance(tmp_path):
    store, base, store_dir = _seed_store(tmp_path, n=12)
    truth = _ground_truth(store, base)
    before = sum(os.path.getsize(os.path.join(store_dir, f))
                 for f in os.listdir(store_dir) if f.startswith("seg-"))
    c0 = _tm_compactions.value
    r0 = _tm_reclaimed.value
    g0 = HISTORY_METRICS.gc.value

    engine = CompactionEngine("10s@1m,60s@1d,600s@inf", store=store,
                              clock=lambda: FUTURE)
    stats = engine.compact_store(store_dir)
    assert stats["source_windows"] == 12
    # 120s of data in 60s buckets (T0 is not bucket-aligned: 3 buckets)
    assert stats["super_windows"] == 3
    assert stats["segments_deleted"] >= 1
    assert stats["levels"] == {1: 3}
    # byte footprint shrinks; reclaim accounted; retention GC untouched
    after = sum(os.path.getsize(os.path.join(store_dir, f))
                for f in os.listdir(store_dir) if f.startswith("seg-"))
    assert after < before
    assert _tm_compactions.value == c0 + 1
    assert _tm_reclaimed.value - r0 == stats["bytes_reclaimed"] > 0
    assert HISTORY_METRICS.gc.value == g0

    merged, kept, notes = _fold(store, base)
    assert notes == []
    assert {w.level for w in kept} == {1}
    assert level_counts(kept) == {1: 3}
    _assert_fold_equals(merged, truth)
    # provenance audit: every source window's digest (and its seq/ts
    # coverage) lands in EXACTLY one super-window
    seen: dict[str, int] = {}
    spans = []
    for w in kept:
        for row in w.compacted_from:
            seen[row["digest"]] = seen.get(row["digest"], 0) + 1
            spans.append((row["start_ts"], row["end_ts"]))
            assert row["seq"] > 0 and row["level"] == 0
    assert sorted(seen.values()) == [1] * 12
    assert min(s for s, _ in spans) == T0
    assert max(e for _, e in spans) == T0 + 120.0


def test_compaction_ladder_reaches_final_level(tmp_path):
    store, base, store_dir = _seed_store(tmp_path, n=12)
    truth = _ground_truth(store, base)
    engine = CompactionEngine("10s@1m,60s@1d,600s@inf", store=store,
                              clock=lambda: FUTURE)
    engine.compact_store(store_dir)   # L0 -> L1
    engine.compact_store(store_dir)   # L1 (aged past 1d) -> L2
    merged, kept, _ = _fold(store, base)
    assert {w.level for w in kept} == {2}
    assert len(kept) == 1             # 120s fits one 600s bucket
    _assert_fold_equals(merged, truth)
    # the final level never self-compacts: a third pass is a no-op
    stats = engine.compact_store(store_dir)
    assert stats["super_windows"] == 0 and stats["segments_deleted"] == 0


def test_active_segment_and_young_windows_are_never_compacted(tmp_path):
    store, base, store_dir = _seed_store(tmp_path, n=6, rotate_every=3)
    # 3 sealed old windows + 3 sealed young + unsealed appends on top
    writer = store.writer_for_dir(store_dir)
    store.append_window(_window(99, seed=99), writer=writer)  # active seg
    young_cut = T0 + 3 * 10.0
    engine = CompactionEngine(
        "10s@1m,60s@inf", store=store,
        clock=lambda: young_cut + 61.0)  # only windows 1..3 aged > 1m
    stats = engine.compact_store(store_dir)
    assert stats["source_windows"] == 3
    merged, kept, _ = _fold(store, base)
    levels = level_counts(kept)
    # 3 aged sources -> 2 super-windows (bucket split); 3 young + 1
    # active-segment window stay at native resolution
    assert levels[1] == 2 and levels[0] == 4
    # and nothing was lost
    truth_events = 6 * 100 + 100
    assert merged.events == truth_events


def test_sigkill_between_super_window_and_gc_is_exactly_once(tmp_path):
    """Crash injection at the widest dangerous window: super-windows
    durable, sources not yet GC'd. Queries dedup (exactly-once), the
    next pass finishes the GC without re-merging, and nothing is lost
    or double-counted — digest-audited."""
    store, base, store_dir = _seed_store(tmp_path, n=12)
    truth = _ground_truth(store, base)
    # two levels so L1 is final: the rerun must ONLY finish the GC, not
    # also ladder the now-aged supers a level up
    engine = CompactionEngine("10s@1m,60s@inf", store=store,
                              clock=lambda: FUTURE)

    def boom():
        raise RuntimeError("simulated SIGKILL before source GC")

    engine._before_gc = boom
    with pytest.raises(RuntimeError, match="simulated SIGKILL"):
        engine.compact_store(store_dir)

    # both tiers are on disk now; the fold must count each source once
    frames = list(store.fetch_windows(base_dir=base, gadget="trace/lc"))
    assert len(frames) == 12 + 3
    merged, kept, notes = _fold(store, base)
    assert len(kept) == 3 and {w.level for w in kept} == {1}
    assert len(notes) == 12 and all("superseded" in n for n in notes)
    _assert_fold_equals(merged, truth)

    # reopen + rerun converges: sources GC'd, nothing re-merged
    engine._before_gc = None
    stats = engine.compact_store(store_dir)
    assert stats["super_windows"] == 0
    assert stats["segments_deleted"] >= 1
    merged, kept, notes = _fold(store, base)
    assert notes == [] and len(kept) == 3
    _assert_fold_equals(merged, truth)


def test_retention_gc_and_compaction_interleave_exactly(tmp_path):
    """Satellite: concurrent retention GC (inside the writer's append
    path) and compaction passes on ONE store never delete the active
    segment, never double-free, and leave the gc/compaction accounting
    exact: every removed segment is counted by exactly one of the two
    planes."""
    store = HistoryStore()
    base = str(tmp_path / "hist")
    store.set_base_dir(base)
    writer = store.writer_for("trace-lc", node="lcnode", base_dir=base,
                              retention_bytes=1 << 30,
                              retention_segments=4,
                              max_segment_age=0.0)
    store_dir = os.path.join(base, "lcnode--trace-lc")
    engine = CompactionEngine("10s@1m,60s@inf", store=store,
                              clock=lambda: FUTURE)
    g0 = HISTORY_METRICS.gc.value
    errors: list = []
    stats_rows: list[dict] = []

    def sealer():
        try:
            for i in range(48):
                store.append_window(_window(i, seed=1000 + i),
                                    writer=writer)
                if (i + 1) % 4 == 0:
                    writer.rotate()
        except Exception as e:  # noqa: BLE001 — assert below
            errors.append(e)

    def compactor():
        try:
            for _ in range(16):
                stats_rows.append(engine.compact_store(store_dir))
        except Exception as e:  # noqa: BLE001 — assert below
            errors.append(e)

    threads = [threading.Thread(target=sealer),
               threading.Thread(target=compactor)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    stats_rows.append(engine.compact_store(store_dir))  # settle

    # the active segment survived and the store still appends
    store.append_window(_window(999, seed=999), writer=writer)
    # exact accounting: every sealed segment ever created either still
    # exists, was deleted by retention GC (counted in ig_history_gc),
    # or was deleted by compaction (counted in its stats) — sums match,
    # so nothing was double-freed or freed uncounted
    from inspektor_gadget_tpu.capture.journal import JournalReader
    reader = JournalReader(store_dir, metrics=HISTORY_METRICS)
    sealed_rows = {row["file"] for row in reader.index}
    present = {os.path.basename(p) for p in reader._segment_files()}
    deleted = len(sealed_rows - present)
    gc_delta = HISTORY_METRICS.gc.value - g0
    compact_deleted = sum(r["segments_deleted"] for r in stats_rows)
    assert deleted == gc_delta + compact_deleted, (
        deleted, gc_delta, compact_deleted)
    # no window was lost except by retention policy: everything still
    # present folds cleanly with exactly-once provenance
    merged, kept, notes = _fold(store, base)
    assert all("superseded" in n for n in notes)
    seen: dict[str, int] = {}
    for w in kept:
        for row in w.compacted_from:
            seen[row["digest"]] = seen.get(row["digest"], 0) + 1
    assert all(v == 1 for v in seen.values())


def test_slice_geometry_mismatch_skips_bucket_keeps_sources(tmp_path):
    """A bucket whose windows disagree on SLICE geometry (sealed by a
    build with different slice constants) is left at its current level
    — a partial merge would silently drop that slice's coverage when
    the sources are GC'd. Other buckets still compact; the skipped
    bucket's segment survives whole."""
    store = HistoryStore()
    base = str(tmp_path / "hist")
    store.set_base_dir(base)
    writer = store.writer_for("trace-lc", node="lcnode", base_dir=base)
    wins = [_window(0), _window(1)]
    # second window disagrees on the SHARED slice key's ent geometry
    wins[1].slices = {"mntns:100": {
        "events": 5, "hll": np.zeros(256, np.uint8),
        "ent": np.zeros(16, np.int64), "hh": [(7, 1)]}}
    wins[1].digest = window_digest(wins[1])
    for w in wins:
        store.append_window(w, writer=writer)
    writer.rotate()
    store_dir = os.path.join(base, "lcnode--trace-lc")
    truth = _ground_truth(store, base)
    engine = CompactionEngine("10s@1m,600s@inf", store=store,
                              clock=lambda: FUTURE)
    stats = engine.compact_store(store_dir)
    assert stats["super_windows"] == 0
    assert stats.get("skipped_buckets") == 1
    assert stats["segments_deleted"] == 0   # coverage kept whole
    merged, kept, _ = _fold(store, base)
    assert len(kept) == 2 and {w.level for w in kept} == {0}
    assert merged.events == truth.events


# ---------------------------------------------------------------------------
# Archive tier: offload, rehydration, digest verification
# ---------------------------------------------------------------------------

def _archived_store(tmp_path, cache_bytes=1 << 20):
    store, base, store_dir = _seed_store(tmp_path, n=12)
    truth = _ground_truth(store, base)
    engine = CompactionEngine("10s@1m,60s@inf", store=store,
                              clock=lambda: FUTURE)
    engine.compact_store(store_dir)   # everything at final level 1
    store.set_archive(str(tmp_path / "objects"), cache_bytes,
                      base_dir=base)
    tier = store.archive(base)
    # the super-windows live in their own sealed segment (compaction
    # rotates); offload every fully-final sealed segment
    stats = tier.archive_store(store_dir, min_level=1,
                               writer=store.writer_for_dir(store_dir))
    return store, base, store_dir, tier, truth, stats


def test_archive_offload_and_manifest_rehydration(tmp_path):
    store, base, store_dir, tier, truth, stats = _archived_store(tmp_path)
    assert stats["segments"] == 1 and stats["windows"] == 3
    assert os.path.isfile(os.path.join(store_dir, ARCHIVE_MANIFEST))
    rows = tier.manifest_rows(store_dir)
    assert len(rows) == 1
    row = rows[0]
    assert row["level"] == 1 and row["windows"] == 3
    assert row["keys"] and row["digest"]
    # the local segment is gone; the object exists under <store>/<seg>
    assert not os.path.isfile(os.path.join(store_dir, row["file"]))
    assert tier.backend.get(row["object"])

    # a range query rehydrates through the manifest and answers
    # identically to the pre-archive fold
    merged, kept, notes = _fold(store, base)
    assert notes == []
    _assert_fold_equals(merged, truth)
    assert tier.misses == 1 and tier.hits == 0
    # second query: cache hit, same answer
    merged, _, _ = _fold(store, base)
    _assert_fold_equals(merged, truth)
    assert tier.hits == 1

    # manifest ranges prune: a disjoint range never touches the backend
    misses = tier.misses
    out = list(store.fetch_windows(base_dir=base, gadget="trace/lc",
                                   start_ts=T0 + 9e6, end_ts=T0 + 9.1e6))
    assert out == [] and tier.misses == misses


def test_archive_corrupted_object_reported_never_merged(tmp_path):
    store, base, store_dir, tier, truth, _ = _archived_store(tmp_path)
    row = tier.manifest_rows(store_dir)[0]
    # corrupt the object in the backend (bit flip mid-payload)
    path = tier.backend._path(row["object"])
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0xFF
    open(path, "wb").write(bytes(data))

    losses: list = []
    frames = list(store.fetch_windows(base_dir=base, gadget="trace/lc",
                                      losses=losses))
    assert frames == []  # the only segment was archived and is refused
    assert any("digest mismatch" in loss["reason"] for loss in losses)
    # and the refusal is typed in a query answer, never silently merged
    ans = answer_query(decode_frames(frames))
    assert ans.windows == 0


def test_archive_manifest_torn_line_repaired_on_read(tmp_path):
    """A crash/ENOSPC-torn archive.jsonl line must not hide every row
    appended after it forever: manifest_rows repairs the file (atomic
    rewrite of the good rows, the journal-index discipline) so later
    offloads stay reachable."""
    store, base, store_dir, tier, truth, _ = _archived_store(tmp_path)
    mpath = os.path.join(store_dir, ARCHIVE_MANIFEST)
    good = open(mpath, "rb").read()
    with open(mpath, "ab") as f:
        f.write(b'{"object": "torn-half')       # torn tail
    assert len(tier.manifest_rows(store_dir)) == 1   # repair happened
    # a row appended AFTER the (repaired) tear is visible again
    from inspektor_gadget_tpu.utils.journal import append_line
    append_line(mpath, {"schema": "x", "object": "o2", "file": "none",
                        "bytes": 1, "digest": "d", "level": 1,
                        "windows": 0, "first_seq": 0, "last_seq": 0,
                        "first_ts": 0.0, "last_ts": 0.0, "keys": []})
    rows = tier.manifest_rows(store_dir)
    assert [r["object"] for r in rows][-1] == "o2"
    assert open(mpath, "rb").read().startswith(good)
    # and queries still answer identically through the surviving row
    merged, _, _ = _fold(store, base)
    _assert_fold_equals(merged, truth)


def test_archive_cache_is_lru_bounded(tmp_path):
    # two stores' worth of archived segments through one tiny cache
    store, base, store_dir = _seed_store(tmp_path, n=12, rotate_every=3)
    engine = CompactionEngine("10s@1m,60s@inf", store=store,
                              clock=lambda: FUTURE)
    engine.compact_store(store_dir)
    writer = store.writer_for_dir(store_dir)
    writer.rotate()
    # archive each super-window segment; cache holds ~one segment
    seg_size = max(
        os.path.getsize(os.path.join(store_dir, f))
        for f in os.listdir(store_dir) if f.startswith("seg-"))
    store.set_archive(str(tmp_path / "objects"), seg_size + 128,
                      base_dir=base)
    tier = store.archive(base)
    tier.archive_store(store_dir, min_level=1, writer=writer)
    _fold(store, base)
    cache_files = []
    for root, _d, files in os.walk(tier.cache_dir):
        cache_files += [os.path.join(root, f) for f in files]
    used = sum(os.path.getsize(p) for p in cache_files)
    assert used <= seg_size + 128 or len(cache_files) == 1
    assert tier.misses >= 1


# ---------------------------------------------------------------------------
# QueryWindows pushdown + FetchWindows pagination (real gRPC agent)
# ---------------------------------------------------------------------------

@pytest.fixture()
def grpc_agent(tmp_path):
    import inspektor_gadget_tpu.all_gadgets  # noqa: F401
    from inspektor_gadget_tpu.agent.client import AgentClient
    from inspektor_gadget_tpu.agent.service import serve
    from inspektor_gadget_tpu.history import HISTORY
    base = str(tmp_path / "hist-area")
    HISTORY.set_base_dir(base)
    writer = HISTORY.writer_for("trace-lc", node="lcnode-0")
    for i in range(6):
        HISTORY.append_window(_window(i, node="lcnode-0"), writer=writer)
    writer.rotate()
    addr = f"unix://{tmp_path}/lc-agent.sock"
    server, _agent = serve(addr, node_name="lcnode-0")
    client = AgentClient(addr, "lcnode-0")
    yield client, addr, base
    client.close()
    server.stop(grace=0.5)
    HISTORY.close_all()
    HISTORY.set_base_dir(None)
    HISTORY.set_archive(None)


def test_fetch_windows_pagination_edges(grpc_agent):
    """Satellite: offset == N and offset > N return empty, well-formed
    replies (not errors), and tiny max_bytes chunking drains every
    window exactly once."""
    from inspektor_gadget_tpu.agent import wire
    client, _addr, _base = grpc_agent
    method = client.channel.unary_unary(
        "/igtpu.GadgetManager/FetchWindows",
        request_serializer=wire.identity_serializer,
        response_deserializer=wire.identity_deserializer)

    def fetch(**kw):
        h, payload = wire.decode_msg(method(
            wire.encode_msg({"gadget": "trace/lc", **kw}),
            timeout=10.0))
        return h, payload

    h, payload = fetch(offset=6)            # offset == N
    assert h["ok"] and h["count"] == 0 and h["eof"] and payload == b""
    h, payload = fetch(offset=7)            # offset == N + 1
    assert h["ok"] and h["count"] == 0 and h["eof"] and payload == b""
    h, payload = fetch(offset=10_000)       # offset far past
    assert h["ok"] and h["count"] == 0 and h["eof"] and payload == b""
    h, _ = fetch(offset="banana")           # malformed: typed, not a 500
    assert "bad offset" in h["error"]

    # chunk-boundary drain: every chunk under the budget, no window
    # lost or duplicated, final chunk lands exactly on eof
    frames, losses = client.fetch_windows(gadget="trace/lc",
                                          chunk_bytes=1)
    assert len(frames) == 6 and not losses
    assert sorted(hh["window"] for hh, _p in frames) == [1, 2, 3, 4, 5, 6]
    # and the one-shot path agrees
    frames2, _ = client.fetch_windows(gadget="trace/lc")
    assert [hh["digest"] for hh, _ in frames2] == \
        [hh["digest"] for hh, _ in frames]


def test_query_windows_pushdown_matches_fetch_and_fold(grpc_agent):
    client, _addr, _base = grpc_agent
    frames, _ = client.fetch_windows(gadget="trace/lc")
    truth = merge_windows(decode_frames(frames))

    res = client.query_windows(gadget="trace/lc")
    assert res["folded"] == 6
    assert res["levels"] == {0: 6}
    assert res["torn"] == 0 and res["dropped"] == []
    win = res["window"]
    assert win is not None and win.node == "lcnode-0"
    _assert_fold_equals(merge_windows([win]), truth)

    # range + slice pushdown prunes node-side
    res = client.query_windows(gadget="trace/lc", start_ts=T0 + 21.0,
                               end_ts=T0 + 49.0)
    assert res["folded"] == 3          # windows 3..5 overlap
    res = client.query_windows(gadget="trace/lc", key="mntns:101")
    assert res["folded"] == 3          # odd windows carry mntns:101
    # no overlap: empty, well-formed
    res = client.query_windows(gadget="trace/lc", start_ts=T0 + 9e6)
    assert res["folded"] == 0 and res["window"] is None


def test_query_history_pushdown_and_fallback_paths(grpc_agent):
    import grpc

    from inspektor_gadget_tpu.runtime.grpc_runtime import GrpcRuntime
    client, addr, _base = grpc_agent
    runtime = GrpcRuntime({"lcnode-0": addr})
    try:
        push = runtime.query_history(gadget="trace/lc")
        assert push.paths == {"lcnode-0": "pushdown"}
        assert push.windows == 6 and push.levels == {0: 6}

        # an old agent answers UNIMPLEMENTED: the runtime falls back to
        # list+fetch PER NODE and labels the path — answers identical
        class OldAgentError(grpc.RpcError):
            def code(self):
                return grpc.StatusCode.UNIMPLEMENTED

            def details(self):
                return "Method not found"

        c = runtime._client("lcnode-0")

        def no_pushdown(**_kw):
            raise OldAgentError()

        c.query_windows = no_pushdown
        fetch = runtime.query_history(gadget="trace/lc")
        assert fetch.paths == {"lcnode-0": "fetch"}
        assert fetch.windows == 6 and fetch.levels == {0: 6}
        assert fetch.events == push.events
        assert fetch.distinct == push.distinct
        assert fetch.heavy_hitters == push.heavy_hitters
        assert not fetch.errors and not push.errors
    finally:
        runtime.close()


def test_dump_state_carries_history_tiers(grpc_agent):
    client, _addr, _base = grpc_agent
    tiers = client.dump_state().get("history_tiers")
    assert tiers and tiers["stores"] == 1
    assert tiers["levels"]["0"]["windows"] == 6
    assert tiers["levels"]["0"]["bytes"] > 0


# ---------------------------------------------------------------------------
# Stats, CLI verbs, doctor row
# ---------------------------------------------------------------------------

def test_stats_reports_per_level_and_per_tier(tmp_path):
    store, base, store_dir, tier, _truth, _ = _archived_store(tmp_path)
    writer = store.writer_for_dir(store_dir)
    store.append_window(_window(77, seed=77), writer=writer)  # fresh L0
    stats = store.stats(base)
    srow = stats["stores"]["lcnode--trace-lc"]
    assert set(srow["levels"]) == {"0"}     # L1 windows are archived
    l0 = srow["levels"]["0"]
    assert l0["windows"] == 1 and l0["bytes"] > 0
    assert l0["oldest_ts"] <= l0["newest_ts"]
    assert srow["archive"]["segments"] == 1
    assert srow["archive"]["windows"] == 3
    tiers = store.tier_stats(base)
    assert tiers["archived"]["segments"] == 1
    assert tiers["archive_cache"]["budget"] == tier.cache_bytes


def test_cli_history_verbs(tmp_path, capsys, monkeypatch):
    from inspektor_gadget_tpu.cli.main import main as cli_main
    store, base, store_dir = _seed_store(tmp_path, n=12)
    monkeypatch.setenv("IG_HISTORY_DIR", base)

    # compact: bad schedule is loud; good schedule folds and reports
    assert cli_main(["history", "compact", "--history", base,
                     "--schedule", "10m@1h,1m@inf"]) == 2
    assert "strictly coarsen" in capsys.readouterr().err
    # a single-level schedule never compacts: clean no-op, not a failure
    assert cli_main(["history", "compact", "--history", base,
                     "--schedule", "60s@inf"]) == 0
    out = capsys.readouterr().out
    assert "0 window(s) -> 0 super-window(s)" in out

    # tiers: per-level table
    assert cli_main(["history", "tiers", "--history", base]) == 0
    out = capsys.readouterr().out
    assert "level 0: 12 window(s)" in out

    # archive with the default (schedule-derived) level: the store has
    # no fully-final segments yet, so nothing moves — still rc 0
    assert cli_main(["history", "archive", "--history", base,
                     "--archive-dir", str(tmp_path / "obj"),
                     "--schedule", "10s@1m,60s@inf"]) == 0
    out = capsys.readouterr().out
    assert "0 segment(s) archived" in out


def test_query_cli_notes_compacted_resolution(tmp_path, capsys,
                                              monkeypatch):
    """Satellite: an answer that consulted compacted windows says so —
    users aren't surprised by resolution loss."""
    from inspektor_gadget_tpu.cli.main import main as cli_main
    store, base, store_dir = _seed_store(tmp_path, n=12)
    engine = CompactionEngine("10s@1m,60s@inf", store=store,
                              clock=lambda: FUTURE)
    engine.compact_store(store_dir)
    monkeypatch.setenv("IG_HISTORY_DIR", base)
    assert cli_main(["query", "--history", base,
                     "--gadget", "trace/lc"]) == 0
    out = capsys.readouterr().out
    assert "compacted to coarser resolution" in out
    assert "L1×3" in out
    # JSON carries the breakdown
    assert cli_main(["query", "--history", base, "--gadget", "trace/lc",
                     "-o", "json"]) == 0
    import json
    doc = json.loads(capsys.readouterr().out)
    assert doc["levels"] == {"1": 3}
    assert doc["compacted_windows"] == 3


def test_history_bench_emits_schema_valid_records(tmp_path):
    """The compaction + pushdown micro-bench points publish as
    schema-valid PerfRecords (the ledger refuses anything else), so
    `bench compare` can gate the lifecycle series like any other."""
    from inspektor_gadget_tpu.perf.history_bench import publish
    from inspektor_gadget_tpu.perf.ledger import read_ledger
    from inspektor_gadget_tpu.perf.schema import validate_record
    ledger = str(tmp_path / "PERF.jsonl")
    records = publish(n_windows=16, ledger=ledger)
    assert {r["config"] for r in records} == {"history-compaction",
                                             "history-pushdown"}
    for rec in records:
        assert validate_record(rec) == []
    push = next(r for r in records if r["config"] == "history-pushdown")
    # the whole point of pushdown: strictly fewer bytes on the wire
    assert push["extra"]["pushdown_wire_bytes"] \
        < push["extra"]["fetch_wire_bytes"]
    assert len(read_ledger(ledger).records) == 2


def test_doctor_history_tiers_row(tmp_path, monkeypatch):
    from inspektor_gadget_tpu.doctor import _probe_history_tiers
    store, base, _store_dir = _seed_store(tmp_path, n=3)
    monkeypatch.setenv("IG_HISTORY_DIR", base)
    w = _probe_history_tiers()
    assert w.ok
    assert "L0: 3w" in w.detail
    monkeypatch.setenv("IG_HISTORY_DIR", str(tmp_path / "empty"))
    w = _probe_history_tiers()
    assert w.ok and "no history stores" in w.detail
