"""Pipeline health plane (ISSUE 18): watermarks, starvation accounting,
alerting, fleet/doctor surfaces, perf-ledger extras — and the FREE
contract (digests and wire headers untouched by the plane).

The acceptance story under test: the BENCH_r04 starvation gap (a device
plane that drains the ring faster than one host thread refills it) is a
standing live signal on every instrumented run. The stager classifies
every tick as starved (host-bound) or saturated (device-bound); each
stage feeds a DDSketch host twin so summaries carry p50/p99 lag; the
`pipeline` block rides harvest summaries + DumpState without perturbing
a single digest byte; `pipeline_lag` turns a lag regression into exactly
one alert; `ig-tpu fleet lag` and the doctor row render it live.
"""

from __future__ import annotations

import json
import tempfile

import numpy as np
import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.gadgets import GadgetContext, get
from inspektor_gadget_tpu.params import Collection
from inspektor_gadget_tpu.telemetry import registry as telemetry_registry
from inspektor_gadget_tpu.telemetry.pipeline import (
    LagSketch,
    PipelineStats,
    live_stats,
)

GADGET = "trace/exec"


@pytest.fixture(autouse=True)
def _release_instances():
    """Instances built outside a real gadget run never see
    post_gadget_run — drop them from the live table, drain their stagers
    and unregister BOTH stats sources so no gauge residue leaks into
    other test files."""
    from inspektor_gadget_tpu.operators import tpusketch
    before = set(tpusketch._live)
    yield
    with tpusketch._live_mu:
        fresh = [rid for rid in list(tpusketch._live) if rid not in before]
        insts = [tpusketch._live.pop(rid) for rid in fresh]
    for inst in insts:
        if getattr(inst, "_stager", None) is not None:
            inst._stager.drain()
        for st in getattr(inst, "_lane_stagers", []):
            st.drain()
        inst._stats.unregister()
        inst._pstats.unregister()


def _pipeline_gauges() -> dict[str, float]:
    return {k: v for k, v in telemetry_registry.snapshot().items()
            if k.startswith("ig_pipeline_") and "backpressure" not in k}


# ---------------------------------------------------------------------------
# LagSketch: parity with the quantile plane's own bucket math
# ---------------------------------------------------------------------------

def test_lag_sketch_parity_with_dd_quantile_np():
    """The scalar-math host twin must read EXACTLY like dd_quantile_np
    over its own lanes — the health plane eats the quantile plane's
    dogfood, it does not fork the math."""
    from inspektor_gadget_tpu.ops.quantiles import dd_quantile_np

    rng = np.random.default_rng(18)
    sk = LagSketch()
    samples = rng.lognormal(np.log(1e-3), 1.5, 5000)
    samples[:100] = 0.0                      # idle ticks → zero bucket
    for v in samples:
        sk.add(float(v))
    assert sk.total == 5000 and sk.zeros == 100
    for q in (0.0, 0.25, 0.50, 0.90, 0.99, 0.999):
        ref = float(dd_quantile_np(sk.counts, sk.zeros, sk.total, q,
                                   alpha=sk.alpha, min_value=sk.min_value))
        assert abs(sk.quantile(q) - ref) < 1e-12, (q, sk.quantile(q), ref)
    # relative accuracy holds against the raw samples (alpha 1%, and the
    # ~0.2% extra from rank-vs-midpoint rounding at this sample count)
    pos = np.sort(samples[samples > 0])
    for q in (0.50, 0.99):
        true = float(np.quantile(samples, q))
        assert abs(sk.quantile(q) - true) / true < 0.03
    # empty + all-zeros sketches read 0.0, never NaN
    assert LagSketch().quantile(0.99) == 0.0
    z = LagSketch()
    z.add(0.0)
    assert z.quantile(0.5) == 0.0 and z.watermark == 0.0


def test_lag_sketch_clips_extremes_without_blowing_up():
    sk = LagSketch()
    sk.add(1e-12)        # below min_value → bucket 0
    sk.add(1e9)          # absurd lag → clipped to the last bucket
    assert sk.total == 2 and sk.zeros == 0
    assert sk.counts.sum() == 2
    assert sk.quantile(0.0) > 0.0


# ---------------------------------------------------------------------------
# PipelineStats: snapshot shape + gauge teardown discipline
# ---------------------------------------------------------------------------

def test_pipeline_stats_snapshot_shape_and_worst_lane():
    ps = PipelineStats("run-ph-shape", GADGET)
    ps.register()
    try:
        assert any(p.run_id == "run-ph-shape" for p in live_stats())
        ps.note_host_lag(0.002)
        ps.note_host_lag(0.004)
        ps.note_host_lag(0.010, lane=1)      # laggiest lane
        ps.note_device_lag(0.001)
        ps.note_starved()
        ps.note_starved()
        ps.note_saturated(0.005)
        ps.note_backpressure("pop", 2)
        ps.note_occupancy("h2d", 2, lane=1)
        ps.note_round()
        snap = ps.snapshot()
        # multi-lane stages report the WORST lane's view, summed counts
        assert snap["stages"]["pop"]["count"] == 3
        assert snap["stages"]["pop"]["watermark_s"] == 0.010
        assert snap["stages"]["pop"]["p99_s"] >= snap["stages"]["pop"]["p50_s"] > 0.0
        assert snap["host_lag_s"] == 0.010
        assert snap["device_lag_s"] == 0.001
        assert snap["starved"] == 2 and snap["saturated"] == 1
        assert snap["starved_ratio"] == pytest.approx(2 / 3)
        assert snap["stall_s"] == pytest.approx(0.005)
        # note_saturated books its stall as h2d backpressure too
        assert snap["backpressure"] == {"h2d": 1, "pop": 2}
        assert snap["occupancy"] == {"h2d:1": 2.0}
        assert snap["rounds"] == 1
        json.dumps(snap)                     # plain JSON-able, always
        # the shared gauges read the live values while registered
        g = _pipeline_gauges()
        assert g['ig_pipeline_stage_lag_seconds{stage="pop",lane="1"}'] == 0.010
        assert g['ig_pipeline_occupancy{stage="h2d",lane="1"}'] == 2.0
        assert g["ig_pipeline_starved_ratio"] == pytest.approx(2 / 3)
    finally:
        ps.unregister()
    # teardown discipline: every touched gauge back EXACTLY to baseline
    assert all(v == 0.0 for v in _pipeline_gauges().values()), \
        _pipeline_gauges()
    assert not any(p.run_id == "run-ph-shape" for p in live_stats())


def test_empty_stats_snapshot_is_all_zero():
    snap = PipelineStats("run-ph-empty").snapshot()
    assert snap["stages"] == {} and snap["starved_ratio"] == 0.0
    assert snap["host_lag_s"] == 0.0 and snap["device_lag_s"] == 0.0


# ---------------------------------------------------------------------------
# H2DStager: the starved/saturated tick classification is deterministic
# ---------------------------------------------------------------------------

def test_stager_classifies_starved_then_saturated_ticks():
    from inspektor_gadget_tpu.sources.staging import (
        H2DStager,
        PinnedBufferPool,
    )

    ps = PipelineStats("run-ph-stager")
    pool = PinnedBufferPool(64, lanes=2)
    stager = H2DStager(pool, depth=2, stats=ps)
    try:
        for i in range(5):
            blk = pool.get()
            devs = stager.stage(blk, [blk[0], blk[1]])
            stager.fence(devs[0])
        snap = ps.snapshot()
        # the first `depth` ticks land on an empty ring (starved — the
        # warmup guarantee the e2e starved_ratio > 0 assertion rides);
        # every later tick finds its slot occupied (saturated)
        assert snap["starved"] == 2 and snap["saturated"] == 3
        assert snap["starved_ratio"] == pytest.approx(2 / 5)
        assert snap["backpressure"]["h2d"] == 3
        assert snap["occupancy"]["h2d:0"] == 2.0   # ring full after warmup
        stager.drain()
        assert ps.snapshot()["occupancy"]["h2d:0"] == 0.0
    finally:
        ps.unregister()


# ---------------------------------------------------------------------------
# operator e2e: a real run carries the block, then leaves no residue
# ---------------------------------------------------------------------------

def _sketch_run_ctx(timeout: float, summaries: list) -> GadgetContext:
    desc = get("trace", "exec")
    params = desc.params().to_params()
    params.set("source", "pysynthetic")
    params.set("rate", "20000")
    params.set("batch-size", "256")
    from inspektor_gadget_tpu.operators.operators import get as get_op
    sp = get_op("tpusketch").instance_params().to_params()
    for k, v in (("enable", "true"), ("log2-width", "8"), ("hll-p", "6"),
                 ("entropy-log2-width", "6"), ("topk", "8"),
                 ("harvest-interval", "300ms")):
        sp.set(k, v)
    op_params = Collection()
    op_params["operator.tpusketch."] = sp
    return GadgetContext(desc, gadget_params=params,
                         operator_params=op_params, timeout=timeout,
                         extra={"on_sketch_summary": summaries.append})


def test_local_run_populates_pipeline_block_and_tears_down():
    from inspektor_gadget_tpu.runtime.local import LocalRuntime

    summaries: list = []
    for attempt in (1, 2):     # one retry: suite load can starve a short run
        result = LocalRuntime().run_gadget(
            _sketch_run_ctx(1.2 * attempt, summaries))
        assert not result.errors(), result.errors()
        if any(s.events for s in summaries):
            break
        summaries.clear()
    s = next(s for s in reversed(summaries) if s.events)
    pipe = s.pipeline
    assert pipe is not None
    # pysynthetic stamps pop_ts == oldest_ts at synthesis, so the pop
    # stage exists with ~zero lag and the h2d stage carries the real
    # staging+dispatch wait
    assert {"pop", "h2d"} <= set(pipe["stages"])
    assert pipe["stages"]["h2d"]["count"] > 0
    assert pipe["stages"]["h2d"]["watermark_s"] > 0.0
    assert pipe["stages"]["h2d"]["p99_s"] > 0.0
    # ring warmup makes starvation deterministic: the first `depth`
    # stage() calls always find an empty slot
    assert pipe["starved"] > 0
    assert pipe["starved_ratio"] > 0.0
    # `rounds` counts sharded-ingest dispatch rounds — 0 on this
    # single-chip path, but the key is always present for consumers
    assert pipe["rounds"] == 0
    # the run ended: no live stats, every shared gauge back to baseline
    assert not any(p.gadget == GADGET for p in live_stats())
    assert all(v == 0.0 for v in _pipeline_gauges().values()), \
        _pipeline_gauges()


# ---------------------------------------------------------------------------
# FREE: digests and wire headers are untouched by the plane
# ---------------------------------------------------------------------------

def test_summary_digest_ignores_pipeline_block():
    """summary_digest builds from a fixed whitelist — the pipeline block
    CANNOT perturb it, so sealed windows and `replay --verify` stay
    byte-identical with the plane on (the tentpole's FREE proof)."""
    from inspektor_gadget_tpu.capture.journal import summary_digest

    base = {"events": 100, "drops": 2, "distinct": 7.0, "entropy": 1.5,
            "epoch": 3, "heavy_hitters": [[1, 5], [2, 3]]}
    with_plane = dict(base, pipeline={
        "stages": {"pop": {"watermark_s": 0.01, "p50_s": 0.01,
                           "p99_s": 0.02, "count": 9}},
        "host_lag_s": 0.01, "device_lag_s": 0.002, "starved": 4,
        "saturated": 1, "starved_ratio": 0.8, "stall_s": 0.0,
        "backpressure": {}, "occupancy": {}, "rounds": 9})
    assert summary_digest(base) == summary_digest(with_plane)


def test_wire_encoding_only_when_present_and_roundtrip():
    from inspektor_gadget_tpu.agent import wire
    from inspektor_gadget_tpu.operators.tpusketch import SketchSummary

    plain = SketchSummary(events=10, drops=0, distinct=3.0,
                          entropy_bits=1.5, heavy_hitters=[(1, 5)], epoch=2)
    h, _ = wire.encode_summary(plain)
    assert "pipeline" not in h            # pre-plane headers byte-identical
    block = {"stages": {"h2d": {"watermark_s": 0.004, "p50_s": 0.003,
                                "p99_s": 0.008, "count": 12}},
             "host_lag_s": 0.0, "device_lag_s": 0.004, "starved": 2,
             "saturated": 10, "starved_ratio": 1 / 6, "stall_s": 0.01,
             "backpressure": {"h2d": 10}, "occupancy": {"h2d:0": 2.0},
             "rounds": 12}
    on = SketchSummary(events=10, drops=0, distinct=3.0, entropy_bits=1.5,
                       heavy_hitters=[(1, 5)], epoch=2, pipeline=block)
    h2, payload = wire.encode_summary(on)
    out = wire.decode_summary(h2, payload)
    assert out["pipeline"] == block


# ---------------------------------------------------------------------------
# alerts: the pipeline_lag detector kind
# ---------------------------------------------------------------------------

def test_pipeline_lag_rule_validation():
    from inspektor_gadget_tpu.alerts.rules import RuleError, load_rules

    rules = load_rules(json.dumps([{"id": "pl", "kind": "pipeline_lag",
                                    "factor": 3.0}]))
    assert rules[0].field == "host_lag"     # the default stage signal
    assert rules[0].threshold == 0.0        # threshold optional
    assert "pipeline health plane" in rules[0].describe()
    rules2 = load_rules(json.dumps([{"id": "pl", "kind": "pipeline_lag",
                                     "field": "starved_ratio",
                                     "factor": 2.0}]))
    assert rules2[0].field == "starved_ratio"
    with pytest.raises(RuleError, match="pipeline_lag watches"):
        load_rules(json.dumps([{"id": "pl", "kind": "pipeline_lag",
                                "field": "entropy", "factor": 2.0}]))


def test_pipeline_lag_fires_once_with_idle_immunity():
    """BENCH_r04 acceptance at the engine layer: healthy epochs build the
    baseline, an idle window (plane off / no traffic → 0.0) must NOT
    poison it, a 4x host-lag regression fires exactly once through the
    hysteresis machine, and staying regressed does not re-fire."""
    from inspektor_gadget_tpu.alerts.engine import AlertEngine
    from inspektor_gadget_tpu.alerts.rules import load_rules

    rules = load_rules(json.dumps([{
        "id": "lag", "kind": "pipeline_lag", "field": "host_lag",
        "factor": 2.0, "window": 3, "for": 0}]))
    eng = AlertEngine(rules, node="n0", gadget=GADGET, dry_run=True)
    base = {"events": 100, "drops": 0, "distinct": 5.0, "entropy": 1.0,
            "heavy_hitters": [], "anomaly": {}}

    def obs(epoch, host_lag, now):
        return eng.observe(
            {**base, "epoch": epoch,
             "pipeline": {"host_lag_s": host_lag,
                          "device_lag_s": host_lag / 4,
                          "starved_ratio": 0.5}}, now=now)

    transitions = []
    # 3 healthy epochs (~2ms), one idle window in the middle
    for i, lag in enumerate((0.0020, 0.0021, 0.0, 0.0019)):
        transitions += [(e.transition, i) for e in obs(i, lag, 10.0 * i)]
    assert transitions == []                # baseline warmup never fires
    evs = obs(4, 0.0080, 40.0)
    assert [e.transition for e in evs] == ["pending", "firing"]
    assert evs[-1].rule == "lag" and evs[-1].value == 0.0080
    evs2 = obs(5, 0.0082, 50.0)
    assert not any(e.transition == "firing" for e in evs2)
    eng.close()


def test_pipeline_lag_ignores_plane_off_summaries():
    from inspektor_gadget_tpu.alerts.engine import AlertEngine
    from inspektor_gadget_tpu.alerts.rules import load_rules

    rules = load_rules(json.dumps([{
        "id": "lag", "kind": "pipeline_lag", "factor": 1.1,
        "window": 2, "for": 0}]))
    eng = AlertEngine(rules, node="n0", gadget=GADGET, dry_run=True)
    base = {"events": 100, "drops": 0, "distinct": 5.0, "entropy": 1.0,
            "heavy_hitters": [], "anomaly": {}}
    evs = []
    for epoch in range(6):                   # plane off: no pipeline key
        evs += eng.observe({**base, "epoch": epoch}, now=10.0 * epoch)
    assert evs == []
    eng.close()


# ---------------------------------------------------------------------------
# CLI: ig-tpu fleet lag (stubbed request path + rendering)
# ---------------------------------------------------------------------------

class _LagArgs:
    remote = ""
    deadline = 3.0
    gadget = ""
    watch = 0.0
    iterations = 0
    output = "table"

    def __init__(self, **kv):
        for k, v in kv.items():
            setattr(self, k, v)


_STUB_ROW = {
    "run_id": "run-stub-000001", "gadget": GADGET,
    "stages": {"pop": {"watermark_s": 0.0005, "p50_s": 0.0004,
                       "p99_s": 0.0009, "count": 120},
               "h2d": {"watermark_s": 0.0020, "p50_s": 0.0018,
                       "p99_s": 0.0041, "count": 120}},
    "host_lag_s": 0.0005, "device_lag_s": 0.0020,
    "starved": 30, "saturated": 90, "starved_ratio": 0.25,
    "stall_s": 0.4, "backpressure": {"h2d": 90},
    "occupancy": {"h2d:0": 2.0}, "rounds": 120,
}


def _stub_client(rows):
    class _StubClient:
        def __init__(self, target, node, rpc_deadline=3.0):
            self.node = node

        def dump_state(self):
            return {"pipeline": rows}

        def close(self):
            pass
    return _StubClient


def test_fleet_lag_renders_table_and_json(monkeypatch, capsys):
    from inspektor_gadget_tpu.agent import client as agent_client
    from inspektor_gadget_tpu.cli.fleet import cmd_fleet_lag

    monkeypatch.setattr(agent_client, "AgentClient",
                        _stub_client([_STUB_ROW]))
    assert cmd_fleet_lag(_LagArgs(remote="n0=localhost:19999")) == 0
    out = capsys.readouterr().out
    assert "STAGE" in out and "STARVED" in out
    assert "pop" in out and "h2d" in out
    assert "run-stub-00000" in out           # rid column (14 chars)
    assert "2.0ms" in out and "4.1ms" in out  # h2d watermark + p99
    assert "500us" in out                     # sub-ms lags render in us
    assert "25%" in out
    # json mode carries the rows verbatim
    assert cmd_fleet_lag(_LagArgs(remote="n0=localhost:19999",
                                  output="json")) == 0
    doc = json.loads(capsys.readouterr().out)
    run = doc["agents"][0]["runs"][0]
    assert run["starved_ratio"] == 0.25
    assert run["stages"]["h2d"]["p99_s"] == 0.0041
    # --gadget filters to matching runs only
    assert cmd_fleet_lag(_LagArgs(remote="n0=localhost:19999",
                                  gadget="trace/open")) == 0
    assert "no instrumented runs" in capsys.readouterr().out


def test_fleet_lag_unreachable_node_is_rc1(monkeypatch, capsys):
    from inspektor_gadget_tpu.agent import client as agent_client
    from inspektor_gadget_tpu.cli.fleet import cmd_fleet_lag

    class _Boom:
        def __init__(self, target, node, rpc_deadline=3.0):
            raise OSError("connection refused")

    monkeypatch.setattr(agent_client, "AgentClient", _Boom)
    assert cmd_fleet_lag(_LagArgs(remote="n0=localhost:19999")) == 1
    assert "unreachable" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# real 2-node gRPC fleet: DumpState → fleet lag table + doctor row
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def agents():
    from inspektor_gadget_tpu.agent.service import serve
    servers, targets = [], {}
    tmp = tempfile.mkdtemp()
    for i in range(2):
        addr = f"unix://{tmp}/lag-agent{i}.sock"
        server, _ = serve(addr, node_name=f"lnode-{i}")
        servers.append(server)
        targets[f"lnode-{i}"] = addr
    yield targets
    for s in servers:
        s.stop(grace=0.5)


def test_fleet_lag_and_doctor_over_real_fleet(agents, capsys):
    from inspektor_gadget_tpu.cli.fleet import cmd_fleet_lag
    from inspektor_gadget_tpu.doctor import _probe_pipeline_health

    ps = PipelineStats("run-fleet-lag-1", GADGET)
    ps.register()
    try:
        ps.note_host_lag(0.003)
        ps.note_device_lag(0.0011)
        ps.note_starved()
        ps.note_saturated(0.002)
        ps.note_occupancy("h2d", 1)
        remote = ",".join(f"{n}={t}" for n, t in agents.items())
        # --watch with --iterations: the second poll computes rates from
        # count deltas (static run → 0/s, but the column renders)
        assert cmd_fleet_lag(_LagArgs(remote=remote, watch=0.05,
                                      iterations=2)) == 0
        out = capsys.readouterr().out
        for node in agents:
            assert node in out
        assert "run-fleet-lag-" in out
        assert "pop" in out and "h2d" in out and "50%" in out
        assert "0/s" in out                 # the delta-rate column
        # the doctor row reads the same live registry
        w = _probe_pipeline_health()
        assert w.name == "pipeline_health" and w.ok
        assert "run-flee" in w.detail and "starved 50%" in w.detail
        assert "3.0ms" in w.detail          # worst-stage lag watermark
    finally:
        ps.unregister()
    w2 = _probe_pipeline_health()
    assert w2.ok and "no live instrumented runs" in w2.detail


def test_dump_state_carries_pipeline_rows(agents):
    from inspektor_gadget_tpu.agent.client import AgentClient

    ps = PipelineStats("run-dump-1", GADGET)
    ps.register()
    try:
        ps.note_device_lag(0.004)
        client = AgentClient(next(iter(agents.values())), "lnode-0")
        try:
            rows = client.dump_state()["pipeline"]
        finally:
            client.close()
        row = next(r for r in rows if r.get("run_id") == "run-dump-1")
        assert row["gadget"] == GADGET
        assert row["stages"]["h2d"]["watermark_s"] == 0.004
    finally:
        ps.unregister()


# ---------------------------------------------------------------------------
# perf: harness extras + the derived pipeline-lag ledger series
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_harness_records_pipeline_extras():
    from inspektor_gadget_tpu.perf.harness import run_harness
    from inspektor_gadget_tpu.perf.schema import validate_record

    rec = run_harness("tiny", platform="cpu")
    assert validate_record(rec) == []
    extra = rec["extra"]
    assert 0.0 <= extra["starved_fraction"] <= 1.0
    assert extra["stall_s"] >= 0.0
    assert {"pop", "h2d"} <= set(extra["stage_lag"])
    for row in extra["stage_lag"].values():
        assert row["p99_s"] >= row["p50_s"] >= 0.0
    # the harness unregisters its stats: gauges back at baseline
    assert all(v == 0.0 for v in _pipeline_gauges().values())


@pytest.mark.slow
def test_bench_run_derives_pipeline_lag_record(tmp_path):
    from inspektor_gadget_tpu.cli.main import main as cli_main
    from inspektor_gadget_tpu.perf.ledger import read_ledger
    from inspektor_gadget_tpu.perf.schema import validate_record

    ledger = str(tmp_path / "PERF.jsonl")
    assert cli_main(["bench", "run", "--config", "tiny", "--platform",
                     "cpu", "--pipeline", "fused", "--ledger",
                     ledger]) == 0
    recs = read_ledger(ledger).records
    assert len(recs) == 2
    main_rec, lag_rec = recs
    assert validate_record(lag_rec) == []
    assert lag_rec["config"] == "harness.tiny.pipeline-lag"
    assert lag_rec["metric"] == "pipeline_device_lag_p99"
    assert lag_rec["unit"] == "seconds"     # → lower_better gating
    assert lag_rec["value"] == \
        main_rec["extra"]["stage_lag"]["h2d"]["p99_s"]
    assert lag_rec["extra"]["source_config"] == "harness.tiny"


# ---------------------------------------------------------------------------
# docs lint: the starved-claim pattern in check_perf_claims
# ---------------------------------------------------------------------------

def test_check_perf_claims_starved_pattern():
    from tools.check_perf_claims import Backing, check_claim, extract_claims

    # both spellings parse, targets are skipped, kinds don't cross-match
    claims = extract_claims(
        "the run sat 13% starved on the cpu harness\n"
        "fleet lag showed starved 97%\n"
        "aim for ≥90% starved coverage\n", "docs/performance.md")
    starved = [c for c in claims if c.kind == "starved_pct"]
    assert [c.lo for c in starved] == [13.0, 97.0, 90.0]
    assert starved[2].skipped.startswith("target")
    cpu13 = Backing(13.04, "cpu", False, "PERF.jsonl:1#starved_fraction",
                    kind="starved_pct")
    # backed + the line says "cpu" → clean
    assert check_claim(starved[0], [cpu13]) == ""
    # an ev/s backing with the same number may NOT back a starved claim
    assert "NO ledger" in check_claim(
        starved[0], [Backing(13.0, "cpu", False, "x")])
    # backed only by a CPU record but the line doesn't say so → violation
    assert "CPU" in check_claim(
        starved[1], [Backing(97.0, "cpu", False, "y",
                             kind="starved_pct")])


def test_ledger_backings_surface_starved_fraction(tmp_path):
    from tools.check_perf_claims import _ledger_backings

    p = tmp_path / "PERF.jsonl"
    p.write_text(json.dumps({
        "config": "harness.e2e", "value": 1e6, "unit": "ev/s",
        "provenance": {"platform": "cpu", "degraded": False},
        "extra": {"starved_fraction": 0.1304}}) + "\n")
    backs = _ledger_backings(p)
    sf = [b for b in backs if b.kind == "starved_pct"]
    assert len(sf) == 1
    assert sf[0].value == pytest.approx(13.04)
    assert sf[0].second_class                # cpu → needs labeling
    assert sf[0].source.endswith("#starved_fraction")
