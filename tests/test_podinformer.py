"""Pod informer tests (ref: pkg/container-collection/podinformer.go's
update-diff contract — containers appearing/vanishing in pod specs become
add/remove events on the collection)."""

import json

from inspektor_gadget_tpu.containers import (
    ContainerCollection,
    ContainerSelector,
    PodInformer,
    file_pod_source,
    with_fallback_pod_informer,
    with_fake_containers,
    with_pod_informer,
)
from inspektor_gadget_tpu.containers.container import Container


def pod(name, ns="default", node="node-a", containers=("main",), labels=None):
    return {
        "name": name, "namespace": ns, "uid": f"uid-{name}", "node": node,
        "labels": labels or {}, "containers": [{"name": c} for c in containers],
    }


def test_informer_diffs_adds_and_removes():
    pods = [pod("web", containers=("nginx", "sidecar"))]
    inf = PodInformer(lambda: pods, interval=999)
    added, removed = [], []
    inf.on_add = lambda c: added.append(c.name)
    inf.on_remove = lambda k: removed.append(k)
    assert inf.refresh() == (2, 0)
    assert sorted(added) == ["nginx", "sidecar"]
    # idempotent: same snapshot → no events
    assert inf.refresh() == (0, 0)
    # drop one container, add a pod
    pods[:] = [pod("web", containers=("nginx",)), pod("db", containers=("pg",))]
    assert inf.refresh() == (1, 1)
    assert added[-1] == "pg" and "sidecar" in removed[0]


def test_informer_node_filter_and_error_resilience():
    calls = {"n": 0}

    def source():
        calls["n"] += 1
        if calls["n"] == 2:
            raise OSError("apiserver blip")
        return [pod("web", node="node-a"), pod("other", node="node-b")]

    inf = PodInformer(source, node_name="node-a", interval=999)
    assert inf.refresh() == (1, 0)         # only node-a's pod
    assert inf.refresh() == (0, 0)         # source error → state untouched
    assert inf.refresh() == (0, 0)         # recovered, still consistent


def test_with_pod_informer_populates_collection(tmp_path):
    manifest = tmp_path / "pods.json"
    manifest.write_text(json.dumps({"pods": [
        pod("web", ns="prod", containers=("nginx",), labels={"app": "web"}),
    ]}))
    cc = ContainerCollection()
    cc.initialize(with_pod_informer(file_pod_source(str(manifest)),
                                    interval=999))
    got = cc.get_all(ContainerSelector(namespace="prod"))
    assert len(got) == 1
    assert got[0].pod == "web" and got[0].labels == {"app": "web"}
    cc._pod_informer.stop()


def test_informer_containers_survive_gadget_run(tmp_path):
    """Regression: attaching the informer via ensure_initialized must mark
    localmanager as initialized, or the first gadget run re-inits it and
    replaces the collection, orphaning every informer-discovered
    container."""
    import inspektor_gadget_tpu.all_gadgets  # noqa: F401  (registers ops)
    from inspektor_gadget_tpu.gadgets import GadgetContext, get
    from inspektor_gadget_tpu.operators.operators import ensure_initialized
    from inspektor_gadget_tpu.runtime import LocalRuntime

    manifest = tmp_path / "pods.json"
    manifest.write_text(json.dumps([pod("web", ns="prod",
                                        containers=("nginx",))]))
    lm = ensure_initialized("localmanager")
    with_pod_informer(file_pod_source(str(manifest)), node_name="node-a",
                      interval=999)(lm.cc)
    try:
        assert any(c.runtime == "podinformer" for c in lm.cc.get_all())

        desc = get("trace", "exec")
        params = desc.params().to_params()
        params.set("source", "pysynthetic")
        params.set("rate", "20000")
        ctx = GadgetContext(desc, gadget_params=params, timeout=0.3)
        result = LocalRuntime().run_gadget(ctx, on_event=lambda e: None)
        assert not result.errors()
        # same collection object, informer container still tracked
        assert any(c.runtime == "podinformer" for c in lm.cc.get_all())
    finally:
        lm.cc._pod_informer.stop()


def test_informer_survives_bad_pod_and_bad_subscriber():
    """Malformed pod dicts or raising callbacks must not kill discovery."""
    pods = [{"name": "ok", "namespace": "d", "uid": "u", "node": "",
             "labels": {}, "containers": [{"id": "x"}]}]  # no 'name' key
    inf = PodInformer(lambda: pods, interval=999)
    assert inf.refresh() == (0, 0)  # malformed → state untouched, no raise
    pods[0]["containers"] = [{"name": "good"}]
    inf.on_add = lambda c: (_ for _ in ()).throw(RuntimeError("subscriber"))
    assert inf.refresh() == (1, 0)  # callback raised, informer kept going
    assert inf.refresh() == (0, 0)  # state consistent afterwards


def test_agent_serve_with_pod_manifest(tmp_path):
    """Black-box: agent discovers containers from a watched pod manifest;
    DumpState exposes them (ref: DumpState dumps containers,
    gadgettracermanager.go:204-219)."""
    import json as _json
    import subprocess
    import sys
    import time

    manifest = tmp_path / "pods.json"
    manifest.write_text(json.dumps([pod("web", ns="prod",
                                        containers=("nginx",))]))
    sock = f"unix://{tmp_path}/agent.sock"
    proc = subprocess.Popen(
        [sys.executable, "-m", "inspektor_gadget_tpu.agent.main", "serve",
         "--listen", sock, "--node-name", "node-a",
         "--pod-manifest", str(manifest), "--informer-interval", "0.2"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        deadline = time.time() + 60
        found = None
        while time.time() < deadline and found is None:
            r = subprocess.run(
                [sys.executable, "-m", "inspektor_gadget_tpu.agent.main",
                 "dump", "--target", sock],
                capture_output=True, text=True, timeout=30)
            if r.returncode == 0:
                dump = _json.loads(r.stdout)
                # procfs discovery may contribute other containers; find ours
                found = next((c for c in dump.get("containers", ())
                              if c["runtime"] == "podinformer"), None)
            if found is None:
                time.sleep(0.5)
        assert found, "pod-informer container never appeared in DumpState"
        assert found["name"] == "nginx" and found["namespace"] == "prod"
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_fallback_informer_only_when_empty(tmp_path):
    manifest = tmp_path / "pods.json"
    manifest.write_text(json.dumps([pod("web")]))
    # collection already populated by another backend → fallback is inert
    cc = ContainerCollection()
    cc.initialize(
        with_fake_containers([Container(id="c1", name="c1")]),
        with_fallback_pod_informer(file_pod_source(str(manifest)),
                                   interval=999),
    )
    assert {c.id for c in cc.get_all()} == {"c1"}
    # empty collection → fallback activates
    cc2 = ContainerCollection()
    cc2.initialize(with_fallback_pod_informer(file_pod_source(str(manifest)),
                                              interval=999))
    assert len(cc2.get_all()) == 1
    cc2._pod_informer.stop()
