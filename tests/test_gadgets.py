"""Per-gadget behavior tests (model: the reference's gadget unit tests +
integration matchers, SURVEY §4)."""

import json
import os
import time

import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.gadgets import GadgetContext, get, get_all
from inspektor_gadget_tpu.runtime import LocalRuntime


def run_gadget(category, name, timeout=0.6, param_overrides=None,
               collect_events=False, collect_arrays=False):
    desc = get(category, name)
    params = desc.params().to_params()
    if "source" in params:
        params.set("source", "pysynthetic")
        params.set("rate", "50000")
    for k, v in (param_overrides or {}).items():
        params.set(k, v)
    ctx = GadgetContext(desc, gadget_params=params, timeout=timeout)
    events, arrays = [], []
    result = LocalRuntime().run_gadget(
        ctx,
        on_event=events.append if collect_events else None,
        on_event_array=arrays.append if collect_arrays else None,
    )
    assert not result.errors(), result.errors()
    return result.first(), events, arrays


def test_all_expected_gadgets_registered():
    have = {(d.category, d.name) for d in get_all()}
    want = {
        ("trace", "exec"), ("trace", "open"), ("trace", "tcp"),
        ("trace", "tcpconnect"), ("trace", "bind"), ("trace", "dns"),
        ("trace", "sni"), ("trace", "network"), ("trace", "mount"),
        ("trace", "signal"), ("trace", "oomkill"), ("trace", "capabilities"),
        ("trace", "fsslower"),
        ("top", "file"), ("top", "tcp"), ("top", "block-io"), ("top", "sketch"),
        ("snapshot", "process"), ("snapshot", "socket"),
        ("profile", "cpu"), ("profile", "block-io"),
        ("audit", "seccomp"),
        ("advise", "seccomp-profile"), ("advise", "network-policy"),
        ("traceloop", "traceloop"),
    }
    missing = want - have
    assert not missing, f"missing gadgets: {missing}"


@pytest.mark.parametrize("name", ["open", "mount", "signal", "oomkill",
                                  "capabilities", "bind", "fsslower", "dns",
                                  "sni", "network"])
def test_trace_gadgets_stream_events(name):
    _, events, _ = run_gadget("trace", name, collect_events=True)
    assert len(events) > 10
    ev = events[0]
    assert ev.timestamp > 0


def test_audit_seccomp_decodes_syscalls():
    # synthetic rows are explicitly labeled SYNTH so fabricated decode can
    # never be mistaken for a captured seccomp outcome
    _, events, _ = run_gadget("audit", "seccomp", collect_events=True)
    assert events
    assert all(e.code == "SYNTH" for e in events[:20] if e is not None)


def test_snapshot_process_lists_self():
    import os
    result, _, _ = run_gadget("snapshot", "process")
    # ctx.result carries the row list; bytes result is the rendered table
    assert result and b"COMM" in result
    assert str(os.getpid()).encode() in result or b"python" in result


def test_snapshot_socket_parses_procnet():
    result, _, _ = run_gadget("snapshot", "socket")
    assert result and b"PROTOCOL" in result


def test_top_file_emits_arrays():
    _, _, arrays = run_gadget("top", "file", timeout=2.5,
                              param_overrides={"interval": "1s"},
                              collect_arrays=True)
    assert arrays  # at least one tick (rows may be empty on idle systems)


def test_profile_blockio_histogram_renders():
    result, _, _ = run_gadget("profile", "block-io", timeout=0.8)
    assert b"usecs" in result and b"distribution" in result
    # the output names its window so degraded data is never mistaken
    # for the per-IO distribution
    assert b"source:" in result


def test_profile_blockio_diskstats_flavour_labeled():
    result, _, _ = run_gadget("profile", "block-io", timeout=0.6,
                              param_overrides={"window": "diskstats"})
    assert b"degraded" in result


def test_profile_blockio_per_io_distribution():
    """With the tracefs window, every IO lands in its own latency bucket —
    a real distribution, not a windowed average (biolatency.bpf.c parity)."""
    import subprocess
    import threading

    from inspektor_gadget_tpu.sources.bridge import blktrace_supported
    if not blktrace_supported() or os.geteuid() != 0:
        pytest.skip("tracefs block events unavailable")

    def io_load():
        time.sleep(0.5)
        for _ in range(3):
            subprocess.run(
                ["dd", "if=/dev/zero", "of=/tmp/ig_blk_g", "bs=4096",
                 "count=64", "oflag=direct"],
                stderr=subprocess.DEVNULL, check=False)

    t = threading.Thread(target=io_load)
    t.start()
    try:
        result, _, _ = run_gadget(
            "profile", "block-io", timeout=3.0,
            param_overrides={"window": "blktrace"})
    finally:
        t.join()
    assert b"per-IO" in result
    # at least ~100 IOs counted individually across the histogram
    counts = [int(line.split(":")[1].split("|")[0])
              for line in result.decode().splitlines()
              if "->" in line and ":" in line]
    assert sum(counts) >= 100, result.decode()


def test_trace_mount_per_container_mntns_attach():
    """Mounts inside a container's private mount ns are invisible to the
    host mountinfo; the Attacher path polls the container's own
    /proc/<pid>/mountinfo (mountsnoop.bpf.c parity: system-wide
    tracepoints see every mount ns)."""
    import shutil
    import subprocess
    import threading

    from inspektor_gadget_tpu.sources.bridge import native_available
    if (not native_available() or os.geteuid() != 0
            or not shutil.which("unshare")):
        pytest.skip("netns tooling unavailable")

    child = subprocess.Popen(
        ["unshare", "-m", "bash", "-c",
         "sleep 1.2; for i in 1 2 3; do mount -t tmpfs igtmp_$i /mnt; "
         "sleep 0.4; umount /mnt; sleep 0.3; done; sleep 5"])
    try:
        time.sleep(0.3)
        desc = get("trace", "mount")
        ctx = GadgetContext(desc, gadget_params=desc.params().to_params(),
                            timeout=5.0)
        g = desc.new_instance(ctx)

        class _C:
            id = "mnt-probe"
            pid = child.pid
        g.attach_container(_C())
        events = []
        g.set_event_handler(events.append)
        threading.Thread(target=ctx.wait_for_timeout_or_done,
                         daemon=True).start()
        g.run(ctx)
    finally:
        child.kill()
        child.wait()
    mine = [(e.operation, e.source) for e in events
            if e is not None and "igtmp" in e.source]
    assert any(op == "mount" for op, _ in mine), mine
    assert any(op == "umount" for op, _ in mine), mine


def test_trace_exec_args_and_ppid():
    """The native exec window carries execsnoop's headline columns: ARGS
    (full argv) and PPID, enriched at capture time (tracer.go:169-181
    parses the same buffer from the BPF event)."""
    import subprocess
    import threading

    from inspektor_gadget_tpu.sources.bridge import native_available
    if not native_available() or os.geteuid() != 0:
        pytest.skip("native exec window unavailable")

    stop = threading.Event()

    def workload():
        time.sleep(0.6)
        while not stop.is_set():
            # the unusual duration doubles as the argv marker; the 130ms
            # lifetime guarantees the capture thread's /proc/cmdline read
            # wins the race (an instantly-exiting `true` can lose it)
            subprocess.run(["sleep", "0.137"], check=False)
            stop.wait(0.1)

    t = threading.Thread(target=workload)
    t.start()
    try:
        _, events, _ = run_gadget(
            "trace", "exec", timeout=3.0,
            param_overrides={"source": "native"}, collect_events=True)
    finally:
        stop.set()
        t.join()
    mine = [e for e in events
            if e is not None and e.args == "sleep 0.137"]
    assert mine, [e.args for e in events if e is not None and e.args][:10]
    assert any(e.ppid == os.getpid() for e in mine)


def _audit_window_available():
    from inspektor_gadget_tpu.sources.bridge import audit_supported
    return audit_supported()


def test_trace_capabilities_host_wide_denials():
    """With no target, trace/capabilities observes real host-wide denials
    via the kernel audit stream (capable.bpf.c:1-250 parity: system-wide
    scope, denial verdicts from failed EPERM/EACCES syscalls)."""
    import subprocess
    import threading

    if not _audit_window_available() or os.geteuid() != 0:
        pytest.skip("audit window unavailable")

    target = "/tmp/ig_cap_host_t"
    open(target, "w").close()
    stop = threading.Event()

    def trigger():
        # rule install needs a few netlink round-trips; keep triggering
        # cheap EPERM chowns (setpriv execs chown directly — no interpreter
        # startup) across the whole gadget window so load can't starve it
        time.sleep(0.5)
        while not stop.is_set():
            subprocess.run(
                ["setpriv", "--reuid", "65534", "--clear-groups",
                 "chown", "0:0", target],
                check=False, stderr=subprocess.DEVNULL)
            stop.wait(0.25)

    t = threading.Thread(target=trigger)
    t.start()
    try:
        _, events, _ = run_gadget(
            "trace", "capabilities", timeout=4.0,
            param_overrides={"source": "auto"}, collect_events=True)
    finally:
        stop.set()
        t.join()
        os.unlink(target)
    denials = [e for e in events
               if e is not None and e.cap == "CHOWN" and e.verdict == "deny"]
    assert denials, [getattr(e, "cap", None) for e in events][:10]
    assert all(e.pid > 0 for e in denials)


def test_audit_seccomp_host_wide_kills():
    """With no target, audit/seccomp reports real host-wide seccomp kills
    via AUDIT_SECCOMP records (audit-seccomp.bpf.c:1-65 parity)."""
    import subprocess
    import threading

    if not _audit_window_available() or os.geteuid() != 0:
        pytest.skip("audit window unavailable")

    # a tiny compiled trigger avoids interpreter startup latency: under
    # full-suite load a `python -c` child can take >1s, sliding every
    # trigger past the gadget window
    helper = "/tmp/ig_seccomp_trigger"
    if not os.path.exists(helper):
        src = "/tmp/ig_seccomp_trigger.c"
        with open(src, "w") as f:
            f.write("#include <sys/prctl.h>\n#include <unistd.h>\n"
                    "int main(){prctl(22,1,0,0,0);return getpid();}\n")
        subprocess.run(["g++", "-O1", "-o", helper, src], check=True)

    stop = threading.Event()

    def trigger():
        time.sleep(0.5)
        while not stop.is_set():
            subprocess.run([helper], check=False)  # SIGKILL + audit record
            stop.wait(0.25)

    t = threading.Thread(target=trigger)
    t.start()
    try:
        _, events, _ = run_gadget(
            "audit", "seccomp", timeout=4.0,
            param_overrides={"source": "auto"}, collect_events=True)
    finally:
        stop.set()
        t.join()
    kills = [e for e in events
             if e is not None and e.code in ("KILL_THREAD", "KILL_PROCESS")]
    assert kills, [getattr(e, "code", None) for e in events][:10]
    assert any(e.syscall == "getpid" for e in kills)


def test_trace_tcp_event_driven_state_transitions():
    """With the inet_sock_set_state window, trace/tcp reports real
    connect/accept/close events with tuple and pid attribution — no scan
    window (tcptracer.bpf.c:1-375 parity)."""
    import socket
    import threading

    from inspektor_gadget_tpu.sources.bridge import sockstate_supported
    if not sockstate_supported() or os.geteuid() != 0:
        pytest.skip("inet_sock_set_state window unavailable")

    port_box = {}
    stop = threading.Event()

    def workload():
        time.sleep(0.8)
        ls = socket.socket()
        ls.bind(("127.0.0.1", 0))
        ls.listen(4)
        port_box["port"] = ls.getsockname()[1]
        def srv():
            while not stop.is_set():
                try:
                    ls.settimeout(0.5)
                    conn, _ = ls.accept()
                    conn.close()
                except OSError:
                    pass
        st = threading.Thread(target=srv)
        st.start()
        while not stop.is_set():
            try:
                cs = socket.create_connection(
                    ("127.0.0.1", port_box["port"]), timeout=1.0)
                cs.close()
            except OSError:
                pass
            stop.wait(0.25)
        st.join()
        ls.close()

    t = threading.Thread(target=workload)
    t.start()
    try:
        _, events, _ = run_gadget(
            "trace", "tcp", timeout=4.0,
            param_overrides={"source": "native"}, collect_events=True)
        # connect-only view against the same live workload: the kind
        # filter must drop the accept/close transitions
        _, cevents, _ = run_gadget(
            "trace", "tcpconnect", timeout=2.0,
            param_overrides={"source": "native"}, collect_events=True)
    finally:
        stop.set()
        t.join()
    port = port_box.get("port")
    mine = [e for e in events
            if e is not None and port in (e.sport, e.dport)]
    ops = {e.operation for e in mine}
    assert {"connect", "accept", "close"} <= ops, (port, ops)
    connects = [e for e in mine if e.operation == "connect"]
    # kubeipresolver may suffix a label onto addresses ("127.0.0.1 (host)")
    assert all(e.daddr.startswith("127.0.0.1") and e.dport == port
               for e in connects)
    assert any(e.pid > 0 and e.comm for e in connects)
    cmine = [e for e in cevents if e is not None]
    assert cmine and all(e.operation == "connect" for e in cmine)


def test_trace_signal_host_wide_tracepoint():
    """With the signal_generate window, trace/signal reports every signal
    host-wide with sender and target (sigsnoop.bpf.c:1-175 parity) — not
    just fatal exits."""
    import signal as sig_mod
    import subprocess
    import threading

    from inspektor_gadget_tpu.sources.bridge import sigtrace_supported
    if not sigtrace_supported() or os.geteuid() != 0:
        pytest.skip("signal_generate window unavailable")

    stop = threading.Event()
    victim = subprocess.Popen(["sleep", "30"])

    def trigger():
        time.sleep(0.8)
        while not stop.is_set():
            os.kill(victim.pid, sig_mod.SIGUSR2)  # non-fatal... for sleep
            stop.wait(0.25)

    t = threading.Thread(target=trigger)
    t.start()
    try:
        _, events, _ = run_gadget(
            "trace", "signal", timeout=3.0,
            param_overrides={"source": "native"}, collect_events=True)
    finally:
        stop.set()
        t.join()
        victim.kill()
        victim.wait()
    # SIGUSR2 kills sleep (default action term) — either way the GENERATE
    # event must carry sender (this process) and target (the sleep pid)
    mine = [e for e in events
            if e is not None and e.tpid == victim.pid and e.origin == "sent"]
    assert mine, [(getattr(e, "tpid", None), getattr(e, "origin", None))
                  for e in events][:10]
    # the sender pid in the trace line is the sending THREAD's tid (the
    # trigger runs in a pytest worker thread), so assert attribution
    # exists rather than equality with the process pid
    assert any(e.pid > 0 and e.comm for e in mine)


def test_trace_fsslower_host_wide():
    """With no target, trace/fsslower observes real host-wide slow fs ops
    via filtered raw_syscalls tracepoints (fsslower.bpf.c:1-239 parity:
    system-wide entry/exit latency above a threshold)."""
    import subprocess
    import threading

    from inspektor_gadget_tpu.sources.bridge import fstrace_supported
    if not fstrace_supported() or os.geteuid() != 0:
        pytest.skip("raw_syscalls window unavailable")

    stop = threading.Event()
    fifo = "/tmp/ig_fsslow_fifo"
    try:
        os.unlink(fifo)
    except OSError:
        pass
    os.mkfifo(fifo)

    def slow_io():
        # a fifo whose writer delays guarantees a >=50ms blocking read on
        # ANY filesystem (dd O_DIRECT tricks fail with EINVAL on tmpfs)
        time.sleep(0.5)
        while not stop.is_set():
            # writer opens the fifo immediately (so the reader's open
            # returns fast) but delays each WRITE — the slow ops are the
            # reads, and the second blocking read keeps dd alive while the
            # first read's exit record resolves its fd path via /proc
            subprocess.run(
                ["sh", "-c",
                 f"( exec 3>{fifo}; sleep 0.08; printf 12345678 >&3; "
                 f"sleep 0.4; printf 12345678 >&3 ) & "
                 f"dd if={fifo} of=/dev/null bs=8 count=2; wait"],
                stderr=subprocess.DEVNULL, check=False)
            stop.wait(0.15)

    t = threading.Thread(target=slow_io)
    t.start()
    try:
        _, events, _ = run_gadget(
            "trace", "fsslower", timeout=4.0,
            param_overrides={"source": "auto", "min-latency": "1"},
            collect_events=True)
    finally:
        stop.set()
        t.join()
        try:
            os.unlink(fifo)
        except OSError:
            pass
    slow = [e for e in events if e is not None and e.latency_us >= 1000]
    assert slow, [getattr(e, "latency_us", None) for e in events][:10]
    dd_rows = [e for e in slow if e.comm == "dd" and e.op == "read"]
    assert dd_rows, [(e.comm, e.op) for e in slow][:10]
    assert any(e.file == fifo for e in dd_rows)


def test_top_file_per_file_rows_under_dd_workload():
    """With the fanotify window, top/file's unit of account is the FILE —
    rows carry real filenames per (pid, file) (filetop.bpf.c:1-108 parity:
    per-(pid,file) stats map → fanotify open/modify aggregation)."""
    import subprocess
    import threading

    from inspektor_gadget_tpu.gadgets.top.file import (
        _fanotify_window_available,
    )
    if not _fanotify_window_available() or os.geteuid() != 0:
        pytest.skip("fanotify window unavailable")

    target = "/tmp/ig_filetop_target"

    def io_load():
        time.sleep(0.4)
        for _ in range(3):
            subprocess.run(
                ["dd", "if=/dev/zero", f"of={target}", "bs=4096",
                 "count=200", "conv=notrunc"],
                stderr=subprocess.DEVNULL, check=False)
            time.sleep(0.3)

    t = threading.Thread(target=io_load)
    t.start()
    try:
        _, _, arrays = run_gadget(
            "top", "file", timeout=3.0,
            param_overrides={"interval": "1s", "window": "fanotify"},
            collect_arrays=True)
    finally:
        t.join()
        try:
            os.unlink(target)
        except OSError:
            pass
    rows = [r for tick in arrays for r in tick]
    mine = [r for r in rows if r.file == target]
    assert mine, f"no per-file rows for {target}: " \
                 f"{sorted({r.file for r in rows})[:15]}"
    assert sum(r.writes for r in mine) > 0
    # a short-lived dd may exit before the capture thread reads its /proc
    # identity, so comm can be empty on a straggler row — but at least one
    # row must be fully identified
    assert any(r.pid > 0 and r.comm for r in mine)


def test_top_file_procio_flavour_still_works():
    _, _, arrays = run_gadget("top", "file", timeout=2.2,
                              param_overrides={"interval": "1s",
                                               "window": "procio"},
                              collect_arrays=True)
    assert arrays  # ticks emitted; rows may be empty on an idle host


def test_trace_open_per_container_mount_attach():
    """Opens on a container's private mounts are invisible to the host "/"
    mount mark; the Attacher path marks the container's root mount via
    /proc/<pid>/root, capturing them with resolved paths."""
    import shutil
    import subprocess
    import threading

    from inspektor_gadget_tpu.gadgets.top.file import (
        _fanotify_window_available,
    )
    if (not _fanotify_window_available() or os.geteuid() != 0
            or not shutil.which("unshare")):
        pytest.skip("fanotify/netns tooling unavailable")

    # writes land on BOTH the container's root mount (a private clone the
    # host "/" mark does not see) and a volume-style tmpfs submount, which
    # the attach covers via the container's mount table
    child = subprocess.Popen(
        ["unshare", "-m", "bash", "-c",
         "mount -t tmpfs igvol /mnt; sleep 0.8; "
         "for i in $(seq 1 50); do echo hi > /ig_attach_open_$i; "
         "echo hi > /mnt/ig_attach_vol_$i; "
         "sleep 0.1; done; rm -f /ig_attach_open_*"])
    try:
        time.sleep(0.8)
        desc = get("trace", "open")
        ctx = GadgetContext(desc, gadget_params=desc.params().to_params(),
                            timeout=4.0)
        g = desc.new_instance(ctx)

        class _C:
            id = "open-mnt-probe"
            pid = child.pid
        g.attach_container(_C())
        events = []
        g.set_event_handler(events.append)
        threading.Thread(target=ctx.wait_for_timeout_or_done,
                         daemon=True).start()
        g.run(ctx)
    finally:
        child.kill()
        child.wait()
        import glob
        for leftover in glob.glob("/ig_attach_open_*"):
            try:
                os.unlink(leftover)
            except OSError:
                pass
    mine = [e for e in events
            if e is not None and "ig_attach_open_" in e.path]
    assert mine, sorted({e.path for e in events if e is not None})[:10]
    assert any(e.op == "write" and e.pid > 0 for e in mine)
    # volume-style submounts are covered too (marked from the container's
    # own mount table)
    vol = [e for e in events
           if e is not None and "ig_attach_vol_" in e.path]
    assert vol, sorted({e.path for e in events if e is not None})[:10]


def test_trace_open_covers_post_attach_mounts():
    """A tmpfs mounted AFTER attach is marked live by the source's remark
    loop polling the container's mountinfo (VERDICT r4 item 6; ref:
    opensnoop.bpf.c sees every open regardless of when the mount
    appeared)."""
    import shutil
    import subprocess
    import threading

    from inspektor_gadget_tpu.gadgets.top.file import (
        _fanotify_window_available,
    )
    if (not _fanotify_window_available() or os.geteuid() != 0
            or not shutil.which("unshare")):
        pytest.skip("fanotify/netns tooling unavailable")

    child = subprocess.Popen(
        ["unshare", "-m", "bash", "-c",
         "sleep 1.5; mount -t tmpfs igpost /mnt; "
         "for i in $(seq 1 40); do echo hi > /mnt/ig_post_mount_$i; "
         "sleep 0.1; done; sleep 3"])
    try:
        time.sleep(0.3)  # attach BEFORE the mount exists
        desc = get("trace", "open")
        ctx = GadgetContext(desc, gadget_params=desc.params().to_params(),
                            timeout=6.0)
        g = desc.new_instance(ctx)

        class _C:
            id = "post-mount-probe"
            pid = child.pid
        g.attach_container(_C())
        events = []
        g.set_event_handler(events.append)
        threading.Thread(target=ctx.wait_for_timeout_or_done,
                         daemon=True).start()
        g.run(ctx)
    finally:
        child.kill()
        child.wait()
    mine = [e for e in events
            if e is not None and "ig_post_mount_" in e.path]
    assert mine, sorted({e.path for e in events if e is not None})[:10]


def test_snapshot_socket_covers_container_netns():
    """snapshot/socket lists sockets of tracked containers' private netns
    too (the reference iterates per container netns), via each pid's
    /proc/<pid>/net view — with container identity on the rows."""
    import shutil
    import subprocess
    import sys

    if (os.geteuid() != 0 or not shutil.which("unshare")
            or not shutil.which("ip")):
        pytest.skip("netns tooling unavailable")

    from inspektor_gadget_tpu.containers import Container
    from inspektor_gadget_tpu.operators.operators import ensure_initialized

    # -S skips site processing: this image's sitecustomize pre-imports
    # jax, which would delay the listener by seconds
    child = subprocess.Popen(
        ["unshare", "-n", "bash", "-c",
         f"ip link set lo up && {sys.executable} -S -c \"\n"
         "import socket, time\n"
         "ls = socket.socket(); ls.bind(('127.0.0.1', 46123)); ls.listen(1)\n"
         "time.sleep(20)\n"
         "\""])
    lm = ensure_initialized("localmanager")
    cid = "netns-snap-probe"
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:  # wait for the bind, not a guess
            try:
                if "B42B" in open(f"/proc/{child.pid}/net/tcp").read():
                    break
            except OSError:
                pass
            time.sleep(0.2)
        lm.cc.add_container(Container(id=cid, name="snap-probe",
                                      pid=child.pid))
        _, _, arrays = run_gadget("snapshot", "socket", timeout=0.5,
                                  param_overrides={"proto": "tcp"},
                                  collect_arrays=True)
    finally:
        lm.cc.remove_container(cid)
        child.kill()
        child.wait()
    rows = [r for tick in arrays for r in tick]
    mine = [r for r in rows if r.localport == 46123]
    assert mine, f"container-netns LISTEN socket missing " \
                 f"({len(rows)} rows total)"
    assert any(r.container == "snap-probe" and r.status == "LISTEN"
               and r.netnsid > 0 for r in mine)


def test_trace_dns_per_netns_container_attach():
    """A DNS query inside a container's private netns is invisible to the
    host-netns sniffer; the Attacher path opens one sniffer per container
    netns (networktracer/tracer.go:54-220 parity: one refcounted
    attachment per netns)."""
    import shutil
    import subprocess
    import sys
    import threading

    from inspektor_gadget_tpu.sources.bridge import native_available
    if (not native_available() or os.geteuid() != 0
            or not shutil.which("unshare") or not shutil.which("ip")):
        pytest.skip("netns tooling unavailable")

    child = subprocess.Popen(
        ["unshare", "-n", "bash", "-c",
         f"ip link set lo up && {sys.executable} -c \"\n"
         "import socket, struct, time\n"
         "time.sleep(2.0)\n"
         "q = struct.pack('>HHHHHH', 0x1234, 0x0100, 1, 0, 0, 0)\n"
         "q += b'\\x07netnsgd\\x07example\\x03com\\x00'"
         " + struct.pack('>HH', 1, 1)\n"
         "s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)\n"
         "for _ in range(30):\n"
         "    s.sendto(q, ('127.0.0.1', 53)); time.sleep(0.15)\n"
         "\""])
    try:
        time.sleep(0.5)
        desc = get("trace", "dns")
        params = desc.params().to_params()
        ctx = GadgetContext(desc, gadget_params=params, timeout=6.0)
        g = desc.new_instance(ctx)

        class _C:
            id = "dns-netns"
            pid = child.pid
        g.attach_container(_C())
        events = []
        g.set_event_handler(events.append)
        threading.Thread(target=ctx.wait_for_timeout_or_done,
                         daemon=True).start()
        g.run(ctx)
    finally:
        child.kill()
        child.wait()
    names = {e.name for e in events if e is not None}
    assert any("netnsgd" in n for n in names), sorted(names)[:10]


def test_top_tcp_per_netns_container_attach():
    """A container with a private netns is invisible to the host-netns
    sock_diag dump; the Attacher path spawns a per-container byte source
    whose capture thread setns()es into the container's netns (the
    per-netns flavour the docs promise)."""
    import shutil
    import subprocess

    from inspektor_gadget_tpu.sources.bridge import tcpinfo_supported
    if (not tcpinfo_supported() or os.geteuid() != 0
            or not shutil.which("unshare") or not shutil.which("ip")):
        pytest.skip("netns tooling or INET_DIAG_INFO unavailable")

    import sys
    child = subprocess.Popen(
        ["unshare", "-n", "bash", "-c",
         f"ip link set lo up && {sys.executable} -c \"\n"
         "import socket, threading, time\n"
         "ls = socket.socket(); ls.bind(('127.0.0.1', 41998)); ls.listen(1)\n"
         "def srv():\n"
         "    conn, _ = ls.accept()\n"
         "    while conn.recv(65536): pass\n"
         "t = threading.Thread(target=srv); t.start()\n"
         "time.sleep(2.5)\n"
         "cs = socket.create_connection(('127.0.0.1', 41998))\n"
         "for _ in range(48): cs.sendall(b'x'*65536); time.sleep(0.03)\n"
         "time.sleep(2.0); cs.close(); t.join()\n"
         "\""])
    try:
        time.sleep(1.0)
        desc = get("top", "tcp")
        params = desc.params().to_params()
        ctx = GadgetContext(desc, gadget_params=params, timeout=6.0)
        g = desc.new_instance(ctx)

        class _C:
            id = "netns-probe"
            pid = child.pid
        g.attach_container(_C())
        arrays = []
        g.set_event_handler_array(arrays.append)
        import threading
        threading.Thread(target=ctx.wait_for_timeout_or_done,
                         daemon=True).start()  # the runtime's timeout role
        g.run(ctx)
        rows = [r for tick in arrays for r in tick]
        mine = [r for r in rows if ":41998" in r.conn]
        assert mine, sorted({r.conn for r in rows})[:10]
        assert sum(r.sent for r in mine) > 1 << 20
    finally:
        child.kill()
        child.wait()


def test_top_tcp_real_bytes_under_live_workload():
    """With the INET_DIAG_INFO window, top/tcp reports real per-connection
    SENT/RECV byte counts (tcptop.bpf.c:1-133 parity: kprobe byte sums →
    sock_diag tcp_info counter deltas)."""
    import socket
    import threading

    from inspektor_gadget_tpu.sources.bridge import tcpinfo_supported
    if not tcpinfo_supported():
        pytest.skip("sock_diag INET_DIAG_INFO unavailable")

    total = {"recv": 0}
    ls = socket.socket()
    ls.bind(("127.0.0.1", 0))
    ls.listen(1)
    port = ls.getsockname()[1]
    stop = threading.Event()

    def server():
        conn, _ = ls.accept()
        while True:
            d = conn.recv(65536)
            if not d:
                break
            total["recv"] += len(d)
        conn.close()

    def client():
        cs = socket.create_connection(("127.0.0.1", port))
        chunk = b"x" * 65536
        # pace ~6 MB across the gadget run so multiple poll ticks observe
        # live deltas, and hold the socket open until the gadget is done
        # (a socket gone before the next dump loses its last delta)
        for _ in range(96):
            cs.sendall(chunk)
            time.sleep(0.02)
        stop.wait(timeout=5.0)
        cs.close()

    st = threading.Thread(target=server)
    ct = threading.Thread(target=client)
    st.start()
    ct.start()
    try:
        _, _, arrays = run_gadget(
            "top", "tcp", timeout=3.5,
            param_overrides={"interval": "1s", "source": "native"},
            collect_arrays=True)
    finally:
        stop.set()
        ct.join()
        st.join()
        ls.close()
    rows = [r for tick in arrays for r in tick]
    mine = [r for r in rows if f":{port}" in r.conn]
    assert mine, f"no rows for test connection on port {port}: " \
                 f"{[r.conn for r in rows][:10]}"
    sent = sum(r.sent for r in mine)
    recv = sum(r.recv for r in mine)
    # both directions of the loopback pair were live sockets; between them
    # the full transfer must be accounted (deltas, not fabrications)
    assert sent + recv >= total["recv"] > 1 << 20, (sent, recv, total)
    assert all(r.pid > 0 for r in mine)


def test_profile_blockio_quantiles_param():
    result, _, _ = run_gadget("profile", "block-io", timeout=0.8,
                              param_overrides={"quantiles": "true"})
    # quantile line appears whenever any IO was observed in the window
    if b"p50=" in result:
        assert b"ddsketch" in result and b"p99=" in result
    else:  # idle disk: histogram still renders, no quantile line
        assert b"distribution" in result


def test_profile_cpu_columns_and_folded():
    result, _, _ = run_gadget("profile", "cpu", timeout=0.7)
    assert b"SAMPLES" in result
    folded, _, _ = run_gadget("profile", "cpu", timeout=0.7,
                              param_overrides={"profile-output": "folded"})
    # folded lines end with a count
    line = folded.decode().strip().splitlines()[0]
    assert line.rsplit(" ", 1)[1].isdigit()


def test_advise_seccomp_profile_generates_oci_json():
    result, _, _ = run_gadget("advise", "seccomp-profile", timeout=0.8)
    profiles = json.loads(result)
    assert profiles
    prof = next(iter(profiles.values()))
    assert prof["defaultAction"] == "SCMP_ACT_ERRNO"
    names = prof["syscalls"][0]["names"]
    assert "execve" in names and prof["syscalls"][0]["action"] == "SCMP_ACT_ALLOW"


def test_advise_seccomp_profile_generates_cr_yaml():
    """--format cr renders SeccompProfile custom resources (ref:
    gadget-collection/gadgets/advise/seccomp/gadget.go:582)."""
    result, _, _ = run_gadget(
        "advise", "seccomp-profile", timeout=0.8,
        param_overrides={"format": "cr", "profile-name": "web"})
    text = result.decode()
    assert "kind: SeccompProfile" in text
    assert "security-profiles-operator.x-k8s.io/v1beta1" in text
    assert 'name: "web-' in text  # user-supplied names are quoted
    assert "defaultAction: SCMP_ACT_ERRNO" in text
    assert "- execve" in text
    # must parse as YAML when a parser is around (structure check)
    try:
        import yaml
    except ImportError:
        pass
    else:
        docs = list(yaml.safe_load_all(text))
        assert docs and docs[0]["kind"] == "SeccompProfile"
        assert "execve" in docs[0]["spec"]["syscalls"][0]["names"]


def test_advise_network_policy_generates_yaml():
    result, _, _ = run_gadget("advise", "network-policy", timeout=0.8)
    text = result.decode()
    assert "kind: NetworkPolicy" in text
    assert "policyTypes:" in text
    assert "port:" in text


def test_traceloop_retrospective_read():
    result, _, _ = run_gadget("traceloop", "traceloop", timeout=0.8)
    text = result.decode()
    assert "SYSCALL" in text
    assert len(text.splitlines()) > 5


def test_traceloop_ring_overwrites_oldest():
    from inspektor_gadget_tpu.gadgets.traceloop.traceloop import Traceloop
    desc = get("traceloop", "traceloop")
    params = desc.params().to_params()
    params.set("source", "pysynthetic")
    params.set("ring-size", "16")
    ctx = GadgetContext(desc, gadget_params=params, timeout=0.5)
    g = desc.new_instance(ctx)
    import numpy as np
    from inspektor_gadget_tpu.sources import EventBatch
    b = EventBatch.alloc(100)
    b.cols["mntns"][:] = 42
    b.cols["ts"][:] = np.arange(100)
    b.cols["aux2"][:] = np.arange(100)
    b.count = 100
    g.process_batch(b)
    records = g.read(42)
    assert len(records) == 16  # overwrote the oldest 84
    assert records[-1].timestamp == 99
