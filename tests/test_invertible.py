"""Invertible heavy-key recovery (ISSUE 15): operator, fleet, alerts,
perf surfaces.

The acceptance story under test: heavy-hitter recovery stops depending
on the host candidate ring. A 2-node fleet seals invertible-plane
windows per node; decoding the MERGED state recovers every ground-truth
key with its EXACT aggregate count — including a key that is heavy only
in aggregate and absent from BOTH nodes' candidate rings — while the
candidate-overflow satellite flags (approx=True + counter) exactly when
the ring stopped being exact, and PSketch-style priority classes keep a
hot tenant's decode complete when the whole stream overflows the base
geometry.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

import jax.numpy as jnp

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.gadgets import GadgetContext, get
from inspektor_gadget_tpu.history import HISTORY, answer_query, decode_frames
from inspektor_gadget_tpu.operators.operators import get as get_op
from inspektor_gadget_tpu.ops import fold64_to_32
from inspektor_gadget_tpu.params import ParamError
from inspektor_gadget_tpu.sources.batch import EventBatch
from inspektor_gadget_tpu.telemetry import registry as telemetry_registry

GADGET = "trace/exec"
K_RING = 8


@pytest.fixture(autouse=True)
def _release_instances():
    """Instances built outside a real gadget run never see
    post_gadget_run — drop them from the live table (checkpoint_all
    iterates it) and drain their stagers (the h2d inflight gauge) so no
    state leaks into other test files."""
    from inspektor_gadget_tpu.operators import tpusketch
    before = set(tpusketch._live)
    yield
    with tpusketch._live_mu:
        fresh = [rid for rid in list(tpusketch._live) if rid not in before]
        insts = [tpusketch._live.pop(rid) for rid in fresh]
    for inst in insts:
        if getattr(inst, "_stager", None) is not None:
            inst._stager.drain()
        for st in getattr(inst, "_lane_stagers", []):
            st.drain()
        inst._stats.unregister()
        inst._pstats.unregister()


def _make_instance(extra_params: dict, node: str = "",
                   extra_ctx: dict | None = None):
    desc = get("trace", "exec")
    ctx = GadgetContext(desc, extra=dict(extra_ctx or {}))
    if node:
        ctx.extra["node"] = node
    op = get_op("tpusketch")
    p = op.instance_params().to_params()
    p.set("enable", "true")
    p.set("depth", "3")
    p.set("log2-width", "10")
    p.set("hll-p", "8")
    p.set("entropy-log2-width", "6")
    p.set("topk", str(K_RING))
    p.set("harvest-interval", "1h")
    for k, v in extra_params.items():
        p.set(k, v)
    return op.instantiate(ctx, None, p)


def _batch(keys64: np.ndarray, mntns: np.ndarray | None = None
           ) -> EventBatch:
    b = EventBatch.alloc(len(keys64), with_comm=False)
    b.cols["key_hash"][:] = keys64
    if mntns is not None:
        b.cols["mntns"][:] = mntns
    b.count = len(keys64)
    return b


# ---------------------------------------------------------------------------
# param validation matrix
# ---------------------------------------------------------------------------

def test_param_error_matrix():
    op = get_op("tpusketch")

    def params(**kv):
        p = op.instance_params().to_params()
        p.set("enable", "true")
        for k, v in kv.items():
            p.set(k, v)
        return p

    # grammar errors answer at the params layer (set-time validator)
    for bad, match in (
        ("gibberish", "name=log2buckets"),
        ("a=12:1,a=10:*", "duplicate class name"),
        ("a=12:7,b=10:7|8,c=9:*", "already claimed"),
        ("a=12:7", "no '\\*' catch-all"),
        ("a=12:*,b=10:*", "second '\\*' catch-all"),
        ("a=99:*", "outside \\[6, 20\\]"),
        ("a=xx:*", "not an integer"),
        ("a=12:", "empty tenant"),
    ):
        with pytest.raises(ParamError, match=match):
            params(**{"priority-classes": bad})
    with pytest.raises(ParamError):
        params(**{"inv-log2-buckets": "25"})
    with pytest.raises(ParamError):
        params(**{"inv-rows": "1"})
    # classes without the plane: loud at instantiation
    with pytest.raises(ParamError, match="needs 'invertible true'"):
        _make_instance({"priority-classes": "hot=9:101,rest=8:*"})
    # budget overrun: classes must PARTITION the base geometry
    with pytest.raises(ParamError, match="budgets"):
        _make_instance({"invertible": "true", "inv-log2-buckets": "9",
                        "priority-classes": "hot=9:101,rest=9:*"})
    # fitting classes instantiate
    inst = _make_instance({"invertible": "true", "inv-log2-buckets": "10",
                           "priority-classes": "hot=9:101,rest=8:*"})
    assert inst.enabled and len(inst._inv_classes) == 2


# ---------------------------------------------------------------------------
# operator harvest: decode, ring-miss reporting, overflow accounting
# ---------------------------------------------------------------------------

def _two_tier_stream(rng, base: int):
    """12 per-node-heavy keys (counts 500..390) + one moderate key X
    (count 300): X sits outside a k=8 candidate ring on every node but
    dominates any single key once two nodes merge."""
    counts = {np.uint64(base + i): 500 - 10 * i for i in range(12)}
    x = np.uint64(9999)
    counts[x] = 300
    keys = np.repeat(np.array(list(counts), dtype=np.uint64),
                     list(counts.values()))
    rng.shuffle(keys)
    return keys, counts, x


def test_harvest_decodes_ring_missed_keys_and_flags_overflow():
    rng = np.random.default_rng(1)
    keys, counts, x = _two_tier_stream(rng, 1000)
    x32 = int(fold64_to_32(np.array([x]))[0])
    truth = {int(fold64_to_32(np.array([k]))[0]): c
             for k, c in counts.items()}
    inst = _make_instance({"invertible": "true", "inv-log2-buckets": "8"})

    def overflow_total() -> float:
        return sum(v for k, v in telemetry_registry.snapshot().items()
                   if k.startswith("ig_sketch_candidate_overflow_total"))

    before = overflow_total()
    inst.enrich_batch(_batch(keys))
    s = inst.harvest()
    # 13 distinct candidates > k=8: the ring saturated and says so
    assert s.approx is True
    assert overflow_total() == before + 1
    # a second harvest must not double-count the same run
    inst.harvest()
    assert overflow_total() == before + 1
    # decode recovers EVERY key exactly (13 distinct << capacity)
    assert dict(s.decoded) == truth
    assert s.inv["complete"] is True
    # the ring (k=8) missed X; decode reports exactly that
    ring = {k for k, _ in s.heavy_hitters}
    assert x32 not in ring
    assert (x32, 300) in s.decoded_only


def test_no_overflow_no_flag():
    inst = _make_instance({"invertible": "true", "inv-log2-buckets": "8"})
    keys = np.repeat(np.arange(1, K_RING + 1, dtype=np.uint64), 20)
    inst.enrich_batch(_batch(keys))
    s = inst.harvest()
    assert s.approx is False
    assert s.decoded_only == []


def test_priority_classes_protect_hot_tenant():
    """PSketch semantics: the flood tenant overloads its class (decode
    partial, reported), the hot tenant's class stays COMPLETE and exact
    under the same total memory budget."""
    rng = np.random.default_rng(2)
    hot_keys = rng.choice(np.arange(1, 1 << 20, dtype=np.uint64), 50,
                          replace=False)
    hot_truth = {int(fold64_to_32(np.array([k]))[0]): 4 for k in hot_keys}
    flood_keys = rng.choice(np.arange(1 << 20, 1 << 22, dtype=np.uint64),
                            3000, replace=False)
    keys = np.concatenate([np.repeat(hot_keys, 4), flood_keys])
    mntns = np.concatenate([np.full(200, 101, np.uint64),
                            np.full(3000, 202, np.uint64)])
    order = rng.permutation(len(keys))
    inst = _make_instance({
        "invertible": "true", "inv-log2-buckets": "10",
        "priority-classes": "hot=9:101,rest=8:*"})
    inst.enrich_batch(_batch(keys[order], mntns[order]))
    s = inst.harvest()
    assert s.classes is not None
    hot = s.classes["hot"]
    rest = s.classes["rest"]
    # hot tenant: 50 distinct << capacity(3, 2^9)=384 → complete + exact
    assert hot["complete"] is True
    assert dict(hot["decoded"]) == dict(
        sorted(hot_truth.items(), key=lambda kv: (-kv[1], kv[0]))[:32])
    assert hot["residual_events"] == 0
    # flood tenant: 3000 distinct >> capacity(3, 2^8)=192 → partial,
    # honestly reported — never wrong, just incomplete
    assert rest["complete"] is False
    assert rest["recovered"] < 3000


@pytest.mark.skipif("config.getoption('-m', default='') == 'slow'")
def test_sharded_summary_decoded_identical_to_single_chip():
    """The inv plane rides the lane-stacked bundle and the psum harvest:
    summaries (decoded keys included) are identical at any chip count —
    the PR-11 bit-identity contract extended to the new plane."""
    import jax
    if jax.local_device_count() < 4:
        pytest.skip("needs the 8-device CPU topology from conftest")
    rng = np.random.default_rng(3)
    keys, _counts, _x = _two_tier_stream(rng, 3000)
    batches = [keys[i::3] for i in range(3)]
    ref = _make_instance({"invertible": "true", "inv-log2-buckets": "8"})
    shard = _make_instance({"invertible": "true", "inv-log2-buckets": "8",
                            "shard-ingest": "true", "chips": "4"})
    for b in batches:
        ref.enrich_batch(_batch(b))
        shard.enrich_batch(_batch(b))
    s_ref, s_shard = ref.harvest(), shard.harvest()
    assert s_ref.decoded == s_shard.decoded
    assert s_ref.decoded_only == s_shard.decoded_only
    assert s_ref.heavy_hitters == s_shard.heavy_hitters
    assert s_ref.approx == s_shard.approx
    shard.post_gadget_run()
    ref.post_gadget_run()


def test_priority_classes_resume_from_checkpoint(tmp_path):
    """Class sketches checkpoint/resume like the bundle: after a
    restart, per-class decodes still reproduce whole-stream totals
    (the class_weights invariant) instead of silently under-reporting
    the pre-restart half."""
    from inspektor_gadget_tpu.operators import tpusketch

    tpusketch.set_checkpoint_dir(str(tmp_path))
    try:
        params = {"invertible": "true", "inv-log2-buckets": "10",
                  "priority-classes": "hot=9:101,rest=8:*"}
        keys = np.repeat(np.arange(1, 21, dtype=np.uint64), 15)
        mntns = np.full(len(keys), 101, np.uint64)
        inst = _make_instance(params)
        inst.enrich_batch(_batch(keys, mntns))
        inst.checkpoint()
        # "restart": a fresh instance resumes bundle AND class state
        inst2 = _make_instance(params)
        inst2.enrich_batch(_batch(keys, mntns))
        s = inst2.harvest()
        truth = {int(fold64_to_32(np.array([np.uint64(k)]))[0]): 30
                 for k in range(1, 21)}
        assert dict(s.decoded) == truth          # whole-stream: 2×15
        assert dict(s.classes["hot"]["decoded"]) == truth  # class matches
        assert s.classes["hot"]["complete"] is True
    finally:
        tpusketch.set_checkpoint_dir(None)


# ---------------------------------------------------------------------------
# acceptance: 2-node fleet — decode of MERGED windows recovers the
# aggregate-heavy key both candidate rings missed
# ---------------------------------------------------------------------------

@pytest.fixture()
def fleet_store(tmp_path):
    HISTORY.set_base_dir(str(tmp_path))
    yield str(tmp_path)
    HISTORY.close_all()
    HISTORY.set_base_dir(None)


def test_two_node_merged_decode_recovers_aggregate_heavy_key(fleet_store):
    rng = np.random.default_rng(4)
    truth_total: dict[int, int] = {}
    x32 = int(fold64_to_32(np.array([np.uint64(9999)]))[0])
    for node, base in (("nA", 1000), ("nB", 2000)):
        keys, counts, _x = _two_tier_stream(rng, base)
        # a zipf tail per node (keys shared across nodes) on top of the
        # two-tier head: the acceptance stream shape from the issue
        tail_keys = rng.choice(np.arange(50_000, 50_120, dtype=np.uint64),
                               60, replace=False)
        tail_counts = rng.zipf(1.5, 60).clip(1, 99).astype(np.int64)
        for k, c in zip(tail_keys.tolist(), tail_counts.tolist()):
            counts[np.uint64(k)] = counts.get(np.uint64(k), 0) + int(c)
        keys = np.concatenate([keys, np.repeat(tail_keys, tail_counts)])
        rng.shuffle(keys)
        for k, c in counts.items():
            k32 = int(fold64_to_32(np.array([k]))[0])
            truth_total[k32] = truth_total.get(k32, 0) + c
        inst = _make_instance(
            {"invertible": "true", "inv-log2-buckets": "9",
             "history": "true", "history-interval": "0",
             "history-log2-width": "8", "history-slots": "2"},
            node=node)
        # two batches per node → window deltas must re-merge exactly
        inst.enrich_batch(_batch(keys[: len(keys) // 2]))
        inst.seal_window()
        inst.enrich_batch(_batch(keys[len(keys) // 2:]))
        inst.seal_window()
        HISTORY.release(inst._hist_writer)
    frames = list(HISTORY.fetch_windows(base_dir=fleet_store,
                                        gadget=GADGET))
    assert len(frames) == 4  # 2 nodes × 2 windows
    ans = answer_query(decode_frames(frames), top=512)
    # every ground-truth key above the documented threshold (here: all
    # 25 keys — the load is far under capacity) decodes with its EXACT
    # aggregate count
    got = {k: c for k, c, _label in ans.heavy_flows}
    assert got == truth_total
    assert ans.inv["complete"] is True
    # X (300 per node, outside both k=8 rings) is the TOP aggregate key
    # — and the candidate path never saw it
    assert ans.heavy_flows[0][0] == x32
    assert ans.heavy_flows[0][1] == 600
    ring = {k for k, _c, _label in ans.heavy_hitters}
    assert x32 not in ring
    assert x32 in {k for k, _c, _label in ans.decoded_only}
    # JSON surface (satellite 2): the decoded-only field rides to_dict
    doc = ans.to_dict()
    assert any(row["count"] == 600 for row in doc["heavy_flows"])
    assert any(row["key"] == f"0x{x32:08x}" for row in doc["decoded_only"])


def test_query_cli_reports_heavy_flows_json(fleet_store, capsys):
    rng = np.random.default_rng(5)
    keys, counts, _x = _two_tier_stream(rng, 4000)
    inst = _make_instance(
        {"invertible": "true", "inv-log2-buckets": "8",
         "history": "true", "history-interval": "0",
         "history-log2-width": "8", "history-slots": "2"}, node="nQ")
    inst.enrich_batch(_batch(keys))
    inst.seal_window()
    HISTORY.release(inst._hist_writer)

    from inspektor_gadget_tpu.cli.query import cmd_query

    class _Args:
        remote = ""
        history = fleet_store
        gadget = GADGET
        start_ts = None
        end_ts = None
        last = ""
        start_seq = None
        end_seq = None
        key = ""
        slices = False
        top = 20
        output = "json"

    assert cmd_query(_Args()) == 0
    doc = json.loads(capsys.readouterr().out)
    x32 = int(fold64_to_32(np.array([np.uint64(9999)]))[0])
    flows = {int(r["key"], 16): r["count"] for r in doc["heavy_flows"]}
    assert flows[x32] == 300
    assert doc["inv"]["complete"] is True
    assert any(int(r["key"], 16) == x32 for r in doc["decoded_only"])


# ---------------------------------------------------------------------------
# alerts: the heavy_flow detector kind
# ---------------------------------------------------------------------------

def test_heavy_flow_rule_validation():
    from inspektor_gadget_tpu.alerts.rules import RuleError, load_rules

    rules = load_rules(json.dumps([{"id": "hf", "kind": "heavy_flow",
                                    "threshold": 100}]))
    assert rules[0].kind == "heavy_flow"
    assert "invertible" in rules[0].describe()
    with pytest.raises(RuleError, match="missing 'threshold'"):
        load_rules(json.dumps([{"id": "hf", "kind": "heavy_flow"}]))
    with pytest.raises(RuleError, match="remove field"):
        load_rules(json.dumps([{"id": "hf", "kind": "heavy_flow",
                                "threshold": 1, "field": "events"}]))


def test_heavy_flow_rule_fires_per_decoded_key_and_resolves():
    from inspektor_gadget_tpu.alerts.engine import AlertEngine
    from inspektor_gadget_tpu.alerts.rules import load_rules

    rules = load_rules(json.dumps([{"id": "hf", "kind": "heavy_flow",
                                    "threshold": 100, "severity":
                                    "critical"}]))
    eng = AlertEngine(rules, node="n0", gadget=GADGET, dry_run=True)
    base = {"events": 1000, "drops": 0, "distinct": 10.0, "entropy": 1.0,
            "epoch": 1, "heavy_hitters": [], "anomaly": {}}
    evs = eng.observe({**base, "decoded": [[0xAB, 500], [0xCD, 50]]},
                      now=10.0)
    fired = {(e.key, e.transition) for e in evs}
    assert ("key:0x000000ab", "firing") in fired          # exact + above
    assert not any(k == "key:0x000000cd" for k, _t in fired)  # below
    # the key stops decoding → vanished-key sweep resolves it
    evs2 = eng.observe({**base, "epoch": 2, "decoded": []}, now=20.0)
    assert {(e.key, e.transition) for e in evs2} == {
        ("key:0x000000ab", "resolved")}


def test_summary_wire_roundtrip_carries_inv_fields():
    from inspektor_gadget_tpu.agent import wire
    from inspektor_gadget_tpu.operators.tpusketch import SketchSummary

    s = SketchSummary(
        events=10, drops=0, distinct=3.0, entropy_bits=1.5,
        heavy_hitters=[(1, 5)], epoch=2, approx=True,
        decoded=[(1, 5), (7, 3)], decoded_only=[(7, 3)],
        inv={"recovered": 2, "complete": True, "residual_events": 0,
             "capacity": 768},
        classes={"hot": {"complete": True, "decoded": [[1, 5]]}})
    h, payload = wire.encode_summary(s)
    out = wire.decode_summary(h, payload)
    assert out["approx"] is True
    assert out["decoded"] == [[1, 5], [7, 3]]
    assert out["decoded_only"] == [[7, 3]]
    assert out["inv"]["complete"] is True
    assert out["classes"]["hot"]["decoded"] == [[1, 5]]
    # plane-off summaries keep the pre-plane header shape exactly
    plain = SketchSummary(events=1, drops=0, distinct=1.0,
                          entropy_bits=0.0, heavy_hitters=[])
    h2, _ = wire.encode_summary(plain)
    assert not ({"approx", "decoded", "decoded_only", "inv", "classes"}
                & set(h2))


# ---------------------------------------------------------------------------
# perf: micro-bench records + harness stages (tier-1 smoke)
# ---------------------------------------------------------------------------

def test_invertible_bench_publishes_schema_valid_records(tmp_path):
    from inspektor_gadget_tpu.perf.invertible_bench import publish
    from inspektor_gadget_tpu.perf.ledger import read_ledger
    from inspektor_gadget_tpu.perf.schema import validate_record

    ledger = str(tmp_path / "PERF.jsonl")
    records = publish(batch=1 << 10, n_keys=128, rows=2, log2_buckets=9,
                      seconds=0.05, ledger=ledger)
    assert {r["config"] for r in records} == {"inv-update", "inv-decode"}
    for rec in records:
        assert validate_record(rec) == []
    assert records[1]["extra"]["complete"] == 1.0
    on_disk = read_ledger(ledger).records
    assert len(on_disk) == 2
    # the series gates like any other: fresh series → no baseline → rc 0
    from inspektor_gadget_tpu.perf.compare import compare_ledger
    results = compare_ledger(on_disk)
    assert all(r.rc == 0 for r in results)


def test_harness_tiny_invertible_smoke():
    from inspektor_gadget_tpu.perf.harness import run_harness
    from inspektor_gadget_tpu.perf.schema import validate_record

    rec = run_harness("tiny", platform="cpu", invertible=True)
    assert validate_record(rec) == []
    assert rec["extra"]["invertible"] is True
    assert "+inv" in rec["extra"]["pipeline"]
    assert "inv_update" in rec["stages"]
    assert "inv_decode" in rec["stages"]
    with pytest.raises(ValueError, match="single-chip"):
        run_harness("tiny", platform="cpu", invertible=True,
                    pipeline="sharded", chips=2)
