"""Tiered-history acceptance tier (ISSUE 13):

A 2-agent fleet seals ≥ 30 fine windows per node under a
``fine@short,coarse@∞`` schedule; then

(a) a fleet range query answered via QueryWindows PUSHDOWN returns one
    merged window per node and matches the pre-compaction
    fetch-and-fold ground truth — additive planes and HLL registers
    exactly, top-k candidate sums exactly (both folds read the same
    sealed candidate lists);
(b) compaction shrinks the store's byte footprint and every source
    window's seq/ts coverage lands in EXACTLY one super-window
    (``compacted_from`` provenance audited);
(c) a real SIGKILL mid-compaction (after the super-windows are durable,
    before source GC) then reopen loses no coverage and double-counts
    nothing — digest-audited, and the next pass converges;
(d) archiving the cold level then querying an archived range rehydrates
    through the manifest, digest-verified, and answers identically.

Tests run in file order: each stage inspects the state the previous one
left (fine windows → crashed compaction → finished compaction →
archive), the way the lifecycle runs in production.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.agent.service import serve
from inspektor_gadget_tpu.gadgets import GadgetContext
from inspektor_gadget_tpu.gadgets import registry as gadget_registry
from inspektor_gadget_tpu.gadgets.interface import GadgetDesc, GadgetType
from inspektor_gadget_tpu.history import (
    HISTORY,
    CompactionEngine,
    decode_frames,
    dedupe_compacted,
    merge_windows,
)
from inspektor_gadget_tpu.operators import operators as op_registry
from inspektor_gadget_tpu.params import Collection, ParamDescs

GADGET = "trace/tiersynth"
N_WINDOWS = 32          # fine windows per node (>= 30 acceptance floor)
BATCH = 256
SCHEDULE = "1s@30s,120s@inf"       # fine@short,coarse@inf
FAR = 1_000_000.0                   # age offset that outruns the horizon

_RNG = np.random.default_rng(33)
_PHASES = []
for _i in range(N_WINDOWS):
    a = (_RNG.zipf(1.5, size=BATCH // 2).clip(1, 64).astype(np.uint64)
         * np.uint64(0x9E3779B97F4A7C15))
    b = _RNG.integers(1, 2 ** 48, BATCH // 2).astype(np.uint64)
    keys = np.concatenate([a, b])
    mntns = np.concatenate([np.full(BATCH // 2, 101, np.uint64),
                            np.full(BATCH // 2, 202, np.uint64)])
    kind = np.concatenate([np.full(BATCH // 4, 10, np.uint32),
                           np.full(BATCH // 4, 11, np.uint32),
                           np.full(BATCH // 2, 11, np.uint32)])
    _PHASES.append((keys, mntns, kind))


class _TierSynthGadget:
    def __init__(self, ctx):
        self.ctx = ctx
        self._batch_handler = None

    def set_batch_handler(self, handler):
        self._batch_handler = handler

    def run(self, ctx):
        from inspektor_gadget_tpu.operators import tpusketch
        from inspektor_gadget_tpu.sources.batch import EventBatch
        inst = next((i for i in tpusketch.live_instances()
                     if i.ctx.run_id == ctx.run_id), None)
        for keys, mntns, kind in _PHASES:
            if ctx.done:
                return
            b = EventBatch.alloc(len(keys), with_comm=False)
            b.cols["key_hash"][:] = keys
            b.cols["mntns"][:] = mntns
            b.cols["kind"][:] = kind
            b.cols["ts"][:] = time.time_ns()
            b.count = len(keys)
            if self._batch_handler is not None:
                self._batch_handler(b)
            if inst is not None:
                inst.harvest()   # history-interval 0: one window/harvest
            ctx.sleep_or_done(0.01)


class _TierSynthDesc(GadgetDesc):
    name = "tiersynth"
    category = "trace"
    gadget_type = GadgetType.TRACE
    description = "scripted two-tenant batch gadget (tiers e2e)"
    event_cls = None

    def params(self) -> ParamDescs:
        return ParamDescs()

    def new_instance(self, ctx) -> _TierSynthGadget:
        return _TierSynthGadget(ctx)


@pytest.fixture(scope="module", autouse=True)
def synth_gadget():
    desc = _TierSynthDesc()
    gadget_registry.register(desc)
    yield desc
    gadget_registry._REGISTRY.pop((desc.category, desc.name), None)


@pytest.fixture(scope="module")
def agents():
    servers, targets = [], {}
    tmp = tempfile.mkdtemp()
    for i in range(2):
        addr = f"unix://{tmp}/tier-agent{i}.sock"
        server, _ = serve(addr, node_name=f"tnode-{i}")
        servers.append(server)
        targets[f"tnode-{i}"] = addr
    yield targets
    for s in servers:
        s.stop(grace=0.5)


@pytest.fixture(scope="module")
def history_area(tmp_path_factory):
    base = str(tmp_path_factory.mktemp("tiers-area"))
    HISTORY.set_base_dir(base)
    yield base
    HISTORY.close_all()
    HISTORY.set_archive(None)
    HISTORY.set_base_dir(None)


def _op_params() -> Collection:
    col = Collection()
    sp = op_registry.get("tpusketch").instance_params().to_params()
    for k, v in (("enable", "true"), ("depth", "4"), ("log2-width", "10"),
                 ("hll-p", "10"), ("entropy-log2-width", "8"),
                 ("topk", "32"), ("harvest-interval", "1h"),
                 ("history", "true"), ("history-interval", "0"),
                 ("history-log2-width", "10"), ("history-slots", "4")):
        sp.set(k, v)
    col["operator.tpusketch."] = sp
    return col


def _store_dir(base: str, node: str) -> str:
    return os.path.join(base, f"{node}--trace-tiersynth")


def _store_bytes(store_dir: str) -> int:
    return sum(os.path.getsize(os.path.join(store_dir, f))
               for f in os.listdir(store_dir) if f.startswith("seg-"))


def _node_fold(base: str, node: str):
    """fetch-and-fold through the store (the PR-6 ground-truth path),
    deduped across tiers."""
    frames = list(HISTORY.fetch_windows(base_dir=base, gadget=GADGET,
                                        node=node))
    kept, notes = dedupe_compacted(decode_frames(frames))
    return merge_windows(kept), kept, notes


@pytest.fixture(scope="module")
def fleet_run(agents, history_area):
    """Run the scripted gadget on both agents (history plane on), then
    capture the PRE-COMPACTION ground truth: every node's decoded
    windows, their fold, digests, and byte footprint."""
    from inspektor_gadget_tpu.runtime.grpc_runtime import GrpcRuntime
    runtime = GrpcRuntime(dict(agents))
    try:
        desc = gadget_registry.get("trace", "tiersynth")
        ctx = GadgetContext(desc, operator_params=_op_params(),
                            timeout=240.0)
        run = runtime.run_gadget(ctx)
        assert not run.errors(), run.errors()
    finally:
        runtime.close()
    truth = {}
    for node in agents:
        merged, kept, notes = _node_fold(history_area, node)
        assert notes == []
        truth[node] = {
            "merged": merged,
            "digests": sorted(w.digest for w in kept),
            "windows": len(kept),
            "bytes": _store_bytes(_store_dir(history_area, node)),
            "spans": sorted((w.start_ts, w.end_ts) for w in kept),
        }
    return truth


def _assert_node_merge_equals(got, want):
    """Additive planes + HLL registers exactly; candidate sums exactly
    (both folds read the same sealed candidate lists); slice events and
    slice HLLs exactly."""
    assert got.events == want.events
    assert got.drops == want.drops
    assert np.array_equal(got.cms, want.cms)
    assert np.array_equal(got.hll, want.hll)
    # entropy buckets are integer-valued float32 deltas summed in
    # float64: exact below 2^24 events, which 32×256 is
    assert np.array_equal(got.ent, want.ent)
    assert got.candidates == want.candidates
    assert set(got.slices) == set(want.slices)
    for skey, s in want.slices.items():
        assert got.slices[skey]["events"] == s["events"]
        assert np.array_equal(got.slices[skey]["hll"], s["hll"])


def test_fleet_seals_fine_windows_per_node(fleet_run, agents):
    from inspektor_gadget_tpu.agent.client import AgentClient
    for node, target in agents.items():
        assert fleet_run[node]["windows"] >= 30
        c = AgentClient(target, node)
        try:
            rows = c.list_windows(gadget=GADGET)["windows"]
            assert len(rows) == N_WINDOWS
            assert all(int(r.get("level", 0)) == 0 for r in rows)
            assert {r["node"] for r in rows} == {node}
        finally:
            c.close()


def test_sigkill_mid_compaction_loses_no_coverage(fleet_run, agents,
                                                  history_area):
    """(c) A REAL SIGKILL after the super-windows are durable and
    before source GC: both tiers are on disk; queries dedup to
    exactly-once; reopen + rerun converges with nothing lost."""
    node = "tnode-0"
    store_dir = _store_dir(history_area, node)
    aged_clock = time.time() + FAR
    child = subprocess.run([
        sys.executable, "-c",
        "import os, signal, sys\n"
        "from inspektor_gadget_tpu.history import (CompactionEngine,\n"
        "    HistoryStore)\n"
        "store = HistoryStore(); store.set_base_dir(sys.argv[1])\n"
        "eng = CompactionEngine(sys.argv[3], store=store,\n"
        "                       clock=lambda: float(sys.argv[4]))\n"
        "eng._before_gc = lambda: os.kill(os.getpid(), signal.SIGKILL)\n"
        "eng.compact_store(sys.argv[2])\n",
        history_area, store_dir, SCHEDULE, str(aged_clock),
    ], timeout=120)
    assert child.returncode == -signal.SIGKILL

    # both tiers on disk: every source must fold exactly once
    frames = list(HISTORY.fetch_windows(base_dir=history_area,
                                        gadget=GADGET, node=node))
    assert len(frames) > N_WINDOWS   # sources + durable super-windows
    merged, kept, notes = _node_fold(history_area, node)
    assert notes and all("superseded" in n for n in notes)
    _assert_node_merge_equals(merged, fleet_run[node]["merged"])
    # ... and the fleet query (pushdown, through the agent) agrees
    from inspektor_gadget_tpu.agent.client import AgentClient
    c = AgentClient(agents[node], node)
    try:
        res = c.query_windows(gadget=GADGET)
        assert res["dropped"] and res["window"] is not None
        _assert_node_merge_equals(merge_windows([res["window"]]),
                                  fleet_run[node]["merged"])
    finally:
        c.close()

    # reopen (the writer the child mutated must be re-recovered) and
    # finish: covered sources GC'd, nothing re-merged
    HISTORY.close_all()
    engine = CompactionEngine(SCHEDULE, clock=lambda: time.time() + FAR)
    stats = engine.compact_store(store_dir)
    assert stats["super_windows"] == 0
    assert stats["segments_deleted"] >= 1
    merged, kept, notes = _node_fold(history_area, node)
    assert notes == []
    assert all(w.level == 1 for w in kept)
    _assert_node_merge_equals(merged, fleet_run[node]["merged"])


def test_pushdown_after_compaction_matches_ground_truth(fleet_run,
                                                        agents,
                                                        history_area):
    """(a) + (b): compact BOTH nodes, audit provenance and footprint,
    then answer the fleet range query via QueryWindows pushdown — one
    merged window per node, equal to the pre-compaction fetch-and-fold
    ground truth."""
    engine = CompactionEngine(SCHEDULE, clock=lambda: time.time() + FAR)
    for node in agents:
        store_dir = _store_dir(history_area, node)
        engine.compact_store(store_dir)
        # (b) byte footprint shrinks vs the fine-grained store
        assert _store_bytes(store_dir) < fleet_run[node]["bytes"]
        # (b) provenance audit: every fine window's digest in exactly
        # one super-window, and the seq/ts coverage is complete
        merged, kept, notes = _node_fold(history_area, node)
        assert notes == []
        assert kept and all(w.level == 1 for w in kept)
        seen: dict[str, int] = {}
        spans = []
        for w in kept:
            for row in w.compacted_from:
                seen[row["digest"]] = seen.get(row["digest"], 0) + 1
                spans.append((row["start_ts"], row["end_ts"]))
        assert sorted(seen) == fleet_run[node]["digests"]
        assert sorted(seen.values()) == [1] * N_WINDOWS
        want_spans = fleet_run[node]["spans"]
        assert sorted(spans) == want_spans
        _assert_node_merge_equals(merged, fleet_run[node]["merged"])

    # (a) the fleet query runs the pushdown path on every node: one
    # merged window per node, O(nodes) on the wire
    from inspektor_gadget_tpu.agent.client import AgentClient
    from inspektor_gadget_tpu.runtime.grpc_runtime import GrpcRuntime
    for node, target in agents.items():
        c = AgentClient(target, node)
        try:
            res = c.query_windows(gadget=GADGET)
            assert res["window"] is not None
            assert res["levels"] == {1: res["folded"]}
            _assert_node_merge_equals(merge_windows([res["window"]]),
                                      fleet_run[node]["merged"])
        finally:
            c.close()
    runtime = GrpcRuntime(dict(agents))
    try:
        ans = runtime.query_history(gadget=GADGET)
        assert ans.paths == {n: "pushdown" for n in agents}
        assert sorted(ans.nodes) == sorted(agents)
        assert not ans.errors
        # consulted-windows accounting is all super-windows now
        assert set(ans.levels) == {1}
        assert ans.compacted_windows() == ans.windows
        # additive planes exact vs ground truth
        want_events = sum(fleet_run[n]["merged"].events for n in agents)
        assert ans.events == want_events
        # HLL max-merge is exact: the fleet estimate must equal the
        # one computed from the pre-compaction per-node registers
        from inspektor_gadget_tpu.history.window import slice_hll_estimate
        gt_hll = np.maximum(fleet_run["tnode-0"]["merged"].hll,
                            fleet_run["tnode-1"]["merged"].hll)
        assert abs(ans.distinct - slice_hll_estimate(gt_hll)) < 1e-9
    finally:
        runtime.close()


def test_archive_cold_level_and_query_rehydrates(fleet_run, agents,
                                                 history_area,
                                                 tmp_path_factory):
    """(d): offload the (fully-compacted) cold level of one node to the
    archive backend; a query overlapping the archived range rehydrates
    through the manifest, digest-verified, and answers identically."""
    node = "tnode-0"
    store_dir = _store_dir(history_area, node)
    archive_root = str(tmp_path_factory.mktemp("tiers-archive"))
    HISTORY.set_archive(archive_root, 1 << 20)
    tier = HISTORY.archive()
    # compaction left the super-windows in a sealed segment; offload it
    writer = HISTORY.writer_for_dir(store_dir)
    writer.rotate()
    stats = tier.archive_store(store_dir, min_level=1, writer=writer)
    assert stats["segments"] >= 1 and stats["windows"] >= 1
    rows = tier.manifest_rows(store_dir)
    assert rows and all(r["digest"] for r in rows)
    archived_files = {r["file"] for r in rows}
    assert not any(os.path.isfile(os.path.join(store_dir, f))
                   for f in archived_files)

    # local fold rehydrates and answers identically (digest-verified)
    merged, kept, notes = _node_fold(history_area, node)
    assert notes == []
    _assert_node_merge_equals(merged, fleet_run[node]["merged"])
    assert tier.misses >= 1

    # and the AGENT answers the same through QueryWindows pushdown —
    # rehydration is node-side, the client never knows
    from inspektor_gadget_tpu.agent.client import AgentClient
    c = AgentClient(agents[node], node)
    try:
        res = c.query_windows(gadget=GADGET)
        assert res["window"] is not None
        _assert_node_merge_equals(merge_windows([res["window"]]),
                                  fleet_run[node]["merged"])
    finally:
        c.close()

    # a corrupted archive object is REPORTED, never silently merged:
    # flip a byte in the backend, drop the cache, query again
    obj_path = tier.backend._path(rows[0]["object"])
    data = bytearray(open(obj_path, "rb").read())
    data[len(data) // 2] ^= 0xFF
    open(obj_path, "wb").write(bytes(data))
    HISTORY.set_archive(archive_root, 1 << 20)   # fresh tier, empty LRU
    import shutil
    shutil.rmtree(os.path.join(history_area, ".archive-cache"),
                  ignore_errors=True)
    losses: list = []
    frames = list(HISTORY.fetch_windows(base_dir=history_area,
                                        gadget=GADGET, node=node,
                                        losses=losses))
    assert any("digest mismatch" in loss["reason"] for loss in losses)
    got = merge_windows(dedupe_compacted(decode_frames(frames))[0])
    assert got.events < fleet_run[node]["merged"].events
