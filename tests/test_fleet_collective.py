"""DCN collective merge tier (ISSUE 20): `make_fleet_merge` must be
the PR-11 cluster harvest unchanged — bit-identical to `cluster_merge`
on one process, deterministic across placements, and (when the backend
supports cross-process CPU collectives) bit-identical between the two
halves of a simulated two-host world."""

from __future__ import annotations

import json
import socket
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from inspektor_gadget_tpu.fleet.collective import (
    bundle_digest,
    fleet_collective_merge,
    make_fleet_merge,
    shard_over_nodes,
)
from inspektor_gadget_tpu.ops import bundle_init, bundle_update
from inspektor_gadget_tpu.parallel import make_mesh
from inspektor_gadget_tpu.parallel.compat import shard_map
from inspektor_gadget_tpu.parallel.mesh import NODE_AXIS

N_NODES = 8
BATCH = 256
BUNDLE_KW = dict(depth=4, log2_width=10, hll_p=8, entropy_log2_width=7,
                 k=16)


def per_node_bundles(seed: int = 0):
    """One updated bundle per node, stacked on a leading node axis —
    what the sharded harvest leaves per chip."""
    rng = np.random.default_rng(seed)
    keys = rng.zipf(1.3, (N_NODES, BATCH)).clip(1, 10_000).astype(
        np.uint32)
    rows = []
    for i in range(N_NODES):
        b = bundle_init(**BUNDLE_KW)
        k = jnp.asarray(keys[i])
        rows.append(bundle_update(b, k, k, k, jnp.ones(BATCH, bool)))
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *rows)
    return stacked, keys


def test_fleet_merge_bit_identical_to_cluster_merge():
    stacked, _ = per_node_bundles()
    mesh = make_mesh(n_nodes=N_NODES)
    merged = make_fleet_merge(mesh)(stacked)

    # the PR-11 path, driven directly through the same shard_map shape
    def specs_like(tree, spec):
        return jax.tree.map(lambda _: spec, tree)

    from jax.sharding import PartitionSpec as P
    reference = jax.jit(shard_map(
        fleet_collective_merge, mesh=mesh,
        in_specs=(specs_like(stacked, P(NODE_AXIS)),),
        out_specs=specs_like(jax.tree.map(lambda x: x[0], stacked), P()),
        check_vma=False))(stacked)
    assert bundle_digest(merged) == bundle_digest(reference)


def test_fleet_merge_deterministic_and_placement_independent():
    stacked, _ = per_node_bundles(seed=3)
    mesh = make_mesh(n_nodes=N_NODES)
    merge = make_fleet_merge(mesh)
    d1 = bundle_digest(merge(stacked))
    d2 = bundle_digest(merge(stacked))
    assert d1 == d2
    # pre-placing the rows on the node axis (what each real host does
    # with make_array_from_process_local_data) changes nothing
    d3 = bundle_digest(merge(shard_over_nodes(mesh, stacked)))
    assert d1 == d3


def test_fleet_merge_integer_lanes_are_exact_sums():
    stacked, keys = per_node_bundles(seed=5)
    mesh = make_mesh(n_nodes=N_NODES)
    merged = make_fleet_merge(mesh)(stacked)
    # CMS psum = per-node table sum, HLL pmax = register max — exact
    np.testing.assert_array_equal(
        np.asarray(merged.cms.table),
        np.asarray(stacked.cms.table).sum(axis=0))
    np.testing.assert_array_equal(
        np.asarray(merged.hll.registers),
        np.asarray(stacked.hll.registers).max(axis=0))
    assert float(merged.events) == float(N_NODES * BATCH)


WORKER = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, os.getcwd())
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")

    coord, pid = sys.argv[1], int(sys.argv[2])
    from inspektor_gadget_tpu.parallel.distributed import (
        init_distributed, make_multihost_mesh, world_size,
    )
    init_distributed(coord, num_processes=2, process_id=pid)
    assert world_size() == 2

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from inspektor_gadget_tpu.fleet.collective import (
        bundle_digest, make_fleet_merge,
    )
    from inspektor_gadget_tpu.ops import bundle_init, bundle_update
    from inspektor_gadget_tpu.parallel.mesh import NODE_AXIS

    mesh = make_multihost_mesh()
    n_nodes = mesh.shape[NODE_AXIS]  # 2 procs x 2 virtual devices
    rng = np.random.default_rng(0)
    keys = rng.zipf(1.3, (n_nodes, 256)).clip(1, 10_000).astype(
        np.uint32)
    rows = []
    for i in range(n_nodes):
        b = bundle_init(depth=4, log2_width=10, hll_p=8,
                        entropy_log2_width=7, k=16)
        k = jnp.asarray(keys[i])
        rows.append(bundle_update(b, k, k, k, jnp.ones(256, bool)))
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *rows)
    sharding = NamedSharding(mesh, P(NODE_AXIS))
    local = jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(
            sharding, np.asarray(x)[pid * 2:(pid + 1) * 2]), stacked)
    try:
        merged = make_fleet_merge(mesh)(local)
    except Exception as e:
        if "Multiprocess computations aren't implemented" in str(e):
            print(json.dumps({"skip": str(e)}), flush=True)
            sys.exit(0)
        raise
    host_view = jax.tree.map(
        lambda a: np.asarray(a.addressable_shards[0].data), merged)
    print(json.dumps({"pid": pid,
                      "digest": bundle_digest(host_view),
                      "events": float(host_view.events)}))
""")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_fleet_merge_digests_match(tmp_path):
    """Both hosts of a simulated 2-process DCN world must materialize
    the SAME fleet bundle — digest-compared across processes, the
    multi-host form of the tier's bit-identity contract."""
    coord = f"127.0.0.1:{_free_port()}"
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coord, str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd="/root/repo")
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=220)
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        line = [ln for ln in out.splitlines() if ln.startswith("{")][-1]
        outs.append(json.loads(line))
    skips = [o for o in outs if "skip" in o]
    if skips:
        pytest.skip("backend cannot run multiprocess collectives: "
                    f"{skips[0]['skip']}")
    assert outs[0]["digest"] == outs[1]["digest"]
    assert outs[0]["events"] == 4 * 256
