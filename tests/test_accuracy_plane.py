"""Accuracy audit plane (ISSUE 19): error envelopes, the shadow sample,
fleet surfaces, alerting, and the overflow-taint bugfix.

The acceptance story under test: every answer the fleet serves carries
its analytic error envelope for free, and a run with `audit-sample N`
additionally carries OBSERVED error against a deterministic bottom-k
shadow sample whose resident weights are exact ground truth. The sample
merges bit-identically under any fold order (windows, nodes, standing
queries); sealed wire bytes and digests with the plane off stay exactly
as they were before the plane existed; `accuracy_drift` turns an
estimate escaping its envelope into exactly one alert; and the TopK
candidate-overflow flag finally survives the seal boundary as
approx=True on every downstream answer.
"""

from __future__ import annotations

import json
import math
import tempfile
from pathlib import Path

import numpy as np
import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.gadgets import GadgetContext, get
from inspektor_gadget_tpu.history import HISTORY, answer_query, decode_frames
from inspektor_gadget_tpu.operators.operators import get as get_op
from inspektor_gadget_tpu.ops.accuracy import (
    HLL_STDERR_CONST,
    LINEAR_COUNTING_FACTOR,
    AccuracyStats,
    ShadowSample,
    accuracy_block,
    accuracy_ratio,
    cms_bound,
    dd_bound,
    entropy_bias_bound,
    hll_bound,
)
from inspektor_gadget_tpu.sources.batch import EventBatch
from inspektor_gadget_tpu.telemetry import registry as telemetry_registry

GADGET = "trace/exec"
ROOT = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _release_instances():
    """Instances built outside a real gadget run never see
    post_gadget_run — drop them from the live table (checkpoint_all
    iterates it), drain their stagers, and unregister their stats rows
    (including the accuracy plane's) so no state leaks across files."""
    from inspektor_gadget_tpu.operators import tpusketch
    before = set(tpusketch._live)
    yield
    with tpusketch._live_mu:
        fresh = [rid for rid in list(tpusketch._live) if rid not in before]
        insts = [tpusketch._live.pop(rid) for rid in fresh]
    for inst in insts:
        if getattr(inst, "_stager", None) is not None:
            inst._stager.drain()
        for st in getattr(inst, "_lane_stagers", []):
            st.drain()
        inst._stats.unregister()
        inst._pstats.unregister()
        if getattr(inst, "_astats", None) is not None:
            inst._astats.unregister()


@pytest.fixture()
def fleet_store(tmp_path):
    HISTORY.set_base_dir(str(tmp_path))
    yield str(tmp_path)
    HISTORY.close_all()
    HISTORY.set_base_dir(None)


def _make_instance(extra_params: dict, node: str = ""):
    desc = get("trace", "exec")
    ctx = GadgetContext(desc, extra={})
    if node:
        ctx.extra["node"] = node
    op = get_op("tpusketch")
    p = op.instance_params().to_params()
    p.set("enable", "true")
    p.set("depth", "3")
    p.set("log2-width", "10")
    p.set("hll-p", "8")
    p.set("entropy-log2-width", "6")
    p.set("topk", "8")
    p.set("harvest-interval", "1h")
    for k, v in extra_params.items():
        p.set(k, v)
    return op.instantiate(ctx, None, p)


def _batch(keys64: np.ndarray) -> EventBatch:
    b = EventBatch.alloc(len(keys64), with_comm=False)
    b.cols["key_hash"][:] = keys64
    b.count = len(keys64)
    return b


def _zipf_stream(rng, n, vocab, s=1.3):
    """Skewed uint32 key stream over a small vocabulary (host-side, for
    direct ShadowSample property tests)."""
    ranks = np.arange(1, vocab + 1, dtype=np.float64)
    p = ranks ** -s
    p /= p.sum()
    ids = rng.choice(vocab, size=n, p=p)
    keys = rng.integers(1, 1 << 32, vocab, dtype=np.uint64).astype(np.uint32)
    return keys[ids]


# ---------------------------------------------------------------------------
# analytic envelopes: formulas + the docs drift-test
# ---------------------------------------------------------------------------

def test_analytic_bounds_formulas():
    hh = cms_bound(4, 65536, 1e6)
    assert hh["bound"] == pytest.approx(math.e / 65536)
    assert hh["bound_abs"] == pytest.approx(1e6 * math.e / 65536)
    assert hh["confidence"] == pytest.approx(1.0 - math.exp(-4))
    # HLL: ±1.04/√m, linear-counting regime labeled below 2.5·m
    d = hll_bound(8, estimate=100.0)
    assert d["bound"] == pytest.approx(HLL_STDERR_CONST / 16.0)
    assert d["regime"] == "linear_counting"          # 100 ≤ 2.5·256
    assert hll_bound(8, estimate=10_000.0)["regime"] == "raw"
    assert hll_bound(8)["regime"] == "raw"           # no estimate yet
    assert hll_bound(8, estimate=LINEAR_COUNTING_FACTOR * 256)[
        "regime"] == "linear_counting"               # switchover inclusive
    # DDSketch: the α guarantee is the parameter itself
    assert dd_bound(0.02)["bound"] == 0.02
    # entropy: (d − 1)/(2·w·ln 2) bits, floor at d = 1
    e = entropy_bias_bound(6, 100.0)
    assert e["bound"] == pytest.approx(99.0 / (2 * 64 * math.log(2)))
    assert entropy_bias_bound(6, 1.0)["bound"] == 0.0


def test_documented_formulas_match_code_constants():
    """Satellite (d): docs/observability.md states the envelopes with
    the CODE's constants interpolated — bumping HLL_STDERR_CONST or
    LINEAR_COUNTING_FACTOR without re-documenting fails here."""
    text = (ROOT / "docs" / "observability.md").read_text()
    assert f"{HLL_STDERR_CONST:g}/√m" in text
    assert f"{LINEAR_COUNTING_FACTOR:g}·m" in text
    assert "N·e/w" in text
    assert "1 − e^−d" in text
    assert "(d − 1)/(2·w·ln 2)" in text


# ---------------------------------------------------------------------------
# shadow sample: determinism, mergeability, exactness (the tentpole's
# property tests)
# ---------------------------------------------------------------------------

def test_shadow_sample_fold_orders_bit_identical():
    """merge = weighted subsample union over a fixed hash: single-pass,
    chunked incremental (any chunk order), left fold of per-chunk
    samples, and pairwise tree merge all yield the BIT-identical
    canonical state."""
    rng = np.random.default_rng(19)
    keys = _zipf_stream(rng, 20_000, 3000)
    cap = 256
    ref = ShadowSample(cap)
    ref.update(keys)

    chunks = np.array_split(keys, 13)
    for perm_seed in (0, 1, 2):
        order = np.random.default_rng(perm_seed).permutation(len(chunks))
        # incremental updates in permuted chunk order
        inc = ShadowSample(cap)
        for i in order:
            inc.update(chunks[i])
        assert np.array_equal(inc.keys, ref.keys)
        assert np.array_equal(inc.weights, ref.weights)
        # pairwise merges of per-chunk samples, same permuted order
        parts = []
        for i in order:
            s = ShadowSample(cap)
            s.update(chunks[i])
            parts.append(s)
        while len(parts) > 1:                      # tree fold
            parts = [parts[j].merge(parts[j + 1]) if j + 1 < len(parts)
                     else parts[j] for j in range(0, len(parts), 2)]
        assert np.array_equal(parts[0].keys, ref.keys)
        assert np.array_equal(parts[0].weights, ref.weights)
    assert ref.keys.dtype == np.uint32 and ref.weights.dtype == np.int64
    assert len(ref) == cap


def test_shadow_sample_resident_weights_are_exact_ground_truth():
    """The threshold argument: a key surviving the final bottom-k was
    never evicted, so its weight equals the true stream total — the
    property that makes the sample usable as ground truth (zipf
    unbiasedness satellite)."""
    rng = np.random.default_rng(7)
    keys = _zipf_stream(rng, 50_000, 2000)
    sh = ShadowSample(128)
    # feed in chunks (evictions happen mid-stream)
    for c in np.array_split(keys, 17):
        sh.update(c)
    uk, uc = np.unique(keys, return_counts=True)
    truth = dict(zip(uk.tolist(), uc.tolist()))
    assert len(sh) == 128 and sh.full
    for k, w in zip(sh.keys.tolist(), sh.weights.tolist()):
        assert w == truth[k], (k, w, truth[k])
    # the bottom-k estimators read the stream, not the sample
    true_distinct = float(uk.size)
    assert abs(sh.distinct_estimate() - true_distinct) / true_distinct < 0.35
    # observed_hh_err over resident keys with exact counts reads 0
    err, n_aud = sh.observed_hh_err(sh.keys[:16],
                                    sh.weights[:16].astype(np.float64),
                                    float(keys.size))
    assert err == 0.0 and n_aud == 16


def test_shadow_sample_entropy_estimator_regimes():
    """Entropy ground truth: EXACT while the sample never filled
    (nothing evicted → the plug-in entropy of the true multiset), and
    within fractions of a bit on a full sample over a balanced stream
    (the inverse-probability estimator's low-variance regime)."""
    rng = np.random.default_rng(13)
    vocab_keys = rng.integers(1, 1 << 32, 2000, dtype=np.uint64).astype(
        np.uint32)
    # not full: exact to machine precision
    small = vocab_keys[:100][rng.integers(0, 100, 5000)]
    sh = ShadowSample(256)
    sh.update(small)
    uk, uc = np.unique(small, return_counts=True)
    p = uc / uc.sum()
    true_h = float(-(p * np.log2(p)).sum())
    assert not sh.full
    assert sh.entropy_estimate(5000.0) == pytest.approx(true_h)
    # full over a balanced stream: every weight is comparable, so the
    # 1/τ scaling has low variance
    stream = vocab_keys[rng.integers(0, 2000, 50_000)]
    full = ShadowSample(128)
    for c in np.array_split(stream, 17):
        full.update(c)
    uk2, uc2 = np.unique(stream, return_counts=True)
    p2 = uc2 / uc2.sum()
    true_h2 = float(-(p2 * np.log2(p2)).sum())
    assert full.full
    assert abs(full.entropy_estimate(50_000.0) - true_h2) < 0.7


def test_shadow_sample_empty_and_off_noops():
    off = ShadowSample(0)
    off.update(np.arange(10, dtype=np.uint32))
    assert len(off) == 0                      # capacity 0: plane off
    s = ShadowSample(8)
    s.update(np.zeros(0, dtype=np.uint32))
    assert len(s) == 0                        # empty batch: no-op
    s.update(np.arange(1, 5, dtype=np.uint32))
    before_k, before_w = s.keys.copy(), s.weights.copy()
    merged = s.merge(ShadowSample(8))         # empty merge: identity
    assert np.array_equal(merged.keys, before_k)
    assert np.array_equal(merged.weights, before_w)
    with pytest.raises(ValueError, match="capacity mismatch"):
        s.merge(ShadowSample(16))
    s.reset()
    assert len(s) == 0 and s.distinct_estimate() == 0.0


def test_accuracy_block_and_ratio_shapes():
    rng = np.random.default_rng(3)
    keys = _zipf_stream(rng, 5_000, 60)
    sh = ShadowSample(256)
    sh.update(keys)
    uk, uc = np.unique(keys, return_counts=True)
    top = np.argsort(uc)[::-1][:8]
    blk = accuracy_block(
        events=float(keys.size), depth=3, width=1024, hll_p=8,
        ent_log2_width=6, distinct=float(uk.size),
        entropy_bits=2.0, hh_keys=uk[top],
        hh_counts=uc[top].astype(np.int64), qt_alpha=0.01, shadow=sh)
    assert blk["audited"] is True
    assert blk["sample_size"] == uk.size and blk["sample_capacity"] == 256
    hh = blk["stats"]["heavy_hitters"]
    assert hh["audited"] and hh["observed_err"] == 0.0   # exact counts fed
    assert hh["audited_keys"] == 8
    assert blk["stats"]["distinct"]["audited"]
    assert blk["stats"]["distinct"]["observed_err"] == 0.0  # truth == truth
    assert blk["stats"]["entropy"]["audited"]
    # the value lane has no shadow: quantiles stay analytic-only
    qt = blk["stats"]["quantiles"]
    assert qt == {"bound": 0.01, "observed_err": None, "audited": False}
    assert blk["ratio"] == accuracy_ratio(blk)
    # unaudited: bounds ride, observations don't, ratio reads 0 (idle
    # immunity — "no observation" is not "zero error")
    off = accuracy_block(events=1000.0, depth=3, width=1024, hll_p=8,
                         ent_log2_width=6, distinct=50.0, shadow=None)
    assert off["audited"] is False and off["ratio"] == 0.0
    assert off["stats"]["heavy_hitters"]["bound"] > 0
    assert all(not s["audited"] for s in off["stats"].values())
    assert accuracy_ratio(None) == 0.0


# ---------------------------------------------------------------------------
# operator harvest: the accuracy block + telemetry accounting
# ---------------------------------------------------------------------------

def _metric(name: str) -> float:
    return sum(v for k, v in telemetry_registry.snapshot().items()
               if k.startswith(name))


def test_harvest_summary_accuracy_and_telemetry():
    rng = np.random.default_rng(11)
    n = 3000
    keys = rng.integers(1, 1 << 32, 50, dtype=np.uint64)[
        rng.integers(0, 50, n)]
    fed0 = _metric("ig_sketch_audit_samples_total")
    inst = _make_instance({"audit-sample": "256"})
    inst.enrich_batch(_batch(keys))
    s = inst.harvest()
    acc = s.accuracy
    assert acc is not None and acc["audited"] is True
    assert 0 < acc["sample_size"] <= 50       # never filled: exact truth
    assert acc["sample_capacity"] == 256
    hh = acc["stats"]["heavy_hitters"]
    assert hh["audited"] and hh["observed_err"] is not None
    assert hh["bound"] == pytest.approx(math.e / 1024)
    assert acc["stats"]["distinct"]["audited"]
    assert acc["stats"]["entropy"]["audited"]
    assert "quantiles" not in acc["stats"]    # value lane off
    assert acc["ratio"] >= 0.0
    # every event fed the shadow exactly once, batch-grain
    assert _metric("ig_sketch_audit_samples_total") == fed0 + n
    assert _metric("ig_sketch_accuracy_ratio") == acc["ratio"]
    # the live row DumpState/doctor/fleet read
    snap = inst._astats.snapshot()
    assert snap["audited"] and snap["samples_fed"] == n
    assert snap["ratio"] == acc["ratio"]
    assert set(snap["stats"]) == {"heavy_hitters", "distinct", "entropy"}


def test_plane_off_summary_wire_and_digest_unchanged():
    """The FREE proof: a plane-off run has accuracy=None, no `accuracy`
    wire header, and the block can never perturb a summary digest —
    sealed history and `replay --verify` stay byte-identical."""
    from inspektor_gadget_tpu.agent import wire
    from inspektor_gadget_tpu.capture.journal import summary_digest
    from inspektor_gadget_tpu.operators.tpusketch import SketchSummary

    rng = np.random.default_rng(2)
    inst = _make_instance({})
    inst.enrich_batch(_batch(rng.integers(1, 1 << 32, 100,
                                          dtype=np.uint64)))
    s = inst.harvest()
    assert s.accuracy is None
    h, _ = wire.encode_summary(s)
    assert "accuracy" not in h
    # plane-on: the block roundtrips the wire verbatim
    blk = {"stats": {"heavy_hitters": {"bound": 0.0026, "bound_abs": 2.6,
                                       "confidence": 0.95,
                                       "observed_err": 0.0001,
                                       "audited": True, "audited_keys": 4}},
           "audited": True, "sample_size": 40, "sample_capacity": 256,
           "ratio": 0.04}
    on = SketchSummary(events=10, drops=0, distinct=3.0, entropy_bits=1.5,
                       heavy_hitters=[(1, 5)], epoch=2, accuracy=blk)
    h2, payload = wire.encode_summary(on)
    assert wire.decode_summary(h2, payload)["accuracy"] == blk
    # digest whitelist: the block cannot enter
    base = {"events": 100, "drops": 2, "distinct": 7.0, "entropy": 1.5,
            "epoch": 3, "heavy_hitters": [[1, 5], [2, 3]]}
    assert summary_digest(base) == summary_digest(dict(base, accuracy=blk))


# ---------------------------------------------------------------------------
# fleet history: per-window shadow deltas, merged audits, coverage rules
# ---------------------------------------------------------------------------

_HIST = {"history": "true", "history-interval": "0",
         "history-log2-width": "8", "history-slots": "4"}


def _seal_node(rng, node, keys64, extra=None):
    inst = _make_instance({**_HIST, **(extra or {})}, node=node)
    inst.enrich_batch(_batch(keys64))
    inst.seal_window()
    HISTORY.release(inst._hist_writer)
    return inst


def test_sealed_windows_carry_shadow_deltas_and_audited_answers(
        fleet_store):
    rng = np.random.default_rng(23)
    for node, lo in (("nA", 1), ("nB", 1 << 20)):
        # 60-key vocabulary per node: the 256-slot window shadow never
        # fills, so the sealed delta is the exact per-window multiset
        keys = rng.integers(lo, lo + 60, 500, dtype=np.uint64)
        # topk 64 > 60 live keys: the candidate ring stays exact, so
        # this is the clean (approx=False) path
        _seal_node(rng, node, keys, {"audit-sample": "256",
                                     "topk": "64"})
    frames = list(HISTORY.fetch_windows(base_dir=fleet_store,
                                        gadget=GADGET))
    wins = decode_frames(frames)
    assert len(wins) == 2
    for w in wins:
        assert w.rs_keys is not None and w.rs_capacity == 256
        assert w.rs_keys.dtype == np.uint32
        assert w.rs_weights.dtype == np.int64
        assert int(w.rs_weights.sum()) == 500     # exact per-window delta
    ans = answer_query(wins)
    acc = ans.accuracy
    assert acc is not None and acc["audited"] is True
    assert acc["stats"]["heavy_hitters"]["audited"]
    assert acc["stats"]["heavy_hitters"]["observed_err"] is not None
    assert ans.approx is False
    doc = ans.to_dict()
    assert doc["accuracy"]["audited"] is True and doc["approx"] is False


def test_plane_off_windows_unchanged_and_analytic_only(fleet_store):
    rng = np.random.default_rng(29)
    # 6 live keys: no candidate overflow either, so the header carries
    # neither accuracy-plane field
    _seal_node(rng, "nP", rng.integers(1, 7, 300, dtype=np.uint64))
    frames = list(HISTORY.fetch_windows(base_dir=fleet_store,
                                        gadget=GADGET))
    for h, payload in frames:
        # plane-off wire bytes byte-identical to the pre-plane format
        assert "rs_capacity" not in h and "approx" not in h
        assert b"rs_keys" not in payload
    ans = answer_query(decode_frames(frames))
    acc = ans.accuracy
    assert acc is not None                     # analytic bounds always ride
    assert acc["audited"] is False and acc["sample_size"] == 0
    assert acc["stats"]["heavy_hitters"]["bound"] > 0
    assert acc["stats"]["heavy_hitters"]["observed_err"] is None


def test_mixed_audit_coverage_drops_observed_error_loudly(fleet_store):
    """One node sealed without the shadow: the merged range keeps the
    analytic envelopes but REFUSES the observed-error audit (partial
    ground truth would lie) and says why."""
    rng = np.random.default_rng(31)
    _seal_node(rng, "nA", rng.integers(1, 4000, 300, dtype=np.uint64),
               {"audit-sample": "128"})
    _seal_node(rng, "nB", rng.integers(1, 4000, 300, dtype=np.uint64))
    frames = list(HISTORY.fetch_windows(base_dir=fleet_store,
                                        gadget=GADGET))
    ans = answer_query(decode_frames(frames))
    assert ans.accuracy is not None
    assert ans.accuracy["audited"] is False
    assert any("ground truth" in note for note in ans.dropped_windows)


# ---------------------------------------------------------------------------
# the satellite bugfix: candidate overflow crosses the seal boundary
# ---------------------------------------------------------------------------

def test_topk_overflow_taints_sealed_and_merged_answers(fleet_store):
    rng = np.random.default_rng(37)
    # 40 distinct live keys vs an 8-slot candidate ring: overflow latches
    hot = np.repeat(rng.integers(1, 1 << 32, 40, dtype=np.uint64), 20)
    _seal_node(rng, "nOv", rng.permutation(hot))
    # a clean node: 6 distinct keys never overflow the ring
    few = np.repeat(rng.integers(1, 1 << 32, 6, dtype=np.uint64), 50)
    _seal_node(rng, "nOk", few)
    frames = list(HISTORY.fetch_windows(base_dir=fleet_store,
                                        gadget=GADGET))
    wins = decode_frames(frames)
    by_node = {w.node: w for w in wins}
    assert by_node["nOv"].approx is True      # the latch crossed the seal
    assert by_node["nOk"].approx is False
    # one tainted window taints the merged answer, however many clean
    # windows join it
    ans = answer_query(wins)
    assert ans.approx is True
    assert ans.to_dict()["approx"] is True
    clean = answer_query([by_node["nOk"]])
    assert clean.approx is False


def test_query_cli_prints_error_bars_and_approx_note(fleet_store, capsys):
    from inspektor_gadget_tpu.cli.query import cmd_query

    class _Args:
        remote = ""
        gadget = GADGET
        start_ts = None
        end_ts = None
        last = ""
        start_seq = None
        end_seq = None
        key = ""
        slices = False
        top = 20
        output = "table"
        quantiles = False

        def __init__(self, **kv):
            for k, v in kv.items():
                setattr(self, k, v)

    rng = np.random.default_rng(41)
    hot = np.repeat(rng.integers(1, 1 << 32, 40, dtype=np.uint64), 20)
    _seal_node(rng, "nQ", rng.permutation(hot), {"audit-sample": "128"})
    assert cmd_query(_Args(history=fleet_store)) == 0
    out = capsys.readouterr().out
    assert "overestimate ≤" in out            # CMS envelope on the header
    assert "±" in out                         # HLL bound on distinct
    assert "accuracy audit" in out            # shadow-sample audit table
    assert "approximate" in out               # the overflow note
    # JSON carries the block + taint verbatim
    assert cmd_query(_Args(history=fleet_store, output="json")) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["approx"] is True
    assert doc["accuracy"]["audited"] is True


# ---------------------------------------------------------------------------
# standing queries inherit the plane through the window monoid
# ---------------------------------------------------------------------------

def test_standing_query_fold_carries_audit_and_taint(fleet_store):
    from inspektor_gadget_tpu.queries.engine import SlidingFold

    rng = np.random.default_rng(43)
    hot = np.repeat(rng.integers(1, 1 << 32, 40, dtype=np.uint64), 20)
    _seal_node(rng, "nS1", rng.integers(1, 7, 400, dtype=np.uint64),
               {"audit-sample": "128"})          # 6 live keys: clean
    _seal_node(rng, "nS2", rng.permutation(hot), {"audit-sample": "128"})
    wins = decode_frames(list(HISTORY.fetch_windows(
        base_dir=fleet_store, gadget=GADGET)))
    wins.sort(key=lambda w: w.node)
    fold = SlidingFold(gadget=GADGET, node="standing")
    fold.push(wins[0])                        # clean, audited
    val = fold.value()
    assert val.rs_keys is not None and val.approx is False
    fold.push(wins[1])                        # overflowed, audited
    val2 = fold.value()
    assert val2.approx is True                # taint survives the fold
    ans = answer_query([val2])
    assert ans.approx is True
    assert ans.accuracy is not None and ans.accuracy["audited"] is True


# ---------------------------------------------------------------------------
# alerts: the accuracy_drift detector kind
# ---------------------------------------------------------------------------

def test_accuracy_drift_rule_validation():
    from inspektor_gadget_tpu.alerts.rules import RuleError, load_rules

    rules = load_rules(json.dumps([{"id": "ad", "kind": "accuracy_drift",
                                    "factor": 0.5}]))
    assert rules[0].field == "accuracy_ratio"   # implied, not chosen
    assert rules[0].threshold == 0.0            # threshold optional
    assert "analytic bound" in rules[0].describe()
    # restating the implied field exactly is fine; any other is loud
    load_rules(json.dumps([{"id": "ad", "kind": "accuracy_drift",
                            "field": "accuracy_ratio", "factor": 0.5}]))
    with pytest.raises(RuleError, match="accuracy_drift"):
        load_rules(json.dumps([{"id": "ad", "kind": "accuracy_drift",
                                "field": "entropy_bits", "factor": 0.5}]))


def test_accuracy_drift_fires_once_with_idle_immunity():
    """The acceptance shape: the ANALYTIC bound is the baseline (no
    rolling window), healthy epochs and idle windows (ratio 0.0 = no
    observation) never fire, the drift epoch fires exactly once, and
    staying drifted does not re-fire."""
    from inspektor_gadget_tpu.alerts.engine import AlertEngine
    from inspektor_gadget_tpu.alerts.rules import load_rules

    rules = load_rules(json.dumps([{
        "id": "drift", "kind": "accuracy_drift", "factor": 0.5,
        "for": 0}]))
    eng = AlertEngine(rules, node="n0", gadget=GADGET, dry_run=True)
    base = {"events": 100, "drops": 0, "distinct": 5.0, "entropy": 1.0,
            "heavy_hitters": [], "anomaly": {}}

    def obs(epoch, ratio, now):
        return eng.observe({**base, "epoch": epoch,
                            "accuracy": {"ratio": ratio, "audited": True}},
                           now=now)

    transitions = []
    # healthy epochs inside the envelope, one idle window in the middle
    for i, r in enumerate((0.2, 0.3, 0.0, 0.25)):
        transitions += [(e.transition, i) for e in obs(i, r, 10.0 * i)]
    assert transitions == []
    # injected skew: observed error escapes half the bound → one firing
    evs = obs(4, 0.8, 40.0)
    assert [e.transition for e in evs] == ["pending", "firing"]
    assert evs[-1].rule == "drift" and evs[-1].value == 0.8
    evs2 = obs(5, 0.9, 50.0)                   # still drifted: no re-fire
    assert not any(e.transition == "firing" for e in evs2)
    eng.close()


def test_accuracy_drift_ignores_plane_off_summaries():
    from inspektor_gadget_tpu.alerts.engine import AlertEngine
    from inspektor_gadget_tpu.alerts.rules import load_rules

    rules = load_rules(json.dumps([{
        "id": "drift", "kind": "accuracy_drift", "factor": 0.1,
        "for": 0}]))
    eng = AlertEngine(rules, node="n0", gadget=GADGET, dry_run=True)
    base = {"events": 100, "drops": 0, "distinct": 5.0, "entropy": 1.0,
            "heavy_hitters": [], "anomaly": {}}
    evs = []
    for epoch in range(6):                     # plane off: no accuracy key
        evs += eng.observe({**base, "epoch": epoch}, now=10.0 * epoch)
    assert evs == []
    eng.close()


# ---------------------------------------------------------------------------
# CLI: ig-tpu fleet accuracy (stubbed request path + rendering)
# ---------------------------------------------------------------------------

class _AccArgs:
    remote = ""
    deadline = 3.0
    gadget = ""
    output = "table"

    def __init__(self, **kv):
        for k, v in kv.items():
            setattr(self, k, v)


_ACC_ROW = {
    "run_id": "run-acc-000001", "gadget": GADGET, "audited": True,
    "sample_size": 128, "ratio": 0.42, "samples_fed": 5000,
    "stats": {
        "heavy_hitters": {"bound": 0.00266, "bound_abs": 13.3,
                          "confidence": 0.95, "observed_err": 0.00112,
                          "audited": True, "audited_keys": 5},
        "distinct": {"bound": 0.065, "regime": "raw",
                     "observed_err": None, "audited": False},
    },
}


def _stub_client(rows):
    class _StubClient:
        def __init__(self, target, node, rpc_deadline=3.0):
            self.node = node

        def dump_state(self):
            return {"accuracy": rows}

        def close(self):
            pass
    return _StubClient


def test_fleet_accuracy_renders_table_and_json(monkeypatch, capsys):
    from inspektor_gadget_tpu.agent import client as agent_client
    from inspektor_gadget_tpu.cli.fleet import cmd_fleet_accuracy

    monkeypatch.setattr(agent_client, "AgentClient",
                        _stub_client([_ACC_ROW]))
    assert cmd_fleet_accuracy(_AccArgs(remote="n0=localhost:19999")) == 0
    out = capsys.readouterr().out
    assert "STAT" in out and "BOUND" in out and "OBSERVED" in out
    assert "run-acc-000001" in out
    assert "heavy_hitters" in out and "distinct" in out
    assert "0.00112" in out and "yes" in out   # audited stat renders err
    assert "-" in out and "no" in out          # unaudited stat renders dash
    assert "0.42" in out and "128" in out
    # json mode carries the rows verbatim
    assert cmd_fleet_accuracy(_AccArgs(remote="n0=localhost:19999",
                                       output="json")) == 0
    doc = json.loads(capsys.readouterr().out)
    run = doc["agents"][0]["runs"][0]
    assert run["ratio"] == 0.42
    assert run["stats"]["heavy_hitters"]["observed_err"] == 0.00112
    # --gadget filters to matching runs only
    assert cmd_fleet_accuracy(_AccArgs(remote="n0=localhost:19999",
                                       gadget="trace/open")) == 0
    assert "no audited runs" in capsys.readouterr().out


def test_fleet_accuracy_unreachable_node_is_rc1(monkeypatch, capsys):
    from inspektor_gadget_tpu.agent import client as agent_client
    from inspektor_gadget_tpu.cli.fleet import cmd_fleet_accuracy

    class _Boom:
        def __init__(self, target, node, rpc_deadline=3.0):
            raise OSError("connection refused")

    monkeypatch.setattr(agent_client, "AgentClient", _Boom)
    assert cmd_fleet_accuracy(_AccArgs(remote="n0=localhost:19999")) == 1
    assert "unreachable" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# real fleet surfaces: DumpState rows + the doctor probe
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def agents():
    from inspektor_gadget_tpu.agent.service import serve
    servers, targets = [], {}
    tmp = tempfile.mkdtemp()
    for i in range(2):
        addr = f"unix://{tmp}/acc-agent{i}.sock"
        server, _ = serve(addr, node_name=f"anode-{i}")
        servers.append(server)
        targets[f"anode-{i}"] = addr
    yield targets
    for s in servers:
        s.stop(grace=0.5)


def _audited_stats(run_id: str) -> AccuracyStats:
    rng = np.random.default_rng(5)
    keys = _zipf_stream(rng, 2000, 40)
    sh = ShadowSample(128)
    sh.update(keys)
    uk, uc = np.unique(keys, return_counts=True)
    a = AccuracyStats(run_id, GADGET)
    a.note_fed(keys.size)
    a.observe_block(accuracy_block(
        events=float(keys.size), depth=3, width=1024, hll_p=8,
        ent_log2_width=6, distinct=float(uk.size), entropy_bits=2.0,
        hh_keys=uk[:8], hh_counts=uc[:8].astype(np.int64), shadow=sh))
    return a


def test_dump_state_and_doctor_carry_accuracy_rows(agents):
    from inspektor_gadget_tpu.agent.client import AgentClient
    from inspektor_gadget_tpu.doctor import _probe_accuracy

    w0 = _probe_accuracy()
    assert w0.ok and "no audited runs" in w0.detail
    a = _audited_stats("run-acc-dump-1")
    a.register()
    try:
        client = AgentClient(next(iter(agents.values())), "anode-0")
        try:
            rows = client.dump_state()["accuracy"]
        finally:
            client.close()
        row = next(r for r in rows if r.get("run_id") == "run-acc-dump-1")
        assert row["gadget"] == GADGET and row["audited"] is True
        assert row["samples_fed"] == 2000
        assert row["stats"]["heavy_hitters"]["audited"] is True
        w = _probe_accuracy()
        assert w.ok and "run-acc-" in w.detail and "ratio" in w.detail
    finally:
        a.unregister()


# ---------------------------------------------------------------------------
# perf: bench records + harness overhead ledger (tier-1 smoke)
# ---------------------------------------------------------------------------

def test_accuracy_bench_publishes_schema_valid_records(tmp_path):
    from inspektor_gadget_tpu.perf.accuracy_bench import publish
    from inspektor_gadget_tpu.perf.compare import compare_ledger
    from inspektor_gadget_tpu.perf.ledger import read_ledger
    from inspektor_gadget_tpu.perf.schema import validate_record

    ledger = str(tmp_path / "PERF.jsonl")
    records = publish(batch=1 << 10, capacity=64, seconds=0.05,
                      events=20_000, ledger=ledger)
    assert {r["config"] for r in records} == {
        "accuracy-audit", "accuracy-overhead", "accuracy-observed-err"}
    for rec in records:
        assert validate_record(rec) == []
    over = next(r for r in records if r["config"] == "accuracy-overhead")
    assert 0.0 <= over["value"] <= 1.0
    err = next(r for r in records
               if r["config"] == "accuracy-observed-err")
    assert err["extra"]["observed_err_pct"] <= err["extra"]["bound_pct"]
    on_disk = read_ledger(ledger).records
    assert len(on_disk) == 3
    assert all(r.rc == 0 for r in compare_ledger(on_disk))


def test_harness_tiny_records_audit_overhead():
    from inspektor_gadget_tpu.perf.harness import run_harness
    from inspektor_gadget_tpu.perf.schema import validate_record

    rec = run_harness("tiny", platform="cpu")
    assert validate_record(rec) == []
    assert "audit_feed" in rec["stages"]
    assert 0.0 <= rec["extra"]["audit_overhead"] <= 1.0


# ---------------------------------------------------------------------------
# docs lint: the err-pct claim pattern in check_perf_claims
# ---------------------------------------------------------------------------

def test_check_perf_claims_err_pct_pattern():
    from tools.check_perf_claims import Backing, check_claim, extract_claims

    claims = extract_claims(
        "the error stays well under the 1% mark\n"
        "observed error within 0.5%\n",
        "inspektor_gadget_tpu/ops/countmin.py")
    errs = [c for c in claims if c.kind == "err_pct"]
    assert [c.hi for c in errs] == [1.0, 0.5]
    ok = Backing(0.0042, "cpu", False, "PERF.jsonl:9#observed_err_pct",
                 kind="err_pct")
    # bound-style: any backing at or under the ceiling is clean, and an
    # accuracy property needs no platform labeling (cpu-exempt)
    assert check_claim(errs[0], [ok]) == ""
    # an ev/s backing with a matching number may NOT back an err claim
    assert "NO ledger" in check_claim(
        errs[0], [Backing(0.5, "cpu", False, "x")])
    # a measurement OVER the ceiling does not back the claim
    assert "NO ledger" in check_claim(
        errs[1], [Backing(1.7, "tpu", False, "y", kind="err_pct")])


def test_ledger_backings_surface_observed_err_pct(tmp_path):
    from tools.check_perf_claims import _ledger_backings

    p = tmp_path / "PERF.jsonl"
    p.write_text(json.dumps({
        "config": "accuracy-observed-err", "value": 0.0042, "unit": "pct",
        "provenance": {"platform": "cpu", "degraded": False},
        "extra": {"observed_err_pct": 0.0042}}) + "\n")
    backs = _ledger_backings(p)
    ep = [b for b in backs if b.kind == "err_pct"]
    assert len(ep) == 1
    assert ep[0].value == pytest.approx(0.0042)
    assert ep[0].source.endswith("#observed_err_pct")
