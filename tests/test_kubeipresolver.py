"""KubeIPResolver cluster inventory against a fake apiserver.

Reference tier: pkg/operators/kubeipresolver/kubeipresolver.go:62-156 —
k8sInventoryCache polls pods AND services into a TTL cache; events'
addresses get pod/service names attached. Here the same poll runs through
KubeClient against an in-process HTTP apiserver whose state the tests
mutate to prove cache-refresh semantics.
"""

import dataclasses
import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from inspektor_gadget_tpu.operators.kubeipresolver import (
    KubeIPResolver,
    kube_inventory,
)
from inspektor_gadget_tpu.utils.k8s import KubeClient


def _pod(ns, name, *ips):
    return {"metadata": {"name": name, "namespace": ns},
            "spec": {},
            "status": {"podIP": ips[0] if ips else "",
                       "podIPs": [{"ip": ip} for ip in ips]}}


def _svc(ns, name, *ips):
    return {"metadata": {"name": name, "namespace": ns},
            "spec": {"clusterIP": ips[0] if ips else "",
                     "clusterIPs": list(ips)}}


class _FakeApi(BaseHTTPRequestHandler):
    pods: list = []
    services: list = []

    def do_GET(self):
        if "/services" in self.path:
            body = {"items": _FakeApi.services}
        elif "/pods" in self.path:
            body = {"items": _FakeApi.pods}
        else:
            self.send_error(404)
            return
        data = json.dumps(body).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *a):
        pass


@pytest.fixture()
def fake_api():
    server = HTTPServer(("127.0.0.1", 0), _FakeApi)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    _FakeApi.pods = [_pod("prod", "web-0", "10.0.0.5"),
                     _pod("prod", "db-0", "10.0.0.6", "fd00::6")]
    _FakeApi.services = [_svc("prod", "web", "10.96.0.10"),
                         _svc("prod", "headless", "None")]
    yield server
    server.shutdown()


def _url(server):
    return f"http://127.0.0.1:{server.server_port}"


def test_inventory_polls_pods_and_services(fake_api):
    inv = kube_inventory(KubeClient(server=_url(fake_api)))()
    assert inv["10.0.0.5"] == ("pod", "prod/web-0")
    assert inv["10.0.0.6"] == ("pod", "prod/db-0")
    assert inv["fd00::6"] == ("pod", "prod/db-0")  # dual-stack secondary IP
    assert inv["10.96.0.10"] == ("svc", "prod/web")
    assert "None" not in inv  # headless services skipped


def test_pod_wins_ip_conflict(fake_api):
    _FakeApi.services = [_svc("prod", "vip", "10.0.0.5")]
    inv = kube_inventory(KubeClient(server=_url(fake_api)))()
    assert inv["10.0.0.5"] == ("pod", "prod/web-0")


def test_resolver_enriches_via_cluster_inventory(fake_api):
    op = KubeIPResolver()
    op.use_kube_client(KubeClient(server=_url(fake_api)))

    @dataclasses.dataclass
    class NetEv:
        saddr: str = ""
        daddr: str = ""

    inst = op.instantiate(None, None, op.instance_params().to_params())
    ev = NetEv(saddr="10.0.0.5:443", daddr="10.96.0.10")
    inst.enrich(ev)
    assert "pod/prod/web-0" in ev.saddr
    assert "svc/prod/web" in ev.daddr


def test_cache_refresh_picks_up_new_pods(fake_api):
    op = KubeIPResolver()
    op.use_kube_client(KubeClient(server=_url(fake_api)),
                       refresh_interval=0.0)
    assert op.lookup("10.0.0.99") is None
    _FakeApi.pods.append(_pod("prod", "new-0", "10.0.0.99"))
    assert op.lookup("10.0.0.99") == ("pod", "prod/new-0")


def test_stale_cache_within_ttl(fake_api):
    op = KubeIPResolver()
    op.use_kube_client(KubeClient(server=_url(fake_api)),
                       refresh_interval=300.0)
    assert op.lookup("10.0.0.5") == ("pod", "prod/web-0")
    _FakeApi.pods = []  # cluster changed, but TTL hasn't expired
    assert op.lookup("10.0.0.5") == ("pod", "prod/web-0")


def test_apiserver_blip_keeps_stale_cache(fake_api):
    op = KubeIPResolver()
    client = KubeClient(server=_url(fake_api))
    op.use_kube_client(client, refresh_interval=0.0)
    assert op.lookup("10.0.0.5") == ("pod", "prod/web-0")
    client.server = "http://127.0.0.1:1"  # unreachable
    assert op.lookup("10.0.0.5") == ("pod", "prod/web-0")  # stale, not lost
