"""Expert-parallel MoE and pipeline-parallel tests (8-device CPU mesh).

Correctness bar for both: the distributed execution must equal the
single-device reference bit-for-bit-ish (fp32 tolerances) — the same
"sharded == sequential" contract the cluster sketch merge tests enforce.
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from inspektor_gadget_tpu.parallel import (
    make_ep_moe,
    make_pp_forward,
    make_pp_train_step,
    moe_apply,
    moe_init,
    pp_block_init,
    pp_reference,
)


def expert_mesh(n=8):
    return Mesh(np.array(jax.devices()[:n]), ("expert",))


def stage_mesh(n=8):
    return Mesh(np.array(jax.devices()[:n]), ("stage",))


def test_moe_reference_routes_and_balances():
    params = moe_init(jax.random.PRNGKey(0), n_experts=8, d_model=32, d_ff=64)
    x = jax.random.normal(jax.random.PRNGKey(1), (128, 32))
    y, (bal, drop) = moe_apply(params, x, capacity_factor=2.0)
    assert y.shape == x.shape
    assert float(bal) >= 1.0 - 1e-5  # balance loss is minimized at 1
    assert 0.0 <= float(drop) <= 1.0
    # ample capacity → nothing dropped, every token touched by an expert
    y2, (_, drop2) = moe_apply(params, x, capacity_factor=8.0)
    assert float(drop2) == 0.0
    assert float(jnp.abs(y2).sum()) > 0


def test_ep_moe_matches_reference():
    mesh = expert_mesh()
    n_tok = 256
    params = moe_init(jax.random.PRNGKey(0), n_experts=8, d_model=32, d_ff=64)
    x = jax.random.normal(jax.random.PRNGKey(1), (n_tok, 32))
    ep = make_ep_moe(mesh, n_experts=8, capacity_factor=8.0)
    y_ep, (bal_ep, drop_ep) = ep(params, x)
    # reference computed per token shard (capacity is per-shard in EP), then
    # concatenated: run moe_apply on each 32-token shard independently.
    shards = [
        moe_apply(params, x[i * 32:(i + 1) * 32], capacity_factor=8.0)
        for i in range(8)
    ]
    y_ref = jnp.concatenate([s[0] for s in shards])
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_ref),
                               rtol=2e-2, atol=2e-2)
    assert float(drop_ep) == 0.0


def test_ep_moe_capacity_drops_are_reported():
    mesh = expert_mesh()
    params = moe_init(jax.random.PRNGKey(2), n_experts=8, d_model=16, d_ff=32)
    # adversarial input: identical tokens all route to one expert
    x = jnp.tile(jax.random.normal(jax.random.PRNGKey(3), (1, 16)), (256, 1))
    ep = make_ep_moe(mesh, n_experts=8, capacity_factor=1.0)
    _, (_, drop) = ep(params, x)
    # capacity 32/8*1 = 4 per expert per shard; 32 tokens/shard to one expert
    assert float(drop) > 0.8


def test_moe_seq_model_trains_single_chip():
    """SeqConfig.n_experts swaps the dense FF for routed experts; the LM
    still learns (loss decreases) and scoring works unchanged."""
    from inspektor_gadget_tpu.models.seqmodel import (
        SeqConfig, seq_init, seq_score, seq_train_step,
    )

    cfg = SeqConfig(vocab=32, d_model=32, n_heads=2, n_layers=2, d_ff=64,
                    n_experts=4)
    sc = seq_init(cfg, seed=0)
    assert "moe" in sc.params["layers"][0] and "ff1" not in sc.params["layers"][0]
    rng = np.random.default_rng(0)
    # learnable structure: repeating bigrams
    toks = jnp.asarray(np.tile(rng.integers(0, 32, (4, 2)), (1, 16)),
                       jnp.int32)
    losses = []
    for _ in range(30):
        sc, loss = seq_train_step(sc, toks)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7
    scores = seq_score(sc, toks)
    assert scores.shape == (4,) and np.isfinite(np.asarray(scores)).all()


def test_ep_train_step_matches_structure_and_learns():
    """Expert-parallel step: experts sharded over the mesh, loss decreases,
    and params stay numerically consistent with their global shapes."""
    from inspektor_gadget_tpu.models.seqmodel import (
        SeqConfig, make_ep_train_step, seq_init,
    )

    mesh = expert_mesh()
    cfg = SeqConfig(vocab=32, d_model=32, n_heads=2, n_layers=1, d_ff=64,
                    n_experts=8)
    sc = seq_init(cfg, seed=1)
    step = make_ep_train_step(mesh, cfg, sc)
    rng = np.random.default_rng(1)
    toks = jnp.asarray(np.tile(rng.integers(0, 32, (8, 2)), (1, 16)),
                       jnp.int32)
    p, o = sc.params, sc.opt_state
    losses = []
    for _ in range(25):
        p, o, loss = step(p, o, toks)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8
    assert p["layers"][0]["moe"]["w1"].shape == (8, 32, 64)


def test_pp_forward_matches_sequential():
    mesh = stage_mesh()
    params = pp_block_init(jax.random.PRNGKey(0), n_stages=8, d_model=32,
                           d_ff=64)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 32))  # [M, mb, d]
    y_pp = make_pp_forward(mesh)(params, x)
    y_ref = jnp.stack([pp_reference(params, x[i]) for i in range(4)])
    np.testing.assert_allclose(np.asarray(y_pp), np.asarray(y_ref),
                               rtol=2e-2, atol=2e-2)


def test_pp_train_step_learns():
    mesh = stage_mesh()
    params = pp_block_init(jax.random.PRNGKey(0), n_stages=8, d_model=16,
                           d_ff=32)
    head = jax.random.normal(jax.random.PRNGKey(1), (16, 4)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 8, 16))
    y = jax.random.normal(jax.random.PRNGKey(3), (4, 8, 4))
    step = make_pp_train_step(mesh, lr=1e-2)
    losses = []
    p, h = params, head
    for _ in range(20):
        p, h, loss = step(p, h, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7
    # block grads stayed stage-sharded: param tree shape unchanged
    assert p["w1"].shape == params["w1"].shape
