"""Gadget-type registry hygiene (VERDICT Weak #7).

advise/* and traceloop ride the legacy CRD start..stop→generate path —
they were mislabeled as PROFILE, which type-keyed handler wiring (agent
+ CLI) silently served with no handlers. Pinned here: the labels, the
loud agent wiring for unknown types, and the run-with-result contract
for every result-typed gadget in the registry.
"""

from __future__ import annotations

import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401 — registers everything
from inspektor_gadget_tpu.agent.service import handlers_for
from inspektor_gadget_tpu.gadgets import registry
from inspektor_gadget_tpu.gadgets.interface import GadgetType


def test_advise_and_traceloop_are_start_stop():
    for cat, name in (("advise", "seccomp-profile"),
                      ("advise", "network-policy"),
                      ("traceloop", "traceloop")):
        desc = registry.get(cat, name)
        assert desc.gadget_type == GadgetType.START_STOP, (
            f"{cat}/{name} registered as {desc.gadget_type}")


def test_profile_label_reserved_for_samplers():
    profiles = [d.full_name for d in registry.get_all()
                if d.gadget_type == GadgetType.PROFILE]
    assert sorted(profiles) == ["profile/block-io", "profile/cpu"]


def test_every_registered_type_has_agent_wiring():
    """The agent must know how to serve every gadget in the registry —
    a new type that reaches the registry without handler wiring is a
    silently-empty stream waiting to happen."""
    sentinel_ev, sentinel_arr = object(), object()
    for desc in registry.get_all():
        ev, arr = handlers_for(desc.gadget_type, {"json"},
                               sentinel_ev, sentinel_arr)
        if desc.gadget_type == GadgetType.TRACE:
            assert ev is sentinel_ev
        elif desc.gadget_type == GadgetType.TRACE_INTERVALS:
            assert arr is sentinel_arr
        else:
            assert ev is None


def test_unknown_type_raises_loudly():
    with pytest.raises(ValueError, match="no handler wiring"):
        handlers_for("holographic", {"json"}, None, None)


def test_one_shot_combiner_gating():
    ev, arr = handlers_for(GadgetType.ONE_SHOT, {"json", "combiner"},
                           "E", "A")
    assert (ev, arr) == (None, "A")
    ev, arr = handlers_for(GadgetType.ONE_SHOT, {"json"}, "E", "A")
    assert (ev, arr) == (None, None)


def test_result_typed_gadgets_implement_run_with_result():
    """Every PROFILE/START_STOP gadget class must expose run_with_result
    — the local runtime now refuses to run one that doesn't (the caller
    would otherwise wait on a result that never comes)."""
    from inspektor_gadget_tpu.gadgets import GadgetContext
    for desc in registry.get_all():
        if desc.gadget_type not in (GadgetType.PROFILE,
                                    GadgetType.START_STOP):
            continue
        ctx = GadgetContext(desc, gadget_params=desc.params().to_params())
        gadget = desc.new_instance(ctx)
        assert hasattr(gadget, "run_with_result"), desc.full_name


def test_local_runtime_rejects_result_type_without_impl():
    from inspektor_gadget_tpu.gadgets import GadgetContext
    from inspektor_gadget_tpu.gadgets.interface import GadgetDesc
    from inspektor_gadget_tpu.runtime.local import LocalRuntime

    class Broken:
        def run(self, ctx):  # streams, despite the result-typed label
            pass

    class BrokenDesc(GadgetDesc):
        name = "broken"
        category = "test"
        gadget_type = GadgetType.START_STOP

        def new_instance(self, ctx):
            return Broken()

    ctx = GadgetContext(BrokenDesc())
    result = LocalRuntime().run_gadget(ctx)
    errs = result.errors()
    assert errs and "run_with_result" in str(errs)
