"""Real-runtime integration tier (VERDICT #4).

Every other container test fakes the runtime (unshared namespaces, fake
collections). This tier runs the actual discovery → enrichment → columns
chain against a REAL container runtime when one is reachable — the
docker/containerd/CRI socket the doctor's `container_runtime` row probes
— and skips cleanly everywhere else, so CI hosts with a runtime get the
coverage and laptops without one lose nothing.
"""

from __future__ import annotations

import os

import pytest

from inspektor_gadget_tpu.containers.runtime_client import (
    CRI_SOCKETS,
    DOCKER_SOCKET,
    detect_runtime_client,
)

_SOCKETS = (DOCKER_SOCKET, *CRI_SOCKETS)


def _any_socket() -> bool:
    return any(os.path.exists(s) for s in _SOCKETS)


NEEDS_RUNTIME = pytest.mark.skipif(
    not _any_socket(),
    reason=f"no container runtime socket present (checked {_SOCKETS})")


@NEEDS_RUNTIME
def test_doctor_reports_runtime_row():
    """The doctor's runtime-availability row must agree with the socket
    this tier keyed off."""
    from inspektor_gadget_tpu.doctor import probe_windows, render_report
    windows = probe_windows()
    assert "container_runtime" in windows
    w = windows["container_runtime"]
    assert w.ok, w.detail
    report = render_report(windows)
    assert "container_runtime" in report


@NEEDS_RUNTIME
def test_discovery_enrichment_columns_real_container():
    """discovery (runtime list) → enrichment (pid → mntns, identity
    completion) → columns (an event in the container's mntns renders its
    name) against a live container."""
    client = detect_runtime_client()
    if client is None:
        pytest.skip("runtime socket exists but no client answered")
    containers = client.get_containers()
    if not containers:
        pytest.skip("runtime reachable but no containers running")

    from inspektor_gadget_tpu.containers import ContainerCollection
    from inspektor_gadget_tpu.containers.runtime_client import (
        with_runtime_enrichment)

    cc = ContainerCollection()
    cc.initialize(with_runtime_enrichment(client))
    discovered = cc.get_all()
    assert discovered, "runtime listed containers but the collection is empty"
    by_id = {c.id: c for c in discovered}
    for c in containers:
        assert c.id in by_id, f"container {c.id} lost in discovery"

    # enrichment: at least one running container resolves a pid and a
    # mount namespace (runtime completion + linux-ns enricher)
    enriched = [c for c in discovered if c.pid and c.mntns]
    if not enriched:
        pytest.skip("no discovered container exposes pid+mntns "
                    "(runtime keeps pids private to this uid?)")
    target = enriched[0]

    # columns: an event carrying the container's mntns renders its name
    # through the standard enrichment path the gadgets use
    from inspektor_gadget_tpu.columns import Columns, TextFormatter
    from inspektor_gadget_tpu.gadgets.trace.exec import ExecEvent

    ev = ExecEvent(mountnsid=target.mntns, pid=target.pid, comm="real-rt")
    cc.enrich_event_by_mntns(ev)
    assert ev.container == target.name, (ev.container, target.name)

    cols = Columns(ExecEvent)
    fmt = TextFormatter(cols)
    line = fmt.format_event(ev)
    assert target.name[:8] in line or target.name in line, line
