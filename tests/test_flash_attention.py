"""Pallas flash-attention tests (interpret mode on CPU — same kernel code
path as the compiled TPU run, which was validated on hardware; see
docs/performance.md)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from inspektor_gadget_tpu.parallel import flash_attention
from inspektor_gadget_tpu.parallel.ring_attention import full_attention


@pytest.mark.parametrize("shape,causal", [
    ((2, 256, 4, 32), True),     # D padding (32 → 128 lanes)
    ((1, 200, 2, 16), False),    # T padding (200 → 256) + D padding
    ((2, 128, 1, 128), True),    # exact hardware shapes, single block
    ((1, 384, 2, 64), True),     # multi-block causal early exit
])
def test_flash_matches_reference(shape, causal):
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(size=shape).astype(np.float32))
               for _ in range(3))
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_flash_first_row_attends_only_self():
    """Causal row 0 must equal v[0] exactly (softmax over one key)."""
    rng = np.random.default_rng(1)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 128, 1, 32)).astype(np.float32))
               for _ in range(3))
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out[0, 0, 0]),
                               np.asarray(v[0, 0, 0]), rtol=1e-5, atol=1e-5)


def test_flash_gradients_match_reference():
    """custom_vjp: grads through the flash kernel equal grads through full
    attention (backward recomputes via the blockwise path)."""
    rng = np.random.default_rng(2)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 128, 2, 32)).astype(np.float32))
               for _ in range(3))

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=True, interpret=True) ** 2).sum()

    def loss_full(q, k, v):
        return (full_attention(q, k, v, causal=True) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3)


def test_flash_gradients_odd_length():
    """Backward path handles T with no small divisors (prime T=251) via
    q-block padding — no degenerate chunk=1 scan."""
    rng = np.random.default_rng(4)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 251, 2, 16)).astype(np.float32))
               for _ in range(3))

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=True, interpret=True) ** 2).sum()

    def loss_full(q, k, v):
        return (full_attention(q, k, v, causal=True) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3)


def test_flash_training_end_to_end():
    """seq_train_step(attn='flash') learns: fused forward + recompute
    backward through the whole model."""
    from inspektor_gadget_tpu.models.seqmodel import (
        SeqConfig, seq_init, seq_train_step,
    )

    cfg = SeqConfig(vocab=16, d_model=16, n_heads=2, n_layers=1, d_ff=32)
    sc = seq_init(cfg)
    rng = np.random.default_rng(3)
    toks = jnp.asarray(np.tile(rng.integers(0, 16, (2, 2)), (1, 64)),
                       jnp.int32)
    losses = []
    for _ in range(15):
        sc, loss = seq_train_step(sc, toks, attn="flash")
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8


def test_seqmodel_flash_backend():
    """attn='flash' scores through the kernel and matches the full-attention
    backend (the per-container NLL hot loop)."""
    from inspektor_gadget_tpu.models.seqmodel import (
        SeqConfig, seq_init, seq_score,
    )

    cfg = SeqConfig(vocab=32, d_model=32, n_heads=2, n_layers=1, d_ff=64)
    sc = seq_init(cfg, seed=0)
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, 32, (2, 128)), jnp.int32)
    s_flash = seq_score(sc, toks, attn="flash")
    s_full = seq_score(sc, toks, attn="full")
    np.testing.assert_allclose(np.asarray(s_flash), np.asarray(s_full),
                               rtol=1e-3, atol=1e-3)
