"""Latency quantile plane (ISSUE 16): operator, fleet, alerting, perf
surfaces.

The acceptance story under test: a fleet that already answers "who is
heavy" (count planes) answers "what got slower" from the same fused
pass. The value lane rides the folded staging block into a DDSketch
grid plane; harvest summaries carry p50/p90/p99/p99.9 with <= alpha
relative error; sealed windows carry per-window bucket deltas that
re-merge bit-exactly across nodes; `quantile_shift` turns a percentile
regression into exactly one alert; and the plane OFF leaves every wire
byte exactly as it was before the plane existed.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.gadgets import GadgetContext, get
from inspektor_gadget_tpu.history import HISTORY, answer_query, decode_frames
from inspektor_gadget_tpu.operators.operators import get as get_op
from inspektor_gadget_tpu.params import ParamError
from inspektor_gadget_tpu.sources.batch import EventBatch
from inspektor_gadget_tpu.telemetry import registry as telemetry_registry

GADGET = "trace/exec"


@pytest.fixture(autouse=True)
def _release_instances():
    """Instances built outside a real gadget run never see
    post_gadget_run — drop them from the live table (checkpoint_all
    iterates it) and drain their stagers (the h2d inflight gauge) so no
    state leaks into other test files."""
    from inspektor_gadget_tpu.operators import tpusketch
    before = set(tpusketch._live)
    yield
    with tpusketch._live_mu:
        fresh = [rid for rid in list(tpusketch._live) if rid not in before]
        insts = [tpusketch._live.pop(rid) for rid in fresh]
    for inst in insts:
        if getattr(inst, "_stager", None) is not None:
            inst._stager.drain()
        for st in getattr(inst, "_lane_stagers", []):
            st.drain()
        inst._stats.unregister()
        inst._pstats.unregister()


@pytest.fixture()
def fleet_store(tmp_path):
    HISTORY.set_base_dir(str(tmp_path))
    yield str(tmp_path)
    HISTORY.close_all()
    HISTORY.set_base_dir(None)


def _make_instance(extra_params: dict, node: str = "",
                   extra_ctx: dict | None = None):
    desc = get("trace", "exec")
    ctx = GadgetContext(desc, extra=dict(extra_ctx or {}))
    if node:
        ctx.extra["node"] = node
    op = get_op("tpusketch")
    p = op.instance_params().to_params()
    p.set("enable", "true")
    p.set("depth", "3")
    p.set("log2-width", "10")
    p.set("hll-p", "8")
    p.set("entropy-log2-width", "6")
    p.set("topk", "8")
    p.set("harvest-interval", "1h")
    for k, v in extra_params.items():
        p.set(k, v)
    return op.instantiate(ctx, None, p)


def _batch(keys64: np.ndarray, aux1: np.ndarray | None = None
           ) -> EventBatch:
    b = EventBatch.alloc(len(keys64), with_comm=False)
    b.cols["key_hash"][:] = keys64
    if aux1 is not None:
        b.cols["aux1"][:] = aux1
    b.count = len(keys64)
    return b


def _latencies(rng, n, median_ns=50_000.0, sigma=0.8):
    return rng.lognormal(np.log(median_ns), sigma, n).astype(np.uint64)


# ---------------------------------------------------------------------------
# param validation matrix
# ---------------------------------------------------------------------------

def test_param_error_matrix():
    op = get_op("tpusketch")

    def params(**kv):
        p = op.instance_params().to_params()
        p.set("enable", "true")
        for k, v in kv.items():
            p.set(k, v)
        return p

    # alpha grammar answers at the params layer (set-time validator)
    for bad in ("0", "-0.01", "0.31", "xx"):
        with pytest.raises(ParamError):
            params(**{"quantile-alpha": bad})
    # cross-param rules answer loudly at instantiation
    with pytest.raises(ParamError, match="needs 'quantiles true'"):
        _make_instance({"quantile-alpha": "0.05"})
    with pytest.raises(ParamError, match="needs 'quantiles true'"):
        _make_instance({"quantile-field": "mntns"})
    with pytest.raises(ParamError, match="not a .*column|wire column"):
        _make_instance({"quantiles": "true", "quantile-field": "latency"})
    # a valid config instantiates with the plane allocated
    inst = _make_instance({"quantiles": "true", "quantile-alpha": "0.02"})
    assert inst.enabled and inst.bundle.quantiles is not None
    assert inst._qt_alpha == 0.02 and inst._qt_field == "aux1"
    # plane off: the bundle carries NO quantile state at all
    off = _make_instance({})
    assert off.bundle.quantiles is None


# ---------------------------------------------------------------------------
# operator harvest: quantile block accuracy + telemetry accounting
# ---------------------------------------------------------------------------

def test_harvest_summary_quantiles_and_telemetry():
    rng = np.random.default_rng(1)
    n = 4000
    lat = _latencies(rng, n)
    lat[:250] = 0                      # no-magnitude events → zero bucket

    def counter(name) -> float:
        return sum(v for k, v in telemetry_registry.snapshot().items()
                   if k.startswith(name))

    ev0 = counter("ig_sketch_quantile_events_total")
    z0 = counter("ig_sketch_quantile_zero_total")
    inst = _make_instance({"quantiles": "true"})
    inst.enrich_batch(_batch(rng.integers(1, 1 << 32, n, dtype=np.uint64),
                             lat))
    s = inst.harvest()
    qt = s.quantiles
    assert qt is not None
    assert qt["total"] == n and qt["zeros"] == 250
    assert qt["alpha"] == 0.01
    pos = lat[lat > 0].astype(np.float64)
    for p, q in (("p50", 0.50), ("p90", 0.90), ("p99", 0.99)):
        true = float(np.quantile(lat.astype(np.float64), q))
        assert abs(qt[p] - true) / true < 0.03, (p, qt[p], true)
    assert pos.min() >= 1.0 and qt["underflow"] == 0
    # telemetry: every absorbed event counted once, zeros split out
    assert counter("ig_sketch_quantile_events_total") == ev0 + n
    assert counter("ig_sketch_quantile_zero_total") == z0 + 250
    # an empty plane-on harvest reads all-zero — never NaN on the wire
    empty = _make_instance({"quantiles": "true"})
    q2 = empty.harvest().quantiles
    assert q2 is not None
    assert q2["total"] == 0 and q2["p50"] == 0.0 and q2["p999"] == 0.0


def test_plane_off_summary_and_wire_shape():
    from inspektor_gadget_tpu.agent import wire
    from inspektor_gadget_tpu.operators.tpusketch import SketchSummary

    rng = np.random.default_rng(2)
    inst = _make_instance({})
    inst.enrich_batch(_batch(rng.integers(1, 1 << 32, 100, dtype=np.uint64),
                             _latencies(rng, 100)))
    s = inst.harvest()
    assert s.quantiles is None
    # plane-off summaries keep the pre-plane header shape exactly
    h, _ = wire.encode_summary(s)
    assert "quantiles" not in h
    # plane-on: the block roundtrips the wire verbatim
    qs = SketchSummary(
        events=10, drops=0, distinct=3.0, entropy_bits=1.5,
        heavy_hitters=[(1, 5)], epoch=2,
        quantiles={"p50": 1.0, "p90": 2.0, "p99": 3.0, "p999": 4.0,
                   "zeros": 1, "total": 10, "underflow": 0,
                   "alpha": 0.01})
    h2, payload = wire.encode_summary(qs)
    out = wire.decode_summary(h2, payload)
    assert out["quantiles"]["p99"] == 3.0
    assert out["quantiles"]["total"] == 10


# ---------------------------------------------------------------------------
# fleet: sealed-window deltas, merged accuracy, mixed-coverage refusal
# ---------------------------------------------------------------------------

def test_sealed_window_deltas_and_query_matches_live_read(fleet_store):
    rng = np.random.default_rng(3)
    n = 600
    lat = _latencies(rng, n)
    keys = rng.integers(1, 1 << 32, n, dtype=np.uint64)
    inst = _make_instance(
        {"quantiles": "true", "history": "true", "history-interval": "0",
         "history-log2-width": "8", "history-slots": "2"}, node="nA")
    inst.enrich_batch(_batch(keys[: n // 2], lat[: n // 2]))
    inst.seal_window()
    inst.enrich_batch(_batch(keys[n // 2:], lat[n // 2:]))
    inst.seal_window()
    live = inst.harvest().quantiles
    HISTORY.release(inst._hist_writer)
    frames = list(HISTORY.fetch_windows(base_dir=fleet_store, gadget=GADGET))
    wins = decode_frames(frames)
    assert len(wins) == 2
    # per-window DELTAS: each carries exactly its half of the stream
    assert sorted(w.qt_total for w in wins) == [n // 2, n // 2]
    ans = answer_query(wins)
    # dd_merge is lossless: the range fold reads EXACTLY like the live
    # bundle that produced the windows
    assert ans.quantiles == live
    assert ans.histogram is not None
    assert sum(ans.histogram) == n - live["zeros"]
    # the JSON surface carries both blocks
    doc = ans.to_dict()
    assert doc["quantiles"]["total"] == n
    assert doc["histogram"] == ans.histogram


def test_two_node_bimodal_merge_accuracy(fleet_store):
    """The acceptance shape: node nA is healthy, node nB regressed 10x.
    The merged fleet answer reads the TRUE combined distribution — a
    per-node average could never show the bimodal p99."""
    rng = np.random.default_rng(4)
    streams = {"nA": _latencies(rng, 500, median_ns=30_000.0),
               "nB": _latencies(rng, 500, median_ns=300_000.0)}
    for node, lat in streams.items():
        inst = _make_instance(
            {"quantiles": "true", "history": "true",
             "history-interval": "0", "history-log2-width": "8",
             "history-slots": "2"}, node=node)
        inst.enrich_batch(_batch(
            rng.integers(1, 1 << 32, len(lat), dtype=np.uint64), lat))
        inst.seal_window()
        HISTORY.release(inst._hist_writer)
    frames = list(HISTORY.fetch_windows(base_dir=fleet_store, gadget=GADGET))
    ans = answer_query(decode_frames(frames))
    both = np.concatenate(list(streams.values())).astype(np.float64)
    for p, q in (("p50", 0.50), ("p90", 0.90), ("p99", 0.99)):
        true = float(np.quantile(both, q))
        assert abs(ans.quantiles[p] - true) / true < 0.03, (p,)
    assert ans.quantiles["total"] == 1000


def test_mixed_coverage_drops_plane_loudly(fleet_store):
    """One node sealed without the plane: the merged range REFUSES to
    answer quantiles (partial coverage would lie) and says why."""
    rng = np.random.default_rng(5)
    for node, qt in (("nA", "true"), ("nB", "false")):
        inst = _make_instance(
            {"quantiles": qt, "history": "true", "history-interval": "0",
             "history-log2-width": "8", "history-slots": "2"}, node=node)
        inst.enrich_batch(_batch(
            rng.integers(1, 1 << 32, 200, dtype=np.uint64),
            _latencies(rng, 200)))
        inst.seal_window()
        HISTORY.release(inst._hist_writer)
    frames = list(HISTORY.fetch_windows(base_dir=fleet_store, gadget=GADGET))
    ans = answer_query(decode_frames(frames))
    assert ans.quantiles is None and ans.histogram is None
    assert any("quantile" in note for note in ans.dropped_windows)


# ---------------------------------------------------------------------------
# CLI: ig-tpu query --quantiles
# ---------------------------------------------------------------------------

def _seal_one(fleet_store, rng, node="nQ"):
    lat = _latencies(rng, 400)
    inst = _make_instance(
        {"quantiles": "true", "history": "true", "history-interval": "0",
         "history-log2-width": "8", "history-slots": "2"}, node=node)
    inst.enrich_batch(_batch(
        rng.integers(1, 1 << 32, 400, dtype=np.uint64), lat))
    inst.seal_window()
    HISTORY.release(inst._hist_writer)
    return lat


class _Args:
    remote = ""
    gadget = GADGET
    start_ts = None
    end_ts = None
    last = ""
    start_seq = None
    end_seq = None
    key = ""
    slices = False
    top = 20
    output = "table"
    quantiles = True

    def __init__(self, **kv):
        for k, v in kv.items():
            setattr(self, k, v)


def test_query_cli_quantiles_table_and_json(fleet_store, capsys):
    from inspektor_gadget_tpu.cli.query import cmd_query

    rng = np.random.default_rng(6)
    _seal_one(fleet_store, rng)
    assert cmd_query(_Args(history=fleet_store)) == 0
    out = capsys.readouterr().out
    assert "latency quantiles" in out
    assert "p99" in out and "ddsketch" in out
    # biolatency-style histogram rows render under the block
    assert "|" in out and "[" in out
    # the JSON surface carries the block verbatim
    assert cmd_query(_Args(history=fleet_store, output="json")) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["quantiles"]["total"] == 400
    assert isinstance(doc["histogram"], list)


def test_query_cli_quantiles_not_available(fleet_store, capsys):
    from inspektor_gadget_tpu.cli.query import cmd_query

    rng = np.random.default_rng(7)
    inst = _make_instance(
        {"history": "true", "history-interval": "0",
         "history-log2-width": "8", "history-slots": "2"}, node="nP")
    inst.enrich_batch(_batch(
        rng.integers(1, 1 << 32, 100, dtype=np.uint64)))
    inst.seal_window()
    HISTORY.release(inst._hist_writer)
    assert cmd_query(_Args(history=fleet_store)) == 0
    out = capsys.readouterr().out
    assert "quantiles: not available" in out


def test_render_histogram_log2_shape():
    from inspektor_gadget_tpu.cli.query import render_histogram_log2

    assert render_histogram_log2([0, 0, 0]) == []
    rows = render_histogram_log2([0, 4, 0, 2, 0])
    # contiguous lo..hi range, zero rows kept for visual continuity
    assert len(rows) == 3
    assert "[         2,          4)" in rows[0]
    assert rows[0].count("*") == 40        # peak row fills the bar
    assert rows[2].count("*") == 20


# ---------------------------------------------------------------------------
# sharded ingest: bit-identity at any chip count
# ---------------------------------------------------------------------------

def test_sharded_summary_quantiles_identical_to_single_chip():
    import jax
    if jax.local_device_count() < 4:
        pytest.skip("needs the 8-device CPU topology from conftest")
    rng = np.random.default_rng(8)
    n = 900
    keys = rng.integers(1, 1 << 32, n, dtype=np.uint64)
    lat = _latencies(rng, n)
    lat[:40] = 0
    ref = _make_instance({"quantiles": "true"})
    shard = _make_instance({"quantiles": "true", "shard-ingest": "true",
                            "chips": "4"})
    for i in range(3):
        ref.enrich_batch(_batch(keys[i::3], lat[i::3]))
        shard.enrich_batch(_batch(keys[i::3], lat[i::3]))
    s_ref, s_shard = ref.harvest(), shard.harvest()
    # the psum fold over int32 lanes is exact: identical, not just close
    assert s_ref.quantiles == s_shard.quantiles
    assert s_ref.quantiles["total"] == n
    assert s_ref.quantiles["zeros"] == 40
    shard.post_gadget_run()
    ref.post_gadget_run()


def test_quantile_plane_resume_from_checkpoint(tmp_path):
    from inspektor_gadget_tpu.operators import tpusketch

    tpusketch.set_checkpoint_dir(str(tmp_path))
    try:
        rng = np.random.default_rng(9)
        params = {"quantiles": "true"}
        keys = rng.integers(1, 1 << 32, 300, dtype=np.uint64)
        lat = _latencies(rng, 300)
        inst = _make_instance(params)
        inst.enrich_batch(_batch(keys, lat))
        inst.checkpoint()
        # "restart": a fresh instance resumes the DDSketch lanes with
        # the rest of the bundle, so totals span the restart
        inst2 = _make_instance(params)
        inst2.enrich_batch(_batch(keys, lat))
        qt = inst2.harvest().quantiles
        assert qt["total"] == 600
    finally:
        tpusketch.set_checkpoint_dir(None)


# ---------------------------------------------------------------------------
# alerts: the quantile_shift detector kind
# ---------------------------------------------------------------------------

def test_quantile_shift_rule_validation():
    from inspektor_gadget_tpu.alerts.rules import RuleError, load_rules

    rules = load_rules(json.dumps([{"id": "qs", "kind": "quantile_shift",
                                    "factor": 2.0}]))
    assert rules[0].field == "p99"          # the default percentile
    assert rules[0].threshold == 0.0        # threshold optional
    assert "quantile plane" in rules[0].describe()
    rules2 = load_rules(json.dumps([{"id": "qs", "kind": "quantile_shift",
                                     "field": "p50", "threshold": 500}]))
    assert rules2[0].field == "p50"
    with pytest.raises(RuleError, match="quantile_shift watches"):
        load_rules(json.dumps([{"id": "qs", "kind": "quantile_shift",
                                "field": "entropy"}]))


def test_quantile_shift_fires_once_on_regression():
    """Bimodal acceptance at the engine layer: healthy epochs build the
    baseline, an idle window (0.0 = no observation) must NOT poison it,
    the 3x regression epoch fires exactly once, and staying regressed
    does not re-fire."""
    from inspektor_gadget_tpu.alerts.engine import AlertEngine
    from inspektor_gadget_tpu.alerts.rules import load_rules

    rules = load_rules(json.dumps([{
        "id": "lat", "kind": "quantile_shift", "field": "p99",
        "factor": 2.0, "window": 3, "threshold": 1000, "for": 0}]))
    eng = AlertEngine(rules, node="n0", gadget=GADGET, dry_run=True)
    base = {"events": 100, "drops": 0, "distinct": 5.0, "entropy": 1.0,
            "heavy_hitters": [], "anomaly": {}}

    def obs(epoch, p99, now):
        return eng.observe({**base, "epoch": epoch,
                            "quantiles": {"p50": p99 / 2, "p90": p99 * 0.9,
                                          "p99": p99, "p999": p99 * 1.1}},
                           now=now)

    transitions = []
    # 3 healthy epochs (~100k ns), one idle window in the middle
    for i, p99 in enumerate((100_000.0, 101_000.0, 0.0, 99_000.0)):
        transitions += [(e.transition, i) for e in obs(i, p99, 10.0 * i)]
    assert transitions == []                # baseline warmup never fires
    # the regression epoch: 3x the baseline mean → exactly one firing
    evs = obs(4, 300_000.0, 40.0)
    # for: 0 → pending surfaces and promotes in the same epoch; exactly
    # ONE firing transition cluster-wide for the whole regression
    assert [e.transition for e in evs] == ["pending", "firing"]
    assert evs[-1].rule == "lat"
    assert evs[-1].value == 300_000.0
    # still regressed next epoch: the alert is already up — no re-fire
    evs2 = obs(5, 310_000.0, 50.0)
    assert not any(e.transition == "firing" for e in evs2)
    eng.close()


def test_quantile_shift_ignores_plane_off_summaries():
    """A fleet mixing plane-on and plane-off nodes: summaries without
    the block read 0.0 (= no observation) and can never trip the rule
    or drag the baseline toward zero."""
    from inspektor_gadget_tpu.alerts.engine import AlertEngine
    from inspektor_gadget_tpu.alerts.rules import load_rules

    rules = load_rules(json.dumps([{
        "id": "lat", "kind": "quantile_shift", "factor": 1.1,
        "window": 2, "for": 0}]))
    eng = AlertEngine(rules, node="n0", gadget=GADGET, dry_run=True)
    base = {"events": 100, "drops": 0, "distinct": 5.0, "entropy": 1.0,
            "heavy_hitters": [], "anomaly": {}}
    evs = []
    for epoch in range(6):                   # plane off: no quantiles key
        evs += eng.observe({**base, "epoch": epoch}, now=10.0 * epoch)
    assert evs == []
    eng.close()


# ---------------------------------------------------------------------------
# perf: micro-bench records + harness stages (tier-1 smoke)
# ---------------------------------------------------------------------------

def test_quantile_bench_publishes_schema_valid_records(tmp_path):
    from inspektor_gadget_tpu.perf.compare import compare_ledger
    from inspektor_gadget_tpu.perf.ledger import read_ledger
    from inspektor_gadget_tpu.perf.quantile_bench import publish
    from inspektor_gadget_tpu.perf.schema import validate_record

    ledger = str(tmp_path / "PERF.jsonl")
    records = publish(batch=1 << 10, n_buckets=256, seconds=0.05,
                      ledger=ledger)
    assert {r["config"] for r in records} == {"quantile-update",
                                              "quantile-merge"}
    for rec in records:
        assert validate_record(rec) == []
    on_disk = read_ledger(ledger).records
    assert len(on_disk) == 2
    # the series gates like any other: fresh series → no baseline → rc 0
    assert all(r.rc == 0 for r in compare_ledger(on_disk))


def test_harness_tiny_quantiles_smoke():
    from inspektor_gadget_tpu.perf.harness import run_harness
    from inspektor_gadget_tpu.perf.schema import validate_record

    rec = run_harness("tiny", platform="cpu", quantiles=True)
    assert validate_record(rec) == []
    assert rec["extra"]["quantiles"] is True
    assert rec["extra"]["qt_geometry"] == "2048@alpha0.01"
    assert "+qt" in rec["extra"]["pipeline"]
    assert "qt_update" in rec["stages"]
    # the plane measures the fused arm only — classic has no value lane
    with pytest.raises(ValueError, match="fused arm"):
        run_harness("tiny", platform="cpu", quantiles=True,
                    pipeline="classic")
