"""Distributed sketch pipeline tests on the 8-device virtual CPU mesh.

Validates the cluster-merge contract: per-node sharded sketch updates +
collective merge must equal the sequential union (the correctness bar the
reference meets with client-side merging, pkg/snapshotcombiner tests).
"""

import numpy as np
import jax
import jax.numpy as jnp

from inspektor_gadget_tpu.models import AEConfig, ae_init, ae_score, ae_train_step
from inspektor_gadget_tpu.models.autoencoder import normalize_counts
from inspektor_gadget_tpu.ops import bundle_init, bundle_update, cms_query, hll_estimate
from inspektor_gadget_tpu.parallel import (
    cluster_init,
    make_cluster_step,
    make_mesh,
)

BATCH = 256
DIM = 256


def small_cfg():
    return AEConfig(input_dim=DIM, hidden_dim=128, latent_dim=32)


def small_bundle_kw():
    return dict(depth=4, log2_width=12, hll_p=10, entropy_log2_width=8, k=32)


def test_mesh_axes():
    mesh = make_mesh()
    assert mesh.shape["node"] == 8
    mesh2 = make_mesh(n_nodes=4, n_model=2)
    assert mesh2.shape == {"node": 4, "model": 2}


def test_autoencoder_trains_and_scores():
    cfg = small_cfg()
    scorer = ae_init(cfg)
    rng = np.random.default_rng(0)
    x = normalize_counts(jnp.asarray(rng.poisson(5.0, (64, DIM)).astype(np.float32)))
    losses = []
    for _ in range(30):
        scorer, loss = ae_train_step(scorer, x)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5  # learns the distribution
    normal_score = float(ae_score(scorer, x).mean())
    weird = jnp.zeros((4, DIM), jnp.float32).at[:, 3].set(1.0)
    anomaly_score = float(ae_score(scorer, weird).mean())
    assert anomaly_score > normal_score


def test_cluster_step_matches_sequential_union():
    mesh = make_mesh(n_nodes=8)
    scorer = ae_init(small_cfg())
    state = cluster_init(mesh, scorer, **small_bundle_kw())
    step, merge = make_cluster_step(mesh, state)

    rng = np.random.default_rng(1)
    keys = rng.zipf(1.3, (8, BATCH)).clip(1, 10_000).astype(np.uint32)
    mask = np.ones((8, BATCH), dtype=bool)
    ae_batch = rng.poisson(3.0, (8, 16, DIM)).astype(np.float32)

    state, loss = step(
        state, jnp.asarray(keys), jnp.asarray(keys), jnp.asarray(keys),
        jnp.asarray(mask), jnp.asarray(ae_batch),
    )
    assert np.isfinite(float(loss))
    merged = merge(state.bundle)

    # sequential reference: all 8 node batches through one bundle
    seq = bundle_init(**small_bundle_kw())
    for i in range(8):
        seq = bundle_update(
            seq, jnp.asarray(keys[i]), jnp.asarray(keys[i]), jnp.asarray(keys[i]),
            jnp.ones(BATCH, bool),
        )

    assert float(merged.events) == 8 * BATCH
    assert jnp.array_equal(merged.cms.table, seq.cms.table)
    assert jnp.array_equal(merged.hll.registers, seq.hll.registers)
    np.testing.assert_allclose(
        np.asarray(merged.entropy.counts), np.asarray(seq.entropy.counts), rtol=1e-6
    )
    # merged top-k should surface the global heavy hitter
    uniq, counts = np.unique(keys, return_counts=True)
    true_top = uniq[np.argmax(counts)]
    tk = np.asarray(merged.topk.keys)
    assert true_top in tk


def test_cluster_distinct_counting_across_nodes():
    mesh = make_mesh(n_nodes=8)
    scorer = ae_init(small_cfg())
    state = cluster_init(mesh, scorer, **small_bundle_kw())
    step, merge = make_cluster_step(mesh, state)
    # each node sees a disjoint key range; merged HLL must see the union
    keys = np.arange(8 * BATCH, dtype=np.uint32).reshape(8, BATCH) * np.uint32(2654435761)
    mask = np.ones((8, BATCH), dtype=bool)
    ae_batch = np.ones((8, 8, DIM), dtype=np.float32)
    state, _ = step(state, jnp.asarray(keys), jnp.asarray(keys), jnp.asarray(keys),
                    jnp.asarray(mask), jnp.asarray(ae_batch))
    merged = merge(state.bundle)
    est = float(hll_estimate(merged.hll))
    assert abs(est - 8 * BATCH) / (8 * BATCH) < 0.1


def test_scorer_stays_replicated_and_synced():
    mesh = make_mesh(n_nodes=8)
    scorer = ae_init(small_cfg())
    state = cluster_init(mesh, scorer, **small_bundle_kw())
    step, _ = make_cluster_step(mesh, state)
    rng = np.random.default_rng(2)
    keys = np.ones((8, BATCH), dtype=np.uint32)
    mask = np.ones((8, BATCH), dtype=bool)
    # different data per node — pmean grads must keep replicas identical
    ae_batch = rng.poisson(3.0, (8, 8, DIM)).astype(np.float32)
    state, _ = step(state, jnp.asarray(keys), jnp.asarray(keys), jnp.asarray(keys),
                    jnp.asarray(mask), jnp.asarray(ae_batch))
    w = state.scorer.params["enc1"]["w"]
    shards = [np.asarray(s.data) for s in w.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)


def test_tp_autoencoder_matches_replicated():
    """TP forward (Megatron sharding, psum contractions) must equal the
    single-device forward on the same weights."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from inspektor_gadget_tpu.models.autoencoder import (
        AEConfig, ae_init, ae_apply, ae_apply_tp)
    from inspektor_gadget_tpu.parallel.cluster import scorer_pspecs
    from inspektor_gadget_tpu.parallel import make_mesh

    cfg = AEConfig(input_dim=128, hidden_dim=128, latent_dim=32,
                   compute_dtype=jnp.float32)
    scorer = ae_init(cfg, seed=3)
    x = normalize_counts(jnp.asarray(
        np.random.default_rng(0).poisson(4.0, (8, 128)).astype(np.float32)))
    ref = ae_apply(scorer.params, x, cfg)

    mesh = make_mesh(n_nodes=4, n_model=2)
    specs = scorer_pspecs(scorer)
    from inspektor_gadget_tpu.parallel.compat import shard_map
    tp_fn = jax.jit(shard_map(
        lambda p, xx: ae_apply_tp(p, xx, cfg, model_axis="model"),
        mesh=mesh,
        in_specs=(specs.params, P()),
        out_specs=P(),
        check_vma=False,
    ))
    sharded_params = jax.device_put(
        scorer.params,
        jax.tree.map(lambda s: NamedSharding(mesh, s), specs.params,
                     is_leaf=lambda v: isinstance(v, P)))
    out = tp_fn(sharded_params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_cluster_step_tp_mode():
    mesh = make_mesh(n_nodes=4, n_model=2)
    scorer = ae_init(AEConfig(input_dim=DIM, hidden_dim=128, latent_dim=32))
    state = cluster_init(mesh, scorer, **small_bundle_kw())
    step, merge = make_cluster_step(mesh, state)
    rng = np.random.default_rng(9)
    keys = rng.integers(1, 2**32, (4, BATCH), dtype=np.uint32)
    mask = np.ones((4, BATCH), bool)
    ae_batch = rng.poisson(3.0, (4, 8, DIM)).astype(np.float32)
    state, loss = step(state, jnp.asarray(keys), jnp.asarray(keys),
                       jnp.asarray(keys), jnp.asarray(mask),
                       jnp.asarray(ae_batch))
    assert np.isfinite(float(loss))
    merged = merge(state.bundle)
    assert float(merged.events) == 4 * BATCH


def test_ring_psum_variants_match_allreduce():
    """Ring all-reduce (ppermute hops) and the reduce-scatter/all-gather
    ring must equal lax.psum exactly on integer tables."""
    from jax.sharding import PartitionSpec as P
    from inspektor_gadget_tpu.parallel.compat import shard_map
    from inspektor_gadget_tpu.parallel.ring import ring_psum, ring_psum_chunked

    mesh = make_mesh(n_nodes=8)
    x = jnp.arange(8 * 37, dtype=jnp.int32).reshape(8, 37)
    want = np.broadcast_to(np.asarray(x).sum(0), (8, 37))
    for fn in (ring_psum, ring_psum_chunked):
        f = jax.jit(shard_map(
            lambda v: fn(v[0], "node")[None], mesh=mesh,
            in_specs=(P("node"),), out_specs=P("node"), check_vma=False))
        np.testing.assert_array_equal(np.asarray(f(x)), want)


def test_vae_trains_and_scores_anomalies():
    from inspektor_gadget_tpu.models import VAEConfig, vae_init, vae_score, vae_train_step

    cfg = VAEConfig(input_dim=DIM, hidden_dim=128, latent_dim=16,
                    compute_dtype=jnp.float32)
    scorer = vae_init(cfg, seed=1)
    rng = np.random.default_rng(0)
    x = normalize_counts(jnp.asarray(rng.poisson(5.0, (64, DIM)).astype(np.float32)))
    losses = []
    for _ in range(30):
        scorer, loss = vae_train_step(scorer, x)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    normal = float(vae_score(scorer, x).mean())
    weird = jnp.zeros((4, DIM), jnp.float32).at[:, 5].set(1.0)
    assert float(vae_score(scorer, weird).mean()) > normal


def test_compat_shim_resolves_this_jax():
    """The ISSUE-14 version-drift shim: drift_notes names how THIS jax
    spells each shimmed symbol, shard_map accepts the new keyword surface
    (check_vma) on every supported jax, axis_size is a static int inside
    the mapped body, and the Pallas TPU compiler-params constructor
    resolves across the rename."""
    from jax.sharding import PartitionSpec as P

    from inspektor_gadget_tpu.parallel import compat

    notes = compat.drift_notes()
    assert set(notes) >= {"jax", "shard_map", "check_flag",
                          "compiler_params", "varying_cast"}

    mesh = make_mesh(n_nodes=4, n_model=1)

    def body(x):
        n = compat.axis_size("node")
        assert isinstance(n, int) and n == 4
        return (x[0] * 2)[None]

    f = jax.jit(compat.shard_map(body, mesh=mesh, in_specs=(P("node"),),
                                 out_specs=P("node"), check_vma=False))
    x = jnp.arange(8, dtype=jnp.int32).reshape(4, 2)
    np.testing.assert_array_equal(np.asarray(f(x)), np.asarray(x) * 2)

    assert compat.tpu_compiler_params(
        dimension_semantics=("parallel",)) is not None


def test_ingest_mesh_shape_and_validation():
    """ingest_mesh (ISSUE 14): a (node)-only mesh over the first N local
    devices, loud on impossible chip counts."""
    import pytest

    from inspektor_gadget_tpu.parallel.mesh import ingest_mesh

    mesh = ingest_mesh(4)
    assert mesh.shape == {"node": 4}
    assert ingest_mesh(1).shape == {"node": 1}
    with pytest.raises(ValueError, match="exceeds"):
        ingest_mesh(99)
    with pytest.raises(ValueError, match=">= 1"):
        ingest_mesh(0)
