"""Black-box CLI tests (model: the reference's ig integration tier —
integration/ig/* runs the built binary and matches output)."""

import json
import subprocess
import sys

import pytest

CLI = [sys.executable, "-m", "inspektor_gadget_tpu.cli.main"]


def run_cli(*args, timeout=120):
    return subprocess.run(CLI + list(args), capture_output=True, text=True,
                          timeout=timeout)


def test_cli_list_and_catalog():
    r = run_cli("list")
    assert r.returncode == 0
    assert "trace" in r.stdout and "exec" in r.stdout
    assert len(r.stdout.strip().splitlines()) >= 25

    r = run_cli("catalog")
    cat = json.loads(r.stdout)
    assert len(cat["gadgets"]) >= 25
    assert any(op["name"] == "tpusketch" for op in cat["operators"])


def test_cli_trace_exec_json_output():
    r = run_cli("trace", "exec", "--source", "pysynthetic", "--rate", "3000",
                "--timeout", "1", "-o", "json")
    assert r.returncode == 0, r.stderr
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert len(lines) > 10
    row = json.loads(lines[0])
    assert row["comm"].startswith("proc-") and row["pid"] > 0


def test_cli_bad_param_exits_2():
    r = run_cli("trace", "exec", "--source", "bogus", "--timeout", "1")
    assert r.returncode == 2
    assert "not in" in r.stderr


def test_cli_deploy_render():
    r = run_cli("deploy", "--render")
    assert r.returncode == 0
    assert "kind: DaemonSet" in r.stdout
    assert "google.com/tpu" in r.stdout
