"""Black-box CLI tests (model: the reference's ig integration tier —
integration/ig/* runs the built binary and matches output)."""

import json
import subprocess
import sys

import pytest

CLI = [sys.executable, "-m", "inspektor_gadget_tpu.cli.main"]


def run_cli(*args, timeout=120):
    return subprocess.run(CLI + list(args), capture_output=True, text=True,
                          timeout=timeout)


def test_cli_list_and_catalog():
    r = run_cli("list")
    assert r.returncode == 0
    assert "trace" in r.stdout and "exec" in r.stdout
    assert len(r.stdout.strip().splitlines()) >= 25

    r = run_cli("catalog")
    cat = json.loads(r.stdout)
    assert len(cat["gadgets"]) >= 25
    assert any(op["name"] == "tpusketch" for op in cat["operators"])


def test_cli_trace_exec_json_output():
    r = run_cli("trace", "exec", "--source", "pysynthetic", "--rate", "3000",
                "--timeout", "1", "-o", "json")
    assert r.returncode == 0, r.stderr
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert len(lines) > 10
    row = json.loads(lines[0])
    assert row["comm"].startswith("proc-") and row["pid"] > 0


def test_cli_bad_param_exits_2():
    r = run_cli("trace", "exec", "--source", "bogus", "--timeout", "1")
    assert r.returncode == 2
    assert "not in" in r.stderr


def test_cli_deploy_render():
    r = run_cli("deploy", "--render")
    assert r.returncode == 0
    assert "kind: DaemonSet" in r.stdout
    assert "google.com/tpu" in r.stdout


def test_cli_traces_lifecycle_against_live_daemon(tmp_path):
    """The kubectl-gadget advise ergonomics (§3.5) as a black box: a real
    agent daemon subprocess + `ig-tpu traces` verbs from separate CLI
    processes (ref: cmd/kubectl-gadget/utils/trace.go:340-848)."""
    import os
    import time

    addr = f"unix://{tmp_path}/agent.sock"
    remote = f"n0={addr}"
    daemon = subprocess.Popen(
        [sys.executable, "-m", "inspektor_gadget_tpu.agent.main", "serve",
         "--listen", addr, "--node-name", "n0", "--no-doctor"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        cwd="/root/repo")
    try:
        deadline = time.time() + 120
        up = False
        while time.time() < deadline and not up:
            if os.path.exists(f"{tmp_path}/agent.sock"):
                r = run_cli("traces", "list", "--remote", remote)
                up = r.returncode == 0
            if not up:
                time.sleep(1.0)
        assert up, "agent never served"

        r = run_cli("traces", "start", "--remote", remote, "--name", "bb1",
                    "--gadget", "advise/seccomp-profile",
                    "-p", "source=pysynthetic", "-p", "rate=20000")
        assert r.returncode == 0, r.stderr
        assert "bb1 Started" in r.stdout
        time.sleep(1.0)
        r = run_cli("traces", "generate", "--remote", remote,
                    "--name", "bb1")
        assert r.returncode == 0, r.stderr
        assert "defaultAction" in r.stdout
        r = run_cli("traces", "delete", "--remote", remote, "--name", "bb1")
        assert r.returncode == 0 and "deleted=True" in r.stdout
    finally:
        daemon.terminate()
        try:
            daemon.wait(timeout=15)
        except subprocess.TimeoutExpired:
            daemon.kill()
