"""Capture-plane unit tier: segment framing, torn-tail recovery,
rotation + retention GC (including under concurrent writers), manifest
provenance round-trip, range reads, digests, the recording manager, and
the shared utils/journal reader all three planes now sit on."""

from __future__ import annotations

import os
import threading
import zlib

import pytest

from inspektor_gadget_tpu.agent import wire
from inspektor_gadget_tpu.capture import (
    RECORDINGS,
    JournalReader,
    JournalWriter,
    build_manifest,
    is_journal,
    summary_digest,
)
from inspektor_gadget_tpu.capture.journal import INDEX, scan_segment
from inspektor_gadget_tpu.utils.journal import append_line, read_json_file, read_jsonl


def _write(tmp_path, name="j", n=5, **kw):
    w = JournalWriter(str(tmp_path / name), **kw)
    for i in range(n):
        w.append(wire.EV_BATCH_NPZ, {"count": i + 1}, f"payload-{i}".encode())
    return w


# -- shared utils/journal reader (the factored-out satellite) ---------------

def test_read_jsonl_stop_vs_skip(tmp_path):
    p = str(tmp_path / "x.jsonl")
    append_line(p, {"a": 1})
    with open(p, "a") as f:
        f.write("{broken}\n")
    append_line(p, {"b": 2})
    stop = read_jsonl(p, on_bad="stop")
    assert [r for r in stop.records] == [{"a": 1}] and stop.skipped
    skip = read_jsonl(p, on_bad="skip")
    assert skip.records == [{"a": 1}, {"b": 2}] and skip.skipped


def test_read_jsonl_validate_and_missing(tmp_path):
    p = str(tmp_path / "y.jsonl")
    append_line(p, {"v": 1})
    append_line(p, {"v": -1})
    r = read_jsonl(p, on_bad="skip",
                   validate=lambda rec: "neg" if rec["v"] < 0 else None)
    assert r.records == [{"v": 1}] and "invalid (neg)" in r.skipped[0]
    assert read_jsonl(str(tmp_path / "absent.jsonl")).records == []


def test_flight_recorder_dump_reads_tolerate_truncation(tmp_path):
    from inspektor_gadget_tpu.telemetry.tracing import RECORDER, load_dump
    p = str(tmp_path / "flight.json")
    RECORDER.dump(p)
    doc, err = load_dump(p)
    assert doc is not None and not err and "spans" in doc
    # crash-truncated dump: reported, not raised
    blob = open(p).read()
    open(p, "w").write(blob[: len(blob) // 2])
    doc, err = load_dump(p)
    assert doc is None and "truncated" in err
    # an interrupted atomic write leaves .tmp.<pid>; recovery reads it
    open(f"{p}.tmp.12345", "w").write(blob)
    doc, err = load_dump(p)
    assert doc is not None and "recovered" in err


def test_webhook_sink_and_ledger_share_the_reader(tmp_path):
    # the two pre-existing consumers still read through their old API
    from inspektor_gadget_tpu.alerts import WebhookFileSink
    from inspektor_gadget_tpu.alerts.engine import AlertEvent
    p = str(tmp_path / "hook.jsonl")
    WebhookFileSink(p).emit(AlertEvent(rule="r", severity="warning",
                                       kind="threshold",
                                       transition="firing"))
    with open(p, "a") as f:
        f.write('{"torn": ')
    events = WebhookFileSink.read(p)
    assert len(events) == 1 and events[0]["rule"] == "r"


# -- framing + torn tails ---------------------------------------------------

def test_journal_roundtrip_types_and_payloads(tmp_path):
    w = _write(tmp_path, n=3)
    w.mark("run-end", run_id="x")
    w.close()
    r = JournalReader(str(tmp_path / "j"))
    recs = list(r.records())
    assert [h["type"] for h, _ in recs] == [wire.EV_BATCH_NPZ] * 3 + [
        wire.EV_JOURNAL_MARK]
    assert [h["seq"] for h, _ in recs] == [1, 2, 3, 4]
    assert recs[0][1] == b"payload-0"
    assert recs[3][0]["mark"] == "run-end"
    assert not r.losses


@pytest.mark.parametrize("tear", ["header", "body", "crc"])
def test_torn_tail_dropped_and_accounted(tmp_path, tear):
    w = _write(tmp_path, name=f"t-{tear}", n=4)
    seg = w._active_path()
    w.close()
    data = open(seg, "rb").read()
    if tear == "header":
        open(seg, "ab").write(b"\x20\x00")          # half a length prefix
    elif tear == "body":
        zp = zlib.compress(b"never-finished")
        frame = (len(zp).to_bytes(4, "little")
                 + (zlib.crc32(zp) & 0xFFFFFFFF).to_bytes(4, "little") + zp)
        open(seg, "ab").write(frame[: len(frame) - 3])
    else:  # flip a payload byte: crc must catch it
        mutated = bytearray(data)
        mutated[-1] ^= 0xFF
        open(seg, "wb").write(bytes(mutated))
    r = JournalReader(os.path.dirname(seg))
    recs = list(r.records())
    assert len(recs) == (4 if tear != "crc" else 3)
    assert len(r.losses) == 1
    loss = r.losses[0]
    assert loss.dropped_bytes > 0
    assert loss.reason  # named, not silent


def test_reopen_after_crash_truncates_tear_and_continues_seq(tmp_path):
    w = _write(tmp_path, name="re", n=3)
    seg = w._active_path()
    # crash: no close(); a torn frame sits at the tail
    open(seg, "ab").write(b"\x99\x00\x00\x00junk")
    w2 = JournalWriter(str(tmp_path / "re"))
    s = w2.append(wire.EV_JOURNAL_MARK, {"mark": "resumed"})
    assert s == 4  # continues after the last GOOD record
    w2.close()
    r = JournalReader(str(tmp_path / "re"))
    recs = list(r.records())
    assert [h["seq"] for h, _ in recs] == [1, 2, 3, 4]
    assert not r.losses  # recovery truncated the tear on reopen


# -- rotation, index, range reads, retention GC -----------------------------

def test_rotation_seals_segments_with_index_ranges(tmp_path):
    w = JournalWriter(str(tmp_path / "rot"), max_segment_bytes=1 << 12,
                      max_segment_age=0)
    for i in range(200):
        w.append(wire.EV_BATCH_NPZ, {"i": i}, os.urandom(100))
    w.close()
    idx = read_jsonl(str(tmp_path / "rot" / INDEX)).records
    assert len(idx) >= 2
    # index rows carry contiguous seq ranges
    assert idx[0]["first_seq"] == 1
    for a, b in zip(idx, idx[1:]):
        assert b["first_seq"] == a["last_seq"] + 1
    r = JournalReader(str(tmp_path / "rot"))
    assert sum(1 for _ in r.records()) == 200


def test_range_reads_use_seq_and_ts(tmp_path):
    t = [100.0]

    def clock():
        t[0] += 1.0
        return t[0]

    w = JournalWriter(str(tmp_path / "rng"), max_segment_bytes=1 << 12,
                      max_segment_age=0, clock=clock)
    for i in range(120):
        w.append(wire.EV_BATCH_NPZ, {"i": i}, b"x" * 64)
    w.close()
    r = JournalReader(str(tmp_path / "rng"))
    seqs = [h["seq"] for h, _ in r.records(start_seq=50, end_seq=60)]
    assert seqs == list(range(50, 61))
    ts_recs = [h for h, _ in r.records(start_ts=150.0, end_ts=160.0)]
    assert ts_recs and all(150.0 <= h["ts"] <= 160.0 for h in ts_recs)


def test_retention_gc_under_concurrent_writes(tmp_path):
    w = JournalWriter(str(tmp_path / "gc"), max_segment_bytes=1 << 12,
                      max_segment_age=0, retention_bytes=3 << 12)
    errors: list[BaseException] = []

    def pump(tid: int):
        try:
            for _ in range(150):
                w.append(wire.EV_BATCH_NPZ, {"tid": tid}, os.urandom(120))
        except BaseException as e:  # noqa: BLE001 — surfaced via the list
            errors.append(e)

    threads = [threading.Thread(target=pump, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    w.close()
    assert not errors, errors
    r = JournalReader(str(tmp_path / "gc"))
    seqs = [h["seq"] for h, _ in r.records()]
    # GC dropped oldest sealed segments; what survives is a strictly
    # increasing contiguous SUFFIX of the stream ending at the last seq
    assert seqs and seqs[-1] == 600
    assert seqs == list(range(seqs[0], 601))
    assert r.missing_segments  # the GC'd history is visible, not silent
    segs = [f for f in os.listdir(tmp_path / "gc") if f.endswith(".igj")]
    total = sum(os.path.getsize(tmp_path / "gc" / f) for f in segs)
    assert total <= (3 << 12) + (2 << 12)  # retention + one active segment


# -- manifest provenance + digests ------------------------------------------

def test_manifest_provenance_round_trip(tmp_path):
    m = build_manifest(journal_id="jid", node="n0", gadget="trace/exec",
                       run_id="r1", params={"gadget.seed": "7"})
    w = JournalWriter(str(tmp_path / "prov"), manifest=m)
    w.append(wire.EV_JOURNAL_MARK, {"mark": "x"})
    w.close()
    r = JournalReader(str(tmp_path / "prov"))
    got = r.manifest
    assert got["schema"] == "ig-tpu/capture-journal/v1"
    assert (got["node"], got["gadget"], got["run_id"]) == \
        ("n0", "trace/exec", "r1")
    assert got["params"] == {"gadget.seed": "7"}
    assert "git_sha" in got and "platform" in got and "host" in got
    assert got["last_seq"] == 1 and got["closed_ts"] >= got["created_ts"]


def test_digest_stable_and_append_sensitive(tmp_path):
    w = _write(tmp_path, name="dig", n=4)
    w.close()
    r1 = JournalReader(str(tmp_path / "dig"))
    d1 = r1.digest()
    assert d1 == JournalReader(str(tmp_path / "dig")).digest()
    w2 = JournalWriter(str(tmp_path / "dig"))
    w2.append(wire.EV_JOURNAL_MARK, {"mark": "more"})
    w2.close()
    assert JournalReader(str(tmp_path / "dig")).digest() != d1


def test_summary_digest_ignores_names_only(tmp_path):
    base = {"events": 10, "drops": 0, "distinct": 3.5, "entropy": 1.25,
            "epoch": 2, "heavy_hitters": [(1, 5), (2, 3)]}
    a = summary_digest({**base, "names": {"1": "x"}})
    b = summary_digest({**base, "names": {"1": "y"}})
    assert a == b
    assert summary_digest({**base, "events": 11}) != a


def test_scan_segment_reports_unreadable(tmp_path):
    recs, loss = scan_segment(str(tmp_path / "nope.igj"))
    assert recs == [] and loss is not None and "unreadable" in loss.reason


# -- recording manager ------------------------------------------------------

def test_recording_manager_lifecycle(tmp_path):
    base = str(tmp_path / "area")
    rec = RECORDINGS.start("rec-1", base_dir=base)
    try:
        w = rec.writer_for(node="n0", gadget="trace/exec", run_id="runA",
                           params={"k": "v"})
        w.append(wire.EV_BATCH_NPZ, {"count": 1}, b"z")
        listed = [r for r in RECORDINGS.list(base) if r["id"] == "rec-1"]
        assert listed and listed[0]["state"] == "recording"
    finally:
        meta = RECORDINGS.stop("rec-1")
    assert meta["journals"] == ["n0--runA"]
    assert is_journal(os.path.join(base, "rec-1", "n0--runA"))
    insp = RECORDINGS.inspect("rec-1", base)
    assert insp["state"] == "stopped"
    j = insp["journals"]["n0--runA"]
    # recording-start mark + batch + recording-stop mark
    assert j["records"] == 3 and not j["losses"]
    stopped = [r for r in RECORDINGS.list(base) if r["id"] == "rec-1"]
    assert stopped and stopped[0]["state"] == "stopped"
    with pytest.raises(KeyError):
        RECORDINGS.stop("rec-1")


def test_reopen_after_clean_close_starts_next_segment(tmp_path):
    """Appending into a SEALED segment would silently invalidate its
    index row (stale last_seq/bytes, duplicate rows on the next seal) —
    a reopen after close() must start the next segment instead."""
    w = _write(tmp_path, name="sealed", n=3)
    w.close()  # seals seg-00000001 into the index
    w2 = JournalWriter(str(tmp_path / "sealed"))
    w2.append(wire.EV_JOURNAL_MARK, {"mark": "after-close"})
    w2.close()
    idx = read_jsonl(str(tmp_path / "sealed" / INDEX)).records
    files = [row["file"] for row in idx]
    assert files == ["seg-00000001.igj", "seg-00000002.igj"]
    assert idx[0]["last_seq"] == 3 and idx[1]["first_seq"] == 4
    r = JournalReader(str(tmp_path / "sealed"))
    assert [h["seq"] for h, _ in r.records()] == [1, 2, 3, 4]
    assert [h["seq"] for h, _ in r.records(start_seq=4)] == [4]


def test_recovered_tail_keeps_its_timestamps(tmp_path):
    """A crash-recovered tail segment must seal with the REAL last_ts —
    a zeroed one makes time-range reads skip the whole segment."""
    t = [1000.0]

    def clock():
        t[0] += 1.0
        return t[0]

    w = JournalWriter(str(tmp_path / "ts"), clock=clock)
    for _ in range(3):
        w.append(wire.EV_BATCH_NPZ, {}, b"x")
    # crash: no close(); reopen and seal without any new appends
    w2 = JournalWriter(str(tmp_path / "ts"), clock=clock)
    w2.close()
    idx = read_jsonl(str(tmp_path / "ts" / INDEX)).records
    assert idx and idx[-1]["last_ts"] >= 1000.0
    r = JournalReader(str(tmp_path / "ts"))
    assert sum(1 for _ in r.records(start_ts=1000.0)) == 3


def test_torn_index_line_repaired_on_reopen(tmp_path):
    """A crash mid-seal can tear an index.jsonl line; a reopened writer
    must repair it (atomic rewrite of the good rows) — otherwise every
    later seal row lands after the tear and stays invisible to the
    on_bad='stop' readers forever."""
    w = JournalWriter(str(tmp_path / "ix"), max_segment_bytes=1 << 12,
                      max_segment_age=0)
    for _ in range(80):
        w.append(wire.EV_BATCH_NPZ, {}, os.urandom(100))
    # crash mid-seal: a torn line at the index tail, no close()
    ipath = str(tmp_path / "ix" / INDEX)
    good_rows = read_jsonl(ipath).records
    assert good_rows
    with open(ipath, "a") as f:
        f.write('{"file": "seg-')
    w2 = JournalWriter(str(tmp_path / "ix"))
    for _ in range(80):
        w2.append(wire.EV_BATCH_NPZ, {}, os.urandom(100))
    w2.close()
    idx = read_jsonl(ipath, on_bad="stop")
    assert not idx.skipped  # repaired: nothing hides behind a torn line
    assert len(idx.records) > len(good_rows)
    r = JournalReader(str(tmp_path / "ix"))
    seqs = [h["seq"] for h, _ in r.records()]
    assert seqs == list(range(1, 161))


def test_recording_id_validation_guards_path_resolution(tmp_path):
    """The agent's recording RPCs resolve <base>/<id> for ids a client
    sent: separators, '..', and absolute ids must be refused, not
    joined (os.path.join discards the base on an absolute component)."""
    from inspektor_gadget_tpu.capture.manager import validate_recording_id
    for bad in ("/etc", "a/b", "..", ".", "", "../x"):
        with pytest.raises(ValueError):
            validate_recording_id(bad)
        with pytest.raises(ValueError):
            RECORDINGS.recording_dir(bad, str(tmp_path))
    assert validate_recording_id("incident-7.2") == "incident-7.2"


def test_fetch_recording_refuses_zip_slip_listing(tmp_path):
    """A compromised agent's listing must not write outside dest_dir."""
    from inspektor_gadget_tpu.agent.client import AgentClient
    client = AgentClient.__new__(AgentClient)
    client.node_name = "evil"
    client.list_recordings = lambda rid: {
        "files": [{"path": "../../escape.txt", "bytes": 1}]}
    client.fetch_file = lambda *a, **k: pytest.fail(
        "must refuse before fetching")
    with pytest.raises(RuntimeError, match="escaping the bundle"):
        client.fetch_recording("r", str(tmp_path / "dest"))


def test_recording_manager_rejects_bad_ids_and_duplicates(tmp_path):
    base = str(tmp_path / "area2")
    with pytest.raises(ValueError):
        RECORDINGS.start("../escape", base_dir=base)
    RECORDINGS.start("dup", base_dir=base)
    try:
        with pytest.raises(ValueError):
            RECORDINGS.start("dup", base_dir=base)
    finally:
        RECORDINGS.stop("dup")
    with pytest.raises(ValueError):  # stopped-on-disk is also a collision
        RECORDINGS.start("dup", base_dir=base)
