"""Gadget framework tests: registry, operator toposort, container tracking,
local runtime end-to-end (the §3.1 minimum slice, synthetic source).
"""

import dataclasses

import numpy as np
import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.containers import (
    Container,
    ContainerCollection,
    ContainerSelector,
    TracerCollection,
    with_fake_containers,
    with_node_name,
)
from inspektor_gadget_tpu.gadgets import GadgetContext, get, get_all
from inspektor_gadget_tpu.operators.operators import (
    Operator,
    OperatorInstance,
    sort_operators,
)
from inspektor_gadget_tpu.params import Collection
from inspektor_gadget_tpu.runtime import LocalRuntime


# -- registry ---------------------------------------------------------------

def test_registry_has_core_gadgets():
    names = {(d.category, d.name) for d in get_all()}
    assert ("trace", "exec") in names
    assert ("trace", "tcp") in names
    assert ("trace", "tcpconnect") in names


def test_registry_get_unknown():
    with pytest.raises(KeyError, match="unknown gadget"):
        get("trace", "nope")


# -- operator toposort (ref: operators.go:269-348 + tests) ------------------

def _op(name, deps):
    class O(Operator):
        pass
    o = O()
    o.name = name
    o.dependencies = lambda: deps
    return o


def test_sort_operators_orders_dependencies():
    a, b, c = _op("a", ["b"]), _op("b", ["c"]), _op("c", [])
    out = sort_operators([a, b, c])
    assert [o.name for o in out] == ["c", "b", "a"]


def test_sort_operators_cycle_detected():
    a, b = _op("a", ["b"]), _op("b", ["a"])
    with pytest.raises(ValueError, match="cycle"):
        sort_operators([a, b])


def test_sort_operators_missing_dep():
    with pytest.raises(ValueError, match="unregistered"):
        sort_operators([_op("a", ["ghost"])])


# -- containers (ref: container-collection tests, match_test.go) ------------

def make_cc():
    cc = ContainerCollection()
    cc.initialize(
        with_node_name("node-1"),
        with_fake_containers([
            Container(id="c1", name="web", pod="web-pod", namespace="prod",
                      mntns=1001, pid=100, labels={"app": "web"}),
            Container(id="c2", name="db", pod="db-pod", namespace="prod",
                      mntns=1002, pid=200),
            Container(id="c3", name="web", pod="web-2", namespace="dev",
                      mntns=1003, pid=300),
        ]),
    )
    return cc


def test_selector_matching():
    cc = make_cc()
    assert len(cc.get_all(ContainerSelector())) == 3
    assert len(cc.get_all(ContainerSelector(name="web"))) == 2
    assert len(cc.get_all(ContainerSelector(namespace="prod", name="web"))) == 1
    assert len(cc.get_all(ContainerSelector(labels={"app": "web"}))) == 1
    assert len(cc.get_all(ContainerSelector(labels={"app": "x"}))) == 0


def test_mntns_lookup_and_removal_grace():
    cc = make_cc()
    assert cc.lookup_by_mntns(1001).name == "web"
    cc.remove_container("c1")
    # 2s removal cache keeps late events enrichable (ref: options.go:689)
    assert cc.lookup_by_mntns(1001).name == "web"
    assert len(cc) == 2


def test_event_enrichment_by_mntns():
    cc = make_cc()

    @dataclasses.dataclass
    class Ev:
        mountnsid: int = 0
        container: str = ""
        pod: str = ""
        namespace: str = ""
        node: str = ""

    ev = Ev(mountnsid=1002)
    cc.enrich_event_by_mntns(ev)
    assert ev.container == "db" and ev.pod == "db-pod" and ev.node == "node-1"


def test_tracer_collection_tracks_membership():
    cc = make_cc()
    tc = TracerCollection(cc)
    tc.add_tracer("t1", ContainerSelector(name="web"))
    assert tc.tracer_mntns_set("t1") == {1001, 1003}
    cc.add_container(Container(id="c4", name="web", mntns=1004, pid=400))
    assert tc.tracer_mntns_set("t1") == {1001, 1003, 1004}
    cc.remove_container("c1")
    assert tc.tracer_mntns_set("t1") == {1003, 1004}
    tc.remove_tracer("t1")
    with pytest.raises(KeyError):
        tc.tracer_mntns_set("t1")


# -- local runtime end-to-end (§3.1 minimum slice) --------------------------

def test_trace_exec_end_to_end_synthetic():
    desc = get("trace", "exec")
    params = desc.params().to_params()
    params.set("source", "pysynthetic")
    params.set("rate", "50000")
    params.set("batch-size", "512")
    ctx = GadgetContext(desc, gadget_params=params, timeout=0.5)
    events = []
    batches = []
    runtime = LocalRuntime()
    result = runtime.run_gadget(
        ctx, on_event=events.append, on_batch=batches.append)
    assert not result.errors()
    assert len(events) > 100
    assert all(e.comm.startswith("proc-") for e in events[:10])
    assert batches and batches[0].count > 0


def test_trace_exec_sketch_operator_end_to_end():
    desc = get("trace", "exec")
    params = desc.params().to_params()
    params.set("source", "pysynthetic")
    params.set("rate", "100000")
    summaries = []
    op_params = Collection()
    from inspektor_gadget_tpu.operators.operators import get as get_op
    sketch_params = get_op("tpusketch").instance_params().to_params()
    sketch_params.set("enable", "true")
    sketch_params.set("log2-width", "12")
    sketch_params.set("hll-p", "10")
    sketch_params.set("harvest-interval", "200ms")
    op_params["operator.tpusketch."] = sketch_params
    ctx = GadgetContext(
        desc, gadget_params=params, operator_params=op_params, timeout=1.0,
        extra={"on_sketch_summary": summaries.append},
    )
    result = LocalRuntime().run_gadget(ctx)
    assert not result.errors()
    assert summaries, "sketch operator must emit harvest summaries"
    last = summaries[-1]
    assert last.events > 1000
    assert last.heavy_hitters, "must surface heavy hitters"
    assert 0 < last.distinct < 2000
    assert last.entropy_bits > 0


def test_native_containers_map_mirror():
    from inspektor_gadget_tpu.sources.bridge import (
        containers_map_lookup, native_available)
    from inspektor_gadget_tpu.containers.options import with_native_containers_map

    if not native_available():
        import pytest as _pytest
        _pytest.skip("no native lib")
    cc = ContainerCollection()
    cc.initialize(
        with_fake_containers([Container(id="nm1", name="webby", mntns=777123)]),
        with_native_containers_map(),
    )
    assert containers_map_lookup(777123) == "webby"
    cc.add_container(Container(id="nm2", name="dbby", mntns=777124))
    assert containers_map_lookup(777124) == "dbby"
    cc.remove_container("nm2")
    assert containers_map_lookup(777124) == ""


def test_trace_exec_seq_anomaly_scorer_end_to_end():
    """tpusketch with the sequence-LM scorer family: per-container NLL
    scores appear in harvest summaries."""
    desc = get("trace", "exec")
    params = desc.params().to_params()
    params.set("source", "pysynthetic")
    params.set("rate", "50000")
    op_params = Collection()
    from inspektor_gadget_tpu.operators.operators import get as get_op
    sketch_params = get_op("tpusketch").instance_params().to_params()
    sketch_params.set("enable", "true")
    sketch_params.set("log2-width", "10")
    sketch_params.set("hll-p", "8")
    sketch_params.set("anomaly", "true")
    sketch_params.set("anomaly-model", "seq")
    sketch_params.set("seq-window", "128")
    sketch_params.set("harvest-interval", "300ms")
    op_params["operator.tpusketch."] = sketch_params
    summaries = []
    ctx = GadgetContext(
        desc, gadget_params=params, operator_params=op_params, timeout=1.2,
        extra={"on_sketch_summary": summaries.append},
    )
    result = LocalRuntime().run_gadget(ctx)
    assert not result.errors()
    scored = [s for s in summaries if s.anomaly]
    assert scored, "sequence scorer must emit per-container scores"
    for ns, score in scored[-1].anomaly.items():
        assert score == score and score >= 0  # finite NLL
