"""Tier-1 lint: no silently-swallowed broad excepts in the package (the
`except Exception: pass` pattern that ate checkpoint failures in round
5), plus self-tests that the checker actually catches the pattern."""

from __future__ import annotations

import textwrap
from pathlib import Path

from tools.check_bare_except import check_paths, check_source

PKG = Path(__file__).resolve().parent.parent / "inspektor_gadget_tpu"


def test_package_has_no_silent_broad_excepts():
    violations = check_paths(PKG)
    assert not violations, "\n".join(violations)


def test_checker_flags_the_round5_pattern():
    bad = textwrap.dedent("""
        try:
            risky()
        except Exception:
            pass
    """)
    (v,) = check_source(bad, "bad.py")
    assert "bad.py:4" in v and "swallowed" in v


def test_checker_flags_bare_and_tuple_and_ellipsis():
    assert check_source("try:\n x()\nexcept:\n pass\n", "f.py")
    assert check_source(
        "try:\n x()\nexcept (ValueError, Exception):\n pass\n", "f.py")
    assert check_source("try:\n x()\nexcept Exception:\n ...\n", "f.py")


def test_checker_allows_narrow_and_handled_and_waived():
    # narrow type: documents exactly what is ignored
    assert not check_source(
        "try:\n x()\nexcept OSError:\n pass\n", "f.py")
    # broad but handled: fine
    assert not check_source(
        "try:\n x()\nexcept Exception as e:\n log(e)\n", "f.py")
    # explicit waiver with a reason of record
    assert not check_source(
        "try:\n x()\n"
        "except Exception:  # lint: allow-silent-except — shutdown\n"
        " pass\n", "f.py")


def test_checker_reports_unparseable_files():
    (v,) = check_source("def broken(:\n", "oops.py")
    assert "unparseable" in v
