"""Telemetry plane: registry semantics, exposition, pipeline coverage,
and regression tests for the three round-6 bugfixes (checkpoint swallow,
stale formatter specs, TraceStore torn read).

Unit tests use private Registry() instances; end-to-end assertions read
DELTAS of the process-wide default registry (resetting it would orphan the
module-level children instrumented code holds)."""

from __future__ import annotations

import dataclasses
import threading
import time
import urllib.request

import numpy as np
import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu import telemetry
from inspektor_gadget_tpu.columns import Columns, TextFormatter, col
from inspektor_gadget_tpu.gadgets import GadgetContext, get
from inspektor_gadget_tpu.params import Collection
from inspektor_gadget_tpu.runtime.local import LocalRuntime
from inspektor_gadget_tpu.telemetry import MetricsServer, Registry


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

def test_counter_semantics():
    r = Registry()
    c = r.counter("req_total", "requests", ("method",))
    c.labels(method="GET").inc()
    c.labels(method="GET").inc(2)
    c.labels(method="PUT").inc(5)
    assert c.labels(method="GET").value == 3
    assert c.labels(method="PUT").value == 5
    with pytest.raises(ValueError):
        c.labels(method="GET").inc(-1)
    with pytest.raises(ValueError):
        c.labels(verb="GET")  # wrong label name


def test_gauge_semantics():
    r = Registry()
    g = r.gauge("depth")
    g.set(4)
    g.inc()
    g.dec(2)
    assert g.value == 3
    g.set_function(lambda: 42)
    assert g.value == 42
    g.set_function(lambda: 1 / 0)  # dead callback reads as 0, not a crash
    assert g.value == 0


def test_histogram_buckets_fixed_log_scale():
    r = Registry()
    h = r.histogram("lat_seconds", buckets=(0.001, 0.01, 0.1))
    for v in (0.0005, 0.005, 0.005, 0.05, 5.0):
        h.observe(v)
    assert h.count == 5
    assert h.sum == pytest.approx(5.0605)
    # cumulative buckets: (le, count<=le)
    assert h.buckets() == [(0.001, 1), (0.01, 3), (0.1, 4),
                           (float("inf"), 5)]
    # a value exactly on a bound counts into that bound's bucket
    h.observe(0.01)
    assert h.buckets()[1] == (0.01, 4)
    with pytest.raises(ValueError):
        r.histogram("bad_seconds", buckets=(0.1, 0.1))


def test_get_or_create_idempotent_and_kind_checked():
    r = Registry()
    a = r.counter("x_total", "first", ("k",))
    b = r.counter("x_total", "second registration ignored", ("k",))
    assert a is b
    with pytest.raises(ValueError):
        r.gauge("x_total")
    with pytest.raises(ValueError):
        r.counter("x_total", labels=("other",))
    h = r.histogram("h_seconds", buckets=(0.1, 1.0))
    assert r.histogram("h_seconds") is h  # None = no opinion on buckets
    assert r.histogram("h_seconds", buckets=(0.1, 1.0)) is h
    with pytest.raises(ValueError):
        r.histogram("h_seconds", buckets=(5.0,))


def test_concurrent_increments_are_exact():
    r = Registry()
    c = r.counter("n_total")
    h = r.histogram("h_seconds", buckets=(1.0,))

    def work():
        for _ in range(5000):
            c.inc()
            h.observe(0.5)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 40000
    assert h.count == 40000
    assert h.buckets() == [(1.0, 40000), (float("inf"), 40000)]


def test_prometheus_text_rendering():
    r = Registry()
    r.counter("ev_total", "events seen", ("gadget",)).labels(
        gadget='trace/exec "x"\nline').inc(7)
    r.gauge("depth").set(2.5)
    r.histogram("lat_seconds", "latency", buckets=(0.01, 1.0)).observe(0.5)
    text = r.render_prometheus()
    assert "# HELP ev_total events seen" in text
    assert "# TYPE ev_total counter" in text
    # label value escaping: backslash, quote, newline
    assert 'ev_total{gadget="trace/exec \\"x\\"\\nline"} 7' in text
    assert "# TYPE depth gauge" in text
    assert "depth 2.5" in text
    assert "# TYPE lat_seconds histogram" in text
    assert 'lat_seconds_bucket{le="0.01"} 0' in text
    assert 'lat_seconds_bucket{le="1.0"} 1' in text
    assert 'lat_seconds_bucket{le="+Inf"} 1' in text
    assert "lat_seconds_sum 0.5" in text
    assert "lat_seconds_count 1" in text


def test_snapshot_deterministic():
    r = Registry()
    # registration order must not leak into the snapshot order
    r.counter("z_total").inc(1)
    r.counter("a_total", labels=("x",)).labels(x="2").inc(2)
    r.counter("a_total", labels=("x",)).labels(x="1").inc(1)
    s1 = r.snapshot()
    s2 = r.snapshot()
    assert s1 == s2
    assert list(s1) == ['a_total{x="1"}', 'a_total{x="2"}', "z_total"]
    import json
    assert json.loads(json.dumps(s1)) == s1  # JSON-embeddable


def test_span_timer_feeds_histogram():
    r = Registry()
    h = r.histogram("span_seconds", buckets=(10.0,))
    with h.time():
        time.sleep(0.01)
    assert h.count == 1
    assert 0.005 < h.sum < 5.0


# ---------------------------------------------------------------------------
# HTTP exposition
# ---------------------------------------------------------------------------

def test_metrics_http_endpoint():
    r = Registry()
    r.counter("served_total").inc(3)
    srv = MetricsServer("127.0.0.1:0", registry=r).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        body = urllib.request.urlopen(f"{base}/metrics", timeout=5).read()
        assert b"served_total 3" in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/nope", timeout=5)
    finally:
        srv.stop()


def test_healthz_endpoint_json():
    """ISSUE 18 satellite: /healthz answers a JSON liveness doc — 200,
    status ok, a monotonic uptime, and a scrape counter that tracks
    /metrics GETs (so a probe can tell 'up but never scraped' from
    'up and scraped')."""
    import json as _json

    srv = MetricsServer("127.0.0.1:0", registry=Registry()).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        resp = urllib.request.urlopen(f"{base}/healthz", timeout=5)
        assert resp.status == 200
        assert resp.headers["Content-Type"] == "application/json"
        doc = _json.loads(resp.read())
        assert doc["status"] == "ok"
        assert doc["uptime"] >= 0.0
        assert doc["scrapes"] == 0         # nothing scraped yet
        urllib.request.urlopen(f"{base}/metrics", timeout=5).read()
        urllib.request.urlopen(f"{base}/metrics?x=1", timeout=5).read()
        doc2 = _json.loads(urllib.request.urlopen(
            f"{base}/healthz?probe=1", timeout=5).read())
        assert doc2["scrapes"] == 2
        assert doc2["uptime"] >= doc["uptime"]
    finally:
        srv.stop()


def test_parse_addr():
    from inspektor_gadget_tpu.telemetry import parse_addr
    assert parse_addr(":9100") == ("0.0.0.0", 9100)
    assert parse_addr("127.0.0.1:80") == ("127.0.0.1", 80)
    with pytest.raises(ValueError):
        parse_addr("nope")


# ---------------------------------------------------------------------------
# end-to-end: a synthetic gadget run leaves non-zero pipeline counters
# ---------------------------------------------------------------------------

def _sample(snap: dict, key: str) -> float:
    return snap.get(key, 0.0)


def test_gadget_run_populates_pipeline_counters():
    before = telemetry.snapshot()
    desc = get("trace", "exec")
    params = desc.params().to_params()
    params.set("source", "pysynthetic")
    params.set("rate", "200000")
    op_params = Collection()
    from inspektor_gadget_tpu.operators.operators import get as get_op
    sp = get_op("tpusketch").instance_params().to_params()
    sp.set("enable", "true")
    sp.set("log2-width", "8")
    sp.set("hll-p", "6")
    sp.set("entropy-log2-width", "6")
    sp.set("topk", "8")
    sp.set("harvest-interval", "200ms")
    op_params["operator.tpusketch."] = sp
    shown = []
    ctx = GadgetContext(desc, gadget_params=params, operator_params=op_params,
                        timeout=0.6)
    result = LocalRuntime().run_gadget(ctx, on_event=shown.append)
    assert not result.errors()
    assert shown
    after = telemetry.snapshot()

    def delta(key):
        return _sample(after, key) - _sample(before, key)

    g = 'gadget="trace/exec"'
    # source plane
    assert delta(f"ig_source_events_total{{{g}}}") > 0
    assert delta(f"ig_source_batches_total{{{g}}}") > 0
    assert delta(f"ig_display_rows_total{{{g}}}") > 0
    # operator chain
    assert delta(f"ig_gadget_events_total{{{g}}}") > 0
    assert delta('ig_operator_enrich_seconds_count{operator="tpusketch"}') > 0
    # tpusketch device plane
    assert delta(f"ig_tpusketch_events_total{{{g}}}") > 0
    assert delta(f"ig_tpusketch_steps_total{{{g}}}") > 0
    assert delta(f"ig_tpusketch_update_seconds_count{{{g}}}") > 0
    assert delta(f"ig_tpusketch_harvests_total{{{g}}}") > 0


def test_top_metrics_gadget_renders_registry():
    telemetry.counter("ig_test_rows_total").inc(5)
    desc = get("top", "metrics")
    ctx = GadgetContext(desc)
    gadget = desc.new_instance(ctx)
    gadget.setup(ctx)
    telemetry.counter("ig_test_rows_total").inc(7)
    rows = gadget.collect(ctx)
    by_name = {(r.metric, r.labels): r for r in rows}
    row = by_name[("ig_test_rows_total", "")]
    assert row.value == 12
    assert row.kind == "counter"
    assert row.rate > 0  # the 7 incremented since setup()
    # histogram buckets are elided; _count/_sum remain
    assert not any(r.metric.endswith("_bucket") for r in rows)
    # rows render through the ordinary column system
    cols = desc.columns()
    formatter = TextFormatter(cols)
    line = formatter.format_event(row)
    assert "ig_test_rows_total" in line


# ---------------------------------------------------------------------------
# bugfix regressions
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _Ev:
    comm: str = col("", width=16)
    pid: int = col(0, width=7, align="right", dtype=np.int32)
    secret: int = col(0, hide=True, dtype=np.int32)


def test_formatter_specs_follow_adjust_widths():
    """Regression: adjust_widths after the first row used to leave stale
    compiled specs — rows kept old widths while the header shrank."""
    cols = Columns(_Ev)
    f = TextFormatter(cols)
    ev = _Ev(comm="a-rather-long-comm", pid=42)
    f.format_event(ev)  # compiles the fast specs at full width
    f.adjust_widths(14)
    fresh = TextFormatter(Columns(_Ev), max_width=14)
    assert f.header() == fresh.header()
    assert f.format_event(ev) == fresh.format_event(ev)


def test_formatter_specs_follow_visibility_changes():
    """Regression: set_visible after the first row used to keep rendering
    the old column set (and KeyError on newly-shown hidden columns)."""
    cols = Columns(_Ev)
    f = TextFormatter(cols)
    ev = _Ev(comm="bash", pid=7, secret=99)
    assert "99" not in f.format_event(ev)
    cols.set_visible(["pid", "secret"])
    row = f.format_event(ev)
    assert "bash" not in row
    assert "99" in row
    assert f.header().split() == ["PID", "SECRET"]


def test_trace_store_readers_never_see_torn_state():
    """Regression: apply() used to mutate the stored resource in place, so
    a concurrent get() could observe the NEW spec with the OLD status."""
    from inspektor_gadget_tpu.gadgets.trace_resource import TraceStore
    store = TraceStore(node_name="n1")
    store.apply({"metadata": {"name": "t1"},
                 "spec": {"gadget": "g/old"}})

    def slow_reconcile(trace):
        time.sleep(0.15)  # window in which readers sample
        trace.status.state = "Reconciled"
        return trace

    store.reconciler.reconcile = slow_reconcile
    t = threading.Thread(target=store.apply, args=(
        {"metadata": {"name": "t1"}, "spec": {"gadget": "g/new"}},))
    t.start()
    torn = []
    while t.is_alive():
        doc = store.get("t1")
        if (doc["spec"]["gadget"] == "g/new"
                and doc["status"]["state"] != "Reconciled"):
            torn.append(doc)
        time.sleep(0.002)
    t.join()
    assert not torn, f"reader saw new spec with stale status: {torn[0]}"
    assert store.get("t1")["status"]["state"] == "Reconciled"


@pytest.fixture()
def sketch_instance(tmp_path):
    from inspektor_gadget_tpu.operators import tpusketch
    from inspektor_gadget_tpu.operators.operators import get as get_op
    tpusketch.set_checkpoint_dir(tmp_path)
    desc = get("trace", "exec")
    ctx = GadgetContext(desc)
    op = get_op("tpusketch")
    p = op.instance_params().to_params()
    p.set("enable", "true")
    p.set("log2-width", "8")
    p.set("hll-p", "6")
    p.set("entropy-log2-width", "6")
    p.set("topk", "8")
    inst = op.instantiate(ctx, None, p)
    yield tmp_path, inst
    from inspektor_gadget_tpu.operators.tpusketch import _live, _live_mu
    with _live_mu:
        _live.pop(ctx.run_id, None)
    tpusketch.set_checkpoint_dir(None)


def test_checkpoint_failure_logged_counted_retried(
        sketch_instance, monkeypatch, caplog):
    """Regression: checkpoint failures used to be `except: pass` — now
    they are logged, bump checkpoint_failures_total, and retry once."""
    import logging

    from inspektor_gadget_tpu.operators import tpusketch
    from inspektor_gadget_tpu.utils import checkpoint as ckpt_mod
    _tmp, inst = sketch_instance
    fail_before = tpusketch._tm_ckpt_fail.value
    ok_before = tpusketch._tm_ckpt_ok.value
    calls = []

    def boom(*a, **kw):
        calls.append(1)
        raise OSError("disk on fire")

    monkeypatch.setattr(ckpt_mod, "save_pytree", boom)
    with caplog.at_level(logging.WARNING, logger="ig-tpu.tpusketch"):
        assert tpusketch.checkpoint_all() == 0
    assert len(calls) == 2  # immediate retry happened
    assert tpusketch._tm_ckpt_fail.value == fail_before + 2
    assert any("checkpoint of trace-exec failed" in r.message
               for r in caplog.records)

    monkeypatch.undo()
    assert tpusketch.checkpoint_all() == 1
    assert tpusketch._tm_ckpt_ok.value == ok_before + 1
    assert (_tmp / "trace-exec.npz").exists()


def test_checkpoint_snapshots_bundle_under_update_pressure(sketch_instance):
    """The checkpointer must survive concurrent enrich_batch updates:
    bundle_update_jit donates its input, so an unlocked reader would hit
    deleted device buffers."""
    from inspektor_gadget_tpu.operators import tpusketch
    from inspektor_gadget_tpu.sources.synthetic import PySyntheticSource
    _tmp, inst = sketch_instance
    src = PySyntheticSource(seed=3, batch_size=512)
    stop = threading.Event()
    errors = []

    def pump():
        try:
            while not stop.is_set():
                inst.enrich_batch(src.generate(512))
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    t = threading.Thread(target=pump)
    t.start()
    try:
        deadline = time.monotonic() + 1.5
        saves = 0
        while time.monotonic() < deadline:
            inst.checkpoint()
            saves += 1
    finally:
        stop.set()
        t.join(timeout=5.0)
    assert not errors
    assert saves > 0
    assert (_tmp / "trace-exec.npz").exists()


# ---------------------------------------------------------------------------
# pinned-pool / H2D staging telemetry + digest donation pin (ISSUE 10)
# ---------------------------------------------------------------------------

def test_ingest_pool_counters_and_inflight_gauge(sketch_instance):
    """The staging plane must account itself: fresh blocks count as pool
    misses, steady-state recycling as hits, and the in-flight H2D gauge
    returns to its baseline once the stager drains — all visible in the
    Prometheus exposition."""
    from inspektor_gadget_tpu.sources import staging
    from inspektor_gadget_tpu.sources.synthetic import PySyntheticSource
    from inspektor_gadget_tpu.telemetry import render_prometheus

    _tmp, inst = sketch_instance
    # the single-chip path stages through lane "0" (ISSUE 14 relabel:
    # the families grew a `lane` label; .total sums across lanes)
    hits0 = staging._tm_pool_hits.total
    miss0 = staging._tm_pool_misses.total
    lane0_hits0 = staging._tm_pool_hits.labels(lane="0").value
    inflight0 = staging._tm_inflight.total

    src = PySyntheticSource(seed=5, batch_size=512)
    for _ in range(8):
        inst.enrich_batch(src.generate(512))
    assert staging._tm_pool_misses.total > miss0, \
        "first staging blocks must be accounted as pool misses"
    assert staging._tm_pool_hits.total > hits0, \
        "steady-state ingest must recycle pinned blocks (pool hits)"
    assert staging._tm_pool_hits.labels(lane="0").value > lane0_hits0, \
        "the unsharded path must stay on lane 0 of the labeled series"
    assert inst._stager is not None
    inst._stager.drain()
    assert staging._tm_inflight.total == inflight0, \
        "drained stager must return the in-flight gauge to baseline"

    text = render_prometheus()
    assert "ig_ingest_pool_hits_total" in text
    assert "ig_ingest_pool_misses_total" in text
    assert "ig_ingest_h2d_inflight" in text


def test_sharded_lane_pool_telemetry_and_gauge_drain():
    """ISSUE 14 satellite: under shard-ingest every device lane accounts
    its OWN pinned pool — lane-labeled miss-then-hit progressions per
    lane, a lane-labeled in-flight gauge that returns to baseline when
    the instance tears down — and the lane label reaches the Prometheus
    exposition."""
    from inspektor_gadget_tpu.operators.operators import get as get_op
    from inspektor_gadget_tpu.sources import staging
    from inspektor_gadget_tpu.sources.synthetic import PySyntheticSource
    from inspektor_gadget_tpu.telemetry import render_prometheus

    desc = get("trace", "exec")
    ctx = GadgetContext(desc)
    op = get_op("tpusketch")
    p = op.instance_params().to_params()
    p.set("enable", "true")
    p.set("log2-width", "8")
    p.set("hll-p", "6")
    p.set("entropy-log2-width", "6")
    p.set("topk", "8")
    p.set("shard-ingest", "true")
    p.set("chips", "2")
    inst = op.instantiate(ctx, None, p)
    assert inst._shard_on

    base = {k: (staging._tm_pool_hits.labels(lane=str(k)).value,
                staging._tm_pool_misses.labels(lane=str(k)).value)
            for k in (0, 1)}
    inflight0 = staging._tm_inflight.total

    src = PySyntheticSource(seed=9, batch_size=512)
    for _ in range(8):
        inst.enrich_batch(src.generate(512))
    for k in (0, 1):
        h0, m0 = base[k]
        assert staging._tm_pool_misses.labels(lane=str(k)).value > m0, \
            f"lane {k}: first blocks must be accounted as misses"
        assert staging._tm_pool_hits.labels(lane=str(k)).value > h0, \
            f"lane {k}: steady state must recycle that lane's blocks"
    inst.harvest()
    inst.post_gadget_run()
    assert staging._tm_inflight.total == inflight0, \
        "teardown must return every lane's in-flight gauge to baseline"

    text = render_prometheus()
    assert 'ig_ingest_pool_hits_total{lane="1"}' in text
    assert 'ig_ingest_h2d_inflight{lane="1"}' in text


def test_ingest_folded_roundtrip_recycles_blocks(sketch_instance):
    """The zero-copy SoA entry point: FoldedBatch lanes from
    folded_block() must absorb into the bundle, recycle through the
    instance's pinned pool (same shape, so put() keeps them), and
    harvest the exact event total."""
    from inspektor_gadget_tpu.sources.batch import FoldedBatch

    _tmp, inst = sketch_instance
    total = 0
    for i in range(4):
        block = inst.folded_block()
        n = 300 + i
        block[0][:n] = np.arange(1, n + 1, dtype=np.uint32)
        block[1][:n] = 1
        block[2][:n] = 101
        inst.ingest_folded(FoldedBatch(lanes=block, count=n))
        total += n
    assert inst._stager is not None
    inst._stager.drain()
    assert inst._pool.free_blocks() > 0, \
        "folded blocks must recycle through the instance pool"
    s = inst.harvest()
    assert s.events == total


def test_harvest_digest_survives_update_pressure(sketch_instance):
    """Donation/aliasing pin (ISSUE 10 satellite, next to the PR-1
    checkpoint-race test above): bundle_digest_jit must never donate its
    input — harvest dispatches it on the LIVE bundle while the
    double-buffered ingest path keeps issuing donating updates, so a
    donating digest would read deleted buffers exactly like the old
    checkpoint race did."""
    from inspektor_gadget_tpu.sources.synthetic import PySyntheticSource

    _tmp, inst = sketch_instance
    src = PySyntheticSource(seed=7, batch_size=512)
    stop = threading.Event()
    errors = []

    def pump():
        try:
            while not stop.is_set():
                inst.enrich_batch(src.generate(512))
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    t = threading.Thread(target=pump)
    t.start()
    try:
        deadline = time.monotonic() + 1.5
        harvests = 0
        while time.monotonic() < deadline:
            s = inst.harvest()
            assert s.events >= 0
            harvests += 1
    finally:
        stop.set()
        t.join(timeout=5.0)
    assert not errors, errors
    assert harvests > 0


# ---------------------------------------------------------------------------
# sketch-history plane telemetry (ISSUE 6 satellite)
# ---------------------------------------------------------------------------

def test_history_counters_and_active_store_gauge(tmp_path):
    """Sealing windows must account into the history plane's OWN
    counters (ig_history_*), visible in the Prometheus exposition, with
    the active-store gauge tracking open writers — and never launder
    through the capture plane's ig_capture_* family."""
    import numpy as np

    from inspektor_gadget_tpu.history import HISTORY, SealedWindow
    from inspektor_gadget_tpu.history.store import HISTORY_METRICS
    from inspektor_gadget_tpu.telemetry import render_prometheus

    windows_before = HISTORY_METRICS.records.labels(type="9").value
    bytes_before = HISTORY_METRICS.bytes.value
    gc_before = HISTORY_METRICS.gc.value
    active_before = HISTORY_METRICS.active.value

    rng = np.random.default_rng(9)

    def win(i):
        # random tables defeat zlib so every frame exceeds the 4 KiB
        # segment floor and rotation/GC fire deterministically
        return SealedWindow(
            gadget="trace/telemetry-probe", node="n0", run_id="r",
            window=i, start_ts=float(i), end_ts=float(i + 1),
            events=10, drops=0,
            cms=rng.integers(0, 2**30, (4, 512)).astype(np.int32),
            hll=np.zeros(16, np.int32),
            ent=np.zeros(8, np.float32),
            topk_keys=np.array([1], np.uint32),
            topk_counts=np.array([5], np.int64), slices={})

    # tight rotation + retention so GC fires deterministically
    w = HISTORY.writer_for("trace/telemetry-probe",
                           base_dir=str(tmp_path),
                           max_segment_bytes=1 << 12, max_segment_age=0,
                           retention_segments=1)
    try:
        for i in range(1, 6):
            HISTORY.append_window(win(i), writer=w)
    finally:
        HISTORY.close_all()

    assert HISTORY_METRICS.records.labels(type="9").value == \
        windows_before + 5
    assert HISTORY_METRICS.bytes.value > bytes_before
    assert HISTORY_METRICS.gc.value > gc_before, \
        "retention GC of sealed history segments was not counted"
    assert HISTORY_METRICS.active.value == active_before  # open+close net 0

    text = render_prometheus()
    assert "ig_history_windows_total" in text
    assert "ig_history_bytes_total" in text
    assert "ig_history_gc_total" in text
    assert "ig_history_active_stores" in text


# ---------------------------------------------------------------------------
# shared-run gauge discipline (ISSUE 12 satellite): attach/detach/evict/
# keepalive-expiry churn must return every per-run gauge EXACTLY to
# baseline — a drifting gauge on a long-lived agent is a lying dashboard
# ---------------------------------------------------------------------------

def _default_metric(name: str, **labels) -> float:
    total = 0.0
    for key, v in telemetry.REGISTRY.snapshot().items():
        if key != name and not key.startswith(name + "{"):
            continue
        if all(f'{k}="{lv}"' in key for k, lv in labels.items()):
            total += v
    return total


def test_shared_run_gauges_return_to_baseline_across_churn():
    """SharedRun-level churn: 3 subscribers attach, one overloads its
    8-deep queue (drops counted per (run, policy, class)) and is evicted
    off its stall window, the rest detach/leave, a late re-attach
    cancels the keepalive, and the final keepalive expiry cancels the
    gadget — after which ig_agent_detached_runs and
    ig_agent_run_subscribers sit exactly where they started."""
    from inspektor_gadget_tpu.agent import wire
    from inspektor_gadget_tpu.agent.service import SharedRun

    detached_before = _default_metric("ig_agent_detached_runs")
    evictions_before = _default_metric(
        "ig_agent_subscriber_evictions_total")

    class _Ctx:
        def __init__(self):
            self.cancelled = threading.Event()

        def cancel(self):
            self.cancelled.set()

    run = SharedRun("gauge-run", "trace/gauge", shared=True,
                    keepalive=0.3, max_subscribers=8, sub_budget=64,
                    node="t")
    ctx = _Ctx()
    run.ctx = ctx
    subs = []
    for i in range(3):
        sub = run.admit({"queue": 8,
                         "priority": "low" if i == 2 else "normal",
                         "evict_after": 0.2 if i == 2 else 60.0})
        assert not isinstance(sub, dict), sub
        q, gen, _ack = run.attach_subscriber(sub, 0)
        subs.append((sub, q, gen))
    assert _default_metric("ig_agent_run_subscribers",
                          run="gauge-run") == 3.0

    # overload: nobody drains, the low-priority 8-deep queue overflows;
    # past its 0.2s stall window the next push evicts it
    for _ in range(20):
        run.push(wire.EV_PAYLOAD_JSON, {"node": "t"}, b"x")
    victim = subs[2][0]
    assert victim.drops > 0
    assert _default_metric("ig_agent_subscriber_drops_total",
                          run="gauge-run", policy="drop-oldest",
                          **{"class": "low"}) >= float(victim.drops)
    time.sleep(0.3)
    run.push(wire.EV_PAYLOAD_JSON, {"node": "t"}, b"x")
    assert victim.evicted and victim.left
    assert _default_metric("ig_agent_subscriber_evictions_total") == \
        evictions_before + 1.0
    assert _default_metric("ig_agent_run_subscribers",
                          run="gauge-run") == 2.0

    # transport-detach one (peers still attached: nothing run-level),
    # then the last leave arms the keepalive
    run.detach(subs[0][0], subs[0][2])
    assert _default_metric("ig_agent_detached_runs") == detached_before
    run.leave(subs[0][0])
    run.leave(subs[1][0])
    assert _default_metric("ig_agent_detached_runs") == \
        detached_before + 1.0
    assert run.keepalive_remaining() > 0.0

    # a re-attach inside the window cancels the countdown and clears the
    # detached gauge; its leave re-arms
    late = run.admit({"queue": 8})
    assert not isinstance(late, dict)
    run.attach_subscriber(late, 0)
    assert _default_metric("ig_agent_detached_runs") == detached_before
    assert not ctx.cancelled.is_set()
    run.leave(late)

    # keepalive expiry cancels the gadget; the run thread would then
    # finish() — after which every gauge is back at baseline
    assert ctx.cancelled.wait(3.0), "keepalive expiry never cancelled"
    run.finish()
    assert _default_metric("ig_agent_detached_runs") == detached_before
    assert _default_metric("ig_agent_run_subscribers",
                          run="gauge-run") == 0.0

    text = telemetry.render_prometheus()
    assert "ig_agent_run_subscribers" in text
    assert "ig_agent_subscriber_drops_total" in text
    assert "ig_agent_subscriber_evictions_total" in text
    assert "ig_agent_attach_refused_total" in text or True  # labeled lazily


def test_agent_active_runs_gauge_baseline_across_shared_lifecycle():
    """Through the real agent: a shared run created, subscribed,
    detached, and keepalive-expired must return ig_agent_active_runs
    and ig_agent_detached_runs exactly to baseline (the run registry
    and the gauges retire together)."""
    import tempfile

    from inspektor_gadget_tpu.agent.client import AgentClient
    from inspektor_gadget_tpu.agent.service import serve

    active_before = _default_metric("ig_agent_active_runs")
    detached_before = _default_metric("ig_agent_detached_runs")
    tmp = tempfile.mkdtemp()
    addr = f"unix://{tmp}/gauge.sock"
    server, agent = serve(addr, node_name="gauge-node")
    try:
        stop = threading.Event()
        holder: dict = {}
        got = threading.Event()

        def owner():
            c = AgentClient(addr, "gauge-node")
            holder["out"] = c.run_gadget(
                "trace", "exec",
                {"gadget.source": "pysynthetic", "gadget.rate": "900"},
                timeout=0.0, run_id="gauge-life", share=True,
                keepalive=0.4,
                on_message=lambda *_: got.set(), stop_event=stop)
            c.close()

        t = threading.Thread(target=owner, daemon=True)
        t.start()
        assert got.wait(30.0), "no stream traffic"
        assert _default_metric("ig_agent_active_runs") == \
            active_before + 1.0
        stop.set()
        t.join(timeout=20.0)
        assert holder["out"]["error"] is None
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            if _default_metric("ig_agent_active_runs") == active_before \
                    and _default_metric("ig_agent_detached_runs") == \
                    detached_before:
                break
            time.sleep(0.1)
        assert _default_metric("ig_agent_active_runs") == active_before
        assert _default_metric("ig_agent_detached_runs") == detached_before
    finally:
        server.stop(grace=0.5)


def test_quantile_plane_counters_follow_value_lane():
    """ISSUE 16 satellite: the DDSketch plane's absorption accounting —
    ig_sketch_quantile_events_total counts every event the value lane
    absorbed, ig_sketch_quantile_zero_total the no-magnitude subset,
    and a plane-OFF instance moves neither."""
    from inspektor_gadget_tpu.operators import tpusketch
    from inspektor_gadget_tpu.operators.operators import get as get_op
    from inspektor_gadget_tpu.sources.batch import EventBatch

    def make(quantiles: str):
        desc = get("trace", "exec")
        ctx = GadgetContext(desc)
        p = get_op("tpusketch").instance_params().to_params()
        p.set("enable", "true")
        p.set("log2-width", "8")
        p.set("hll-p", "6")
        p.set("entropy-log2-width", "6")
        p.set("topk", "8")
        p.set("harvest-interval", "1h")
        p.set("quantiles", quantiles)
        return get_op("tpusketch").instantiate(ctx, None, p)

    def batch(n, zeros):
        b = EventBatch.alloc(n, with_comm=False)
        b.cols["key_hash"][:] = np.arange(1, n + 1, dtype=np.uint64)
        b.cols["aux1"][:] = 1000
        b.cols["aux1"][:zeros] = 0
        b.count = n
        return b

    def counter(name) -> float:
        return sum(v for k, v in telemetry.snapshot().items()
                   if k.startswith(name))

    ev0 = counter("ig_sketch_quantile_events_total")
    z0 = counter("ig_sketch_quantile_zero_total")
    live_before = set(tpusketch._live)
    on, off = make("true"), make("false")
    try:
        on.enrich_batch(batch(64, zeros=5))
        assert counter("ig_sketch_quantile_events_total") == ev0 + 64
        assert counter("ig_sketch_quantile_zero_total") == z0 + 5
        # plane off: the counters must not move — there is no lane
        off.enrich_batch(batch(64, zeros=5))
        assert counter("ig_sketch_quantile_events_total") == ev0 + 64
        assert counter("ig_sketch_quantile_zero_total") == z0 + 5
        # counter discipline: both render in the Prometheus exposition
        text = telemetry.render_prometheus()
        assert "ig_sketch_quantile_events_total" in text
        assert "ig_sketch_quantile_zero_total" in text
    finally:
        with tpusketch._live_mu:
            fresh = [r for r in list(tpusketch._live) if r not in live_before]
            insts = [tpusketch._live.pop(r) for r in fresh]
        for inst in insts:
            if getattr(inst, "_stager", None) is not None:
                inst._stager.drain()
            inst._stats.unregister()
            inst._pstats.unregister()


def test_accuracy_gauges_return_to_baseline_across_churn():
    """Accuracy audit plane (ISSUE 19) gauge discipline: a run that set
    observed-error gauges and the drift ratio must return every
    `ig_sketch_accuracy_*` gauge exactly to baseline on unregister
    (the counter stays monotonic — counters never rewind)."""
    import numpy as np

    from inspektor_gadget_tpu.ops.accuracy import (
        AccuracyStats, ShadowSample, accuracy_block, live_stats)

    obs0 = _default_metric("ig_sketch_accuracy_observed_err")
    ratio0 = _default_metric("ig_sketch_accuracy_ratio")
    fed0 = _default_metric("ig_sketch_audit_samples_total")
    keys = (np.arange(1, 401, dtype=np.uint32) % 40) + 1
    sh = ShadowSample(64)
    sh.update(keys)
    uk, uc = np.unique(keys, return_counts=True)
    a = AccuracyStats("run-acc-tm-1", "trace/exec")
    a.register()
    try:
        a.note_fed(keys.size)
        a.observe_block(accuracy_block(
            events=float(keys.size), depth=3, width=1024, hll_p=8,
            ent_log2_width=6, distinct=float(uk.size) + 1.0,
            entropy_bits=4.0, hh_keys=uk[:8],
            hh_counts=uc[:8].astype(np.int64) + 2, shadow=sh))
        assert _default_metric("ig_sketch_audit_samples_total") == fed0 + 400
        # audited stats set their observed-error gauges + the ratio
        assert _default_metric("ig_sketch_accuracy_observed_err",
                               stat="heavy_hitters") > 0.0
        assert _default_metric("ig_sketch_accuracy_observed_err",
                               stat="distinct") > 0.0
        assert _default_metric("ig_sketch_accuracy_ratio") > 0.0
        assert any(s.run_id == "run-acc-tm-1" for s in live_stats())
        text = telemetry.render_prometheus()
        assert "ig_sketch_accuracy_observed_err" in text
        assert "ig_sketch_accuracy_ratio" in text
        assert "ig_sketch_audit_samples_total" in text
    finally:
        a.unregister()
    # every gauge the run touched is exactly back at baseline
    assert _default_metric("ig_sketch_accuracy_observed_err") == obs0
    assert _default_metric("ig_sketch_accuracy_ratio") == ratio0
    assert not any(s.run_id == "run-acc-tm-1" for s in live_stats())
    # the feed counter is monotonic: unregister must not rewind it
    assert _default_metric("ig_sketch_audit_samples_total") == fed0 + 400


def test_fleet_merge_metrics_lifecycle(monkeypatch):
    """Fleet aggregation tier (ISSUE 20) metric discipline: the depth
    gauge holds the tree's height exactly while a fold is in flight and
    sits back at 0 after (crash paths included — it resets in a
    finally), subtree folds count per aggregator with a result label,
    and the fallback counter trips once per subtree re-folded flat."""
    from inspektor_gadget_tpu.fleet import aggregator as agg_mod
    from inspektor_gadget_tpu.fleet import fold_tree
    from inspektor_gadget_tpu.fleet.sim import GADGET, SimFleet

    assert _default_metric("ig_fleet_merge_depth") == 0.0
    ok0 = _default_metric("ig_fleet_subtree_folds_total", result="ok")
    failed0 = _default_metric("ig_fleet_subtree_folds_total",
                              result="failed")
    fb0 = _default_metric("ig_fleet_fallback_total")

    fleet = SimFleet(8, n_windows=1)
    topo = fleet.topology("auto:4")
    in_flight: list[float] = []

    def spying_fetch(node):
        in_flight.append(_default_metric("ig_fleet_merge_depth"))
        return fleet.fetch_leaf(node)

    tf = fold_tree(topo, spying_fetch, gadget=GADGET)
    assert tf.window is not None
    # set for the WHOLE fold (every leaf pull saw it), 0 again after
    assert in_flight and all(v == float(topo.depth())
                             for v in in_flight)
    assert _default_metric("ig_fleet_merge_depth") == 0.0
    assert _default_metric("ig_fleet_subtree_folds_total",
                           result="ok") == ok0 + len(topo.aggregators())
    assert _default_metric("ig_fleet_subtree_folds_total",
                           result="failed") == failed0
    assert _default_metric("ig_fleet_fallback_total") == fb0

    # client-driven aggregator crash: failed + fallback each tick once,
    # the refold still answers, the gauge still lands back at 0
    real = agg_mod.merged_to_sealed
    crashed: list[str] = []

    def crash_once(merged, *, gadget, node):
        if node == "agg1-000" and not crashed:
            crashed.append(node)
            raise RuntimeError("injected seal crash")
        return real(merged, gadget=gadget, node=node)

    monkeypatch.setattr(agg_mod, "merged_to_sealed", crash_once)
    tf2 = fold_tree(topo, fleet.fetch_leaf, gadget=GADGET)
    monkeypatch.setattr(agg_mod, "merged_to_sealed", real)
    assert tf2.fallback == ["agg1-000"] and tf2.window is not None
    assert _default_metric("ig_fleet_subtree_folds_total",
                           result="failed") == failed0 + 1
    assert _default_metric("ig_fleet_fallback_total") == fb0 + 1
    assert _default_metric("ig_fleet_merge_depth") == 0.0

    # unreachable deployed aggregator: fallback ticks, failed does not
    # (nothing crashed HERE — the remote tier just never answered)
    fetch_subtree = fleet.make_fetch_subtree(fail={"fleet"})
    tf3 = fold_tree(topo, fleet.fetch_leaf,
                    fetch_subtree=fetch_subtree, gadget=GADGET)
    assert tf3.fallback == ["fleet"] and tf3.window is not None
    assert _default_metric("ig_fleet_fallback_total") == fb0 + 2
    assert _default_metric("ig_fleet_subtree_folds_total",
                           result="failed") == failed0 + 1
    assert _default_metric("ig_fleet_merge_depth") == 0.0

    text = telemetry.render_prometheus()
    assert "ig_fleet_merge_depth" in text
    assert "ig_fleet_subtree_folds_total" in text
    assert "ig_fleet_fallback_total" in text
