"""Environment doctor + fail-loudly + container auto-attach tests.

Models the entrypoint's capability detection
(reference gadget-container/entrypoint.sh:21-120) and the per-container
attach model (localmanager.go:230-260): probes must describe this host,
no-target ptrace gadgets must error rather than fabricate, and a container
filter must auto-attach the syscall stream.
"""

import os
import subprocess
import time

import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.doctor import (
    gadget_report, probe_windows, render_report,
)
from inspektor_gadget_tpu.gadgets import GadgetContext, get
from inspektor_gadget_tpu.runtime import LocalRuntime
from inspektor_gadget_tpu.sources import native_available

needs_native = pytest.mark.skipif(not native_available(), reason="no native lib")
needs_root = pytest.mark.skipif(os.geteuid() != 0, reason="needs root")


def test_probe_windows_names_and_shape():
    windows = probe_windows()
    expected = {"native_lib", "native_toolchain", "fanotify", "perf",
                "kmsg", "ptrace", "sock_diag", "netlink_proc", "af_packet",
                "mountinfo", "procfs", "blktrace", "tcpinfo", "audit",
                "captrace", "fstrace", "sockstate", "sigtrace",
                "container_runtime", "capture_dir", "history_dir",
                "history_tiers", "standing_queries", "fleet_health",
                "shared_runs", "device_topology", "pipeline_health",
                "accuracy", "fleet_topology"}
    assert set(windows) == expected
    for w in windows.values():
        assert isinstance(w.ok, bool) and w.detail


def test_device_topology_row_agrees_with_probe():
    """The device-plane topology row (ISSUE 14 satellite): the reported
    device count, mesh shape, and shard-ingest eligibility must agree
    with what jax actually exposes — the row is what an operator reads
    before flipping `shard-ingest` on, so a row that disagrees with the
    probe is worse than no row."""
    import jax

    from inspektor_gadget_tpu.doctor import _probe_device_topology

    # the row only reads an ALREADY-initialized backend (it must never
    # be the thing that hangs on TPU acquisition) — initialize here
    jax.local_device_count()
    w = _probe_device_topology()
    assert w.ok
    n = jax.local_device_count()
    plat = jax.local_devices()[0].platform
    assert f"{n} local {plat} device(s)" in w.detail
    assert f"(node={n})" in w.detail
    if n >= 2:
        assert "shard-ingest eligible" in w.detail
    else:
        assert "needs >= 2 devices" in w.detail


def test_fleet_topology_row_reports_tree_shape(monkeypatch, tmp_path):
    """The fleet-tier doctor row (ISSUE 20): with no deployed fleet the
    row passes and says the tier is a query-time choice; with a deploy
    state it reports the auto-balanced tree's shape and wire cost."""
    import json

    from inspektor_gadget_tpu.cli import deploy
    from inspektor_gadget_tpu.doctor import _probe_fleet_topology

    state = tmp_path / "fleet.json"
    monkeypatch.setattr(deploy, "STATE_FILE", str(state))
    w = _probe_fleet_topology()
    assert w.ok and "query-time choice" in w.detail

    state.write_text(json.dumps(
        {"targets": {f"n{i}": f"unix:///tmp/{i}.sock"
                     for i in range(6)}}))
    w = _probe_fleet_topology()
    assert w.ok
    assert "6 agent(s)" in w.detail
    assert "fan-in 4" in w.detail
    assert "frame(s)/query" in w.detail


def test_history_dir_row_reports_writability_usage_and_free(monkeypatch,
                                                           tmp_path):
    """The history plane's doctor row: a writable store area reports its
    usage and free space; an unwritable one degrades the row, not the
    probe run (ISSUE 6 satellite)."""
    monkeypatch.setenv("IG_HISTORY_DIR", str(tmp_path / "hist"))
    w = probe_windows()["history_dir"]
    assert w.ok
    assert "writable" in w.detail and "segment(s)" in w.detail
    assert "MiB" in w.detail
    if os.geteuid() != 0:
        ro = tmp_path / "ro"
        ro.mkdir()
        os.chmod(ro, 0o500)
        monkeypatch.setenv("IG_HISTORY_DIR", str(ro / "hist"))
        w = probe_windows()["history_dir"]
        assert not w.ok and "unwritable" in w.detail


def test_fleet_health_row_reports_local_fleet(monkeypatch):
    """The fleet-plane doctor row (ISSUE 11 satellite): no registered
    local fleet is fine (single-node mode); a registered agent nobody
    serves degrades the row with the unreachable node named."""
    import inspektor_gadget_tpu.cli.deploy as deploy
    from inspektor_gadget_tpu.doctor import _probe_fleet_health

    monkeypatch.setattr(deploy, "local_targets", lambda: {})
    w = _probe_fleet_health()
    assert w.ok and "single-node" in w.detail

    monkeypatch.setattr(deploy, "local_targets",
                        lambda: {"ghost": "127.0.0.1:1"})
    monkeypatch.setenv("IG_RPC_DEADLINE", "2.0")
    w = _probe_fleet_health()
    assert not w.ok
    assert "unreachable" in w.detail and "ghost" in w.detail


def test_shared_runs_row_reports_fleet_shared_state(monkeypatch):
    """The shared-run doctor row (ISSUE 12 satellite): no fleet is fine
    (single-node mode); an unreadable agent degrades the row — an
    overloaded node you cannot see is the outage in waiting."""
    import inspektor_gadget_tpu.cli.deploy as deploy
    from inspektor_gadget_tpu.doctor import _probe_shared_runs

    monkeypatch.setattr(deploy, "local_targets", lambda: {})
    w = _probe_shared_runs()
    assert w.ok and "single-node" in w.detail

    monkeypatch.setattr(deploy, "local_targets",
                        lambda: {"ghost": "127.0.0.1:1"})
    monkeypatch.setenv("IG_RPC_DEADLINE", "2.0")
    w = _probe_shared_runs()
    assert not w.ok
    assert "unreadable" in w.detail and "ghost" in w.detail


def test_standing_queries_row_reports_live_engines():
    """The standing-query doctor row (ISSUE 17): no registered queries
    is healthy (the plane is opt-in); with a live engine the row names
    each query's coverage and the result-cache counters."""
    from inspektor_gadget_tpu.doctor import _probe_standing_queries
    from inspektor_gadget_tpu.queries import (
        StandingQuery, StandingQueryEngine,
    )
    from inspektor_gadget_tpu.queries import engine as qengine

    assert not [r for r in qengine.live_stats()
                if r["run_id"] == "doctor-test"]
    w = _probe_standing_queries()
    if not qengine.live_engines():
        assert w.ok and "opt-in" in w.detail
    qengine.register("doctor-test", StandingQueryEngine(
        [StandingQuery(id="hot", stats=("topk",), range_s=60.0)],
        gadget="trace/exec", node="n0"))
    try:
        w = _probe_standing_queries()
        assert w.ok
        assert "hot" in w.detail and "cache" in w.detail
    finally:
        qengine.unregister("doctor-test")


def test_gadget_report_covers_every_registered_gadget():
    from inspektor_gadget_tpu.gadgets import get_all
    report = gadget_report()
    reported = {(g.category, g.name) for g in report}
    registered = {(d.category, d.name) for d in get_all()}
    assert reported == registered
    assert all(g.status in ("real", "degraded", "unavailable",
                            "synthetic-only") for g in report)


@needs_native
def test_gadget_report_reflects_live_windows():
    """On a host where the windows probe ok, the trace family maps real."""
    windows = probe_windows()
    by_name = {(g.category, g.name): g for g in gadget_report(windows)}
    if windows["fanotify"].ok:
        assert by_name[("trace", "open")].status == "real"
    if windows["mountinfo"].ok:
        assert by_name[("trace", "mount")].status == "real"
    if windows["captrace"].ok:
        assert by_name[("trace", "capabilities")].status == "real"
    elif windows["audit"].ok:  # tracepoint absent → audit denial-only
        assert by_name[("trace", "capabilities")].status == "degraded"
    # a window reported down must degrade/unavail its gadget, never "real"
    down = dict(windows)
    import dataclasses
    down["fanotify"] = dataclasses.replace(windows["fanotify"], ok=False,
                                           detail="forced down")
    g = {(x.category, x.name): x for x in gadget_report(down)}
    assert g[("trace", "open")].status == "unavailable"


def test_render_report_has_sections():
    out = render_report()
    assert "CAPTURE WINDOWS" in out and "GADGETS" in out and "SUMMARY" in out


def test_doctor_cli_command():
    from inspektor_gadget_tpu.cli.main import main
    # table output; exit code 0 when nothing is unavailable on this host
    rc = main(["doctor"])
    assert rc in (0, 1)


# ---------------------------------------------------------------------------
# fail-loudly: a no-target ptrace gadget must error, never fabricate
# ---------------------------------------------------------------------------

@needs_native
@pytest.mark.parametrize("category,name", [
    ("traceloop", "traceloop"),
])
def test_no_target_ptrace_gadget_fails_loudly(category, name):
    """traceloop's per-container ring model is inherently per-target: a
    no-target run must error, never fabricate. (capabilities, fsslower
    and audit/seccomp gained host-wide tracepoint/audit flavours and now
    run targetless — covered in test_gadgets.)"""
    desc = get(category, name)
    params = desc.params().to_params()  # source defaults to auto, no target
    ctx = GadgetContext(desc, gadget_params=params, timeout=0.5)
    events = []
    result = LocalRuntime().run_gadget(ctx, on_event=events.append)
    errs = result.errors()
    assert errs, "no-target ptrace gadget ran without erroring"
    assert "target" in str(errs).lower()
    assert not events, "fabricated events emitted despite the error"


@needs_native
def test_no_target_fsslower_without_window_fails_loudly():
    """When the host-wide raw_syscalls window is absent too, a no-target
    fsslower run errors loudly instead of fabricating."""
    from inspektor_gadget_tpu.sources.bridge import fstrace_supported
    if fstrace_supported():
        pytest.skip("fstrace window available — host-wide flavour applies")
    desc = get("trace", "fsslower")
    params = desc.params().to_params()
    ctx = GadgetContext(desc, gadget_params=params, timeout=0.5)
    events = []
    result = LocalRuntime().run_gadget(ctx, on_event=events.append)
    assert result.errors()
    assert not events


@needs_native
@pytest.mark.parametrize("category,name", [
    ("trace", "capabilities"), ("audit", "seccomp"),
])
def test_no_target_without_audit_window_fails_loudly(category, name):
    """When the host-wide audit window is absent too, the no-target run
    still errors loudly instead of fabricating."""
    from inspektor_gadget_tpu.sources.bridge import audit_supported
    if audit_supported():
        pytest.skip("audit window available — host-wide flavour applies")
    desc = get(category, name)
    params = desc.params().to_params()
    ctx = GadgetContext(desc, gadget_params=params, timeout=0.5)
    events = []
    result = LocalRuntime().run_gadget(ctx, on_event=events.append)
    assert result.errors()
    assert not events


@needs_native
def test_explicit_synthetic_still_works():
    desc = get("trace", "capabilities")
    params = desc.params().to_params()
    params.set("source", "synthetic")
    params.set("rate", "50000")
    # the threaded source ramps up over ~0.5s; give it a whole second
    ctx = GadgetContext(desc, gadget_params=params, timeout=1.0)
    events = []
    result = LocalRuntime().run_gadget(ctx, on_event=events.append)
    assert not result.errors()
    assert events


# ---------------------------------------------------------------------------
# container auto-attach: the Attacher path carries the capture
# ---------------------------------------------------------------------------

class _FakeContainer:
    def __init__(self, pid, id="c1", name="probe", mntns=0):
        self.pid = pid
        self.id = id
        self.name = name
        self.mntns = mntns


@needs_native
@needs_root
def test_ptrace_gadget_auto_attach_captures_container_activity():
    """Attach trace/capabilities to a fake container's init pid and observe
    a real CAP_CHOWN from inside it — no --command/--pid given."""
    open("/tmp/ig_attach_probe", "w").write("x")
    child = subprocess.Popen(
        ["sh", "-c",
         "sleep 0.8; chown 0:0 /tmp/ig_attach_probe; sleep 4"])
    try:
        desc = get("trace", "capabilities")
        params = desc.params().to_params()
        ctx = GadgetContext(desc, gadget_params=params, timeout=3.0)
        g = desc.new_instance(ctx)
        g.attach_container(_FakeContainer(pid=child.pid))
        events = []
        g.set_event_handler(events.append)
        import threading
        th = threading.Thread(target=g.run, args=(ctx,))
        th.start()
        deadline = time.time() + 4.0
        while time.time() < deadline:
            if any(e.cap == "CHOWN" for e in events if e is not None):
                break
            time.sleep(0.1)
        ctx.cancel()
        th.join(3.0)
    finally:
        child.kill()
        child.wait()
    assert any(e.cap == "CHOWN" and e.verdict == "allow"
               for e in events if e is not None), \
        [getattr(e, "cap", e) for e in events][:20]


@needs_native
@needs_root
def test_container_filter_auto_attach_through_runtime():
    """Full stack: a containername selector on the localmanager operator
    auto-attaches trace/capabilities to the matching container's pid —
    the reference's per-container attach semantics without --pid."""
    from inspektor_gadget_tpu.containers import Container
    from inspektor_gadget_tpu.operators.operators import ensure_initialized
    from inspektor_gadget_tpu.params import Collection

    open("/tmp/ig_attach_probe2", "w").write("x")
    child = subprocess.Popen(
        ["sh", "-c",
         "sleep 1.0; chown 0:0 /tmp/ig_attach_probe2; sleep 4"])
    lm = ensure_initialized("localmanager")
    cid = "igtest-attach"
    try:
        lm.cc.add_container(Container(
            id=cid, name="ig-attach-probe", pid=child.pid,
            mntns=os.stat(f"/proc/{child.pid}/ns/mnt").st_ino))
        desc = get("trace", "capabilities")
        params = desc.params().to_params()
        op_params = Collection()
        lp = lm.instance_params().to_params()
        lp.set("containername", "ig-attach-probe")
        op_params["operator.localmanager."] = lp
        ctx = GadgetContext(desc, gadget_params=params,
                            operator_params=op_params, timeout=4.0)
        events = []
        result = LocalRuntime().run_gadget(ctx, on_event=events.append)
        assert not result.errors(), result.errors()
    finally:
        lm.cc.remove_container(cid)
        child.kill()
        child.wait()
    assert any(e is not None and e.cap == "CHOWN" for e in events), \
        [getattr(e, "cap", e) for e in events][:20]


@needs_native
@needs_root
def test_no_selector_means_no_auto_attach():
    """Without a container selector the Attacher gate stays closed: the
    gadget must error loudly, not ptrace every discovered process.
    (traceloop: the one ptrace gadget with no host-wide flavour.)"""
    desc = get("traceloop", "traceloop")
    params = desc.params().to_params()
    ctx = GadgetContext(desc, gadget_params=params, timeout=0.5)
    result = LocalRuntime().run_gadget(ctx)
    assert result.errors()


@needs_native
@needs_root
def test_attach_then_detach_stops_capture():
    child = subprocess.Popen(["sleep", "5"])
    try:
        desc = get("trace", "capabilities")
        params = desc.params().to_params()
        ctx = GadgetContext(desc, gadget_params=params, timeout=1.0)
        g = desc.new_instance(ctx)
        c = _FakeContainer(pid=child.pid)
        g.attach_container(c)
        assert g._attach_sources
        g.detach_container(c)
        assert not g._attach_sources
        # detach retires (stops) but must NOT free: a concurrent pop may
        # still hold the handle — the retired source stays valid
        assert g._retired_sources
        assert g._retired_sources[0].pop().count >= 0  # handle still live
    finally:
        child.kill()
        child.wait()


@needs_native
@needs_root
def test_selector_with_late_container_waits_then_attaches():
    """A selector that matches nothing at startup must not error: the
    gadget waits, and a container added mid-run attaches live."""
    from inspektor_gadget_tpu.containers import Container
    from inspektor_gadget_tpu.operators.operators import ensure_initialized
    from inspektor_gadget_tpu.params import Collection
    import threading

    open("/tmp/ig_attach_probe3", "w").write("x")
    lm = ensure_initialized("localmanager")
    cid = "igtest-late"
    desc = get("trace", "capabilities")
    params = desc.params().to_params()
    op_params = Collection()
    lp = lm.instance_params().to_params()
    lp.set("containername", "ig-late-probe")
    op_params["operator.localmanager."] = lp
    ctx = GadgetContext(desc, gadget_params=params,
                        operator_params=op_params, timeout=5.0)
    events = []
    box = {}

    def _run():
        box["result"] = LocalRuntime().run_gadget(ctx, on_event=events.append)

    th = threading.Thread(target=_run)
    th.start()
    child = None
    try:
        time.sleep(1.0)  # gadget is up, selector matches nothing yet
        assert th.is_alive(), "gadget exited instead of waiting for attach"
        child = subprocess.Popen(
            ["sh", "-c",
             "sleep 0.5; chown 0:0 /tmp/ig_attach_probe3; sleep 4"])
        lm.cc.add_container(Container(
            id=cid, name="ig-late-probe", pid=child.pid,
            mntns=os.stat(f"/proc/{child.pid}/ns/mnt").st_ino))
        deadline = time.time() + 4.0
        while time.time() < deadline:
            if any(e is not None and e.cap == "CHOWN" for e in events):
                break
            time.sleep(0.1)
        # mid-run detach while the run loop is popping: must not crash
        lm.cc.remove_container(cid)
        time.sleep(0.3)
        ctx.cancel()
        th.join(4.0)
    finally:
        lm.cc.remove_container(cid)
        if child is not None:
            child.kill()
            child.wait()
    result = box.get("result")
    assert result is not None and not result.errors(), (
        result.errors() if result else "no result")
    assert any(e is not None and e.cap == "CHOWN" for e in events)
