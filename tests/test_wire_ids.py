"""Tier-1 lint: EV_* wire constants must be unique and registered in the
one WIRE_EVENT_IDS table (tools/check_wire_ids.py) — PR 4 hand-assigned
EV_ALERT=7 with nothing preventing a future collision; this gate makes a
collision or an unregistered id a test failure. Plus self-tests that the
checker catches each drift mode, and a runtime cross-check that the
imported module agrees with its own table."""

from __future__ import annotations

import textwrap

from tools.check_wire_ids import check_file, check_source


def test_repo_wire_ids_are_registered_and_unique():
    violations = check_file()
    assert not violations, "\n".join(violations)


def test_history_window_id_registered():
    """EV_WINDOW (the sealed-window record the history plane journals
    and the agents serve) must ride the one authoritative table like
    every other plane's wire id — and must not collide with the capture
    plane's EV_JOURNAL_MARK it sits next to."""
    from inspektor_gadget_tpu.agent import wire
    assert wire.WIRE_EVENT_IDS["EV_WINDOW"] == wire.EV_WINDOW
    assert wire.EV_WINDOW != wire.EV_JOURNAL_MARK
    assert 0 < wire.EV_WINDOW < (1 << wire.EV_LOG_SHIFT)


def test_checker_would_catch_unregistered_window_id():
    """The drift mode PR 6 could have introduced: hand-assigning the new
    plane's id without registering it fails the gate."""
    src = _src("""
        EV_JOURNAL_MARK = 8
        EV_WINDOW = 9
        WIRE_EVENT_IDS = {"EV_JOURNAL_MARK": EV_JOURNAL_MARK}
    """)
    assert any("EV_WINDOW" in v and "not registered" in v
               for v in check_source(src, "w.py"))
    collide = _src("""
        EV_JOURNAL_MARK = 8
        EV_WINDOW = 8
        WIRE_EVENT_IDS = {"EV_JOURNAL_MARK": EV_JOURNAL_MARK,
                          "EV_WINDOW": EV_WINDOW}
    """)
    assert any("multiple constants" in v for v in check_source(collide, "w.py"))


def test_overload_plane_ids_registered():
    """EV_DROP_NOTICE (per-subscriber overload accounting) and
    EV_ATTACH_ACK (shared-run attach/refusal) must ride the one
    authoritative table like every other plane's wire id, distinct from
    the resume ack they sit next to."""
    from inspektor_gadget_tpu.agent import wire
    assert wire.WIRE_EVENT_IDS["EV_DROP_NOTICE"] == wire.EV_DROP_NOTICE
    assert wire.WIRE_EVENT_IDS["EV_ATTACH_ACK"] == wire.EV_ATTACH_ACK
    assert len({wire.EV_RESUME_ACK, wire.EV_DROP_NOTICE,
                wire.EV_ATTACH_ACK}) == 3
    assert all(0 < v < (1 << wire.EV_LOG_SHIFT)
               for v in (wire.EV_DROP_NOTICE, wire.EV_ATTACH_ACK))


def test_checker_would_catch_overload_plane_drift():
    """The drift modes ISSUE 12 could have introduced: hand-assigning
    the attach ack onto the resume ack's id, or registering the drop
    notice with a value its constant doesn't have."""
    collide = _src("""
        EV_RESUME_ACK = 10
        EV_ATTACH_ACK = 10
        WIRE_EVENT_IDS = {"EV_RESUME_ACK": EV_RESUME_ACK,
                          "EV_ATTACH_ACK": EV_ATTACH_ACK}
    """)
    assert any("multiple constants" in v
               for v in check_source(collide, "w.py"))
    mismatch = _src("""
        EV_DROP_NOTICE = 11
        WIRE_EVENT_IDS = {"EV_DROP_NOTICE": 12}
    """)
    assert any("registers 12" in v for v in check_source(mismatch, "w.py"))
    # a table row pointing at a constant that was renamed away must be
    # flagged stale, not silently decode as the old id
    renamed = _src("""
        EV_ATTACH_ACK = 12
        WIRE_EVENT_IDS = {"EV_ATTACH_ACK": EV_ATTACH_ACK,
                          "EV_ADMIT_ACK": 12}
    """)
    assert any("stale" in v for v in check_source(renamed, "w.py"))


def test_runtime_table_matches_module_constants():
    from inspektor_gadget_tpu.agent import wire
    for name, value in wire.WIRE_EVENT_IDS.items():
        assert getattr(wire, name) == value
    consts = {n: v for n, v in vars(wire).items()
              if n.startswith("EV_") and n != "EV_LOG_SHIFT"}
    assert consts == wire.WIRE_EVENT_IDS
    values = list(wire.WIRE_EVENT_IDS.values())
    assert len(values) == len(set(values))
    assert all(0 < v < (1 << wire.EV_LOG_SHIFT) for v in values)


def _src(body: str) -> str:
    return textwrap.dedent(body)


def test_checker_flags_duplicate_ids():
    src = _src("""
        EV_A = 1
        EV_B = 1
        WIRE_EVENT_IDS = {"EV_A": EV_A, "EV_B": EV_B}
    """)
    assert any("multiple constants" in v for v in check_source(src, "w.py"))


def test_checker_flags_unregistered_constant():
    src = _src("""
        EV_A = 1
        EV_B = 2
        WIRE_EVENT_IDS = {"EV_A": EV_A}
    """)
    assert any("not registered" in v for v in check_source(src, "w.py"))


def test_checker_flags_stale_table_row_and_value_mismatch():
    stale = _src("""
        EV_A = 1
        WIRE_EVENT_IDS = {"EV_A": EV_A, "EV_GONE": 9}
    """)
    assert any("stale" in v for v in check_source(stale, "w.py"))
    mismatch = _src("""
        EV_A = 1
        WIRE_EVENT_IDS = {"EV_A": 2}
    """)
    assert any("registers 2" in v for v in check_source(mismatch, "w.py"))


def test_checker_flags_severity_bit_collision_and_missing_table():
    collide = _src("""
        EV_LOG_SHIFT = 16
        EV_HUGE = 65536
        WIRE_EVENT_IDS = {"EV_HUGE": EV_HUGE}
    """)
    assert any("severity bits" in v for v in check_source(collide, "w.py"))
    assert any("no WIRE_EVENT_IDS" in v
               for v in check_source("EV_A = 1\n", "w.py"))


def test_checker_allows_the_clean_shape():
    src = _src("""
        EV_A = 1
        EV_B = 2
        EV_LOG_SHIFT = 16
        WIRE_EVENT_IDS = {"EV_A": EV_A, "EV_B": EV_B}
    """)
    assert check_source(src, "w.py") == []
