"""Standing queries (ISSUE 17): seal-time materialized answers, the
digest-keyed result cache, and the watch/fleet surfaces.

The acceptance story under test: a registered continuous query is
answered INCREMENTALLY — each seal tick folds exactly one new window
into a running materialized answer via the two-stack sliding
aggregation — and that answer is BYTE-IDENTICAL (same window digest) to
an ad-hoc `answer_query` refold of the same sealed windows, at every
tick, under eviction, compaction, restart+backfill, mixed plane
coverage, and across a 2-node fleet. A repeat read within one coverage
is a digest-keyed cache hit performing ZERO window folds (counter-
asserted); a coverage move is a provable invalidation, never a TTL
guess.
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np
import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.gadgets import GadgetContext, get
from inspektor_gadget_tpu.history import HISTORY, answer_query, decode_frames
from inspektor_gadget_tpu.history.query import pack_frames, unpack_frames
from inspektor_gadget_tpu.history.window import (
    decode_window,
    encode_window,
    merge_windows,
    merged_to_sealed,
    window_digest,
)
from inspektor_gadget_tpu.operators.operators import get as get_op
from inspektor_gadget_tpu.params import ParamError
from inspektor_gadget_tpu.perf.standing_bench import make_windows
from inspektor_gadget_tpu.queries import (
    QueryError,
    ResultCache,
    SlidingFold,
    StandingQuery,
    StandingQueryEngine,
    live_engines,
    live_stats,
    load_queries,
    load_queries_file,
)
from inspektor_gadget_tpu.queries import engine as queries_engine
from inspektor_gadget_tpu.sources.batch import EventBatch

GADGET = "trace/exec"

QDOC = json.dumps([{"id": "hot", "stats": ["topk", "cardinality"],
                    "range": "1h", "top": 8}])


@pytest.fixture(autouse=True)
def _release_instances():
    """Instances built outside a real gadget run never see
    post_gadget_run — drop them from the live tables (operator AND
    standing-query registry) and drain their stagers so no state leaks
    into other test files."""
    from inspektor_gadget_tpu.operators import tpusketch
    before = set(tpusketch._live)
    before_q = {rid for rid, _ in live_engines()}
    yield
    with tpusketch._live_mu:
        fresh = [rid for rid in list(tpusketch._live) if rid not in before]
        insts = [tpusketch._live.pop(rid) for rid in fresh]
    for inst in insts:
        if getattr(inst, "_stager", None) is not None:
            inst._stager.drain()
        for st in getattr(inst, "_lane_stagers", []):
            st.drain()
        inst._stats.unregister()
        inst._pstats.unregister()
    for rid, _ in live_engines():
        if rid not in before_q:
            queries_engine.unregister(rid)


@pytest.fixture()
def fleet_store(tmp_path):
    HISTORY.set_base_dir(str(tmp_path))
    yield str(tmp_path)
    HISTORY.close_all()
    HISTORY.set_base_dir(None)


def _make_instance(extra_params: dict, node: str = "",
                   extra_ctx: dict | None = None):
    desc = get("trace", "exec")
    ctx = GadgetContext(desc, extra=dict(extra_ctx or {}))
    if node:
        ctx.extra["node"] = node
    op = get_op("tpusketch")
    p = op.instance_params().to_params()
    p.set("enable", "true")
    p.set("depth", "3")
    p.set("log2-width", "10")
    p.set("hll-p", "8")
    p.set("entropy-log2-width", "6")
    p.set("topk", "8")
    p.set("harvest-interval", "1h")
    for k, v in extra_params.items():
        p.set(k, v)
    return op.instantiate(ctx, None, p)


def _batch(keys64: np.ndarray) -> EventBatch:
    b = EventBatch.alloc(len(keys64), with_comm=False)
    b.cols["key_hash"][:] = keys64
    b.count = len(keys64)
    return b


_HIST = {"history": "true", "history-interval": "0",
         "history-log2-width": "8", "history-slots": "2"}


def _flat(wins, *, gadget="bench/standing", node="bench0"):
    """The ad-hoc recompute: one flat left-fold over the covered
    windows, sealed with the same normalization the engine uses."""
    return merged_to_sealed(merge_windows(wins), gadget=gadget, node=node,
                            window=0, run_id="")


def _roundtrip(win):
    """One pass through the wire codec. encode_window caps per-slice
    heavy-hitter tables at SLICE_HH_K (the cut lands AFTER the
    (-count, key) canonical sort, so every fold shape truncates to the
    same top set); published standing payloads are encoded, so the
    honest byte-level comparison is wire-vs-wire — the same contract
    QueryWindows pushdown replies already live under."""
    return decode_window(
        *unpack_frames(pack_frames([encode_window(win)]))[0][0])


# ---------------------------------------------------------------------------
# registration grammar (spec.py): alert-rule discipline — loud at load
# ---------------------------------------------------------------------------

def test_load_queries_valid_forms():
    qs = load_queries(QDOC)
    assert len(qs) == 1 and qs[0].id == "hot"
    assert qs[0].stats == ("topk", "cardinality")
    assert qs[0].range_s == 3600.0 and qs[0].top == 8 and qs[0].every == 1
    # wrapped form + numeric range + explicit every
    qs = load_queries(json.dumps({"queries": [
        {"id": "a", "stats": ["entropy"], "range": 30, "every": 3},
        {"id": "b", "stats": ["quantiles"], "range": "15m",
         "key": "mntns:42"}]}))
    assert [q.id for q in qs] == ["a", "b"]
    assert qs[0].every == 3 and qs[1].key == "mntns:42"
    # default_every applies only where the doc is silent
    qs = load_queries(json.dumps({"queries": [
        {"id": "a", "stats": ["topk"], "range": 30, "every": 2},
        {"id": "b", "stats": ["topk"], "range": 30}]}), default_every=6)
    assert (qs[0].every, qs[1].every) == (2, 6)
    assert "topk over last" in qs[0].describe()
    assert json.loads(qs[0].identity())["id"] == "a"


def test_load_queries_error_matrix():
    cases = [
        ("", "empty"),
        ("[]", "no queries"),
        ('{"watch": []}', "unknown top-level"),
        ('"hot"', "expected a list"),
        ('[42]', "expected an object"),
        ('[{"stats": ["topk"], "range": 30}]', "id must match"),
        ('[{"id": "bad id!", "stats": ["topk"], "range": 30}]',
         "id must match"),
        ('[{"id": "q", "stats": ["topk"], "range": 30, "topk": 5}]',
         "unknown key"),
        ('[{"id": "q", "range": 30}]', "stats must be"),
        ('[{"id": "q", "stats": [], "range": 30}]', "stats must be"),
        ('[{"id": "q", "stats": ["median"], "range": 30}]',
         "unknown statistic"),
        ('[{"id": "q", "stats": ["topk", "topk"], "range": 30}]',
         "duplicate statistic"),
        ('[{"id": "q", "stats": ["topk"]}]', "missing 'range'"),
        ('[{"id": "q", "stats": ["topk"], "range": "soon"}]', "bad range"),
        ('[{"id": "q", "stats": ["topk"], "range": -5}]', "must be > 0"),
        ('[{"id": "q", "stats": ["topk"], "range": 30, "key": 7}]',
         "key must be"),
        ('[{"id": "q", "stats": ["topk"], "range": 30, "top": 0}]',
         "top must be"),
        ('[{"id": "q", "stats": ["topk"], "range": 30, "top": 99999}]',
         "top must be"),
        ('[{"id": "q", "stats": ["topk"], "range": 30, "every": 0}]',
         "every must be"),
        ('[{"id": "q", "stats": ["topk"], "range": 30},'
         ' {"id": "q", "stats": ["topk"], "range": 60}]', "duplicate query"),
    ]
    for doc, match in cases:
        with pytest.raises(QueryError, match=match):
            load_queries(doc)


def test_load_queries_range_cap_and_missing_file(tmp_path):
    with pytest.raises(QueryError, match="exceeds the configured cap"):
        load_queries(QDOC, max_range_s=60.0)
    with pytest.raises(QueryError, match="cannot read query file"):
        load_queries_file(str(tmp_path / "absent.json"))
    p = tmp_path / "qs.json"
    p.write_text(QDOC, encoding="utf-8")
    assert load_queries_file(str(p))[0].id == "hot"


# ---------------------------------------------------------------------------
# the two-stack sliding fold: exact vs flat refold, at every tick
# ---------------------------------------------------------------------------

def test_sliding_fold_matches_flat_fold_every_tick():
    """The tentpole invariant: after every push/evict, the incremental
    value seals BYTE-IDENTICALLY (same digest) to a flat left-fold over
    the covered windows — fold shape never leaks into the answer."""
    wins = make_windows(12, width=32, hll_m=32, ent_w=16, k=4)
    fold = SlidingFold(gadget="bench/standing", node="bench0")
    live: list = []
    range_s = 5.0
    for w in wins:
        fold.push(w)
        live.append(w)
        cutoff = w.end_ts - range_s
        fold.evict_older_than(cutoff)
        live = [x for x in live if x.end_ts >= cutoff]
        assert fold.coverage() == frozenset(x.digest for x in live)
        got = fold.value()
        want = _flat(live)
        want.digest = window_digest(want)
        got2 = decode_window(*unpack_frames(
            pack_frames([encode_window(got)]))[0][0])
        assert window_digest(got) == want.digest
        # and the encoded bytes round-trip to the same content
        assert window_digest(got2) == want.digest
    # eviction actually happened (12 one-second windows, 5s range)
    assert len(fold) < 12


def test_sliding_fold_amortized_folds():
    """Refresh cost is amortized O(1) merges per tick: push is 2 seals,
    value ≤ 1, and each window enters the front stack at most once —
    total folds are linear in ticks, NOT ticks × range."""
    wins = make_windows(64, width=16, hll_m=16, ent_w=8, k=2)
    fold = SlidingFold(gadget="bench/standing", node="bench0")
    for w in wins:
        fold.push(w)
        fold.evict_older_than(w.end_ts - 8.0)
        fold.value()
    assert fold.folds <= 4 * len(wins) + 8


# ---------------------------------------------------------------------------
# engine + digest-keyed result cache
# ---------------------------------------------------------------------------

def test_engine_repeat_read_is_zero_fold_cache_hit():
    eng = StandingQueryEngine(
        [StandingQuery(id="hot", stats=("topk",), range_s=3600.0)],
        gadget="bench/standing", node="bench0")
    assert eng.read("hot") is None  # empty range: nothing to answer
    with pytest.raises(KeyError, match="no standing query 'nope'"):
        eng.read("nope")
    wins = make_windows(3, width=16, hll_m=16, ent_w=8, k=2)
    pubs = eng.on_seal(wins[0], now=wins[0].end_ts)
    assert len(pubs) == 1 and pubs[0][0]["schema"].startswith("ig-tpu/")
    folds0 = eng._folds["hot"].folds
    h1, p1, hit1 = eng.read("hot")
    h2, p2, hit2 = eng.read("hot")
    # on_seal already cached this coverage: both reads hit, zero folds
    assert hit1 and hit2
    assert eng._folds["hot"].folds == folds0
    assert p1 == p2 and h1["coverage_digest"] == h2["coverage_digest"]
    stats = eng.cache.stats()
    assert stats["hits"] >= 2 and stats["entries"] == 1
    # a new seal tick MOVES coverage: the old entry is provably stale
    eng.on_seal(wins[1], now=wins[1].end_ts)
    h3, _p3, _ = eng.read("hot")
    assert h3["coverage_digest"] != h1["coverage_digest"]
    assert h3["windows"] == 2
    assert eng.cache.stats()["invalidations"] >= 1


def test_engine_publish_cadence_and_stats():
    eng = StandingQueryEngine(
        [StandingQuery(id="hot", stats=("topk",), range_s=3600.0,
                       every=2)],
        gadget="bench/standing", node="bench0")
    wins = make_windows(4, width=16, hll_m=16, ent_w=8, k=2)
    published = [len(eng.on_seal(w, now=w.end_ts)) for w in wins]
    # every=2: publish on ticks 2 and 4; refresh happens every tick
    assert published == [0, 1, 0, 1]
    row = eng.stats()[0]
    assert row["id"] == "hot" and row["ticks"] == 4
    assert row["refreshed"] == 4 and row["published"] == 2
    assert row["windows"] == 4 and row["cache"]["entries"] == 1


def test_result_cache_exact_coverage_and_lru():
    cache = ResultCache(max_bytes=4096)
    cov_a = frozenset({"d1", "d2"})
    cache.put("a", cov_a, {"id": "a"}, b"x" * 64)
    assert cache.get("a", cov_a) == ({"id": "a"}, b"x" * 64)
    # coverage moved: provably stale — dropped + invalidation, then miss
    assert cache.get("a", frozenset({"d2", "d3"})) is None
    st = cache.stats()
    assert st["invalidations"] == 1 and st["misses"] == 1 \
        and st["entries"] == 0
    # LRU-by-bytes: the budget holds ~2 entries; oldest is evicted
    # WITHOUT counting an invalidation (nothing became stale)
    for qid in ("a", "b", "c"):
        cache.put(qid, frozenset({qid}), {"id": qid}, b"y" * 1500)
    st = cache.stats()
    assert st["entries"] == 2 and st["bytes"] <= 4096
    assert st["invalidations"] == 1
    assert cache.get("a", frozenset({"a"})) is None  # evicted
    assert cache.get("c", frozenset({"c"})) is not None
    with pytest.raises(ValueError, match="max_bytes"):
        ResultCache(max_bytes=0)


# ---------------------------------------------------------------------------
# operator integration: param matrix + the seal-tick feed
# ---------------------------------------------------------------------------

def test_param_error_matrix():
    # knobs without the feature: loud, named, before the first batch
    with pytest.raises(ParamError, match="query-cache-bytes.*needs"):
        _make_instance({"query-cache-bytes": "1024"})
    with pytest.raises(ParamError, match="query-refresh.*needs"):
        _make_instance({"query-refresh": "2"})
    with pytest.raises(ParamError, match="query-max-range.*needs"):
        _make_instance({"query-max-range": "1h"})
    # the feature without its substrate
    with pytest.raises(ParamError, match="needs 'history true'"):
        _make_instance({"standing-queries": QDOC})
    # a bad document answers as a ParamError naming the param
    with pytest.raises(ParamError,
                       match="standing-queries.*expected a list"):
        _make_instance({"standing-queries": '{"queries": 42}', **_HIST})
    with pytest.raises(ParamError, match="exceeds the configured cap"):
        _make_instance({"standing-queries": QDOC,
                        "query-max-range": "10m", **_HIST})
    with pytest.raises(ParamError, match="cannot read query file"):
        _make_instance({"standing-queries": "@/nonexistent/qs.json",
                        **_HIST})
    # grammar-level validators still answer at set() time
    with pytest.raises(ParamError):
        _make_instance({"standing-queries": QDOC, "query-cache-bytes": "0",
                        **_HIST})


def test_operator_seals_feed_engine_and_publish(fleet_store):
    rng = np.random.default_rng(7)
    pubs: list[tuple[dict, bytes]] = []
    inst = _make_instance(
        {"standing-queries": QDOC, **_HIST}, node="nA",
        extra_ctx={"on_query_answer":
                   lambda h, p: pubs.append((h, p))})
    rid = inst.ctx.run_id
    assert any(r == rid for r, _ in live_engines())
    per_tick: list[bytes] = []
    for _ in range(3):
        inst.enrich_batch(_batch(
            rng.integers(1, 1 << 32, 300, dtype=np.uint64)))
        inst.seal_window()
        assert pubs, "seal tick must publish the refreshed answer"
        per_tick.append(pubs[-1][1])
    HISTORY.release(inst._hist_writer)
    # every published header speaks the wire schema
    for h, _p in pubs:
        assert h["schema"] == "ig-tpu/standing-query/v1"
        assert h["id"] == "hot" and h["gadget"] == GADGET
        assert h["node"] == "nA" and h["top"] == 8
    assert [h["windows"] for h, _ in pubs] == [1, 2, 3]
    # the engine's read serves the same bytes the wire published
    eng = dict(live_engines())[rid]
    header, payload, _cached = eng.read("hot")
    assert payload == per_tick[-1]
    assert header["coverage_digest"] == pubs[-1][0]["coverage_digest"]
    # exactness AT EVERY TICK: each published answer is byte-identical
    # to the flat answer_query-style refold of the windows sealed so far
    frames = list(HISTORY.fetch_windows(base_dir=fleet_store,
                                        gadget=GADGET))
    wins = sorted(decode_frames(frames), key=lambda w: w.window)
    assert len(wins) == 3
    for i, payload_i in enumerate(per_tick):
        std = decode_window(*unpack_frames(payload_i)[0][0])
        want = _flat(wins[:i + 1], gadget=GADGET, node="nA")
        assert window_digest(std) == window_digest(_roundtrip(want))
    # and the rendered answers agree (the user-facing equivalence)
    ad_hoc = answer_query(wins, top=8)
    standing = answer_query([decode_window(
        *unpack_frames(per_tick[-1])[0][0])], top=8)
    assert standing.heavy_hitters == ad_hoc.heavy_hitters
    assert standing.distinct == ad_hoc.distinct
    assert standing.entropy_bits == ad_hoc.entropy_bits
    assert standing.events == ad_hoc.events
    # live_stats surfaces the accounting row for dump_state/doctor
    rows = [r for r in live_stats() if r["run_id"] == rid]
    assert rows and rows[0]["ticks"] == 3 and rows[0]["windows"] == 3


# ---------------------------------------------------------------------------
# churn matrix: compaction, restart+backfill, mixed planes, 2-node fleet
# ---------------------------------------------------------------------------

def test_standing_equals_recompute_across_compaction():
    """Compaction rewrites the range into a super-window + raw tail;
    the ad-hoc fold dedupes and re-merges. The standing answer (which
    folded the raw seals) must render identically — compaction is a
    lossless refold, not a new answer."""
    wins = make_windows(4, width=32, hll_m=32, ent_w=16, k=4)
    superw = merged_to_sealed(
        merge_windows(wins[:2]), gadget="bench/standing", node="bench0",
        level=1, window=1,
        compacted_from=[{"digest": w.digest} for w in wins[:2]])
    superw.digest = window_digest(superw)
    eng = StandingQueryEngine(
        [StandingQuery(id="q", stats=("topk",), range_s=3600.0)],
        gadget="bench/standing", node="bench0")
    for w in wins:
        eng.on_seal(w, now=w.end_ts)
    _h, payload, _ = eng.read("q")
    standing = answer_query(
        [decode_window(*unpack_frames(payload)[0][0])], top=8)
    # the compacted store still holds a not-yet-GCed raw source window:
    # dedupe must drop it, and the answer must match the standing fold
    ad_hoc = answer_query([superw, wins[0], wins[2], wins[3]], top=8)
    assert any("superseded" in n for n in ad_hoc.dropped_windows)
    assert standing.heavy_hitters == ad_hoc.heavy_hitters
    assert standing.distinct == ad_hoc.distinct
    assert standing.entropy_bits == ad_hoc.entropy_bits
    assert (standing.events, standing.drops) == (ad_hoc.events,
                                                 ad_hoc.drops)


def test_restart_backfill_rebuilds_identical_answer():
    """An engine restarted from nothing and backfilled with the same
    sealed windows (the store replay path) converges to the SAME
    coverage digest and the SAME payload bytes."""
    wins = make_windows(6, width=32, hll_m=32, ent_w=16, k=4)
    spec = StandingQuery(id="q", stats=("topk", "cardinality"),
                         range_s=4.0)
    a = StandingQueryEngine([spec], gadget="bench/standing", node="bench0")
    for w in wins:
        a.on_seal(w, now=w.end_ts)
    b = StandingQueryEngine([spec], gadget="bench/standing", node="bench0")
    for w in wins:
        b.on_seal(w, now=w.end_ts)
    ha, pa, _ = a.read("q")
    hb, pb, _ = b.read("q")
    assert ha["coverage_digest"] == hb["coverage_digest"]
    assert ha["windows"] == hb["windows"] < 6  # range evicted the head
    assert pa == pb


def test_mixed_plane_coverage_refusal_matches(fleet_store):
    """One node seals with the quantile plane, one without: the standing
    fold must refuse quantiles exactly like the ad-hoc fold (refusal is
    an AND over windows — associative), not average partial coverage."""
    rng = np.random.default_rng(9)
    for node, qt in (("nA", "true"), ("nB", "false")):
        inst = _make_instance({"quantiles": qt, **_HIST}, node=node)
        b = _batch(rng.integers(1, 1 << 32, 200, dtype=np.uint64))
        if qt == "true":
            b.cols["aux1"][:] = rng.integers(1, 1 << 20, 200)
        inst.enrich_batch(b)
        inst.seal_window()
        HISTORY.release(inst._hist_writer)
    frames = list(HISTORY.fetch_windows(base_dir=fleet_store,
                                        gadget=GADGET))
    wins = decode_frames(frames)
    assert len(wins) == 2
    eng = StandingQueryEngine(
        [StandingQuery(id="q", stats=("topk", "quantiles"),
                       range_s=3600.0)], gadget=GADGET, node="")
    for w in sorted(wins, key=lambda w: w.node):
        eng.on_seal(w, now=max(x.end_ts for x in wins))
    _h, payload, _ = eng.read("q")
    standing = answer_query(
        [decode_window(*unpack_frames(payload)[0][0])], top=8)
    ad_hoc = answer_query(wins, top=8)
    assert standing.quantiles is None and ad_hoc.quantiles is None
    assert standing.histogram is None
    assert standing.heavy_hitters == ad_hoc.heavy_hitters
    assert standing.events == ad_hoc.events == 400


def test_two_node_fleet_standing_matches_fleet_recompute(fleet_store):
    """The fleet shape subscribe_query folds client-side: one standing
    answer per node, merged at the client. That merge must equal the
    ad-hoc fleet recompute over every node's sealed windows."""
    rng = np.random.default_rng(11)
    per_node: dict[str, bytes] = {}
    for node in ("nA", "nB"):
        inst = _make_instance({"standing-queries": QDOC, **_HIST},
                              node=node)
        rid = inst.ctx.run_id
        for _ in range(2):
            inst.enrich_batch(_batch(
                rng.integers(1, 1 << 32, 250, dtype=np.uint64)))
            inst.seal_window()
        HISTORY.release(inst._hist_writer)
        _h, payload, _ = dict(live_engines())[rid].read("hot")
        per_node[node] = payload
    std_wins = [decode_window(*unpack_frames(p)[0][0])
                for p in per_node.values()]
    frames = list(HISTORY.fetch_windows(base_dir=fleet_store,
                                        gadget=GADGET))
    raw_wins = decode_frames(frames)
    assert len(raw_wins) == 4
    # client-side merge of the two standing answers vs the full refold:
    # byte-identical sealed content on every GLOBAL plane (digest
    # excludes node identity). Per-slice heavy-hitter tables are
    # compared only to truncation: each node's published answer already
    # cut ITS union at SLICE_HH_K on encode, so the client-side merge
    # holds the union of two capped tables while the raw refold holds
    # the union of four — lossy exactly like the pushdown reply path.
    merged_std = _flat(std_wins, gadget=GADGET, node="")
    merged_raw = _flat(raw_wins, gadget=GADGET, node="")
    assert window_digest(
        dataclasses.replace(merged_std, slices={})) == window_digest(
        dataclasses.replace(merged_raw, slices={}))
    standing = answer_query(std_wins, top=8)
    ad_hoc = answer_query(raw_wins, top=8)
    assert standing.heavy_hitters == ad_hoc.heavy_hitters
    assert standing.distinct == ad_hoc.distinct
    assert standing.events == ad_hoc.events == 1000


# ---------------------------------------------------------------------------
# wire plane: EV_QUERY rides the summary tier
# ---------------------------------------------------------------------------

def test_ev_query_wire_roundtrip():
    from inspektor_gadget_tpu.agent import wire
    from inspektor_gadget_tpu.agent.service import _SUMMARY_KINDS
    assert wire.EV_QUERY == 13
    assert wire.WIRE_EVENT_IDS["EV_QUERY"] == wire.EV_QUERY
    # summary-tier subscribers receive standing answers without raw
    # batches — EV_QUERY must be in the tier's allow set
    assert wire.EV_QUERY in _SUMMARY_KINDS
    win = make_windows(1, width=16, hll_m=16, ent_w=8, k=2)[0]
    payload = pack_frames([encode_window(win)])
    header = {"node": "n0", "query": {"id": "hot", "tick": 1}}
    data = wire.encode_msg(header, payload)
    h2, p2 = wire.decode_msg(data)
    assert h2 == header
    got = decode_window(*unpack_frames(p2)[0][0])
    assert window_digest(got) == win.digest


# ---------------------------------------------------------------------------
# CLI: ig-tpu watch / fleet queries
# ---------------------------------------------------------------------------

class _Args:
    id = ""
    remote = ""
    local = False
    list_queries = False
    gadget = ""
    run = ""
    json = False
    iterations = 0
    duration = 0.0
    interval = 0.01
    top = 10
    quantiles = False
    deadline = 3.0
    output = "table"


def _registered_engine(run_id="run-watch-1"):
    eng = StandingQueryEngine(
        [StandingQuery(id="hot", stats=("topk",), range_s=3600.0)],
        gadget="bench/standing", node="bench0")
    for w in make_windows(2, width=16, hll_m=16, ent_w=8, k=2):
        eng.on_seal(w, now=w.end_ts)
    queries_engine.register(run_id, eng)
    return eng


def test_watch_list_local(capsys):
    from inspektor_gadget_tpu.cli.watch import cmd_watch
    _registered_engine()
    args = _Args()
    args.local = True
    args.list_queries = True
    assert cmd_watch(args) == 0
    out = capsys.readouterr().out
    assert "hot" in out and "QUERY" in out
    args.output = "json"
    assert cmd_watch(args) == 0
    doc = json.loads(capsys.readouterr().out)
    rows = [r for r in doc["queries"] if r["id"] == "hot"]
    assert rows and rows[0]["windows"] == 2 and rows[0]["ticks"] == 2


def test_watch_local_streams_json(capsys):
    from inspektor_gadget_tpu.cli.watch import cmd_watch
    _registered_engine()
    args = _Args()
    args.id = "hot"
    args.local = True
    args.json = True
    args.iterations = 1
    assert cmd_watch(args) == 0
    line = capsys.readouterr().out.strip().splitlines()[0]
    doc = json.loads(line)
    assert doc["refresh"] == 1 and doc["meta"]["id"] == "hot"
    assert doc["answer"]["windows"] == 1  # one merged standing window


def test_watch_requires_id_or_list(capsys):
    from inspektor_gadget_tpu.cli.watch import cmd_watch
    args = _Args()
    assert cmd_watch(args) == 2
    assert "--id is required" in capsys.readouterr().err


def test_watch_local_unknown_query(capsys):
    from inspektor_gadget_tpu.cli.watch import cmd_watch
    args = _Args()
    args.id = "nope"
    args.local = True
    args.iterations = 1
    assert cmd_watch(args) == 1
    assert "no live engine" in capsys.readouterr().err


def test_fleet_queries_renders_dump_state_rows(monkeypatch, capsys):
    from inspektor_gadget_tpu.agent import client as agent_client
    from inspektor_gadget_tpu.cli.fleet import cmd_fleet_queries

    class _StubClient:
        def __init__(self, target, node, rpc_deadline=3.0):
            self.node = node

        def dump_state(self):
            return {"standing_queries": [{
                "run_id": "r1", "id": "hot", "gadget": GADGET,
                "stats": ["topk"], "key": "", "range_s": 900.0,
                "every": 1, "windows": 4, "events": 1234, "ticks": 4,
                "refreshed": 4, "published": 4, "folds": 13,
                "cache": {"hits": 3, "misses": 1, "invalidations": 2,
                          "entries": 1, "bytes": 2048,
                          "max_bytes": 8 << 20}}]}

        def close(self):
            pass

    monkeypatch.setattr(agent_client, "AgentClient", _StubClient)
    args = _Args()
    args.remote = "n0=localhost:19999"
    assert cmd_fleet_queries(args) == 0
    out = capsys.readouterr().out
    assert "hot" in out and "3/1/2" in out and "1,234" in out


# ---------------------------------------------------------------------------
# perf: the economic pair lands as schema-valid ledger records
# ---------------------------------------------------------------------------

def test_standing_bench_publishes_valid_records(tmp_path):
    from inspektor_gadget_tpu.perf.ledger import read_ledger
    from inspektor_gadget_tpu.perf.schema import validate_record
    from inspektor_gadget_tpu.perf.standing_bench import publish
    ledger = str(tmp_path / "PERF.jsonl")
    records = publish(range_small=4, range_large=8, steps=8,
                      ledger=ledger)
    assert [r["config"] for r in records] == [
        "standing-refresh", "standing-recompute", "standing-cache-hit"]
    for rec in records:
        assert validate_record(rec) == []
    refresh, recompute, cache = records
    # the auditable independence pair: both range lengths in extra
    assert refresh["extra"]["range_small"] == 4
    assert refresh["extra"]["range_large"] == 8
    assert refresh["extra"]["large_over_small"] > 0
    assert recompute["extra"]["large_over_small"] > 0
    # zero-fold cache reads, counter-asserted inside the bench
    assert cache["extra"]["folds_during_reads"] == 0
    on_disk = read_ledger(path=ledger)
    assert len(on_disk.records) == 3 and not on_disk.skipped
