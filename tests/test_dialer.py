"""Dialer reconnect-churn tier (ISSUE 11 satellite): a supervised
runtime redials on every reconnect attempt, so dial/close cycles are no
longer rare — 50 cycles must not grow fds or threads, and the
exec-tunnel's per-connection subprocesses must be reaped (no zombies)
across churn (dialer.py subprocess-reap path)."""

from __future__ import annotations

import os
import socket
import sys
import tempfile
import threading
import time

import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.agent.client import AgentClient
from inspektor_gadget_tpu.agent.dialer import DirectDialer, ExecTunnelDialer
from inspektor_gadget_tpu.agent.service import serve


@pytest.fixture(scope="module")
def agent_addr():
    tmp = tempfile.mkdtemp()
    addr = f"unix://{tmp}/dialer-agent.sock"
    server, _ = serve(addr, node_name="dialer-node")
    yield addr
    server.stop(grace=0.5)


def _fd_count() -> int:
    return len(os.listdir("/proc/self/fd"))


def test_direct_dialer_churn_no_fd_or_thread_growth(agent_addr):
    """50 dial → RPC → close cycles: bounded fd/thread growth. gRPC
    keeps a small shared pool, so allow slack — what must NOT happen is
    linear growth with the cycle count."""
    # warm up once so lazily-created shared state doesn't count as leak
    c = AgentClient(agent_addr, "warm")
    c.get_catalog(use_cache_on_error=False)
    c.close()
    time.sleep(0.3)
    fd0 = _fd_count()
    th0 = threading.active_count()
    for _ in range(50):
        client = AgentClient(agent_addr, "churn")
        client.get_catalog(use_cache_on_error=False)
        client.close()
    time.sleep(1.0)  # let grpc wind down its per-channel workers
    fd_growth = _fd_count() - fd0
    th_growth = threading.active_count() - th0
    assert fd_growth <= 16, f"fd leak over 50 dial/close cycles: +{fd_growth}"
    assert th_growth <= 8, f"thread leak over 50 cycles: +{th_growth}"


def test_direct_dialer_reconnect_churn(agent_addr):
    """The supervisor's redial path: one client, 50 reconnect() calls,
    each followed by a live RPC — bounded fds, every channel usable."""
    client = AgentClient(agent_addr, "reconn")
    client.get_catalog(use_cache_on_error=False)
    time.sleep(0.3)
    fd0 = _fd_count()
    for _ in range(50):
        client.reconnect()
        client.get_catalog(use_cache_on_error=False)
    time.sleep(1.0)
    growth = _fd_count() - fd0
    client.close()
    assert growth <= 16, f"fd leak over 50 reconnect cycles: +{growth}"


# a stdio↔unix-socket bridge: what socat/kubectl-exec does, stdlib-only
# (the container has no socat)
_BRIDGE = r"""
import socket, sys, threading
s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
s.connect(sys.argv[1])
def up():
    while True:
        d = sys.stdin.buffer.read1(65536)
        if not d:
            break
        s.sendall(d)
    try:
        s.shutdown(socket.SHUT_WR)
    except OSError:
        pass
t = threading.Thread(target=up, daemon=True)
t.start()
while True:
    d = s.recv(65536)
    if not d:
        break
    sys.stdout.buffer.write(d)
    sys.stdout.buffer.flush()
"""


def test_exec_tunnel_end_to_end_and_subprocess_reap(agent_addr):
    """A real exec tunnel (python stdio bridge standing in for
    socat/kubectl-exec): catalog RPCs work through it, and repeated
    dial/close cycles reap every tunnel subprocess — the reap path at
    dialer.py _pump_in must leave no zombies behind."""
    sock_path = agent_addr[len("unix://"):]
    dialer = ExecTunnelDialer([sys.executable, "-c", _BRIDGE, sock_path])
    try:
        for _ in range(5):
            client = AgentClient(agent_addr, "tunnel", dialer=dialer)
            # the dialer owns the subprocesses; don't let client.close()
            # tear the shared dialer down between cycles
            client.dialer = DirectDialer()
            cat = client.get_catalog(use_cache_on_error=False)
            assert any(g["name"] == "exec" for g in cat["gadgets"])
            client.close()
        # every tunnel subprocess exits and is waited on (no zombies:
        # a zombie still answers poll() None only until reaped; after
        # the reap path ran, returncode is set and _procs is empty)
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and dialer._procs:
            time.sleep(0.2)
        assert not dialer._procs, \
            f"{len(dialer._procs)} tunnel subprocess(es) not reaped"
    finally:
        dialer.close()


def test_exec_tunnel_raw_churn_reaps_and_survives(agent_addr):
    """Raw connection churn (no gRPC): 10 open/close cycles against the
    tunnel listener; all subprocesses reaped, listener still serving."""
    sock_path = agent_addr[len("unix://"):]
    dialer = ExecTunnelDialer([sys.executable, "-c", _BRIDGE, sock_path])
    try:
        for _ in range(10):
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.connect(dialer._path)
            s.close()
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and dialer._procs:
            time.sleep(0.2)
        assert not dialer._procs, "churned tunnels not reaped"
        # the listener is still alive: one more real roundtrip works
        client = AgentClient(agent_addr, "tunnel2", dialer=dialer)
        client.dialer = DirectDialer()
        assert client.get_catalog(use_cache_on_error=False)["gadgets"]
        client.close()
    finally:
        dialer.close()
