"""Auxiliary subsystem tests: parser facade, checkpoint/resume, logger
stream encoding, kubeipresolver/kubemanager operators, netns helpers."""

import dataclasses
import json

import numpy as np
import jax.numpy as jnp
import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.columns import Columns, col
from inspektor_gadget_tpu.parser import Parser
from inspektor_gadget_tpu.types import Event


@dataclasses.dataclass
class Ev(Event):
    comm: str = col("", width=16)
    pid: int = col(0, width=7, dtype=np.int32)
    reads: int = col(0, width=8, group="sum", dtype=np.int64)


def test_parser_filter_sort_callback():
    p = Parser(Columns(Ev))
    p.set_filters("comm:bash")
    p.set_sort("-reads")
    got = []
    p.set_event_callback(got.append)
    p.event_handler(Ev(comm="bash", pid=1, reads=5))
    p.event_handler(Ev(comm="curl", pid=2, reads=9))
    assert len(got) == 1 and got[0].comm == "bash"

    arrays = []
    p.set_event_callback_array(arrays.append)
    p.event_handler_array([Ev(comm="bash", reads=1), Ev(comm="bash", reads=7),
                           Ev(comm="zsh", reads=3)])
    assert [e.reads for e in arrays[0]] == [7, 1]


def test_parser_json_handlers_and_snapshots():
    p = Parser(Columns(Ev))
    got = []
    p.set_event_callback(got.append)
    p.json_handler("node-9")(json.dumps({"comm": "x", "pid": 3}))
    assert got[0].node == "node-9" and got[0].pid == 3

    p.enable_snapshots(ttl_ticks=2)
    arrays = []
    p.set_event_callback_array(arrays.append)
    p.json_handler_array("n1")(json.dumps([{"comm": "a", "reads": 1}]))
    p.json_handler_array("n2")(json.dumps([{"comm": "b", "reads": 2}]))
    p.tick()
    assert {e.comm for e in arrays[0]} == {"a", "b"}


def test_parser_oneshot_accumulate_flush():
    p = Parser(Columns(Ev))
    arrays = []
    p.set_event_callback_array(arrays.append)
    p.accumulate([Ev(comm="a")])
    p.accumulate([Ev(comm="b")])
    assert not arrays
    p.flush()
    assert len(arrays[0]) == 2


def test_checkpoint_roundtrip(tmp_path):
    from inspektor_gadget_tpu.ops import bundle_init, bundle_update, cms_query
    from inspektor_gadget_tpu.utils.checkpoint import load_pytree, save_pytree

    b = bundle_init(depth=4, log2_width=10, hll_p=8, entropy_log2_width=7, k=8)
    keys = jnp.array([7, 7, 9], dtype=jnp.uint32)
    b = bundle_update(b, keys, keys, keys, jnp.ones(3, bool))
    save_pytree(tmp_path / "sketch", b)
    restored = load_pytree(tmp_path / "sketch", bundle_init(
        depth=4, log2_width=10, hll_p=8, entropy_log2_width=7, k=8))
    assert float(restored.events) == 3
    q = cms_query(restored.cms, jnp.array([7], dtype=jnp.uint32))
    assert int(q[0]) == 2
    # resumed state keeps absorbing
    more = bundle_update(restored, keys, keys, keys, jnp.ones(3, bool))
    assert float(more.events) == 6


def test_checkpoint_structure_mismatch_rejected(tmp_path):
    """A checkpoint saved from one structure must not silently unflatten
    into a different `like` that happens to have the same leaf count —
    the saved treedef is validated on load."""
    import pytest as _pytest

    from inspektor_gadget_tpu.utils.checkpoint import load_pytree, save_pytree

    save_pytree(tmp_path / "pair", {"a": jnp.zeros(3), "b": jnp.ones(2)})
    with _pytest.raises(ValueError, match="structure mismatch"):
        load_pytree(tmp_path / "pair",
                    {"x": jnp.zeros(3), "y": jnp.ones(2)})


def test_stream_logger_severity_encoding():
    from inspektor_gadget_tpu.utils.logger import WARN, StreamLogger

    pushed = []
    sl = StreamLogger(lambda t, hdr, payload: pushed.append((t, hdr, payload)))
    sl.warn("careful")
    t, hdr, payload = pushed[0]
    assert t >> 16 == WARN
    assert hdr == {}  # no run/trace identity configured
    assert payload == b"careful"


def test_kubeipresolver_enriches_addresses():
    from inspektor_gadget_tpu.operators.kubeipresolver import KubeIPResolver
    from inspektor_gadget_tpu.operators.operators import get as get_op

    op: KubeIPResolver = get_op("kubeipresolver")
    op.set_inventory({"10.0.0.5": ("pod", "web-0")})

    @dataclasses.dataclass
    class NetEv:
        saddr: str = ""
        daddr: str = ""

    inst = op.instantiate(None, None, op.instance_params().to_params())
    ev = NetEv(saddr="10.0.0.5", daddr="8.8.8.8")
    inst.enrich(ev)
    assert "pod/web-0" in ev.saddr
    assert ev.daddr == "8.8.8.8"


def test_kubemanager_selector_filtering():
    from inspektor_gadget_tpu.containers import Container
    from inspektor_gadget_tpu.gadgets import GadgetContext, get
    from inspektor_gadget_tpu.operators.operators import get as get_op

    lm = get_op("localmanager")
    if lm.cc is None:
        lm.init(lm.global_params().to_params())
    lm.cc.add_container(Container(id="km1", name="web", pod="web-0",
                                  namespace="prod", mntns=555001, pid=1))
    lm.cc.add_container(Container(id="km2", name="db", pod="db-0",
                                  namespace="prod", mntns=555002, pid=1))

    km = get_op("kubemanager")
    desc = get("trace", "exec")
    ctx = GadgetContext(desc)
    params = km.instance_params().to_params()
    params.set("namespace", "prod")
    params.set("podname", "web-0")

    class FakeGadget:
        def __init__(self):
            self.filter = None

        def set_mntns_filter(self, ids):
            self.filter = ids

    from inspektor_gadget_tpu.gadgets.interface import MountNsFilterSetter
    g = FakeGadget()
    assert isinstance(g, MountNsFilterSetter)
    inst = km.instantiate(ctx, g, params)
    inst.pre_gadget_run()
    assert g.filter == {555001}
    inst.post_gadget_run()
    lm.cc.remove_container("km1")
    lm.cc.remove_container("km2")


def test_dnstester_builds_valid_query():
    from tools.dnstester import build_query

    pkt = build_query("a.example.com", qtype=28)
    assert pkt[:2] == b"\x12\x34"
    assert b"\x01a\x07example\x03com\x00" in pkt
    assert pkt.endswith(b"\x00\x1c\x00\x01")  # AAAA, IN


def test_runtime_client_detection_degrades():
    from inspektor_gadget_tpu.containers.runtime_client import (
        DockerClient, detect_runtime_client, with_runtime_enrichment)
    from inspektor_gadget_tpu.containers import ContainerCollection

    # no docker socket in this environment → probe must degrade cleanly
    assert DockerClient("/nonexistent.sock").available() is False
    detect_runtime_client()  # must not raise
    cc = ContainerCollection()
    cc.initialize(with_runtime_enrichment())  # silent no-op
    assert len(cc) >= 0


def test_windowed_example_scripts_importable():
    import examples.sketch_pipeline
    import examples.custom_gadget  # registers trace/heartbeat
    from inspektor_gadget_tpu.gadgets import get
    assert get("trace", "heartbeat").description


def test_baseline_configs_bench_emits_records(capsys):
    """benchmarks/configs.py: each BASELINE config emits one JSON record
    with platform + metric (driver-runnable; short window here)."""
    import json as _json

    from benchmarks.configs import main as configs_main

    rc = configs_main(["--seconds", "0.3", "--configs", "2,3,5"])
    assert rc == 0
    recs = [_json.loads(line)
            for line in capsys.readouterr().out.strip().splitlines()]
    by_cfg = {r["config"]: r for r in recs}
    assert set(by_cfg) == {2, 3, 5}
    assert all("platform" in r and "error" not in r for r in recs)
    # sketch accuracy invariants hold even at a short window
    assert by_cfg[2]["value"] < 0.05          # HLL distinct error
    assert by_cfg[3]["value"] < 0.01          # heavy-hitter error
    assert by_cfg[5]["value"] < 50.0          # merge p50 ms target
