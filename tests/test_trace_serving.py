"""Trace-resource serving: the §3.5 call stack against a live agent, plus
the kube-API-backed controller loop against a fake apiserver.

Reference tiers modeled: cmd/kubectl-gadget/utils/trace.go:340-848 (client
creates a Trace, sets operation annotations, waits on status) and
pkg/controllers/suite_test.go (reconciler against a real apiserver — here
an in-process HTTP one serving/storing CR-shaped documents).
"""

import json
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.agent.client import AgentClient
from inspektor_gadget_tpu.agent.service import serve
from inspektor_gadget_tpu.gadgets.trace_resource import (
    OPERATION_ANNOTATION,
    STATE_COMPLETED,
    STATE_STARTED,
    TraceStore,
    TraceWatcher,
    trace_from_doc,
    trace_to_doc,
)
from inspektor_gadget_tpu.utils.k8s import KubeClient


@pytest.fixture(scope="module")
def agent():
    tmp = tempfile.mkdtemp()
    addr = f"unix://{tmp}/agent.sock"
    server, agent_obj = serve(addr, node_name="node-t")
    yield addr
    server.stop(grace=0.5)


def _start_doc(name, gadget, params=None, node=""):
    return {
        "metadata": {"name": name,
                     "annotations": {OPERATION_ANNOTATION: "start"}},
        "spec": {"gadget": gadget, "node": node,
                 "parameters": params or {"source": "pysynthetic",
                                          "rate": "20000"}},
    }


def _op_doc(name, op):
    return {"metadata": {"name": name,
                         "annotations": {OPERATION_ANNOTATION: op}}}


def test_doc_roundtrip():
    doc = _start_doc("t", "trace/exec")
    trace = trace_from_doc(doc)
    assert trace.spec.gadget == "trace/exec"
    back = trace_to_doc(trace)
    assert back["spec"]["parameters"]["source"] == "pysynthetic"
    assert back["metadata"]["annotations"][OPERATION_ANNOTATION] == "start"


def test_agent_serves_advise_lifecycle(agent):
    """§3.5 end to end over RPC: start records, generate parks the OCI
    seccomp JSON in status.output (seccomp factory contract)."""
    client = AgentClient(agent, "node-t")
    doc = client.apply_trace(_start_doc("adv1", "advise/seccomp-profile"))
    assert doc["status"]["state"] == STATE_STARTED
    assert doc["metadata"]["annotations"] == {}  # operation consumed
    assert any(t["metadata"]["name"] == "adv1" for t in client.list_traces())
    time.sleep(0.6)
    doc = client.apply_trace(_op_doc("adv1", "generate"))
    assert doc["status"]["state"] == STATE_COMPLETED, doc["status"]
    profiles = json.loads(doc["status"]["output"])
    assert profiles and "defaultAction" in next(iter(profiles.values()))
    # the completed trace is fetchable until deleted
    assert client.get_trace("adv1")["status"]["state"] == STATE_COMPLETED
    assert client.delete_trace("adv1") is True
    with pytest.raises(RuntimeError, match="not found"):
        client.get_trace("adv1")
    client.close()


def test_agent_serves_traceloop(agent):
    """traceloop rides the same path (ref: main.go:72 legacy commands)."""
    client = AgentClient(agent, "node-t")
    doc = client.apply_trace(_start_doc("tl1", "traceloop/traceloop"))
    assert doc["status"]["state"] == STATE_STARTED
    time.sleep(0.6)
    doc = client.apply_trace(_op_doc("tl1", "generate"))
    assert doc["status"]["state"] == STATE_COMPLETED, doc["status"]
    assert "SYSCALL" in doc["status"]["output"]  # rendered syscall table
    client.delete_trace("tl1")
    client.close()


def test_agent_reports_operation_error(agent):
    client = AgentClient(agent, "node-t")
    doc = client.apply_trace(_op_doc("ghost", "stop"))
    assert "not running" in doc["status"]["operationError"]
    # an operation on a never-created name must not mint a phantom resource
    assert all(t["metadata"]["name"] != "ghost" for t in client.list_traces())
    client.close()


def test_stop_then_restart_and_spec_retry(agent):
    """A stopped name is restartable, and a failed start can be retried
    with a corrected spec (spec update allowed while not running)."""
    client = AgentClient(agent, "node-t")
    bad = _start_doc("retry1", "advise/no-such-gadget")
    doc = client.apply_trace(bad)
    assert doc["status"]["operationError"]
    doc = client.apply_trace(_start_doc("retry1", "trace/exec"))
    assert doc["status"]["state"] == STATE_STARTED, doc["status"]
    # spec update against a RUNNING trace is rejected loudly
    doc = client.apply_trace(_start_doc("retry1", "trace/tcp"))
    assert "spec update rejected" in doc["status"]["operationError"]
    doc = client.apply_trace(_op_doc("retry1", "stop"))
    assert doc["status"]["state"] == "Stopped"
    doc = client.apply_trace(_op_doc("retry1", "start"))
    assert doc["status"]["state"] == STATE_STARTED
    client.delete_trace("retry1")
    client.close()


def test_node_filter_no_phantom(agent):
    """A trace pinned to another node is neither run nor stored."""
    client = AgentClient(agent, "node-t")
    doc = client.apply_trace(_start_doc("elsewhere", "trace/exec",
                                        node="node-other"))
    assert doc["status"]["state"] == ""
    assert doc["metadata"]["annotations"].get(OPERATION_ANNOTATION) == "start"
    assert all(t["metadata"]["name"] != "elsewhere"
               for t in client.list_traces())
    client.close()


def test_delete_stops_running_trace(agent):
    client = AgentClient(agent, "node-t")
    client.apply_trace(_start_doc("run1", "trace/exec"))
    assert client.delete_trace("run1") is True
    assert all(t["metadata"]["name"] != "run1" for t in client.list_traces())
    client.close()


def test_cli_traces_verbs(agent, capsys):
    """The kubectl-gadget advise ergonomics through `ig-tpu traces`."""
    from inspektor_gadget_tpu.cli.main import main as cli_main

    remote = f"node-t={agent}"
    assert cli_main(["traces", "start", "--remote", remote, "--name", "c1",
                     "--gadget", "advise/seccomp-profile",
                     "-p", "source=pysynthetic", "-p", "rate=20000"]) == 0
    out = capsys.readouterr().out
    assert "c1 Started" in out
    time.sleep(0.6)
    assert cli_main(["traces", "list", "--remote", remote]) == 0
    assert "advise/seccomp-profile" in capsys.readouterr().out
    assert cli_main(["traces", "generate", "--remote", remote,
                     "--name", "c1"]) == 0
    out = capsys.readouterr().out
    assert "defaultAction" in out
    assert cli_main(["traces", "delete", "--remote", remote,
                     "--name", "c1"]) == 0


# -- kube-API-backed controller loop (fake apiserver tier) ------------------

class _FakeTraceApi(BaseHTTPRequestHandler):
    """CR-shaped document store: GET list, PUT single resource.

    Failure-mode knobs (VERDICT #9 — the rejections a live apiserver
    actually issues):
      conflict_puts   — reject the next N main-resource PUTs with a 409
                        and bump the stored resourceVersion, simulating a
                        concurrent writer landing between the caller's
                        poll and its PUT.
      status_subresource — reject main-resource PUTs that modify status
                        with a 422 naming the status subresource; status
                        then only lands via PUT <name>/status.
      enforce_versions — reject any main-resource PUT whose
                        resourceVersion is not current (409), like a
                        real apiserver; /status writes bump the version,
                        so a split write MUST re-poll before its main
                        PUT.
    """

    store: dict = {}
    puts: list = []
    rejects: list = []
    versions: dict = {}
    conflict_puts: int = 0
    status_subresource: bool = False
    enforce_versions: bool = False

    def _send(self, body: dict):
        data = json.dumps(body).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        if self.path.endswith("/traces"):
            self._send({"items": list(_FakeTraceApi.store.values())})
        else:
            name = self.path.rpartition("/")[2]
            if name in _FakeTraceApi.store:
                self._send(_FakeTraceApi.store[name])
            else:
                self.send_error(404)

    @classmethod
    def _stamp(cls, name: str, doc: dict) -> dict:
        ver = cls.versions.get(name, 0) + 1
        cls.versions[name] = ver
        doc = {**doc, "metadata": {**doc.get("metadata", {}),
                                   "resourceVersion": str(ver)}}
        return doc

    def do_PUT(self):
        cls = _FakeTraceApi
        n = int(self.headers.get("Content-Length", 0))
        doc = json.loads(self.rfile.read(n))
        is_status = self.path.endswith("/status")
        name = self.path.rpartition("/")[2]
        if is_status:
            name = self.path.rsplit("/", 2)[1]
            stored = cls.store.get(name, {})
            merged = cls._stamp(name, {**stored,
                                       "status": doc.get("status", {})})
            cls.store[name] = merged
            cls.puts.append((name + "/status", merged))
            self._send(merged)
            return
        if cls.conflict_puts > 0:
            cls.conflict_puts -= 1
            sent = doc.get("metadata", {}).get("resourceVersion", "")
            cls.rejects.append((name, sent))
            # the concurrent writer that caused the conflict: stored copy
            # advances (new resourceVersion) AND gains an annotation the
            # retry must not clobber
            cur = cls.store.get(name, doc)
            meta = cur.get("metadata", {})
            cur = {**cur, "metadata": {
                **meta, "annotations": {**meta.get("annotations", {}),
                                        "concurrent/marker": "added"}}}
            cls.store[name] = cls._stamp(name, cur)
            self.send_error(409, "Conflict",
                            f"resourceVersion mismatch: sent {sent!r}")
            return
        if cls.enforce_versions:
            sent = doc.get("metadata", {}).get("resourceVersion", "")
            if sent != str(cls.versions.get(name, 0)):
                cls.rejects.append((name, sent))
                self.send_error(409, "Conflict",
                                f"resourceVersion mismatch: sent {sent!r}")
                return
        if cls.status_subresource:
            stored_status = cls.store.get(name, {}).get("status") or {}
            sent_status = doc.get("status")
            if sent_status is not None and sent_status != stored_status:
                self.send_error(
                    422, "Unprocessable Entity",
                    "may not modify status on the main resource; "
                    "use the status subresource")
                return
            # a main PUT without status leaves the stored status intact
            doc = {**doc, "status": stored_status}
        doc = cls._stamp(name, doc)
        cls.store[name] = doc
        cls.puts.append((name, doc))
        self._send(doc)

    def log_message(self, *a):
        pass


@pytest.fixture()
def fake_trace_api():
    server = HTTPServer(("127.0.0.1", 0), _FakeTraceApi)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    _FakeTraceApi.store = {}
    _FakeTraceApi.puts = []
    _FakeTraceApi.rejects = []
    _FakeTraceApi.versions = {}
    _FakeTraceApi.conflict_puts = 0
    _FakeTraceApi.status_subresource = False
    _FakeTraceApi.enforce_versions = False
    yield f"http://127.0.0.1:{server.server_port}"
    server.shutdown()


def test_watcher_reconciles_from_apiserver(fake_trace_api):
    """trace_controller.go:100 against a (fake) apiserver: annotation in,
    status written back, node filter honored."""
    store = TraceStore(node_name="node-w")
    watcher = TraceWatcher(KubeClient(server=fake_trace_api), store,
                           namespace="ig-tpu")

    _FakeTraceApi.store["k1"] = _start_doc("k1", "advise/seccomp-profile")
    # a trace pinned to another node must be left alone (ref: :172-175)
    _FakeTraceApi.store["other"] = _start_doc(
        "other", "trace/exec", node="node-elsewhere")

    assert watcher.poll_once() == 1
    written = _FakeTraceApi.store["k1"]
    assert written["status"]["state"] == STATE_STARTED
    assert OPERATION_ANNOTATION not in written["metadata"]["annotations"]
    assert _FakeTraceApi.store["other"].get("status") is None

    # idempotent: no annotation left → nothing served
    assert watcher.poll_once() == 0

    time.sleep(0.6)
    _FakeTraceApi.store["k1"]["metadata"]["annotations"][
        OPERATION_ANNOTATION] = "generate"
    assert watcher.poll_once() == 1
    written = _FakeTraceApi.store["k1"]
    assert written["status"]["state"] == STATE_COMPLETED, written["status"]
    profiles = json.loads(written["status"]["output"])
    assert "defaultAction" in next(iter(profiles.values()))


def test_watcher_reports_bad_operation(fake_trace_api):
    store = TraceStore(node_name="node-w")
    watcher = TraceWatcher(KubeClient(server=fake_trace_api), store)
    doc = _start_doc("bad", "no-such/gadget")
    _FakeTraceApi.store["bad"] = doc
    assert watcher.poll_once() == 1
    written = _FakeTraceApi.store["bad"]
    assert written["status"]["operationError"]


def test_watcher_retries_on_resource_version_conflict(fake_trace_api):
    """VERDICT #9: a 409 between poll and PUT must re-poll and retry with
    the fresh resourceVersion, not drop the write-back (a dropped write
    leaves the consumed operation annotation in the apiserver, re-firing
    the operation forever)."""
    store = TraceStore(node_name="node-w")
    watcher = TraceWatcher(KubeClient(server=fake_trace_api), store)
    _FakeTraceApi.store["c1"] = _start_doc("c1", "trace/exec")
    _FakeTraceApi.conflict_puts = 2  # two concurrent-writer collisions

    assert watcher.poll_once() == 1
    written = _FakeTraceApi.store["c1"]
    assert written["status"]["state"] == STATE_STARTED
    assert OPERATION_ANNOTATION not in written["metadata"]["annotations"]
    # the concurrent writer's annotation survived the retry (the re-poll
    # grafts our update onto the FRESH metadata, not the stale snapshot)
    assert written["metadata"]["annotations"].get(
        "concurrent/marker") == "added"
    # both rejections were observed, and the accepted retry carried the
    # version the second concurrent writer left behind
    assert len(_FakeTraceApi.rejects) == 2
    accepted = [d for n, d in _FakeTraceApi.puts if n == "c1"]
    assert accepted, "writeback was dropped instead of retried"
    final_sent = _FakeTraceApi.rejects[-1][1]  # second attempt's version
    assert final_sent != _FakeTraceApi.rejects[0][1], (
        "retry did not re-poll: same stale resourceVersion sent twice")
    # the annotation is consumed server-side: the next poll serves nothing
    assert watcher.poll_once() == 0
    store.delete("c1")


def test_watcher_conflict_gives_up_after_bounded_retries(fake_trace_api):
    """Unbounded conflict (a writer that always wins) must not spin the
    reconciler forever; the cycle gives up and the next poll retries."""
    store = TraceStore(node_name="node-w")
    watcher = TraceWatcher(KubeClient(server=fake_trace_api), store)
    _FakeTraceApi.store["c2"] = _start_doc("c2", "trace/exec")
    _FakeTraceApi.conflict_puts = 10_000  # always-conflicting apiserver
    assert watcher.poll_once() == 0
    # 1 initial attempt + WRITE_RETRIES retries, no more
    assert len(_FakeTraceApi.rejects) == 1 + TraceWatcher.WRITE_RETRIES
    store.delete("c2")


def test_watcher_splits_write_on_status_subresource_rejection(fake_trace_api):
    """A 422 naming the status subresource routes the write through
    PUT <name> (spec/annotations) + PUT <name>/status, like the real
    controller's Status().Update split."""
    store = TraceStore(node_name="node-w")
    watcher = TraceWatcher(KubeClient(server=fake_trace_api), store)
    _FakeTraceApi.store["s1"] = _start_doc("s1", "advise/seccomp-profile")
    _FakeTraceApi.status_subresource = True

    assert watcher.poll_once() == 1
    written = _FakeTraceApi.store["s1"]
    assert written["status"]["state"] == STATE_STARTED, written.get("status")
    assert OPERATION_ANNOTATION not in written["metadata"]["annotations"]
    # the split actually happened: one status-subresource PUT landed
    assert any(n == "s1/status" for n, _ in _FakeTraceApi.puts)

    time.sleep(0.6)
    _FakeTraceApi.store["s1"]["metadata"]["annotations"][
        OPERATION_ANNOTATION] = "generate"
    assert watcher.poll_once() == 1
    written = _FakeTraceApi.store["s1"]
    assert written["status"]["state"] == STATE_COMPLETED, written["status"]
    assert json.loads(written["status"]["output"])
    store.delete("s1")


def test_watcher_split_write_survives_status_version_bump(fake_trace_api):
    """Real-apiserver shape: /status writes bump resourceVersion, so the
    split write's follow-up main PUT starts stale — it must re-poll and
    retry (409) instead of leaving the annotation to re-fire forever."""
    store = TraceStore(node_name="node-w")
    watcher = TraceWatcher(KubeClient(server=fake_trace_api), store)
    seeded = _FakeTraceApi._stamp("sv1", _start_doc("sv1", "trace/exec"))
    _FakeTraceApi.store["sv1"] = seeded
    _FakeTraceApi.status_subresource = True
    _FakeTraceApi.enforce_versions = True

    assert watcher.poll_once() == 1
    written = _FakeTraceApi.store["sv1"]
    assert written["status"]["state"] == STATE_STARTED, written.get("status")
    assert OPERATION_ANNOTATION not in written["metadata"]["annotations"]
    # the stale main PUT was rejected once and retried with the bumped
    # version (not dropped): one 409 on record, then success
    assert any(n == "sv1" for n, _ in _FakeTraceApi.rejects)
    assert any(n == "sv1/status" for n, _ in _FakeTraceApi.puts)
    # the annotation is consumed: the next poll serves nothing (no
    # infinite reconcile loop)
    assert watcher.poll_once() == 0
    store.delete("sv1")


def test_watcher_background_loop(fake_trace_api):
    store = TraceStore(node_name="node-w")
    watcher = TraceWatcher(KubeClient(server=fake_trace_api), store,
                           interval=0.05)
    watcher.start()
    try:
        _FakeTraceApi.store["bg"] = _start_doc("bg", "trace/exec")
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            if _FakeTraceApi.store["bg"].get("status", {}).get("state"):
                break
            time.sleep(0.05)
        assert _FakeTraceApi.store["bg"]["status"]["state"] == STATE_STARTED
    finally:
        watcher.stop()
    store.delete("bg")
