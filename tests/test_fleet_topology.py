"""Fleet merge-tree topology (ISSUE 20): the declared zone grammar,
the auto-balancer's O(log N) shape, and the loud TopologyError
validation that keeps every tree an exactly-once fold over the roster
— a spec that would double-count, invent, or silently omit an agent
must refuse to parse, never fold wrong."""

from __future__ import annotations

import pytest

from inspektor_gadget_tpu.fleet import (
    Topology,
    TopologyError,
    TreeNode,
    auto_topology,
    parse_topology,
)

NODES = [f"n{i:03d}" for i in range(100)]


# ---------------------------------------------------------------------------
# auto-balancer
# ---------------------------------------------------------------------------

def test_auto_100_agents_fan4_is_log_depth():
    topo = auto_topology(NODES, fan_in=4)
    # level sizes 100 → 25 → 7 → 2 → 1
    assert topo.depth() == 4
    assert topo.fan_in() == 4
    assert topo.leaves() == sorted(NODES)
    # 25 + 6 + 2 + 1 aggregators (remainder chunks promote, not wrap)
    assert len(topo.aggregators()) == 34
    # every vertex but the root ships one summary frame up
    assert topo.edges() == 100 + 34 - 1
    assert topo.root.id == "fleet"


def test_auto_leaves_are_exactly_once_and_in_canonical_order():
    import random
    shuffled = NODES[:]
    random.Random(7).shuffle(shuffled)
    topo = auto_topology(shuffled, fan_in=4)
    # roster order can't leak: leaves come out sorted, each exactly once
    assert topo.leaves() == sorted(NODES)


@pytest.mark.parametrize("fan_in", [2, 3, 4, 8])
@pytest.mark.parametrize("n", [1, 2, 5, 9, 17, 64, 100])
def test_auto_every_aggregator_folds_at_least_two(n, fan_in):
    topo = auto_topology(NODES[:n], fan_in=fan_in)
    assert sorted(topo.leaves()) == sorted(NODES[:n])
    for agg in topo.aggregators():
        if n == 1:
            assert len(agg.children) == 1  # single-agent root folds one
        else:
            # a run of one is promoted, never wrapped — a single-child
            # aggregator would add a hop and fold nothing
            assert len(agg.children) >= 2
        assert len(agg.children) <= fan_in


def test_auto_promotes_remainder_chunk():
    # 5 agents, fan-in 4: [n000..n003] fold under one aggregator, n004
    # is promoted to sit beside it under the root
    topo = auto_topology(NODES[:5], fan_in=4)
    assert topo.depth() == 2
    kinds = [c.is_leaf for c in topo.root.children]
    assert kinds == [False, True]
    assert topo.root.children[1].id == "n004"


def test_auto_single_agent_still_aggregates():
    topo = auto_topology(["solo"])
    assert topo.root.id == "fleet"
    assert topo.leaves() == ["solo"]
    assert topo.depth() == 1


def test_auto_rejects_degenerate_inputs():
    with pytest.raises(TopologyError, match="fan-in must be >= 2"):
        auto_topology(NODES[:4], fan_in=1)
    with pytest.raises(TopologyError, match="no agents"):
        auto_topology([])


def test_auto_chunk_ids_sort_in_chunk_order():
    # 100 leaves at fan-in 2 puts 50 chunks on one level: zero-padded
    # ids keep display sorts aligned with chunk order (agg1-002 before
    # agg1-010)
    topo = auto_topology(NODES, fan_in=2)
    ids = [a.id for a in topo.aggregators() if a.id.startswith("agg1-")]
    assert ids == sorted(ids)
    assert len(ids) == 50


# ---------------------------------------------------------------------------
# spec parsing
# ---------------------------------------------------------------------------

def test_parse_auto_specs():
    assert parse_topology("auto", NODES[:8]).fan_in() == 4
    assert parse_topology("", NODES[:8]).fan_in() == 4  # default
    assert parse_topology("auto:8", NODES[:9]).fan_in() == 8
    with pytest.raises(TopologyError, match="auto:<int>"):
        parse_topology("auto:x", NODES[:8])


def test_declared_flat_zones():
    topo = parse_topology("zone-a=n000,n001;zone-b=n002,n003", NODES[:4])
    assert [c.id for c in topo.root.children] == ["zone-a", "zone-b"]
    assert topo.leaves() == ["n000", "n001", "n002", "n003"]
    assert topo.depth() == 2
    assert topo.fan_in() == 2


def test_declared_nested_zone_paths():
    topo = parse_topology(
        "dc1/rack-a=n000,n001;dc1/rack-b=n002;dc2=n003", NODES[:4])
    dc1 = topo.root.children[0]
    assert dc1.id == "dc1"
    assert [c.id for c in dc1.children] == ["rack-a", "rack-b"]
    assert topo.depth() == 3
    # fleet, dc1, rack-a, rack-b, dc2
    assert topo.to_dict()["aggregators"] == 5


@pytest.mark.parametrize("spec,match", [
    ("z1=n000,n000;z2=n001", "assigned twice"),
    ("z1=n000,n001;z2=n000", "assigned twice"),
    ("z1=n000,nope", "unknown agent"),
    ("z1=n000", "not placed in any zone"),
    ("a=n000;b/a=n001", "reused"),
    ("n000=n000,n001", "collide with agent names"),
    (";;", "empty topology spec"),
    ("zone-a", "bad clause"),
    ("zone-a=", "no members"),
    ("/=n000", "bad zone path"),
])
def test_declared_validation_refuses(spec, match):
    with pytest.raises(TopologyError, match=match):
        parse_topology(spec, NODES[:2])


def test_to_dict_shape():
    d = auto_topology(NODES[:8], fan_in=4).to_dict()
    assert d["leaves"] == 8
    assert d["depth"] == 2
    assert d["fan_in"] == 4
    assert d["edges"] == 10  # 8 leaf edges + 2 zone edges
    assert set(d) == {"root", "leaves", "aggregators", "depth",
                      "fan_in", "edges"}


def test_validate_catches_hand_built_double_count():
    n0 = TreeNode("n000")
    tree = Topology(TreeNode("fleet", (TreeNode("a", (n0,)),
                                      TreeNode("b", (n0,)))))
    from inspektor_gadget_tpu.fleet.topology import _validate
    with pytest.raises(TopologyError, match="assigned twice"):
        _validate(tree, ["n000"])
