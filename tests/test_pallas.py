"""Pallas kernel tests (CPU: the XLA reference path; the TPU kernel itself
is exercised by bench.py and verified equal on hardware)."""

import numpy as np
import jax.numpy as jnp

from inspektor_gadget_tpu.ops.pallas_kernels import xla_histogram
from inspektor_gadget_tpu.ops.entropy import entropy_init, entropy_update
from inspektor_gadget_tpu.ops.hashing import multiply_shift


def test_xla_histogram_matches_manual():
    rng = np.random.default_rng(0)
    keys = jnp.asarray(rng.integers(0, 2**32, 4096, dtype=np.uint32))
    w = jnp.ones(4096, jnp.float32)
    h = xla_histogram(keys, w, log2_width=10)
    assert float(h.sum()) == 4096
    # same hash family as the sketch plane's row 0
    idx = multiply_shift(keys, 0, 10)
    manual = np.zeros(1024, np.float32)
    np.add.at(manual, np.asarray(idx), 1.0)
    np.testing.assert_array_equal(np.asarray(h), manual)


def test_entropy_update_consistent_across_backends():
    # on CPU this takes the scatter path; sums and estimates must agree
    keys = jnp.arange(512, dtype=jnp.uint32)
    e = entropy_update(entropy_init(10), keys)
    assert float(e.counts.sum()) == 512
