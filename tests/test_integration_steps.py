"""Cluster-integration-tier tests via the step framework.

Model: integration/inspektor-gadget/trace_exec_test.go:26-90 and siblings —
each test is a list of steps (gadget command, workload, cleanup) run with
RunTestSteps, asserting on normalized JSON events. Here the CLI is the
built binary and synthetic sources are the workload generators.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from inspektor_gadget_tpu.testing import (
    Command,
    FuncStep,
    build_common_data,
    expect_all_entries_to_match,
    expect_entries_in_array_to_match,
    expect_entries_to_match,
    run_test_steps,
)
from inspektor_gadget_tpu.testing.steps import StepError, ig_cli


def normalize_trace(e: dict) -> None:
    """Zero unpredictable fields (ref: trace_exec_test.go normalize fn)."""
    for k in ("timestamp", "pid", "ppid", "uid", "mountnsid", "tid"):
        e.pop(k, None)


def test_trace_exec_steps():
    def check(output: str) -> None:
        expect_entries_to_match(
            output, normalize_trace,
            {"comm": "proc-0", "type": "normal", **build_common_data()},
        )

    steps = [
        Command(
            name="trace-exec",
            cmd=ig_cli("trace", "exec", "--source", "pysynthetic",
                       "--rate", "5000", "-o", "json"),
            start_and_stop=True,
            expected_output_fn=check,
        ),
    ]
    run_test_steps(steps, step_wait=2.0)


def test_trace_exec_filter_all_match():
    cmd = Command(
        name="trace-exec-filtered",
        cmd=ig_cli("trace", "exec", "--source", "pysynthetic",
                   "--rate", "5000", "-F", "comm:proc-1", "-o", "json"),
        start_and_stop=True,
        expected_output_fn=lambda out: expect_all_entries_to_match(
            out, normalize_trace, {"comm": "proc-1"}),
    )
    run_test_steps([cmd], step_wait=2.0)


def test_snapshot_process_steps():
    me = os.path.basename(sys.executable)[:16]

    def check(output: str) -> None:
        entries = [e for e in json.loads(output)
                   if e["pid"] == os.getpid() or "py" in e["comm"]]
        assert entries, "test process not in snapshot"

    run_test_steps([
        Command(name="snapshot-process",
                cmd=ig_cli("snapshot", "process", "-o", "json"),
                expected_output_fn=check),
    ])


def test_snapshot_socket_array_match():
    # open a listening socket as the workload, then snapshot
    import socket as socklib

    srv = socklib.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def normalize(e: dict) -> None:
        e.pop("netnsid", None)

    try:
        run_test_steps([
            Command(
                name="snapshot-socket",
                cmd=ig_cli("snapshot", "socket", "--proto", "tcp",
                           "-o", "json"),
                expected_output_fn=lambda out: expect_entries_in_array_to_match(
                    out, normalize,
                    {"protocol": "tcp", "status": "LISTEN",
                     "localport": port}),
            ),
        ])
    finally:
        srv.close()


def test_cleanup_runs_after_failure():
    ran = {"cleanup": False}
    steps = [
        FuncStep(name="boom", fn=lambda: (_ for _ in ()).throw(
            StepError("induced failure"))),
        FuncStep(name="never-runs", fn=lambda: pytest.fail(
            "step after failure must not run")),
        FuncStep(name="cleanup", fn=lambda: ran.__setitem__("cleanup", True),
                 cleanup=True),
    ]
    with pytest.raises(StepError, match="induced"):
        run_test_steps(steps)
    assert ran["cleanup"], "cleanup step must run even after a failure"


def test_start_and_stop_kill_on_failure():
    # a started step is killed (not left running) when a later step fails
    cmd = Command(
        name="stream",
        cmd=ig_cli("trace", "exec", "--source", "pysynthetic",
                   "--rate", "100", "-o", "json"),
        start_and_stop=True,
    )
    with pytest.raises(StepError, match="later"):
        run_test_steps([
            cmd,
            FuncStep(name="fail", fn=lambda: (_ for _ in ()).throw(
                StepError("later step failed"))),
        ])
    assert not cmd.running
    assert cmd._proc.poll() is not None, "subprocess must be reaped"


def test_expected_regexp_and_string():
    run_test_steps([
        Command(name="version", cmd=ig_cli("version"),
                expected_regexp=r"^ig-tpu \d"),
    ])
    with pytest.raises(StepError, match="regexp"):
        run_test_steps([
            Command(name="version-bad", cmd=ig_cli("version"),
                    expected_regexp=r"^not-the-version"),
        ])


def test_profile_cpu_json_output():
    r = subprocess.run(ig_cli("profile", "cpu", "--timeout", "1",
                              "-o", "json"),
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    rows = json.loads(r.stdout)
    assert isinstance(rows, list)
    if rows:
        assert "comm" in rows[0] and "samples" in rows[0]
