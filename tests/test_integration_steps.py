"""Cluster-integration-tier tests via the step framework.

Model: integration/inspektor-gadget/trace_exec_test.go:26-90 and siblings —
each test is a list of steps (gadget command, workload, cleanup) run with
RunTestSteps, asserting on normalized JSON events. Here the CLI is the
built binary and synthetic sources are the workload generators.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from inspektor_gadget_tpu.testing import (
    Command,
    FuncStep,
    build_common_data,
    expect_all_entries_to_match,
    expect_entries_in_array_to_match,
    expect_entries_to_match,
    run_test_steps,
)
from inspektor_gadget_tpu.testing.steps import StepError, ig_cli


def normalize_trace(e: dict) -> None:
    """Zero unpredictable fields (ref: trace_exec_test.go normalize fn)."""
    for k in ("timestamp", "pid", "ppid", "uid", "mountnsid", "tid"):
        e.pop(k, None)


def test_trace_exec_steps():
    def check(output: str) -> None:
        expect_entries_to_match(
            output, normalize_trace,
            {"comm": "proc-0", "type": "normal", **build_common_data()},
        )

    steps = [
        Command(
            name="trace-exec",
            cmd=ig_cli("trace", "exec", "--source", "pysynthetic",
                       "--rate", "5000", "-o", "json"),
            start_and_stop=True,
            expected_output_fn=check,
        ),
    ]
    run_test_steps(steps, step_wait=2.0)


def test_trace_exec_filter_all_match():
    cmd = Command(
        name="trace-exec-filtered",
        cmd=ig_cli("trace", "exec", "--source", "pysynthetic",
                   "--rate", "5000", "-F", "comm:proc-1", "-o", "json"),
        start_and_stop=True,
        expected_output_fn=lambda out: expect_all_entries_to_match(
            out, normalize_trace, {"comm": "proc-1"}),
    )
    run_test_steps([cmd], step_wait=2.0)


def test_snapshot_process_steps():
    me = os.path.basename(sys.executable)[:16]

    def check(output: str) -> None:
        entries = [e for e in json.loads(output)
                   if e["pid"] == os.getpid() or "py" in e["comm"]]
        assert entries, "test process not in snapshot"

    run_test_steps([
        Command(name="snapshot-process",
                cmd=ig_cli("snapshot", "process", "-o", "json"),
                expected_output_fn=check),
    ])


def test_snapshot_socket_array_match():
    # open a listening socket as the workload, then snapshot
    import socket as socklib

    srv = socklib.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def normalize(e: dict) -> None:
        e.pop("netnsid", None)

    try:
        run_test_steps([
            Command(
                name="snapshot-socket",
                cmd=ig_cli("snapshot", "socket", "--proto", "tcp",
                           "-o", "json"),
                expected_output_fn=lambda out: expect_entries_in_array_to_match(
                    out, normalize,
                    {"protocol": "tcp", "status": "LISTEN",
                     "localport": port}),
            ),
        ])
    finally:
        srv.close()


def test_cleanup_runs_after_failure():
    ran = {"cleanup": False}
    steps = [
        FuncStep(name="boom", fn=lambda: (_ for _ in ()).throw(
            StepError("induced failure"))),
        FuncStep(name="never-runs", fn=lambda: pytest.fail(
            "step after failure must not run")),
        FuncStep(name="cleanup", fn=lambda: ran.__setitem__("cleanup", True),
                 cleanup=True),
    ]
    with pytest.raises(StepError, match="induced"):
        run_test_steps(steps)
    assert ran["cleanup"], "cleanup step must run even after a failure"


def test_start_and_stop_kill_on_failure():
    # a started step is killed (not left running) when a later step fails
    cmd = Command(
        name="stream",
        cmd=ig_cli("trace", "exec", "--source", "pysynthetic",
                   "--rate", "100", "-o", "json"),
        start_and_stop=True,
    )
    with pytest.raises(StepError, match="later"):
        run_test_steps([
            cmd,
            FuncStep(name="fail", fn=lambda: (_ for _ in ()).throw(
                StepError("later step failed"))),
        ])
    assert not cmd.running
    assert cmd._proc.poll() is not None, "subprocess must be reaped"


def test_expected_regexp_and_string():
    run_test_steps([
        Command(name="version", cmd=ig_cli("version"),
                expected_regexp=r"^ig-tpu \d"),
    ])
    with pytest.raises(StepError, match="regexp"):
        run_test_steps([
            Command(name="version-bad", cmd=ig_cli("version"),
                    expected_regexp=r"^not-the-version"),
        ])


needs_root = pytest.mark.skipif(os.geteuid() != 0, reason="needs root")


def _window(name: str) -> bool:
    from inspektor_gadget_tpu.sources import bridge
    fn = getattr(bridge, name, None)
    return bool(fn and fn())


@needs_root
def test_trace_tcp_host_wide_steps():
    """e2e tier for the event-driven tcp window: CLI subprocess + live
    loopback workload + JSON entry match (ref: integration
    trace_tcp_test.go shape)."""
    if not _window("sockstate_supported"):
        pytest.skip("inet_sock_set_state window unavailable")
    import socket as socklib
    import threading

    box = {}

    def workload():
        # the CLI subprocess needs several seconds to boot (jax import)
        # before it captures; keep connecting across that window
        ls = socklib.socket()
        ls.bind(("127.0.0.1", 0))
        ls.listen(8)
        box["port"] = ls.getsockname()[1]
        stop = threading.Event()

        def srv():
            while not stop.is_set():
                try:
                    ls.settimeout(0.5)
                    conn, _ = ls.accept()
                    conn.close()
                except OSError:
                    pass
        t = threading.Thread(target=srv)
        t.start()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            try:
                cs = socklib.create_connection(("127.0.0.1", box["port"]),
                                               timeout=1.0)
                cs.close()
            except OSError:
                pass
            time.sleep(0.4)
        stop.set()
        t.join()
        ls.close()
        time.sleep(0.5)

    def normalize(e: dict) -> None:
        for k in ("timestamp", "pid", "mountnsid", "netnsid", "comm",
                  "saddr", "daddr", "sport"):
            e.pop(k, None)
        # entries from other connections on the host are irrelevant
        if e.get("dport") != box.get("port"):
            e.clear()
            e["skip"] = True

    def check(output: str) -> None:
        expect_entries_to_match(
            output, normalize,
            {"operation": "connect", "ipversion": 4,
             "dport": box["port"], "type": "normal",
             **build_common_data()})

    run_test_steps([
        Command(name="trace-tcp",
                cmd=ig_cli("trace", "tcp", "--source", "native",
                           "-o", "json"),
                start_and_stop=True,
                expected_output_fn=check),
        FuncStep(name="workload", fn=workload),
    ], step_wait=1.0)


@needs_root
def test_trace_capabilities_host_wide_steps():
    """e2e tier for the host-wide capability window: CLI subprocess +
    unprivileged chown workload + JSON entry match."""
    if not (_window("captrace_supported") or _window("audit_supported")):
        pytest.skip("no host-wide capability window")
    target = "/tmp/ig_step_cap"

    def workload():
        # span the CLI subprocess's slow boot (jax import) with triggers
        open(target, "w").close()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            subprocess.run(
                ["setpriv", "--reuid", "65534", "--clear-groups",
                 "chown", "0:0", target],
                check=False, stderr=subprocess.DEVNULL)
            time.sleep(0.4)
        time.sleep(0.5)

    def normalize(e: dict) -> None:
        for k in ("timestamp", "pid", "uid", "mountnsid", "comm",
                  "audit"):
            e.pop(k, None)
        if not (e.get("cap") == "CHOWN" and e.get("verdict") == "deny"):
            e.clear()
            e["skip"] = True

    def check(output: str) -> None:
        expect_entries_to_match(
            output, normalize,
            {"cap": "CHOWN", "verdict": "deny", "type": "normal",
             **build_common_data()})

    try:
        run_test_steps([
            Command(name="trace-capabilities",
                    cmd=ig_cli("trace", "capabilities", "-o", "json"),
                    start_and_stop=True,
                    expected_output_fn=check),
            FuncStep(name="workload", fn=workload),
        ], step_wait=1.0)
    finally:
        try:
            os.unlink(target)
        except OSError:
            pass


def test_profile_cpu_json_output():
    r = subprocess.run(ig_cli("profile", "cpu", "--timeout", "1",
                              "-o", "json"),
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    rows = json.loads(r.stdout)
    assert isinstance(rows, list)
    if rows:
        assert "comm" in rows[0] and "samples" in rows[0]
