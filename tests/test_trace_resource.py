"""Legacy CRD-path tests (model: pkg/controllers/trace_controller_test.go
under envtest — here the reconciler runs in-process against the registry)."""

import json
import time

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.gadgets.trace_resource import (
    OPERATION_ANNOTATION,
    STATE_COMPLETED,
    STATE_STARTED,
    STATE_STOPPED,
    TraceReconciler,
    TraceResource,
    TraceSpec,
)


def make_trace(name="t1", gadget="advise/seccomp-profile", node=""):
    return TraceResource(
        name=name,
        spec=TraceSpec(node=node, gadget=gadget,
                       parameters={"source": "pysynthetic", "rate": "20000"}),
    )


def test_start_generate_lifecycle():
    r = TraceReconciler(node_name="node-a")
    tr = make_trace()
    tr.annotations[OPERATION_ANNOTATION] = "start"
    r.reconcile(tr)
    assert tr.status.state == STATE_STARTED and not tr.status.operation_error
    assert r.active() == ["t1"]
    time.sleep(0.5)
    tr.annotations[OPERATION_ANNOTATION] = "generate"
    r.reconcile(tr)
    assert tr.status.state == STATE_COMPLETED, tr.status.operation_error
    profiles = json.loads(tr.status.output)
    assert profiles and "defaultAction" in next(iter(profiles.values()))
    assert r.active() == []


def test_stop_operation():
    r = TraceReconciler()
    tr = make_trace(name="t2", gadget="trace/exec")
    tr.annotations[OPERATION_ANNOTATION] = "start"
    r.reconcile(tr)
    assert tr.status.state == STATE_STARTED
    tr.annotations[OPERATION_ANNOTATION] = "stop"
    r.reconcile(tr)
    assert tr.status.state == STATE_STOPPED


def test_node_filter_ignores_foreign_traces():
    r = TraceReconciler(node_name="node-a")
    tr = make_trace(name="t3", node="node-b")
    tr.annotations[OPERATION_ANNOTATION] = "start"
    r.reconcile(tr)
    assert tr.status.state == ""  # untouched
    assert r.active() == []


def test_bad_operation_reports_error():
    r = TraceReconciler()
    tr = make_trace(name="t4")
    tr.annotations[OPERATION_ANNOTATION] = "explode"
    r.reconcile(tr)
    assert "unsupported operation" in tr.status.operation_error


def test_double_start_rejected():
    r = TraceReconciler()
    tr = make_trace(name="t5", gadget="trace/exec")
    tr.annotations[OPERATION_ANNOTATION] = "start"
    r.reconcile(tr)
    tr.annotations[OPERATION_ANNOTATION] = "start"
    r.reconcile(tr)
    assert "already started" in tr.status.operation_error
    tr.annotations[OPERATION_ANNOTATION] = "stop"
    r.reconcile(tr)
