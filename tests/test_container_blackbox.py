"""The full §3.4 chain as ONE black box, from outside the process.

Reference tier: integration/ig/non-k8s drives the `ig` binary against real
containers (pkg/container-utils/testutils/docker.go:114), asserting on its
JSON output. Here the 'container' is an unshared-mount-namespace process
(internal/test/runner.go:103-218's technique), the binary is
`python -m inspektor_gadget_tpu.cli.main`, and the chain exercised is:
procfs discovery → selector match → per-container fanotify attach →
capture → mntns enrichment → JSON rows naming the container.
"""

import json
import os
import shutil
import subprocess
import sys
import time

import pytest

from inspektor_gadget_tpu.sources.bridge import native_available

NEEDS = pytest.mark.skipif(
    not native_available() or os.geteuid() != 0
    or not shutil.which("unshare"),
    reason="native capture / root / unshare unavailable")

COMM = "igbb-target"  # distinct comm so the selector matches only ours


@NEEDS
def test_trace_open_containername_black_box(tmp_path):
    # a copied shell gives the fake container a unique comm (the procfs
    # discovery names containers by comm)
    shell = tmp_path / COMM
    shutil.copy("/bin/bash", shell)
    shell.chmod(0o755)
    child = subprocess.Popen(
        ["unshare", "-m", str(shell), "-c",
         "mount -t tmpfs igbb /mnt; "
         "for i in $(seq 1 200); do echo hi > /mnt/igbb_file_$i; "
         "sleep 0.1; done"])
    try:
        time.sleep(1.0)  # container must exist before the CLI's scan
        proc = subprocess.run(
            [sys.executable, "-m", "inspektor_gadget_tpu.cli.main",
             "trace", "open", "--localmanager-containername", COMM,
             "--timeout", "5", "-o", "json"],
            capture_output=True, text=True, cwd="/root/repo", timeout=240)
    finally:
        child.kill()
        child.wait()
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = []
    for line in proc.stdout.splitlines():
        if line.startswith("{"):
            rows.append(json.loads(line))
    assert rows, proc.stdout[:2000] or proc.stderr[-2000:]
    mine = [r for r in rows if "igbb_file_" in r.get("path", "")]
    assert mine, sorted({r.get("path", "") for r in rows})[:10]
    # enrichment names the container on every row of its mntns
    assert any(r.get("container") == COMM for r in mine), mine[:3]
    # selector scoping: no rows from other mount namespaces leak in
    foreign = [r for r in rows
               if r.get("container") not in ("", COMM, None)]
    assert not foreign, foreign[:5]


@NEEDS
def test_trace_open_wrong_containername_sees_nothing(tmp_path):
    """Negative control (the reference's wrong-mntns test shape,
    tracer_test.go): a selector naming a nonexistent container must
    produce zero rows."""
    shell = tmp_path / COMM
    shutil.copy("/bin/bash", shell)
    shell.chmod(0o755)
    child = subprocess.Popen(
        ["unshare", "-m", str(shell), "-c",
         "mount -t tmpfs igbb /mnt; "
         "for i in $(seq 1 60); do echo hi > /mnt/igbb_neg_$i; "
         "sleep 0.1; done"])
    try:
        time.sleep(1.0)
        proc = subprocess.run(
            [sys.executable, "-m", "inspektor_gadget_tpu.cli.main",
             "trace", "open", "--localmanager-containername", "no-such-ctr",
             "--timeout", "3", "-o", "json"],
            capture_output=True, text=True, cwd="/root/repo", timeout=240)
    finally:
        child.kill()
        child.wait()
    rows = [json.loads(l) for l in proc.stdout.splitlines()
            if l.startswith("{")]
    assert not [r for r in rows if "igbb_neg_" in r.get("path", "")], rows[:5]
