"""Tier-1 docs-drift gate: the generated gadget table in docs/gadgets.md
must match the live registry (tools/gen_gadget_docs.py --check), exactly
like the bare-except and perf-claims lints — a registered gadget that
isn't in the docs (or a doc row whose gadget is gone) fails the suite.
Plus self-tests that the checker catches each drift mode."""

from __future__ import annotations

from pathlib import Path

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from tools.gen_gadget_docs import BEGIN, END, check, render_block, write

ROOT = Path(__file__).resolve().parent.parent


def test_repo_gadget_docs_match_registry():
    problems = check(ROOT / "docs" / "gadgets.md")
    assert not problems, "\n".join(problems)


def test_generated_table_covers_new_gadgets():
    block = render_block()
    # the gadget this PR added must be present — the exact rot VERDICT #8
    # called out
    assert "`top/alerts`" in block
    assert "`trace/exec`" in block


def test_checker_flags_drift(tmp_path):
    doc = tmp_path / "gadgets.md"
    write(doc)  # fresh block
    assert check(doc) == []
    # simulate a stale docs row: drop one generated line
    lines = doc.read_text().splitlines()
    pruned = [ln for ln in lines if "`top/alerts`" not in ln]
    doc.write_text("\n".join(pruned))
    (problem,) = check(doc)
    assert "drifted" in problem and "--write" in problem


def test_checker_flags_missing_markers(tmp_path):
    doc = tmp_path / "gadgets.md"
    doc.write_text("# hand-written only\n")
    (problem,) = check(doc)
    assert "missing" in problem


def test_write_repairs_and_preserves_prose(tmp_path):
    doc = tmp_path / "gadgets.md"
    doc.write_text(f"# intro prose\n\n{BEGIN}\nstale\n{END}\n\n## outro\n")
    assert write(doc) is True
    text = doc.read_text()
    assert check(doc) == []
    assert text.startswith("# intro prose")
    assert text.rstrip().endswith("## outro")
    # idempotent
    assert write(doc) is False
