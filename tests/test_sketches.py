"""Sketch-plane tests: accuracy bounds, mergeability, static-shape jit.

Accuracy targets from BASELINE.md: <1% heavy-hitter error; HLL standard
error ~1.04/sqrt(m) (p=14 → ~0.8%).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from inspektor_gadget_tpu.ops import (
    bundle_init, bundle_update, bundle_merge,
    cms_init, cms_update, cms_query, cms_merge,
    hll_init, hll_update, hll_estimate, hll_merge,
    entropy_init, entropy_update, entropy_estimate, entropy_merge,
    topk_init, topk_update, topk_merge,
    fold64_to_32,
)
from inspektor_gadget_tpu.ops.sketches import bundle_update_jit


def zipf_keys(rng, n, vocab=1000, a=1.5):
    return rng.zipf(a, size=n).clip(1, vocab).astype(np.uint32) * np.uint32(2654435761)


def test_fold64():
    k = np.array([0x123456789ABCDEF0], dtype=np.uint64)
    assert fold64_to_32(k)[0] == np.uint32(0x12345678 ^ 0x9ABCDEF0)


# -- count-min ---------------------------------------------------------------

def test_cms_exact_on_sparse():
    cms = cms_init(depth=4, log2_width=12)
    keys = jnp.array([1, 2, 3, 1, 1, 2], dtype=jnp.uint32)
    cms = cms_update(cms, keys)
    q = cms_query(cms, jnp.array([1, 2, 3, 99], dtype=jnp.uint32))
    assert q[0] == 3 and q[1] == 2 and q[2] == 1
    assert q[3] <= 1  # overestimate only, tiny on sparse table
    assert float(cms.total) == 6


def test_cms_weighted_and_masked():
    cms = cms_init(depth=4, log2_width=10)
    keys = jnp.array([5, 5, 7, 7], dtype=jnp.uint32)
    w = jnp.array([2, 3, 1, 0], dtype=jnp.int32)  # last slot masked out
    cms = cms_update(cms, keys, w)
    q = cms_query(cms, jnp.array([5, 7], dtype=jnp.uint32))
    assert q[0] == 5 and q[1] == 1


def test_cms_heavy_hitter_error_under_1pct():
    rng = np.random.default_rng(0)
    keys = zipf_keys(rng, 200_000)
    cms = cms_init(depth=4, log2_width=16)
    cms = cms_update(cms, jnp.asarray(keys))
    uniq, exact = np.unique(keys, return_counts=True)
    heavy = exact >= 0.001 * len(keys)
    est = np.asarray(cms_query(cms, jnp.asarray(uniq)))
    rel_err = np.abs(est[heavy] - exact[heavy]) / exact[heavy]
    assert rel_err.max() < 0.01


def test_cms_merge_equals_union():
    rng = np.random.default_rng(1)
    k1, k2 = zipf_keys(rng, 5000), zipf_keys(rng, 5000)
    a = cms_update(cms_init(4, 14), jnp.asarray(k1))
    b = cms_update(cms_init(4, 14), jnp.asarray(k2))
    merged = cms_merge(a, b)
    union = cms_update(cms_update(cms_init(4, 14), jnp.asarray(k1)), jnp.asarray(k2))
    assert jnp.array_equal(merged.table, union.table)


# -- HLL ---------------------------------------------------------------------

def test_hll_estimate_within_2pct():
    rng = np.random.default_rng(2)
    n = 50_000
    keys = rng.integers(0, 2**32, size=n, dtype=np.uint32)
    distinct = len(np.unique(keys))
    h = hll_update(hll_init(p=14), jnp.asarray(keys))
    est = float(hll_estimate(h))
    assert abs(est - distinct) / distinct < 0.02


def test_hll_small_range_linear_counting():
    keys = jnp.arange(1, 101, dtype=jnp.uint32) * jnp.uint32(2654435761)
    h = hll_update(hll_init(p=12), keys)
    est = float(hll_estimate(h))
    assert abs(est - 100) < 3


def test_hll_merge_is_union():
    rng = np.random.default_rng(3)
    k1 = rng.integers(0, 2**32, 10_000, dtype=np.uint32)
    k2 = rng.integers(0, 2**32, 10_000, dtype=np.uint32)
    a = hll_update(hll_init(12), jnp.asarray(k1))
    b = hll_update(hll_init(12), jnp.asarray(k2))
    m = hll_merge(a, b)
    both = hll_update(hll_update(hll_init(12), jnp.asarray(k1)), jnp.asarray(k2))
    assert jnp.array_equal(m.registers, both.registers)


def test_hll_mask():
    keys = jnp.arange(1, 65, dtype=jnp.uint32)
    mask = jnp.arange(64) < 32
    h = hll_update(hll_init(12), keys, mask)
    assert abs(float(hll_estimate(h)) - 32) < 3


# -- entropy -----------------------------------------------------------------

def test_entropy_uniform_vs_skewed():
    uniform = jnp.arange(256, dtype=jnp.uint32)
    e1 = entropy_update(entropy_init(12), uniform)
    constant = jnp.zeros(256, dtype=jnp.uint32) + 7
    e2 = entropy_update(entropy_init(12), constant)
    h1, h2 = float(entropy_estimate(e1)), float(entropy_estimate(e2))
    assert abs(h1 - 8.0) < 0.2  # 256 distinct → ~8 bits
    assert h2 == pytest.approx(0.0, abs=1e-5)


def test_entropy_merge_additive():
    a = entropy_update(entropy_init(10), jnp.array([1, 2], dtype=jnp.uint32))
    b = entropy_update(entropy_init(10), jnp.array([2, 3], dtype=jnp.uint32))
    m = entropy_merge(a, b)
    assert float(m.counts.sum()) == 4


# -- top-k -------------------------------------------------------------------

def test_topk_finds_true_heavy_hitters():
    rng = np.random.default_rng(4)
    keys = zipf_keys(rng, 100_000, vocab=5000)
    uniq, exact = np.unique(keys, return_counts=True)
    true_top = set(uniq[np.argsort(-exact)[:10]].tolist())
    cms = cms_init(4, 16)
    tk = topk_init(64)
    for i in range(0, len(keys), 8192):
        chunk = np.zeros(8192, dtype=np.uint32)
        got = keys[i:i + 8192]
        chunk[: len(got)] = got
        mask = jnp.arange(8192) < len(got)
        cms = cms_update(cms, jnp.asarray(chunk), mask.astype(jnp.int32))
        tk = topk_update(tk, cms, jnp.asarray(chunk), mask)
    got_top = set(np.asarray(tk.keys)[np.argsort(-np.asarray(tk.counts))[:10]].tolist())
    assert len(true_top & got_top) >= 9  # ≥90% of top-10 recovered


def test_topk_dedupes_and_sorts():
    cms = cms_init(4, 12)
    keys = jnp.array([10, 10, 10, 20, 20, 30], dtype=jnp.uint32)
    cms = cms_update(cms, keys)
    tk = topk_update(topk_init(4), cms, keys)
    kk = np.asarray(tk.keys)
    assert len(set(kk[kk != 0].tolist())) == len(kk[kk != 0])  # unique
    order = np.argsort(-np.asarray(tk.counts))
    assert kk[order[0]] == 10


def test_topk_merge():
    cms = cms_init(4, 12)
    k1 = jnp.array([1, 1, 1], dtype=jnp.uint32)
    k2 = jnp.array([2, 2, 2, 2], dtype=jnp.uint32)
    cms = cms_update(cms_update(cms, k1), k2)
    a = topk_update(topk_init(4), cms, k1)
    b = topk_update(topk_init(4), cms, k2)
    m = topk_merge(a, b, cms)
    order = np.argsort(-np.asarray(m.counts))
    assert np.asarray(m.keys)[order[0]] == 2


# -- bundle ------------------------------------------------------------------

def test_bundle_update_and_merge():
    rng = np.random.default_rng(5)
    keys = jnp.asarray(zipf_keys(rng, 4096))
    mask = jnp.ones(4096, dtype=bool)
    b1 = bundle_update(bundle_init(), keys, keys, keys, mask)
    b2 = bundle_update(bundle_init(), keys, keys, keys, mask)
    m = bundle_merge(b1, b2)
    assert float(m.events) == 8192
    assert float(m.cms.total) == 8192


def test_bundle_update_jit_donation():
    b = bundle_init(log2_width=12, hll_p=10, entropy_log2_width=8, k=16)
    keys = jnp.arange(256, dtype=jnp.uint32)
    mask = jnp.ones(256, dtype=bool)
    b = bundle_update_jit(b, keys, keys, keys, mask)
    b = bundle_update_jit(b, keys, keys, keys, mask)
    assert float(b.events) == 512


# -- sliding window (TTL semantics on device) --------------------------------

def test_windowed_cms_ttl_semantics():
    from inspektor_gadget_tpu.ops.window import (
        wcms_advance, wcms_init, wcms_query, wcms_update)

    w = wcms_init(n_slots=3, depth=4, log2_width=10)
    k7 = jnp.array([7, 7], dtype=jnp.uint32)
    k9 = jnp.array([9], dtype=jnp.uint32)
    w = wcms_update(w, k7)          # epoch 0: 7 -> 2
    w = wcms_advance(w)
    w = wcms_update(w, k9)          # epoch 1: 9 -> 1
    q = wcms_query(w, jnp.array([7, 9], dtype=jnp.uint32))
    assert q[0] == 2 and q[1] == 1  # both epochs live
    # only last 1 epoch: 7 aged out of scope
    q1 = wcms_query(w, jnp.array([7, 9], dtype=jnp.uint32), last_k=1)
    assert q1[0] == 0 and q1[1] == 1
    # rotate twice more: epoch-0 slot is dropped entirely
    w = wcms_advance(w)
    w = wcms_advance(w)             # wraps onto slot 0, zeroing it
    q = wcms_query(w, jnp.array([7], dtype=jnp.uint32))
    assert q[0] == 0


def test_wcms_merge_associative_and_commutative():
    """The history plane's lazy query-time fold reorders and regroups
    merges freely (per-node, per-window, chunked fetches) — legal only
    because slot-wise merge is a commutative monoid. Assert it on real
    updated states, not axioms."""
    from inspektor_gadget_tpu.ops.window import (
        wcms_init, wcms_merge, wcms_update)

    rng = np.random.default_rng(11)
    states = []
    for _ in range(3):
        s = wcms_init(n_slots=4, depth=4, log2_width=10)
        s = wcms_update(s, jnp.asarray(zipf_keys(rng, 2048)))
        states.append(s)
    a, b, c = states
    ab_c = wcms_merge(wcms_merge(a, b), c)
    a_bc = wcms_merge(a, wcms_merge(b, c))
    assert jnp.array_equal(ab_c.slots, a_bc.slots)
    ba = wcms_merge(b, a)
    ab = wcms_merge(a, b)
    assert jnp.array_equal(ab.slots, ba.slots)


def test_wcms_psum_equals_pairwise_merge():
    """Cluster-wide wcms_psum over a named axis must agree with the
    host-side pairwise merge — the two merge paths (device all-reduce
    vs client-side fold over fetched windows) may never diverge."""
    from inspektor_gadget_tpu.ops.window import (
        wcms_init, wcms_merge, wcms_psum, wcms_update)

    rng = np.random.default_rng(12)
    a = wcms_update(wcms_init(n_slots=2, depth=4, log2_width=10),
                    jnp.asarray(zipf_keys(rng, 1024)))
    b = wcms_update(wcms_init(n_slots=2, depth=4, log2_width=10),
                    jnp.asarray(zipf_keys(rng, 1024)))
    stacked = jax.tree.map(lambda x, y: jnp.stack([x, y]), a, b)
    out = jax.vmap(lambda s: wcms_psum(s, "nodes"),
                   axis_name="nodes")(stacked)
    want = wcms_merge(a, b)
    assert jnp.array_equal(out.slots[0], want.slots)
    assert jnp.array_equal(out.slots[1], want.slots)


def test_range_query_answers_are_split_invariant():
    """Order-invariance over random window splits: the same key stream
    chopped into arbitrary per-window sketches and merged in any
    grouping must answer range queries like one single-pass sketch —
    exactly for the additive planes (CMS/entropy), within documented
    sketch error for HLL."""
    from inspektor_gadget_tpu.history import merge_windows
    from inspektor_gadget_tpu.history.window import SealedWindow
    from inspektor_gadget_tpu.ops.entropy import (
        entropy_estimate, entropy_init, entropy_update)
    from inspektor_gadget_tpu.ops.hll import hll_estimate, hll_init, hll_update

    rng = np.random.default_rng(13)
    keys = zipf_keys(rng, 60_000, vocab=3000)

    def window_of(chunk: np.ndarray, i: int) -> SealedWindow:
        cms = cms_update(cms_init(4, 12), jnp.asarray(chunk))
        h = hll_update(hll_init(10), jnp.asarray(chunk))
        e = entropy_update(entropy_init(8), jnp.asarray(chunk))
        uniq, counts = np.unique(chunk, return_counts=True)
        order = np.argsort(-counts)[:16]
        return SealedWindow(
            gadget="t", node="n", run_id="r", window=i,
            start_ts=float(i), end_ts=float(i + 1),
            events=len(chunk), drops=0,
            cms=np.asarray(cms.table), hll=np.asarray(h.registers),
            ent=np.asarray(e.counts),
            topk_keys=uniq[order].astype(np.uint32),
            topk_counts=counts[order].astype(np.int64),
            slices={})

    # ground truth: ONE sketch over the whole stream
    truth = window_of(keys, 0)
    true_distinct = len(np.unique(keys))

    # random splits, merged in shuffled order and random groupings
    for trial in range(3):
        trng = np.random.default_rng(100 + trial)
        cuts = np.sort(trng.choice(np.arange(1, len(keys)),
                                   size=trng.integers(3, 9), replace=False))
        chunks = np.split(keys, cuts)
        wins = [window_of(c, i) for i, c in enumerate(chunks) if len(c)]
        trng.shuffle(wins)
        # random grouping: fold a random prefix first, then the rest
        k = int(trng.integers(1, len(wins))) if len(wins) > 1 else 1
        merged = merge_windows(
            [w for grp in (wins[:k], wins[k:]) for w in grp])
        assert not merged.skipped
        # additive planes reproduce the single-pass sketch EXACTLY
        assert np.array_equal(merged.cms, truth.cms.astype(np.int64))
        assert np.array_equal(merged.ent, truth.ent.astype(np.float64))
        assert merged.events == len(keys)
        # HLL max-merge over a partition reproduces the single-pass
        # registers EXACTLY (max over sub-maxima = max over all), so the
        # merged answer IS the single-merge ground truth; the estimate
        # itself sits within the p=10 sketch's documented ~3.3% error
        assert np.array_equal(merged.hll, truth.hll)
        est = merged.distinct()
        assert abs(est - true_distinct) / true_distinct < 0.1, (
            trial, est, true_distinct)
        single = merge_windows([truth])
        assert abs(merged.entropy_bits() - single.entropy_bits()) < 1e-9
        assert est == single.distinct()


# -- fused bundle_update parity (ISSUE 10 tentpole) --------------------------
# The fused Pallas kernel must be BIT-IDENTICAL to the separate reference
# ops — CMS table, HLL registers, entropy counts, top-k state, totals.
# On CPU CI the kernel itself runs in the Pallas interpreter
# (_bundle_update_pallas(interpret=True)); on TPU the same code path is
# the production fused step.

_BUNDLE_LEAVES = ("cms.table", "cms.total", "hll.registers",
                  "entropy.counts", "topk.keys", "topk.counts",
                  "events", "drops")


def _leaf(bundle, dotted):
    out = bundle
    for part in dotted.split("."):
        out = getattr(out, part)
    return np.asarray(out)


def _assert_bundles_bit_identical(a, b, ctx=""):
    for name in _BUNDLE_LEAVES:
        assert np.array_equal(_leaf(a, name), _leaf(b, name)), (ctx, name)


def _streams(rng, n):
    return (jnp.asarray(rng.integers(0, 2**32, n, dtype=np.uint32)),
            jnp.asarray(rng.integers(0, 2**32, n, dtype=np.uint32)),
            jnp.asarray(rng.integers(0, 2**32, n, dtype=np.uint32)))


def test_fused_kernel_bit_identical_across_widths_and_masks():
    """Interpret-mode fused kernel vs the reference composition across
    sketch widths, depths, and ragged (odd-count) masks."""
    from inspektor_gadget_tpu.ops.sketches import _bundle_update_pallas

    rng = np.random.default_rng(21)
    cases = [  # (depth, log2w, ent_log2w, hll_p, n, valid)
        (4, 10, 8, 8, 256, 256),
        (2, 12, 10, 7, 512, 501),   # odd valid count under the pad mask
        (5, 11, 6, 10, 512, 384),
    ]
    for depth, log2w, entw, p, n, valid in cases:
        b0 = bundle_init(depth=depth, log2_width=log2w, hll_p=p,
                         entropy_log2_width=entw, k=16)
        hh, distinct, dist = _streams(rng, n)
        mask = jnp.asarray(np.arange(n) < valid)
        drops = jnp.float32(2)
        ref = bundle_update(b0, hh, distinct, dist, mask, drops)
        fused = _bundle_update_pallas(b0, hh, distinct, dist, mask, drops,
                                      interpret=True)
        _assert_bundles_bit_identical(ref, fused, ctx=(depth, log2w, entw, p))
        # and a second absorbed batch on top of live state
        hh2, d2, dd2 = _streams(rng, n)
        ref2 = bundle_update(ref, hh2, d2, dd2, mask)
        fused2 = _bundle_update_pallas(fused, hh2, d2, dd2, mask,
                                       interpret=True)
        _assert_bundles_bit_identical(ref2, fused2, ctx="second batch")


def test_fused_dispatch_selection_and_fallback():
    """bundle_update_fused picks the kernel only for aligned shapes on a
    TPU backend; odd batches and narrow configs take the reference path
    — and the entry point's result equals bundle_update either way."""
    from inspektor_gadget_tpu.ops import bundle_update_fused, fused_supported

    b = bundle_init(depth=4, log2_width=12, hll_p=10,
                    entropy_log2_width=8, k=8)
    assert fused_supported(b, 512)
    assert not fused_supported(b, 999)        # odd batch size
    narrow = bundle_init(depth=4, log2_width=8, hll_p=6,
                         entropy_log2_width=6, k=8)
    assert not fused_supported(narrow, 512)   # widest plane < one tile
    rng = np.random.default_rng(22)
    for n in (999, 512):                      # ragged AND aligned
        hh, distinct, dist = _streams(rng, n)
        mask = jnp.asarray(np.arange(n) < n - 7)
        ref = bundle_update(b, hh, distinct, dist, mask)
        got = bundle_update_fused(b, hh, distinct, dist, mask)
        _assert_bundles_bit_identical(ref, got, ctx=n)


def test_fused_update_under_vmap_and_psum_merge():
    """Per-node fused updates must vmap cleanly and their states must
    merge exactly like reference states — both by pairwise bundle_merge
    and by the device psum/pmax collectives over a named axis."""
    from inspektor_gadget_tpu.ops import bundle_update_fused
    from inspektor_gadget_tpu.ops.countmin import cms_psum
    from inspektor_gadget_tpu.ops.entropy import entropy_psum
    from inspektor_gadget_tpu.ops.hll import hll_pmax

    rng = np.random.default_rng(23)
    n = 512
    b0 = bundle_init(depth=4, log2_width=10, hll_p=8,
                     entropy_log2_width=8, k=16)
    k1, _, _ = _streams(rng, n)
    k2, _, _ = _streams(rng, n)
    mask = jnp.ones(n, bool)

    stacked0 = jax.tree.map(lambda x: jnp.stack([x, x]), b0)
    keys = jnp.stack([k1, k2])
    out = jax.vmap(lambda b, k: bundle_update_fused(b, k, k, k, mask))(
        stacked0, keys)
    ref1 = bundle_update(b0, k1, k1, k1, mask)
    ref2 = bundle_update(b0, k2, k2, k2, mask)
    for i, ref in enumerate((ref1, ref2)):
        got = jax.tree.map(lambda x: x[i], out)
        _assert_bundles_bit_identical(ref, got, ctx=f"vmap lane {i}")

    # psum/pmax collectives over the stacked axis ≡ pairwise merge
    merged = bundle_merge(ref1, ref2)
    cms_all = jax.vmap(lambda s: cms_psum(s, "n"), axis_name="n")(out.cms)
    hll_all = jax.vmap(lambda s: hll_pmax(s, "n"), axis_name="n")(out.hll)
    ent_all = jax.vmap(lambda s: entropy_psum(s, "n"),
                       axis_name="n")(out.entropy)
    assert jnp.array_equal(cms_all.table[0], merged.cms.table)
    assert jnp.array_equal(hll_all.registers[0], merged.hll.registers)
    assert jnp.array_equal(ent_all.counts[0], merged.entropy.counts)


def test_window_digests_identical_on_fused_and_reference_paths():
    """Replay determinism across paths (ISSUE 10 satellite): the SAME
    recorded batch stream sealed into history windows must produce
    byte-identical window digests whether the bundle state came from the
    reference ops or the fused kernel — `replay --verify` cannot hold
    otherwise. Digests are the history plane's state-only content hash,
    so this pins bit-equality end to end, not just array equality."""
    from inspektor_gadget_tpu.history import window_digest
    from inspektor_gadget_tpu.history.window import SealedWindow
    from inspektor_gadget_tpu.ops.sketches import _bundle_update_pallas

    rng = np.random.default_rng(24)
    n = 256
    batches = [_streams(rng, n)[0] for _ in range(3)]
    mask = jnp.ones(n, bool)

    def seal(path):
        b = bundle_init(depth=2, log2_width=10, hll_p=8,
                        entropy_log2_width=8, k=8)
        for k in batches:
            if path == "fused":
                b = _bundle_update_pallas(b, k, k, k, mask, interpret=True)
            else:
                b = bundle_update(b, k, k, k, mask)
        win = SealedWindow(
            gadget="trace/parity", node="n0", run_id="r", window=1,
            start_ts=1.0, end_ts=2.0, events=int(b.events), drops=0,
            cms=np.asarray(b.cms.table, dtype=np.int32),
            hll=np.asarray(b.hll.registers, dtype=np.int32),
            ent=np.asarray(b.entropy.counts, dtype=np.float32),
            topk_keys=np.asarray(b.topk.keys),
            topk_counts=np.asarray(b.topk.counts, dtype=np.int64),
            slices={})
        return window_digest(win)

    assert seal("reference") == seal("fused")


def test_windowed_cms_merge_and_jit():
    import jax as _jax
    from inspektor_gadget_tpu.ops.window import (
        wcms_init, wcms_merge, wcms_query, wcms_update)

    a = wcms_init(n_slots=2, depth=4, log2_width=10)
    b = wcms_init(n_slots=2, depth=4, log2_width=10)
    keys = jnp.array([5, 5, 6], dtype=jnp.uint32)
    upd = _jax.jit(wcms_update)
    a = upd(a, keys)
    b = upd(b, keys)
    m = wcms_merge(a, b)
    q = wcms_query(m, jnp.array([5, 6], dtype=jnp.uint32))
    assert q[0] == 4 and q[1] == 2
