"""Sketch-plane tests: accuracy bounds, mergeability, static-shape jit.

Accuracy targets from BASELINE.md: <1% heavy-hitter error; HLL standard
error ~1.04/sqrt(m) (p=14 → ~0.8%).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from inspektor_gadget_tpu.ops import (
    bundle_init, bundle_update, bundle_merge,
    cms_init, cms_update, cms_query, cms_merge,
    hll_init, hll_update, hll_estimate, hll_merge,
    entropy_init, entropy_update, entropy_estimate, entropy_merge,
    topk_init, topk_update, topk_merge,
    fold64_to_32,
)
from inspektor_gadget_tpu.ops.sketches import bundle_update_jit


def zipf_keys(rng, n, vocab=1000, a=1.5):
    return rng.zipf(a, size=n).clip(1, vocab).astype(np.uint32) * np.uint32(2654435761)


def test_fold64():
    k = np.array([0x123456789ABCDEF0], dtype=np.uint64)
    assert fold64_to_32(k)[0] == np.uint32(0x12345678 ^ 0x9ABCDEF0)


# -- count-min ---------------------------------------------------------------

def test_cms_exact_on_sparse():
    cms = cms_init(depth=4, log2_width=12)
    keys = jnp.array([1, 2, 3, 1, 1, 2], dtype=jnp.uint32)
    cms = cms_update(cms, keys)
    q = cms_query(cms, jnp.array([1, 2, 3, 99], dtype=jnp.uint32))
    assert q[0] == 3 and q[1] == 2 and q[2] == 1
    assert q[3] <= 1  # overestimate only, tiny on sparse table
    assert float(cms.total) == 6


def test_cms_weighted_and_masked():
    cms = cms_init(depth=4, log2_width=10)
    keys = jnp.array([5, 5, 7, 7], dtype=jnp.uint32)
    w = jnp.array([2, 3, 1, 0], dtype=jnp.int32)  # last slot masked out
    cms = cms_update(cms, keys, w)
    q = cms_query(cms, jnp.array([5, 7], dtype=jnp.uint32))
    assert q[0] == 5 and q[1] == 1


def test_cms_heavy_hitter_error_under_1pct():
    rng = np.random.default_rng(0)
    keys = zipf_keys(rng, 200_000)
    cms = cms_init(depth=4, log2_width=16)
    cms = cms_update(cms, jnp.asarray(keys))
    uniq, exact = np.unique(keys, return_counts=True)
    heavy = exact >= 0.001 * len(keys)
    est = np.asarray(cms_query(cms, jnp.asarray(uniq)))
    rel_err = np.abs(est[heavy] - exact[heavy]) / exact[heavy]
    assert rel_err.max() < 0.01


def test_cms_merge_equals_union():
    rng = np.random.default_rng(1)
    k1, k2 = zipf_keys(rng, 5000), zipf_keys(rng, 5000)
    a = cms_update(cms_init(4, 14), jnp.asarray(k1))
    b = cms_update(cms_init(4, 14), jnp.asarray(k2))
    merged = cms_merge(a, b)
    union = cms_update(cms_update(cms_init(4, 14), jnp.asarray(k1)), jnp.asarray(k2))
    assert jnp.array_equal(merged.table, union.table)


# -- HLL ---------------------------------------------------------------------

def test_hll_estimate_within_2pct():
    rng = np.random.default_rng(2)
    n = 50_000
    keys = rng.integers(0, 2**32, size=n, dtype=np.uint32)
    distinct = len(np.unique(keys))
    h = hll_update(hll_init(p=14), jnp.asarray(keys))
    est = float(hll_estimate(h))
    assert abs(est - distinct) / distinct < 0.02


def test_hll_small_range_linear_counting():
    keys = jnp.arange(1, 101, dtype=jnp.uint32) * jnp.uint32(2654435761)
    h = hll_update(hll_init(p=12), keys)
    est = float(hll_estimate(h))
    assert abs(est - 100) < 3


def test_hll_merge_is_union():
    rng = np.random.default_rng(3)
    k1 = rng.integers(0, 2**32, 10_000, dtype=np.uint32)
    k2 = rng.integers(0, 2**32, 10_000, dtype=np.uint32)
    a = hll_update(hll_init(12), jnp.asarray(k1))
    b = hll_update(hll_init(12), jnp.asarray(k2))
    m = hll_merge(a, b)
    both = hll_update(hll_update(hll_init(12), jnp.asarray(k1)), jnp.asarray(k2))
    assert jnp.array_equal(m.registers, both.registers)


def test_hll_mask():
    keys = jnp.arange(1, 65, dtype=jnp.uint32)
    mask = jnp.arange(64) < 32
    h = hll_update(hll_init(12), keys, mask)
    assert abs(float(hll_estimate(h)) - 32) < 3


# -- entropy -----------------------------------------------------------------

def test_entropy_uniform_vs_skewed():
    uniform = jnp.arange(256, dtype=jnp.uint32)
    e1 = entropy_update(entropy_init(12), uniform)
    constant = jnp.zeros(256, dtype=jnp.uint32) + 7
    e2 = entropy_update(entropy_init(12), constant)
    h1, h2 = float(entropy_estimate(e1)), float(entropy_estimate(e2))
    assert abs(h1 - 8.0) < 0.2  # 256 distinct → ~8 bits
    assert h2 == pytest.approx(0.0, abs=1e-5)


def test_entropy_merge_additive():
    a = entropy_update(entropy_init(10), jnp.array([1, 2], dtype=jnp.uint32))
    b = entropy_update(entropy_init(10), jnp.array([2, 3], dtype=jnp.uint32))
    m = entropy_merge(a, b)
    assert float(m.counts.sum()) == 4


# -- top-k -------------------------------------------------------------------

def test_topk_finds_true_heavy_hitters():
    rng = np.random.default_rng(4)
    keys = zipf_keys(rng, 100_000, vocab=5000)
    uniq, exact = np.unique(keys, return_counts=True)
    true_top = set(uniq[np.argsort(-exact)[:10]].tolist())
    cms = cms_init(4, 16)
    tk = topk_init(64)
    for i in range(0, len(keys), 8192):
        chunk = np.zeros(8192, dtype=np.uint32)
        got = keys[i:i + 8192]
        chunk[: len(got)] = got
        mask = jnp.arange(8192) < len(got)
        cms = cms_update(cms, jnp.asarray(chunk), mask.astype(jnp.int32))
        tk = topk_update(tk, cms, jnp.asarray(chunk), mask)
    got_top = set(np.asarray(tk.keys)[np.argsort(-np.asarray(tk.counts))[:10]].tolist())
    assert len(true_top & got_top) >= 9  # ≥90% of top-10 recovered


def test_topk_dedupes_and_sorts():
    cms = cms_init(4, 12)
    keys = jnp.array([10, 10, 10, 20, 20, 30], dtype=jnp.uint32)
    cms = cms_update(cms, keys)
    tk = topk_update(topk_init(4), cms, keys)
    kk = np.asarray(tk.keys)
    assert len(set(kk[kk != 0].tolist())) == len(kk[kk != 0])  # unique
    order = np.argsort(-np.asarray(tk.counts))
    assert kk[order[0]] == 10


def test_topk_merge():
    cms = cms_init(4, 12)
    k1 = jnp.array([1, 1, 1], dtype=jnp.uint32)
    k2 = jnp.array([2, 2, 2, 2], dtype=jnp.uint32)
    cms = cms_update(cms_update(cms, k1), k2)
    a = topk_update(topk_init(4), cms, k1)
    b = topk_update(topk_init(4), cms, k2)
    m = topk_merge(a, b, cms)
    order = np.argsort(-np.asarray(m.counts))
    assert np.asarray(m.keys)[order[0]] == 2


# -- bundle ------------------------------------------------------------------

def test_bundle_update_and_merge():
    rng = np.random.default_rng(5)
    keys = jnp.asarray(zipf_keys(rng, 4096))
    mask = jnp.ones(4096, dtype=bool)
    b1 = bundle_update(bundle_init(), keys, keys, keys, mask)
    b2 = bundle_update(bundle_init(), keys, keys, keys, mask)
    m = bundle_merge(b1, b2)
    assert float(m.events) == 8192
    assert float(m.cms.total) == 8192


def test_bundle_update_jit_donation():
    b = bundle_init(log2_width=12, hll_p=10, entropy_log2_width=8, k=16)
    keys = jnp.arange(256, dtype=jnp.uint32)
    mask = jnp.ones(256, dtype=bool)
    b = bundle_update_jit(b, keys, keys, keys, mask)
    b = bundle_update_jit(b, keys, keys, keys, mask)
    assert float(b.events) == 512


# -- sliding window (TTL semantics on device) --------------------------------

def test_windowed_cms_ttl_semantics():
    from inspektor_gadget_tpu.ops.window import (
        wcms_advance, wcms_init, wcms_query, wcms_update)

    w = wcms_init(n_slots=3, depth=4, log2_width=10)
    k7 = jnp.array([7, 7], dtype=jnp.uint32)
    k9 = jnp.array([9], dtype=jnp.uint32)
    w = wcms_update(w, k7)          # epoch 0: 7 -> 2
    w = wcms_advance(w)
    w = wcms_update(w, k9)          # epoch 1: 9 -> 1
    q = wcms_query(w, jnp.array([7, 9], dtype=jnp.uint32))
    assert q[0] == 2 and q[1] == 1  # both epochs live
    # only last 1 epoch: 7 aged out of scope
    q1 = wcms_query(w, jnp.array([7, 9], dtype=jnp.uint32), last_k=1)
    assert q1[0] == 0 and q1[1] == 1
    # rotate twice more: epoch-0 slot is dropped entirely
    w = wcms_advance(w)
    w = wcms_advance(w)             # wraps onto slot 0, zeroing it
    q = wcms_query(w, jnp.array([7], dtype=jnp.uint32))
    assert q[0] == 0


def test_wcms_merge_associative_and_commutative():
    """The history plane's lazy query-time fold reorders and regroups
    merges freely (per-node, per-window, chunked fetches) — legal only
    because slot-wise merge is a commutative monoid. Assert it on real
    updated states, not axioms."""
    from inspektor_gadget_tpu.ops.window import (
        wcms_init, wcms_merge, wcms_update)

    rng = np.random.default_rng(11)
    states = []
    for _ in range(3):
        s = wcms_init(n_slots=4, depth=4, log2_width=10)
        s = wcms_update(s, jnp.asarray(zipf_keys(rng, 2048)))
        states.append(s)
    a, b, c = states
    ab_c = wcms_merge(wcms_merge(a, b), c)
    a_bc = wcms_merge(a, wcms_merge(b, c))
    assert jnp.array_equal(ab_c.slots, a_bc.slots)
    ba = wcms_merge(b, a)
    ab = wcms_merge(a, b)
    assert jnp.array_equal(ab.slots, ba.slots)


def test_wcms_psum_equals_pairwise_merge():
    """Cluster-wide wcms_psum over a named axis must agree with the
    host-side pairwise merge — the two merge paths (device all-reduce
    vs client-side fold over fetched windows) may never diverge."""
    from inspektor_gadget_tpu.ops.window import (
        wcms_init, wcms_merge, wcms_psum, wcms_update)

    rng = np.random.default_rng(12)
    a = wcms_update(wcms_init(n_slots=2, depth=4, log2_width=10),
                    jnp.asarray(zipf_keys(rng, 1024)))
    b = wcms_update(wcms_init(n_slots=2, depth=4, log2_width=10),
                    jnp.asarray(zipf_keys(rng, 1024)))
    stacked = jax.tree.map(lambda x, y: jnp.stack([x, y]), a, b)
    out = jax.vmap(lambda s: wcms_psum(s, "nodes"),
                   axis_name="nodes")(stacked)
    want = wcms_merge(a, b)
    assert jnp.array_equal(out.slots[0], want.slots)
    assert jnp.array_equal(out.slots[1], want.slots)


def test_range_query_answers_are_split_invariant():
    """Order-invariance over random window splits: the same key stream
    chopped into arbitrary per-window sketches and merged in any
    grouping must answer range queries like one single-pass sketch —
    exactly for the additive planes (CMS/entropy), within documented
    sketch error for HLL."""
    from inspektor_gadget_tpu.history import merge_windows
    from inspektor_gadget_tpu.history.window import SealedWindow
    from inspektor_gadget_tpu.ops.entropy import (
        entropy_estimate, entropy_init, entropy_update)
    from inspektor_gadget_tpu.ops.hll import hll_estimate, hll_init, hll_update

    rng = np.random.default_rng(13)
    keys = zipf_keys(rng, 60_000, vocab=3000)

    def window_of(chunk: np.ndarray, i: int) -> SealedWindow:
        cms = cms_update(cms_init(4, 12), jnp.asarray(chunk))
        h = hll_update(hll_init(10), jnp.asarray(chunk))
        e = entropy_update(entropy_init(8), jnp.asarray(chunk))
        uniq, counts = np.unique(chunk, return_counts=True)
        order = np.argsort(-counts)[:16]
        return SealedWindow(
            gadget="t", node="n", run_id="r", window=i,
            start_ts=float(i), end_ts=float(i + 1),
            events=len(chunk), drops=0,
            cms=np.asarray(cms.table), hll=np.asarray(h.registers),
            ent=np.asarray(e.counts),
            topk_keys=uniq[order].astype(np.uint32),
            topk_counts=counts[order].astype(np.int64),
            slices={})

    # ground truth: ONE sketch over the whole stream
    truth = window_of(keys, 0)
    true_distinct = len(np.unique(keys))

    # random splits, merged in shuffled order and random groupings
    for trial in range(3):
        trng = np.random.default_rng(100 + trial)
        cuts = np.sort(trng.choice(np.arange(1, len(keys)),
                                   size=trng.integers(3, 9), replace=False))
        chunks = np.split(keys, cuts)
        wins = [window_of(c, i) for i, c in enumerate(chunks) if len(c)]
        trng.shuffle(wins)
        # random grouping: fold a random prefix first, then the rest
        k = int(trng.integers(1, len(wins))) if len(wins) > 1 else 1
        merged = merge_windows(
            [w for grp in (wins[:k], wins[k:]) for w in grp])
        assert not merged.skipped
        # additive planes reproduce the single-pass sketch EXACTLY
        assert np.array_equal(merged.cms, truth.cms.astype(np.int64))
        assert np.array_equal(merged.ent, truth.ent.astype(np.float64))
        assert merged.events == len(keys)
        # HLL max-merge over a partition reproduces the single-pass
        # registers EXACTLY (max over sub-maxima = max over all), so the
        # merged answer IS the single-merge ground truth; the estimate
        # itself sits within the p=10 sketch's documented ~3.3% error
        assert np.array_equal(merged.hll, truth.hll)
        est = merged.distinct()
        assert abs(est - true_distinct) / true_distinct < 0.1, (
            trial, est, true_distinct)
        single = merge_windows([truth])
        assert abs(merged.entropy_bits() - single.entropy_bits()) < 1e-9
        assert est == single.distinct()


# -- fused bundle_update parity (ISSUE 10 tentpole) --------------------------
# The fused Pallas kernel must be BIT-IDENTICAL to the separate reference
# ops — CMS table, HLL registers, entropy counts, top-k state, totals.
# On CPU CI the kernel itself runs in the Pallas interpreter
# (_bundle_update_pallas(interpret=True)); on TPU the same code path is
# the production fused step.

_BUNDLE_LEAVES = ("cms.table", "cms.total", "hll.registers",
                  "entropy.counts", "topk.keys", "topk.counts",
                  "events", "drops")


def _leaf(bundle, dotted):
    out = bundle
    for part in dotted.split("."):
        out = getattr(out, part)
    return np.asarray(out)


def _assert_bundles_bit_identical(a, b, ctx=""):
    for name in _BUNDLE_LEAVES:
        assert np.array_equal(_leaf(a, name), _leaf(b, name)), (ctx, name)


def _streams(rng, n):
    return (jnp.asarray(rng.integers(0, 2**32, n, dtype=np.uint32)),
            jnp.asarray(rng.integers(0, 2**32, n, dtype=np.uint32)),
            jnp.asarray(rng.integers(0, 2**32, n, dtype=np.uint32)))


def test_fused_kernel_bit_identical_across_widths_and_masks():
    """Interpret-mode fused kernel vs the reference composition across
    sketch widths, depths, and ragged (odd-count) masks."""
    from inspektor_gadget_tpu.ops.sketches import _bundle_update_pallas

    rng = np.random.default_rng(21)
    cases = [  # (depth, log2w, ent_log2w, hll_p, n, valid)
        (4, 10, 8, 8, 256, 256),
        (2, 12, 10, 7, 512, 501),   # odd valid count under the pad mask
        (5, 11, 6, 10, 512, 384),
    ]
    for depth, log2w, entw, p, n, valid in cases:
        b0 = bundle_init(depth=depth, log2_width=log2w, hll_p=p,
                         entropy_log2_width=entw, k=16)
        hh, distinct, dist = _streams(rng, n)
        mask = jnp.asarray(np.arange(n) < valid)
        drops = jnp.float32(2)
        ref = bundle_update(b0, hh, distinct, dist, mask, drops)
        fused = _bundle_update_pallas(b0, hh, distinct, dist, mask, drops,
                                      interpret=True)
        _assert_bundles_bit_identical(ref, fused, ctx=(depth, log2w, entw, p))
        # and a second absorbed batch on top of live state
        hh2, d2, dd2 = _streams(rng, n)
        ref2 = bundle_update(ref, hh2, d2, dd2, mask)
        fused2 = _bundle_update_pallas(fused, hh2, d2, dd2, mask,
                                       interpret=True)
        _assert_bundles_bit_identical(ref2, fused2, ctx="second batch")


def test_fused_dispatch_selection_and_fallback():
    """bundle_update_fused picks the kernel only for aligned shapes on a
    TPU backend; odd batches and narrow configs take the reference path
    — and the entry point's result equals bundle_update either way."""
    from inspektor_gadget_tpu.ops import bundle_update_fused, fused_supported

    b = bundle_init(depth=4, log2_width=12, hll_p=10,
                    entropy_log2_width=8, k=8)
    assert fused_supported(b, 512)
    assert not fused_supported(b, 999)        # odd batch size
    narrow = bundle_init(depth=4, log2_width=8, hll_p=6,
                         entropy_log2_width=6, k=8)
    assert not fused_supported(narrow, 512)   # widest plane < one tile
    rng = np.random.default_rng(22)
    for n in (999, 512):                      # ragged AND aligned
        hh, distinct, dist = _streams(rng, n)
        mask = jnp.asarray(np.arange(n) < n - 7)
        ref = bundle_update(b, hh, distinct, dist, mask)
        got = bundle_update_fused(b, hh, distinct, dist, mask)
        _assert_bundles_bit_identical(ref, got, ctx=n)


def test_fused_update_under_vmap_and_psum_merge():
    """Per-node fused updates must vmap cleanly and their states must
    merge exactly like reference states — both by pairwise bundle_merge
    and by the device psum/pmax collectives over a named axis."""
    from inspektor_gadget_tpu.ops import bundle_update_fused
    from inspektor_gadget_tpu.ops.countmin import cms_psum
    from inspektor_gadget_tpu.ops.entropy import entropy_psum
    from inspektor_gadget_tpu.ops.hll import hll_pmax

    rng = np.random.default_rng(23)
    n = 512
    b0 = bundle_init(depth=4, log2_width=10, hll_p=8,
                     entropy_log2_width=8, k=16)
    k1, _, _ = _streams(rng, n)
    k2, _, _ = _streams(rng, n)
    mask = jnp.ones(n, bool)

    stacked0 = jax.tree.map(lambda x: jnp.stack([x, x]), b0)
    keys = jnp.stack([k1, k2])
    out = jax.vmap(lambda b, k: bundle_update_fused(b, k, k, k, mask))(
        stacked0, keys)
    ref1 = bundle_update(b0, k1, k1, k1, mask)
    ref2 = bundle_update(b0, k2, k2, k2, mask)
    for i, ref in enumerate((ref1, ref2)):
        got = jax.tree.map(lambda x: x[i], out)
        _assert_bundles_bit_identical(ref, got, ctx=f"vmap lane {i}")

    # psum/pmax collectives over the stacked axis ≡ pairwise merge
    merged = bundle_merge(ref1, ref2)
    cms_all = jax.vmap(lambda s: cms_psum(s, "n"), axis_name="n")(out.cms)
    hll_all = jax.vmap(lambda s: hll_pmax(s, "n"), axis_name="n")(out.hll)
    ent_all = jax.vmap(lambda s: entropy_psum(s, "n"),
                       axis_name="n")(out.entropy)
    assert jnp.array_equal(cms_all.table[0], merged.cms.table)
    assert jnp.array_equal(hll_all.registers[0], merged.hll.registers)
    assert jnp.array_equal(ent_all.counts[0], merged.entropy.counts)


def test_window_digests_identical_on_fused_and_reference_paths():
    """Replay determinism across paths (ISSUE 10 satellite): the SAME
    recorded batch stream sealed into history windows must produce
    byte-identical window digests whether the bundle state came from the
    reference ops or the fused kernel — `replay --verify` cannot hold
    otherwise. Digests are the history plane's state-only content hash,
    so this pins bit-equality end to end, not just array equality."""
    from inspektor_gadget_tpu.history import window_digest
    from inspektor_gadget_tpu.history.window import SealedWindow
    from inspektor_gadget_tpu.ops.sketches import _bundle_update_pallas

    rng = np.random.default_rng(24)
    n = 256
    batches = [_streams(rng, n)[0] for _ in range(3)]
    mask = jnp.ones(n, bool)

    def seal(path):
        b = bundle_init(depth=2, log2_width=10, hll_p=8,
                        entropy_log2_width=8, k=8)
        for k in batches:
            if path == "fused":
                b = _bundle_update_pallas(b, k, k, k, mask, interpret=True)
            else:
                b = bundle_update(b, k, k, k, mask)
        win = SealedWindow(
            gadget="trace/parity", node="n0", run_id="r", window=1,
            start_ts=1.0, end_ts=2.0, events=int(b.events), drops=0,
            cms=np.asarray(b.cms.table, dtype=np.int32),
            hll=np.asarray(b.hll.registers, dtype=np.int32),
            ent=np.asarray(b.entropy.counts, dtype=np.float32),
            topk_keys=np.asarray(b.topk.keys),
            topk_counts=np.asarray(b.topk.counts, dtype=np.int64),
            slices={})
        return window_digest(win)

    assert seal("reference") == seal("fused")


# -- invertible heavy-key plane (ISSUE 15) -----------------------------------
# Merge-algebra property tier: the invertible lanes are pure integer adds,
# so every grouping/ordering of merges — pairwise host folds, device psum
# collectives, window-level adds — must produce identical state, and
# decode of that state must be exact whenever the distinct-key load fits
# pure buckets (<= inv_capacity). Beyond capacity the documented envelope
# is: recovered pairs stay exact, coverage degrades, complete=False.


def _inv_filled(rng, n_keys, rows=3, log2b=10, vocab_hi=1 << 22):
    """An InvSketch holding n_keys distinct keys with zipf-ish weights,
    plus the ground-truth {key: count} map."""
    import jax as _jax
    from inspektor_gadget_tpu.ops.invertible import inv_init, inv_update

    keys = rng.choice(np.arange(1, vocab_hi, dtype=np.uint32),
                      size=n_keys, replace=False)
    # cap at a value with few trailing zero bits: counts divisible by
    # 2^17+ are the documented decode blind spot, and a power-of-two
    # clip would manufacture exactly that pathology
    counts = rng.zipf(1.5, size=n_keys).clip(1, 100_000).astype(np.int64)
    step = _jax.jit(inv_update, donate_argnums=0)
    s = step(inv_init(rows, log2b), jnp.asarray(keys),
             jnp.asarray(counts.astype(np.int32)))
    return s, dict(zip(keys.tolist(), counts.tolist()))


def test_inv_merge_associative_and_commutative():
    from inspektor_gadget_tpu.ops.invertible import inv_merge

    rng = np.random.default_rng(31)
    states = [_inv_filled(rng, 100)[0] for _ in range(3)]
    a, b, c = states
    ab_c = inv_merge(inv_merge(a, b), c)
    a_bc = inv_merge(a, inv_merge(b, c))
    for lane in ("count", "keysum", "fpsum"):
        assert jnp.array_equal(getattr(ab_c, lane), getattr(a_bc, lane))
    ab, ba = inv_merge(a, b), inv_merge(b, a)
    for lane in ("count", "keysum", "fpsum"):
        assert jnp.array_equal(getattr(ab, lane), getattr(ba, lane))


def test_inv_psum_under_vmap_equals_pairwise_merge():
    """Device all-reduce (the cluster/fleet merge path) ≡ host pairwise
    merge — the two ways merged state is built may never diverge, or
    decode answers would depend on WHERE the merge ran."""
    from inspektor_gadget_tpu.ops.invertible import inv_merge, inv_psum

    rng = np.random.default_rng(32)
    a, _ = _inv_filled(rng, 80)
    b, _ = _inv_filled(rng, 80)
    stacked = jax.tree.map(lambda x, y: jnp.stack([x, y]), a, b)
    out = jax.vmap(lambda s: inv_psum(s, "nodes"),
                   axis_name="nodes")(stacked)
    want = inv_merge(a, b)
    for lane in ("count", "keysum", "fpsum"):
        assert jnp.array_equal(getattr(out, lane)[0], getattr(want, lane))
        assert jnp.array_equal(getattr(out, lane)[1], getattr(want, lane))


def test_inv_decode_exact_when_keys_fit_pure_buckets():
    """Under the documented capacity, decode recovers EVERY key with its
    EXACT total weight (odd and even totals alike — the host finisher's
    trailing-zero enumeration covers even pure buckets) and reports
    complete=True. Also across a merge: decode(merge(a,b)) == union."""
    from inspektor_gadget_tpu.ops.invertible import (inv_capacity,
                                                     inv_decode, inv_merge)

    rng = np.random.default_rng(33)
    rows, log2b = 3, 10
    cap = inv_capacity(rows, log2b)
    assert cap == 3 * 1024 // 4
    s, truth = _inv_filled(rng, cap // 2, rows=rows, log2b=log2b)
    dec = inv_decode(s)
    assert dec.complete and dec.residual_events == 0
    assert dict(dec.keys) == truth
    s2, truth2 = _inv_filled(rng, cap // 3, rows=rows, log2b=log2b)
    merged_truth = dict(truth)
    for k, c in truth2.items():
        merged_truth[k] = merged_truth.get(k, 0) + c
    dec2 = inv_decode(inv_merge(s, s2))
    assert dec2.complete
    assert dict(dec2.keys) == merged_truth


def test_inv_decode_device_loop_matches_host_only_decode():
    """The jittable fixed-iteration device loop + host finisher must
    answer exactly like the pure-numpy peel over the same state."""
    from inspektor_gadget_tpu.ops.invertible import inv_decode

    rng = np.random.default_rng(34)
    s, truth = _inv_filled(rng, 300)
    via_device = inv_decode(s)                      # jnp leaves → device loop
    host_only = inv_decode((np.asarray(s.count), np.asarray(s.keysum),
                            np.asarray(s.fpsum)))   # numpy → host peel only
    assert dict(via_device.keys) == dict(host_only.keys) == truth
    assert via_device.complete and host_only.complete


def test_inv_decode_error_envelope_on_zipf_overload():
    """Past capacity the decode is PARTIAL, never wrong: every recovered
    pair must match ground truth exactly, completeness is reported
    False, and the undecoded mass is accounted in residual_events."""
    from inspektor_gadget_tpu.ops.invertible import (inv_capacity,
                                                     inv_decode)

    rng = np.random.default_rng(35)
    rows, log2b = 3, 8
    cap = inv_capacity(rows, log2b)
    s, truth = _inv_filled(rng, cap * 4, rows=rows, log2b=log2b)
    dec = inv_decode(s)
    assert not dec.complete
    for k, c in dec.keys:
        assert truth.get(k) == c, (k, c)
    total = sum(truth.values())
    recovered_mass = sum(c for _, c in dec.keys)
    assert recovered_mass + dec.residual_events == total


def test_fused_kernel_parity_with_invertible_planes():
    """Interpret-mode fused kernel vs the reference composition with the
    invertible planes ON: every bundle leaf — the new count/keysum/fpsum
    lanes included — is bit-identical, over ragged masks and a second
    batch on live state."""
    from inspektor_gadget_tpu.ops.sketches import _bundle_update_pallas

    rng = np.random.default_rng(36)
    leaves = _BUNDLE_LEAVES + ("inv.count", "inv.keysum", "inv.fpsum",
                               "topk.overflow")
    for depth, log2w, entw, p, inv_rows, inv_lb, n, valid in (
            (4, 10, 8, 8, 3, 9, 256, 256),
            (2, 12, 10, 7, 2, 12, 512, 501),):
        b0 = bundle_init(depth=depth, log2_width=log2w, hll_p=p,
                         entropy_log2_width=entw, k=16,
                         inv_rows=inv_rows, inv_log2_buckets=inv_lb)
        hh, distinct, dist = _streams(rng, n)
        mask = jnp.asarray(np.arange(n) < valid)
        ref = bundle_update(b0, hh, distinct, dist, mask, jnp.float32(1))
        fused = _bundle_update_pallas(b0, hh, distinct, dist, mask,
                                      jnp.float32(1), interpret=True)
        for name in leaves:
            assert np.array_equal(_leaf(ref, name), _leaf(fused, name)), \
                (name, depth, inv_rows)
        hh2, d2, dd2 = _streams(rng, n)
        ref2 = bundle_update(ref, hh2, d2, dd2, mask)
        fused2 = _bundle_update_pallas(fused, hh2, d2, dd2, mask,
                                       interpret=True)
        for name in leaves:
            assert np.array_equal(_leaf(ref2, name), _leaf(fused2, name)), \
                ("second batch", name)


def test_candidate_overflow_flag_flips_exactly_at_overflow():
    """The approx flag (ISSUE 15 satellite): k distinct candidate keys
    leave it 0 — the re-rank is exact; the (k+1)-th distinct key flips
    it to 1, on update AND merge paths, and psum/merge never resets it."""
    from inspektor_gadget_tpu.ops.sketches import decode_digest, bundle_digest

    k = 8
    n = 256
    mask = jnp.ones(n, bool)

    def feed(b, vocab):
        keys = jnp.asarray((np.arange(n) % vocab + 1).astype(np.uint32))
        return bundle_update(b, keys, keys, keys, mask)

    b = bundle_init(depth=2, log2_width=10, hll_p=8,
                    entropy_log2_width=8, k=k)
    b = feed(b, k)                       # exactly k distinct
    assert int(b.topk.overflow) == 0
    assert decode_digest(bundle_digest(b))[4] is False
    b = feed(b, k + 1)                   # the (k+1)-th distinct key
    assert int(b.topk.overflow) == 1
    assert decode_digest(bundle_digest(b))[4] is True
    # merge paths: union overflow + latched inputs
    a1 = feed(bundle_init(depth=2, log2_width=10, hll_p=8,
                          entropy_log2_width=8, k=k), k)
    a2keys = jnp.asarray((np.arange(n) % k + 100).astype(np.uint32))
    a2 = bundle_update(bundle_init(depth=2, log2_width=10, hll_p=8,
                                   entropy_log2_width=8, k=k),
                       a2keys, a2keys, a2keys, mask)
    assert int(a1.topk.overflow) == 0 and int(a2.topk.overflow) == 0
    m = bundle_merge(a1, a2)             # union is 2k distinct > k
    assert int(m.topk.overflow) == 1
    m2 = bundle_merge(m, bundle_init(depth=2, log2_width=10, hll_p=8,
                                     entropy_log2_width=8, k=k))
    assert int(m2.topk.overflow) == 1    # latched through further merges


def test_window_digest_invertible_plane_conditional():
    """Digest discipline: a window without the invertible arrays hashes
    exactly as before the plane existed (the fields never enter the
    doc), and adding the arrays changes — removing them restores — the
    digest, so plane-off replay `--verify` stays green."""
    from inspektor_gadget_tpu.history import window_digest
    from inspektor_gadget_tpu.history.window import (SealedWindow,
                                                     decode_window,
                                                     encode_window)

    base = dict(
        gadget="t", node="n", run_id="r", window=1, start_ts=1.0,
        end_ts=2.0, events=10, drops=0,
        cms=np.ones((2, 8), np.int32), hll=np.zeros(16, np.int32),
        ent=np.zeros(8, np.float32),
        topk_keys=np.array([5], np.uint32),
        topk_counts=np.array([10], np.int64), slices={})
    plain = SealedWindow(**base)
    with_inv = SealedWindow(**base,
                            inv_count=np.ones((2, 8), np.int32),
                            inv_keysum=np.ones((2, 8), np.uint32),
                            inv_fpsum=np.ones((2, 8), np.uint32))
    assert window_digest(plain) != window_digest(with_inv)
    stripped = SealedWindow(**base)
    assert window_digest(plain) == window_digest(stripped)
    # codec roundtrip preserves the plane bit-for-bit
    h, payload = encode_window(with_inv)
    back = decode_window(h, payload)
    assert np.array_equal(back.inv_count, with_inv.inv_count)
    assert np.array_equal(back.inv_keysum, with_inv.inv_keysum)
    assert np.array_equal(back.inv_fpsum, with_inv.inv_fpsum)
    assert window_digest(back) == window_digest(with_inv)


def test_merge_windows_inv_plane_fold_and_refusal():
    """Range-fold semantics: windows all carrying the plane fold into
    decodable merged state (decode == union of per-window streams);
    one window WITHOUT the plane disables decode for the range with a
    loud note instead of decoding partial coverage."""
    import jax as _jax
    from inspektor_gadget_tpu.history import merge_windows
    from inspektor_gadget_tpu.history.window import SealedWindow
    from inspektor_gadget_tpu.ops.invertible import (inv_decode, inv_init,
                                                     inv_update)

    step = _jax.jit(inv_update, donate_argnums=0)
    rng = np.random.default_rng(37)

    def window_of(i, keys, counts, with_inv=True):
        s = step(inv_init(2, 8), jnp.asarray(keys),
                 jnp.asarray(counts.astype(np.int32)))
        kw = {}
        if with_inv:
            kw = dict(inv_count=np.asarray(s.count),
                      inv_keysum=np.asarray(s.keysum),
                      inv_fpsum=np.asarray(s.fpsum))
        return SealedWindow(
            gadget="t", node="n", run_id="r", window=i,
            start_ts=float(i), end_ts=float(i + 1),
            events=int(counts.sum()), drops=0,
            cms=np.zeros((2, 8), np.int32), hll=np.zeros(16, np.int32),
            ent=np.zeros(8, np.float32),
            topk_keys=np.zeros(4, np.uint32),
            topk_counts=np.zeros(4, np.int64), slices={}, **kw)

    k1 = rng.choice(np.arange(1, 1000, dtype=np.uint32), 40, replace=False)
    c1 = rng.integers(1, 50, 40).astype(np.int64)
    k2 = rng.choice(np.arange(1000, 2000, dtype=np.uint32), 30,
                    replace=False)
    c2 = rng.integers(1, 50, 30).astype(np.int64)
    w1, w2 = window_of(1, k1, c1), window_of(2, k2, c2)
    merged = merge_windows([w1, w2])
    truth = dict(zip(k1.tolist(), c1.tolist()))
    truth.update(zip(k2.tolist(), c2.tolist()))
    assert dict(merged.heavy_flows()) == truth
    # one plane-less window → decode disabled, loudly
    merged2 = merge_windows([w1, window_of(3, k2, c2, with_inv=False)])
    assert merged2.inv_count is None
    assert merged2.heavy_flows() == []
    assert any("heavy-flow decode disabled" in s for s in merged2.skipped)


def test_windowed_cms_merge_and_jit():
    import jax as _jax
    from inspektor_gadget_tpu.ops.window import (
        wcms_init, wcms_merge, wcms_query, wcms_update)

    a = wcms_init(n_slots=2, depth=4, log2_width=10)
    b = wcms_init(n_slots=2, depth=4, log2_width=10)
    keys = jnp.array([5, 5, 6], dtype=jnp.uint32)
    upd = _jax.jit(wcms_update)
    a = upd(a, keys)
    b = upd(b, keys)
    m = wcms_merge(a, b)
    q = wcms_query(m, jnp.array([5, 6], dtype=jnp.uint32))
    assert q[0] == 4 and q[1] == 2


def test_fused_kernel_parity_with_quantile_plane():
    """Interpret-mode fused kernel vs the reference composition with the
    DDSketch quantile plane ON: every bundle leaf — counts/zeros/total
    value lanes included — is bit-identical, over ragged masks, a second
    batch on live state, and with the invertible planes riding along."""
    from inspektor_gadget_tpu.ops.sketches import _bundle_update_pallas

    rng = np.random.default_rng(40)
    for depth, log2w, entw, p, inv_rows, n, valid in (
            (4, 10, 8, 8, 0, 256, 256),
            (2, 12, 10, 7, 2, 512, 501),):    # ragged + inv planes too
        leaves = _BUNDLE_LEAVES + ("quantiles.counts", "quantiles.zeros",
                                   "quantiles.total")
        if inv_rows:
            leaves += ("inv.count", "inv.keysum", "inv.fpsum")
        b0 = bundle_init(depth=depth, log2_width=log2w, hll_p=p,
                         entropy_log2_width=entw, k=16,
                         inv_rows=inv_rows, inv_log2_buckets=10,
                         quantiles=True, quantile_buckets=2048)
        hh, distinct, dist = _streams(rng, n)
        vals = jnp.asarray(rng.lognormal(np.log(50_000.0), 1.2, n)
                           .astype(np.float32).astype(np.uint32))
        vals = vals.at[:5].set(0)            # exercise the zero bucket
        mask = jnp.asarray(np.arange(n) < valid)
        ref = bundle_update(b0, hh, distinct, dist, mask, jnp.float32(1),
                            values=vals)
        fused = _bundle_update_pallas(b0, hh, distinct, dist, mask,
                                      jnp.float32(1), values=vals,
                                      interpret=True)
        for name in leaves:
            assert np.array_equal(_leaf(ref, name), _leaf(fused, name)), \
                (name, depth, inv_rows)
        hh2, d2, dd2 = _streams(rng, n)
        vals2 = jnp.asarray(rng.integers(0, 1 << 20, n, dtype=np.uint32))
        ref2 = bundle_update(ref, hh2, d2, dd2, mask, values=vals2)
        fused2 = _bundle_update_pallas(fused, hh2, d2, dd2, mask,
                                       values=vals2, interpret=True)
        for name in leaves:
            assert np.array_equal(_leaf(ref2, name), _leaf(fused2, name)), \
                ("second batch", name)


def test_bundle_quantile_plane_matches_standalone_sketch():
    """The bundle's value-lane fold must produce the exact DDSketch the
    standalone dd_update produces over the same masked values — the
    bundle plane is the same sketch, just riding the fused step."""
    from inspektor_gadget_tpu.ops import dd_init, dd_update

    rng = np.random.default_rng(41)
    n = 512
    b = bundle_init(depth=2, log2_width=10, hll_p=8,
                    entropy_log2_width=6, k=8, quantiles=True,
                    quantile_buckets=1024, quantile_alpha=0.02)
    hh, distinct, dist = _streams(rng, n)
    vals = rng.integers(0, 1 << 24, n, dtype=np.uint32)
    vals[:17] = 0
    mask = np.arange(n) < 400
    got = bundle_update(b, hh, distinct, dist, jnp.asarray(mask),
                        values=jnp.asarray(vals))
    want = dd_update(dd_init(alpha=0.02, n_buckets=1024, min_value=1.0),
                     jnp.asarray(vals.astype(np.float32)),
                     jnp.asarray(mask))
    np.testing.assert_array_equal(np.asarray(got.quantiles.counts),
                                  np.asarray(want.counts))
    assert int(got.quantiles.zeros) == int(want.zeros) == 17
    assert int(got.quantiles.total) == int(want.total) == 400
    # plane-off bundle: quantiles stays None and values= is refused
    off = bundle_init(depth=2, log2_width=10, hll_p=8,
                      entropy_log2_width=6, k=8)
    assert off.quantiles is None
    out = bundle_update(off, hh, distinct, dist, jnp.asarray(mask))
    assert out.quantiles is None


def test_window_digest_quantile_plane_conditional():
    """Same digest discipline as the invertible plane: a window without
    the quantile lanes hashes exactly as before the plane existed, the
    lanes change the digest when present, and the codec roundtrips them
    bit-for-bit."""
    from inspektor_gadget_tpu.history import window_digest
    from inspektor_gadget_tpu.history.window import (SealedWindow,
                                                     decode_window,
                                                     encode_window)

    base = dict(
        gadget="t", node="n", run_id="r", window=1, start_ts=1.0,
        end_ts=2.0, events=10, drops=0,
        cms=np.ones((2, 8), np.int32), hll=np.zeros(16, np.int32),
        ent=np.zeros(8, np.float32),
        topk_keys=np.array([5], np.uint32),
        topk_counts=np.array([10], np.int64), slices={})
    plain = SealedWindow(**base)
    with_qt = SealedWindow(**base,
                           qt_counts=np.arange(32, dtype=np.int64),
                           qt_zeros=3, qt_total=499, qt_alpha=0.02,
                           qt_min_value=1.0)
    assert window_digest(plain) != window_digest(with_qt)
    assert window_digest(plain) == window_digest(SealedWindow(**base))
    h, payload = encode_window(with_qt)
    back = decode_window(h, payload)
    assert np.array_equal(back.qt_counts, with_qt.qt_counts)
    assert back.qt_zeros == 3 and back.qt_total == 499
    assert back.qt_alpha == 0.02 and back.qt_min_value == 1.0
    assert window_digest(back) == window_digest(with_qt)
    # plane-off window: no qt keys on the wire at all
    h2, _ = encode_window(plain)
    assert not any(k.startswith("qt_") for k in h2)


def test_merge_windows_qt_plane_fold_and_refusal():
    """Range-fold semantics for the quantile plane: matching-geometry
    windows fold into lanes whose quantile read equals the ground-truth
    combined stream; a plane-less window or a different alpha drops the
    plane from the answer WITH a note — a mixed-base fold would render
    confident-looking but wrong percentiles."""
    import jax as _jax
    from inspektor_gadget_tpu.history import merge_windows
    from inspektor_gadget_tpu.history.window import SealedWindow
    from inspektor_gadget_tpu.ops import dd_init, dd_update

    step = _jax.jit(dd_update, donate_argnums=0)
    rng = np.random.default_rng(42)

    def window_of(i, vals, with_qt=True, alpha=0.01):
        kw = {}
        if with_qt:
            s = step(dd_init(alpha=alpha, n_buckets=1024, min_value=1.0),
                     jnp.asarray(vals))
            kw = dict(qt_counts=np.asarray(s.counts),
                      qt_zeros=int(s.zeros), qt_total=int(s.total),
                      qt_alpha=alpha, qt_min_value=1.0)
        return SealedWindow(
            gadget="t", node="n", run_id="r", window=i,
            start_ts=float(i), end_ts=float(i + 1),
            events=len(vals), drops=0,
            cms=np.zeros((2, 8), np.int32), hll=np.zeros(16, np.int32),
            ent=np.zeros(8, np.float32),
            topk_keys=np.zeros(4, np.uint32),
            topk_counts=np.zeros(4, np.int64), slices={}, **kw)

    v1 = rng.lognormal(np.log(30_000.0), 0.7, 600).astype(np.float32)
    v2 = rng.lognormal(np.log(900_000.0), 0.7, 400).astype(np.float32)
    w1, w2 = window_of(1, v1), window_of(2, v2)
    merged = merge_windows([w1, w2])
    assert merged.qt_total == 1000 and merged.qt_zeros == 0
    both = np.concatenate([v1, v2])
    for q in (0.5, 0.9, 0.99):
        est = float(merged.quantile(q))
        true = float(np.quantile(both, q))
        assert abs(est - true) / true < 0.03, (q, est, true)
    # the quantile_answer block is wire-shaped and self-describing
    ans = merged.quantile_answer()
    assert ans["total"] == 1000 and ans["alpha"] == 0.01
    assert set(ans) >= {"p50", "p90", "p99", "p999"}
    # histogram over the merged lanes conserves positive mass
    hist = merged.histogram_log2()
    assert int(hist.sum()) == merged.qt_total - merged.qt_zeros
    # a plane-less window in the range → quantiles disabled, loudly
    m2 = merge_windows([w1, window_of(3, v2, with_qt=False)])
    assert m2.qt_counts is None and np.isnan(m2.quantile(0.5))
    assert m2.quantile_answer() is None
    assert any("latency quantiles disabled" in s for s in m2.skipped)
    # a different log base (alpha) → refusal, not a silent mixed fold
    m3 = merge_windows([w1, window_of(4, v2, alpha=0.05)])
    assert m3.qt_counts is None
    assert any("quantile geometry" in s for s in m3.skipped)
    # order matters not: plane-less FIRST also disables with a note
    m4 = merge_windows([window_of(5, v1, with_qt=False), w2])
    assert m4.qt_counts is None
    assert any("earlier window lacked" in s for s in m4.skipped)
