"""ISSUE 20 scale proof: ~100 in-process agents folded through the
merge tree under churn, partition, clock skew, and aggregator crashes —
every query byte-identical to the flat fold over the same reachable
roster, every reachable leaf counted exactly once per query."""

from __future__ import annotations

import pytest

from inspektor_gadget_tpu.fleet import fold_tree
from inspektor_gadget_tpu.fleet.sim import GADGET, SimFleet
from inspektor_gadget_tpu.history import encode_window, pack_frames

N = 100


def frame(win) -> bytes:
    return pack_frames([encode_window(win)])


@pytest.fixture
def fleet() -> SimFleet:
    # one window per agent keeps a 100-agent fold tier-1 fast; inv+qt on
    # so the identity claim covers the refusal-bearing planes at scale
    return SimFleet(N, n_windows=1, inv=True, qt=True)


def tree_query(fleet: SimFleet, spec: str = "auto:4", **kw):
    return fold_tree(fleet.topology(spec), fleet.fetch_leaf,
                     gadget=GADGET, **kw)


def test_100_agents_tree_identical_to_flat(fleet):
    tf = tree_query(fleet)
    assert tf.depth == 4
    assert frame(tf.window) == frame(fleet.flat_reference())
    assert tf.levels == {0: N}
    assert tf.errors == {} and tf.fallback == []
    # exactly-once: one leaf pull per agent for the whole tree
    assert sorted(fleet.fetches) == fleet.nodes()
    assert all(v == 1 for v in fleet.fetches.values())
    # every aggregator folded client-side exactly once
    assert tf.subtree_folds == len(fleet.topology("auto:4").aggregators())
    assert tf.aggregate["folded"] == N
    assert tf.aggregate["missing"] == []


def test_churn_rebuild_topology_and_refold(fleet):
    tf0 = tree_query(fleet)
    # churn: 7 agents leave, 5 fresh ones join — the tree is a function
    # of the roster, so the next query folds through a REBUILT topology
    for node in ["n003", "n017", "n042", "n055", "n068", "n081", "n099"]:
        fleet.kill(node)
    joined = [fleet.spawn() for _ in range(5)]
    assert len(fleet.nodes()) == N - 7 + 5
    tf1 = tree_query(fleet)
    assert frame(tf1.window) == frame(fleet.flat_reference())
    assert tf1.levels == {0: N - 7 + 5}
    assert tf1.window.digest != tf0.window.digest  # the roster changed
    assert all(j in tf1.paths for j in joined)
    # exactly-once PER QUERY: survivors were pulled twice (two queries),
    # joiners once, the churned-out never after leaving
    assert all(fleet.fetches[j] == 1 for j in joined)
    assert all(fleet.fetches[node] == 2 for node in fleet.nodes()
               if node not in joined)


def test_partition_10_nodes_then_heal(fleet):
    dark = [f"n{i:03d}" for i in range(0, N, 10)]
    fleet.partition(*dark)
    tf = tree_query(fleet)
    # the tree answers for the 90 reachable agents, byte-identical to
    # the flat fold over the same survivors
    assert frame(tf.window) == frame(fleet.flat_reference())
    assert tf.levels == {0: N - len(dark)}
    assert sorted(tf.errors) == dark
    assert all(tf.paths[n] == "unreachable" for n in dark)
    assert tf.aggregate["missing"] == dark
    fleet.heal()
    tf2 = tree_query(fleet)
    assert frame(tf2.window) == frame(fleet.flat_reference())
    assert tf2.levels == {0: N} and tf2.errors == {}


def test_skewed_clocks_still_fold_identically(fleet):
    for node, s in [("n010", 300.0), ("n020", -300.0), ("n030", 4e6)]:
        fleet.skew(node, s)
    tf = tree_query(fleet)
    assert frame(tf.window) == frame(fleet.flat_reference())
    # the skew is visible (span stretched by the worst offender), just
    # never a fold divergence
    assert tf.window.end_ts - tf.window.start_ts > 4e6


def test_aggregator_crash_refolds_flat_exactly_once(fleet):
    # the deployed tier: one fetch_subtree hop per zone, with a nested
    # aggregator crashed — its failure surfaces at the root hop, the
    # whole fold falls back flat, and no leaf is pulled twice
    fetch_subtree = fleet.make_fetch_subtree(fail={"agg2-001"})
    tf = tree_query(fleet, fetch_subtree=fetch_subtree)
    assert tf.fallback == ["fleet"]
    assert any("aggregator unreachable" in d for d in tf.dropped)
    assert frame(tf.window) == frame(fleet.flat_reference())
    # exactly-once is an ACCOUNTING guarantee: the crashed subtree's
    # partial remote pulls are wasted network work, but no leaf enters
    # the merged answer twice — levels stays one count per agent
    assert tf.levels == {0: N}
    assert tf.aggregate["folded"] == N
    assert all(p == "flat-fallback" for p in tf.paths.values())


def test_chaos_soak_identity_holds_every_round(fleet):
    # churn + partition + skew layered across rounds; after each fault
    # the tree answer must still match the flat fold over whatever
    # roster is currently reachable
    rounds = [
        lambda: fleet.partition("n001", "n002", "n003"),
        lambda: fleet.kill("n050"),
        lambda: fleet.skew("n060", 120.0),
        lambda: [fleet.spawn(), fleet.heal("n002")],
        lambda: fleet.partition("n099"),
    ]
    before = {}
    for i, chaos in enumerate(rounds):
        chaos()
        before = dict(fleet.fetches)
        tf = tree_query(fleet)
        flat = fleet.flat_reference()
        assert frame(tf.window) == frame(flat), f"round {i} diverged"
        reachable = [n for n in fleet.nodes()
                     if n not in fleet.partitioned]
        assert tf.levels == {0: len(reachable)}
        # exactly-once per round: each reachable leaf +1 fetch, no more
        assert all(fleet.fetches[n] - before.get(n, 0) == 1
                   for n in reachable), f"round {i} double-counted"


def test_scaling_series_shapes():
    # the bench's agents axis, in miniature: identity at every N the
    # perf ledger publishes (100 covered above)
    for n in (4, 16, 64):
        fleet = SimFleet(n, n_windows=1)
        tf = tree_query(fleet)
        assert frame(tf.window) == frame(fleet.flat_reference()), n
        assert tf.levels == {0: n}
