"""Alerting-plane acceptance tier: synthetic events driven through
agent → GrpcRuntime with an `entropy_jump` rule.

Two in-process 'nodes' each run a controlled-batch gadget whose key
stream goes constant → uniform-random → constant, so the sketch plane's
harvested entropy genuinely jumps and then plateaus. The asserted
contract (ISSUE 4 acceptance):

- the alert transitions pending → firing → resolved with correct
  debounce timing (firing only after `for` held),
- it fires exactly ONCE cluster-wide when both nodes trip it,
- it appears in `ig-tpu alerts list` output,
- `ig_alerts_firing` shows up in the Prometheus exposition,
- every transition leaves a fact in the flight-recorder dump.
"""

from __future__ import annotations

import json
import tempfile
import time

import numpy as np
import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.agent.client import AgentClient
from inspektor_gadget_tpu.agent.service import serve
from inspektor_gadget_tpu.gadgets import GadgetContext
from inspektor_gadget_tpu.gadgets.interface import GadgetDesc, GadgetType
from inspektor_gadget_tpu.gadgets import registry as gadget_registry
from inspektor_gadget_tpu.operators import operators as op_registry
from inspektor_gadget_tpu.params import Collection, ParamDescs
from inspektor_gadget_tpu.sources.batch import EventBatch

RULE_ID = "entropy-jump"
FOR_S = 0.05
EPOCH_GAP_S = 0.08

RULES_DOC = json.dumps({"rules": [{
    "id": RULE_ID, "kind": "entropy_jump", "threshold": 1.0, "window": 3,
    "for": FOR_S, "cooldown": "5s", "severity": "warning",
}]})


class _AlertSynthGadget:
    """Batch gadget with a scripted key distribution: 3 constant-key
    epochs (entropy ~0), 3 uniform-key epochs (entropy jumps to ~7 bits),
    3 tiny constant epochs (entropy plateaus, the jump's baseline catches
    up and the alert resolves). One batch per harvest epoch."""

    def __init__(self, ctx):
        self.ctx = ctx
        self._batch_handler = None

    def set_batch_handler(self, handler):
        self._batch_handler = handler

    @staticmethod
    def _batch(keys: np.ndarray) -> EventBatch:
        n = len(keys)
        b = EventBatch.alloc(n, with_comm=False)
        b.cols["key_hash"][:] = keys.astype(np.uint64)
        b.cols["mntns"][:] = 1
        b.cols["ts"][:] = time.time_ns()
        b.count = n
        return b

    def run(self, ctx):
        rng = np.random.default_rng(7)
        phases = (
            [np.full(2048, 0xDEADBEEF, dtype=np.uint64)] * 3
            + [rng.integers(1, 2**32, 8192, dtype=np.uint64)
               for _ in range(3)]
            + [np.full(64, 0xDEADBEEF, dtype=np.uint64)] * 3
        )
        for keys in phases:
            if ctx.done:
                return
            if self._batch_handler is not None:
                self._batch_handler(self._batch(keys))
            ctx.sleep_or_done(EPOCH_GAP_S)


class _AlertSynthDesc(GadgetDesc):
    name = "alertsynth"
    category = "trace"
    gadget_type = GadgetType.TRACE
    description = "scripted-entropy batch gadget (alerting e2e)"
    event_cls = None

    def params(self) -> ParamDescs:
        return ParamDescs()

    def new_instance(self, ctx) -> _AlertSynthGadget:
        return _AlertSynthGadget(ctx)


@pytest.fixture(scope="module", autouse=True)
def synth_gadget():
    """Register the scripted gadget for this module only: leaving it in
    the global registry would drift docs/gadgets.md's generated table
    (tests/test_gadget_docs.py) and the doctor's gadget report."""
    desc = _AlertSynthDesc()
    gadget_registry.register(desc)
    yield desc
    gadget_registry._REGISTRY.pop((desc.category, desc.name), None)


@pytest.fixture(scope="module")
def agents():
    servers, targets = [], {}
    tmp = tempfile.mkdtemp()
    for i in range(2):
        addr = f"unix://{tmp}/alert-agent{i}.sock"
        server, _ = serve(addr, node_name=f"anode-{i}")
        servers.append(server)
        targets[f"anode-{i}"] = addr
    yield targets
    for s in servers:
        s.stop(grace=0.5)


def _op_params(webhook_path: str) -> Collection:
    col = Collection()
    ap = op_registry.get("alerts").instance_params().to_params()
    ap.set("rules", RULES_DOC)
    ap.set("webhook-file", webhook_path)
    col["operator.alerts."] = ap
    sp = op_registry.get("tpusketch").instance_params().to_params()
    for k, v in (("enable", "true"), ("depth", "4"), ("log2-width", "10"),
                 ("hll-p", "8"), ("entropy-log2-width", "8"),
                 ("topk", "16"), ("harvest-interval", "10ms")):
        sp.set(k, v)
    col["operator.tpusketch."] = sp
    return col


def test_entropy_jump_alert_end_to_end(agents, tmp_path, capsys):
    from inspektor_gadget_tpu.alerts import ACTIVE
    from inspektor_gadget_tpu.runtime.grpc_runtime import GrpcRuntime
    from inspektor_gadget_tpu.telemetry import render_prometheus
    from inspektor_gadget_tpu.telemetry.tracing import RECORDER

    webhook = tmp_path / "transitions.jsonl"
    ACTIVE.clear()
    cluster_events: list[dict] = []

    desc = gadget_registry.get("trace", "alertsynth")
    ctx = GadgetContext(desc, operator_params=_op_params(str(webhook)),
                        timeout=120.0)
    runtime = GrpcRuntime(dict(agents))
    try:
        result = runtime.run_gadget(ctx, on_alert=cluster_events.append)
    finally:
        runtime.close()
    assert not result.errors(), result.errors()

    # -- lifecycle: pending → firing → resolved, cluster-folded ------------
    transitions = [e["transition"] for e in cluster_events
                   if e["rule"] == RULE_ID]
    assert transitions == ["pending", "firing", "resolved"], cluster_events

    # exactly ONCE cluster-wide although both nodes tripped it
    firing = [e for e in cluster_events if e["transition"] == "firing"]
    assert len(firing) == 1

    # both nodes contributed: the store's cluster entry lists both, and
    # the final resolve carries the full node list
    cluster_rows = [a for a in ACTIVE.all()
                    if a["scope"] == "cluster" and a["rule"] == RULE_ID]
    assert cluster_rows and set(cluster_rows[0]["nodes"]) == set(agents)
    resolved = cluster_events[-1]
    assert set(resolved["nodes"]) == set(agents)

    # -- per-node evidence: the webhook-file sink saw the full lifecycle
    # from EACH node, with debounce timing (firing held >= `for`) --------
    from inspektor_gadget_tpu.alerts import WebhookFileSink
    by_node: dict[str, list[dict]] = {}
    for ev in WebhookFileSink.read(str(webhook)):
        by_node.setdefault(ev["node"], []).append(ev)
    assert set(by_node) == set(agents), sorted(by_node)
    for node, evs in by_node.items():
        seq = [e["transition"] for e in evs if e["rule"] == RULE_ID]
        assert seq == ["pending", "firing", "resolved"], (node, seq)
        pend = next(e for e in evs if e["transition"] == "pending")
        fire = next(e for e in evs if e["transition"] == "firing")
        # debounce: firing only after the condition HELD for `for`
        assert fire["ts"] - pend["ts"] >= FOR_S * 0.8, (node, evs)
        assert fire["value"] > 1.0  # the jump, in bits over threshold

    # -- surfaces ----------------------------------------------------------
    # `ig-tpu alerts list` shows the (now resolved) alert
    from inspektor_gadget_tpu.cli.main import main as cli_main
    assert cli_main(["alerts", "list"]) == 0
    out = capsys.readouterr().out
    assert RULE_ID in out and "resolved" in out

    # Prometheus exposition carries the firing gauge + transition counters
    text = render_prometheus()
    assert f'ig_alerts_firing{{rule="{RULE_ID}"' in text
    assert "ig_alerts_transitions_total" in text

    # flight recorder: the transition fact is in the dump (both the
    # in-process snapshot and the agent's DumpState view)
    facts = RECORDER.snapshot()["facts"]
    assert f"alert:{RULE_ID}:*" in facts, sorted(facts)
    assert facts[f"alert:{RULE_ID}:*"]["state"] == "resolved"
    client = AgentClient(next(iter(agents.values())), "anode-0")
    try:
        state = client.dump_state()
        assert f"alert:{RULE_ID}:*" in state["flight_record"]["facts"]
        # the agent's DumpState also carries its node-scope alert table
        node_rows = [a for a in state["alerts"]
                     if a["rule"] == RULE_ID and a["scope"] == "node"]
        assert node_rows and node_rows[0]["state"] == "resolved"
    finally:
        client.close()


def test_top_alerts_gadget_renders_table(agents):
    """The `top alerts` gadget renders whatever the e2e run left in the
    active-alert table through the ordinary column path."""
    from inspektor_gadget_tpu.alerts import ACTIVE
    from inspektor_gadget_tpu.runtime.local import LocalRuntime

    if not any(a["rule"] == RULE_ID for a in ACTIVE.all()):
        pytest.skip("e2e run did not populate the table (ran standalone?)")
    desc = gadget_registry.get("top", "alerts")
    params = desc.params().to_params()
    params.set("interval", "50ms")
    ctx = GadgetContext(desc, gadget_params=params, timeout=0.3)
    batches: list[list] = []
    result = LocalRuntime().run_gadget(ctx, on_event_array=batches.append)
    assert not result.errors()
    rows = [r for rows in batches for r in rows]
    assert any(r.rule == RULE_ID for r in rows), rows
    row = next(r for r in rows if r.rule == RULE_ID and r.scope == "cluster")
    assert set(row.nodes.split(",")) == set(agents)
    cols = ctx.columns
    line = __import__(
        "inspektor_gadget_tpu.columns", fromlist=["TextFormatter"]
    ).TextFormatter(cols).format_event(row)
    assert RULE_ID in line
