"""Tier-1 native-build smoke (ISSUE 10 satellite).

When this host carries a C++ toolchain, libigcapture.so must COMPILE
from native/Makefile and one `ig_source_pop_folded` batch must roundtrip
into a pinned staging block. Hosts without a toolchain skip VISIBLY, not
silently: the doctor's `native_toolchain` row reports the same facts the
skip condition reads, so a degraded CI host shows up in `ig-tpu doctor`
instead of as a quietly-green test run.
"""

import os
import shutil
import subprocess
from pathlib import Path

import numpy as np
import pytest

from inspektor_gadget_tpu.doctor import probe_windows

NATIVE = (Path(__file__).resolve().parent.parent
          / "inspektor_gadget_tpu" / "native")

_CXX = os.environ.get("CXX") or "g++"
_HAVE_TOOLCHAIN = bool(shutil.which(_CXX) and shutil.which("make"))

needs_toolchain = pytest.mark.skipif(
    not _HAVE_TOOLCHAIN,
    reason=f"no C++ toolchain ({_CXX}/make) — see doctor native_toolchain row")


def test_doctor_reports_toolchain_row():
    """The skip condition above and the doctor row must agree — that is
    what makes a toolchain-less skip visible instead of silent."""
    w = probe_windows()["native_toolchain"]
    assert w.ok == _HAVE_TOOLCHAIN
    if _HAVE_TOOLCHAIN:
        assert "present" in w.detail
    else:
        assert "missing" in w.detail
        assert "smoke tier skips" in w.detail


@needs_toolchain
def test_makefile_builds_capture_library():
    r = subprocess.run(["make", "-C", str(NATIVE)], capture_output=True,
                       text=True, timeout=600)
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    assert (NATIVE / "libigcapture.so").exists()


@needs_toolchain
def test_pop_folded_roundtrips_one_batch():
    """One ig_source_pop_folded batch through a pinned pool block: the
    exporter must fill all three SoA lanes, and the folded key universe
    must be exactly the xor-fold of the 64-bit key universe the classic
    pop path reports (tiny vocab → both paths certainly see every key)."""
    import time

    from inspektor_gadget_tpu.sources import PinnedBufferPool
    from inspektor_gadget_tpu.sources.bridge import (
        SRC_SYNTH_EXEC, NativeCapture, native_available,
    )
    assert native_available()
    src = NativeCapture(SRC_SYNTH_EXEC, seed=11, rate=2_000_000, vocab=8,
                        batch_size=4096)
    pool = PinnedBufferPool(4096)
    block = pool.get()
    try:
        with src:
            time.sleep(0.2)
            classic = src.pop()
            assert classic.count > 0
            time.sleep(0.2)
            fb = src.pop_folded(block)
        assert fb.count > 0
        assert fb.capacity == 4096
        assert (fb.weights[:fb.count] == 1).all()
        assert (fb.keys[:fb.count] != 0).all()
        # fold law: the folded lane's key set ⊆ fold64(classic key set)
        # (vocab=8 → every key appears in both multi-thousand-row pops)
        k64 = classic.cols["key_hash"][:classic.count].astype(np.uint64)
        fold = ((k64 >> np.uint64(32))
                ^ (k64 & np.uint64(0xFFFFFFFF))).astype(np.uint32)
        assert set(fb.keys[:fb.count].tolist()) <= set(fold.tolist())
        # mntns lane folds the same way (synthetic ns ids are < 2^32, so
        # the fold is the identity and must land in the classic set)
        m64 = classic.cols["mntns"][:classic.count].astype(np.uint64)
        mfold = ((m64 >> np.uint64(32))
                 ^ (m64 & np.uint64(0xFFFFFFFF))).astype(np.uint32)
        assert set(fb.mntns[:fb.count].tolist()) <= set(mfold.tolist())
    finally:
        src.close()
        pool.put(block)


@needs_toolchain
def test_stale_library_rebuilds_for_new_symbol(tmp_path):
    """The bridge must rebuild a stale .so that predates
    ig_source_pop_folded instead of crashing on the missing symbol (the
    AttributeError → rebuild path in sources.bridge._load)."""
    import ctypes

    lib = ctypes.CDLL(str(NATIVE / "libigcapture.so"))
    assert hasattr(lib, "ig_source_pop_folded")
