"""Columns system tests (coverage model: pkg/columns/columns_test.go, 448 LoC)."""

import dataclasses

import numpy as np
import pytest

from inspektor_gadget_tpu.columns import (
    Columns,
    col,
    parse_filters,
    match_event,
    columnar_mask,
    parse_sort,
    sort_events,
    columnar_argsort,
    group_events,
    TextFormatter,
    truncate,
)
from inspektor_gadget_tpu.columns.columns import fnv1a64


@dataclasses.dataclass
class Ev:
    comm: str = col("", width=16)
    pid: int = col(0, width=7, dtype=np.int32)
    reads: int = col(0, width=10, group="sum", dtype=np.int64)
    lat: float = col(0.0, width=8, precision=3, dtype=np.float32)
    hidden: int = col(0, hide=True, dtype=np.int32)


@pytest.fixture
def cols():
    return Columns(Ev)


def events():
    return [
        Ev("bash", 10, 5, 1.5),
        Ev("curl", 20, 3, 0.25),
        Ev("bash", 30, 7, 2.0),
        Ev("python", 5, 1, 9.125),
    ]


def test_registry_names_and_visibility(cols):
    assert cols.names() == ["comm", "pid", "reads", "lat"]
    assert cols.names(visible_only=False) == ["comm", "pid", "reads", "lat", "hidden"]
    assert cols.get("PID").dtype == np.dtype(np.int32)
    assert cols.get("comm").is_string


def test_set_visible_reorders(cols):
    cols.set_visible(["pid", "comm"])
    assert cols.names() == ["pid", "comm"]


def test_duplicate_column_rejected():
    @dataclasses.dataclass
    class Dup:
        a: int = col(0, name="x")
        b: int = col(0, name="x")

    with pytest.raises(ValueError, match="duplicate"):
        Columns(Dup)


def test_to_dict_json_roundtrip(cols):
    ev = Ev("bash", 10, 5, 1.5)
    d = cols.to_dict(ev)
    assert d["comm"] == "bash" and d["pid"] == 10
    back = cols.from_dict(d)
    assert back == ev


# -- filters (ref: pkg/columns/filter/filter_test.go) -----------------------

def test_filter_exact_and_negated(cols):
    fs = parse_filters("comm:bash", cols)
    got = [e for e in events() if match_event(e, fs, cols)]
    assert len(got) == 2
    fs = parse_filters("comm:!bash", cols)
    got = [e for e in events() if match_event(e, fs, cols)]
    assert {e.comm for e in got} == {"curl", "python"}


def test_filter_numeric_comparisons(cols):
    fs = parse_filters("pid:>=20", cols)
    got = [e for e in events() if match_event(e, fs, cols)]
    assert {e.pid for e in got} == {20, 30}
    fs = parse_filters("lat:<1", cols)
    got = [e for e in events() if match_event(e, fs, cols)]
    assert [e.comm for e in got] == ["curl"]


def test_filter_regex_and_multi(cols):
    fs = parse_filters("comm:~^py,pid:<10", cols)
    got = [e for e in events() if match_event(e, fs, cols)]
    assert [e.comm for e in got] == ["python"]


def test_filter_unknown_column(cols):
    with pytest.raises(ValueError, match="unknown column"):
        parse_filters("nope:1", cols)


def test_columnar_mask_matches_rowwise(cols):
    vocab: dict[int, str] = {}
    batch = cols.tensorize(events(), vocab)
    fs = parse_filters("comm:bash,reads:>5", cols)
    mask = columnar_mask(batch, fs, cols, vocab)
    row = [match_event(e, fs, cols) for e in events()]
    assert mask.tolist() == row


# -- sort (ref: pkg/columns/sort/sort_test.go) ------------------------------

def test_sort_multi_key(cols):
    specs = parse_sort("comm,-pid", cols)
    out = sort_events(events(), specs, cols)
    assert [(e.comm, e.pid) for e in out] == [
        ("bash", 30), ("bash", 10), ("curl", 20), ("python", 5),
    ]


def test_columnar_argsort_matches(cols):
    batch = cols.tensorize(events())
    specs = parse_sort("-reads", cols)
    idx = columnar_argsort(batch, specs, cols)
    assert batch["reads"][idx].tolist() == [7, 5, 3, 1]


# -- group (ref: pkg/columns/group/group_test.go) ---------------------------

def test_group_by_sums_annotated(cols):
    out = group_events(events(), ["comm"], cols)
    by = {e.comm: e.reads for e in out}
    assert by == {"bash": 12, "curl": 3, "python": 1}


# -- tensorize --------------------------------------------------------------

def test_tensorize_dtypes_and_hash(cols):
    vocab: dict[int, str] = {}
    batch = cols.tensorize(events(), vocab)
    assert batch["pid"].dtype == np.int32
    assert batch["comm"].dtype == np.uint64
    assert vocab[int(batch["comm"][0])] == "bash"
    assert batch["comm"][0] == np.uint64(fnv1a64("bash"))
    assert batch["comm"][0] == batch["comm"][2]  # same string, same hash


# -- formatter (ref: formatter/textcolumns tests) ---------------------------

def test_formatter_header_and_rows(cols):
    f = TextFormatter(cols)
    h = f.header()
    assert h.startswith("COMM")
    row = f.format_event(Ev("bash", 10, 5, 1.5))
    assert "bash" in row and "1.500" in row
    assert "hidden" not in h.lower()


def test_formatter_width_scaling(cols):
    f = TextFormatter(cols, max_width=25)
    assert all(len(line) <= 25 for line in f.format_table(events()).splitlines())


def test_formatter_fast_cache_invalidated_by_visibility_change():
    """Regression: format_event compiles per-column specs once (_fast)
    and must recompile when the Columns layout changes AFTER the first
    row rendered — a stale cache kept rendering hidden (e.g.
    kubernetes-tagged) columns, disagreeing with the header."""
    @dataclasses.dataclass
    class KEv:
        comm: str = col("", width=8)
        pod: str = col("", width=12, tags=("kubernetes",))

    kcols = Columns(KEv)
    f = TextFormatter(kcols)
    assert "pod-a" in f.format_event(KEv("bash", "pod-a"))
    kcols.hide_tagged(["kubernetes"])
    assert "pod" not in f.header().lower()
    assert "pod-a" not in f.format_event(KEv("bash", "pod-a"))
    # and back the other way: re-show in a new order via set_visible
    kcols.set_visible(["pod", "comm"])
    assert f.format_event(KEv("bash", "pod-a")).startswith("pod-a")


def test_truncate_modes():
    assert truncate("abcdefgh", 5, "end") == "abcd…"
    assert truncate("abcdefgh", 5, "start") == "…efgh"
    assert truncate("abcdefgh", 5, "middle") == "ab…gh"
    assert truncate("abc", 5, "end") == "abc"
    assert truncate("abcdefgh", 5, "none") == "abcde"
