"""DDSketch quantile-plane tests: relative-error guarantee, mergeability
(sharded == sequential, the cluster-merge contract), log2 re-binning parity
with the reference's biolatency histogram."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from inspektor_gadget_tpu.ops import (
    dd_histogram_log2, dd_init, dd_merge, dd_psum, dd_quantile, dd_update,
)


def test_quantile_relative_error_bound():
    rng = np.random.default_rng(0)
    vals = rng.lognormal(mean=-6.0, sigma=2.0, size=20000).astype(np.float32)
    sk = dd_init(alpha=0.01)
    sk = jax.jit(dd_update)(sk, jnp.asarray(vals))
    for q in (0.5, 0.9, 0.95, 0.99):
        est = float(dd_quantile(sk, q))
        true = float(np.quantile(vals, q))
        assert abs(est - true) / true < 0.02, (q, est, true)


def test_zero_bucket_and_empty():
    sk = dd_init(alpha=0.02)
    assert np.isnan(float(dd_quantile(sk, 0.5)))
    vals = jnp.asarray([0.0, 0.0, 0.0, 1.0], jnp.float32)
    sk = dd_update(sk, vals)
    assert float(sk.zeros) == 3.0
    assert float(dd_quantile(sk, 0.25)) == 0.0   # rank inside zero bucket
    est = float(dd_quantile(sk, 1.0))
    assert abs(est - 1.0) < 0.05


def test_mask_and_merge_equals_sequential():
    rng = np.random.default_rng(1)
    a = rng.exponential(0.01, 4096).astype(np.float32)
    b = rng.exponential(0.10, 4096).astype(np.float32)
    mask = np.ones(4096, bool)
    mask[2048:] = False  # padding slots must not count
    sk_a = dd_update(dd_init(), jnp.asarray(a), jnp.asarray(mask))
    sk_b = dd_update(dd_init(), jnp.asarray(b), jnp.asarray(mask))
    merged = dd_merge(sk_a, sk_b)
    seq = dd_update(dd_update(dd_init(), jnp.asarray(a), jnp.asarray(mask)),
                    jnp.asarray(b), jnp.asarray(mask))
    np.testing.assert_array_equal(np.asarray(merged.counts),
                                  np.asarray(seq.counts))
    assert float(merged.total) == float(mask.sum()) * 2
    both = np.concatenate([a[:2048], b[:2048]])
    est = float(dd_quantile(merged, 0.5))
    true = float(np.quantile(both, 0.5))
    assert abs(est - true) / true < 0.02


def test_cluster_psum_merge_over_mesh():
    """Per-node latency shards psum-merged == global sketch (the
    snapshotcombiner role for quantiles)."""
    rng = np.random.default_rng(2)
    vals = rng.lognormal(-5.0, 1.0, (8, 2048)).astype(np.float32)
    mesh = Mesh(np.array(jax.devices()[:8]), ("node",))

    def update_and_merge(v):
        sk = dd_update(dd_init(), v)
        return dd_psum(sk, "node")

    from inspektor_gadget_tpu.parallel.compat import shard_map
    merged = jax.jit(shard_map(
        update_and_merge, mesh=mesh, in_specs=P("node"),
        out_specs=P(), check_vma=False))(jnp.asarray(vals))
    est = float(dd_quantile(merged, 0.95))
    true = float(np.quantile(vals.reshape(-1), 0.95))
    assert float(merged.total) == vals.size
    assert abs(est - true) / true < 0.02


def test_log2_rebinning_conserves_counts():
    rng = np.random.default_rng(3)
    vals = rng.lognormal(-7.0, 1.5, 8192).astype(np.float32)
    sk = dd_update(dd_init(), jnp.asarray(vals))
    hist = dd_histogram_log2(sk)
    assert float(hist.sum()) == float(sk.counts.sum())
    # mass concentrates around log2(us) of the distribution median
    med_us = np.quantile(vals, 0.5) * 1e6
    peak_slot = int(np.argmax(np.asarray(hist)))
    assert abs(peak_slot - np.log2(med_us)) <= 2.5
