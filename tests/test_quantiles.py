"""DDSketch quantile-plane tests: relative-error guarantee, mergeability
(sharded == sequential, the cluster-merge contract), log2 re-binning parity
with the reference's biolatency histogram."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from inspektor_gadget_tpu.ops import (
    dd_histogram_log2, dd_init, dd_merge, dd_psum, dd_quantile, dd_update,
)


def test_quantile_relative_error_bound():
    rng = np.random.default_rng(0)
    vals = rng.lognormal(mean=-6.0, sigma=2.0, size=20000).astype(np.float32)
    sk = dd_init(alpha=0.01)
    sk = jax.jit(dd_update)(sk, jnp.asarray(vals))
    for q in (0.5, 0.9, 0.95, 0.99):
        est = float(dd_quantile(sk, q))
        true = float(np.quantile(vals, q))
        assert abs(est - true) / true < 0.02, (q, est, true)


def test_zero_bucket_and_empty():
    sk = dd_init(alpha=0.02)
    assert np.isnan(float(dd_quantile(sk, 0.5)))
    vals = jnp.asarray([0.0, 0.0, 0.0, 1.0], jnp.float32)
    sk = dd_update(sk, vals)
    assert float(sk.zeros) == 3.0
    assert float(dd_quantile(sk, 0.25)) == 0.0   # rank inside zero bucket
    est = float(dd_quantile(sk, 1.0))
    assert abs(est - 1.0) < 0.05


def test_mask_and_merge_equals_sequential():
    rng = np.random.default_rng(1)
    a = rng.exponential(0.01, 4096).astype(np.float32)
    b = rng.exponential(0.10, 4096).astype(np.float32)
    mask = np.ones(4096, bool)
    mask[2048:] = False  # padding slots must not count
    sk_a = dd_update(dd_init(), jnp.asarray(a), jnp.asarray(mask))
    sk_b = dd_update(dd_init(), jnp.asarray(b), jnp.asarray(mask))
    merged = dd_merge(sk_a, sk_b)
    seq = dd_update(dd_update(dd_init(), jnp.asarray(a), jnp.asarray(mask)),
                    jnp.asarray(b), jnp.asarray(mask))
    np.testing.assert_array_equal(np.asarray(merged.counts),
                                  np.asarray(seq.counts))
    assert float(merged.total) == float(mask.sum()) * 2
    both = np.concatenate([a[:2048], b[:2048]])
    est = float(dd_quantile(merged, 0.5))
    true = float(np.quantile(both, 0.5))
    assert abs(est - true) / true < 0.02


def test_cluster_psum_merge_over_mesh():
    """Per-node latency shards psum-merged == global sketch (the
    snapshotcombiner role for quantiles)."""
    rng = np.random.default_rng(2)
    vals = rng.lognormal(-5.0, 1.0, (8, 2048)).astype(np.float32)
    mesh = Mesh(np.array(jax.devices()[:8]), ("node",))

    def update_and_merge(v):
        sk = dd_update(dd_init(), v)
        return dd_psum(sk, "node")

    from inspektor_gadget_tpu.parallel.compat import shard_map
    merged = jax.jit(shard_map(
        update_and_merge, mesh=mesh, in_specs=P("node"),
        out_specs=P(), check_vma=False))(jnp.asarray(vals))
    est = float(dd_quantile(merged, 0.95))
    true = float(np.quantile(vals.reshape(-1), 0.95))
    assert float(merged.total) == vals.size
    assert abs(est - true) / true < 0.02


def test_log2_rebinning_conserves_counts():
    rng = np.random.default_rng(3)
    vals = rng.lognormal(-7.0, 1.5, 8192).astype(np.float32)
    sk = dd_update(dd_init(), jnp.asarray(vals))
    hist = dd_histogram_log2(sk)
    assert float(hist.sum()) == float(sk.counts.sum())
    # mass concentrates around log2(us) of the distribution median
    med_us = np.quantile(vals, 0.5) * 1e6
    peak_slot = int(np.argmax(np.asarray(hist)))
    assert abs(peak_slot - np.log2(med_us)) <= 2.5


def test_int32_counts_exact_past_f32_mantissa():
    """The count lanes are int32 on purpose: an f32 tally silently stops
    incrementing at 2^24 (x + 1 == x). Seed a bucket at exactly 2^24 and
    fold one more value into it — the increment must land."""
    sk = dd_init(alpha=0.01, min_value=1.0)
    seed = 1 << 24
    sk = sk.replace(counts=sk.counts.at[100].set(seed),
                    total=jnp.asarray(seed, jnp.int32))
    # bucket-100 midpoint: ceil(log_gamma(mid)) == 100
    mid = 2.0 * sk.gamma ** 100 / (sk.gamma + 1.0)
    sk = jax.jit(dd_update)(sk, jnp.asarray([mid], jnp.float32))
    assert int(sk.counts[100]) == seed + 1
    assert int(sk.total) == seed + 1


def test_quantile_monotone_in_q():
    rng = np.random.default_rng(4)
    vals = rng.lognormal(-5.0, 2.5, 10000).astype(np.float32)
    sk = dd_update(dd_init(alpha=0.02), jnp.asarray(vals))
    qs = [0.0, 0.01, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99, 0.999, 1.0]
    reads = [float(dd_quantile(sk, q)) for q in qs]
    assert all(a <= b for a, b in zip(reads, reads[1:])), reads


def test_merge_order_invariance():
    """Bucket-wise int adds are associative AND commutative, so any fold
    order over node shards yields bit-identical lanes — the property the
    sealed-window pushdown/client-side fold split relies on."""
    rng = np.random.default_rng(5)
    chunks = [rng.exponential(10.0 ** -i, 1024).astype(np.float32)
              for i in range(4)]
    sketches = [dd_update(dd_init(), jnp.asarray(c)) for c in chunks]
    fwd = sketches[0]
    for s in sketches[1:]:
        fwd = dd_merge(fwd, s)
    rev = sketches[3]
    for s in (sketches[1], sketches[2], sketches[0]):
        rev = dd_merge(rev, s)
    np.testing.assert_array_equal(np.asarray(fwd.counts),
                                  np.asarray(rev.counts))
    assert int(fwd.zeros) == int(rev.zeros)
    assert int(fwd.total) == int(rev.total)


def test_psum_equals_pairwise_merge():
    """dd_psum over a mesh axis must be bit-identical to folding the
    per-shard sketches with dd_merge on the host."""
    rng = np.random.default_rng(6)
    vals = rng.lognormal(-6.0, 1.5, (8, 512)).astype(np.float32)
    mesh = Mesh(np.array(jax.devices()[:8]), ("node",))
    from inspektor_gadget_tpu.parallel.compat import shard_map
    merged = jax.jit(shard_map(
        lambda v: dd_psum(dd_update(dd_init(), v), "node"),
        mesh=mesh, in_specs=P("node"), out_specs=P(),
        check_vma=False))(jnp.asarray(vals))
    pair = dd_update(dd_init(), jnp.asarray(vals[0]))
    for row in vals[1:]:
        pair = dd_merge(pair, dd_update(dd_init(), jnp.asarray(row)))
    np.testing.assert_array_equal(np.asarray(merged.counts),
                                  np.asarray(pair.counts))
    assert int(merged.zeros) == int(pair.zeros)
    assert int(merged.total) == int(pair.total)


def test_host_twins_match_device_reads():
    """dd_quantile_np / dd_histogram_log2_np (the sealed-window fold path)
    agree with the device reads over the same raw lanes."""
    from inspektor_gadget_tpu.ops.quantiles import (
        dd_histogram_log2_np, dd_quantile_np,
    )
    rng = np.random.default_rng(7)
    vals = rng.lognormal(-5.5, 1.8, 8192).astype(np.float32)
    vals[:100] = 0.0  # exercise the zero bucket
    sk = dd_update(dd_init(), jnp.asarray(vals))
    counts = np.asarray(sk.counts)
    zeros, total = int(sk.zeros), int(sk.total)
    for q in (0.005, 0.5, 0.9, 0.99):
        dev = float(dd_quantile(sk, q))
        host = float(dd_quantile_np(counts, zeros, total, q,
                                    alpha=sk.alpha, min_value=sk.min_value))
        assert np.isclose(dev, host, rtol=1e-5), (q, dev, host)
    # array-q form matches the scalar reads
    arr = dd_quantile_np(counts, zeros, total, np.asarray([0.5, 0.99]),
                         alpha=sk.alpha, min_value=sk.min_value)
    assert arr.shape == (2,)
    # empty sketch: NaN on both twins
    assert np.isnan(float(dd_quantile_np(np.zeros(16), 0, 0, 0.5)))
    dev_hist = np.asarray(dd_histogram_log2(sk))
    host_hist = dd_histogram_log2_np(counts, alpha=sk.alpha,
                                     min_value=sk.min_value)
    np.testing.assert_array_equal(dev_hist, host_hist)
