"""Params system tests (coverage model: pkg/params/*_test.go)."""

import pytest

from inspektor_gadget_tpu.params import (
    Collection,
    ParamDesc,
    ParamDescs,
    ParamError,
    TypeHint,
    parse_duration,
    validate_int_range,
    validate_one_of,
)
from inspektor_gadget_tpu.params.params import descs_from_json


def make_descs():
    return ParamDescs([
        ParamDesc(key="timeout", default="0", type_hint=TypeHint.DURATION),
        ParamDesc(key="max-rows", default="20", type_hint=TypeHint.INT,
                  validator=validate_int_range(1, 100)),
        ParamDesc(key="sort", default="-reads"),
        ParamDesc(key="host", default="false", type_hint=TypeHint.BOOL),
        ParamDesc(key="mode", default="all", possible_values=("all", "new")),
    ])


def test_defaults_and_typed_getters():
    p = make_descs().to_params()
    assert p.get("max-rows").as_int() == 20
    assert p.get("host").as_bool() is False
    assert p.get("sort").as_string() == "-reads"
    assert p.get("timeout").as_duration() == 0.0


def test_set_validates():
    p = make_descs().to_params()
    p.set("max-rows", "50")
    assert p.get("max-rows").as_int() == 50
    with pytest.raises(ParamError):
        p.set("max-rows", "500")
    with pytest.raises(ParamError):
        p.set("mode", "bogus")
    with pytest.raises(ParamError):
        p.set("host", "maybe")


def test_set_non_string_coerced():
    p = make_descs().to_params()
    p.set("host", True)
    assert p.get("host").as_bool() is True
    p.set("max-rows", 3)
    assert p.get("max-rows").as_int() == 3


def test_duration_parsing():
    assert parse_duration("1m30s") == 90.0
    assert parse_duration("500ms") == 0.5
    assert parse_duration("2h") == 7200.0
    assert parse_duration("15") == 15.0
    with pytest.raises(ValueError):
        parse_duration("abc")


def test_copy_map_roundtrip_with_prefix():
    p = make_descs().to_params()
    p.set("sort", "comm")
    wire = p.copy_to_map(prefix="gadget.")
    assert wire["gadget.sort"] == "comm"
    q = make_descs().to_params()
    q.copy_from_map(wire, prefix="gadget.")
    assert q.get("sort").as_string() == "comm"


def test_collection_prefixes():
    coll = Collection({
        "gadget.": make_descs().to_params(),
        "operator.sketch.": ParamDescs([
            ParamDesc(key="width", default="2048", type_hint=TypeHint.INT),
        ]).to_params(),
    })
    wire = {"gadget.max-rows": "5", "operator.sketch.width": "4096", "junk": "x"}
    coll.copy_from_map(wire)
    assert coll["gadget."].get("max-rows").as_int() == 5
    assert coll["operator.sketch."].get("width").as_int() == 4096
    out = coll.copy_to_map()
    assert out["operator.sketch.width"] == "4096"


def test_catalog_json_roundtrip():
    p = make_descs().to_params()
    j = p.to_descs_json()
    descs = descs_from_json(j)
    q = descs.to_params()
    assert q.get("mode").desc.possible_values == ("all", "new")
    assert q.get("max-rows").as_int() == 20


def test_mandatory_param():
    descs = ParamDescs([ParamDesc(key="name", is_mandatory=True)])
    p = descs.to_params()
    with pytest.raises(ParamError):
        p.validate()
    p.set("name", "x")
    p.validate()


def test_validate_one_of():
    v = validate_one_of(["a", "b"])
    v("a")
    with pytest.raises(ValueError):
        v("c")
