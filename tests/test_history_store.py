"""Sketch-history store tier: window codec roundtrip, digest
determinism, torn-window accounting, index-key pruning, retention GC,
slice sketch accuracy, and the merge algebra's failure accounting.

The e2e contract (2-agent range queries, kill-mid-seal, replay digest
reproduction) lives in tests/test_history_query_e2e.py; this file pins
the store and codec invariants those journeys rest on.
"""

from __future__ import annotations

import os
import zlib

import numpy as np
import pytest

from inspektor_gadget_tpu.agent import wire
from inspektor_gadget_tpu.history import (
    HISTORY,
    HistoryStore,
    SealedWindow,
    SliceSketch,
    answer_query,
    decode_frames,
    decode_window,
    encode_window,
    header_overlaps,
    merge_windows,
    pack_frames,
    unpack_frames,
    validate_store_name,
    window_digest,
)


@pytest.fixture()
def store(tmp_path):
    s = HistoryStore()
    s.set_base_dir(str(tmp_path))
    yield s
    s.close_all()


def _window(i: int, *, keys: np.ndarray | None = None, gadget="trace/exec",
            node="n0", slices=True, width=64) -> SealedWindow:
    rng = np.random.default_rng(i)
    if keys is None:
        keys = rng.integers(1, 500, 256, dtype=np.uint32)
    sl = {}
    if slices:
        s = SliceSketch()
        s.update(keys, keys, keys)
        sl[f"mntns:{i % 2}"] = {"events": s.events, "hll": s.hll,
                                "ent": s.ent, "hh": s.sealed_hh()}
    w = SealedWindow(
        gadget=gadget, node=node, run_id="r", window=i,
        start_ts=1000.0 + i, end_ts=1001.0 + i,
        events=len(keys), drops=i % 3,
        cms=rng.integers(0, 9, (4, width)).astype(np.int32),
        hll=rng.integers(0, 5, 256).astype(np.int32),
        ent=rng.integers(0, 9, 64).astype(np.float32),
        topk_keys=np.array([1, 2, 3], np.uint32),
        topk_counts=np.array([30, 20, 10], np.int64),
        slices=sl, names={1: "bash"})
    w.digest = window_digest(w)
    return w


# -- codec -------------------------------------------------------------------

def test_window_roundtrip_and_digest_stability():
    w = _window(1)
    header, payload = encode_window(w)
    back = decode_window({**header, "seq": 42}, payload)
    assert back.seq == 42
    assert back.events == w.events and back.drops == w.drops
    assert np.array_equal(back.cms, w.cms)
    assert np.array_equal(back.hll, w.hll)
    assert back.slices.keys() == w.slices.keys()
    assert back.names == {1: "bash"}
    # digest is over decoded VALUES, so it survives the codec and does
    # NOT depend on wall timestamps (the replay-determinism anchor)
    assert window_digest(back) == w.digest
    shifted = decode_window({**header, "start_ts": 9e9, "end_ts": 9e9},
                            payload)
    assert window_digest(shifted) == w.digest
    # ...but any state change shows
    back.events += 1
    assert window_digest(back) != w.digest


def test_header_overlap_rule():
    h = {"start_ts": 10.0, "end_ts": 20.0, "seq": 5, "keys": ["mntns:1"]}
    assert header_overlaps(h)
    assert header_overlaps(h, start_ts=15.0)          # straddles
    assert not header_overlaps(h, start_ts=20.5)
    assert not header_overlaps(h, end_ts=9.0)
    assert header_overlaps(h, start_seq=5, end_seq=5)
    assert not header_overlaps(h, start_seq=6)
    assert header_overlaps(h, key="mntns:1")
    assert not header_overlaps(h, key="mntns:2")


# -- store -------------------------------------------------------------------

def test_append_list_fetch_roundtrip(store, tmp_path):
    w = store.writer_for("trace/exec", node="n0")
    for i in range(1, 4):
        store.append_window(_window(i), writer=w)
    store.release(w)
    rows = store.list_windows()
    assert [r["window"] for r in rows] == [1, 2, 3]
    assert all(r["digest"] for r in rows)
    # seq/ts range restriction
    assert len(store.list_windows(start_ts=1002.5)) == 2
    assert len(store.list_windows(start_seq=3)) == 1
    # slice-key restriction (odd windows carry mntns:1)
    assert [r["window"] for r in store.list_windows(key="mntns:1")] == [1, 3]
    frames = list(store.fetch_windows(key="mntns:0"))
    wins = decode_frames(frames)
    assert [x.window for x in wins] == [2]


def test_range_end_keeps_straddling_window(store, tmp_path):
    """Regression (review finding): a window straddling the query's END
    bound must be included — the frame ts is the window's end_ts, and
    pushing end_ts into the reader's per-record filter silently dropped
    exactly the window that overlaps the range end."""
    w = store.writer_for("trace/exec", node="n0")
    win = _window(1)
    win.start_ts, win.end_ts = 10.0, 20.0
    store.append_window(win, writer=w)
    rows = store.list_windows(start_ts=5.0, end_ts=15.0)
    assert [r["window"] for r in rows] == [1]
    # and a range strictly before/after still excludes it
    assert store.list_windows(end_ts=9.0) == []
    assert store.list_windows(start_ts=20.5) == []


def test_index_rows_carry_slice_keys_and_window_counts(store, tmp_path):
    from inspektor_gadget_tpu.utils.journal import read_jsonl
    w = store.writer_for("trace/exec", node="n0")
    for i in range(1, 4):
        store.append_window(_window(i), writer=w)
    store.release(w)  # seals the active segment
    rows = read_jsonl(
        str(tmp_path / "n0--trace-exec" / "index.jsonl")).records
    assert rows
    assert rows[-1]["windows"] == 3
    assert set(rows[-1]["keys"]) == {"mntns:0", "mntns:1"}


def test_torn_window_dropped_and_accounted(store, tmp_path):
    """A kill mid-seal leaves exactly one torn window at the active
    segment's tail: readers drop it, account it, and every earlier
    window survives."""
    w = store.writer_for("trace/exec", node="n0")
    for i in range(1, 4):
        store.append_window(_window(i), writer=w)
    seg = tmp_path / "n0--trace-exec" / "seg-00000001.igj"
    header, payload = encode_window(_window(4))
    zp = zlib.compress(wire.encode_msg(
        {**header, "type": wire.EV_WINDOW, "seq": 4, "ts": 0.0}, payload), 1)
    torn = (len(zp).to_bytes(4, "little")
            + (zlib.crc32(zp) & 0xFFFFFFFF).to_bytes(4, "little") + zp)
    with open(seg, "ab") as f:
        f.write(torn[: len(torn) // 2])
    losses: list = []
    rows = store.list_windows(losses=losses)
    assert [r["window"] for r in rows] == [1, 2, 3]
    assert len(losses) == 1
    assert losses[0]["dropped_bytes"] == len(torn) // 2
    # reopening the store for writing truncates the tear and continues
    store.close_all()
    w2 = store.writer_for("trace/exec", node="n0")
    seq = store.append_window(_window(5), writer=w2)
    assert seq == 4  # continues after the last GOOD window
    assert [r["window"] for r in store.list_windows()] == [1, 2, 3, 5]


def test_retention_gc_never_touches_active_segment(store, tmp_path):
    rng = np.random.default_rng(0)
    w = store.writer_for(
        "trace/exec", node="n0",
        max_segment_bytes=1 << 12, max_segment_age=0, retention_segments=1)
    for i in range(1, 7):
        big = rng.integers(1, 2**30, 2048, dtype=np.uint32)
        win = _window(i, slices=False, width=512)
        win.cms = big.reshape(4, 512).astype(np.int32)
        store.append_window(win, writer=w)
    segs = sorted(os.listdir(tmp_path / "n0--trace-exec"))
    seg_files = [s for s in segs if s.startswith("seg-")]
    # GC bounded the sealed history to 1 + the active segment
    assert len(seg_files) <= 2
    # the ACTIVE (highest-numbered) segment always survives
    assert seg_files[-1] == sorted(seg_files)[-1]
    rows = store.list_windows()
    assert rows, "GC must never empty the store"


def test_store_name_guard():
    for bad in ("", ".", "..", "a/b", "/abs"):
        with pytest.raises(ValueError):
            validate_store_name(bad)
    assert validate_store_name("trace-exec") == "trace-exec"


# -- pack/unpack (the FetchWindows chunk format) -----------------------------

def test_pack_unpack_tolerates_truncated_tail():
    frames = [encode_window(_window(i)) for i in (1, 2, 3)]
    blob = pack_frames([({**h, "seq": i + 1}, p)
                        for i, (h, p) in enumerate(frames)])
    back, dropped = unpack_frames(blob)
    assert len(back) == 3 and dropped == 0
    cut, dropped = unpack_frames(blob[: len(blob) - 7])
    assert len(cut) == 2 and dropped > 0


# -- merge accounting --------------------------------------------------------

def test_merge_skips_and_reports_geometry_mismatch():
    a, b = _window(1), _window(2)
    odd = _window(3, width=128)  # different CMS geometry
    merged = merge_windows([a, b, odd])
    assert merged.windows == 2
    assert len(merged.skipped) == 1 and "geometry" in merged.skipped[0]
    ans = answer_query([a, b, odd])
    assert ans.windows == 2 and ans.dropped_windows


def test_slice_sketch_answers_within_documented_error():
    """Slice cardinality/entropy from sealed state vs ground truth: the
    p=8 slice HLL documents ~6.5% standard error (worse in the linear-
    counting crossover), entropy is near-exact for < 64 distinct."""
    rng = np.random.default_rng(5)
    keys = rng.integers(1, 17, 20_000, dtype=np.uint32)  # 16 distinct
    s = SliceSketch()
    for i in range(0, len(keys), 4096):
        chunk = keys[i:i + 4096]
        s.update(chunk, chunk, chunk)
    w = _window(1, slices=False)
    w.slices["mntns:7"] = {"events": s.events, "hll": s.hll, "ent": s.ent,
                           "hh": s.sealed_hh()}
    ans = answer_query([w], key="mntns:7").slices["mntns:7"]
    assert ans["events"] == len(keys)
    assert abs(ans["distinct"] - 16) / 16 < 0.2
    # 16 equiprobable keys → 4 bits, biased down only by the (rare at
    # 16-in-64 occupancy) bucket collisions
    assert abs(ans["entropy_bits"] - 4.0) < 0.35
    hh_keys = {h["key"] for h in ans["heavy_hitters"]}
    assert len(hh_keys) >= 10  # truncated-exact table kept the heavy keys


def test_slice_merge_across_windows_equals_single_pass():
    """Slice HLL max-merge and entropy add across windows reproduce the
    single-pass slice sketch exactly (the mergeability property the
    whole plane rests on, asserted at the slice tier too)."""
    rng = np.random.default_rng(6)
    keys = rng.integers(1, 4000, 30_000, dtype=np.uint32)
    single = SliceSketch()
    single.update(keys, keys, keys)
    wins = []
    for i, chunk in enumerate(np.array_split(keys, 5)):
        s = SliceSketch()
        s.update(chunk, chunk, chunk)
        w = _window(i + 1, slices=False)
        w.slices["kind:1"] = {"events": s.events, "hll": s.hll,
                              "ent": s.ent, "hh": s.sealed_hh()}
        wins.append(w)
    merged = merge_windows(wins)
    got = merged.slices["kind:1"]
    assert np.array_equal(got["hll"], single.hll)
    assert np.array_equal(got["ent"], single.ent.astype(np.int64))
    assert got["events"] == len(keys)


def test_global_history_singleton_is_isolated_by_base_dir(tmp_path):
    HISTORY.set_base_dir(str(tmp_path / "a"))
    try:
        w = HISTORY.writer_for("trace/exec", node="n0")
        HISTORY.append_window(_window(1), writer=w)
        assert HISTORY.list_windows()
        # another base sees nothing
        assert HISTORY.list_windows(base_dir=str(tmp_path / "b")) == []
        st = HISTORY.stats()
        assert st["stores"]["n0--trace-exec"]["windows"] == 1
        assert st["bytes"] > 0
    finally:
        HISTORY.close_all()
        HISTORY.set_base_dir(None)
