"""Sketch-history acceptance tier (ISSUE 6):

- a 2-agent GrpcRuntime run with the tpusketch history plane on seals
  mergeable windows on both nodes (each node's store carries only its
  own windows),
- `ig-tpu query` over a seq/ts range pulls only index-overlapping
  windows from both nodes and merges them client-side, answering
  cardinality, heavy-hitter, and entropy queries — whole-traffic and
  for a (key, time-range) subpopulation slice — matching single-merge
  ground truth within the documented sketch error,
- a node killed mid-seal leaves exactly one torn window at the store's
  active tail, dropped-and-accounted on read (the query still answers
  from the surviving windows and reports the loss),
- replaying the same PR-5 capture journal reseals windows whose content
  digests are byte-identical to the live run's — the determinism
  contract extended from summaries to sealed history.
"""

from __future__ import annotations

import binascii
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import zlib

import numpy as np
import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.agent import wire
from inspektor_gadget_tpu.agent.service import serve
from inspektor_gadget_tpu.capture import RECORDINGS, replay_journal
from inspektor_gadget_tpu.gadgets import GadgetContext
from inspektor_gadget_tpu.gadgets import registry as gadget_registry
from inspektor_gadget_tpu.gadgets.interface import GadgetDesc, GadgetType
from inspektor_gadget_tpu.history import HISTORY
from inspektor_gadget_tpu.operators import operators as op_registry
from inspektor_gadget_tpu.ops import fold64_to_32
from inspektor_gadget_tpu.params import Collection, ParamDescs

REC_ID = "history-e2e"
GADGET = "trace/historysynth"

# deterministic scripted traffic, fixed at import: two tenants (mntns)
# × two syscalls (kind), a zipf-heavy stream for tenant A and a
# high-cardinality uniform stream for tenant B
_RNG = np.random.default_rng(21)
N_BATCHES = 6
BATCH = 2048


def _zipf(n):
    return (_RNG.zipf(1.5, size=n).clip(1, 64).astype(np.uint64)
            * np.uint64(0x9E3779B97F4A7C15))


_PHASES = []
for _i in range(N_BATCHES):
    a = _zipf(BATCH // 2)                                     # tenant 101
    b = _RNG.integers(1, 2**48, BATCH // 2).astype(np.uint64)  # tenant 202
    keys = np.concatenate([a, b])
    mntns = np.concatenate([np.full(BATCH // 2, 101, np.uint64),
                            np.full(BATCH // 2, 202, np.uint64)])
    kind = np.concatenate([np.full(BATCH // 4, 10, np.uint32),
                           np.full(BATCH // 4, 11, np.uint32),
                           np.full(BATCH // 2, 11, np.uint32)])
    _PHASES.append((keys, mntns, kind))


def _truth(sel=None):
    """Exact ground truth over the scripted stream (folded 32-bit keys,
    the stream the sketches actually absorb)."""
    keys, counts = [], {}
    for bkeys, bmntns, _bkind in _PHASES:
        k32 = fold64_to_32(bkeys)
        mask = slice(None) if sel is None else (bmntns == sel)
        for k in k32[mask].tolist():
            counts[k] = counts.get(k, 0) + 1
        keys.append(k32[mask])
    allk = np.concatenate(keys)
    return {
        "events": len(allk),
        "distinct": len(np.unique(allk)),
        "top": sorted(counts.items(), key=lambda kv: -kv[1]),
    }


class _HistorySynthGadget:
    """Scripted batches with one explicit harvest per batch: with
    history-interval 0, every harvest seals a window, so the recorded
    journal and the live store share deterministic boundaries."""

    def __init__(self, ctx):
        self.ctx = ctx
        self._batch_handler = None

    def set_batch_handler(self, handler):
        self._batch_handler = handler

    def run(self, ctx):
        from inspektor_gadget_tpu.operators import tpusketch
        from inspektor_gadget_tpu.sources.batch import EventBatch
        inst = next((i for i in tpusketch.live_instances()
                     if i.ctx.run_id == ctx.run_id), None)
        for keys, mntns, kind in _PHASES:
            if ctx.done:
                return
            b = EventBatch.alloc(len(keys), with_comm=False)
            b.cols["key_hash"][:] = keys
            b.cols["mntns"][:] = mntns
            b.cols["kind"][:] = kind
            b.cols["ts"][:] = time.time_ns()
            b.count = len(keys)
            if self._batch_handler is not None:
                self._batch_handler(b)
            if inst is not None:
                inst.harvest()
            ctx.sleep_or_done(0.05)


class _HistorySynthDesc(GadgetDesc):
    name = "historysynth"
    category = "trace"
    gadget_type = GadgetType.TRACE
    description = "scripted two-tenant batch gadget (history e2e)"
    event_cls = None

    def params(self) -> ParamDescs:
        return ParamDescs()

    def new_instance(self, ctx) -> _HistorySynthGadget:
        return _HistorySynthGadget(ctx)


@pytest.fixture(scope="module", autouse=True)
def synth_gadget():
    desc = _HistorySynthDesc()
    gadget_registry.register(desc)
    yield desc
    gadget_registry._REGISTRY.pop((desc.category, desc.name), None)


@pytest.fixture(scope="module")
def agents():
    servers, targets = [], {}
    tmp = tempfile.mkdtemp()
    for i in range(2):
        addr = f"unix://{tmp}/hist-agent{i}.sock"
        server, _ = serve(addr, node_name=f"hnode-{i}")
        servers.append(server)
        targets[f"hnode-{i}"] = addr
    yield targets
    for s in servers:
        s.stop(grace=0.5)


@pytest.fixture(scope="module")
def history_area(tmp_path_factory):
    base = str(tmp_path_factory.mktemp("history-area"))
    HISTORY.set_base_dir(base)
    yield base
    HISTORY.close_all()
    HISTORY.set_base_dir(None)


@pytest.fixture(scope="module")
def capture_area(tmp_path_factory):
    base = str(tmp_path_factory.mktemp("hist-capture"))
    RECORDINGS.set_base_dir(base)
    yield base
    RECORDINGS.set_base_dir(None)


def _op_params() -> Collection:
    col = Collection()
    sp = op_registry.get("tpusketch").instance_params().to_params()
    for k, v in (("enable", "true"), ("depth", "4"), ("log2-width", "10"),
                 ("hll-p", "10"), ("entropy-log2-width", "8"),
                 ("topk", "32"), ("harvest-interval", "1h"),
                 ("history", "true"), ("history-interval", "0"),
                 ("history-log2-width", "12"), ("history-slots", "4")):
        sp.set(k, v)
    col["operator.tpusketch."] = sp
    return col


@pytest.fixture(scope="module")
def recorded_fleet(agents, history_area, capture_area, tmp_path_factory):
    """Arm a PR-5 recording, run the scripted gadget on both agents with
    the history plane on, stop, fetch the bundle — the shared journey
    every test below inspects from a different side."""
    from inspektor_gadget_tpu.runtime.grpc_runtime import GrpcRuntime
    runtime = GrpcRuntime(dict(agents))
    try:
        results, errors = runtime.start_recording(REC_ID)
        assert not errors, errors
        desc = gadget_registry.get("trace", "historysynth")
        ctx = GadgetContext(desc, operator_params=_op_params(), timeout=120.0)
        run = runtime.run_gadget(ctx)
        assert not run.errors(), run.errors()
        _, stop_errors = runtime.stop_recording(REC_ID)
        assert not stop_errors, stop_errors
        bundle_dir = str(tmp_path_factory.mktemp("hist-bundle"))
        bundle = runtime.fetch_recording(REC_ID, bundle_dir)
        assert not bundle["errors"], bundle["errors"]
    finally:
        runtime.close()
    return {"bundle_dir": bundle_dir}


def test_both_nodes_sealed_their_own_windows(recorded_fleet, agents,
                                             history_area):
    from inspektor_gadget_tpu.agent.client import AgentClient
    for node, target in agents.items():
        c = AgentClient(target, node)
        try:
            listing = c.list_windows(gadget=GADGET)
            rows = listing["windows"]
            # one window per scripted batch, served per node: an agent
            # never hands out a peer's windows even though the
            # in-process fleet shares one base area
            assert len(rows) == N_BATCHES, (node, len(rows))
            assert {r["node"] for r in rows} == {node}
            assert [r["window"] for r in rows] == list(range(1, N_BATCHES + 1))
            assert all(r["digest"] for r in rows)
            # subpopulation keys ride the headers (and the index)
            assert {"mntns:101", "mntns:202", "kind:10", "kind:11",
                    "mntns:101|kind:10"} <= set(rows[0]["keys"])
        finally:
            c.close()


def test_range_query_matches_single_merge_ground_truth(recorded_fleet,
                                                       agents):
    from inspektor_gadget_tpu.runtime.grpc_runtime import GrpcRuntime
    runtime = GrpcRuntime(dict(agents))
    try:
        ans = runtime.query_history(gadget=GADGET)
        # both nodes ran the same script: 2 × the scripted stream
        truth = _truth()
        assert ans.windows == 2 * N_BATCHES
        assert sorted(ans.nodes) == sorted(agents)
        assert ans.events == 2 * truth["events"]
        # cardinality: both nodes saw the SAME keys, so distinct stays
        # ~truth (HLL p=10 documents ~3.3% standard error)
        assert abs(ans.distinct - truth["distinct"]) / truth["distinct"] \
            < 0.12, (ans.distinct, truth["distinct"])
        # heavy hitters: the zipf head must surface, counts within CMS
        # overestimate-only error (≤ ~1% at this width)
        got = dict((k, c) for k, c, _label in ans.heavy_hitters)
        for true_key, true_count in truth["top"][:5]:
            assert true_key in got, hex(true_key)
            est = got[true_key]
            assert 2 * true_count <= est <= 2 * true_count * 1.02 + 8, (
                hex(true_key), est, 2 * true_count)
        assert ans.entropy_bits > 0

        # (key, time-range) slice: tenant 101 over the middle windows
        listing, errors = runtime.list_windows(gadget=GADGET)
        assert not errors
        rows = listing["hnode-0"]["windows"]
        # consecutive windows touch (window k's start == k-1's end), and
        # overlap is inclusive of touching/straddling windows — pick
        # bounds strictly inside the interior so the ends are pruned
        t0 = rows[1]["end_ts"] + 1e-4          # excludes windows 1..2
        t1 = rows[4]["start_ts"] - 1e-4        # excludes windows 5..6
        sliced = runtime.query_history(gadget=GADGET, key="mntns:101",
                                       start_ts=t0, end_ts=t1)
        # hnode-1's windows carry different wall times; assert only the
        # range restriction pruned SOME windows and kept the slice exact
        assert 0 < sliced.windows < 2 * N_BATCHES
        s = sliced.slices["mntns:101"]
        # slice events are exact (counted, not sketched): 1024 per
        # window per node within the range
        assert s["events"] % (BATCH // 2) == 0 and s["events"] > 0
        truth_a = _truth(sel=101)
        # tenant A's slice cardinality, within the p=8 slice HLL's
        # documented error envelope (~6.5% σ; allow 3σ)
        assert abs(s["distinct"] - truth_a["distinct"]) \
            / truth_a["distinct"] < 0.25, (s["distinct"],
                                           truth_a["distinct"])
        # tenant A's heavy head is exact per-slice (truncated table)
        slice_top = {h["key"] for h in s["heavy_hitters"][:3]}
        want_top = {f"0x{k:08x}" for k, _ in truth_a["top"][:3]}
        assert want_top & slice_top, (slice_top, want_top)
        # entropy: tenant A is zipf-skewed, the whole stream is not —
        # the slice answer must show visibly LESS entropy
        assert s["entropy_bits"] < ans.entropy_bits
    finally:
        runtime.close()


def test_seq_range_prunes_windows(recorded_fleet, agents):
    from inspektor_gadget_tpu.agent.client import AgentClient
    node, target = next(iter(agents.items()))
    c = AgentClient(target, node)
    try:
        rows = c.list_windows(gadget=GADGET, start_seq=3,
                              end_seq=4)["windows"]
        assert [r["seq"] for r in rows] == [3, 4]
        frames, losses = c.fetch_windows(gadget=GADGET, start_seq=3,
                                         end_seq=4)
        assert len(frames) == 2 and not losses
    finally:
        c.close()


def test_kill_mid_seal_tears_exactly_one_window(recorded_fleet, agents,
                                                history_area):
    """A SIGKILLed node mid-seal: its store's active segment ends in a
    half-written window frame. Readers drop exactly that window,
    account the loss, and the fleet query still answers."""
    from inspektor_gadget_tpu.runtime.grpc_runtime import GrpcRuntime
    store = os.path.join(history_area, "hnode-0--trace-historysynth")
    segs = sorted(f for f in os.listdir(store) if f.endswith(".igj"))
    seg = os.path.join(store, segs[-1])
    header = {"type": wire.EV_WINDOW, "seq": 10_000, "ts": time.time(),
              "gadget": GADGET, "node": "hnode-0", "window": 99,
              "start_ts": 0.0, "end_ts": 9e12, "events": 1, "keys": []}
    zp = zlib.compress(wire.encode_msg(header, b"x" * 512), 1)
    frame = (len(zp).to_bytes(4, "little")
             + (zlib.crc32(zp) & 0xFFFFFFFF).to_bytes(4, "little") + zp)
    child = subprocess.Popen([
        sys.executable, "-c",
        "import binascii, os, signal, sys\n"
        "f = open(sys.argv[1], 'ab')\n"
        "f.write(binascii.unhexlify(sys.argv[2]))\n"
        "f.flush(); os.fsync(f.fileno())\n"
        "os.kill(os.getpid(), signal.SIGKILL)\n",
        seg, binascii.hexlify(frame[: len(frame) // 2]).decode(),
    ])
    child.wait(timeout=30)
    assert child.returncode == -signal.SIGKILL
    try:
        losses: list = []
        rows = HISTORY.list_windows(gadget=GADGET, node="hnode-0",
                                    losses=losses)
        # every whole window survives; exactly ONE torn window accounted
        assert len(rows) == N_BATCHES
        assert len(losses) == 1
        assert losses[0]["dropped_bytes"] == len(frame) // 2
        # the fleet query reports the loss and still answers
        runtime = GrpcRuntime(dict(agents))
        try:
            ans = runtime.query_history(gadget=GADGET)
        finally:
            runtime.close()
        assert ans.windows == 2 * N_BATCHES
        assert any("torn window tail" in d for d in ans.dropped_windows)
    finally:
        # heal the segment for the tests that follow
        with open(seg, "r+b") as f:
            f.seek(0, os.SEEK_END)
            f.truncate(f.tell() - len(frame) // 2)


def test_replay_reseals_byte_identical_window_digests(recorded_fleet,
                                                      history_area,
                                                      tmp_path):
    """The determinism anchor: re-driving the PR-5 capture journal
    through the real chain reseals windows whose content digests are
    byte-identical to the live run's, twice over."""
    bundle_dir = recorded_fleet["bundle_dir"]
    node = "hnode-0"
    live = HISTORY.list_windows(gadget=GADGET, node=node)
    live_digests = [r["digest"] for r in live]
    assert len(live_digests) == N_BATCHES

    from inspektor_gadget_tpu.capture import JournalReader, is_journal
    root = os.path.join(bundle_dir, node)
    jpath = next(os.path.join(root, d) for d in sorted(os.listdir(root))
                 if is_journal(os.path.join(root, d))
                 and JournalReader(os.path.join(root, d)).manifest
                 .get("node") == node)

    digests = []
    for attempt in range(2):
        replay_dir = str(tmp_path / f"replay-hist-{attempt}")
        res = replay_journal(jpath, speed=0.0, param_overrides={
            "operator.tpusketch.history-dir": replay_dir})
        assert res.digests_match  # the PR-5 summary contract still holds
        rows = HISTORY.list_windows(base_dir=replay_dir, gadget=GADGET)
        digests.append([r["digest"] for r in rows])
    assert digests[0] == digests[1], "replay-to-replay digest drift"
    assert digests[0] == live_digests, "replay diverged from the live seal"


def test_query_cli_remote_and_local(recorded_fleet, agents, history_area,
                                    capsys):
    from inspektor_gadget_tpu.cli.main import main as cli_main
    spec = ",".join(f"{k}={v}" for k, v in agents.items())
    assert cli_main(["query", "--remote", spec, "--gadget", GADGET,
                     "--key", "mntns:101", "--top", "3"]) == 0
    out = capsys.readouterr().out
    assert f"{2 * N_BATCHES} window(s)" in out
    assert "slice mntns:101:" in out
    assert "distinct≈" in out and "entropy=" in out

    # JSON output carries the full answer shape
    assert cli_main(["query", "--remote", spec, "--gadget", GADGET,
                     "-o", "json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["windows"] == 2 * N_BATCHES
    assert doc["errors"] == {}
    assert doc["heavy_hitters"]

    # the local path reads the node area directly (no agents)
    assert cli_main(["query", "--history", history_area,
                     "--gadget", GADGET]) == 0
    out = capsys.readouterr().out
    assert f"{2 * N_BATCHES} window(s)" in out


def test_top_windows_gadget_lists_sealed_windows(recorded_fleet,
                                                 history_area):
    from inspektor_gadget_tpu.gadgets import get
    from inspektor_gadget_tpu.runtime.local import LocalRuntime
    desc = get("top", "windows")
    params = desc.params().to_params()
    params.set("interval", "200ms")
    ctx = GadgetContext(desc, gadget_params=params, timeout=0.5)
    snapshots = []
    result = LocalRuntime().run_gadget(ctx, on_event_array=snapshots.append)
    assert not result.errors(), result.errors()
    rows = [r for snap in snapshots for r in snap
            if r.gadget == GADGET]
    assert rows, "top windows never showed the sealed history"
    assert any(r.events > 0 and r.slices > 0 for r in rows)
