"""Checkpoint/resume wired into the agent (VERDICT r4 item 2).

The reference's closest analogue is pinned BPF maps surviving daemon
restarts (pkg/gadgets/helpers.go:36); here the persisted state is the
tpusketch bundle (+ scorer): periodically host-offloaded by the agent's
checkpointer, merged back on the next start. The kill test is the real
thing — SIGKILL a serving agent mid-ingest, restart it, and assert the
resumed counts include everything the checkpoint had (no silent reset).
"""

import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path

import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.gadgets import GadgetContext, get
from inspektor_gadget_tpu.operators import tpusketch
from inspektor_gadget_tpu.operators.operators import get as get_op
from inspektor_gadget_tpu.ops import bundle_init
from inspektor_gadget_tpu.params import Collection
from inspektor_gadget_tpu.runtime.local import LocalRuntime
from inspektor_gadget_tpu.utils.checkpoint import load_pytree


@pytest.fixture()
def ckpt_dir(tmp_path):
    tpusketch.set_checkpoint_dir(tmp_path)
    yield tmp_path
    tpusketch.set_checkpoint_dir(None)


def _run_sketch(timeout=0.8, **extra_params):
    desc = get("trace", "exec")
    params = desc.params().to_params()
    params.set("source", "pysynthetic")
    params.set("rate", "100000")
    summaries = []
    op_params = Collection()
    sketch_params = get_op("tpusketch").instance_params().to_params()
    sketch_params.set("enable", "true")
    sketch_params.set("harvest-interval", "200ms")
    for k, v in extra_params.items():
        sketch_params.set(k, v)
    op_params["operator.tpusketch."] = sketch_params
    ctx = GadgetContext(desc, gadget_params=params, operator_params=op_params,
                        timeout=timeout,
                        extra={"on_sketch_summary": summaries.append})
    result = LocalRuntime().run_gadget(ctx)
    assert not result.errors()
    return summaries


def test_clean_shutdown_saves_and_next_run_resumes(ckpt_dir):
    """post_gadget_run checkpoints; the next run's counts start from it."""
    first = _run_sketch()
    assert first and first[-1].events > 1000
    e1 = first[-1].events
    assert (ckpt_dir / "trace-exec.npz").exists()

    second = _run_sketch()
    # resumed bundle absorbed the first run's events before adding its own
    assert second[-1].events >= e1 + 1000, (second[-1].events, e1)


def test_config_change_falls_back_to_fresh(ckpt_dir):
    _run_sketch()
    # different sketch geometry → treedef/leaf mismatch → fresh state
    small = _run_sketch(**{"log2-width": "10", "hll-p": "10"})
    assert small[-1].events < 1_000_000  # ran fine, no crash on mismatch


def test_corrupt_checkpoint_falls_back_to_fresh(ckpt_dir):
    """A torn .npz (crash mid-write, disk corruption) must mean fresh
    state, never a gadget that refuses to start."""
    (ckpt_dir / "trace-exec.npz").write_bytes(b"not a zip at all")
    (ckpt_dir / "trace-exec.json").write_text("{}")
    summaries = _run_sketch()
    assert summaries and summaries[-1].events > 1000


def test_scorer_checkpoint_roundtrip(ckpt_dir):
    first = _run_sketch(anomaly="true")
    assert first[-1].anomaly
    assert (ckpt_dir / "trace-exec-scorer.npz").exists()
    second = _run_sketch(anomaly="true")
    assert second[-1].anomaly  # scorer resumed and kept scoring


def test_agent_kill_and_resume(tmp_path):
    """SIGKILL a serving agent mid-ingest; restart; merged counts must be
    >= the checkpointed pre-kill counts."""
    ckpt = tmp_path / "ckpt"
    sock_dir = tempfile.mkdtemp()
    addr = f"unix://{sock_dir}/agent.sock"
    env = dict(os.environ)

    def spawn():
        # --platform cpu (the PR-2 flag) pins the spawned agent's device
        # plane instead of inheriting JAX_PLATFORMS from the test env:
        # with the TPU tunnel down the inherited-auto probe used to eat
        # most of the startup deadline and flake this test
        return subprocess.Popen(
            [sys.executable, "-m", "inspektor_gadget_tpu.agent.main",
             "serve", "--listen", addr, "--node-name", "ckpt-node",
             "--no-doctor", "--platform", "cpu",
             "--checkpoint-dir", str(ckpt),
             "--checkpoint-interval", "0.3"],
            env=env, cwd="/root/repo",
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)

    proc = spawn()
    try:
        # wait for the socket to serve
        from inspektor_gadget_tpu.agent.client import AgentClient
        deadline = time.monotonic() + 120
        client = None
        while time.monotonic() < deadline:
            if Path(f"{sock_dir}/agent.sock").exists():
                try:
                    client = AgentClient(addr, "ckpt-node")
                    client.get_catalog(use_cache_on_error=False)
                    break
                except Exception:
                    client = None
            time.sleep(0.5)
        assert client is not None, "agent never came up"

        # unbounded sketch run in the background (ingest is live when killed)
        def run():
            try:
                client.run_gadget(
                    "trace", "exec",
                    {"gadget.source": "pysynthetic", "gadget.rate": "50000",
                     "operator.tpusketch.enable": "true",
                     "operator.tpusketch.harvest-interval": "200ms"},
                    timeout=0.0, outputs=("summary",))
            except Exception:
                pass  # the kill below tears the stream

        t = threading.Thread(target=run, daemon=True)
        t.start()

        # wait for a checkpoint with real counts
        base = ckpt / "trace-exec"
        deadline = time.monotonic() + 60
        pre_kill = 0.0
        while time.monotonic() < deadline:
            try:
                b = load_pytree(base, like=bundle_init())
                pre_kill = float(b.events)
                if pre_kill > 1000:
                    break
            except Exception:
                pass
            time.sleep(0.2)
        assert pre_kill > 1000, "no checkpoint with counts before kill"

        proc.send_signal(signal.SIGKILL)  # mid-ingest, no clean shutdown
        proc.wait(timeout=10)
        t.join(timeout=5)

        # restart: a fresh run must resume (merge), not silently reset
        proc = spawn()
        client2 = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            try:
                client2 = AgentClient(addr, "ckpt-node")
                client2.get_catalog(use_cache_on_error=False)
                break
            except Exception:
                client2 = None
                time.sleep(0.5)
        assert client2 is not None, "agent never restarted"

        # no gRPC deadline: the fresh process recompiles the sketch jit
        # (tens of seconds); stop as soon as a summary proves the resume
        summaries = []
        stop = threading.Event()

        def on_s(node, s):
            summaries.append(s)
            if s["events"] >= pre_kill:
                stop.set()

        watchdog = threading.Timer(120.0, stop.set)
        watchdog.start()
        res = client2.run_gadget(
            "trace", "exec",
            {"gadget.source": "pysynthetic", "gadget.rate": "50000",
             "operator.tpusketch.enable": "true",
             "operator.tpusketch.harvest-interval": "200ms"},
            timeout=0.0, outputs=("summary",), on_summary=on_s,
            stop_event=stop)
        watchdog.cancel()
        assert res["error"] is None, res["error"]
        assert summaries, "no summaries after restart"
        assert max(s["events"] for s in summaries) >= pre_kill, (
            f"reset detected: {summaries[-1]['events']} < {pre_kill}")
        client2.close()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
