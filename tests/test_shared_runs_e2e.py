"""Shared-run multiplexing acceptance (ISSUE 12): one gadget run, many
subscribers, graceful degradation under fan-out.

- K subscribers on one 2-node fleet run: each agent provably runs ONE
  gadget (run registry + active-runs gauge counted once per node), the
  healthy subscribers receive identical record streams (content-aligned
  batches, identical summaries per epoch) with contiguous per-subscriber
  seqs,
- a deliberately-stalled low-priority subscriber accumulates drops on
  ITS OWN queue (EV_DROP_NOTICE + ig_agent_subscriber_drops_total) and
  is EVICTED with a labeled terminal record while its peers stream on
  unaffected,
- detach-all starts the run-keepalive countdown and a re-attach within
  it resumes WITHOUT a gadget restart (same context, same stream state),
- admission control refuses typed (max-subscribers, memory-budget; low
  priority first),
- a subscriber-churn chaos round (testing/chaos.SubscriberChurn, some
  rounds leaving by proxy cut) leaves no leaked queues, threads, or
  lingering runs,
- the summary pub/sub tier delivers harvest summaries + sealed-window
  announcements with zero raw batches.
"""

from __future__ import annotations

import tempfile
import threading
import time

import pytest

import inspektor_gadget_tpu.all_gadgets  # noqa: F401
from inspektor_gadget_tpu.agent import wire
from inspektor_gadget_tpu.agent.client import AgentClient
from inspektor_gadget_tpu.agent.service import serve
from inspektor_gadget_tpu.gadgets import GadgetContext, get
from inspektor_gadget_tpu.params import Params
from inspektor_gadget_tpu.runtime.grpc_runtime import GrpcRuntime
from inspektor_gadget_tpu.telemetry import REGISTRY
from inspektor_gadget_tpu.testing.chaos import ChaosProxy, SubscriberChurn

pytestmark = pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")

RUN_PARAMS = {"gadget.source": "pysynthetic", "gadget.rate": "2000",
              "gadget.batch-size": "128"}


def _metric(name: str, **labels) -> float:
    total = 0.0
    for key, v in REGISTRY.snapshot().items():
        if key != name and not key.startswith(name + "{"):
            continue
        if all(f'{k}="{lv}"' in key for k, lv in labels.items()):
            total += v
    return total


@pytest.fixture(scope="module")
def shared_agents():
    """Two in-process agents on unix sockets."""
    tmp = tempfile.mkdtemp()
    servers, agents, targets = [], {}, {}
    for i in range(2):
        addr = f"unix://{tmp}/shared{i}.sock"
        server, agent = serve(addr, node_name=f"shnode-{i}")
        servers.append(server)
        agents[f"shnode-{i}"] = agent
        targets[f"shnode-{i}"] = addr
    yield {"agents": agents, "targets": targets}
    for s in servers:
        s.stop(grace=0.5)


class _Collector:
    """Per-subscriber stream capture: seqs, data-record content keys
    (batch payload bytes), and summaries keyed by epoch."""

    def __init__(self):
        self.seqs: list[int] = []
        self.content: list[bytes] = []
        self.summaries: dict[int, tuple] = {}
        self.stop = threading.Event()
        self.out: dict = {}

    def on_message(self, _node, seq, _t):
        self.seqs.append(seq)

    def on_batch(self, _node, batch):
        self.content.append(batch.cols["key_hash"].tobytes())

    def on_summary(self, _node, s):
        self.summaries[int(s["epoch"])] = (int(s["events"]),
                                           int(s["distinct"]))


def _aligned_overlap(a: list, b: list) -> int:
    """Length of the contiguous common window of two record streams
    (each subscriber joins the SAME pipeline at its own moment, so one
    stream must be a windowed suffix of the other)."""
    if not a or not b:
        return 0
    for first, second in ((a, b), (b, a)):
        if second[0] in first:
            i = first.index(second[0])
            n = min(len(first) - i, len(second))
            if first[i:i + n] == second[:n]:
                return n
    return 0


def test_shared_fleet_run_one_gadget_k_subscribers(shared_agents):
    """The tentpole: a 2-node fleet run with share=true; two extra
    subscribers per node ride the SAME gadget (one run per agent, the
    active-runs gauge counts 2 for the whole fleet), receive identical
    record streams, and their accounting is exact."""
    agents = shared_agents["agents"]
    targets = shared_agents["targets"]
    runs_before = _metric("ig_agent_active_runs")

    from inspektor_gadget_tpu.operators import operators as op_registry
    from inspektor_gadget_tpu.params import Collection

    runtime = GrpcRuntime(dict(targets))
    desc = get("trace", "exec")
    params = desc.params().to_params()
    params.set("source", "pysynthetic")
    params.set("rate", "2000")
    params.set("batch-size", "128")
    op_params = Collection()
    sp = op_registry.get("tpusketch").instance_params().to_params()
    for k, v in (("enable", "true"), ("log2-width", "10"),
                 ("hll-p", "10"), ("harvest-interval", "500ms")):
        sp.set(k, v)
    op_params["operator.tpusketch."] = sp
    rp = Params(runtime.params())
    rp.set("share", "true")
    rp.set("run-keepalive", "1s")
    ctx = GadgetContext(desc, gadget_params=params, operator_params=op_params,
                        runtime_params=rp, timeout=10.0)
    events = []
    fleet_done = threading.Event()
    fleet_holder: dict = {}

    def fleet_run():
        fleet_holder["result"] = runtime.run_gadget(
            ctx, on_event=events.append, on_batch=lambda b: None,
            on_summary=lambda n, s: None)
        fleet_done.set()

    threading.Thread(target=fleet_run, daemon=True).start()

    # wait until the shared run is registered on both agents, then
    # attach two extra subscribers per node as fast as possible (the
    # sketch warmup keeps the pipeline quiet far longer than this)
    def live_run(agent):
        for st in agent._streams.values():
            if st.shared and not st.done:
                return st
        return None

    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:
        if all(live_run(a) is not None for a in agents.values()):
            break
        time.sleep(0.02)
    run_states = {n: live_run(a) for n, a in agents.items()}
    assert all(run_states.values()), "shared runs never registered"

    subs: dict[tuple, _Collector] = {}
    threads = []
    for node, target in targets.items():
        for j in range(2):
            col = _Collector()
            subs[(node, j)] = col

            def pump(target=target, node=node, col=col):
                client = AgentClient(target, node)
                col.out = client.run_gadget(
                    "", "", attach_to=run_states[node].run_id,
                    subscriber={"priority": "high", "queue": 4096},
                    on_message=col.on_message, on_batch=col.on_batch,
                    on_summary=col.on_summary, stop_event=col.stop)
                client.close()

            t = threading.Thread(target=pump, daemon=True)
            t.start()
            threads.append(t)

    # ONE gadget per agent while K=3 subscribers ride each node
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if all(st.live_subscribers() >= 3 for st in run_states.values()):
            break
        time.sleep(0.05)
    for node, st in run_states.items():
        assert st.live_subscribers() >= 3, (node, st.subscriber_rows())
        assert len(agents[node]._runs) == 1, \
            f"{node} runs a private gadget per subscriber"
    assert _metric("ig_agent_active_runs") - runs_before == 2.0
    assert _metric("ig_agent_run_subscribers",
                   run=run_states["shnode-0"].run_id) >= 3.0

    # let data flow to every subscriber, then detach the extras cleanly
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline:
        if all(len(c.content) >= 6 and len(c.summaries) >= 2
               for c in subs.values()):
            break
        time.sleep(0.1)
    for col in subs.values():
        col.stop.set()
    for t in threads:
        t.join(timeout=20.0)
    assert not fleet_done.is_set() or fleet_holder.get("result") is not None

    for (node, j), col in subs.items():
        out = col.out
        assert out["error"] is None, (node, j, out["error"])
        assert out["attach"] and out["attach"]["shared"] is True
        # exact per-subscriber accounting: contiguous seqs, no drops
        assert col.seqs == list(range(1, len(col.seqs) + 1)), (node, j)
        assert out["records"] == out["last_seq"] and out["gaps"] == 0
        assert out["sub_drops"] == 0 and out["evicted"] is False
        assert len(col.content) >= 6, (node, j, len(col.content))

    # identical record streams per node: the two subscribers' batch
    # sequences align on a long contiguous window, and their summaries
    # agree exactly on every epoch both observed
    for node in targets:
        a, b = subs[(node, 0)], subs[(node, 1)]
        overlap = _aligned_overlap(a.content, b.content)
        assert overlap >= min(len(a.content), len(b.content)) - 1 >= 5, \
            (node, len(a.content), len(b.content), overlap)
        common = set(a.summaries) & set(b.summaries)
        assert common, "no common summary epochs"
        for ep in common:
            assert a.summaries[ep] == b.summaries[ep], (node, ep)

    # the fleet run itself ends clean and labeled shared-aware
    assert fleet_done.wait(30.0)
    result = fleet_holder["result"]
    assert not result.errors(), result.errors()
    assert result.partial is False
    assert result.overloaded() == {}
    for node, r in result.items():
        assert r.records + r.gaps == r.last_seq, (node, r)
        assert r.sub_drops == 0 and not r.evicted
    runtime.close()

    # detach-all + keepalive expiry: the agents' gauges return to
    # baseline and nothing lingers
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline:
        if _metric("ig_agent_active_runs") == runs_before:
            break
        time.sleep(0.1)
    assert _metric("ig_agent_active_runs") == runs_before


def test_stalled_low_priority_subscriber_dropped_and_evicted(shared_agents):
    """Overload protection: a low-priority subscriber whose client stops
    draining accumulates drops on ITS OWN 4-deep queue, is evicted after
    its stall window with a labeled terminal record, and the healthy
    peer on the same run never sees a gap, a drop, or a stall."""
    agents = shared_agents["agents"]
    target = shared_agents["targets"]["shnode-1"]
    evictions_before = _metric("ig_agent_subscriber_evictions_total")

    owner_stop = threading.Event()
    owner_holder: dict = {}
    params = dict(RUN_PARAMS)
    params["gadget.rate"] = "3000"     # distinct share key per test
    params["gadget.batch-size"] = "256"

    def owner():
        c = AgentClient(target, "shnode-1")
        owner_holder["out"] = c.run_gadget(
            "trace", "exec", params, timeout=0.0, run_id="evict-e2e",
            share=True, keepalive=1.0, outputs=("batch",),
            subscriber={"priority": "high"},
            on_message=lambda *_: None, stop_event=owner_stop)
        c.close()

    t_owner = threading.Thread(target=owner, daemon=True)
    t_owner.start()
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:
        st = agents["shnode-1"]._streams.get("evict-e2e")
        if st is not None and st.index > 0:
            break
        time.sleep(0.05)
    assert st is not None and not st.done, "shared run never produced"

    # the victim: low priority, tiny queue, short stall budget, and a
    # client whose handler BLOCKS on a gate — the wedged-dashboard case.
    # The gate opens only after the agent has evicted it, so the client
    # can then drain its transport buffer and observe the labeled
    # terminal record.
    gate = threading.Event()
    victim_holder: dict = {}

    def victim():
        c = AgentClient(target, "victim")
        victim_holder["out"] = c.run_gadget(
            "", "", attach_to="evict-e2e",
            subscriber={"priority": "low", "queue": 4,
                        "evict_after": 0.8,
                        "drop_policy": "drop-oldest"},
            on_message=lambda *_: gate.wait(60.0))
        c.close()

    t_victim = threading.Thread(target=victim, daemon=True)
    t_victim.start()

    # a healthy peer riding the same run throughout the eviction
    peer = _Collector()

    def peer_pump():
        c = AgentClient(target, "peer")
        peer.out = c.run_gadget(
            "", "", attach_to="evict-e2e",
            subscriber={"priority": "normal", "queue": 4096},
            on_message=peer.on_message, stop_event=peer.stop)
        c.close()

    t_peer = threading.Thread(target=peer_pump, daemon=True)
    t_peer.start()

    # wait for the agent to evict the wedged subscriber, then open the
    # gate so the client can drain to the terminal record
    deadline = time.monotonic() + 45.0
    evicted_row = None
    while time.monotonic() < deadline:
        rows = [s for s in st.subscriber_rows()
                if s["priority"] == "low" and s["evicted"]]
        if rows:
            evicted_row = rows[0]
            break
        time.sleep(0.1)
    assert evicted_row is not None, \
        f"agent never evicted the wedged subscriber: {st.subscriber_rows()}"
    assert evicted_row["drops"] > 0, evicted_row
    gate.set()

    t_victim.join(timeout=60.0)
    assert not t_victim.is_alive(), "evicted subscriber stream never ended"
    out = victim_holder["out"]
    assert out["evicted"] is True
    assert "evicted" in (out["error"] or "")
    assert out["sub_drops"] > 0, "no drops accounted before eviction"
    assert _metric("ig_agent_subscriber_evictions_total") \
        >= evictions_before + 1.0
    assert _metric("ig_agent_subscriber_drops_total", run="evict-e2e",
                   policy="drop-oldest", **{"class": "low"}) \
        >= float(out["sub_drops"])

    # the gadget and the peer never noticed
    st = agents["shnode-1"]._streams.get("evict-e2e")
    assert st is not None and not st.done, "eviction hurt the shared run"
    time.sleep(0.5)
    peer.stop.set()
    t_peer.join(timeout=20.0)
    assert peer.out["error"] is None
    assert peer.out["sub_drops"] == 0 and peer.out["evicted"] is False
    assert peer.seqs == list(range(1, len(peer.seqs) + 1))
    assert peer.out["records"] == peer.out["last_seq"]
    # eviction shows in the DumpState subscriber rows (fleet runs view)
    rows = {s["sub_id"]: s for s in st.subscriber_rows()}
    assert any(s["evicted"] and s["priority"] == "low"
               for s in rows.values()), rows
    # ...and on the operator CLI: `ig-tpu fleet runs` labels the run's
    # drops and eviction — no silently-partial subscriber stream
    import contextlib
    import io

    from inspektor_gadget_tpu.cli.main import main as cli_main
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli_main(["fleet", "runs", "--remote",
                       f"shnode-1={target}"])
    assert rc == 0
    cli_out = buf.getvalue()
    line = next(ln for ln in cli_out.splitlines() if "evict-e2e" in ln)
    cols = line.split()
    assert "serving" in line
    # DROPS and EVICT columns are nonzero on the labeled row
    assert int(cols[-3]) >= out["sub_drops"] and int(cols[-2]) >= 1, line
    owner_stop.set()
    t_owner.join(timeout=20.0)
    assert owner_holder["out"]["error"] is None


def test_detach_all_keepalive_reattach_without_restart(shared_agents):
    """Dashboard churn must not thrash capture: when every subscriber
    leaves, the gadget keeps running for run-keepalive seconds; a
    re-attach inside the window rides the SAME run (same context object,
    same stream state, subscriber count back up) with no restart."""
    agents = shared_agents["agents"]
    target = shared_agents["targets"]["shnode-0"]
    stop1 = threading.Event()
    h1: dict = {}

    ka_params = dict(RUN_PARAMS, **{"gadget.rate": "2100"})

    def first():
        c = AgentClient(target, "ka-1")
        h1["out"] = c.run_gadget(
            "trace", "exec", ka_params, timeout=0.0, run_id="ka-e2e",
            share=True, keepalive=3.0,
            on_message=lambda *_: None, stop_event=stop1)
        c.close()

    t1 = threading.Thread(target=first, daemon=True)
    t1.start()
    deadline = time.monotonic() + 20.0
    st = None
    while time.monotonic() < deadline:
        st = agents["shnode-0"]._streams.get("ka-e2e")
        if st is not None and st.index > 0:
            break
        time.sleep(0.05)
    assert st is not None
    ctx_before = agents["shnode-0"]._runs.get("ka-e2e")
    assert ctx_before is not None

    # detach-all: the lone subscriber leaves; keepalive holds the run
    stop1.set()
    t1.join(timeout=20.0)
    assert h1["out"]["error"] is None
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and st.is_attached():
        time.sleep(0.05)
    assert not st.is_attached()
    assert not st.done, "gadget stopped instead of keeping alive"
    assert st.keepalive_remaining() > 0.0
    assert st.live_subscribers() == 0

    # re-attach within the window: same run, same context — no restart
    col = _Collector()
    h2: dict = {}

    def second():
        c = AgentClient(target, "ka-2")
        h2["out"] = c.run_gadget(
            "trace", "exec", ka_params, timeout=0.0, run_id="ignored",
            share=True,  # same (gadget, params, outputs) key → attach
            on_message=col.on_message, stop_event=col.stop)
        c.close()

    t2 = threading.Thread(target=second, daemon=True)
    t2.start()
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and not col.seqs:
        time.sleep(0.05)
    assert col.seqs, "re-attached subscriber got no records"
    assert agents["shnode-0"]._runs.get("ka-e2e") is ctx_before, \
        "keepalive re-attach restarted the gadget"
    assert agents["shnode-0"]._streams.get("ka-e2e") is st
    assert st.live_subscribers() == 1
    col.stop.set()
    t2.join(timeout=20.0)
    assert h2["out"]["error"] is None
    assert h2["out"]["attach"]["run_id"] == "ka-e2e"
    assert h2["out"]["attach"]["shared"] is True

    # last detach again → keepalive expiry actually stops the gadget
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline and not st.done:
        time.sleep(0.1)
    assert st.done, "keepalive expiry never stopped the gadget"


def test_admission_control_refuses_typed(shared_agents):
    """max-subscribers and the per-run subscriber budget refuse with a
    TYPED reason the client surfaces; low priority is refused at a
    budget level where high is still admitted."""
    target = shared_agents["targets"]["shnode-0"]
    refused_before = _metric("ig_agent_attach_refused_total",
                             reason="max-subscribers")
    stop = threading.Event()
    holder: dict = {}

    def owner():
        c = AgentClient(target, "adm-owner")
        holder["out"] = c.run_gadget(
            "trace", "exec", dict(RUN_PARAMS, **{"gadget.rate": "1900"}),
            timeout=0.0, run_id="adm-e2e",
            share=True, keepalive=0.2, max_subscribers=2, sub_budget=2048,
            subscriber={"queue": 1024, "priority": "high"},
            on_message=lambda *_: None, stop_event=stop)
        c.close()

    t = threading.Thread(target=owner, daemon=True)
    t.start()
    client = AgentClient(target, "adm-probe")
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:
        if any(r["run_id"] == "adm-e2e" for r in client.shared_runs()):
            break
        time.sleep(0.05)

    # budget: 1024 (owner) of 2048 used. A low-priority 512-queue would
    # reach 1536 > 60% of 2048 (=1228) → refused; the same queue at
    # high priority fits (≤ 2048) → admitted.
    low = client.run_gadget("", "", attach_to="adm-e2e",
                            subscriber={"priority": "low", "queue": 512},
                            timeout=5.0)
    assert low["attach_refused"] == "memory-budget", low
    assert "attach refused" in (low["error"] or "")
    assert _metric("ig_agent_attach_refused_total",
                   reason="memory-budget") >= 1.0

    keep = threading.Event()
    high_holder: dict = {}

    def high_sub():
        c2 = AgentClient(target, "adm-high")
        high_holder["out"] = c2.run_gadget(
            "", "", attach_to="adm-e2e",
            subscriber={"priority": "high", "queue": 512},
            on_message=lambda *_: None, stop_event=keep)
        c2.close()

    th = threading.Thread(target=high_sub, daemon=True)
    th.start()
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        rows = [r for r in client.shared_runs()
                if r["run_id"] == "adm-e2e"]
        if rows and rows[0]["live_subscribers"] >= 2:
            break
        time.sleep(0.05)
    assert rows and rows[0]["live_subscribers"] == 2, rows

    # the run is now at max-subscribers=2: ANY further admission refuses
    third = client.run_gadget("", "", attach_to="adm-e2e",
                              subscriber={"priority": "high"},
                              timeout=5.0)
    assert third["attach_refused"] == "max-subscribers", third
    assert _metric("ig_agent_attach_refused_total",
                   reason="max-subscribers") >= refused_before + 1.0
    # malformed options refuse loudly CLIENT-side before the wire
    with pytest.raises(ValueError):
        client.run_gadget("", "", attach_to="adm-e2e",
                          subscriber={"priority": "vip"})
    client.close()
    keep.set()
    th.join(timeout=20.0)
    assert high_holder["out"]["error"] is None
    stop.set()
    t.join(timeout=20.0)
    assert holder["out"]["error"] is None


def test_subscriber_churn_leaves_no_leaks(shared_agents):
    """The chaos round: attach/hold/detach churn (every 3rd round
    leaving by proxy cut) against one shared run — the run survives
    every round, and afterwards nothing lingers: no stream states, no
    leaked subscriber queues, thread count back to baseline."""
    agents = shared_agents["agents"]
    target = shared_agents["targets"]["shnode-1"]
    proxy = ChaosProxy(target)
    stop = threading.Event()
    holder: dict = {}
    baseline_threads = threading.active_count()

    def owner():
        c = AgentClient(target, "churn-owner")
        holder["out"] = c.run_gadget(
            "trace", "exec", dict(RUN_PARAMS, **{"gadget.rate": "1800"}),
            timeout=0.0, run_id="churn-e2e",
            share=True, keepalive=0.6,
            on_message=lambda *_: None, stop_event=stop)
        c.close()

    t = threading.Thread(target=owner, daemon=True)
    t.start()
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:
        st = agents["shnode-1"]._streams.get("churn-e2e")
        if st is not None and st.index > 0:
            break
        time.sleep(0.05)
    assert st is not None

    churn = SubscriberChurn(proxy.target, "churn-e2e", node="churner",
                            proxy=proxy,
                            subscriber={"priority": "normal",
                                        "queue": 256})
    churn.run(6, hold=0.4, cut_every=3)
    proxy.close()
    assert churn.rounds == 6 and churn.cuts == 2
    assert churn.acks >= 4, "clean rounds must ack their attach"
    assert not churn.errors, churn.errors
    assert not st.done, "subscriber churn killed the shared run"

    stop.set()
    t.join(timeout=20.0)
    assert holder["out"]["error"] is None

    # drain: keepalive + retire window pass; registries and threads
    # return to baseline — no leaked queues, threads, or lingering runs
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:
        if "churn-e2e" not in agents["shnode-1"]._streams \
                and threading.active_count() <= baseline_threads + 4:
            break
        time.sleep(0.2)
    assert "churn-e2e" not in agents["shnode-1"]._streams, \
        "stream state leaked past its retire window"
    assert threading.active_count() <= baseline_threads + 4, \
        "subscriber churn leaked threads"
    assert _metric("ig_agent_run_subscribers", run="churn-e2e") == 0.0


def test_summary_tier_gets_summaries_never_batches(shared_agents):
    """The summary pub/sub tier: a tier=summary subscriber on a shared
    run with history enabled receives harvest summaries and
    sealed-window announcements from the ONE shared harvest — and not a
    single raw row/batch/log message."""
    import os
    agents = shared_agents["agents"]
    target = shared_agents["targets"]["shnode-0"]
    from inspektor_gadget_tpu.history import HISTORY
    hist = tempfile.mkdtemp()
    HISTORY.set_base_dir(hist)
    stop = threading.Event()
    holder: dict = {}
    params = dict(RUN_PARAMS)
    params.update({"operator.tpusketch.enable": "true",
                   "operator.tpusketch.log2-width": "10",
                   "operator.tpusketch.hll-p": "10",
                   "operator.tpusketch.harvest-interval": "400ms",
                   "operator.tpusketch.history": "true",
                   "operator.tpusketch.history-interval": "0",
                   "operator.tpusketch.history-log2-width": "10",
                   "operator.tpusketch.history-slots": "4"})

    def owner():
        c = AgentClient(target, "sum-owner")
        holder["out"] = c.run_gadget(
            "trace", "exec", params, timeout=0.0, run_id="summary-e2e",
            share=True, keepalive=0.5,
            outputs=("json", "batch", "summary"),
            on_message=lambda *_: None, stop_event=stop)
        c.close()

    t = threading.Thread(target=owner, daemon=True)
    t.start()
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:
        st = agents["shnode-0"]._streams.get("summary-e2e")
        if st is not None and not st.done:
            break
        time.sleep(0.05)
    assert st is not None

    # the cheap consumer: a GrpcRuntime-level summary subscription
    runtime = GrpcRuntime({"shnode-0": target})
    summaries: list = []
    windows: list = []
    kinds: list = []
    sub_stop = threading.Event()
    threading.Timer(4.0, sub_stop.set).start()
    client_kinds_seen = kinds.append
    res = runtime.subscribe_summaries(
        gadget="trace/exec",
        on_summary=lambda n, s: (summaries.append(s),
                                 client_kinds_seen(wire.EV_SUMMARY)),
        on_window=lambda n, w: (windows.append(w),
                                client_kinds_seen(wire.EV_WINDOW)),
        stop_event=sub_stop)
    runtime.close()
    out = res["shnode-0"]
    assert out.get("error") is None, out
    assert out["attach"] and out["attach"]["shared"] is True
    assert summaries, "summary tier delivered no summaries"
    assert windows, "summary tier delivered no window announcements"
    assert all(w.get("digest") and w.get("events", 0) >= 0
               for w in windows)
    # zero raw records reached this subscriber: every seq-bearing
    # message it got was summary-tier (the out['records'] count equals
    # what the summary/window/notice handlers saw, and no batch handler
    # even existed to call)
    assert out["records"] >= len(summaries) + len(windows)
    rows = {s["sub_id"]: s for r in [agents["shnode-0"]._streams[
        "summary-e2e"]] for s in r.subscriber_rows()}
    tier_rows = [s for s in rows.values() if s["tier"] == "summary"]
    assert tier_rows and all(s["drops"] == 0 for s in tier_rows)

    stop.set()
    t.join(timeout=20.0)
    assert holder["out"]["error"] is None
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and not st.done:
        time.sleep(0.1)
    HISTORY.close_all()
    HISTORY.set_base_dir(None)
    assert os.path.isdir(hist)


# ---------------------------------------------------------------------------
# SharedRun-level regressions (review findings): anonymous-resume routing
# and detached-subscriber expiry
# ---------------------------------------------------------------------------

def test_anonymous_resume_prefers_detached_subscriber():
    """A resume without sub_id (PR-8 wire compat) must resolve to a
    DETACHED subscriber — picking the attached primary would hijack a
    live peer's stream and silently end it."""
    from inspektor_gadget_tpu.agent.service import SharedRun

    run = SharedRun("route-run", "trace/route", shared=True,
                    keepalive=5.0, node="t")
    a = run.admit({"queue": 64})
    run.attach_subscriber(a, 0)
    b = run.admit({"queue": 64})
    _qb, gen_b, _ack = run.attach_subscriber(b, 0)
    for _ in range(5):
        run.push(wire.EV_PAYLOAD_JSON, {"node": "t"}, b"x")
    run.detach(b, gen_b)
    assert a.attached and not b.attached

    resolved = run.resume("", b.seq)
    assert resolved is not None
    sub, _q, _gen, ack = resolved
    assert sub is b, "anonymous resume hijacked the attached primary"
    assert ack["sub_id"] == b.sub_id
    assert a.attached, "the live peer must be untouched"
    # a named resume still routes precisely
    resolved2 = run.resume(a.sub_id, a.seq)
    assert resolved2 is not None and resolved2[0] is a
    run.finish()


def test_detached_subscriber_expires_and_frees_its_slot():
    """A subscriber that disconnects and never resumes must not hold a
    max-subscribers slot (or budget capacity) for the life of the run:
    past the resume window (`linger`) it is expired-and-left, and a
    fresh admission succeeds where it would have been refused."""
    from inspektor_gadget_tpu.agent.service import SharedRun

    run = SharedRun("expire-run", "trace/expire", shared=True,
                    linger=0.2, keepalive=5.0, max_subscribers=2,
                    sub_budget=1 << 20, node="t")
    a = run.admit({"queue": 64})
    run.attach_subscriber(a, 0)
    b = run.admit({"queue": 64})
    _qb, gen_b, _ack = run.attach_subscriber(b, 0)
    run.detach(b, gen_b)

    # at capacity: a third admission refuses while the ghost lingers
    refused = run.admit({"queue": 64})
    assert isinstance(refused, dict) and \
        refused["reason"] == "max-subscribers"

    time.sleep(0.3)
    run.push(wire.EV_PAYLOAD_JSON, {"node": "t"}, b"x")
    assert b.left, "detached subscriber never expired past its window"
    # the ghost's resume answers gone (→ unknown_run upstream), and the
    # freed slot admits a live client
    assert run.resume(b.sub_id, 0) is None
    c = run.admit({"queue": 64})
    assert not isinstance(c, dict), c
    assert run.live_subscribers() == 2
    run.finish()
