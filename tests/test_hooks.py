"""Hook installation + runtime-invoked hook round trip (ref:
gadget-container/entrypoint.sh:83-142 hook installation,
hooks/oci/main.go container add via the agent socket)."""

import io
import json
import os
import subprocess
import tempfile
from pathlib import Path

import pytest

from inspektor_gadget_tpu.agent.hooks import (
    HookInstaller, detect_hook_mode, run_oci_hook,
)
from inspektor_gadget_tpu.agent.main import main as agent_main


def test_detect_hook_mode(tmp_path):
    assert detect_hook_mode(str(tmp_path)) == "fanotify"
    (tmp_path / "run/containerd").mkdir(parents=True)
    (tmp_path / "run/containerd/containerd.sock").touch()
    assert detect_hook_mode(str(tmp_path)) == "nri"
    (tmp_path / "run/crio").mkdir(parents=True)
    (tmp_path / "run/crio/crio.sock").touch()
    assert detect_hook_mode(str(tmp_path)) == "oci"  # crio preferred


def test_install_and_uninstall_oci_hook_configs(tmp_path):
    inst = HookInstaller(str(tmp_path), "unix:///run/ig.sock")
    res = inst.install("oci")
    assert res.mode == "oci" and len(res.installed) == 4  # 2 dirs × 2 stages
    cfg = json.loads((tmp_path / "etc/containers/oci/hooks.d/"
                      "ig-tpu-prestart.json").read_text())
    assert cfg["version"] == "1.0.0"
    assert cfg["stages"] == ["prestart"]
    assert cfg["when"] == {"always": True}
    assert "--stage" in cfg["hook"]["args"]
    post = json.loads((tmp_path / "usr/share/containers/oci/hooks.d/"
                       "ig-tpu-poststop.json").read_text())
    assert post["stages"] == ["poststop"]
    removed = inst.uninstall()
    assert len(removed) == 4
    assert not list((tmp_path / "etc/containers/oci/hooks.d").iterdir())


def test_install_nri_appends_to_existing_conf(tmp_path):
    conf = tmp_path / "etc/nri/conf.json"
    conf.parent.mkdir(parents=True)
    conf.write_text(json.dumps(
        {"version": "0.1", "plugins": [{"type": "other-plugin"}]}))
    inst = HookInstaller(str(tmp_path))
    res = inst.install("nri")
    assert res.mode == "nri"
    data = json.loads(conf.read_text())
    types = [p["type"] for p in data["plugins"]]
    assert types == ["other-plugin", "ig-tpu-nri"]  # appended, not replaced
    shim = tmp_path / "opt/nri/bin/ig-tpu-nri"
    assert shim.exists() and os.access(shim, os.X_OK)
    # idempotent: a second install must not duplicate the entry
    inst.install("nri")
    assert [p["type"] for p in json.loads(conf.read_text())["plugins"]] == \
        ["other-plugin", "ig-tpu-nri"]
    inst.uninstall()
    data = json.loads(conf.read_text())
    assert [p["type"] for p in data["plugins"]] == ["other-plugin"]
    assert not shim.exists()


@pytest.fixture()
def live_agent():
    from inspektor_gadget_tpu.agent.service import serve
    tmp = tempfile.mkdtemp()
    addr = f"unix://{tmp}/hook-agent.sock"
    server, _agent = serve(addr, node_name="hook-node")
    yield addr
    server.stop(grace=0.5)


def _fake_bundle(tmp_path):
    bundle = tmp_path / "bundle"
    bundle.mkdir()
    (bundle / "config.json").write_text(json.dumps({"annotations": {
        "io.kubernetes.cri.sandbox-name": "pod-hooked",
        "io.kubernetes.cri.sandbox-namespace": "ns-hooked",
        "io.kubernetes.cri.container-name": "app-hooked",
        "io.kubernetes.cri.container-type": "container",
    }}))
    return bundle


def _agent_containers(addr):
    from inspektor_gadget_tpu.agent.client import AgentClient
    client = AgentClient(addr)
    try:
        return {c["id"]: c for c in client.dump_state().get("containers", [])}
    finally:
        client.close()


def test_oci_hook_round_trip_in_process(tmp_path, live_agent, monkeypatch):
    """prestart state in → container lands in the collection with bundle
    identity resolved; poststop removes it."""
    bundle = _fake_bundle(tmp_path)
    state = {"ociVersion": "1.0.2", "id": "hooked-1", "pid": os.getpid(),
             "bundle": str(bundle)}
    rc = run_oci_hook("prestart", live_agent, io.StringIO(json.dumps(state)))
    assert rc == 0
    containers = _agent_containers(live_agent)
    assert "hooked-1" in containers, containers
    c = containers["hooked-1"]
    assert c["name"] == "app-hooked"
    assert c["pod"] == "pod-hooked" and c["namespace"] == "ns-hooked"
    assert int(c["mntns"]) == os.stat(f"/proc/{os.getpid()}/ns/mnt").st_ino

    rc = run_oci_hook("poststop", live_agent,
                      io.StringIO(json.dumps({"id": "hooked-1"})))
    assert rc == 0
    assert "hooked-1" not in _agent_containers(live_agent)


def test_installed_hook_config_round_trip_subprocess(tmp_path, live_agent):
    """The full fake-runtime path: install into a scratch host root, then
    execute exactly the command the installed config tells the runtime to
    run, with the OCI state on stdin — the container must appear."""
    inst = HookInstaller(str(tmp_path), live_agent)
    inst.install("oci")
    cfg = json.loads((tmp_path / "etc/containers/oci/hooks.d/"
                      "ig-tpu-prestart.json").read_text())
    cmd = [cfg["hook"]["path"]] + cfg["hook"]["args"][1:]
    bundle = _fake_bundle(tmp_path)
    state = {"ociVersion": "1.0.2", "id": "hooked-sub", "pid": os.getpid(),
             "bundle": str(bundle)}
    env = dict(os.environ)
    env["PYTHONPATH"] = str(Path(__file__).resolve().parent.parent)
    r = subprocess.run(cmd, input=json.dumps(state), text=True,
                       capture_output=True, env=env, timeout=60)
    assert r.returncode == 0, r.stderr
    containers = _agent_containers(live_agent)
    assert "hooked-sub" in containers
    assert containers["hooked-sub"]["name"] == "app-hooked"


def test_oci_hook_rejects_bad_state(live_agent):
    assert run_oci_hook("prestart", live_agent, io.StringIO("not json")) == 1
    assert run_oci_hook("prestart", live_agent, io.StringIO("{}")) == 1


def test_oci_hook_degrades_when_agent_down(tmp_path):
    """A prestart hook exiting nonzero BLOCKS container creation on the
    host (OCI contract) — an unreachable agent must degrade to exit 0,
    fast (bounded timeout, not the 30s client default)."""
    import time
    state = {"id": "orphan", "pid": 1}
    t0 = time.monotonic()
    rc = run_oci_hook("prestart", f"unix://{tmp_path}/nope.sock",
                      io.StringIO(json.dumps(state)))
    elapsed = time.monotonic() - t0
    assert rc == 0
    assert elapsed < 10.0, f"hook stalled {elapsed:.1f}s"


def test_nri_unknown_events_are_ignored(live_agent):
    """Sandbox/synchronize NRI events must not land in the collection as
    workload containers."""
    for event in ("RunPodSandbox", "StopPodSandbox", "Synchronize"):
        rc = run_oci_hook("prestart", live_agent,
                          io.StringIO(json.dumps(
                              {"event": event, "id": f"sbx-{event}",
                               "pid": 1})), nri=True)
        assert rc == 0
    containers = _agent_containers(live_agent)
    assert not any(c.startswith("sbx-") for c in containers)


def test_containerized_install_warns_on_host_invalid_command(tmp_path):
    """Installing from a container (host_root != /) with the default
    in-container interpreter must warn that the host can't exec it."""
    inst = HookInstaller(str(tmp_path), "unix:///run/ig.sock")
    res = inst.install("oci")
    assert any("WARNING" in n for n in res.notes), res.notes
    # an explicit host-valid command silences the warning
    (tmp_path / "usr/bin").mkdir(parents=True)
    (tmp_path / "usr/bin/ig-hook").touch()
    inst2 = HookInstaller(str(tmp_path), "unix:///run/ig.sock",
                          hook_cmd=["/usr/bin/ig-hook", "--socket",
                                    "unix:///run/ig.sock"])
    res2 = inst2.install("oci")
    assert not any("WARNING" in n for n in res2.notes)
    cfg = json.loads((tmp_path / "etc/containers/oci/hooks.d/"
                      "ig-tpu-prestart.json").read_text())
    assert cfg["hook"]["path"] == "/usr/bin/ig-hook"


def test_cli_install_hooks_subcommand(tmp_path, capsys):
    rc = agent_main(["install-hooks", "--host-root", str(tmp_path),
                     "--mode", "oci", "--socket", "unix:///run/x.sock"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "hook mode: oci" in out and "ig-tpu-prestart.json" in out
    rc = agent_main(["uninstall-hooks", "--host-root", str(tmp_path)])
    assert rc == 0
    assert "removed" in capsys.readouterr().out
